"""The declarative adversarial scenario library (FORMATS.md §19).

A scenario is a plain dict (JSON-able): the SimSpec world keys plus an
``ops`` list — the adversarial program. Ops compose the existing
primitives: the serving plane's withholding gate (das/server.withhold),
the malicious-producer fixtures (testing/malicious.py), topology cuts
(partitions, downs, eclipses), deterministic spam, and state-sync joins.
``run_scenario`` builds the world, installs the ops, runs the seeded
timeline, and reduces the raw results to ONE verdict dict — the BENCH
JSON payload of ``bench.py --scenario`` and the byte-identity witness of
the tier-1 determinism matrix.

Op grammar (each op is a dict with an ``op`` key):

  withhold_threshold   {height, fraction?}    every validator withholds
      the committed height's cells past the scheme's recoverability
      threshold the moment it commits: rs2d-nmt loses the minimal
      unrecoverable (k+1)x(k+1) subgrid (the ¼ bound — arXiv:1809.09044
      regime); cmt-ldpc loses ``fraction`` of its base layer (default
      1.0: past any peeling threshold — arXiv:1910.01247 stopping sets).
  incorrect_coding     {k?}                   after the LAST scheduled
      height commits, >2/3 collude to certify a non-codeword: the
      malicious fixtures build a committed-but-invalid entry
      (testing/malicious.py), every validator serves it (half the bad
      axis withheld so naive re-serving cannot mask it), and a forged
      header+certificate rides the light nodes' header gossip.
  partition            {t, groups}            validator indices per
      partition cell; unlisted validators (and all light nodes) stay in
      cell 0.          heal {t} reunites everyone.
  down / up            {t, validator}         whole-node outage windows.
  lazy                 {validator}            never proposes (its slots
      time out and rotate) but votes honestly.
  spam                 {t, every, until, count}  deterministic junk +
      oversized txs against every validator's admission path.
  eclipse              {t, lights, validator, height}  the listed light
      nodes see ONLY the given validator, which withholds `height` from
      its own core — the captor-withholder shape.
  statesync_join       {t, validator}         the validator (kept down
      from genesis by a paired ``down`` at t=0) snapshot-joins from the
      first reachable peer, then catch-up replays the rest.
  crash_storm          {heights, validators, down_s}  at each listed
      height's commit, a seeded pick of the listed validators drops at
      the post-commit instant (the consensus.post_apply fault point's
      moment) and returns ``down_s`` later.
  traffic              {t?, every?, until?, sequences?, pfbs_per_wave?,
      blob_sizes?, blobs_per_pfb?, gas_prices?, namespaces?}  seeded
      txsim-shaped PFB lanes inside virtual time: per-lane rng draws the
      tools/txsim.py size/namespace/gas-price distributions, every wave
      enters through the BATCHED admission path (add_txs: prevalidate +
      CheckTx) of every up validator, sequences chain on the primary's
      verdicts, and confirmations are counted from committed block txs.
  asym_fault           {kind, t?, until?, src?, dst?, path?, prob?,
      delay?, seed?}  a deterministic per-message asymmetric fault on
      the light fleet's transport (engine.AsymRule): drop/delay/corrupt
      keyed by sha256(seed|src|dst|path|msg-index) — per-message
      reproducible, unlike thread-interleaved fault draws.
  soak                 {eds_entries?, sig_cache?, commitment_cache?,
      ttl_blocks?, ttl_seconds?, expire_every?, snapshot_every?,
      snapshot_keep?, pack_every?, pack_keep?, stale_every?, stale_to?,
      stale_lanes?}  the long-horizon resource-churn harness: shrinks
      every validator's EDS/sig/commitment cache caps and mempool TTLs
      so LRUs actually cycle, runs the production expire tick on the
      virtual clock, writes + prunes state snapshots and proof packs at
      height marks, and feeds a stale-tx lane into a lazy validator's
      pool so TTL expiry (not commits) drains it. The verdict's "soak"
      block reports every resource's churn count.

Verdict metrics (FORMATS.md §19.2): blocks_to_detection, liveness_gap_s,
false_condemnation_rate, recovery_s, sim_lights, sim_virtual_blocks,
peak_rss_bytes (reported, but excluded from the byte-identity form —
memory peaks are not run-deterministic), per-op blocks (traffic / spam /
soak / asym_msgs), plus per-height block/app hashes and the event-trace
digest (the determinism witness).
"""

from __future__ import annotations

import json
import math
import tempfile

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import codec as dacodec
from celestia_app_tpu.sim import engine
from celestia_app_tpu.sim.engine import (
    SimConsensusConfig,
    SimSpec,
    Simulation,
)


# ---------------------------------------------------------------------------
# op installation
# ---------------------------------------------------------------------------


def _ods(k: int, seed: int) -> np.ndarray:
    """A deterministic valid-share ODS for the malicious fixtures."""
    o = np.random.default_rng(seed).integers(  # lint: disable=det-rng
        0, 256, size=(k, k, appconsts.SHARE_SIZE), dtype=np.uint8)
    o[..., :appconsts.NAMESPACE_SIZE] = 0
    o[..., appconsts.NAMESPACE_SIZE - 1] = 7
    return o


def _threshold_cells(entry, fraction: float | None) -> list[tuple]:
    """The scheme's at-the-recoverability-threshold withholding set."""
    if entry.scheme == dacodec.RS2D_NAME:
        k = entry.cache_entry.k
        # the minimal unrecoverable pattern for 2D-RS: a (k+1)^2 subgrid
        # (any k available per axis iterates the crossword to completion;
        # k+1 missing on both axes wedges it) — the ¼ sampling bound's
        # worst case
        side = k + 1
        return [(r, c) for r in range(side) for c in range(side)]
    comm = entry.cache_entry.commitments
    frac = 1.0 if fraction is None else float(fraction)
    n = min(comm.n_base, max(1, math.ceil(comm.n_base * frac)))
    return [(0, i) for i in range(n)]


def _install_withhold_threshold(sim: Simulation, op: dict,
                                expect: dict) -> None:
    height = int(op["height"])
    expect.update(kind="withholding", fault_height=height)

    def arm(s: Simulation, committer) -> None:
        entry = committer.core._entry(height)
        cells = _threshold_cells(entry, op.get("fraction"))
        s.withhold_everywhere(height, cells)
        s.sched.note(f"op.withhold_threshold h={height} "
                     f"cells={len(cells)} scheme={entry.scheme}")

    sim.on_commit_height(height, arm)


def _install_incorrect_coding(sim: Simulation, op: dict,
                              expect: dict) -> None:
    from celestia_app_tpu.chain import consensus as c
    from celestia_app_tpu.chain.block import Header, validators_hash_of
    from celestia_app_tpu.testing import malicious

    k = int(op.get("k", 4))
    after = int(op.get("after_height", sim.spec.heights))
    bad_h = after + 1  # past the last real height: never collides
    expect.update(kind="fraud", fault_height=bad_h)

    def inject(s: Simulation, committer) -> None:
        scheme = s.spec.scheme
        ods = _ods(k, seed=5)
        # the scheme-keyed committed-non-codeword fixture: entry +
        # provable location + a withholding set that forces escalation
        # while keeping the fraud equation's members served — one hook,
        # no per-scheme branches here (testing/malicious.py)
        entry, _location, withheld, wire_scheme = \
            malicious.incorrect_coding_fixture(scheme, ods)
        app0 = committer.vnode.app  # the one node sure to hold `after`
        header = Header(
            chain_id=s.chain_id, height=bad_h,
            time_unix=s.block_timestamp(bad_h),
            data_hash=entry.data_root, square_size=k,
            app_hash=b"\x77" * 32,
            proposer=committer.vnode.address,
            app_version=app0.app_version,
            last_block_hash=app0.last_block_hash,
            validators_hash=validators_hash_of(
                [(v.vnode.address, 10) for v in s.validators]),
            da_scheme=wire_scheme,
        )
        votes = tuple(
            c.Vote(
                bad_h, header.hash(), v.vnode.address,
                v.vnode.priv.sign(c.Vote.sign_bytes(
                    s.chain_id, bad_h, header.hash(), "precommit", 0)),
                "precommit", 0,
            )
            for v in s.validators
        )
        cert = c.CommitCertificate(bad_h, header.hash(), votes, 0)
        s.forged_headers[bad_h] = (header, cert)
        for v in s.validators:
            v.core.seed_scheme_entry(bad_h, entry)
            v.core.withhold(bad_h, withheld)
        s.sched.note(f"op.incorrect_coding h={bad_h} scheme={scheme} "
                     f"k={k} withheld={len(withheld)}")

    sim.on_commit_height(after, inject)


def _install_ops(sim: Simulation) -> dict:
    """Install every op of the spec; returns the expectations dict the
    verdict reducer consumes."""
    expect: dict = {"kind": None, "fault_height": None, "marks": [],
                    "collectors": []}
    for op in sim.spec.ops:
        name = op["op"]
        if name == "withhold_threshold":
            _install_withhold_threshold(sim, op, expect)
        elif name == "incorrect_coding":
            _install_incorrect_coding(sim, op, expect)
        elif name == "partition":
            groups = [list(g) for g in op["groups"]]

            def cut(s: Simulation, groups=groups) -> None:
                for gi, members in enumerate(groups):
                    for idx in members:
                        v = s.validator_by_index(idx)
                        s.net.group[v.name] = gi
                s.sched.note(f"op.partition groups={groups}")

            sim.at(float(op["t"]), lambda cut=cut: cut(sim),
                   "op.partition")
        elif name == "heal":
            t = float(op["t"])
            expect["marks"].append(("heal", t, None))

            def heal(s: Simulation = sim) -> None:
                s.net.group.clear()
                s.sched.note("op.heal")

            sim.at(t, heal, "op.heal")
        elif name == "down":
            idx = int(op["validator"])

            def down(s: Simulation = sim, idx=idx) -> None:
                s.validator_by_index(idx).go_down()

            sim.at(float(op["t"]), down, f"op.down val={idx}")
        elif name == "up":
            idx = int(op["validator"])
            t = float(op["t"])
            expect["marks"].append(
                ("up", t, sim.validator_by_index(idx).name))

            def up(s: Simulation = sim, idx=idx) -> None:
                s.validator_by_index(idx).go_up()

            sim.at(t, up, f"op.up val={idx}")
        elif name == "lazy":
            sim.validator_by_index(int(op["validator"])).lazy = True
        elif name == "spam":
            _install_spam(sim, op, expect)
        elif name == "traffic":
            _install_traffic(sim, op, expect)
        elif name == "asym_fault":
            _install_asym(sim, op, expect)
        elif name == "soak":
            _install_soak(sim, op, expect)
        elif name == "eclipse":
            _install_eclipse(sim, op, expect)
        elif name == "statesync_join":
            idx = int(op["validator"])
            t = float(op["t"])
            expect["marks"].append(
                ("join", t, sim.validator_by_index(idx).name))

            def join(s: Simulation = sim, idx=idx) -> None:
                _statesync_join(s, idx)

            sim.at(t, join, f"op.statesync_join val={idx}")
        elif name == "crash_storm":
            _install_crash_storm(sim, op, expect)
        elif name == "slo":
            _install_slo(sim, op, expect)
        else:
            raise ValueError(f"unknown scenario op {name!r}")
    return expect


def _install_spam(sim: Simulation, op: dict, expect: dict) -> None:
    """Junk + oversized floods through the REAL batched admission path
    (add_txs: admission-plane prevalidation, then per-tx CheckTx and the
    pool's byte gate) — the scenario exercises the REJECTION plane and
    its counters, and its verdict block proves nothing junk was pooled."""
    every = float(op.get("every", 0.5))
    until = float(op.get("until", sim.spec.auto_duration(sim.ccfg)))
    count = int(op.get("count", 16))
    state = {"i": 0, "sent": 0, "rejected": 0, "admitted": 0}

    def flood() -> None:
        t = sim.sched.clock.monotonic()
        for v in sim.validators:
            batch = []
            for _j in range(count):
                state["i"] += 1
                # undecodable: prevalidation cannot parse it, CheckTx
                # refuses it, and it must never reach the pool
                batch.append((b"spam-" + str(state["i"]).encode()) * 7)
            # the byte-cap gate too: one oversized tx per wave
            batch.append(
                b"\x5a" * (appconsts.MEMPOOL_MAX_TX_BYTES + 1))
            results = v.vnode.add_txs(batch)
            state["sent"] += len(batch)
            state["rejected"] += sum(1 for r in results if r.code != 0)
            state["admitted"] += sum(1 for r in results if r.code == 0)
        sim.sched.note(f"op.spam wave i={state['i']}")
        if t + every <= until:
            sim.sched.call_after(every, flood, "op.spam")

    sim.at(float(op.get("t", 0.5)), flood, "op.spam")

    def collect(s: Simulation) -> dict:
        pool_rejected = sum(
            v.vnode.pool.metrics.counters.get("rejected", 0)
            for v in s.validators)
        return {"spam": {**{k: state[k] for k in
                            ("sent", "rejected", "admitted")},
                         "pool_rejected": pool_rejected}}

    expect["collectors"].append(collect)


def _install_slo(sim: Simulation, op: dict, expect: dict) -> None:
    """Fleet-wide SLO judging inside the scenario plane
    (tools/fleetmon.py): the op carries its rule list inline
    ({"op": "slo", "rules": [...]}, FORMATS §22.1), the telemetry
    registry is baselined at install time, and the verdict evaluates the
    RUN'S DELTA — counters accumulated by earlier cells in the same
    process never leak into this scenario's verdict. The whole verdict
    joins `verdict_of`, so rules here should pin sim-deterministic
    families (counters, count/sum of deterministic histograms); latency
    quantile budgets belong to the HTTP fleetmon against a live devnet,
    where verdict bytes are compared per fleet STATE, not per seed."""
    from celestia_app_tpu.tools import fleetmon
    from celestia_app_tpu.utils import telemetry

    rules = fleetmon.normalize_rules(op.get("rules") or [])
    base = telemetry.export()

    def collect(s: Simulation) -> dict:
        node = fleetmon.registry_node(base=base)
        verdict = fleetmon.evaluate(rules, {"nodes": {"sim": node}})
        s.sched.note(f"op.slo pass={verdict['pass']} "
                     f"failed={len(verdict['failed'])}")
        return {"slo": verdict}

    expect["collectors"].append(collect)


def _install_traffic(sim: Simulation, op: dict, expect: dict) -> None:
    """Seeded txsim-shaped PFB lanes inside virtual time: the
    tools/txsim.py sequence-worker distributions (blob count/size,
    namespace, gas price), drawn from per-lane seeded rngs, submitted
    through every up validator's BATCHED admission path. The primary's
    verdict decides whether a lane's sequence advances (the txsim
    resync analog); commits are watched so the verdict can report how
    much admitted traffic actually landed in blocks."""
    from celestia_app_tpu.chain import modules
    from celestia_app_tpu.da.blob import Blob
    from celestia_app_tpu.da.namespace import Namespace

    t0 = float(op.get("t", 0.8))
    every = float(op.get("every", 0.9))
    until = float(op.get("until", sim.spec.auto_duration(sim.ccfg)))
    per_wave = int(op.get("pfbs_per_wave", 1))
    blob_sizes = tuple(op.get("blob_sizes", (96, 512)))
    blobs_per_pfb = tuple(op.get("blobs_per_pfb", (1, 2)))
    gas_prices = tuple(op.get("gas_prices", (0.002, 0.02)))
    namespaces = int(op.get("namespaces", 4))
    n_seq = int(op.get("sequences", 2))
    lanes = [
        {"priv": p, "addr": p.public_key().address(), "tag": i,
         # one independent stream per lane off the scenario seed: the
         # sim analog of txsim's per-sequence default_rng(seed, seq)
         "rng": np.random.default_rng([sim.spec.seed, 8800 + i])}  # lint: disable=det-rng
        for i, p in enumerate(sim.claim_traffic_accounts(n_seq))
    ]
    stats = {"submitted": 0, "accepted": 0, "rejected": 0,
             "confirmed": 0}
    pending: set[bytes] = set()  # admitted raws awaiting a commit

    def draw_pfb(lane: dict) -> bytes:
        rng = lane["rng"]
        n_blobs = int(rng.integers(blobs_per_pfb[0],
                                   blobs_per_pfb[1] + 1))
        blobs = []
        for _b in range(n_blobs):
            size = int(rng.integers(blob_sizes[0], blob_sizes[1] + 1))
            ns_id = 1 + int(rng.integers(0, max(1, namespaces)))
            ns = Namespace.v0(bytes([lane["tag"] + 1, ns_id]) * 5)
            blobs.append(Blob(ns, rng.integers(
                0, 256, size, dtype=np.uint8).tobytes()))
        gas = int(modules.estimate_pfb_gas(
            [len(b.data) for b in blobs]) * 1.2)
        price = float(rng.uniform(gas_prices[0], gas_prices[1]))
        fee = max(1, int(gas * price) + 1)
        return sim.signer.create_pay_for_blobs(
            lane["addr"], blobs, fee=fee, gas_limit=gas)

    def wave() -> None:
        ups = [v for v in sim.validators if v.up]
        if ups:
            drawn = [(lane, draw_pfb(lane))
                     for lane in lanes for _ in range(per_wave)]
            batch = [raw for _lane, raw in drawn]
            results = ups[0].vnode.add_txs(batch)
            for (lane, raw), res in zip(drawn, results):
                stats["submitted"] += 1
                if res.code == 0:
                    stats["accepted"] += 1
                    # the lane chains on the primary's verdict; a
                    # rejection leaves the sequence for the next wave
                    sim.signer.accounts[lane["addr"]].sequence += 1
                    pending.add(raw)
                else:
                    stats["rejected"] += 1
            for v in ups[1:]:
                v.vnode.add_txs(batch)
        t = sim.sched.clock.monotonic()
        if t + every <= until:
            sim.sched.call_after(every, wave, "op.traffic")

    sim.at(t0, wave, "op.traffic")

    def confirm(s: Simulation, _val, _height, block) -> None:
        for raw in block.txs:
            if raw in pending:
                pending.discard(raw)
                stats["confirmed"] += 1

    sim.commit_listeners.append(confirm)
    expect["collectors"].append(lambda s: {"traffic": {
        **stats, "in_flight": len(pending)}})


def _install_asym(sim: Simulation, op: dict, expect: dict) -> None:
    from celestia_app_tpu.sim.engine import AsymRule

    rule = AsymRule(
        kind=str(op["kind"]),
        src=str(op.get("src", "light")),
        dst=str(op.get("dst", "")),
        path=str(op.get("path", "")),
        prob=float(op.get("prob", 0.2)),
        delay=float(op.get("delay", 0.05)),
        seed=int(op.get("seed", sim.spec.seed)),
    )
    if rule.kind not in ("drop", "delay", "corrupt"):
        raise ValueError(f"unknown asym_fault kind {rule.kind!r}")

    def arm() -> None:
        sim.net.asym_rules.append(rule)
        sim.sched.note(f"op.asym_fault kind={rule.kind} src={rule.src} "
                       f"path={rule.path} prob={rule.prob}")

    sim.at(float(op.get("t", 0.0)), arm, "op.asym_fault")
    if op.get("until") is not None:
        def disarm() -> None:
            if rule in sim.net.asym_rules:
                sim.net.asym_rules.remove(rule)
            sim.sched.note(f"op.asym_fault.disarm kind={rule.kind}")

        sim.at(float(op["until"]), disarm, "op.asym_fault.disarm")


def _install_soak(sim: Simulation, op: dict, expect: dict) -> None:
    """The long-horizon resource-churn harness: every bounded resource
    the node runs on — EDS-cache LRU, verified sig/commitment LRUs,
    mempool TTL, snapshot keep-N, pack prune — is capped small enough
    (and the run is long enough) that each cycles at least twice, while
    the verdict proves the degradation stayed graceful."""
    import os

    from celestia_app_tpu.chain import consensus as c
    from celestia_app_tpu.chain import sync as sync_mod
    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.das import packs as packs_mod

    eds_entries = int(op.get("eds_entries", 2))
    sig_cache = int(op.get("sig_cache", 24))
    commitment_cache = int(op.get("commitment_cache", 12))
    ttl_blocks = int(op.get("ttl_blocks", 3))
    ttl_seconds = float(op.get("ttl_seconds", 0.0))
    expire_every = float(op.get("expire_every", 1.0))
    snapshot_every = int(op.get("snapshot_every", 4))
    snapshot_keep = int(op.get("snapshot_keep", 2))
    pack_every = int(op.get("pack_every", 3))
    pack_keep = int(op.get("pack_keep", 2))
    stale_every = float(op.get("stale_every", 0.8))
    stale_to = int(op.get("stale_to", sim.spec.validators - 1))
    n_stale = int(op.get("stale_lanes", 1))
    state = {"snapshot_writes": 0, "snapshot_prunes": 0,
             "pack_builds": 0, "stale_submitted": 0}

    def shrink() -> None:
        for v in sim.validators:
            app = v.vnode.app
            # caps mutate IN PLACE: put() reads them live, and the ante
            # handler holds a construction-time reference to the sig
            # cache that a replacement would silently orphan
            app.eds_cache.max_entries = eds_entries
            app.sig_cache.maxsize = sig_cache
            app.commitment_cache.maxsize = commitment_cache
            v.vnode.pool.ttl_blocks = ttl_blocks
            if ttl_seconds > 0:
                v.vnode.pool.ttl_seconds = ttl_seconds
        sim.sched.note(f"op.soak.caps eds={eds_entries} sig={sig_cache} "
                       f"commitment={commitment_cache} "
                       f"ttl_blocks={ttl_blocks}")

    sim.at(0.0, shrink, "op.soak.caps")

    # the production node-loop's mempool TTL tick, on the virtual clock
    def expire_tick() -> None:
        for v in sim.validators:
            v.vnode.pool.expire(v.vnode.app.height)
        sim.sched.call_after(expire_every, expire_tick, "op.soak.expire")

    sim.at(expire_every, expire_tick, "op.soak.expire")

    # snapshot churn: write + keep-N prune at height marks (the
    # committer holds the height's state at its commit instant)
    snaproot = os.path.join(sim.workdir, "soak-snapshots")
    os.makedirs(snaproot, exist_ok=True)

    def snap(s: Simulation, committer) -> None:
        manifest, chunks = c.snapshot_app_chunks(committer.vnode.app)
        out = os.path.join(snaproot, str(int(manifest["height"])))
        if os.path.exists(out):
            return
        sync_mod.write_snapshot_dir(manifest, chunks, out)
        state["snapshot_writes"] += 1
        before = sum(
            1 for name in os.listdir(snaproot)
            if os.path.exists(os.path.join(snaproot, name,
                                           "manifest.json")))
        sync_mod.prune_snapshots(snaproot, keep=snapshot_keep)
        state["snapshot_prunes"] += max(0, before - snapshot_keep)
        s.sched.note(f"op.soak.snapshot h={manifest['height']}")

    for h in range(snapshot_every, sim.spec.heights + 1, snapshot_every):
        sim.on_commit_height(h, snap)

    # pack churn: one dedicated PackStore fed each marked height's
    # committed entry; build() itself prunes to keep-N
    pack_store = packs_mod.PackStore(
        os.path.join(sim.workdir, "soak-packs"), keep=pack_keep)

    def pack(s: Simulation, committer, h: int) -> None:
        entry = committer.core._entry(h).cache_entry
        pack_store.build(h, entry)
        state["pack_builds"] += 1
        s.sched.note(f"op.soak.pack h={h}")

    for h in range(pack_every, sim.spec.heights + 1, pack_every):
        sim.on_commit_height(h, lambda s, cm, h=h: pack(s, cm, h))

    # the stale-tx lane: sequence-0 sends with varying payloads into a
    # LAZY validator's pool only — it never proposes, so nothing ever
    # commits them and ONLY the TTL tick can drain the pool
    lazy = sim.validator_by_index(stale_to)
    lazy.lazy = True
    stale_privs = sim.claim_traffic_accounts(n_stale)
    sink = sim.privs[0].public_key().address()

    def stale_tick() -> None:
        for p in stale_privs:
            addr = p.public_key().address()
            acct = sim.signer.accounts[addr]
            acct.sequence = 0  # never commits: state sequence stays 0
            state["stale_submitted"] += 1
            tx = sim.signer.create_tx(
                addr, [MsgSend(addr, sink,
                               1000 + state["stale_submitted"])],
                fee=2000, gas_limit=100_000,
            )
            lazy.vnode.add_tx(tx.encode())
        sim.sched.call_after(stale_every, stale_tick, "op.soak.stale")

    sim.at(max(stale_every, 0.2), stale_tick, "op.soak.stale")

    def collect(s: Simulation) -> dict:
        apps = [v.vnode.app for v in s.validators]
        return {"soak": {
            "eds_evictions": sum(a.eds_cache.evictions for a in apps),
            "sig_evictions": sum(a.sig_cache.evictions for a in apps),
            "commitment_evictions": sum(
                a.commitment_cache.evictions for a in apps),
            "mempool_expired": sum(
                v.vnode.pool.metrics.counters.get("expired_height", 0)
                + v.vnode.pool.metrics.counters.get("expired_time", 0)
                for v in s.validators),
            "snapshot_writes": state["snapshot_writes"],
            "snapshot_prunes": state["snapshot_prunes"],
            "pack_builds": state["pack_builds"],
            "pack_prunes": max(0, state["pack_builds"] - pack_keep),
            "stale_submitted": state["stale_submitted"],
        }}

    expect["collectors"].append(collect)


def _install_eclipse(sim: Simulation, op: dict, expect: dict) -> None:
    t = float(op["t"])
    lights = [int(i) for i in op["lights"]]
    captor = sim.validator_by_index(int(op.get("validator", 0)))
    height = int(op["height"])
    expect.update(kind="withholding", fault_height=height)

    def eclipse() -> None:
        for i in lights:
            name = sim.lights[i % len(sim.lights)].name
            sim.net.allowed[name] = {captor.name}
        sim.sched.note(
            f"op.eclipse lights={len(lights)} captor={captor.name}")

    sim.at(t, eclipse, "op.eclipse")

    def arm(s: Simulation, committer) -> None:
        entry = committer.core._entry(height)
        captor.core.withhold(height,
                             _threshold_cells(entry, op.get("fraction")))
        s.sched.note(f"op.eclipse_withhold h={height}")

    sim.on_commit_height(height, arm)


def _statesync_join(sim: Simulation, idx: int) -> None:
    from celestia_app_tpu.chain import consensus as c

    joiner = sim.validator_by_index(idx)
    peer = next(
        (v for v in sim.validators
         if v is not joiner and v.name not in sim.net.down
         and v.vnode.app.height > joiner.vnode.app.height + 1),
        None,
    )
    if peer is not None:
        manifest, chunks = c.snapshot_app_chunks(peer.vnode.app)
        if int(manifest["height"]) > joiner.vnode.app.height:
            c.state_sync_bootstrap(joiner.vnode, manifest, chunks)
            sim.sched.note(
                f"op.statesync_join {joiner.name} "
                f"h={manifest['height']} from={peer.name}")
    joiner.go_up()


def _install_crash_storm(sim: Simulation, op: dict, expect: dict) -> None:
    heights = [int(h) for h in op["heights"]]
    victims = [int(i) for i in op["validators"]]
    down_s = float(op.get("down_s", 2.0))

    for h in heights:
        def crash(s: Simulation, _committer, h=h) -> None:
            # seeded pick at the post-commit instant — the in-process
            # stand-in for a crash fault at consensus.post_apply
            idx = victims[s.sched.rng.randrange(len(victims))]
            v = s.validator_by_index(idx)
            if not v.up:
                return  # already down: one outage at a time per victim
            v.go_down()
            s.sched.note(f"op.crash h={h} victim={v.name}")
            s.sched.call_after(down_s, v.go_up, f"op.revive {v.name}")

        sim.on_commit_height(h, crash)


# ---------------------------------------------------------------------------
# verdicts
# ---------------------------------------------------------------------------


def _liveness_gap(commit_times: dict[int, float]) -> float:
    prev = 0.0
    gap = 0.0
    for h in sorted(commit_times):
        gap = max(gap, commit_times[h] - prev)
        prev = commit_times[h]
    return round(gap, 9)


def _detection(sim: Simulation, expect: dict) -> tuple:
    """(blocks_to_detection, detection_t) for the armed fault."""
    kind, fh = expect["kind"], expect["fault_height"]
    if kind is None:
        return None, None
    if kind == "fraud":
        hits = [d for d in sim.light_halts
                if d.get("height") == fh
                and d.get("reason") == "bad-encoding"]
    else:
        hits = [d for d in sim.detections
                if d["height"] == fh
                and d["status"] in ("unavailable", "error")]
    if not hits:
        return None, None
    det_t = min(d["t"] for d in hits)
    committed_by_then = sum(
        1 for t in sim.commit_times.values() if t <= det_t)
    # blocks the chain grew between the fault's activation height and
    # detection (>= 1: detection within the fault height's own era).
    # A fraud height sits past the chain tip, so its activation is the
    # tip it was injected at.
    activation = min(fh, max(sim.commit_times, default=fh))
    return max(1, committed_by_then - activation + 1), det_t


def _false_condemnations(sim: Simulation, expect: dict) -> int:
    fh = expect["fault_height"] if expect["kind"] == "fraud" else None
    return sum(
        1 for halt in sim.light_halts
        if not (fh is not None and halt.get("height") == fh)
    )


def _recovery(sim: Simulation, expect: dict):
    """Virtual seconds from the last heal/up/join mark to the network
    being whole again: the marked validator (for `up`/`join`) — or EVERY
    validator (for `heal`) — back at the committed head, walking the
    per-validator commit/adoption log."""
    out = None
    for kind, t_op, target in expect["marks"]:
        out = None  # the LAST mark decides: an earlier success must
        # not mask a later recovery that never completed
        watch = ([target] if target is not None
                 else [v.name for v in sim.validators])
        cur = {name: 0 for name in (v.name for v in sim.validators)}
        head = 0
        for t, name, height in sorted(sim.val_commit_log):
            cur[name] = max(cur[name], height)
            head = max(head, height)
            if t >= t_op and min(cur[n] for n in watch) >= head:
                out = round(t - t_op, 9)
                break
    return out


def verdict_of(sim: Simulation, expect: dict) -> dict:
    blocks_to_detection, det_t = _detection(sim, expect)
    false_halts = _false_condemnations(sim, expect)
    n_lights = max(1, len(sim.lights))
    return {
        "scenario": sim.spec.name,
        "scheme": sim.spec.scheme,
        "seed": sim.spec.seed,
        "validators": len(sim.validators),
        "light_nodes": len(sim.lights),
        "heights": sim.spec.heights,
        "heights_committed": max(sim.commit_times, default=0),
        "liveness_gap_s": _liveness_gap(sim.commit_times),
        "blocks_to_detection": blocks_to_detection,
        "detection_t": det_t,
        "false_condemnation_rate": round(false_halts / n_lights, 9),
        "light_halts": len(sim.light_halts),
        "unavailable_reports": sum(
            1 for d in sim.detections if d["status"] == "unavailable"),
        "recovery_s": _recovery(sim, expect),
        "dropped_msgs": sim.net.dropped,
        "events": sim.sched.executed,
        "block_hashes": {str(h): sim.block_hashes[h]
                         for h in sorted(sim.block_hashes)},
        "app_hashes": {str(h): sim.app_hashes[h]
                       for h in sorted(sim.app_hashes)},
        "trace_digest": sim.sched.trace_digest(),
        # fleet-scale telemetry (FORMATS §19.2): how BIG this cell was,
        # and what the process peaked at getting there. peak_rss_bytes
        # is measured, not simulated — verdict_bytes drops it.
        "sim_lights": len(sim.lights),
        "sim_virtual_blocks": max(sim.commit_times, default=0),
        "peak_rss_bytes": engine.peak_rss_bytes(),
        "asym_msgs": {k: sim.net.asym_hits[k]
                      for k in sorted(sim.net.asym_hits)},
        # per-op blocks (traffic/spam/soak collectors installed by the
        # ops program; absent keys mean the op was not armed)
        **{k: v for fn in expect["collectors"]
           for k, v in fn(sim).items()},
    }


def verdict_bytes(verdict: dict) -> bytes:
    """The canonical byte form two same-seed runs must match exactly.

    `peak_rss_bytes` is excluded: it is a measurement of THIS process
    (allocator layout, import order, prior cells in the same run), not
    of the simulated world, so it legitimately differs between two
    byte-identical simulations."""
    return json.dumps({k: v for k, v in verdict.items()
                       if k != "peak_rss_bytes"},
                      sort_keys=True).encode()


# ---------------------------------------------------------------------------
# the library + runner
# ---------------------------------------------------------------------------

#: name -> (description, spec-builder(scheme, seed, **overrides) -> dict)
SCENARIOS: dict[str, tuple[str, object]] = {}


def _scenario(name: str, desc: str):
    def register(builder):
        SCENARIOS[name] = (desc, builder)
        return builder

    return register


def _base(name: str, scheme: str, seed: int, **over) -> dict:
    doc = {"name": name, "scheme": scheme, "seed": seed,
           "validators": 8, "light_nodes": 64, "heights": 5,
           "samples_per_header": 2}
    doc.update(over)
    return doc


@_scenario("honest", "fault-free chain: the false-condemnation and "
                     "cross-seed consensus-invariance control")
def _honest(scheme: str, seed: int, **over) -> dict:
    return _base("honest", scheme, seed, **over)


@_scenario("withhold-threshold",
           "every validator withholds one height past the scheme's "
           "recoverability threshold at its commit")
def _withhold(scheme: str, seed: int, **over) -> dict:
    doc = _base("withhold-threshold", scheme, seed, **over)
    fault_h = max(2, doc["heights"] - 1)
    doc["ops"] = [{"op": "withhold_threshold", "height": fault_h}]
    return doc


@_scenario("incorrect-coding",
           ">2/3 certify a committed non-codeword; the fleet escalates "
           "to a verified fraud proof and condemns the root")
def _incorrect(scheme: str, seed: int, **over) -> dict:
    doc = _base("incorrect-coding", scheme, seed, **over)
    doc.setdefault("duration", 0.0)
    doc["ops"] = [{"op": "incorrect_coding", "k": 4}]
    return doc


@_scenario("partition-churn",
           "a >1/3 minority is cut off mid-run and healed: the majority "
           "keeps committing, the minority catches up")
def _partition(scheme: str, seed: int, **over) -> dict:
    doc = _base("partition-churn", scheme, seed, **over)
    n = doc["validators"]
    minority = list(range(n - max(1, n // 4), n))
    majority = [i for i in range(n) if i not in minority]
    doc["ops"] = [
        {"op": "partition", "t": 2.2,
         "groups": [majority, minority]},
        {"op": "heal", "t": 6.2},
    ]
    return doc


@_scenario("lazy-validator",
           "one validator never proposes: its slots time out, rotate, "
           "and the chain stays live")
def _lazy(scheme: str, seed: int, **over) -> dict:
    doc = _base("lazy-validator", scheme, seed, **over)
    doc["ops"] = [{"op": "lazy", "validator": 1}]
    return doc


@_scenario("spam-flood",
           "sustained junk + oversized tx floods against every "
           "validator's admission path while real load commits")
def _spam(scheme: str, seed: int, **over) -> dict:
    doc = _base("spam-flood", scheme, seed, **over)
    doc.setdefault("txs_per_height", 1)
    doc["ops"] = [{"op": "spam", "t": 0.5, "every": 0.7, "count": 12,
                   "until": 6.0}]
    return doc


@_scenario("eclipse",
           "a slice of the light fleet sees only one captor validator, "
           "which withholds a height from them alone")
def _eclipse(scheme: str, seed: int, **over) -> dict:
    doc = _base("eclipse", scheme, seed, **over)
    fault_h = max(2, doc["heights"] - 1)
    doc["ops"] = [{"op": "eclipse", "t": 0.2,
                   "lights": list(range(doc["light_nodes"] // 2)),
                   "validator": 0, "height": fault_h}]
    return doc


@_scenario("crash-storm",
           "seeded validator crashes at post-commit instants across a "
           "height window, each reviving and catching up")
def _crash(scheme: str, seed: int, **over) -> dict:
    doc = _base("crash-storm", scheme, seed, **over)
    n = doc["validators"]
    doc["ops"] = [{"op": "crash_storm",
                   "heights": [2, 3],
                   "validators": list(range(n // 2, n)),
                   "down_s": 2.5}]
    return doc


@_scenario("flaky-network",
           "seeded probabilistic drops on the light fleet's transport "
           "(the net.request fault point): rotation + retries absorb "
           "them, sampling verdicts stay clean")
def _flaky(scheme: str, seed: int, **over) -> dict:
    doc = _base("flaky-network", scheme, seed, **over)
    doc["faults"] = [{"point": "net.request", "action": "drop",
                      "prob": 0.25, "match": {"owner": "^light"}}]
    return doc


@_scenario("statesync-join",
           "a validator dark since genesis snapshot-joins mid-run under "
           "load and catches up to the head")
def _join(scheme: str, seed: int, **over) -> dict:
    doc = _base("statesync-join", scheme, seed, **over)
    idx = doc["validators"] - 1
    doc["ops"] = [
        {"op": "down", "t": 0.0, "validator": idx},
        {"op": "statesync_join", "t": 4.2, "validator": idx},
    ]
    return doc


@_scenario("long-soak",
           "long-horizon resource churn: every bounded resource (EDS/"
           "sig/commitment LRUs, mempool TTL, snapshot keep-N, pack "
           "prune) cycles >=2x under seeded PFB traffic and asymmetric "
           "per-message faults, with graceful-degradation verdicts")
def _long_soak(scheme: str, seed: int, **over) -> dict:
    doc = _base("long-soak", scheme, seed,
                validators=4, light_nodes=24, heights=30,
                samples_per_header=2, txs_per_height=1,
                sweep_interval=2.0, trace_keep=50_000)
    doc.update(over)
    doc.setdefault("ops", [
        {"op": "traffic", "t": 0.8, "every": 0.9, "sequences": 2,
         "pfbs_per_wave": 1},
        {"op": "asym_fault", "kind": "corrupt", "src": "light",
         "prob": 0.15},
        {"op": "asym_fault", "kind": "delay", "src": "light",
         "prob": 0.1, "delay": 0.05},
        {"op": "soak", "eds_entries": 2, "sig_cache": 24,
         "commitment_cache": 12, "ttl_blocks": 3, "expire_every": 1.0,
         "snapshot_every": 4, "snapshot_keep": 2,
         "pack_every": 3, "pack_keep": 2, "stale_every": 0.8},
    ])
    return doc


@_scenario("fleet-scale",
           "the network-scale determinism cell: 1000+ continuation-"
           "driven DASer lights over 1000+ virtual blocks in one "
           "process, byte-identical verdicts per seed")
def _fleet_scale(scheme: str, seed: int, **over) -> dict:
    doc = _base("fleet-scale", scheme, seed,
                validators=4, light_nodes=1000, heights=1000,
                samples_per_header=1, txs_per_height=0,
                sweep_interval=5.0, light_job_size=64,
                max_events=6_000_000, trace_keep=100_000)
    doc.update(over)
    return doc


def scenario_spec(name: str, scheme: str = "rs2d-nmt", seed: int = 0,
                  **over) -> dict:
    """The library's named spec, as a plain dict (edit freely)."""
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r}; one of {sorted(SCENARIOS)}")
    _desc, builder = SCENARIOS[name]
    return builder(scheme, seed, **over)


def run_scenario(doc: dict, workdir: str | None = None,
                 ccfg: SimConsensusConfig | None = None) -> dict:
    """Build, run, and reduce one scenario spec to its verdict dict.
    ``faults`` specs are armed on the process fault registry (reseeded
    to the scenario seed so probabilistic triggers replay exactly) for
    the run's duration and disarmed after — the scenario grammar's
    third leg beside malicious entries and topology ops."""
    from celestia_app_tpu import faults as faults_mod

    spec = SimSpec.from_dict(doc)
    if ccfg is None and "consensus" in doc:
        ccfg = SimConsensusConfig(**doc["consensus"])
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix=f"sim-{spec.name}-")
    sim = Simulation(spec, workdir, ccfg=ccfg)
    expect = _install_ops(sim)
    armed: list[int] = []
    if spec.faults:
        faults_mod.REGISTRY.reseed(spec.seed)
        armed = faults_mod.arm_from_spec([dict(f) for f in spec.faults])
    try:
        sim.run()
    finally:
        for fid in armed:
            faults_mod.disarm(fault_id=fid)
    return verdict_of(sim, expect)
