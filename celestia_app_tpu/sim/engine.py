"""The simulation world: validators, light nodes, topology, verdicts.

One :class:`Simulation` runs tens of ``ValidatorNode``-backed consensus
reactors plus hundreds of real ``das/daser.DASer`` light nodes on ONE
seeded virtual timeline (sim/scheduler.py):

- :class:`SimNet` — topology: partition groups, node up/down, eclipse
  allowlists, and seeded per-message latency for consensus gossip.
- :class:`SimTransport` — a PeerClient-shaped direct-call transport: the
  DASer's PeerSet speaks the REAL wire routes (/das/*, /ibc/header)
  against the REAL das/server.SampleCore of each validator, with no HTTP
  and no real sockets, so a hundred samplers cost function calls. Every
  request still passes the ``net.request`` fault point, so seeded fault
  specs act here exactly as on the production transport.
- :class:`SimValidator` — an event-driven Tendermint round machine over
  ``chain/consensus.ValidatorNode``: propose → prevote → (polka? lock) →
  precommit → commit as scheduler events with per-message latency and
  phase timeouts, the same vote/lock/apply primitives the production
  reactor uses (prevote_on runs ProcessProposal; apply runs the full
  finalize+commit with certificate-derived presence accounting). The
  engine never perturbs consensus bytes: proposal timestamps come from
  the fixed per-height schedule, so fault-free runs commit identical
  block and app hashes under EVERY seed (pinned in
  tests/test_scenarios.py).
- :class:`SimLightNode` — a real DASer (virtual clock injected) swept on
  the timeline: verified header following through its own LightClient,
  sampling/retry/escalation/fraud-proof assembly, halting — all the
  production code paths, hundreds of instances in one process.

Determinism contract: a Simulation executes ONE event at a time on the
caller's thread; all randomness (event tiebreaks, latencies, sampler
draws) descends from the one scenario seed; all time descends from the
one VirtualClock. Consensus-vote gossip is only ever faulted
symmetrically (partitions and whole-node downs — never probabilistic
per-message drops), so every validator that assembles a certificate for
a height assembles the same one and presence accounting cannot fork
app hashes within a run. Background warmer threads only pre-build
caches whose contents are content-addressed; verdicts never read them.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import os
import sys
import urllib.parse

import numpy as np

from celestia_app_tpu import faults
from celestia_app_tpu.chain import consensus as c
from celestia_app_tpu.chain import light as light_mod
from celestia_app_tpu.chain.crypto import PrivateKey
from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
from celestia_app_tpu.das.checkpoint import Checkpoint
from celestia_app_tpu.das.daser import DASer, DASerConfig, PeerSet
from celestia_app_tpu.das.server import SampleCore, SampleError, route_das
from celestia_app_tpu.net.transport import TransportError
from celestia_app_tpu.sim.scheduler import Scheduler


# ---------------------------------------------------------------------------
# topology + transport
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AsymRule:
    """One deterministic per-message asymmetric fault (FORMATS §19.1
    ``asym_fault`` op). Matched by PREFIX on (requester name, serving
    peer name, wire path); whether a matched message actually faults is
    a pure function of ``sha256(seed|src|dst|path|msg-index)`` — the
    per-message determinism the continuation-DASer makes possible (no
    thread interleaving decides which request draws the fault). Applies
    only to SimTransport requests (the light fleet's plane); consensus
    vote gossip is never asymmetrically faulted — see the module
    docstring's determinism contract."""

    kind: str          # "drop" | "delay" | "corrupt"
    src: str = ""      # requester name prefix ("" = any)
    dst: str = ""      # serving peer name prefix
    path: str = ""     # wire path prefix (query string excluded)
    prob: float = 1.0
    delay: float = 0.05  # virtual seconds (kind="delay")
    seed: int = 0


class SimNet:
    """Who can reach whom, and how late. Registered handlers answer the
    wire routes for ``sim://<name>`` URLs; partition groups / down sets /
    eclipse allowlists gate every delivery and every direct request."""

    def __init__(self, sched: Scheduler, latency: tuple[float, float]):
        self.sched = sched
        self.latency = latency
        self.handlers: dict[str, object] = {}  # "sim://name" -> router fn
        self.group: dict[str, int] = {}  # partition group (default 0)
        self.down: set[str] = set()
        # light-node eclipse: name -> allowed peer names (None = all)
        self.allowed: dict[str, set[str] | None] = {}
        self.dropped = 0
        # per-message asymmetric faults: armed rules, the per-(src, dst,
        # path) message counters that key them, and fired-fault tallies
        self.asym_rules: list[AsymRule] = []
        self.asym_index: dict[tuple[str, str, str], int] = {}
        self.asym_hits: dict[str, int] = {}

    # -- asymmetric per-message faults ----------------------------------

    def asym_match(self, src: str, dst: str, path: str) -> AsymRule | None:
        """The first armed rule that fires for THIS message, advancing
        the (src, dst, path) message index either way so arming or
        removing one rule never re-keys another's decisions."""
        key = (src, dst, path)
        idx = self.asym_index.get(key, 0)
        self.asym_index[key] = idx + 1
        for rule in self.asym_rules:
            if not (src.startswith(rule.src) and dst.startswith(rule.dst)
                    and path.startswith(rule.path)):
                continue
            digest = hashlib.sha256(
                f"{rule.seed}|{src}|{dst}|{path}|{idx}".encode()).digest()
            if int.from_bytes(digest[:8], "big") / 2.0**64 < rule.prob:
                self.asym_hits[rule.kind] = \
                    self.asym_hits.get(rule.kind, 0) + 1
                return rule
        return None

    @staticmethod
    def tamper(doc, src: str, dst: str, path: str, idx: int):
        """Deterministically corrupt one served value: flip one
        character of the first long-enough string (or one byte of a
        bytes value), chosen by the same message key that fired the
        rule. Structure stays parseable — the damage must be caught by
        VERIFICATION (proof/commitment checks), not by a JSON error."""
        doc = copy.deepcopy(doc)
        targets: list[tuple] = []

        def walk(node, setter):
            if isinstance(node, str) and len(node) >= 16:
                targets.append((node, setter))
            elif isinstance(node, (bytes, bytearray)) and len(node) >= 1:
                targets.append((node, setter))
            elif isinstance(node, dict):
                for k in sorted(node):
                    walk(node[k], lambda v, n=node, k=k: n.__setitem__(k, v))
            elif isinstance(node, list):
                for i, item in enumerate(node):
                    walk(item, lambda v, n=node, i=i: n.__setitem__(i, v))

        box = [doc]
        walk(doc, lambda v: box.__setitem__(0, v))
        if not targets:
            return doc
        digest = hashlib.sha256(
            f"tamper|{src}|{dst}|{path}|{idx}".encode()).digest()
        value, setter = targets[int.from_bytes(digest[:4], "big")
                                % len(targets)]
        pos = int.from_bytes(digest[4:8], "big") % len(value)
        if isinstance(value, str):
            repl = "0" if value[pos] != "0" else "1"
            setter(value[:pos] + repl + value[pos + 1:])
        else:
            flipped = bytearray(value)
            flipped[pos] ^= 0xFF
            setter(bytes(flipped))
        return box[0]

    def register(self, name: str, router) -> str:
        url = f"sim://{name}"
        self.handlers[url] = router
        return url

    def link_ok(self, a: str, b: str) -> bool:
        if a in self.down or b in self.down:
            return False
        if self.group.get(a, 0) != self.group.get(b, 0):
            return False
        for src, dst in ((a, b), (b, a)):
            allow = self.allowed.get(src)
            if allow is not None and dst not in allow:
                return False
        return True

    def draw_latency(self) -> float:
        lo, hi = self.latency
        return lo + (hi - lo) * self.sched.rng.random()

    def deliver(self, src: str, dst: str, fn, label: str) -> None:
        """Schedule a one-way message: dropped when the link is cut NOW
        (a partition heal never resurrects in-flight messages — they
        were sent into the void)."""
        if not self.link_ok(src, dst):
            self.dropped += 1
            return
        self.sched.call_after(self.draw_latency(), fn, label)


class SimTransport:
    """PeerClient-shaped direct-call transport over SimNet handlers.

    Serves the DASer's PeerSet: ``request(url, path, payload, raw=)``
    plus the ``available``/``penalize``/``snapshot`` surface. Requests
    are synchronous function calls (zero virtual latency — scheduled
    events carry the timeline; retry backoffs in the callers advance it),
    but every one passes the ``net.request`` fault point with the same
    context the production transport fires, so scenario fault specs
    (drop/error, matched on owner/peer/path) behave identically here."""

    def __init__(self, net: SimNet, owner: str):
        self.net = net
        self.owner = owner
        self.penalties: dict[str, int] = {}

    def request(self, url: str, path: str, payload: dict | None = None,
                *, timeout: float | None = None,
                retries: int | None = None, raw: bool = False):
        url = url.rstrip("/")
        dst = url[len("sim://"):]
        if not self.net.link_ok(self.owner, dst):
            raise TransportError(f"{self.owner}: no route to {url}")
        action = faults.fire("net.request", owner=self.owner, peer=url,
                            path=path)
        if action in ("drop", "error"):
            raise TransportError(f"injected fault: {action} {url}{path}")
        router = self.net.handlers.get(url)
        if router is None:
            raise TransportError(f"unknown sim peer {url}")
        parsed = urllib.parse.urlparse(path)
        query = urllib.parse.parse_qs(parsed.query)
        # the per-message asymmetric fault point: keyed by the message
        # index this request draws (query excluded so the key space
        # stays bounded); drop raises before the route runs, delay costs
        # virtual seconds, corrupt tampers the served doc after
        rule = self.net.asym_match(self.owner, dst, parsed.path)
        msg_idx = self.net.asym_index[(self.owner, dst, parsed.path)] - 1
        if rule is not None and rule.kind == "drop":
            raise TransportError(f"asym fault: drop {url}{parsed.path}")
        if rule is not None and rule.kind == "delay":
            self.net.sched.clock.sleep(rule.delay)
        method = "GET" if payload is None else "POST"
        try:
            out = router(method, parsed.path, query, payload)
        except SampleError as e:
            # the HTTP services answer 4xx here; to the rotating caller
            # that is a refusal to retry elsewhere
            raise ValueError(str(e)) from None
        if action == "duplicate":
            out = router(method, parsed.path, query, payload)
        if rule is not None and rule.kind == "corrupt":
            out = self.net.tamper(out, self.owner, dst, parsed.path,
                                  msg_idx)
        return out

    def get(self, url: str, path: str, **kw):
        return self.request(url, path, None, **kw)

    def post(self, url: str, path: str, payload: dict, **kw):
        return self.request(url, path, payload, **kw)

    def available(self, url: str) -> bool:
        dst = url.rstrip("/")[len("sim://"):]
        return self.net.link_ok(self.owner, dst)

    def penalize(self, url: str, reason: str) -> None:
        self.penalties[url] = self.penalties.get(url, 0) + 1

    def health_snapshot(self) -> dict:
        """The PeerClient.snapshot() analog, under its own name — the
        shared `snapshot` spelling would alias this class into the
        state-snapshot call graph the analysis plane walks."""
        return {"penalties": dict(self.penalties)}


def peak_rss_bytes() -> int:
    """This process's peak resident set in bytes (the verdict's memory
    number: scale claims need one). Linux ru_maxrss is KiB, macOS is
    bytes; 0 where getrusage is unavailable. NOT run-deterministic —
    verdict_bytes excludes it from the byte-identity form."""
    try:
        import resource
    except ImportError:
        return 0
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(peak) * (1 if sys.platform == "darwin" else 1024)


class MemoryCheckpointStore:
    """In-memory stand-in for das/checkpoint.CheckpointStore: hundreds of
    simulated samplers need no fsync'd file each."""

    def __init__(self):
        self.doc: dict | None = None

    def load(self) -> Checkpoint:
        return (Checkpoint() if self.doc is None
                else Checkpoint.from_json(self.doc))

    def save_doc(self, doc: dict) -> None:
        self.doc = doc


# ---------------------------------------------------------------------------
# the validator reactor (event-driven)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SimConsensusConfig:
    """Phase timeouts and pacing, all in VIRTUAL seconds. Defaults are
    sized so a fault-free round completes in a few latency hops and a
    dead proposer costs one timeout_propose; commit_grace must exceed the
    worst-case message latency so every live validator's precommit is in
    every certificate (the determinism note in the module docstring)."""

    timeout_propose: float = 3.0
    timeout_prevote: float = 2.0
    timeout_precommit: float = 2.0
    # after quorum: wait for stragglers (Tendermint TimeoutCommit) unless
    # every validator's precommit already arrived
    commit_grace: float = 0.5
    block_interval: float = 1.0  # pause between committed heights
    block_time: float = 10.0  # header timestamp spacing (chain seconds)
    catchup_poll: float = 1.0  # laggard pull probe period
    catchup_batch: int = 64  # heights replayed per poll


class SimValidator:
    """One validator as scheduler events over a ValidatorNode."""

    def __init__(self, sim: "Simulation", index: int, vnode):
        self.sim = sim
        self.index = index
        self.vnode = vnode
        self.name = vnode.name
        self.core = SampleCore(vnode.app)
        self.cfg = sim.ccfg
        self.lazy = False  # never proposes (scenario op)
        # (height, round) currently being worked + the step within it;
        # stale timeout events compare against these and no-op
        self.cur: tuple[int, int] = (0, 0)
        self.step = "idle"
        self.proposals: dict[tuple[int, int], c.Block] = {}
        self.prevotes: dict[tuple[int, int], dict[bytes, c.Vote]] = {}
        self.precommits: dict[tuple[int, int], dict[bytes, c.Vote]] = {}
        self.records: dict[int, tuple] = {}  # height -> (block, cert)
        self.pending: dict[int, tuple] = {}  # future gossiped commits
        self.app_hashes: dict[int, str] = {}
        self._poll_i = 0  # catch-up peer rotation cursor

    # -- helpers ---------------------------------------------------------

    @property
    def up(self) -> bool:
        return self.name not in self.sim.net.down

    def _powers(self) -> dict[bytes, int]:
        app = self.vnode.app
        ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                      app.chain_id, app.app_version)
        return dict(app.staking.validators(ctx))

    def _rotation(self) -> list[bytes]:
        known = self.vnode.known_pubkeys()
        rot = sorted(op for op in self._powers() if op in known)
        return rot or sorted(known)

    def proposer_for(self, height: int, round_: int) -> bytes:
        rot = self._rotation()
        return rot[(height + round_) % len(rot)]

    def _broadcast(self, kind: str, payload: tuple) -> None:
        for peer in self.sim.validators:
            if peer is self:
                continue
            self.sim.net.deliver(
                self.name, peer.name,
                lambda p=peer: p.on_message(kind, payload),
                f"{peer.name}.on_{kind}",
            )

    # -- height/round lifecycle -----------------------------------------

    def begin_height(self, height: int) -> None:
        if not self.up:
            return
        if self.vnode.app.height + 1 != height:
            return  # stale schedule (a gossiped commit advanced us)
        if height > self.sim.spec.heights:
            self.step = "idle"
            return  # target chain length reached: stop producing
        self.cur = (height, 0)
        self.start_round(height, 0)

    def _schedule_next_height(self) -> None:
        nxt = self.vnode.app.height + 1
        self.sim.sched.call_after(
            self.cfg.block_interval,
            lambda h=nxt: self.begin_height(h),
            f"{self.name}.begin_height h={nxt}",
        )

    def start_round(self, height: int, round_: int) -> None:
        if not self.up or self.cur != (height, round_):
            return
        self.step = "propose"
        proposer = self.proposer_for(height, round_)
        if proposer == self.vnode.address and not self.lazy:
            self.sim.tx_hook(height, self)
            block = self.vnode.propose(t=self.sim.block_timestamp(height))
            self.proposals[(height, round_)] = block
            self._broadcast("proposal", (height, round_, block))
            self._enter_prevote(height, round_, block)
            return
        got = self.proposals.get((height, round_))
        if got is not None:
            # the proposal outran our inter-height pause: prevote NOW —
            # waiting for the propose timeout here would leave this
            # node's precommit out of an otherwise-full certificate,
            # leaking event timing into presence accounting
            self._enter_prevote(height, round_, got)
            return
        self.sim.sched.call_after(
            self.cfg.timeout_propose,
            lambda: self._on_propose_timeout(height, round_),
            f"{self.name}.propose_timeout h={height} r={round_}",
        )

    def _on_propose_timeout(self, height: int, round_: int) -> None:
        if not self.up or self.cur != (height, round_) \
                or self.step != "propose":
            return
        self._enter_prevote(height, round_, None)

    def _acceptable(self, block: c.Block, height: int,
                    round_: int) -> bool:
        hdr = block.header
        return (hdr.height == height
                and hdr.last_block_hash == self.vnode.app.last_block_hash)

    def _enter_prevote(self, height: int, round_: int,
                       block: c.Block | None) -> None:
        self.step = "prevote"
        if block is not None and self._acceptable(block, height, round_):
            pv = self.vnode.prevote_on(block, round_)  # ProcessProposal
        else:
            pv = self.vnode._signed(height, None, "prevote", round_)
        self._record_vote(pv)
        self._broadcast("vote", (pv,))
        self.sim.sched.call_after(
            self.cfg.timeout_prevote,
            lambda: self._on_prevote_timeout(height, round_),
            f"{self.name}.prevote_timeout h={height} r={round_}",
        )
        self._check_polka(height, round_)

    def _on_prevote_timeout(self, height: int, round_: int) -> None:
        if not self.up or self.cur != (height, round_) \
                or self.step != "prevote":
            return
        # no polka observed in time: precommit nil, keep listening
        self._enter_precommit(height, round_, None)

    def _enter_precommit(self, height: int, round_: int,
                         block: c.Block | None) -> None:
        self.step = "precommit"
        if block is not None:
            self.vnode.on_polka(block, round_)
            pc = self.vnode.precommit_on(block, round_)
        else:
            pc = self.vnode.precommit_on(None, round_)
        self._record_vote(pc)
        self._broadcast("vote", (pc,))
        self.sim.sched.call_after(
            self.cfg.timeout_precommit,
            lambda: self._on_precommit_timeout(height, round_),
            f"{self.name}.precommit_timeout h={height} r={round_}",
        )
        self._check_quorum(height, round_)

    def _on_precommit_timeout(self, height: int, round_: int) -> None:
        if not self.up or self.cur != (height, round_) \
                or self.step != "precommit":
            return
        self._fail_round(height, round_)

    def _fail_round(self, height: int, round_: int) -> None:
        self.sim.sched.note(f"{self.name}.round_failed h={height} "
                            f"r={round_}")
        self.cur = (height, round_ + 1)
        self.start_round(height, round_ + 1)

    # -- gossip intake ---------------------------------------------------

    def on_message(self, kind: str, payload: tuple) -> None:
        if not self.up:
            return
        if kind == "proposal":
            height, round_, block = payload
            self.proposals.setdefault((height, round_), block)
            if self.cur == (height, round_) and self.step == "propose":
                self._enter_prevote(height, round_, block)
        elif kind == "vote":
            (vote,) = payload
            self._record_vote(vote)
            if self.cur == (vote.height, vote.round):
                if vote.phase == "prevote" and self.step == "prevote":
                    self._check_polka(vote.height, vote.round)
                elif vote.phase == "precommit" \
                        and self.step in ("precommit", "commit-wait"):
                    self._check_quorum(vote.height, vote.round)
        elif kind == "commit":
            height, block, cert = payload
            if height == self.vnode.app.height + 1:
                if self._adopt(block, cert):
                    self._drain_pending()
                    self._schedule_next_height()
            elif height > self.vnode.app.height + 1:
                self.pending.setdefault(height, (block, cert))

    def _record_vote(self, vote: c.Vote) -> None:
        pool = self.prevotes if vote.phase == "prevote" else self.precommits
        pool.setdefault((vote.height, vote.round), {}) \
            .setdefault(vote.validator, vote)

    # -- tallies ---------------------------------------------------------

    def _check_polka(self, height: int, round_: int) -> None:
        if self.cur != (height, round_) or self.step != "prevote":
            return
        powers = self._powers()
        total = sum(powers.values())
        pool = self.prevotes.get((height, round_), {})
        by_hash: dict[bytes, int] = {}
        nil_power = 0
        for v in pool.values():
            p = powers.get(v.validator, 0)
            if v.block_hash is None:
                nil_power += p
            else:
                by_hash[v.block_hash] = by_hash.get(v.block_hash, 0) + p
        for bh in sorted(by_hash):
            if by_hash[bh] * 3 <= total * 2:
                continue
            prop = self.proposals.get((height, round_))
            mine = pool.get(self.vnode.address)
            if (prop is not None and prop.header.hash() == bh
                    and mine is not None and mine.block_hash == bh
                    and self.vnode.lock_permits(bh, round_)):
                self._enter_precommit(height, round_, prop)
            else:
                self._enter_precommit(height, round_, None)
            return
        if nil_power * 3 > total * 2:
            self._fail_round(height, round_)

    def _check_quorum(self, height: int, round_: int) -> None:
        if self.cur != (height, round_) \
                or self.step not in ("precommit", "commit-wait"):
            return
        powers = self._powers()
        total = sum(powers.values())
        pool = self.precommits.get((height, round_), {})
        by_hash: dict[bytes, int] = {}
        for v in pool.values():
            if v.block_hash is not None:
                by_hash[v.block_hash] = (by_hash.get(v.block_hash, 0)
                                         + powers.get(v.validator, 0))
        for bh in sorted(by_hash):
            if by_hash[bh] * 3 <= total * 2:
                continue
            if self.proposals.get((height, round_)) is None or \
                    self.proposals[(height, round_)].header.hash() != bh:
                return  # cert without the block: let gossip deliver it
            have = sum(1 for v in pool.values() if v.block_hash == bh)
            if have == len(powers):
                # every validator's precommit arrived: commit NOW (the
                # fault-free fast path — certificates are full and
                # therefore identical at every assembler)
                self._finalize(height, round_, bh)
            elif self.step != "commit-wait":
                # quorum but stragglers possible: Tendermint's
                # TimeoutCommit — wait a grace so every live vote lands
                # in the certificate before it freezes
                self.step = "commit-wait"
                self.sim.sched.call_after(
                    self.cfg.commit_grace,
                    lambda: self._finalize(height, round_, bh),
                    f"{self.name}.commit_grace h={height} r={round_}",
                )
            return

    # -- commit ----------------------------------------------------------

    def _finalize(self, height: int, round_: int, bh: bytes) -> None:
        if not self.up or self.vnode.app.height >= height:
            return
        pool = self.precommits.get((height, round_), {})
        votes = tuple(pool[a] for a in sorted(pool)
                      if pool[a].block_hash == bh)
        cert = c.CommitCertificate(height, bh, votes, round_)
        block = self.proposals[(height, round_)]
        ah = self.vnode.apply(block, cert)
        self.vnode.clear_lock()
        self.app_hashes[height] = ah.hex()
        self.records[height] = (block, cert)
        self.step = "committed"
        self._prune(height)
        self.sim._note_commit(self, height, block, cert)
        self._broadcast("commit", (height, block, cert))
        self._schedule_next_height()

    def _adopt(self, block: c.Block, cert: c.CommitCertificate) -> bool:
        """Laggard path: apply a gossiped/pulled commit after full local
        verification (cert against OUR trust roots, then ProcessProposal
        — a tampered record must never advance the chain)."""
        vnode = self.vnode
        height = vnode.app.height + 1
        if cert.height != height \
                or cert.block_hash != block.header.hash():
            return False
        if block.header.last_block_hash != vnode.app.last_block_hash:
            return False
        if not vnode.verify_certificate(cert):
            return False
        if not vnode.app.process_proposal(block):
            return False
        ah = vnode.apply(block, cert)
        vnode.clear_lock()
        self.app_hashes[height] = ah.hex()
        self.records[height] = (block, cert)
        self._prune(height)
        self.sim._note_commit(self, height, block, cert, adopted=True)
        return True

    def _drain_pending(self) -> None:
        while True:
            nxt = self.vnode.app.height + 1
            got = self.pending.pop(nxt, None)
            if got is None or not self._adopt(*got):
                break

    def _prune(self, height: int) -> None:
        floor = height  # keep only the live height's round state
        for pool in (self.proposals, self.prevotes, self.precommits):
            for key in [k for k in pool if k[0] <= floor]:
                del pool[key]
        for h in [h for h in self.pending if h <= floor]:
            del self.pending[h]

    # -- catch-up (partition heal / restart / late join) -----------------

    def catchup_poll(self) -> None:
        """Periodic pull probe: ask one reachable peer (seeded rotation)
        for commit records above our height and replay them through the
        verified _adopt path — the sim analog of the reactor's
        blocksync. Reschedules itself for the simulation's lifetime."""
        if self.up:
            peers = [p for p in self.sim.validators if p is not self]
            for off in range(len(peers)):
                peer = peers[(self._poll_i + off) % len(peers)]
                if not self.sim.net.link_ok(self.name, peer.name):
                    continue
                nxt = self.vnode.app.height + 1
                if nxt not in peer.records:
                    continue
                applied = 0
                while applied < self.cfg.catchup_batch:
                    got = peer.records.get(self.vnode.app.height + 1)
                    if got is None or not self._adopt(*got):
                        break
                    applied += 1
                if applied:
                    self.sim.sched.note(
                        f"{self.name}.catchup applied={applied} "
                        f"from={peer.name}")
                    self._schedule_next_height()
                    break
            self._poll_i += 1
        self.sim.sched.call_after(
            self.cfg.catchup_poll, self.catchup_poll, "")

    # -- scenario ops ----------------------------------------------------

    def go_down(self) -> None:
        self.sim.net.down.add(self.name)
        self.step = "down"
        self.sim.sched.note(f"{self.name}.down")

    def go_up(self) -> None:
        self.sim.net.down.discard(self.name)
        self.sim.sched.note(f"{self.name}.up")
        self.vnode.clear_lock()
        self._schedule_next_height()

    # -- the wire routes (SimTransport handler) --------------------------

    def route(self, method: str, path: str, query: dict, payload):
        if path.startswith("/das/"):
            return route_das(self.core, method, path, query, payload)
        if path == "/ibc/header" and method == "POST":
            height = int((payload or {})["height"])
            got = self.records.get(height)
            if got is None:
                raise SampleError(f"height {height} not certified here")
            block, cert = got
            return {"header": c.header_to_json(block.header),
                    "cert": c.cert_to_json(cert)}
        if path == "/consensus/height":
            return {"height": self.vnode.app.height}
        raise SampleError(f"no sim route {method} {path}")


# ---------------------------------------------------------------------------
# light nodes
# ---------------------------------------------------------------------------


class SimLightNode:
    """One DASer light node on the virtual timeline."""

    def __init__(self, sim: "Simulation", index: int):
        self.sim = sim
        self.index = index
        self.name = f"light{index}"
        spec = sim.spec
        urls = [f"sim://{v.name}" for v in sim.validators]
        transport = SimTransport(sim.net, self.name)
        peers = PeerSet(urls, retries=2, backoff=0.02, client=transport,
                        clock=sim.sched.clock)
        trust = light_mod.TrustedState(
            height=0, header_hash=b"",
            validators={v.vnode.address:
                        v.vnode.priv.public_key().compressed
                        for v in sim.validators},
            powers={v.vnode.address: 10 for v in sim.validators},
        )
        from celestia_app_tpu.das import daser as daser_mod

        base = daser_mod.http_header_source(peers)

        def source(h: int):
            forged = sim.forged_headers.get(h)
            if forged is not None:
                return forged
            return base(h)

        cfg = DASerConfig(
            samples_per_header=spec.samples_per_header,
            workers=1, job_size=spec.light_job_size, retries=2,
            backoff=0.02, prefer_packs=False,
            # long-horizon runs: the checkpoint is the durable record;
            # reports and the span tables stay O(1) per light
            report_keep=64,
        )
        # one independent child stream per light node off the scenario
        # seed: sampler draws are seeded end to end, never ambient
        rng = np.random.default_rng([spec.seed, 7700 + index])  # lint: disable=det-rng
        self.daser = DASer(
            peers, light_mod.LightClient(sim.chain_id, trust),
            MemoryCheckpointStore(), cfg=cfg, header_source=source,
            rng=rng, name=self.name, clock=sim.sched.clock,
        )
        self.daser.traces.MAX_ROWS = 256
        self._seen: dict[int, str] = {}  # height -> last reported status
        self._cont = None  # the in-flight SweepCont (continuation mode)
        self.halt: dict | None = None

    def sweep(self) -> None:
        """One CONTINUATION STEP of the current sweep — not a whole
        sweep per event. Each firing advances the DASer's SweepCont by
        one bounded unit (plan, one catch-up job, or fold) and yields
        the timeline back, so a 1000-light fleet interleaves at job
        granularity under the scheduler's seeded tiebreaks instead of
        each light monopolizing an instant (or an OS thread)."""
        if self.name in self.sim.net.down:
            self._cont = None  # a downed node abandons its sweep
            self._reschedule()
            return
        if self.daser.halted:
            self._note_halt()
            return  # terminal: no more sweeps for this node
        if self._cont is None:
            self._cont = self.daser.begin_sweep()
        if self._cont.step():
            self.sim.sched.call_after(0.0, self.sweep,
                                      f"{self.name}.step")
            return
        cont, self._cont = self._cont, None
        for h in sorted(cont.results):
            rep = cont.results[h]
            if self._seen.get(h) != rep["status"]:
                self._seen[h] = rep["status"]
                self.sim._note_report(self, h, rep)
        # drop dedup entries below the never-resampled floor (heights
        # the checkpoint durably completed): _seen stays O(window)
        with self.daser._lock:
            floor = min([self.daser.cp.sample_from]
                        + sorted(self.daser.cp.failed)[:1])
        for h in [h for h in self._seen if h < floor]:
            del self._seen[h]
        if self.daser.halted:
            self._note_halt()
            return
        self._reschedule()

    def _note_halt(self) -> None:
        if self.halt is None:
            with self.daser._lock:
                self.halt = dict(self.daser.cp.halted or {})
            self.sim._note_light_halt(self, self.halt)

    def _reschedule(self) -> None:
        self.sim.sched.call_after(
            self.sim.spec.sweep_interval, self.sweep,
            f"{self.name}.sweep",
        )


# ---------------------------------------------------------------------------
# the simulation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SimSpec:
    """The declarative world description (FORMATS.md §19.1). ``ops`` is
    the adversarial program — see sim/scenarios.py for the op grammar."""

    name: str = "honest"
    seed: int = 0
    validators: int = 8
    light_nodes: int = 64
    heights: int = 6
    scheme: str = "rs2d-nmt"
    samples_per_header: int = 2
    txs_per_height: int = 0
    sweep_interval: float = 1.0
    latency: tuple[float, float] = (0.005, 0.02)
    duration: float = 0.0  # 0 = auto from heights
    # network-scale knobs (FORMATS §19.1): catch-up job width for the
    # light fleet's continuation sweeps, the scheduler's runaway bound
    # (0 = its default), and trace-row retention (0 = unbounded; the
    # streamed digest is unaffected either way)
    light_job_size: int = 4
    max_events: int = 0
    trace_keep: int = 0
    ops: tuple = ()
    # fault-registry arms (faults.arm_from_spec shape): armed for the
    # run with the registry reseeded to the scenario seed, so
    # probabilistic faults (e.g. net.request drops against the light
    # fleet's transport) trigger in a reproducible sequence
    faults: tuple = ()

    def auto_duration(self, ccfg: SimConsensusConfig) -> float:
        if self.duration > 0:
            return self.duration
        # heights at one block_interval each + room for two full timeout
        # cascades and a sampling tail. The fleet term matters: every
        # light node's retry/escalation backoffs ADVANCE the one shared
        # timeline (a sleep anywhere is virtual seconds everywhere), so
        # a large fleet hammering a withheld height inflates clock time
        # without slowing event order — budget for it or the run wall
        # cuts the chain's tail off.
        per = ccfg.block_interval + 0.2
        return self.heights * per + 2 * (
            ccfg.timeout_propose + ccfg.timeout_prevote
            + ccfg.timeout_precommit) + 6.0 + 0.15 * self.light_nodes

    def extra_accounts(self) -> int:
        """Funded non-validator accounts the ops program needs: traffic
        generator lanes plus soak stale lanes. Zero for every spec
        without those ops, so existing scenarios' genesis (and therefore
        their consensus bytes) stay exactly as they were."""
        n = 0
        for op in self.ops:
            if op.get("op") == "traffic":
                n += int(op.get("sequences", 2))
            elif op.get("op") == "soak":
                n += int(op.get("stale_lanes", 1))
        return n

    @staticmethod
    def from_dict(doc: dict) -> "SimSpec":
        known = {f.name for f in dataclasses.fields(SimSpec)}
        unknown = set(doc) - known - {"consensus"}
        if unknown:
            raise ValueError(f"unknown scenario spec keys: {sorted(unknown)}")
        kw = {k: v for k, v in doc.items() if k in known}
        if "latency" in kw:
            kw["latency"] = tuple(kw["latency"])
        if "ops" in kw:
            kw["ops"] = tuple(dict(op) for op in kw["ops"])
        if "faults" in kw:
            kw["faults"] = tuple(dict(f) for f in kw["faults"])
        return SimSpec(**kw)


class Simulation:
    """Build the world from a SimSpec, run it, surface raw results.
    Verdict computation (metrics + expectations) lives in scenarios.py."""

    def __init__(self, spec: SimSpec, workdir: str,
                 ccfg: SimConsensusConfig | None = None):
        self.spec = spec
        self.ccfg = ccfg or SimConsensusConfig()
        self.chain_id = f"sim-{spec.name}"
        self.workdir = workdir
        self.sched = Scheduler(spec.seed)
        self.net = SimNet(self.sched, spec.latency)
        self.forged_headers: dict[int, tuple] = {}
        # results
        self.commit_times: dict[int, float] = {}  # first commit per h
        self.val_commit_log: list[tuple] = []  # (t, name, height)
        self.block_hashes: dict[int, str] = {}
        self.app_hashes: dict[int, str] = {}
        self.detections: list[dict] = []  # non-"sampled" light reports
        self.light_halts: list[dict] = []
        self.divergence: list[str] = []
        self._commit_hooks: dict[int, list] = {}  # height -> [fn(sim)]
        # fn(sim, committer, height, block) at every height's FIRST
        # commit (the traffic plane's confirmation watcher)
        self.commit_listeners: list = []
        self._tx_seq = 0

        # validator identities are a function of the SLOT, never the
        # seed: the seed explores event orderings of the SAME world, so
        # fault-free consensus bytes stay seed-invariant (satellite pin)
        privs = [PrivateKey.from_seed(f"sim-val-{i}".encode())
                 for i in range(spec.validators)]
        # traffic/stale-lane accounts: slot-keyed like the validators,
        # present ONLY when the ops program asks (extra_accounts), so a
        # spec without traffic ops keeps byte-identical genesis
        self.traffic_privs = [
            PrivateKey.from_seed(f"sim-traffic-{i}".encode())
            for i in range(spec.extra_accounts())
        ]
        self._traffic_cursor = 0  # claim_traffic_accounts allocation
        genesis = {
            "time_unix": self.sched.clock.epoch,
            "accounts": [
                {"address": p.public_key().address().hex(),
                 "balance": 10**13}
                for p in privs + self.traffic_privs
            ],
            "validators": [
                {"operator": p.public_key().address().hex(), "power": 10,
                 "pubkey": p.public_key().compressed.hex()}
                for p in privs
            ],
        }
        self.genesis = genesis
        self.privs = privs
        self.validators: list[SimValidator] = []
        vnodes = []
        for i, p in enumerate(privs):
            vnode = c.ValidatorNode(
                f"val{i}", p, genesis, self.chain_id,
                data_dir=os.path.join(workdir, f"val{i}"),
                da_scheme=spec.scheme,
            )
            # mempool TTL on the virtual timeline (the injected-clock
            # satellite): stamps and expiry run in simulated seconds
            vnode.pool.clock = self.sched.clock
            vnodes.append(vnode)
        # peer pubkey exchange (the LocalNetwork handshake analog)
        peer_keys = {v.address: v.priv.public_key().compressed
                     for v in vnodes}
        for v in vnodes:
            v.validator_pubkeys = {**peer_keys, **v.validator_pubkeys}
        order = sorted(range(len(vnodes)),
                       key=lambda i: vnodes[i].address)
        for slot, i in enumerate(order):
            sv = SimValidator(self, slot, vnodes[i])
            self.validators.append(sv)
            self.net.register(sv.name, sv.route)
        self.lights = [SimLightNode(self, i)
                       for i in range(spec.light_nodes)]
        # the tx signer: account 0 funds every injected MsgSend; content
        # is a pure function of (chain height, injection counter), so
        # fault-free runs commit identical blocks under every seed
        from celestia_app_tpu.client.tx_client import Signer

        self.signer = Signer(self.chain_id)
        for i, p in enumerate(privs + self.traffic_privs):
            self.signer.add_account(p, number=i)

    # -- schedule-time helpers ------------------------------------------

    def block_timestamp(self, height: int) -> float:
        """Header timestamps follow the fixed per-height schedule, NOT
        the event clock: consensus bytes must be seed-independent in
        fault-free runs (the engine never perturbs consensus)."""
        return self.sched.clock.epoch + height * self.ccfg.block_time

    def validator_by_index(self, i: int) -> SimValidator:
        return self.validators[i % len(self.validators)]

    def claim_traffic_accounts(self, n: int) -> list[PrivateKey]:
        """Allocate `n` of the pre-funded traffic accounts to an op
        installer (ops claim in install order; SimSpec.extra_accounts
        sized the pool with the same per-op arithmetic)."""
        got = self.traffic_privs[self._traffic_cursor:
                                 self._traffic_cursor + n]
        if len(got) < n:
            raise ValueError("traffic account pool exhausted")
        self._traffic_cursor += n
        return got

    def at(self, t: float, fn, label: str) -> None:
        self.sched.call_at(t, fn, label)

    def on_commit_height(self, height: int, fn) -> None:
        """Run `fn(sim, committer)` when the FIRST validator commits
        `height` — the committer is the only node guaranteed to hold the
        height's state at that instant."""
        self._commit_hooks.setdefault(height, []).append(fn)

    def withhold_everywhere(self, height: int, cells) -> None:
        for v in self.validators:
            v.core.withhold(height, cells)

    def tx_hook(self, height: int, proposer: SimValidator) -> None:
        """Deterministic per-height load: inject txs_per_height MsgSends
        into the proposer's pool right before it proposes. Sequence
        numbers follow the injection counter, so content is identical
        under every seed (fault-free) and every re-run (same seed)."""
        from celestia_app_tpu.chain.tx import MsgSend

        n = self.spec.txs_per_height
        if n <= 0:
            return
        a0 = self.privs[0].public_key().address()
        a1 = self.privs[1 % len(self.privs)].public_key().address()
        for _ in range(n):
            self.signer.accounts[a0].sequence = self._tx_seq
            tx = self.signer.create_tx(
                a0, [MsgSend(a0, a1, 1000 + self._tx_seq)],
                fee=2000, gas_limit=100_000,
            )
            res = proposer.vnode.add_tx(tx.encode())
            if res.code == 0:
                self._tx_seq += 1

    # -- result intake ---------------------------------------------------

    def _note_commit(self, val: SimValidator, height: int, block, cert,
                     adopted: bool = False) -> None:
        t = self.sched.clock.monotonic()
        bh = block.header.hash().hex()
        ah = val.app_hashes[height]
        self.val_commit_log.append((round(t, 9), val.name, height))
        if height not in self.commit_times:
            self.commit_times[height] = round(t, 9)
            self.block_hashes[height] = bh
            self.app_hashes[height] = ah
            for fn in self.commit_listeners:
                fn(self, val, height, block)
        else:
            if (self.block_hashes[height], self.app_hashes[height]) \
                    != (bh, ah):
                self.divergence.append(
                    f"h={height} {val.name}: block/app hash mismatch")
        self.sched.note(
            f"{val.name}.{'adopt' if adopted else 'commit'} h={height} "
            f"block={bh[:12]} app={ah[:12]}")
        for fn in self._commit_hooks.pop(height, []):
            fn(self, val)

    def _note_report(self, lightnode: SimLightNode, height: int,
                     rep: dict) -> None:
        status = rep["status"]
        if status in ("sampled", "recovered"):
            return
        self.detections.append({
            "t": round(self.sched.clock.monotonic(), 9),
            "light": lightnode.name,
            "height": height,
            "status": status,
            "chain_height": max(self.commit_times, default=0),
        })
        self.sched.note(
            f"{lightnode.name}.report h={height} status={status}")

    def _note_light_halt(self, lightnode: SimLightNode,
                         halt: dict) -> None:
        self.light_halts.append({
            "t": round(self.sched.clock.monotonic(), 9),
            "light": lightnode.name,
            **halt,
        })
        self.sched.note(
            f"{lightnode.name}.halt h={halt.get('height')} "
            f"reason={halt.get('reason')}")

    # -- run -------------------------------------------------------------

    def run(self) -> "Simulation":
        spec = self.spec
        for v in self.validators:
            self.sched.call_at(0.0, lambda v=v: v.begin_height(1),
                               f"{v.name}.begin_height h=1")
            self.sched.call_after(
                self.ccfg.catchup_poll
                * (1.0 + self.sched.rng.random()),  # lint: disable=det-rng
                v.catchup_poll, "")
        for i, ln in enumerate(self.lights):
            # seeded phase offsets spread the fleet across the sweep
            # period instead of thundering at one instant
            self.sched.call_at(
                0.5 + spec.sweep_interval * self.sched.rng.random(),  # lint: disable=det-rng
                ln.sweep, f"{ln.name}.sweep")
        self.sched.trace_keep = spec.trace_keep
        kw = ({"max_events": spec.max_events} if spec.max_events else {})
        self.sched.run(until=spec.auto_duration(self.ccfg), **kw)
        if self.divergence:
            raise AssertionError(
                "consensus divergence in simulation: "
                + "; ".join(self.divergence[:5]))
        return self
