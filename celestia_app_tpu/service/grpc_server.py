"""gRPC services: the reference's :9090 surface — tx service + the query
services the client bootstrap depends on.

pkg/user/tx_client.go broadcasts over gRPC (BroadcastMode_SYNC,
tx_client.go:320-330) and estimates gas via Simulate; GetTx backs
ConfirmTx polling. SetupTxClient additionally bootstraps over five query
RPCs (tx_client.go:147-198): tendermint GetLatestBlock (chain-id +
app version), auth Account (number/sequence), node Config + params/minfee
(min gas price); bank Balance and celestia.blob.v1 Params round out the
module query surface clients use. This server exposes all of them with the
real service/method names and the real cosmos wire messages (hand-rolled
codecs in wire/txpb.py, cross-checked against the protobuf runtime), so a
generated cosmos client stub can point at it unchanged. Handlers run
under the same single-writer lock as the HTTP service.

No protoc codegen: grpcio's generic method handlers with identity
serializers carry the raw message bytes; the codecs do the rest.
"""

from __future__ import annotations

import hashlib
import threading
from concurrent import futures

import grpc

from celestia_app_tpu.wire import bech32, txpb

SERVICE = "cosmos.tx.v1beta1.Service"
TM_SERVICE = "cosmos.base.tendermint.v1beta1.Service"
NODE_SERVICE = "cosmos.base.node.v1beta1.Service"
AUTH_QUERY = "cosmos.auth.v1beta1.Query"
BANK_QUERY = "cosmos.bank.v1beta1.Query"
PARAMS_QUERY = "cosmos.params.v1beta1.Query"
BLOB_QUERY = "celestia.blob.v1.Query"
MINFEE_QUERY = "celestia.minfee.v1.Query"
STAKING_QUERY = "cosmos.staking.v1beta1.Query"
GOV_QUERY = "cosmos.gov.v1beta1.Query"
DA_SERVICE = "celestia_tpu.da.v1.DAService"


class DAGrpcService:
    """gRPC transport for the stateless DA core (§7.1.7 shim surface) —
    the same DACore the HTTP /da/* routes use, encoded per
    proto/celestia_tpu/da/v1/da.proto with the hand-rolled codec
    (wire/proto.py). No node state, no lock: callers are foreign
    processes swapping da.ExtendShares for ExtendAndCommit."""

    def __init__(self, da_core):
        self.core = da_core

    def extend_and_commit(self, request: bytes, context) -> bytes:
        from celestia_app_tpu.service.da_service import DAError
        from celestia_app_tpu.wire import proto as p

        req = p.Fields(request)
        # raw bytes straight through — no base64 detour on the hot path
        # (an 8 MB 128x128 ODS per block is exactly what this service
        # exists to accelerate)
        payload = {"ods": req.get_bytes(1)}
        k = req.get_int(2)
        if k:
            payload["square_size"] = k
        try:
            out = self.core.extend_and_commit(payload)
        except DAError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return b"".join([
            p.field_varint(1, out["square_size"]),
            p.field_repeated_bytes(
                2, [bytes.fromhex(r) for r in out["row_roots"]]),
            p.field_repeated_bytes(
                3, [bytes.fromhex(r) for r in out["col_roots"]]),
            p.field_bytes(4, bytes.fromhex(out["data_root"])),
        ])

    def prove_shares(self, request: bytes, context) -> bytes:
        import base64

        from celestia_app_tpu.service.da_service import DAError
        from celestia_app_tpu.wire import proto as p

        req = p.Fields(request)
        payload = {"start": req.get_int(3), "end": req.get_int(4)}
        if req.has(1):
            payload["data_root"] = req.get_bytes(1).hex()
        if req.has(2):
            payload["ods"] = req.get_bytes(2)
        if req.has(5):
            payload["namespace"] = req.get_bytes(5).hex()
        try:
            out = self.core.prove_shares(payload)
        except DAError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        pf = out["proof"]

        def nmt_range(sp: dict) -> bytes:
            return b"".join([
                p.field_varint(1, sp["start"]),
                p.field_varint(2, sp["end"]),
                p.field_varint(3, sp["total"]),
                p.field_repeated_bytes(
                    4, [base64.b64decode(n) for n in sp["nodes"]]),
            ])

        def merkle(mp: dict) -> bytes:
            return b"".join([
                p.field_varint(1, mp["index"]),
                p.field_varint(2, mp["total"]),
                p.field_bytes(3, base64.b64decode(mp["leaf_hash"])),
                p.field_repeated_bytes(
                    4, [base64.b64decode(a) for a in mp["aunts"]]),
            ])

        rp = pf["row_proof"]
        row_proof = b"".join([
            p.field_repeated_bytes(
                1, [bytes.fromhex(r) for r in rp["row_roots"]]),
            b"".join(p.field_message(2, merkle(mp))
                     for mp in rp["proofs"]),
            p.field_varint(3, rp["start_row"]),
            p.field_varint(4, rp["end_row"]),
        ])
        share_proof = b"".join([
            p.field_repeated_bytes(
                1, [base64.b64decode(d) for d in pf["data"]]),
            b"".join(p.field_message(2, nmt_range(sp))
                     for sp in pf["share_proofs"]),
            p.field_bytes(3, bytes.fromhex(pf["namespace"])),
            p.field_message(4, row_proof),
            p.field_varint(5, pf["start_share"]),
            p.field_varint(6, pf["end_share"]),
        ])
        return b"".join([
            p.field_message(1, share_proof),
            p.field_bytes(2, bytes.fromhex(out["data_root"])),
        ])


class CosmosTxService:
    def __init__(self, node, lock: threading.Lock | None = None):
        self.node = node
        self.lock = lock or threading.Lock()

    # -- handlers (bytes in, bytes out) ---------------------------------

    def broadcast_tx(self, request: bytes, context) -> bytes:
        tx_bytes, mode = txpb.parse_broadcast_tx_request(request)
        if mode not in (0, txpb.BROADCAST_MODE_SYNC):
            # ASYNC/BLOCK semantics are NOT silently downgraded to SYNC —
            # a BLOCK-mode caller would misread height=0 as committed
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"only BROADCAST_MODE_SYNC is supported, got mode={mode}",
            )
        with self.lock:
            res = self.node.broadcast_tx(tx_bytes)
        resp = txpb.tx_response_pb(
            height=0,  # SYNC mode: not yet in a block
            txhash=hashlib.sha256(tx_bytes).hexdigest().upper(),
            code=res.code,
            raw_log=res.log,
            gas_wanted=res.gas_wanted,
            gas_used=res.gas_used,
        )
        return txpb.broadcast_tx_response_pb(resp)

    def simulate(self, request: bytes, context) -> bytes:
        tx_bytes = txpb.parse_simulate_request(request)
        with self.lock:
            res = self.node.app.simulate_tx(tx_bytes)
        if res.code != 0:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"simulation failed: {res.log}")
        return txpb.simulate_response_pb(0, res.gas_used)

    def get_tx(self, request: bytes, context) -> bytes:
        want = txpb.parse_get_tx_request(request).lower()
        try:
            want_raw = bytes.fromhex(want)
        except ValueError:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"tx hash must be hex, got {want!r}")
        with self.lock:
            entry = self.node.committed.get(want_raw)
            pending = (getattr(self.node, "pool", None) is not None
                       and entry is None and self.node.pool.has(want_raw))
        if entry is None:
            # distinguish "still in the mempool" from "unknown" — a
            # ConfirmTx poller needs to keep waiting for the former and
            # may resubmit on the latter (tx_client.go:430 PENDING state)
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"tx {want} pending in mempool" if pending
                else f"tx {want} not found",
            )
        height, res = entry
        resp = txpb.tx_response_pb(
            height=height,
            txhash=want.upper(),
            code=res.code,
            raw_log=res.log,
            gas_wanted=res.gas_wanted,
            gas_used=res.gas_used,
        )
        return txpb.get_tx_response_pb(resp)


class QueryServices:
    """The bootstrap query surface (one instance serves all five services).
    Reads go through the app's keepers under the shared lock, mirroring the
    HTTP QueryRouter's accessors (chain/query.py)."""

    def __init__(self, node, lock: threading.Lock):
        self.node = node
        self.lock = lock

    def _ctx(self):
        from celestia_app_tpu.chain.state import Context, InfiniteGasMeter

        app = self.node.app
        return Context(app.store, InfiniteGasMeter(), app.height, 0.0,
                       app.chain_id, app.app_version)

    # -- cosmos.base.tendermint.v1beta1.Service -------------------------

    def get_latest_block(self, request: bytes, context) -> bytes:
        with self.lock:
            app = self.node.app
            return txpb.get_latest_block_response_pb(
                app.chain_id, app.height, app.app_version
            )

    # -- cosmos.base.node.v1beta1.Service -------------------------------

    def config(self, request: bytes, context) -> bytes:
        from celestia_app_tpu import appconsts

        price = getattr(self.node.app, "min_gas_price",
                        appconsts.DEFAULT_MIN_GAS_PRICE)
        return txpb.node_config_response_pb(
            f"{price:.18f}{appconsts.BOND_DENOM}"
        )

    # -- cosmos.auth.v1beta1.Query --------------------------------------

    def account(self, request: bytes, context) -> bytes:
        addr_str = txpb.parse_query_account_request(request)
        try:
            addr = bech32.decode(addr_str, bech32.HRP_ACCOUNT)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        with self.lock:
            acc = self.node.app.auth.account(self._ctx(), addr)
        if acc is None:
            # the reference returns NotFound for unknown accounts and
            # SetupTxClient skips them (tx_client.go:176-180)
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"account {addr_str} not found")
        pub = bytes.fromhex(acc["pubkey"]) if acc.get("pubkey") else None
        base = txpb.base_account_pb(addr_str, pub, acc["number"], acc["sequence"])
        return txpb.query_account_response_pb(base)

    # -- cosmos.bank.v1beta1.Query --------------------------------------

    def balance(self, request: bytes, context) -> bytes:
        from celestia_app_tpu import appconsts

        addr_str, denom = txpb.parse_query_balance_request(request)
        try:
            addr = bech32.decode(addr_str, bech32.HRP_ACCOUNT)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        with self.lock:
            amount = self.node.app.bank.balance(self._ctx(), addr)
        return txpb.query_balance_response_pb(
            denom or appconsts.BOND_DENOM, amount
        )

    # -- cosmos.params.v1beta1.Query ------------------------------------

    def subspace_params(self, request: bytes, context) -> bytes:
        import json

        subspace, key = txpb.parse_query_subspace_params_request(request)
        if subspace == "minfee" and key == "NetworkMinGasPrice":
            if self.node.app.app_version < 2:
                # v1 has no minfee subspace; the reference surfaces exactly
                # this error string, which QueryMinimumGasPrice matches on
                # to fall back to the local price (tx_client.go:580)
                context.abort(grpc.StatusCode.NOT_FOUND,
                              "unknown subspace: minfee")
            with self.lock:
                price = self.node.app.minfee.network_min_gas_price(self._ctx())
            return txpb.query_subspace_params_response_pb(
                subspace, key, json.dumps(f"{price:.18f}")
            )
        context.abort(grpc.StatusCode.NOT_FOUND,
                      f"unknown subspace: {subspace}")

    # -- celestia.blob.v1.Query -----------------------------------------

    def blob_params(self, request: bytes, context) -> bytes:
        with self.lock:
            p = self.node.app.blob.params(self._ctx())
        return txpb.blob_params_response_pb(
            p["gas_per_blob_byte"], p["gov_max_square_size"]
        )

    # -- cosmos.staking.v1beta1.Query -----------------------------------

    def staking_validator(self, request: bytes, context) -> bytes:
        addr_str = txpb.parse_query_validator_request(request)
        try:
            op = bech32.decode(addr_str, bech32.HRP_VALOPER)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        with self.lock:
            v = self.node.app.staking.validator(self._ctx(), op)
            if v is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"validator {addr_str} not found")
            return txpb.query_validator_response_pb(txpb.validator_pb(
                op, v["jailed"], v["bonded"], v["tokens"]
            ))

    def staking_validators(self, request: bytes, context) -> bytes:
        with self.lock:
            ctx = self._ctx()
            out = []
            for op, _power in self.node.app.staking.validators(ctx):
                v = self.node.app.staking.validator(ctx, op)
                out.append(txpb.validator_pb(
                    op, v["jailed"], v["bonded"], v["tokens"]
                ))
        return txpb.query_validators_response_pb(out)

    # -- cosmos.gov.v1beta1.Query ---------------------------------------

    def gov_proposal(self, request: bytes, context) -> bytes:
        pid = txpb.parse_query_proposal_request(request)
        with self.lock:
            p = self.node.app.gov.proposal(self._ctx(), pid)
        if p is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"proposal {pid} doesn't exist")
        return txpb.query_proposal_response_pb(p["id"], p["status"])

    # -- celestia.minfee.v1.Query ---------------------------------------

    def network_min_gas_price(self, request: bytes, context) -> bytes:
        if self.node.app.app_version < 2:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          "minfee is a v2+ module")
        with self.lock:
            price = self.node.app.minfee.network_min_gas_price(self._ctx())
        return txpb.minfee_response_pb(price)


def _identity(x: bytes) -> bytes:
    return x


def _handler(fn):
    return grpc.unary_unary_rpc_method_handler(
        fn, request_deserializer=_identity, response_serializer=_identity
    )


class GrpcTxServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 9090,
                 lock: threading.Lock | None = None, da_core=None):
        self.service = CosmosTxService(node, lock)
        self.queries = QueryServices(node, self.service.lock)
        # share the caller's DACore when both transports live in one
        # process (cli start --grpc): an ExtendAndCommit over gRPC must
        # be provable over HTTP by data_root from ONE square cache
        if da_core is None:
            from celestia_app_tpu.service.da_service import DACore

            da_core = DACore(
                engine="device" if getattr(node.app, "engine", "host")
                in ("device", "mesh") else "host"
            )
        self.da = DAGrpcService(da_core)
        q = self.queries
        services = {
            DA_SERVICE: {
                "ExtendAndCommit": _handler(self.da.extend_and_commit),
                "ProveShares": _handler(self.da.prove_shares),
            },
            SERVICE: {
                "BroadcastTx": _handler(self.service.broadcast_tx),
                "Simulate": _handler(self.service.simulate),
                "GetTx": _handler(self.service.get_tx),
            },
            TM_SERVICE: {"GetLatestBlock": _handler(q.get_latest_block)},
            NODE_SERVICE: {"Config": _handler(q.config)},
            AUTH_QUERY: {"Account": _handler(q.account)},
            BANK_QUERY: {"Balance": _handler(q.balance)},
            PARAMS_QUERY: {"Params": _handler(q.subspace_params)},
            BLOB_QUERY: {"Params": _handler(q.blob_params)},
            MINFEE_QUERY: {"NetworkMinGasPrice": _handler(q.network_min_gas_price)},
            STAKING_QUERY: {
                "Validator": _handler(q.staking_validator),
                "Validators": _handler(q.staking_validators),
            },
            GOV_QUERY: {"Proposal": _handler(q.gov_proposal)},
        }
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self.server.add_generic_rpc_handlers(tuple(
            grpc.method_handlers_generic_handler(name, handlers)
            for name, handlers in services.items()
        ))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            # add_insecure_port returns 0 on bind FAILURE (port taken);
            # a requested port of 0 legitimately returns an ephemeral one
            raise OSError(f"could not bind gRPC port {host}:{port}")
        self.server.start()

    def stop(self) -> None:
        self.server.stop(grace=0.5)
