"""gRPC tx service: the reference's cosmos.tx.v1beta1.Service on :9090.

pkg/user/tx_client.go broadcasts over gRPC (BroadcastMode_SYNC,
tx_client.go:320-330) and estimates gas via Simulate; GetTx backs
ConfirmTx polling. This server exposes exactly those methods with the
real service/method names and the real cosmos wire messages
(BroadcastTxRequest/TxResponse/SimulateRequest/... — hand-rolled codecs
in wire/txpb.py, cross-checked against the protobuf runtime), so a
generated cosmos client stub can point at it unchanged. Handlers run
under the same single-writer lock as the HTTP service.

No protoc codegen: grpcio's generic method handlers with identity
serializers carry the raw message bytes; the codecs do the rest.
"""

from __future__ import annotations

import hashlib
import threading
from concurrent import futures

import grpc

from celestia_app_tpu.wire import txpb

SERVICE = "cosmos.tx.v1beta1.Service"


class CosmosTxService:
    def __init__(self, node, lock: threading.Lock | None = None):
        self.node = node
        self.lock = lock or threading.Lock()

    # -- handlers (bytes in, bytes out) ---------------------------------

    def broadcast_tx(self, request: bytes, context) -> bytes:
        tx_bytes, mode = txpb.parse_broadcast_tx_request(request)
        if mode not in (0, txpb.BROADCAST_MODE_SYNC):
            # ASYNC/BLOCK semantics are NOT silently downgraded to SYNC —
            # a BLOCK-mode caller would misread height=0 as committed
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"only BROADCAST_MODE_SYNC is supported, got mode={mode}",
            )
        with self.lock:
            res = self.node.broadcast_tx(tx_bytes)
        resp = txpb.tx_response_pb(
            height=0,  # SYNC mode: not yet in a block
            txhash=hashlib.sha256(tx_bytes).hexdigest().upper(),
            code=res.code,
            raw_log=res.log,
            gas_wanted=res.gas_wanted,
            gas_used=res.gas_used,
        )
        return txpb.broadcast_tx_response_pb(resp)

    def simulate(self, request: bytes, context) -> bytes:
        tx_bytes = txpb.parse_simulate_request(request)
        with self.lock:
            res = self.node.app.simulate_tx(tx_bytes)
        if res.code != 0:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"simulation failed: {res.log}")
        return txpb.simulate_response_pb(0, res.gas_used)

    def get_tx(self, request: bytes, context) -> bytes:
        want = txpb.parse_get_tx_request(request).lower()
        try:
            want_raw = bytes.fromhex(want)
        except ValueError:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"tx hash must be hex, got {want!r}")
        with self.lock:
            entry = self.node.committed.get(want_raw)
        if entry is None:
            context.abort(grpc.StatusCode.NOT_FOUND, f"tx {want} not found")
        height, res = entry
        resp = txpb.tx_response_pb(
            height=height,
            txhash=want.upper(),
            code=res.code,
            raw_log=res.log,
            gas_wanted=res.gas_wanted,
            gas_used=res.gas_used,
        )
        return txpb.get_tx_response_pb(resp)


def _identity(x: bytes) -> bytes:
    return x


class GrpcTxServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 9090,
                 lock: threading.Lock | None = None):
        self.service = CosmosTxService(node, lock)
        handlers = {
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                self.service.broadcast_tx,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "Simulate": grpc.unary_unary_rpc_method_handler(
                self.service.simulate,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "GetTx": grpc.unary_unary_rpc_method_handler(
                self.service.get_tx,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
        }
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self.server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            # add_insecure_port returns 0 on bind FAILURE (port taken);
            # a requested port of 0 legitimately returns an ephemeral one
            raise OSError(f"could not bind gRPC port {host}:{port}")
        self.server.start()

    def stop(self) -> None:
        self.server.stop(grace=0.5)
