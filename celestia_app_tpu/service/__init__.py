"""Out-of-process service plane (HTTP JSON API over the node)."""
