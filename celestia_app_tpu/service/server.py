"""HTTP JSON service over an in-process node: the out-of-process boundary.

Reference parity: the reference node exposes gRPC + RPC endpoints (tx
broadcast, ABCI queries incl. the custom proof routes at app/app.go:393-394,
block fetch). grpcio is not available in this environment, so the service
speaks JSON over HTTP/1.1 via the stdlib ThreadingHTTPServer — same routes,
same payloads as chain/query.py. A Go (or any-language) host process can
drive ExtendAndCommit/ProveShares through these endpoints, which is the
SURVEY §7.1.7 shim boundary.

Endpoints:
  GET  /status                         chain identity + telemetry
  GET  /block/<height>                 stored block (header + b64 txs)
  POST /broadcast_tx   {"tx": b64}     CheckTx + mempool admission
  POST /simulate_tx    {"tx": b64}     dry-run gas estimation (Simulate rpc)
  POST /produce_block  {"time": t?}    devnet convenience: one round
  POST /abci_query     {"path": ..., "data": {...}}
  POST /da/extend_commit {"ods": b64}  stateless DA core: ODS -> DAH
  POST /da/prove_shares  {...}         share-range proof (§7.1.7 shim)
  GET  /das/head | /das/header | /das/sample | /das/availability
  POST /das/samples                    DAS sample serving (das/server.py)
  GET  /sync/snapshots                 state-sync manifests, newest first
  GET  /sync/chunk?height=&index=      raw snapshot chunk bytes (§15)
  GET  /faults                         fault-plane admin (armed + fired)
  POST /faults/arm|disarm|reset        arm/disarm fault points (chaos)
  GET  /metrics                        Prometheus text exposition (§10)
  GET  /trace/<table>?since=&limit=    columnar trace pull (spans incl.)
  POST /debug/profile {seconds, dir?}  on-demand jax.profiler capture

Every request's X-Celestia-Trace header (if any) is installed as the
incoming span context, so serve-side spans join the caller's trace
(obs/spans.py; docs/FORMATS.md §10).
"""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from celestia_app_tpu import obs
from celestia_app_tpu.chain.query import QueryError, QueryRouter
from celestia_app_tpu.utils import telemetry


class NodeService:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 26658):
        self.node = node
        self.router = QueryRouter(node.app)
        self.lock = threading.Lock()  # node state is single-writer
        # the stateless DA-core shim surface (§7.1.7): /da/extend_commit
        # + /da/prove_shares for foreign callers. Host engine unless this
        # node itself runs on device — a host-engine validator process
        # must never import-and-dispatch jax (relay-down hang class).
        from celestia_app_tpu.service.da_service import DACore

        self.da_core = DACore(
            engine="device" if getattr(node.app, "engine", "host")
            in ("device", "mesh") else "host"
        )
        # the DAS sample-serving plane (das/server.py): committed blocks
        # answered cell-by-cell with NMT proofs from cached row trees.
        # Shares this service's writer lock for square rebuilds (callers
        # that swap self.lock must swap das_core.app_lock with it).
        from celestia_app_tpu.das.server import SampleCore

        self.das_core = SampleCore(node.app, app_lock=self.lock)
        # the read plane (das/blob_server.py): batched namespace reads
        # + blob-pack static serving over the SAME entry cache, so the
        # two planes share one single-flight build per height
        from celestia_app_tpu.das.blob_server import BlobCore

        self.blob_core = BlobCore(self.das_core)
        # block plane: every commit hands its EDS/DAH cache entry to this
        # serving core on the warmer's background thread (App.commit ->
        # ProverWarmer -> seed_cache_entry), so the first /das/sample
        # after a commit is index arithmetic — no rebuild, no re-extend
        node.app.add_da_seed_listener(self.das_core.seed_cache_entry)
        # sync plane: serve the interval snapshots the start loop writes
        # to <home>/snapshots (chain/sync.py) — straight from disk, never
        # a capture, never under the service lock
        from celestia_app_tpu.chain import sync as sync_mod

        self.sync_store = sync_mod.store_for(node)
        service = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: a thousand-sampler fleet must not pay
            # a TCP handshake per sample round (every response carries
            # Content-Length, so pipelined framing is always correct)
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_raw(self, code: int, body: bytes) -> None:
                # /sync/chunk serves raw bytes (octet-stream, NOT base64)
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # incoming trace context (X-Celestia-Trace): spans opened
                # while serving this request join the caller's trace
                obs.begin_request(self.headers)
                try:
                    self._get()
                finally:
                    obs.end_request()

            def do_POST(self):
                obs.begin_request(self.headers)
                try:
                    self._post()
                finally:
                    obs.end_request()

            def _get(self):
                try:
                    if self.path == "/status":
                        with service.lock:
                            out = service.router.query("status", {})
                            # mempool plane: per-node CAT pool stats (the
                            # process-wide gauges also ride the telemetry
                            # snapshot / prometheus endpoint)
                            pool = getattr(service.node, "pool", None)
                            if pool is not None:
                                out["mempool"] = pool.stats()
                            # admission + traffic plane counters (the
                            # same block /consensus/status serves)
                            from celestia_app_tpu.chain import (
                                admission as admission_mod,
                            )

                            out["admission"] = admission_mod.status_block(
                                service.node.app)
                            # read plane counters (blob.* / blobpacks.*)
                            from celestia_app_tpu.das import (
                                blob_server as blob_server_mod,
                            )

                            out["blob"] = blob_server_mod.status_block()
                        self._send(200, out)
                    elif self.path == "/metrics":
                        # Prometheus text exposition (the reference's
                        # metrics provider endpoint, SURVEY §5.1); ONE
                        # implementation shared with the validator
                        # service (obs.serve_metrics)
                        obs.serve_metrics(self)
                    elif self.path.startswith("/trace/"):
                        # columnar trace tables (pkg/trace pull, §5.1):
                        # /trace/<table>?since=<index>&limit=<n> — ONE
                        # router shared with the validator service
                        # (obs.route_trace); TraceTables locks its own
                        # reads, so the big writer lock stays out of the
                        # poll path
                        self._send(200, obs.route_trace(
                            service.node.app.traces, self.path))
                    elif self.path.startswith("/das/"):
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.das.server import (
                            SampleError,
                            route_das,
                        )

                        parsed = urlparse(self.path)
                        try:
                            out = route_das(
                                service.das_core, "GET", parsed.path,
                                parse_qs(parsed.query),
                            )
                            if isinstance(out, bytes):
                                # /das/pack/chunk: raw static bytes
                                self._send_raw(200, out)
                            else:
                                self._send(200, out)
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                    elif self.path.startswith("/blob/"):
                        # the read plane (das/blob_server.py): namespace
                        # reads + blob-pack static serving; BlobError is
                        # a SampleError, so one handler covers both
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.das.server import SampleError
                        from celestia_app_tpu.das.blob_server import (
                            route_blob,
                        )

                        parsed = urlparse(self.path)
                        try:
                            out = route_blob(
                                service.blob_core, "GET", parsed.path,
                                parse_qs(parsed.query),
                            )
                            if isinstance(out, bytes):
                                # /blob/pack/chunk: raw static bytes
                                self._send_raw(200, out)
                            else:
                                self._send(200, out)
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                    elif self.path.startswith("/sync/"):
                        # chunked state-sync serving (chain/sync.py):
                        # manifests + raw chunks from disk, lock-free
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.chain import sync as sync_mod

                        parsed = urlparse(self.path)
                        try:
                            out = sync_mod.route_sync(
                                service.sync_store, parsed.path,
                                parse_qs(parsed.query),
                            )
                        except sync_mod.SyncError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                            return
                        if isinstance(out, bytes):
                            self._send_raw(200, out)
                        else:
                            self._send(200, out)
                    elif self.path == "/faults":
                        # fault-plane admin (celestia_app_tpu/faults):
                        # armed specs + per-point fire counts
                        from celestia_app_tpu.faults import route_faults

                        self._send(200, route_faults("GET", self.path))
                    elif self.path.startswith("/block/"):
                        height = int(self.path.split("/")[2])
                        blk = service.node.app.db.load_block(height)
                        self._send(200, {
                            "height": blk.header.height,
                            "data_hash": blk.header.data_hash.hex(),
                            "square_size": blk.header.square_size,
                            "app_hash": blk.header.app_hash.hex(),
                            "time_unix": blk.header.time_unix,
                            "txs": [base64.b64encode(t).decode() for t in blk.txs],
                        })
                    else:
                        self._send(404, {"error": f"no route {self.path}"})
                except (QueryError, ValueError) as e:
                    # GET-side ValueErrors are path/query parse failures
                    # (non-integer height, bad since=): client errors
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    telemetry.incr("http.500")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def _post(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/broadcast_tx":
                        raw = base64.b64decode(payload["tx"])
                        with service.lock:
                            res = service.node.broadcast_tx(raw)
                        self._send(200, {
                            "code": res.code, "log": res.log,
                            "gas_wanted": res.gas_wanted,
                            "gas_used": res.gas_used,
                        })
                    elif self.path == "/simulate_tx":
                        raw = base64.b64decode(payload["tx"])
                        with service.lock:
                            res = service.node.app.simulate_tx(raw)
                        self._send(200, {
                            "code": res.code, "log": res.log,
                            "gas_used": res.gas_used,
                        })
                    elif self.path == "/produce_block":
                        with service.lock:
                            blk, results = service.node.produce_block(
                                t=payload.get("time")
                            )
                        self._send(200, {
                            "height": blk.header.height,
                            "data_hash": blk.header.data_hash.hex(),
                            "app_hash": service.node.app.last_app_hash.hex(),
                            "n_txs": len(blk.txs),
                            "results": [
                                {"code": r.code, "log": r.log} for r in results
                            ],
                        })
                    elif self.path == "/abci_query":
                        with service.lock:
                            out = service.router.query(
                                payload["path"], payload.get("data", {})
                            )
                        self._send(200, out)
                    elif self.path.startswith("/da/"):
                        # stateless DA core (no node state, no service
                        # lock): foreign nodes extend/commit/prove here
                        from celestia_app_tpu.service.da_service import (
                            DAError,
                        )

                        try:
                            self._send(200, service.da_core.handle(
                                self.path, payload))
                        except DAError as e:
                            self._send(400, {"error": str(e)})
                    elif self.path.startswith("/das/"):
                        from urllib.parse import urlparse

                        from celestia_app_tpu.das.server import (
                            SampleError,
                            route_das,
                        )

                        try:
                            self._send(200, route_das(
                                service.das_core, "POST",
                                urlparse(self.path).path, {}, payload,
                            ))
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                    elif self.path.startswith("/blob/"):
                        from urllib.parse import urlparse

                        from celestia_app_tpu.das.server import (
                            SampleError,
                        )
                        from celestia_app_tpu.das.blob_server import (
                            route_blob,
                        )

                        try:
                            self._send(200, route_blob(
                                service.blob_core, "POST",
                                urlparse(self.path).path, {}, payload,
                            ))
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                    elif self.path.startswith("/faults/"):
                        # arm/disarm/reset fault points on a LIVE node —
                        # the chaos harness's runtime switchboard
                        from celestia_app_tpu.faults import route_faults

                        try:
                            self._send(200, route_faults(
                                "POST", self.path, payload))
                        except (ValueError, KeyError) as e:
                            self._send(400, {"error": str(e)})
                    elif self.path == "/debug/profile":
                        # on-demand jax.profiler capture (FORMATS §10.3);
                        # refuses in processes that never imported jax
                        self._send(*obs.route_profile(payload))
                    elif self.path == "/ibc/prove":
                        # membership/absence proof of a raw store key: the
                        # relayer's proof source (public data — any light
                        # client could derive the same against the root)
                        key = bytes.fromhex(payload["key"])
                        try:
                            with service.lock:
                                if payload.get("absence"):
                                    proof = (service.node.app.store
                                             .prove_absence(key))
                                else:
                                    proof = service.node.app.store.prove(key)
                        except KeyError:
                            self._send(404, {"error": "no such key"})
                            return
                        self._send(200, {"proof": proof})
                    elif self.path == "/ibc/ack":
                        from celestia_app_tpu.chain.state import (
                            Context, InfiniteGasMeter,
                        )

                        with service.lock:
                            app = service.node.app
                            ctx = Context(app.store, InfiniteGasMeter(),
                                          app.height, 0, app.chain_id,
                                          app.app_version)
                            ack = app.ibc.channels.get_ack(
                                ctx, payload["packet"]
                            )
                        self._send(200, {"ack": ack})
                    elif self.path == "/ibc/client_height":
                        from celestia_app_tpu.chain.state import (
                            Context, InfiniteGasMeter,
                        )

                        with service.lock:
                            app = service.node.app
                            ctx = Context(app.store, InfiniteGasMeter(),
                                          app.height, 0, app.chain_id,
                                          app.app_version)
                            h = app.ibc.clients.latest_height(
                                ctx, payload["client_id"]
                            )
                        self._send(200, {"latest_height": h})
                    elif self.path == "/ibc/header":
                        # certified header + commit certificate at a
                        # height (the verifying-client update payload);
                        # 404 when this node is not consensus-backed or
                        # the height is not yet certified
                        from celestia_app_tpu.chain import (
                            consensus as consensus_mod,
                        )

                        h = int(payload["height"])
                        certs = getattr(service.node, "certificates", None)
                        with service.lock:
                            db = getattr(service.node.app, "db", None)
                            if not certs or h not in certs or db is None:
                                self._send(404, {"error": "not certified"})
                                return
                            block = db.load_block(h)
                            self._send(200, {
                                "header": consensus_mod.header_to_json(
                                    block.header
                                ),
                                "cert": consensus_mod.cert_to_json(
                                    certs[h]
                                ),
                            })
                    elif self.path == "/ibc/events":
                        # committed packet events, the relayer's work list
                        # (bounded by the node's committed-index window)
                        want = payload.get("type", "send_packet")
                        with service.lock:
                            rows = [
                                {"height": h, **ev}
                                for _tx, (h, res) in sorted(
                                    service.node.committed.items(),
                                    key=lambda kv: kv[1][0],
                                )
                                if res.code == 0
                                for ev in res.events
                                if ev.get("type") == want
                            ]
                        self._send(200, {"events": rows})
                    else:
                        self._send(404, {"error": f"no route {self.path}"})
                except QueryError as e:
                    # client-side problem or policy refusal (e.g. a
                    # validator's /produce_block): 4xx, not a 5xx that
                    # trips server-health monitoring. Internal errors that
                    # surface as bare ValueError stay 500 on purpose — a
                    # failing node must look unhealthy.
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    telemetry.incr("http.500")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        class Server(ThreadingHTTPServer):
            # a thousand-sampler fleet connects in one burst: the stdlib
            # default listen backlog of 5 resets most of it on arrival
            request_queue_size = 1024

        self.httpd = Server((host, port), Handler)
        self.port = self.httpd.server_address[1]
        # GIL-pressure sampler for this serving plane (no-op unless
        # CELESTIA_OBS is on): gil.pressure{service="node"} in /metrics
        from celestia_app_tpu.obs import gil
        gil.start("node")

    def serve_background(self) -> threading.Thread:
        th = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        th.start()
        return th

    def shutdown(self) -> None:
        # deregister the commit-seed hook so a replaced service's dead
        # SampleCore stops receiving (and pinning) future entries
        self.node.app.remove_da_seed_listener(
            self.das_core.seed_cache_entry)
        self.httpd.shutdown()
        self.httpd.server_close()
