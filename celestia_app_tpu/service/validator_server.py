"""Per-validator HTTP consensus service: the socket-crossing vote plane.

Reference parity: celestia-core's p2p reactors gossip proposals, votes, and
txs between validator PROCESSES over TCP (SURVEY §5.8). This server gives
one ValidatorNode (chain/consensus.py) that same out-of-process surface:
every proposal, prevote, precommit, commit, and state-sync chunk crosses a
real socket as JSON — nothing consensus-critical stays in-process. The
devnet's `--processes` mode runs one OS process per validator around this
server (cli.py cmd_validator_serve), with `chain/remote_consensus.py`
driving the round schedule from outside.

Trust model: the node signs votes LOCALLY and verifies every inbound
certificate against its own genesis pubkeys + staking powers
(`ValidatorNode.verify_certificate`) before applying — the orchestrator is
a scheduler, not a trusted party (a forged /consensus/commit is refused).

Routes (all JSON unless noted):
  GET  /consensus/status            {name, height, app_hash, chain_id, mempool}
  GET  /consensus/height            {height} — the lightweight probe
  POST /broadcast_tx {tx: b64}      CheckTx + mempool admission
  POST /consensus/propose {time}    -> {block}    (PrepareProposal or lock)
  POST /consensus/prevote {block}   -> {vote}     (ProcessProposal inside)
  POST /consensus/precommit {block?, polka, round} -> {vote}  (lock if polka)
  POST /consensus/commit {block, cert, evidence} -> {app_hash}

Sync plane (chain/sync.py; docs/FORMATS.md §15):
  GET  /sync/snapshots              {snapshots: [manifest,...]} newest first
  GET  /sync/chunk?height=&index=   raw chunk bytes (octet-stream)
  GET  /gossip/commits?from=&to=    {commits: [...]} batched blocksync
  GET  /consensus/snapshot          DEPRECATED one-shot adapter (§15.4)
  POST /consensus/sync {peer}       DEPRECATED orchestrated pull adapter

Autonomous (gossip) mode adds the peer-to-peer plane consumed by
chain/reactor.py — these routes deliberately BYPASS the big writer lock
(they only record into the reactor's inbox; a slow propose must not
starve vote intake):
  POST /gossip/proposal {proposal}  signed Proposal from a peer
  POST /gossip/vote {round, vote}   prevote/precommit from a peer
  POST /gossip/commit {proposal, cert}   a peer's committed height
  GET  /gossip/commit_at?height=H   recent commit record (laggard catch-up)
  POST /gossip/seen_tx {hash, from} CAT SeenTx announce (want/have gossip)
  GET  /gossip/want_tx?hash=H       CAT WantTx pull -> {tx: b64} delivery
  POST /gossip/tx {tx: b64}         direct Tx push (legacy flood delivery)

DAS serving plane (das/server.py; docs/FORMATS.md §7, §14):
  GET  /das/head | /das/header | /das/sample | /das/availability
  POST /das/samples                 batched sample serving — every commit
                                    seeds its EDS/DAH cache entry here, so
                                    post-commit samples never rebuild under
                                    the consensus lock

Fault-plane admin (celestia_app_tpu/faults; docs/FORMATS.md §9):
  GET  /faults                      armed fault specs + per-point fire counts
  POST /faults/arm {point, action, ...}   arm a fault; -> {id}
  POST /faults/disarm {id|point}    disarm one / by point / all
  POST /faults/reset {seed?}        disarm everything and reseed the rng

Observability plane (celestia_app_tpu/obs; docs/FORMATS.md §10):
  GET  /metrics                     Prometheus text exposition — validator
                                    processes are scrapable, not just nodes
  GET  /trace/<table>?since=&limit= columnar trace pull (spans included)
  POST /debug/profile {seconds}     on-demand jax.profiler capture
Every request's X-Celestia-Trace header is installed as the incoming
span context, so serve-side spans join the calling node's trace.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from celestia_app_tpu import obs
from celestia_app_tpu.chain import consensus as c
from celestia_app_tpu.utils import telemetry


class ValidatorService:
    def __init__(self, vnode: "c.ValidatorNode", host: str = "127.0.0.1",
                 port: int = 0):
        self.vnode = vnode
        self.lock = threading.Lock()
        self.reactor = None  # set by attach_reactor (autonomous mode)
        # block plane: validator processes serve DAS samples too — the
        # commit path seeds every committed height's EDS/DAH cache entry
        # into this core from the warmer's background thread, so a light
        # client sampling straight off a validator right after commit
        # never triggers a rebuild under the consensus lock
        from celestia_app_tpu.das.server import SampleCore

        self.das_core = SampleCore(vnode.app, app_lock=self.lock)
        vnode.app.add_da_seed_listener(self.das_core.seed_cache_entry)
        # read plane: validators answer namespace reads off the SAME
        # commit-seeded entry cache — no second build path
        from celestia_app_tpu.das.blob_server import BlobCore

        self.blob_core = BlobCore(self.das_core)
        # sync plane: the snapshot set this process serves for chunked
        # state sync (<home>/snapshots, written by the reactor's interval
        # hook / the CLI start loop); None for in-memory nodes — /sync/*
        # then serves an empty manifest list and 404s chunks
        from celestia_app_tpu.chain import sync as sync_mod

        self.sync_store = sync_mod.store_for(vnode)
        service = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive (serving plane): gossip peers and
            # sampler fleets reuse connections; every response carries
            # Content-Length
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_raw(self, code: int, body: bytes) -> None:
                # /sync/chunk serves raw bytes (octet-stream, NOT base64):
                # chunk transfers must not pay the 4/3 b64 inflation
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # incoming trace context (X-Celestia-Trace): serve-side
                # spans join the calling node's trace (obs/spans.py)
                obs.begin_request(self.headers)
                try:
                    self._get()
                finally:
                    obs.end_request()

            def do_POST(self):
                obs.begin_request(self.headers)
                try:
                    self._post()
                finally:
                    obs.end_request()

            def _get(self):
                try:
                    if self.path == "/consensus/status":
                        with service.lock:
                            self._send(200, service._status())
                    elif self.path == "/consensus/height":
                        # the lightweight height probe (sync plane): one
                        # integer, no lock, no telemetry/mempool/net
                        # blocks — what reactor._probe_peer_heights polls
                        self._send(200,
                                   {"height": service.vnode.app.height})
                    elif self.path.startswith("/sync/"):
                        # chunked state-sync serving (chain/sync.py):
                        # manifests + raw chunks straight from disk —
                        # never a capture, never under the service lock
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.chain import sync as sync_mod

                        parsed = urlparse(self.path)
                        try:
                            out = sync_mod.route_sync(
                                service.sync_store, parsed.path,
                                parse_qs(parsed.query),
                            )
                        except sync_mod.SyncError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                            return
                        if isinstance(out, bytes):
                            self._send_raw(200, out)
                        else:
                            self._send(200, out)
                    elif self.path == "/metrics":
                        # Prometheus text exposition — validator
                        # processes were invisible to scrapers before
                        # this route (only the node service had it);
                        # ONE implementation shared with the node
                        # service (obs.serve_metrics)
                        obs.serve_metrics(self)
                    elif self.path.startswith("/trace/"):
                        # columnar trace pull (spans included) from THIS
                        # validator's per-app tables — the route e2e
                        # tooling and tools/timeline.py scrape
                        try:
                            self._send(200, obs.route_trace(
                                service.vnode.app.traces, self.path))
                        except ValueError as e:
                            self._send(400, {"error": str(e)})
                    elif self.path == "/faults":
                        # fault-plane admin surface (celestia_app_tpu/
                        # faults): chaos harnesses inspect and arm fault
                        # points on a LIVE validator through it
                        from celestia_app_tpu.faults import route_faults

                        self._send(200, route_faults("GET", self.path))
                    elif self.path.startswith("/gossip/commit_at"):
                        from urllib.parse import parse_qs, urlparse

                        if service.reactor is None:
                            self._send(404, {"error": "not autonomous"})
                            return
                        q = parse_qs(urlparse(self.path).query)
                        h = int(q.get("height", ["0"])[0])
                        self._send(200, service.reactor.commit_at(h) or {})
                    elif self.path.startswith("/gossip/commits"):
                        # batched blocksync serving (sync plane): one
                        # response carries a whole verification window of
                        # commit records, bytes-capped by the reactor
                        from urllib.parse import parse_qs, urlparse

                        if service.reactor is None:
                            self._send(404, {"error": "not autonomous"})
                            return
                        q = parse_qs(urlparse(self.path).query)
                        lo = int(q.get("from", ["0"])[0])
                        hi = int(q.get("to", ["0"])[0])
                        self._send(200, {
                            "commits":
                                service.reactor.commits_range(lo, hi),
                        })
                    elif self.path.startswith("/gossip/want_tx"):
                        # WantTx pull: serve tx content for an announced
                        # hash (the Tx delivery of the want/have protocol)
                        from urllib.parse import parse_qs, urlparse

                        if service.reactor is None:
                            self._send(404, {"error": "not autonomous"})
                            return
                        q = parse_qs(urlparse(self.path).query)
                        try:
                            h = bytes.fromhex(q.get("hash", [""])[0])
                        except ValueError:
                            self._send(400, {"error": "hash must be hex"})
                            return
                        raw = service.reactor.serve_want_tx(h)
                        self._send(200, {} if raw is None else {
                            "tx": base64.b64encode(raw).decode()
                        })
                    elif self.path.startswith("/das/"):
                        # DAS sample serving (das/server.py): commit-
                        # seeded entries answer from pre-built provers;
                        # misses take the service lock inside route_das
                        # (SampleCore.app_lock), never here
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.das.server import (
                            SampleError,
                            route_das,
                        )

                        parsed = urlparse(self.path)
                        try:
                            out = route_das(
                                service.das_core, "GET", parsed.path,
                                parse_qs(parsed.query),
                            )
                            if isinstance(out, bytes):
                                # /das/pack/chunk: raw static bytes
                                self._send_raw(200, out)
                            else:
                                self._send(200, out)
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                    elif self.path.startswith("/blob/"):
                        # read plane (das/blob_server.py): namespace
                        # reads + blob-pack static serving; BlobError
                        # is a SampleError, so one handler covers both
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.das.server import (
                            SampleError,
                        )
                        from celestia_app_tpu.das.blob_server import (
                            route_blob,
                        )

                        parsed = urlparse(self.path)
                        try:
                            out = route_blob(
                                service.blob_core, "GET", parsed.path,
                                parse_qs(parsed.query),
                            )
                            if isinstance(out, bytes):
                                # /blob/pack/chunk: raw static bytes
                                self._send_raw(200, out)
                            else:
                                self._send(200, out)
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                    elif self.path.split("?", 1)[0] \
                            == "/consensus/snapshot":
                        # DEPRECATED one-shot pull (FORMATS §15.4), now a
                        # thin adapter over the chunked plane: the newest
                        # restorable disk snapshot ahead of the puller's
                        # ?min_height= (no capture, no lock), else the
                        # legacy capture-on-request so fresh chains and
                        # already-ahead pullers keep bootstrapping
                        from urllib.parse import parse_qs, urlparse

                        from celestia_app_tpu.chain import sync as sync_mod

                        q = parse_qs(urlparse(self.path).query)
                        self._send(200, sync_mod.legacy_snapshot_doc(
                            service.vnode, service.sync_store,
                            service_lock=service.lock,
                            min_height=int(
                                q.get("min_height", ["0"])[0]),
                        ))
                    else:
                        self._send(404, {"error": f"no route {self.path}"})
                except Exception as e:
                    telemetry.incr("http.500")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def _post(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    # gossip intake records into the reactor inbox WITHOUT
                    # the writer lock — vote delivery must not wait behind
                    # a propose/apply in progress
                    gossip = {
                        "/gossip/proposal": "on_proposal",
                        "/gossip/vote": "on_vote",
                        "/gossip/commit": "on_commit",
                        "/gossip/tx": "on_tx",
                        "/gossip/seen_tx": "on_seen_tx",
                    }.get(self.path)
                    if gossip is not None:
                        if service.reactor is None:
                            self._send(404, {"error": "not autonomous"})
                            return
                        try:
                            # gossip receives are spans too: adopted into
                            # the sender's trace via the incoming header
                            with obs.span(
                                "gossip.recv."
                                + self.path.rsplit("/", 1)[1],
                                traces=service.vnode.app.traces,
                                node=service.vnode.name,
                            ):
                                getattr(service.reactor, gossip)(payload)
                        except (KeyError, TypeError, ValueError) as e:
                            # malformed peer input is the peer's problem,
                            # not a server error
                            self._send(400, {
                                "error": f"malformed gossip: "
                                         f"{type(e).__name__}"
                            })
                            return
                        self._send(200, {"ok": True})
                        return
                    if self.path.startswith("/faults/"):
                        from celestia_app_tpu.faults import route_faults

                        try:
                            self._send(200, route_faults(
                                "POST", self.path, payload))
                        except (ValueError, KeyError) as e:
                            # malformed spec: 400, matching the node
                            # service (FORMATS.md §9.1)
                            self._send(400, {"error": str(e)})
                        return
                    if self.path == "/debug/profile":
                        # on-demand jax.profiler capture (FORMATS §10.3);
                        # refuses on host-engine processes (jax unloaded)
                        self._send(*obs.route_profile(payload))
                        return
                    if self.path in ("/das/samples", "/das/headers"):
                        from celestia_app_tpu.das.server import (
                            SampleError,
                            route_das,
                        )

                        try:
                            self._send(200, route_das(
                                service.das_core, "POST", self.path,
                                {}, payload,
                            ))
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                        return
                    if self.path == "/blob/namespaces":
                        from celestia_app_tpu.das.server import (
                            SampleError,
                        )
                        from celestia_app_tpu.das.blob_server import (
                            route_blob,
                        )

                        try:
                            self._send(200, route_blob(
                                service.blob_core, "POST", self.path,
                                {}, payload,
                            ))
                        except SampleError as e:
                            self._send(404 if "not served" in str(e)
                                       else 400, {"error": str(e)})
                        return
                    route = {
                        "/broadcast_tx": service._broadcast_tx,
                        "/consensus/propose": service._propose,
                        "/consensus/prevote": service._prevote,
                        "/consensus/precommit": service._precommit,
                        "/consensus/commit": service._commit,
                        "/consensus/sync": service._sync,
                    }.get(self.path)
                    if route is None:
                        self._send(404, {"error": f"no route {self.path}"})
                        return
                    with service.lock:
                        self._send(200, route(payload))
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:
                    telemetry.incr("http.500")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

        class Server(ThreadingHTTPServer):
            # burst connects (gossip storms, sampler fleets): the stdlib
            # default listen backlog of 5 resets most of a burst
            request_queue_size = 1024

        self.httpd = Server((host, port), Handler)
        self.port = self.httpd.server_address[1]
        # GIL-pressure sampler for this serving plane (no-op unless
        # CELESTIA_OBS is on): gil.pressure{service="validator"}
        from celestia_app_tpu.obs import gil
        gil.start("validator")

    # -- handlers (under self.lock) --------------------------------------

    @staticmethod
    def _admission_status(app) -> dict:
        from celestia_app_tpu.chain import admission as admission_mod

        return admission_mod.status_block(app)

    @staticmethod
    def _blob_status() -> dict:
        from celestia_app_tpu.das import blob_server as blob_server_mod

        return blob_server_mod.status_block()

    def _status(self) -> dict:
        v = self.vnode
        out = {
            "name": v.name,
            "address": v.address.hex(),
            "chain_id": v.app.chain_id,
            "height": v.app.height,
            "app_version": v.app.app_version,
            "app_hash": v.app.last_app_hash.hex(),
            "mempool": len(v.pool),
            "mempool_bytes": v.pool.pool_bytes,
            # CAT pool counters (admitted/rejected/duplicate/evicted/
            # expired_*/recheck_dropped/committed) — per NODE, unlike the
            # process-wide prometheus endpoint
            "mempool_stats": v.pool.stats(),
            "locked": v.locked_block.header.hash().hex()
            if v.locked_block is not None else None,
            # admission plane + traffic plane: the verified-sig and
            # verified-commitment cache behavior (FORMATS §12.3/§20.3)
            # plus any co-resident txsim load's counters — process-wide
            # (the same numbers /metrics exposes), surfaced here so an
            # operator sees admission economics next to the mempool
            "admission": self._admission_status(v.app),
            # read plane counters (blob.* / blobpacks.*) — process-wide
            "blob": self._blob_status(),
        }
        if self.reactor is not None:
            out["reactor"] = {
                "round": self.reactor.round,
                "step": self.reactor.step,
                "height_view": self.reactor.height_view,
                "loop_errors": self.reactor.loop_errors,
                # sync-plane failure visibility: a dead snapshot peer or
                # failing record fetches show up HERE, not as silence
                "statesync_errors": self.reactor.statesync_errors,
                "blocksync_fetch_errors":
                    self.reactor.blocksync_fetch_errors,
                # boundary observatory: ledger bytes the LAST committed
                # block moved across the host<->device boundary —
                # ROADMAP item 2's per-block gauge, beside the round
                # state an operator already watches
                "host_bytes_crossed_per_block":
                    v.app.last_host_bytes_crossed,
            }
            out["mempool_gossip"] = dict(self.reactor.mempool_gossip.stats)
            # per-peer transport health: breaker state, success/failure
            # tallies, EWMA latency (net/transport.py; FORMATS.md §9) —
            # how an operator (and the chaos tests) see a tripped breaker
            out["net"] = self.reactor.net.snapshot()
        return out

    def attach_reactor(self, peer_urls: list[str], config=None,
                       self_url: str | None = None):
        """Switch this validator to autonomous mode: start the consensus
        reactor thread gossiping with `peer_urls` (chain/reactor.py).
        `self_url` is the URL peers reach THIS service at (rides SeenTx
        announces so peers know whom to pull tx content from); defaults
        to localhost:port, which matches how the devnet spawner and the
        in-process test nets address each other."""
        from celestia_app_tpu.chain.reactor import ConsensusReactor

        self.reactor = ConsensusReactor(
            self.vnode, peer_urls, self.lock, config,
            self_url=self_url or f"http://127.0.0.1:{self.port}",
        )
        self.reactor.start()
        return self.reactor

    def _broadcast_tx(self, p: dict) -> dict:
        raw = base64.b64decode(p["tx"])
        res = self.vnode.add_tx(raw)  # the ONE admission path
        if res.code == 0 and self.reactor is not None:
            # autonomous mode: flood to peers (the mempool reactor) so any
            # upcoming proposer can include the tx
            self.reactor.gossip_tx(raw)
        return {"code": res.code, "log": res.log,
                "gas_wanted": res.gas_wanted, "gas_used": res.gas_used}

    def _propose(self, p: dict) -> dict:
        block = self.vnode.propose(t=float(p["time"]))
        return {"block": c.block_to_json(block)}

    def _prevote(self, p: dict) -> dict:
        block = c.block_from_json(p["block"])
        round_ = int(p.get("round", 0))
        return {"vote": c.vote_to_json(self.vnode.prevote_on(block, round_))}

    def _precommit(self, p: dict) -> dict:
        """polka=true: the orchestrator relays the >2/3 prevote set as the
        polka justification; the node re-counts it AGAINST ITS OWN trust
        roots before locking — a lying coordinator cannot force a lock.
        The polka must be FROM the precommit's round (stale-round prevote
        pooling is refused in _polka_checks_out), must not regress an
        existing lock to an older round, and the sign guard's monotonic
        watermark independently refuses old-round signatures — three
        layers against coordinator-harvested conflicting precommits."""
        round_ = int(p.get("round", 0))
        if not p.get("polka"):
            return {"vote": c.vote_to_json(
                self.vnode.precommit_on(None, round_))}
        block = c.block_from_json(p["block"])
        prevotes = [c.vote_from_json(v) for v in p.get("prevotes", [])]
        lock_ok = self.vnode.lock_permits(block.header.hash(), round_)
        if not lock_ok or not self._polka_checks_out(block, prevotes,
                                                     round_):
            return {"vote": c.vote_to_json(
                self.vnode.precommit_on(None, round_))}
        self.vnode.on_polka(block, round_)
        return {"vote": c.vote_to_json(
            self.vnode.precommit_on(block, round_))}

    def _polka_checks_out(self, block, prevotes, round_: int) -> bool:
        from celestia_app_tpu.chain.crypto import PublicKey
        from celestia_app_tpu.chain.state import Context, InfiniteGasMeter

        v = self.vnode
        bh = block.header.hash()
        ctx = Context(v.app.store, InfiniteGasMeter(), v.app.height, 0,
                      v.app.chain_id, v.app.app_version)
        powers = dict(v.app.staking.validators(ctx))
        known = v.known_pubkeys()
        signed = 0
        seen: set[bytes] = set()
        # a polka is >2/3 prevote power in ONE round — the round we are
        # being asked to precommit. Counting each prevote against its own
        # claimed round would let a lying coordinator pool stale prevotes
        # from failed rounds into a quorum no single round ever had.
        doc = c.Vote.sign_bytes(v.app.chain_id, block.header.height,
                                bh, "prevote", round_)
        for pv in prevotes:
            if (pv.block_hash != bh or pv.phase != "prevote"
                    or pv.round != round_ or pv.validator in seen):
                continue
            pub = known.get(pv.validator)
            if pub is None or not PublicKey(pub).verify(pv.signature, doc):
                continue
            seen.add(pv.validator)
            signed += powers.get(pv.validator, 0)
        return signed * 3 > sum(powers.values()) * 2

    def _commit(self, p: dict) -> dict:
        block = c.block_from_json(p["block"])
        cert = c.cert_from_json(p["cert"])
        evidence = tuple(
            c.evidence_from_json(e) for e in p.get("evidence", [])
        )
        if cert.block_hash != block.header.hash():
            raise ValueError("certificate does not cover this block")
        if not self.vnode.verify_certificate(cert):
            raise ValueError("commit certificate failed local verification")
        app_hash = self.vnode.apply(block, cert, evidence)
        self.vnode.clear_lock()
        return {"app_hash": app_hash.hex(), "height": self.vnode.app.height}

    def _sync(self, p: dict) -> dict:
        """State-sync catch-up over the wire (DEPRECATED orchestrated
        route, FORMATS §15.4) — now a thin adapter over the chunked
        plane: a peer serving /sync/* gets the parallel, verified,
        resumable chunk fetch; one that predates it falls back to the
        legacy one-shot /consensus/snapshot pull. Adoption goes through
        the unchanged app-hash-anchored state_sync_bootstrap either way."""
        import tempfile

        from celestia_app_tpu.chain import sync as sync_mod
        from celestia_app_tpu.net import transport

        before = self.vnode.app.height
        home = sync_mod.home_for(self.vnode)
        ephemeral = home is None
        workdir = (tempfile.mkdtemp(prefix="statesync-") if ephemeral
                   else os.path.join(home, sync_mod.RESTORE_DIRNAME))
        client = sync_mod.StateSyncClient(
            [p["peer"]], workdir, min_height=before,
            name=self.vnode.name,
            da_scheme=sync_mod.scheme_of(self.vnode),
        )
        try:
            try:
                manifest, chunks = client.fetch()
            except sync_mod.StateSyncUnavailable:
                import urllib.error

                try:
                    doc = transport.request_json(
                        p["peer"],
                        f"/consensus/snapshot?min_height={before}",
                        timeout=30,
                    )
                except urllib.error.HTTPError:
                    # pre-query peer: exact-path route only
                    doc = transport.request_json(
                        p["peer"], "/consensus/snapshot", timeout=30
                    )
                manifest = doc["manifest"]
                chunks = [base64.b64decode(ch) for ch in doc["chunks"]]
            # the legacy endpoint can serve a DISK snapshot OLDER than
            # this node (the capture-on-request original was always the
            # peer's tip): adopting it would rewind the chain
            if int(manifest["height"]) <= before:
                raise ValueError(
                    f"peer snapshot at {manifest['height']} is not "
                    f"ahead of height {before}"
                )
            c.state_sync_bootstrap(self.vnode, manifest, chunks)
            client.cleanup()
        except Exception:
            # failed adoption: drop the restore material, or the resume
            # preference would latch onto the same manifest next call
            client.cleanup()
            raise
        finally:
            if ephemeral:
                import shutil

                shutil.rmtree(workdir, ignore_errors=True)
        return {"height": self.vnode.app.height, "from_height": before,
                "app_hash": self.vnode.app.last_app_hash.hex()}

    # -- lifecycle -------------------------------------------------------

    def serve_background(self) -> threading.Thread:
        th = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        th.start()
        return th

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        if self.reactor is not None:
            self.reactor.stop()
        # deregister the commit-seed hook: a service rebuilt over a
        # long-lived vnode must not leave its dead SampleCore receiving
        # (and pinning) every future height's entries
        self.vnode.app.remove_da_seed_listener(
            self.das_core.seed_cache_entry)
        self.httpd.shutdown()
        self.httpd.server_close()
