"""Stateless DA-core service: the boundary a FOREIGN node calls.

This is the SURVEY §7.1.7 shim surface — the framework's stated reason
to exist as a drop-in accelerator. A Go node (or any language) keeps its
own square builder and consensus, swaps the body of `da.ExtendShares` +
`NewDataAvailabilityHeader` (reference
``pkg/da/data_availability_header.go:44-75``, called from
``app/extend_block.go:14-26``) for one RPC here, and uses the returned
DAH verbatim:

  ExtendAndCommit  ODS shares in -> row roots + column roots + data root
                   (the erasure extension and every NMT/Merkle hash run
                   on this side — on TPU when a device engine backs the
                   service, host SIMD otherwise).
  ProveShares      share range in -> ShareProof against the data root
                   (``pkg/proof`` ProveShares analog), served from the
                   bounded cache of recently extended squares (keyed by
                   data root) or from a caller-supplied ODS.

Callers: the node HTTP service mounts these under ``/da/*``
(service/server.py), the standalone ``da-serve`` CLI serves them with no
chain attached (the sidecar deployment shape), the gRPC plane exposes
them as ``celestia_tpu.da.v1.DAService`` (proto/celestia_tpu/da/v1/
da.proto), ``shim/go`` holds the Go-side drop-in source, and
``native/da_client.cc`` drives the HTTP route end-to-end from C++ with
an independent local recompute (byte-identity check).
"""

from __future__ import annotations

import base64
import collections
import threading

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.utils import telemetry


class DAError(ValueError):
    pass


class DACore:
    """Engine-gated extend/commit/prove with a bounded square cache.

    engine="host": pure NumPy/SIMD path — safe in any process (never
    imports-and-dispatches jax; a validator next to a dead TPU relay
    must not hang). engine="device": one jitted dispatch per square
    (da/dah.new_dah_from_ods). Proof construction is host-side either
    way (tree traversal, not FLOPs)."""

    def __init__(self, engine: str = "host", cache_squares: int = 4):
        if engine not in ("host", "device"):
            raise DAError(f"unknown engine {engine!r}")
        self.engine = engine
        self._cache: collections.OrderedDict[str, tuple] = \
            collections.OrderedDict()
        self._cache_squares = cache_squares
        self._lock = threading.Lock()

    # -- core ------------------------------------------------------------

    def _pipeline(self, ods: np.ndarray):
        """(eds_obj, dah, data_root) for an ODS array."""
        from celestia_app_tpu.da import dah as dah_mod

        if self.engine == "device":
            dah, eds, root = dah_mod.new_dah_from_ods(ods)
            return eds, dah, root
        from celestia_app_tpu.utils import refimpl

        eds_np, rows, cols, root = refimpl.pipeline_host(ods)
        dah = dah_mod.DataAvailabilityHeader(
            row_roots=tuple(rows), col_roots=tuple(cols)
        )
        return dah_mod.ExtendedDataSquare(eds_np), dah, root

    def _decode_ods(self, payload: dict) -> np.ndarray:
        from celestia_app_tpu.da import dah as dah_mod

        raw = payload["ods"]
        if isinstance(raw, str):  # JSON transport; gRPC hands raw bytes
            raw = base64.b64decode(raw)
        if len(raw) % appconsts.SHARE_SIZE:
            raise DAError(
                f"ods byte length {len(raw)} is not a multiple of the "
                f"{appconsts.SHARE_SIZE}-byte share size"
            )
        n = len(raw) // appconsts.SHARE_SIZE
        k = int(n ** 0.5)
        if k * k != n or k & (k - 1) or not n:
            raise DAError(
                f"share count {n} is not a power-of-two perfect square"
            )
        # protocol cap is 128 (appconsts.square_size_upper_bound); allow
        # 2x headroom for benchmark-scale squares on device engines
        cap = 2 * appconsts.square_size_upper_bound(
            appconsts.LATEST_VERSION)
        if k > cap:
            raise DAError(f"square size {k} exceeds the service cap {cap}")
        if self.engine == "host" and k > 128:
            raise DAError(
                "host engine covers the GF(2^8) range (k <= 128); run the "
                "service with a device engine for larger squares"
            )
        want = payload.get("square_size")
        if want is not None and int(want) != k:
            raise DAError(
                f"square_size {want} does not match the {k}x{k} ods"
            )
        return dah_mod.shares_to_ods(
            [raw[i * appconsts.SHARE_SIZE:(i + 1) * appconsts.SHARE_SIZE]
             for i in range(n)]
        )

    def extend_and_commit(self, payload: dict) -> dict:
        """ODS in -> DAH out; the extended square is cached by data root
        so a follow-up ProveShares costs tree traversal only."""
        ods = self._decode_ods(payload)
        eds, dah, root = self._pipeline(ods)
        key = root.hex()
        with self._lock:
            self._cache[key] = (eds, dah)
            self._cache.move_to_end(key)
            while len(self._cache) > self._cache_squares:
                self._cache.popitem(last=False)
        return {
            "square_size": int(ods.shape[0]),
            "row_roots": [r.hex() for r in dah.row_roots],
            "col_roots": [r.hex() for r in dah.col_roots],
            "data_root": key,
        }

    def prove_shares(self, payload: dict) -> dict:
        """Share-range proof. Source square: ``data_root`` (hex, from the
        cache of recent ExtendAndCommit results) or a fresh ``ods``.
        Every malformed input raises DAError (transports map it to a
        client error, never a 500)."""
        from celestia_app_tpu.chain.query import _share_proof_json
        from celestia_app_tpu.da import proof as proof_mod

        want_root = payload.get("data_root")
        if want_root is not None:
            with self._lock:
                hit = self._cache.get(want_root)
                if hit is not None:
                    self._cache.move_to_end(want_root)
            if hit is None:
                raise DAError(
                    f"no cached square for data root {want_root}; resend "
                    "the ods or re-run extend_commit"
                )
            eds, dah = hit
            root = bytes.fromhex(want_root)
        elif "ods" in payload:
            eds, dah, root = self._pipeline(self._decode_ods(payload))
        else:
            raise DAError("prove_shares needs data_root or ods")

        try:
            start, end = int(payload["start"]), int(payload["end"])
        except (KeyError, TypeError, ValueError):
            raise DAError("prove_shares needs integer start and end") \
                from None
        k = eds.width // 2
        if not (0 <= start < end <= k * k):
            raise DAError(
                f"invalid share range [{start}, {end}) for a {k}x{k} square"
            )
        # namespace parsing + extraction live on the read plane's shared
        # helpers (da/namespace_device.py) — one codec for every caller
        from celestia_app_tpu.da import namespace_device as nsdev

        try:
            namespace = nsdev.decode_namespace(payload.get("namespace", ""))
        except ValueError:
            raise DAError("namespace must be hex") from None
        if not namespace:
            namespace = nsdev.share_namespace(eds.squares[start // k,
                                                          start % k])
        pf = proof_mod.new_share_inclusion_proof(eds, dah, start, end,
                                                 namespace)
        return {
            "proof": _share_proof_json(pf),
            "data_root": root.hex(),
        }

    # -- one dispatcher shared by every transport ------------------------

    def handle(self, path: str, payload: dict) -> dict:
        try:
            if path == "/da/extend_commit":
                return self.extend_and_commit(payload)
            if path == "/da/prove_shares":
                return self.prove_shares(payload)
        except KeyError as e:  # missing request field = client error
            raise DAError(f"missing field {e}") from None
        raise DAError(f"no DA route {path}")


class DAService:
    """Standalone HTTP server for the two DA routes — the sidecar shape:
    run it next to a foreign node, point the shim at it, no chain state
    anywhere in the process."""

    def __init__(self, core: DACore, host: str = "127.0.0.1",
                 port: int = 26659):
        import json
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        service = self
        self.core = core

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    out = service.core.handle(self.path, payload)
                    code = 200
                except DAError as e:
                    out, code = {"error": str(e)}, 400
                except Exception as e:  # never kill the serving thread
                    telemetry.incr("http.500")
                    out, code = {"error": f"{type(e).__name__}: {e}"}, 500
                body = json.dumps(out).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/status":
                    body = json.dumps({
                        "service": "da", "engine": service.core.engine,
                    }).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]

    def serve_background(self):
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def shutdown(self):
        self._httpd.shutdown()
