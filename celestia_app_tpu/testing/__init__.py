"""Adversarial test fixtures (the reference's test/util/malicious analog)."""
