"""Malicious-proposer fixtures: build blocks that honest validators must reject.

Reference parity: test/util/malicious/ —
  tree.go:19-60            BlindTree: an NMT that skips namespace-ordering
                           verification (ForceAddLeaf instead of Push), so a
                           malicious proposer can still produce axis roots
                           over an invalid share ordering.
  out_of_order_builder.go  OutOfOrderExport: swaps two blobs in the square.
  out_of_order_prepare.go  OutOfOrderPrepareProposal: honest tx filtering,
                           malicious square + commitment.

These fixtures exist so tests can assert the *honest* ProcessProposal path
rejects each class of malice (the reference additionally uses them to source
fraud proofs)."""

from __future__ import annotations

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.block import Block, Header
from celestia_app_tpu.da import dah as dah_mod
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.utils import merkle_host, nmt_host

NS = appconsts.NAMESPACE_SIZE


class BlindNmtTree(nmt_host.NmtTree):
    """NMT that accepts leaves in any namespace order (malicious/tree.go)."""

    def push(self, ns: bytes, data: bytes) -> None:  # ForceAddLeaf
        self.leaves.append((ns, data))


def swap_first_two_blobs(square) -> list[bytes]:
    """Square share list with the first two blobs' share ranges swapped
    (OutOfOrderExport, out_of_order_builder.go:62-79). Requires >= 2 blobs."""
    shares = list(square.share_bytes())
    keys = sorted(square.blob_start_indexes.keys())
    if len(keys) < 2:
        raise ValueError("need at least two blobs to swap")
    (i0, j0), (i1, j1) = keys[0], keys[1]
    s0 = square.blob_start_indexes[(i0, j0)]
    c0 = square.pfbs[i0].blobs[j0].share_count()
    s1 = square.blob_start_indexes[(i1, j1)]
    c1 = square.pfbs[i1].blobs[j1].share_count()
    if c0 != c1:
        # swap equal-length prefixes so the layout geometry stays identical
        c0 = c1 = min(c0, c1)
    a, b = shares[s0 : s0 + c0], shares[s1 : s1 + c1]
    shares[s0 : s0 + c0], shares[s1 : s1 + c1] = b, a
    return shares


def blind_dah(ods: np.ndarray):
    """DAH over an (invalidly ordered) ODS using blind trees: the malicious
    analog of utils/refimpl.pipeline_host — an honest NmtTree would raise."""
    from celestia_app_tpu.utils import refimpl

    eds = refimpl.extend_square_host(ods)
    two_k = eds.shape[0]
    k = two_k // 2

    def tree_root(axis_get, axis_index) -> bytes:
        tree = BlindNmtTree()
        for j in range(two_k):
            share = axis_get(j).tobytes()
            in_q0 = axis_index < k and j < k
            ns = share[:NS] if in_q0 else ns_mod.PARITY_NS_RAW
            tree.push(ns, share)
        return nmt_host.serialize(tree.root())

    rows = [tree_root(lambda j, r=r: eds[r, j], r) for r in range(two_k)]
    cols = [tree_root(lambda j, c=c: eds[j, c], c) for c in range(two_k)]
    root = merkle_host.hash_from_leaves(rows + cols)
    return dah_mod.DataAvailabilityHeader(tuple(rows), tuple(cols)), root


def out_of_order_prepare(app, raw_txs: list[bytes], t: float) -> Block:
    """Malicious PrepareProposal: honest filtering and square build, then the
    first two blobs swapped and the data root recomputed with blind trees
    (out_of_order_prepare.go:18-76)."""
    honest = app.prepare_proposal(raw_txs, t=t)
    sq = honest.square if hasattr(honest, "square") else None
    block = honest.block if hasattr(honest, "block") else honest
    if sq is None:
        raise ValueError("prepare_proposal result carries no square")
    shares = swap_first_two_blobs(sq)
    ods = dah_mod.shares_to_ods(shares)
    _, root = blind_dah(ods)
    import dataclasses

    # replace ONLY the data root: every other header field (including any
    # added later, like validators_hash) stays honest, so ProcessProposal's
    # rejection exercises the data-root check and nothing else
    forged = dataclasses.replace(block.header, data_hash=root)
    return Block(header=forged, txs=block.txs)


def cmt_bad_parity_entry(ods: np.ndarray, equation: int,
                         xor_byte: int = 0x5A,
                         engine: str = "host"):
    """Malicious CMT producer (codec plane, da/cmt.py): encode the ODS
    honestly, then corrupt base-layer parity symbol `equation` BEFORE
    hashing — the commitments bind the corrupt symbol, so sampling alone
    verifies it, and only the peeling decoder's parity-equation audit
    (one violated equation = the whole fraud proof) can convict. The CMT
    analog of blind_dah's committed non-codeword."""
    from celestia_app_tpu.da import cmt

    honest = cmt.build_layers(ods, engine)
    k = ods.shape[0]
    n_data0 = k * k
    layer0 = honest.layers[0].copy()
    layer0[n_data0 + equation, 0] ^= xor_byte
    # rebuild every layer ABOVE the corruption from the corrupt hashes
    # (the producer commits a self-consistent tree over bad symbols)
    layers = [layer0]
    hash_lists = [cmt._hash_symbols(layer0, engine)]
    data = hash_lists[0].reshape(-1, cmt.Q * cmt.HASH_BYTES)
    for _ in cmt.layer_plan(k)[1:]:
        from celestia_app_tpu.ops import ldpc

        parity = ldpc.encode(data, engine)
        coded = np.concatenate([data, parity], axis=0)
        hash_lists.append(cmt._hash_symbols(coded, engine))
        layers.append(coded)
        data = hash_lists[-1].reshape(-1, cmt.Q * cmt.HASH_BYTES)
    commitments = cmt.CmtCommitments(
        k=k, root_hashes=tuple(bytes(h) for h in hash_lists[-1]))
    return cmt.CmtEntry(commitments, layers, hash_lists)


def pcmt_bad_parity_entry(ods: np.ndarray, equation: int | None = None,
                          xor_byte: int = 0x5A,
                          engine: str = "host"):
    """Malicious PCMT producer (codec plane, da/pcmt.py): polar-encode
    the ODS honestly, corrupt ONE non-data committed class BEFORE
    hashing, and grow the whole hash tree over the result — the
    commitments bind the corrupt class, sampling alone verifies it, and
    only the SC peeling decoder's check audit can convict. With
    ``equation`` the corrupt class is that check's lowest non-data
    member; by default it is the lowest check-constrained non-data
    class. The provable location — (0, lowest check containing the
    corrupt class), which is what ``repair`` raises when that check's
    members are all served — rides on the entry as
    ``entry.fraud_location``."""
    from celestia_app_tpu.da import pcmt
    from celestia_app_tpu.ops import polar

    k = ods.shape[0]
    g = polar.geometry(k * k)
    data = np.ascontiguousarray(ods, dtype=np.uint8).reshape(
        k * k, appconsts.SHARE_SIZE)
    base = polar.encode(data, engine).copy()
    is_data = np.zeros(g.C, dtype=bool)
    is_data[g.data_class] = True
    if equation is None:
        in_check = np.zeros(g.C, dtype=bool)
        in_check[g.checks.ravel()] = True
        target = int(np.flatnonzero(~is_data & in_check)[0])
    else:
        cand = [int(x) for x in g.checks[equation] if not is_data[x]]
        if not cand:
            raise ValueError(
                f"check {equation} has only data members; pick another")
        target = min(cand)
    base[target, 0] ^= xor_byte
    entry = pcmt.build_from_base(ods, base, engine)
    containing = np.flatnonzero((g.checks == target).any(axis=1))
    entry.fraud_location = (0, int(containing[0]))
    return entry


def incorrect_coding_fixture(scheme: str, ods: np.ndarray,
                             engine: str = "host"):
    """THE scheme-keyed committed-non-codeword fixture: returns (entry,
    location, withheld_cells, wire_id) for any registered scheme — the
    one hook sim/scenarios.py and bench.py drive, so judging a new
    codec needs a fixture here and no if-chains there. ``location`` is
    what the scheme's repair provably raises; ``withheld_cells`` is a
    quarter-ish withholding set that forces samplers to escalate while
    keeping the fraud location's members served (the proof must stay
    assemblable from served symbols)."""
    k = ods.shape[0]
    if scheme == "rs2d-nmt":
        entry = rs2d_bad_parity_entry(ods, row=1)
        # half the bad row withheld: samplers escalate, yet the
        # orthogonal-proof BEFP still finds its k members
        return entry, ("row", 1), [(1, j) for j in range(k)], 0
    if scheme == "cmt-ldpc":
        from celestia_app_tpu.da import cmt as cmt_mod

        bad_eq = 3
        entry = cmt_bad_parity_entry(ods, equation=bad_eq,
                                     engine=engine)
        comm = entry.commitments
        members = set(cmt_mod.equation_members(comm, 0, bad_eq))
        candidates = [i for i in range(comm.n_base)
                      if i not in members]
        withheld = [(0, i) for i in candidates[: comm.n_base // 4]]
        return entry, (0, bad_eq), withheld, 1
    if scheme == "pcmt-polar":
        from celestia_app_tpu.da import pcmt as pcmt_mod

        entry = pcmt_bad_parity_entry(ods, engine=engine)
        location = entry.fraud_location
        comm = entry.commitments
        members = set(pcmt_mod.equation_members(
            comm, location[0], location[1]))
        candidates = [i for i in range(comm.n_base)
                      if i not in members]
        withheld = [(0, i) for i in candidates[: comm.n_base // 4]]
        return entry, location, withheld, 2
    raise ValueError(f"no malicious fixture for scheme {scheme!r}")


def rs2d_bad_parity_entry(ods: np.ndarray, row: int = 1,
                          xor_byte: int = 0x5A):
    """Malicious 2D-RS producer (codec plane): extend honestly, corrupt
    one parity cell of `row`, and commit NMT trees over the RESULT — a
    committed non-codeword whose samples all verify, convictable only by
    a BEFP. The one shared fixture for the rs2d fraud accept/reject
    conformance and the --codec bench (duplicate copies of a
    security-sensitive fixture drift)."""
    from celestia_app_tpu.da import edscache as edscache_mod
    from celestia_app_tpu.utils import fast_host

    k = ods.shape[0]
    eds = fast_host.extend_square_fast(ods).copy()
    eds[row, k + 2] ^= xor_byte
    rows, cols = fast_host.axis_roots_fast(eds)
    dah = dah_mod.DataAvailabilityHeader(
        row_roots=tuple(bytes(r) for r in rows),
        col_roots=tuple(bytes(c) for c in cols),
    )
    return edscache_mod.EdsCacheEntry(
        dah_mod.ExtendedDataSquare(eds), dah, dah.hash())
