"""Malicious-proposer fixtures: build blocks that honest validators must reject.

Reference parity: test/util/malicious/ —
  tree.go:19-60            BlindTree: an NMT that skips namespace-ordering
                           verification (ForceAddLeaf instead of Push), so a
                           malicious proposer can still produce axis roots
                           over an invalid share ordering.
  out_of_order_builder.go  OutOfOrderExport: swaps two blobs in the square.
  out_of_order_prepare.go  OutOfOrderPrepareProposal: honest tx filtering,
                           malicious square + commitment.

These fixtures exist so tests can assert the *honest* ProcessProposal path
rejects each class of malice (the reference additionally uses them to source
fraud proofs)."""

from __future__ import annotations

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.block import Block, Header
from celestia_app_tpu.da import dah as dah_mod
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.utils import merkle_host, nmt_host

NS = appconsts.NAMESPACE_SIZE


class BlindNmtTree(nmt_host.NmtTree):
    """NMT that accepts leaves in any namespace order (malicious/tree.go)."""

    def push(self, ns: bytes, data: bytes) -> None:  # ForceAddLeaf
        self.leaves.append((ns, data))


def swap_first_two_blobs(square) -> list[bytes]:
    """Square share list with the first two blobs' share ranges swapped
    (OutOfOrderExport, out_of_order_builder.go:62-79). Requires >= 2 blobs."""
    shares = list(square.share_bytes())
    keys = sorted(square.blob_start_indexes.keys())
    if len(keys) < 2:
        raise ValueError("need at least two blobs to swap")
    (i0, j0), (i1, j1) = keys[0], keys[1]
    s0 = square.blob_start_indexes[(i0, j0)]
    c0 = square.pfbs[i0].blobs[j0].share_count()
    s1 = square.blob_start_indexes[(i1, j1)]
    c1 = square.pfbs[i1].blobs[j1].share_count()
    if c0 != c1:
        # swap equal-length prefixes so the layout geometry stays identical
        c0 = c1 = min(c0, c1)
    a, b = shares[s0 : s0 + c0], shares[s1 : s1 + c1]
    shares[s0 : s0 + c0], shares[s1 : s1 + c1] = b, a
    return shares


def blind_dah(ods: np.ndarray):
    """DAH over an (invalidly ordered) ODS using blind trees: the malicious
    analog of utils/refimpl.pipeline_host — an honest NmtTree would raise."""
    from celestia_app_tpu.utils import refimpl

    eds = refimpl.extend_square_host(ods)
    two_k = eds.shape[0]
    k = two_k // 2

    def tree_root(axis_get, axis_index) -> bytes:
        tree = BlindNmtTree()
        for j in range(two_k):
            share = axis_get(j).tobytes()
            in_q0 = axis_index < k and j < k
            ns = share[:NS] if in_q0 else ns_mod.PARITY_NS_RAW
            tree.push(ns, share)
        return nmt_host.serialize(tree.root())

    rows = [tree_root(lambda j, r=r: eds[r, j], r) for r in range(two_k)]
    cols = [tree_root(lambda j, c=c: eds[j, c], c) for c in range(two_k)]
    root = merkle_host.hash_from_leaves(rows + cols)
    return dah_mod.DataAvailabilityHeader(tuple(rows), tuple(cols)), root


def out_of_order_prepare(app, raw_txs: list[bytes], t: float) -> Block:
    """Malicious PrepareProposal: honest filtering and square build, then the
    first two blobs swapped and the data root recomputed with blind trees
    (out_of_order_prepare.go:18-76)."""
    honest = app.prepare_proposal(raw_txs, t=t)
    sq = honest.square if hasattr(honest, "square") else None
    block = honest.block if hasattr(honest, "block") else honest
    if sq is None:
        raise ValueError("prepare_proposal result carries no square")
    shares = swap_first_two_blobs(sq)
    ods = dah_mod.shares_to_ods(shares)
    _, root = blind_dah(ods)
    import dataclasses

    # replace ONLY the data root: every other header field (including any
    # added later, like validators_hash) stays honest, so ProcessProposal's
    # rejection exercises the data-root check and nothing else
    forged = dataclasses.replace(block.header, data_hash=root)
    return Block(header=forged, txs=block.txs)


def cmt_bad_parity_entry(ods: np.ndarray, equation: int,
                         xor_byte: int = 0x5A,
                         engine: str = "host"):
    """Malicious CMT producer (codec plane, da/cmt.py): encode the ODS
    honestly, then corrupt base-layer parity symbol `equation` BEFORE
    hashing — the commitments bind the corrupt symbol, so sampling alone
    verifies it, and only the peeling decoder's parity-equation audit
    (one violated equation = the whole fraud proof) can convict. The CMT
    analog of blind_dah's committed non-codeword."""
    from celestia_app_tpu.da import cmt

    honest = cmt.build_layers(ods, engine)
    k = ods.shape[0]
    n_data0 = k * k
    layer0 = honest.layers[0].copy()
    layer0[n_data0 + equation, 0] ^= xor_byte
    # rebuild every layer ABOVE the corruption from the corrupt hashes
    # (the producer commits a self-consistent tree over bad symbols)
    layers = [layer0]
    hash_lists = [cmt._hash_symbols(layer0, engine)]
    data = hash_lists[0].reshape(-1, cmt.Q * cmt.HASH_BYTES)
    for _ in cmt.layer_plan(k)[1:]:
        from celestia_app_tpu.ops import ldpc

        parity = ldpc.encode(data, engine)
        coded = np.concatenate([data, parity], axis=0)
        hash_lists.append(cmt._hash_symbols(coded, engine))
        layers.append(coded)
        data = hash_lists[-1].reshape(-1, cmt.Q * cmt.HASH_BYTES)
    commitments = cmt.CmtCommitments(
        k=k, root_hashes=tuple(bytes(h) for h in hash_lists[-1]))
    return cmt.CmtEntry(commitments, layers, hash_lists)


def rs2d_bad_parity_entry(ods: np.ndarray, row: int = 1,
                          xor_byte: int = 0x5A):
    """Malicious 2D-RS producer (codec plane): extend honestly, corrupt
    one parity cell of `row`, and commit NMT trees over the RESULT — a
    committed non-codeword whose samples all verify, convictable only by
    a BEFP. The one shared fixture for the rs2d fraud accept/reject
    conformance and the --codec bench (duplicate copies of a
    security-sensitive fixture drift)."""
    from celestia_app_tpu.da import edscache as edscache_mod
    from celestia_app_tpu.utils import fast_host

    k = ods.shape[0]
    eds = fast_host.extend_square_fast(ods).copy()
    eds[row, k + 2] ^= xor_byte
    rows, cols = fast_host.axis_roots_fast(eds)
    dah = dah_mod.DataAvailabilityHeader(
        row_roots=tuple(bytes(r) for r in rows),
        col_roots=tuple(bytes(c) for c in cols),
    )
    return edscache_mod.EdsCacheEntry(
        dah_mod.ExtendedDataSquare(eds), dah, dah.hash())
