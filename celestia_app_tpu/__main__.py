from celestia_app_tpu.cli import main
import sys

sys.exit(main())
