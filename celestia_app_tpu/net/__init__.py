"""Peer networking: the one hardened HTTP transport (net/transport.py)."""

from celestia_app_tpu.net.transport import (  # noqa: F401
    BreakerOpen,
    PeerClient,
    TransportConfig,
    TransportError,
)
