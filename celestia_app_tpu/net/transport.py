"""THE hardened peer HTTP transport: timeouts, retries, circuit breakers.

Before this module, raw ``urllib.request.urlopen`` calls were scattered
across eight modules with divergent timeout/retry behavior and no memory
of peer health: a dead peer cost every caller a fresh connect timeout on
every attempt, forever. This is the one client every peer-facing HTTP
call goes through (a tier-1 lint test enforces it), giving the whole
process:

- **per-request timeouts** — every request has one; no unbounded waits.
- **bounded retries with jittered exponential backoff** — retry storms
  against a struggling peer are the classic self-inflicted outage; the
  jitter decorrelates the fleet.
- **a per-peer circuit breaker** (closed -> open -> half-open): after
  ``failure_threshold`` consecutive failures the peer's circuit OPENS and
  requests fail instantly (``BreakerOpen``) without touching the socket;
  after ``reset_timeout`` ONE probe request is let through (half-open) —
  success closes the circuit, failure re-opens it. The reference's p2p
  layer gets the same effect from peer eviction + reconnect backoff.
- **peer health scoring** — per-peer success/failure counts, consecutive-
  failure streak, EWMA latency, last error; ``snapshot()`` feeds the
  ``net`` block of ``/consensus/status`` (docs/FORMATS.md §9).
- **telemetry** — ``net.requests`` / ``net.failures`` /
  ``net.breaker_open`` / ``net.breaker_rejected`` / ``net.retries``
  counters plus per-client latency timers, all in the global registry.
- **fault injection** — every outbound request passes the
  ``net.request`` fault point (celestia_app_tpu/faults) with context
  ``{owner, peer, path}``: armed drop/delay/error/duplicate faults act
  HERE, so chaos tests partition and degrade real nodes without touching
  the network stack.

Error contract: transport-level failures (refused, timeout, DNS, garbled
body, injected faults, open breakers) raise ``TransportError`` (an
``OSError`` — existing ``except OSError`` callers keep working;
``BreakerOpen`` subclasses it). An HTTP *status* error means the peer is
ALIVE and answering — it counts as peer health success and propagates as
``urllib.error.HTTPError`` for callers that read error bodies (the
relayer's 404-means-absent probe, remote_consensus's refusal mapping).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import threading
import time
import urllib.error
import urllib.request

from celestia_app_tpu import faults
from celestia_app_tpu import obs
from celestia_app_tpu.utils import telemetry


class TransportError(OSError):
    """A request failed at the transport level after all retries."""


class BreakerOpen(TransportError):
    """The peer's circuit is open: failed fast, no I/O attempted."""


@dataclasses.dataclass
class TransportConfig:
    timeout: float = 5.0          # per-request socket timeout (seconds)
    retries: int = 2              # attempts per request() call
    backoff: float = 0.05         # base sleep between attempts (doubles)
    backoff_max: float = 2.0      # backoff ceiling
    jitter: float = 0.25          # +/- fraction of the backoff, decorrelates
    failure_threshold: int = 5    # consecutive failures -> breaker opens
    reset_timeout: float = 3.0    # open -> half-open probe window


class _PeerState:
    """Health record + breaker state for one peer URL (lock: the owning
    PeerClient's)."""

    __slots__ = ("state", "successes", "failures", "consecutive",
                 "opened_at", "latency_ms", "last_error", "probing")

    def __init__(self):
        self.state = "closed"        # closed | open | half-open
        self.successes = 0
        self.failures = 0
        self.consecutive = 0         # consecutive failures
        self.opened_at = 0.0
        self.latency_ms = None       # EWMA over successful requests
        self.last_error = None
        self.probing = False         # a half-open probe is in flight

    def to_json(self) -> dict:
        return {
            "state": self.state,
            "successes": self.successes,
            "failures": self.failures,
            "consecutive_failures": self.consecutive,
            "latency_ms": round(self.latency_ms, 3)
            if self.latency_ms is not None else None,
            "last_error": self.last_error,
        }


class PeerClient:
    """One hardened HTTP client; holds per-peer breaker/health state, so
    components that talk to the same peers repeatedly (the reactor, the
    DASer's PeerSet, an orchestrator) should share one instance across
    their requests. `name` tags telemetry and the fault context (chaos
    specs match on it to scope faults to one node of an in-process net)."""

    def __init__(self, cfg: TransportConfig | None = None,
                 name: str = "peer", clock=None):
        self.cfg = cfg or TransportConfig()
        self.name = name
        # THE retry-backoff + breaker time source (utils/clock.py):
        # SystemClock by default (behavior unchanged); components running
        # under the scenario plane hand their VirtualClock down so
        # breaker open-timers and backoff sleeps run on virtual seconds
        from celestia_app_tpu.utils import clock as clock_mod

        self.clock = clock if clock is not None else clock_mod.SYSTEM
        # url -> breaker/health state, shared by every thread that
        # sends through this client
        self._peers: dict[str, _PeerState] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # jitter entropy only — never consulted by fault injection, so a
        # seeded fault run stays deterministic regardless of this rng
        self._rng = random.Random()

    # -- breaker gate -----------------------------------------------------

    def _peer_locked(self, url: str) -> _PeerState:
        st = self._peers.get(url)
        if st is None:
            st = self._peers[url] = _PeerState()
        return st

    def available(self, url: str) -> bool:
        """True when a request to `url` would be ATTEMPTED (circuit
        closed, half-open, or open-but-probe-eligible). Send loops use
        this to skip an open peer without paying even the fast
        BreakerOpen raise per queued message."""
        url = url.rstrip("/")
        with self._lock:
            st = self._peers.get(url)
            if st is None or st.state != "open":
                return True
            return (self.clock.monotonic() - st.opened_at
                    >= self.cfg.reset_timeout)

    def _admit(self, url: str) -> bool:
        """Breaker admission for one attempt. Returns True when this
        attempt is the half-open probe (so failure handling re-opens
        rather than merely counting)."""
        with self._lock:
            st = self._peer_locked(url)
            if st.state == "closed":
                return False
            if st.state == "open":
                if (self.clock.monotonic() - st.opened_at
                        < self.cfg.reset_timeout):
                    telemetry.incr("net.breaker_rejected")
                    raise BreakerOpen(
                        f"{self.name}: circuit open for {url} "
                        f"(last: {st.last_error})"
                    )
                st.state = "half-open"
                st.probing = True
                return True
            # half-open: exactly one probe in flight
            if st.probing:
                telemetry.incr("net.breaker_rejected")
                raise BreakerOpen(
                    f"{self.name}: half-open probe in flight for {url}"
                )
            st.probing = True
            return True

    def _record_success(self, url: str, dt_ms: float) -> None:
        with self._lock:
            st = self._peer_locked(url)
            st.successes += 1
            st.consecutive = 0
            st.probing = False
            if st.state != "closed":
                telemetry.incr("net.breaker_closed")
            st.state = "closed"
            st.latency_ms = dt_ms if st.latency_ms is None else (
                0.8 * st.latency_ms + 0.2 * dt_ms
            )

    def _record_failure(self, url: str, err: str, probe: bool) -> None:
        with self._lock:
            st = self._peer_locked(url)
            st.failures += 1
            st.consecutive += 1
            st.last_error = err[:200]
            st.probing = False
            if probe or st.consecutive >= self.cfg.failure_threshold:
                if st.state != "open":
                    telemetry.incr("net.breaker_open")
                st.state = "open"
                st.opened_at = self.clock.monotonic()
        telemetry.incr("net.failures")

    # -- the request path -------------------------------------------------

    def _one(self, url: str, path: str, payload, timeout: float,
             raw: bool):
        # span propagation (obs/spans.py): while a span is active on the
        # calling thread, every peer request carries X-Celestia-Trace so
        # the serving side links its work into the originating trace
        headers: dict[str, str] = {}
        trace = obs.http_header()
        if trace is not None:
            headers[obs.TRACE_HEADER] = trace
        if payload is None:
            req = urllib.request.Request(url + path, headers=headers)
        else:
            headers["Content-Type"] = "application/json"
            req = urllib.request.Request(
                url + path, data=json.dumps(payload).encode(),
                headers=headers,
                method="POST",
            )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = r.read()
        return body if raw else json.loads(body)

    def request(self, url: str, path: str, payload: dict | None = None,
                *, timeout: float | None = None, retries: int | None = None,
                raw: bool = False):
        """GET (payload None) or JSON POST ``url + path``; returns the
        parsed JSON body (bytes with ``raw=True``). Raises BreakerOpen /
        TransportError / urllib.error.HTTPError per the module error
        contract."""
        url = url.rstrip("/")
        timeout = self.cfg.timeout if timeout is None else timeout
        attempts = max(1, self.cfg.retries if retries is None else retries)
        delay = self.cfg.backoff
        last = "no attempt"
        for attempt in range(attempts):
            probe = self._admit(url)  # raises BreakerOpen when rejected
            t0 = time.perf_counter()
            try:
                action = faults.fire("net.request", owner=self.name,
                                     peer=url, path=path)
                if action in ("drop", "error"):
                    # drop: the bytes never leave this process; error: the
                    # peer "answered garbage" — both are transport
                    # failures to the caller and to peer health
                    raise TransportError(
                        f"injected fault: {action} {url}{path}"
                    )
                out = self._one(url, path, payload, timeout, raw)
                if action == "duplicate":
                    out = self._one(url, path, payload, timeout, raw)
            except urllib.error.HTTPError as e:
                # an HTTP status error is an ANSWER: the peer is alive
                self._record_success(
                    url, (time.perf_counter() - t0) * 1e3
                )
                telemetry.incr("net.requests")
                raise e
            except (urllib.error.URLError, OSError, ValueError,
                    TimeoutError, http.client.HTTPException) as e:
                # HTTPException: a garbled/torn HTTP response (e.g.
                # BadStatusLine) — NOT an OSError subclass, but the same
                # transport-failure class; it must feed the breaker, not
                # escape and wedge a half-open probe
                last = f"{type(e).__name__}: {e}"
                self._record_failure(url, last, probe)
                if attempt + 1 < attempts and self.available(url):
                    telemetry.incr("net.retries")
                    jit = 1.0 + self.cfg.jitter * (
                        2.0 * self._rng.random() - 1.0
                    )
                    self.clock.sleep(
                        min(delay, self.cfg.backoff_max) * jit)
                    delay *= 2
                continue
            except BaseException as e:
                # unexpected escape (programming error, non-serializable
                # payload, injected chaos): record it so a granted
                # half-open probe can never stay "in flight" forever and
                # wedge the peer in BreakerOpen
                self._record_failure(
                    url, f"{type(e).__name__}: {e}", probe
                )
                raise
            dt_ms = (time.perf_counter() - t0) * 1e3
            self._record_success(url, dt_ms)
            telemetry.incr("net.requests")
            telemetry.measure_since(f"net.{self.name}.request",
                                    t0)
            return out
        raise TransportError(
            f"{self.name}: {url}{path} failed after {attempts} "
            f"attempt(s): {last}"
        )

    def get(self, url: str, path: str, **kw):
        return self.request(url, path, None, **kw)

    def post(self, url: str, path: str, payload: dict, **kw):
        return self.request(url, path, payload, **kw)

    def penalize(self, url: str, reason: str) -> None:
        """Application-level failure report: the peer ANSWERED, but with
        content that failed verification (e.g. a state-sync chunk whose
        sha256 mismatched its manifest). Feeds the peer's health score
        and consecutive-failure streak exactly like a transport failure,
        so a corrupt-serving peer is deprioritized and — past the
        failure threshold — breaker-skipped entirely."""
        self._record_failure(url.rstrip("/"), f"penalized: {reason[:160]}",
                             False)
        telemetry.incr("net.penalized")

    # -- health surface ---------------------------------------------------

    def snapshot(self) -> dict:
        """{peer_url: health} — the ``net`` block of /consensus/status."""
        with self._lock:
            return {u: st.to_json() for u, st in self._peers.items()}

    def reset_peer(self, url: str) -> None:
        with self._lock:
            self._peers.pop(url.rstrip("/"), None)


# Shared default client for one-shot tooling (CLI subcommands, scripts)
# that has no long-lived component to hang peer state off of. Components
# with real peer relationships (reactor, DASer, orchestrator) own their
# instances so their health state is per-component and inspectable.
DEFAULT = PeerClient(name="default")


def request_json(url: str, path: str = "", payload: dict | None = None,
                 *, timeout: float = 10.0, retries: int = 1):
    """One-shot convenience over the shared DEFAULT client."""
    return DEFAULT.request(url, path, payload, timeout=timeout,
                           retries=retries)
