"""Protobuf tx objects: signing, decoding, and the envelope dispatcher.

`ProtoTx` is the wire-default transaction: cosmos TxRaw bytes
(body_bytes ‖ auth_info_bytes ‖ signature) with SIGN_MODE_DIRECT sign docs
(cosmos tx.proto SignDoc — body, auth info, chain id, account number), the
format `pkg/user/signer.go` produces and `app/encoding` decodes in the
reference. The legacy framework codec (chain/tx.py Tx) remains accepted on
decode for old fixtures; `decode_any_tx` sniffs the format.

Note the structural difference from the legacy codec: chain_id and
account_number are NOT in the tx bytes — they bind through the sign doc
only, so signature verification needs them from context (the ante handler
passes ctx.chain_id + the account record, exactly like the SDK's
SigVerificationDecorator).
"""

from __future__ import annotations

import dataclasses
import hashlib

from celestia_app_tpu.chain import tx as itx
from celestia_app_tpu.chain.crypto import PublicKey
from celestia_app_tpu.utils import telemetry
from celestia_app_tpu.wire import txpb
from celestia_app_tpu.wire.proto import Fields, decode_varint


@dataclasses.dataclass(frozen=True)
class ProtoTx:
    """Decoded cosmos TxRaw; duck-types chain/tx.py Tx for the protocol
    plane (.body/.pubkey/.signature/.encode()/.hash())."""

    raw: bytes  # original TxRaw bytes (canonical: re-emitted verbatim)
    body_bytes: bytes
    auth_info_bytes: bytes
    body: itx.TxBody  # chain_id="" / account_number=0: bound via sign doc
    pubkey: bytes
    signature: bytes

    wire_format = "proto"

    def encode(self) -> bytes:
        return self.raw

    def hash(self) -> bytes:
        return hashlib.sha256(self.raw).digest()

    def sign_doc(self, chain_id: str, account_number: int) -> bytes:
        return txpb.sign_doc_pb(
            self.body_bytes, self.auth_info_bytes, chain_id, account_number
        )

    def verify_signature(self, chain_id: str = "", account_number: int = 0) -> bool:
        try:
            return PublicKey(self.pubkey).verify(
                self.signature, self.sign_doc(chain_id, account_number)
            )
        except Exception:
            # undecodable pubkey/signature bytes verify False — counted,
            # so a flood of malformed txs is visible in /metrics
            telemetry.incr("wire.sig_verify_errors")
            return False


def sign_tx_proto(body: itx.TxBody, priv) -> ProtoTx:
    """Build + sign a protobuf tx from the internal TxBody description.

    body.chain_id/account_number go into the SIGN DOC (not the tx bytes);
    sequence/fee/gas/fee_granter go into AuthInfo; msgs/memo/timeout into
    TxBody — the exact SIGN_MODE_DIRECT construction of pkg/user/signer.go."""
    pub = priv.public_key().compressed
    body_bytes = txpb.tx_body_pb(body.msgs, body.memo, body.timeout_height)
    auth_bytes = txpb.auth_info_pb(
        pub, body.sequence, body.fee, body.gas_limit, body.fee_granter
    )
    doc = txpb.sign_doc_pb(
        body_bytes, auth_bytes, body.chain_id, body.account_number
    )
    sig = priv.sign(doc)
    raw = txpb.tx_raw_pb(body_bytes, auth_bytes, sig)
    return ProtoTx(
        raw=raw,
        body_bytes=body_bytes,
        auth_info_bytes=auth_bytes,
        body=body,
        pubkey=pub,
        signature=sig,
    )


def decode_proto_tx(raw: bytes) -> ProtoTx:
    """Strict TxRaw decode; raises ValueError on any structural problem."""
    f = Fields(raw)
    body_bytes = f.get_bytes(1)
    auth_bytes = f.get_bytes(2)
    sigs = f.repeated_bytes(3)
    if not body_bytes or not auth_bytes:
        raise ValueError("TxRaw missing body or auth info")
    if len(sigs) != 1 or not sigs[0]:
        raise ValueError(f"expected exactly 1 non-empty signature, got {len(sigs)}")

    bf = Fields(body_bytes)
    msgs = tuple(txpb.decode_msg_any(a) for a in bf.repeated_bytes(1))
    memo = bf.get_string(2)
    timeout_height = bf.get_int(3)

    af = Fields(auth_bytes)
    signer_infos = af.repeated_bytes(1)
    if len(signer_infos) != 1:
        raise ValueError(f"expected exactly 1 signer, got {len(signer_infos)}")
    sf = Fields(signer_infos[0])
    url, pk_value = txpb.parse_any(sf.get_bytes(1))
    if url != txpb.SECP256K1_PUBKEY_URL:
        raise ValueError(f"unsupported pubkey type {url!r}")
    pubkey = Fields(pk_value).get_bytes(1)
    sequence = sf.get_int(3)

    fee = 0
    gas_limit = 0
    fee_granter = b""
    if af.has(2):
        ff = Fields(af.get_bytes(2))
        for c in ff.repeated_bytes(1):
            denom, amount = txpb.parse_coin(c)
            if denom == txpb.BOND_DENOM:
                fee += amount
        gas_limit = ff.get_int(2)
        granter_str = ff.get_string(4)
        if granter_str:
            fee_granter = txpb._addr_bytes(granter_str)

    body = itx.TxBody(
        msgs=msgs,
        chain_id="",  # bound via the sign doc (see module docstring)
        account_number=0,
        sequence=sequence,
        fee=fee,
        gas_limit=gas_limit,
        memo=memo,
        timeout_height=timeout_height,
        fee_granter=fee_granter,
    )
    return ProtoTx(
        raw=raw,
        body_bytes=body_bytes,
        auth_info_bytes=auth_bytes,
        body=body,
        pubkey=pubkey,
        signature=sigs[0],
    )


def looks_like_proto_tx(raw: bytes) -> bool:
    """Cheap sniff: TxRaw must start with field-1 length-delimited (0x0a)
    whose length fits in the buffer, followed by field 2 — the legacy codec
    never produces that pair at those positions for real txs."""
    if not raw or raw[0] != 0x0A:
        return False
    try:
        n, off = decode_varint(raw, 1)
    except ValueError:
        return False
    off2 = off + n
    return off2 < len(raw) and raw[off2] == 0x12


def decode_any_tx(raw: bytes):
    """Wire dispatcher: protobuf TxRaw (default) or the legacy codec."""
    if looks_like_proto_tx(raw):
        try:
            return decode_proto_tx(raw)
        except ValueError:
            pass  # fall through: maybe a legacy tx that sniffed as proto
    return itx.Tx.decode(raw)
