"""Protobuf wire plane: byte-compatible encoding of the reference's tx
formats (proto/celestia/blob/v1/tx.proto, proto/celestia/core/v1/blob/
blob.proto, cosmos tx.proto) so reference clients/signers interoperate."""
