"""Protobuf codecs for the reference's tx types.

Byte-compatible with:
  - cosmos.tx.v1beta1 Tx/TxRaw/TxBody/AuthInfo/SignDoc (cosmos-sdk
    proto/cosmos/tx/v1beta1/tx.proto, used by app/encoding/encoding.go:26)
  - celestia.blob.v1.MsgPayForBlobs (/root/reference/proto/celestia/blob/v1/
    tx.proto:17-35; field 8 for share_versions is the reference's own quirk)
  - celestia.core.v1.blob Blob/BlobTx (/root/reference/proto/celestia/core/
    v1/blob/blob.proto) and the go-square BlobTx/IndexWrapper envelopes with
    type IDs "BLOB"/"INDX" (x/blob/types/blob_tx.go:37-108 decode semantics)
  - the cosmos std msgs celestia-app routes (bank, staking, gov v1beta1,
    authz, ibc transfer) and celestia's own signal/qgb msgs
    (/root/reference/proto/celestia/{signal,qgb}/v1/tx.proto)

Internal msgs (chain/tx.py dataclasses) carry 20-byte addresses; the wire
carries bech32 "celestia1..." strings — converted here at the boundary.
"""

from __future__ import annotations

import json

from celestia_app_tpu.chain import tx as itx
from celestia_app_tpu.wire import bech32
from celestia_app_tpu.wire.proto import (
    Fields,
    field_bytes,
    field_message,
    field_packed_uint,
    field_repeated_bytes,
    field_string,
    field_varint,
)

BOND_DENOM = "utia"
SIGN_MODE_DIRECT = 1

BLOB_TX_TYPE_ID = "BLOB"
INDEX_WRAPPER_TYPE_ID = "INDX"

SECP256K1_PUBKEY_URL = "/cosmos.crypto.secp256k1.PubKey"


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------


def any_pb(type_url: str, value: bytes) -> bytes:
    return field_string(1, type_url) + field_bytes(2, value)


def parse_any(raw: bytes) -> tuple[str, bytes]:
    f = Fields(raw)
    return f.get_string(1), f.get_bytes(2)


def coin_pb(denom: str, amount: int) -> bytes:
    return field_string(1, denom) + field_string(2, str(amount))


def parse_coin(raw: bytes) -> tuple[str, int]:
    f = Fields(raw)
    return f.get_string(1), int(f.get_string(2) or "0")


def _addr_str(addr20: bytes) -> str:
    return bech32.encode(addr20)


def _addr_bytes(s: str) -> bytes:
    if not s:
        return b""
    # accept exactly the two chain HRPs (valoper/account share the same 20
    # underlying bytes for operator keys); any foreign prefix — however
    # valid its checksum — is rejected at decode, as the reference's
    # sdk.AccAddressFromBech32 rejects non-celestia address strings
    pos = s.rfind("1")
    hrp = s[:pos] if pos > 0 else bech32.HRP_ACCOUNT
    if hrp not in (bech32.HRP_ACCOUNT, bech32.HRP_VALOPER):
        raise ValueError(f"unsupported bech32 prefix {hrp!r}")
    return bech32.decode(s, hrp)


# ---------------------------------------------------------------------------
# per-msg codecs: internal dataclass <-> (type_url, pb bytes)
# ---------------------------------------------------------------------------


def _enc_send(m: itx.MsgSend) -> bytes:
    return (
        field_string(1, _addr_str(m.from_addr))
        + field_string(2, _addr_str(m.to_addr))
        + field_message(3, coin_pb(BOND_DENOM, m.amount))
    )


def _dec_send(raw: bytes) -> itx.MsgSend:
    f = Fields(raw)
    coins = [parse_coin(c) for c in f.repeated_bytes(3)]
    amount = sum(a for d, a in coins if d == BOND_DENOM)
    return itx.MsgSend(
        _addr_bytes(f.get_string(1)), _addr_bytes(f.get_string(2)), amount
    )


def _enc_pfb(m: itx.MsgPayForBlobs) -> bytes:
    return (
        field_string(1, _addr_str(m.signer))
        + field_repeated_bytes(2, m.namespaces)
        + field_packed_uint(3, m.blob_sizes)
        + field_repeated_bytes(4, m.share_commitments)
        + field_packed_uint(8, m.share_versions)
    )


def _dec_pfb(raw: bytes) -> itx.MsgPayForBlobs:
    f = Fields(raw)
    return itx.MsgPayForBlobs(
        signer=_addr_bytes(f.get_string(1)),
        namespaces=tuple(f.repeated_bytes(2)),
        blob_sizes=tuple(f.repeated_uint(3)),
        share_commitments=tuple(f.repeated_bytes(4)),
        share_versions=tuple(f.repeated_uint(8)),
    )


def _enc_delegate(m: itx.MsgDelegate) -> bytes:
    return (
        field_string(1, _addr_str(m.delegator))
        + field_string(2, bech32.encode(m.validator, bech32.HRP_VALOPER))
        + field_message(3, coin_pb(BOND_DENOM, m.amount))
    )


def _dec_delegate(raw: bytes) -> itx.MsgDelegate:
    f = Fields(raw)
    _, amount = parse_coin(f.get_bytes(3)) if f.has(3) else (BOND_DENOM, 0)
    return itx.MsgDelegate(
        _addr_bytes(f.get_string(1)), _addr_bytes(f.get_string(2)), amount
    )


def _enc_undelegate(m: itx.MsgUndelegate) -> bytes:
    return (
        field_string(1, _addr_str(m.delegator))
        + field_string(2, bech32.encode(m.validator, bech32.HRP_VALOPER))
        + field_message(3, coin_pb(BOND_DENOM, m.amount))
    )


def _dec_undelegate(raw: bytes) -> itx.MsgUndelegate:
    f = Fields(raw)
    _, amount = parse_coin(f.get_bytes(3)) if f.has(3) else (BOND_DENOM, 0)
    return itx.MsgUndelegate(
        _addr_bytes(f.get_string(1)), _addr_bytes(f.get_string(2)), amount
    )


def _enc_redelegate(m: itx.MsgBeginRedelegate) -> bytes:
    return (
        field_string(1, _addr_str(m.delegator))
        + field_string(2, bech32.encode(m.src_validator, bech32.HRP_VALOPER))
        + field_string(3, bech32.encode(m.dst_validator, bech32.HRP_VALOPER))
        + field_message(4, coin_pb(BOND_DENOM, m.amount))
    )


def _dec_redelegate(raw: bytes) -> itx.MsgBeginRedelegate:
    f = Fields(raw)
    _, amount = parse_coin(f.get_bytes(4)) if f.has(4) else (BOND_DENOM, 0)
    return itx.MsgBeginRedelegate(
        _addr_bytes(f.get_string(1)),
        _addr_bytes(f.get_string(2)),
        _addr_bytes(f.get_string(3)),
        amount,
    )


_SECP256K1_PUBKEY_URL = "/cosmos.crypto.secp256k1.PubKey"


def _enc_create_validator(m: itx.MsgCreateValidator) -> bytes:
    # subset of cosmos.staking.v1beta1.MsgCreateValidator: the internal model
    # has no description/commission split — operator key == account key.
    # Field 6 is the consensus pubkey as google.protobuf.Any wrapping
    # cosmos.crypto.secp256k1.PubKey{key=1}, the reference's Pubkey field
    # (what lets a runtime validator's votes verify — chain/reactor.py).
    out = field_string(5, bech32.encode(m.operator, bech32.HRP_VALOPER))
    if m.pubkey:  # ascending field order, as the canonical runtime emits
        any_pb = (
            field_string(1, _SECP256K1_PUBKEY_URL)
            + field_message(2, field_bytes(1, m.pubkey))
        )
        out += field_message(6, any_pb)
    out += field_message(7, coin_pb(BOND_DENOM, m.self_stake))
    return out


def _dec_create_validator(raw: bytes) -> itx.MsgCreateValidator:
    f = Fields(raw)
    _, stake = parse_coin(f.get_bytes(7)) if f.has(7) else (BOND_DENOM, 0)
    pubkey = b""
    if f.has(6):
        any_f = Fields(f.get_bytes(6))
        url = any_f.get_string(1)
        if url != _SECP256K1_PUBKEY_URL:
            # reject loudly: silently dropping the key would create a
            # validator that counts in power totals but can never vote
            raise ValueError(
                f"unsupported consensus pubkey type {url!r} "
                f"(only {_SECP256K1_PUBKEY_URL})"
            )
        pubkey = Fields(any_f.get_bytes(2)).get_bytes(1)
    return itx.MsgCreateValidator(
        _addr_bytes(f.get_string(5)), stake, pubkey
    )


_VOTE_OPTIONS = {"yes": 1, "abstain": 2, "no": 3, "veto": 4}
_VOTE_NAMES = {v: k for k, v in _VOTE_OPTIONS.items()}


def _enc_vote(m: itx.MsgVote) -> bytes:
    return (
        field_varint(1, m.proposal_id)
        + field_string(2, _addr_str(m.voter))
        + field_varint(3, _VOTE_OPTIONS.get(m.option, 0))
    )


def _dec_vote(raw: bytes) -> itx.MsgVote:
    f = Fields(raw)
    return itx.MsgVote(
        _addr_bytes(f.get_string(2)),
        f.get_int(1),
        _VOTE_NAMES.get(f.get_int(3), "unknown"),
    )


def _enc_deposit(m: itx.MsgDeposit) -> bytes:
    return (
        field_varint(1, m.proposal_id)
        + field_string(2, _addr_str(m.depositor))
        + field_message(3, coin_pb(BOND_DENOM, m.amount))
    )


def _dec_deposit(raw: bytes) -> itx.MsgDeposit:
    f = Fields(raw)
    coins = [parse_coin(c) for c in f.repeated_bytes(3)]
    amount = sum(a for d, a in coins if d == BOND_DENOM)
    return itx.MsgDeposit(_addr_bytes(f.get_string(2)), f.get_int(1), amount)


PARAM_CHANGE_PROPOSAL_URL = "/cosmos.params.v1beta1.ParameterChangeProposal"


_RAW_CHANGES_FIELD = 15  # framework extension: malformed payloads round-trip
# so the SERVER rejects them in DeliverTx (consensus-safe failure), instead
# of the client crashing at encode time


def _enc_submit_proposal(m: itx.MsgSubmitProposal) -> bytes:
    body = field_string(1, m.title)
    try:
        changes = json.loads(m.changes_json)
        if not isinstance(changes, list):
            raise ValueError("changes must be a list")
        parts = []
        for c in changes:
            subspace, _, key = c["param"].partition("/")
            parts.append(
                field_string(1, subspace)
                + field_string(2, key)
                + field_string(3, json.dumps(c["value"], sort_keys=True))
            )
        for p in parts:
            body += field_message(3, p, emit_default=True)
    except (ValueError, TypeError, AttributeError, KeyError):
        body += field_bytes(_RAW_CHANGES_FIELD, bytes(m.changes_json))
    content = any_pb(PARAM_CHANGE_PROPOSAL_URL, body)
    return (
        field_message(1, content)
        + field_message(2, coin_pb(BOND_DENOM, m.initial_deposit))
        + field_string(3, _addr_str(m.proposer))
    )


def _dec_submit_proposal(raw: bytes) -> itx.MsgSubmitProposal:
    f = Fields(raw)
    url, content = parse_any(f.get_bytes(1))
    if url != PARAM_CHANGE_PROPOSAL_URL:
        raise ValueError(f"unsupported proposal content {url!r}")
    cf = Fields(content)
    title = cf.get_string(1)
    if cf.has(_RAW_CHANGES_FIELD):
        changes_json = cf.get_bytes(_RAW_CHANGES_FIELD)
    else:
        changes = []
        for c in cf.repeated_bytes(3):
            ch = Fields(c)
            changes.append(
                {
                    "param": f"{ch.get_string(1)}/{ch.get_string(2)}",
                    "value": json.loads(ch.get_string(3)),
                }
            )
        changes_json = json.dumps(changes, sort_keys=True).encode()
    coins = [parse_coin(c) for c in f.repeated_bytes(2)]
    deposit = sum(a for d, a in coins if d == BOND_DENOM)
    return itx.MsgSubmitProposal(
        proposer=_addr_bytes(f.get_string(3)),
        changes_json=changes_json,
        initial_deposit=deposit,
        title=title,
    )


def _enc_signal(m: itx.MsgSignalVersion) -> bytes:
    return (
        field_string(1, bech32.encode(m.validator, bech32.HRP_VALOPER))
        + field_varint(2, m.version)
    )


def _dec_signal(raw: bytes) -> itx.MsgSignalVersion:
    f = Fields(raw)
    return itx.MsgSignalVersion(_addr_bytes(f.get_string(1)), f.get_int(2))


def _enc_try_upgrade(m: itx.MsgTryUpgrade) -> bytes:
    return field_string(1, _addr_str(m.signer))


def _dec_try_upgrade(raw: bytes) -> itx.MsgTryUpgrade:
    return itx.MsgTryUpgrade(_addr_bytes(Fields(raw).get_string(1)))


def _enc_register_evm(m: itx.MsgRegisterEVMAddress) -> bytes:
    return (
        field_string(1, bech32.encode(m.validator, bech32.HRP_VALOPER))
        + field_string(2, "0x" + m.evm_address.hex())
    )


def _dec_register_evm(raw: bytes) -> itx.MsgRegisterEVMAddress:
    f = Fields(raw)
    evm = f.get_string(2)
    return itx.MsgRegisterEVMAddress(
        _addr_bytes(f.get_string(1)),
        bytes.fromhex(evm[2:] if evm.startswith("0x") else evm),
    )


def _enc_exec(m: itx.MsgExec) -> bytes:
    out = field_string(1, _addr_str(m.grantee))
    for inner in m.inner:
        out += field_message(2, encode_msg_any(inner), emit_default=True)
    return out


def _dec_exec(raw: bytes) -> itx.MsgExec:
    f = Fields(raw)
    inner = tuple(decode_msg_any(a) for a in f.repeated_bytes(2))
    return itx.MsgExec(_addr_bytes(f.get_string(1)), inner)


def _enc_transfer(m: itx.MsgTransfer) -> bytes:
    out = (
        field_string(1, "transfer")
        + field_string(2, m.source_channel)
        + field_message(3, coin_pb(m.denom, m.amount))
        + field_string(4, _addr_str(m.sender))
        + field_string(5, m.receiver)
    )
    if m.timeout_height:
        # ibc.core.client.v1.Height{revision_number=1, revision_height=2}
        out += field_message(6, field_varint(2, m.timeout_height))
    return out


def _dec_transfer(raw: bytes) -> itx.MsgTransfer:
    f = Fields(raw)
    denom, amount = parse_coin(f.get_bytes(3)) if f.has(3) else (BOND_DENOM, 0)
    timeout = Fields(f.get_bytes(6)).get_int(2) if f.has(6) else 0
    return itx.MsgTransfer(
        sender=_addr_bytes(f.get_string(4)),
        source_channel=f.get_string(2),
        receiver=f.get_string(5),
        denom=denom,
        amount=amount,
        timeout_height=timeout,
    )


def _enc_update_client(m: itx.MsgUpdateClient) -> bytes:
    return (
        field_string(1, m.client_id)
        + field_varint(2, m.height)
        + field_bytes(3, m.root)
        + field_bytes(4, m.header_json)
        + field_bytes(5, m.cert_json)
        + field_bytes(6, m.valset_json)
        + field_string(7, _addr_str(m.relayer))
    )


def _dec_update_client(raw: bytes) -> itx.MsgUpdateClient:
    f = Fields(raw)
    return itx.MsgUpdateClient(
        _addr_bytes(f.get_string(7)), f.get_string(1), f.get_int(2),
        f.get_bytes(3), f.get_bytes(4), f.get_bytes(5), f.get_bytes(6),
    )


def _enc_recv_packet(m: itx.MsgRecvPacket) -> bytes:
    return (
        field_bytes(1, m.packet_json)
        + field_bytes(2, m.proof_json)
        + field_varint(3, m.proof_height)
        + field_string(4, _addr_str(m.relayer))
    )


def _dec_recv_packet(raw: bytes) -> itx.MsgRecvPacket:
    f = Fields(raw)
    return itx.MsgRecvPacket(
        _addr_bytes(f.get_string(4)), f.get_bytes(1), f.get_bytes(2),
        f.get_int(3),
    )


def _enc_ack_packet(m: itx.MsgAcknowledgePacket) -> bytes:
    return (
        field_bytes(1, m.packet_json)
        + field_bytes(2, m.ack_json)
        + field_bytes(3, m.proof_json)
        + field_varint(4, m.proof_height)
        + field_string(5, _addr_str(m.relayer))
    )


def _dec_ack_packet(raw: bytes) -> itx.MsgAcknowledgePacket:
    f = Fields(raw)
    return itx.MsgAcknowledgePacket(
        _addr_bytes(f.get_string(5)), f.get_bytes(1), f.get_bytes(2),
        f.get_bytes(3), f.get_int(4),
    )


def _enc_timeout_packet(m: itx.MsgTimeoutPacket) -> bytes:
    return (
        field_bytes(1, m.packet_json)
        + field_bytes(2, m.proof_json)
        + field_varint(3, m.proof_height)
        + field_string(4, _addr_str(m.relayer))
    )


def _dec_timeout_packet(raw: bytes) -> itx.MsgTimeoutPacket:
    f = Fields(raw)
    return itx.MsgTimeoutPacket(
        _addr_bytes(f.get_string(4)), f.get_bytes(1), f.get_bytes(2),
        f.get_int(3),
    )


# type_url -> (internal class, encoder, decoder)
MSG_CODECS = {
    "/cosmos.bank.v1beta1.MsgSend": (itx.MsgSend, _enc_send, _dec_send),
    "/celestia.blob.v1.MsgPayForBlobs": (itx.MsgPayForBlobs, _enc_pfb, _dec_pfb),
    "/cosmos.staking.v1beta1.MsgDelegate": (
        itx.MsgDelegate, _enc_delegate, _dec_delegate),
    "/cosmos.staking.v1beta1.MsgUndelegate": (
        itx.MsgUndelegate, _enc_undelegate, _dec_undelegate),
    "/cosmos.staking.v1beta1.MsgBeginRedelegate": (
        itx.MsgBeginRedelegate, _enc_redelegate, _dec_redelegate),
    "/cosmos.staking.v1beta1.MsgCreateValidator": (
        itx.MsgCreateValidator, _enc_create_validator, _dec_create_validator),
    "/cosmos.gov.v1beta1.MsgVote": (itx.MsgVote, _enc_vote, _dec_vote),
    "/cosmos.gov.v1beta1.MsgDeposit": (itx.MsgDeposit, _enc_deposit, _dec_deposit),
    "/cosmos.gov.v1beta1.MsgSubmitProposal": (
        itx.MsgSubmitProposal, _enc_submit_proposal, _dec_submit_proposal),
    "/celestia.signal.v1.MsgSignalVersion": (
        itx.MsgSignalVersion, _enc_signal, _dec_signal),
    "/celestia.signal.v1.MsgTryUpgrade": (
        itx.MsgTryUpgrade, _enc_try_upgrade, _dec_try_upgrade),
    "/celestia.qgb.v1.MsgRegisterEVMAddress": (
        itx.MsgRegisterEVMAddress, _enc_register_evm, _dec_register_evm),
    "/cosmos.authz.v1beta1.MsgExec": (itx.MsgExec, _enc_exec, _dec_exec),
    "/ibc.applications.transfer.v1.MsgTransfer": (
        itx.MsgTransfer, _enc_transfer, _dec_transfer),
    # relay envelopes: consensus-routed packet application. The packet/
    # proof payloads are the FRAMEWORK's canonical-JSON forms (chain/ibc.py)
    # — deliberately framework-scoped type URLs, not ibc-go's (whose Packet
    # proto this framework does not carry on the wire).
    "/celestia_tpu.ibc.MsgRecvPacket": (
        itx.MsgRecvPacket, _enc_recv_packet, _dec_recv_packet),
    "/celestia_tpu.ibc.MsgAcknowledgePacket": (
        itx.MsgAcknowledgePacket, _enc_ack_packet, _dec_ack_packet),
    "/celestia_tpu.ibc.MsgUpdateClient": (
        itx.MsgUpdateClient, _enc_update_client, _dec_update_client),
    "/celestia_tpu.ibc.MsgTimeoutPacket": (
        itx.MsgTimeoutPacket, _enc_timeout_packet, _dec_timeout_packet),
}

_URL_BY_CLASS = {cls: url for url, (cls, _e, _d) in MSG_CODECS.items()}


def encode_msg_any(msg) -> bytes:
    """Internal msg dataclass -> google.protobuf.Any bytes."""
    url = _URL_BY_CLASS.get(type(msg))
    if url is None:
        raise ValueError(f"no protobuf codec for {type(msg).__name__}")
    _cls, enc, _dec = MSG_CODECS[url]
    return any_pb(url, enc(msg))


def decode_msg_any(raw: bytes):
    url, value = parse_any(raw)
    entry = MSG_CODECS.get(url)
    if entry is None:
        raise ValueError(f"unknown msg type_url {url!r}")
    _cls, _enc, dec = entry
    return dec(value)


# ---------------------------------------------------------------------------
# Tx envelope: TxBody / AuthInfo / TxRaw / SignDoc
# ---------------------------------------------------------------------------


def tx_body_pb(msgs, memo: str = "", timeout_height: int = 0) -> bytes:
    out = b""
    for m in msgs:
        out += field_message(1, encode_msg_any(m), emit_default=True)
    out += field_string(2, memo)
    out += field_varint(3, timeout_height)
    return out


def auth_info_pb(
    pubkey33: bytes, sequence: int, fee: int, gas_limit: int,
    fee_granter20: bytes = b"", fee_payer20: bytes = b"",
) -> bytes:
    signer_info = (
        field_message(
            1, any_pb(SECP256K1_PUBKEY_URL, field_bytes(1, pubkey33)),
            emit_default=True,
        )
        + field_message(2, field_message(1, field_varint(1, SIGN_MODE_DIRECT)),
                        emit_default=True)
        + field_varint(3, sequence)
    )
    fee_pb = field_message(1, coin_pb(BOND_DENOM, fee)) + field_varint(2, gas_limit)
    if fee_payer20:
        fee_pb += field_string(3, _addr_str(fee_payer20))
    if fee_granter20:
        fee_pb += field_string(4, _addr_str(fee_granter20))
    return (
        field_message(1, signer_info, emit_default=True)
        + field_message(2, fee_pb, emit_default=True)
    )


def tx_raw_pb(body_bytes: bytes, auth_info_bytes: bytes, signature: bytes) -> bytes:
    return (
        field_bytes(1, body_bytes)
        + field_bytes(2, auth_info_bytes)
        + field_bytes(3, signature, emit_default=True)
    )


def sign_doc_pb(
    body_bytes: bytes, auth_info_bytes: bytes, chain_id: str, account_number: int
) -> bytes:
    return (
        field_bytes(1, body_bytes)
        + field_bytes(2, auth_info_bytes)
        + field_string(3, chain_id)
        + field_varint(4, account_number)
    )


# ---------------------------------------------------------------------------
# BlobTx / IndexWrapper envelopes (go-square blob package wire format)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# cosmos.tx.v1beta1.Service messages (the gRPC:9090 surface TxClient talks
# to — pkg/user/tx_client.go:320-330 BroadcastTx/Simulate)
# ---------------------------------------------------------------------------

BROADCAST_MODE_SYNC = 2


def broadcast_tx_request_pb(tx_bytes: bytes, mode: int = BROADCAST_MODE_SYNC) -> bytes:
    return field_bytes(1, tx_bytes) + field_varint(2, mode)


def parse_broadcast_tx_request(raw: bytes) -> tuple[bytes, int]:
    f = Fields(raw)
    return f.get_bytes(1), f.get_int(2)


def tx_response_pb(
    height: int, txhash: str, code: int, raw_log: str,
    gas_wanted: int, gas_used: int,
) -> bytes:
    """cosmos.base.abci.v1beta1.TxResponse (the fields clients read)."""
    return (
        field_varint(1, height)
        + field_string(2, txhash)
        + field_varint(4, code)
        + field_string(6, raw_log)
        + field_varint(9, gas_wanted)
        + field_varint(10, gas_used)
    )


def parse_tx_response(raw: bytes) -> dict:
    f = Fields(raw)
    return {
        "height": f.get_int(1),
        "txhash": f.get_string(2),
        "code": f.get_int(4),
        "raw_log": f.get_string(6),
        "gas_wanted": f.get_int(9),
        "gas_used": f.get_int(10),
    }


def broadcast_tx_response_pb(tx_response: bytes) -> bytes:
    return field_message(1, tx_response, emit_default=True)


def parse_broadcast_tx_response(raw: bytes) -> dict:
    return parse_tx_response(Fields(raw).get_bytes(1))


def simulate_request_pb(tx_bytes: bytes) -> bytes:
    return field_bytes(2, tx_bytes)  # field 1 (Tx) is deprecated upstream


def parse_simulate_request(raw: bytes) -> bytes:
    return Fields(raw).get_bytes(2)


def simulate_response_pb(gas_wanted: int, gas_used: int) -> bytes:
    gas_info = field_varint(1, gas_wanted) + field_varint(2, gas_used)
    return field_message(1, gas_info, emit_default=True)


def parse_simulate_response(raw: bytes) -> dict:
    g = Fields(Fields(raw).get_bytes(1))
    return {"gas_wanted": g.get_int(1), "gas_used": g.get_int(2)}


def get_tx_request_pb(txhash: str) -> bytes:
    return field_string(1, txhash)


def parse_get_tx_request(raw: bytes) -> str:
    return Fields(raw).get_string(1)


def get_tx_response_pb(tx_response: bytes) -> bytes:
    return field_message(2, tx_response, emit_default=True)


def parse_get_tx_response(raw: bytes) -> dict:
    return parse_tx_response(Fields(raw).get_bytes(2))


def blob_pb(namespace29: bytes, data: bytes, share_version: int) -> bytes:
    """celestia.core.v1.blob.Blob: split 29-byte raw namespace into
    version byte (field 4) + 28-byte id (field 1)."""
    return (
        field_bytes(1, namespace29[1:])
        + field_bytes(2, data)
        + field_varint(3, share_version)
        + field_varint(4, namespace29[0])
    )


def parse_blob(raw: bytes) -> tuple[bytes, bytes, int]:
    """-> (namespace29, data, share_version)"""
    f = Fields(raw)
    ns_id = f.get_bytes(1)
    if len(ns_id) != 28:
        raise ValueError(f"namespace id must be 28 bytes, got {len(ns_id)}")
    version = f.get_int(4)
    return bytes([version]) + ns_id, f.get_bytes(2), f.get_int(3)


def blob_tx_pb(tx: bytes, blobs) -> bytes:
    """blobs: iterable of (namespace29, data, share_version)."""
    out = field_bytes(1, tx)
    for ns, data, ver in blobs:
        out += field_message(2, blob_pb(ns, data, ver), emit_default=True)
    out += field_string(3, BLOB_TX_TYPE_ID)
    return out


def parse_blob_tx(raw: bytes) -> tuple[bytes, list[tuple[bytes, bytes, int]]]:
    f = Fields(raw)
    if f.get_string(3) != BLOB_TX_TYPE_ID:
        raise ValueError("not a protobuf BlobTx (bad type_id)")
    return f.get_bytes(1), [parse_blob(b) for b in f.repeated_bytes(2)]


def index_wrapper_pb(tx: bytes, share_indexes) -> bytes:
    return (
        field_bytes(1, tx)
        + field_packed_uint(2, share_indexes)
        + field_string(3, INDEX_WRAPPER_TYPE_ID)
    )


def parse_index_wrapper(raw: bytes) -> tuple[bytes, list[int]]:
    f = Fields(raw)
    if f.get_string(3) != INDEX_WRAPPER_TYPE_ID:
        raise ValueError("not a protobuf IndexWrapper (bad type_id)")
    return f.get_bytes(1), f.repeated_uint(2)


# ---------------------------------------------------------------------------
# gRPC query services for the client bootstrap surface: the reference's
# SetupTxClient populates chain-id / account number / sequence / min gas
# price over exactly these five RPCs (pkg/user/tx_client.go:147-198,
# account.go:59-80, tx_client.go:561-610) before a single tx is signed.
# Hand-rolled wire messages, same style as the tx service above.
# ---------------------------------------------------------------------------

BASE_ACCOUNT_TYPE_URL = "/cosmos.auth.v1beta1.BaseAccount"
DEC_SCALE = 10**18  # cosmos sdk.Dec wire form: value*10^18 as an integer str


def dec_pb_str(value: float) -> str:
    return str(int(round(value * DEC_SCALE)))


def parse_dec_str(s: str) -> float:
    return int(s) / DEC_SCALE if s else 0.0


# -- cosmos.auth.v1beta1.Query/Account --------------------------------------


def query_account_request_pb(address: str) -> bytes:
    return field_string(1, address)


def parse_query_account_request(raw: bytes) -> str:
    return Fields(raw).get_string(1)


def base_account_pb(
    address: str, pubkey33: bytes | None, account_number: int, sequence: int
) -> bytes:
    out = field_string(1, address)
    if pubkey33:
        out += field_message(
            2, any_pb("/cosmos.crypto.secp256k1.PubKey", field_bytes(1, pubkey33))
        )
    out += field_varint(3, account_number) + field_varint(4, sequence)
    return out


def query_account_response_pb(base_account: bytes) -> bytes:
    return field_message(
        1, any_pb(BASE_ACCOUNT_TYPE_URL, base_account), emit_default=True
    )


def parse_query_account_response(raw: bytes) -> dict:
    """-> {address, account_number, sequence, pubkey?} (the fields
    QueryAccount unpacks from the Any, account.go:72-79)."""
    url, value = parse_any(Fields(raw).get_bytes(1))
    if url != BASE_ACCOUNT_TYPE_URL:
        raise ValueError(f"unexpected account type {url!r}")
    f = Fields(value)
    out = {
        "address": f.get_string(1),
        "account_number": f.get_int(3),
        "sequence": f.get_int(4),
    }
    any_raw = f.get_bytes(2)
    if any_raw:
        _, pk_value = parse_any(any_raw)
        out["pubkey"] = Fields(pk_value).get_bytes(1)
    return out


# -- cosmos.bank.v1beta1.Query/Balance --------------------------------------


def query_balance_request_pb(address: str, denom: str) -> bytes:
    return field_string(1, address) + field_string(2, denom)


def parse_query_balance_request(raw: bytes) -> tuple[str, str]:
    f = Fields(raw)
    return f.get_string(1), f.get_string(2)


def query_balance_response_pb(denom: str, amount: int) -> bytes:
    return field_message(1, coin_pb(denom, amount), emit_default=True)


def parse_query_balance_response(raw: bytes) -> tuple[str, int]:
    return parse_coin(Fields(raw).get_bytes(1))


# -- cosmos.base.tendermint.v1beta1.Service/GetLatestBlock -------------------
# SetupTxClient reads SdkBlock.Header.{ChainID, Version.App}
# (tx_client.go:154-162); Height rides along for status-style callers.


def get_latest_block_response_pb(
    chain_id: str, height: int, app_version: int
) -> bytes:
    header = (
        field_message(1, field_varint(2, app_version))  # Consensus.app
        + field_string(2, chain_id)
        + field_varint(3, height)
    )
    sdk_block = field_message(1, header)
    return field_message(3, sdk_block, emit_default=True)


def parse_get_latest_block_response(raw: bytes) -> dict:
    header = Fields(Fields(Fields(raw).get_bytes(3)).get_bytes(1))
    version = Fields(header.get_bytes(1))
    return {
        "chain_id": header.get_string(2),
        "height": header.get_int(3),
        "app_version": version.get_int(2),
    }


# -- cosmos.base.node.v1beta1.Service/Config ---------------------------------
# local (operator-set) min gas price as a DecCoins string, e.g. "0.002utia"
# (tx_client.go:564-573 parses it with ParseDecCoins)


def node_config_response_pb(minimum_gas_price: str) -> bytes:
    return field_string(1, minimum_gas_price)


def parse_node_config_response(raw: bytes) -> str:
    return Fields(raw).get_string(1)


# -- cosmos.params.v1beta1.Query/Params (subspace queries) -------------------
# QueryNetworkMinGasPrice falls back through this generic params route with
# subspace "minfee" (tx_client.go:593-610); the param VALUE is the JSON
# encoding of the param (a quoted decimal string for the min gas price).


def query_subspace_params_request_pb(subspace: str, key: str) -> bytes:
    return field_string(1, subspace) + field_string(2, key)


def parse_query_subspace_params_request(raw: bytes) -> tuple[str, str]:
    f = Fields(raw)
    return f.get_string(1), f.get_string(2)


def query_subspace_params_response_pb(subspace: str, key: str, value: str) -> bytes:
    change = (
        field_string(1, subspace) + field_string(2, key) + field_string(3, value)
    )
    return field_message(1, change, emit_default=True)


def parse_query_subspace_params_response(raw: bytes) -> dict:
    f = Fields(Fields(raw).get_bytes(1))
    return {
        "subspace": f.get_string(1),
        "key": f.get_string(2),
        "value": f.get_string(3),
    }


# -- celestia.blob.v1.Query/Params -------------------------------------------


def blob_params_response_pb(gas_per_blob_byte: int, gov_max_square_size: int) -> bytes:
    params = field_varint(1, gas_per_blob_byte) + field_varint(2, gov_max_square_size)
    return field_message(1, params, emit_default=True)


def parse_blob_params_response(raw: bytes) -> dict:
    f = Fields(Fields(raw).get_bytes(1))
    return {
        "gas_per_blob_byte": f.get_int(1),
        "gov_max_square_size": f.get_int(2),
    }


# -- celestia.minfee.v1.Query/NetworkMinGasPrice -----------------------------
# response field 1 is a cosmos.Dec (proto/celestia/minfee/v1/query.proto:23)


def minfee_response_pb(network_min_gas_price: float) -> bytes:
    return field_string(1, dec_pb_str(network_min_gas_price))


def parse_minfee_response(raw: bytes) -> float:
    return parse_dec_str(Fields(raw).get_string(1))


# -- cosmos.staking.v1beta1.Query (Validator / Validators) -------------------
# Subset of the reference's Validator message the client surfaces actually
# read: operator_address(1), jailed(3), status(4; 3 = BOND_STATUS_BONDED),
# tokens(5, integer string).


def parse_query_validator_request(raw: bytes) -> str:
    return Fields(raw).get_string(1)


def validator_pb(operator: bytes, jailed: bool, bonded: bool,
                 tokens: int) -> bytes:
    return (
        field_string(1, bech32.encode(operator, bech32.HRP_VALOPER))
        + field_varint(3, 1 if jailed else 0)
        + field_varint(4, 3 if bonded else 1, emit_default=True)
        + field_string(5, str(tokens))
    )


def query_validator_response_pb(validator: bytes) -> bytes:
    return field_message(1, validator, emit_default=True)


def parse_validator(raw: bytes) -> dict:
    f = Fields(raw)
    return {
        "operator_address": f.get_string(1),
        "jailed": bool(f.get_int(3)),
        "bonded": f.get_int(4) == 3,
        "tokens": int(f.get_string(5) or "0"),
    }


def parse_query_validator_response(raw: bytes) -> dict:
    return parse_validator(Fields(raw).get_bytes(1))


def query_validators_response_pb(validators: list[bytes]) -> bytes:
    return b"".join(field_message(1, v, emit_default=True)
                    for v in validators)


def parse_query_validators_response(raw: bytes) -> list[dict]:
    return [parse_validator(v) for v in Fields(raw).repeated_bytes(1)]


# -- cosmos.gov.v1beta1.Query (Proposal) -------------------------------------
# Subset: proposal_id(1), status(3) with the SDK ProposalStatus codes
# (1 deposit, 2 voting, 3 passed, 4 rejected, 5 failed), mapped from the
# keeper's status strings (chain/gov.py).

_GOV_STATUS_CODES = {
    "deposit_period": 1,
    "voting_period": 2,
    "passed": 3,
    "rejected_deposit": 4,  # both rejection flavors share the SDK code;
    "rejected": 4,          # "rejected" (later entry) names code 4 on decode
    "failed": 5,
}
_GOV_STATUS_NAMES = {v: k for k, v in _GOV_STATUS_CODES.items()}


def parse_query_proposal_request(raw: bytes) -> int:
    return Fields(raw).get_int(1)


def query_proposal_response_pb(pid: int, status: str) -> bytes:
    body = (
        field_varint(1, pid, emit_default=True)
        + field_varint(3, _GOV_STATUS_CODES.get(status, 0),
                       emit_default=True)
    )
    return field_message(1, body, emit_default=True)


def parse_query_proposal_response(raw: bytes) -> tuple[int, str]:
    f = Fields(Fields(raw).get_bytes(1))
    return f.get_int(1), _GOV_STATUS_NAMES.get(f.get_int(3), "unknown")
