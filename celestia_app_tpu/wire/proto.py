"""Minimal protobuf (proto3) wire-format primitives.

Hand-rolled instead of a generated stack: the message set is small, the
container has no protoc-python runtime guarantees, and — critically — the
encoder must be canonical: fields emitted in ascending field-number order,
default values omitted, repeated scalars packed. That matches what gogoproto
`Marshal` produces for the reference's types (celestia-app's generated
*.pb.go), so byte vectors pin compatibility.

Wire types: 0 = varint, 1 = 64-bit, 2 = length-delimited, 5 = 32-bit.
"""

from __future__ import annotations


def encode_varint(v: int) -> bytes:
    if v < 0:
        # proto3 negative int32/int64 are 10-byte two's-complement varints
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(raw: bytes, off: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if off >= len(raw):
            raise ValueError("truncated varint")
        b = raw[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, off
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def tag(field: int, wire_type: int) -> bytes:
    return encode_varint((field << 3) | wire_type)


def field_varint(field: int, v: int, *, emit_default: bool = False) -> bytes:
    """Varint field; proto3 omits zero values."""
    if v == 0 and not emit_default:
        return b""
    return tag(field, 0) + encode_varint(v)


def field_bytes(field: int, data: bytes, *, emit_default: bool = False) -> bytes:
    if not data and not emit_default:
        return b""
    return tag(field, 2) + encode_varint(len(data)) + data


def field_string(field: int, s: str, *, emit_default: bool = False) -> bytes:
    return field_bytes(field, s.encode(), emit_default=emit_default)


def field_message(field: int, data: bytes, *, emit_default: bool = False) -> bytes:
    """Nested message: emitted even when empty only if emit_default (proto3
    distinguishes unset from empty for message fields; gogoproto emits set
    submessages regardless of content)."""
    if not data and not emit_default:
        return b""
    return tag(field, 2) + encode_varint(len(data)) + data


def field_packed_uint(field: int, values) -> bytes:
    """repeated uint32/uint64 — packed (proto3 default)."""
    values = list(values)
    if not values:
        return b""
    payload = b"".join(encode_varint(v) for v in values)
    return tag(field, 2) + encode_varint(len(payload)) + payload


def field_repeated_bytes(field: int, items) -> bytes:
    return b"".join(field_bytes(field, it, emit_default=True) for it in items)


class Fields:
    """Parsed view of one message level: field number -> list of raw values.

    Varint fields parse to int; length-delimited to bytes; 32/64-bit to raw
    little-endian bytes. Unknown fields are preserved (kept in order) so a
    decode-reencode of a message we fully model is byte-identical."""

    def __init__(self, raw: bytes):
        self.order: list[tuple[int, int, object]] = []  # (field, wt, value)
        by_field: dict[int, list] = {}
        off = 0
        while off < len(raw):
            key, off = decode_varint(raw, off)
            field, wt = key >> 3, key & 7
            if wt == 0:
                v, off = decode_varint(raw, off)
            elif wt == 2:
                n, off2 = decode_varint(raw, off)
                v = raw[off2 : off2 + n]
                if len(v) != n:
                    raise ValueError("truncated length-delimited field")
                off = off2 + n
            elif wt == 5:
                v = raw[off : off + 4]
                if len(v) != 4:
                    raise ValueError("truncated fixed32")
                off += 4
            elif wt == 1:
                v = raw[off : off + 8]
                if len(v) != 8:
                    raise ValueError("truncated fixed64")
                off += 8
            else:
                raise ValueError(f"unsupported wire type {wt}")
            self.order.append((field, wt, v))
            by_field.setdefault(field, []).append(v)
        self._by_field = by_field

    def get_int(self, field: int, default: int = 0) -> int:
        vs = self._by_field.get(field)
        if not vs:
            return default
        v = vs[-1]
        if not isinstance(v, int):
            raise ValueError(f"field {field} is not a varint")
        return v

    def get_bytes(self, field: int, default: bytes = b"") -> bytes:
        vs = self._by_field.get(field)
        if not vs:
            return default
        v = vs[-1]
        if not isinstance(v, bytes):
            raise ValueError(f"field {field} is not length-delimited")
        return v

    def get_string(self, field: int, default: str = "") -> str:
        return self.get_bytes(field, default.encode()).decode()

    def repeated_bytes(self, field: int) -> list[bytes]:
        return [v for v in self._by_field.get(field, []) if isinstance(v, bytes)]

    def repeated_uint(self, field: int) -> list[int]:
        """Packed or unpacked repeated varints (decoders must accept both)."""
        out: list[int] = []
        for v in self._by_field.get(field, []):
            if isinstance(v, int):
                out.append(v)
            else:
                off = 0
                while off < len(v):
                    x, off = decode_varint(v, off)
                    out.append(x)
        return out

    def has(self, field: int) -> bool:
        return field in self._by_field
