"""Bech32 (BIP-173) address encoding with Celestia's HRPs.

The reference's protobuf messages carry bech32 STRINGS for addresses
(e.g. MsgPayForBlobs.signer, proto/celestia/blob/v1/tx.proto:20), derived
from 20-byte account bytes with HRP "celestia" (cosmos-sdk bech32 config).
"""

from __future__ import annotations

CHARSET = "qpzry9x8gf2tvdw0s3jn54khce6mua7l"
HRP_ACCOUNT = "celestia"
HRP_VALOPER = "celestiavaloper"

_GEN = (0x3B6A57B2, 0x26508E6D, 0x1EA119FA, 0x3D4233DD, 0x2A1462B3)


def _polymod(values) -> int:
    chk = 1
    for v in values:
        top = chk >> 25
        chk = (chk & 0x1FFFFFF) << 5 ^ v
        for i in range(5):
            if (top >> i) & 1:
                chk ^= _GEN[i]
    return chk


def _hrp_expand(hrp: str) -> list[int]:
    return [ord(c) >> 5 for c in hrp] + [0] + [ord(c) & 31 for c in hrp]


def _create_checksum(hrp: str, data: list[int]) -> list[int]:
    values = _hrp_expand(hrp) + data
    polymod = _polymod(values + [0, 0, 0, 0, 0, 0]) ^ 1
    return [(polymod >> 5 * (5 - i)) & 31 for i in range(6)]


def _convertbits(data, frombits: int, tobits: int, pad: bool) -> list[int]:
    acc = 0
    bits = 0
    ret = []
    maxv = (1 << tobits) - 1
    for value in data:
        if value < 0 or value >> frombits:
            raise ValueError("invalid data value")
        acc = (acc << frombits) | value
        bits += frombits
        while bits >= tobits:
            bits -= tobits
            ret.append((acc >> bits) & maxv)
    if pad:
        if bits:
            ret.append((acc << (tobits - bits)) & maxv)
    elif bits >= frombits or ((acc << (tobits - bits)) & maxv):
        raise ValueError("invalid padding")
    return ret


def encode(data: bytes, hrp: str = HRP_ACCOUNT) -> str:
    d5 = _convertbits(data, 8, 5, True)
    checksum = _create_checksum(hrp, d5)
    return hrp + "1" + "".join(CHARSET[d] for d in d5 + checksum)


def decode(addr: str, expected_hrp: str | None = HRP_ACCOUNT) -> bytes:
    if addr != addr.lower() and addr != addr.upper():
        raise ValueError("mixed-case bech32")
    addr = addr.lower()
    pos = addr.rfind("1")
    if pos < 1 or pos + 7 > len(addr):
        raise ValueError("invalid bech32 structure")
    hrp, rest = addr[:pos], addr[pos + 1 :]
    if expected_hrp is not None and hrp != expected_hrp:
        raise ValueError(f"wrong bech32 prefix {hrp!r} (want {expected_hrp!r})")
    try:
        data = [CHARSET.index(c) for c in rest]
    except ValueError:
        raise ValueError("invalid bech32 character") from None
    if _polymod(_hrp_expand(hrp) + data) != 1:
        raise ValueError("bad bech32 checksum")
    return bytes(_convertbits(data[:-6], 5, 8, False))
