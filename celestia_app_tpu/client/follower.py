"""Rollup follower: a verifying namespace reader (the read plane's client).

The consuming half of the read plane (docs/DESIGN.md "The read plane"):
a rollup node that trusts nothing but a genesis validator set follows ONE
namespace across heights, and every byte it delivers to the rollup's
execution layer is proven:

- **headers**: fetched per height (/ibc/header) and verified through the
  light client (chain/light.py) — >2/3 of the trusted set signed, hash
  linkage checked, condemned data roots refused. The follower never takes
  a serving peer's word for what the chain committed.
- **commitments**: the height's DAH doc (/das/header) is parsed AND
  verified against the certified data root by the scheme codec
  (``commitments_from_doc``) — a Byzantine peer serving fake row roots
  that happen to "prove" fake blobs is rejected HERE, before any blob
  bytes are even fetched.
- **blobs**: resolved from the peer's static blob pack when one is
  advertised (chunk sha256-checked against the manifest, fetched pinned
  to the advertising peer, mismatch penalized on the shared transport
  health score) or the live /blob/get route; either way the response's
  inclusion (or absence) proof must pass
  ``da/namespace_data.verify_namespace_data`` against the certified DAH.
  Absence is a verified claim too: a height with no blobs yields a
  checked absence witness, not a shrug.
- **checkpointing**: progress persists fsync-before-replace
  (das/checkpoint.CheckpointStore.save_doc) after every verified height,
  so a restarted follower resumes at ``next_height`` instead of
  re-reading the chain; the snapshot is taken under the follower's lock
  and the fsync paid outside it.

Telemetry: ``follower.heights`` / ``follower.blobs`` /
``follower.absences`` / ``follower.pack_reads`` / ``follower.live_reads``
/ ``follower.verify_failures``. Wire formats: docs/FORMATS.md §21.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import os
import threading

from celestia_app_tpu.chain import light as light_mod
from celestia_app_tpu.da import codec as dacodec
from celestia_app_tpu.da import namespace_data as nsd_mod
from celestia_app_tpu.das.checkpoint import CheckpointStore
from celestia_app_tpu.das.daser import PeerError, PeerSet
from celestia_app_tpu.utils import telemetry

NS = 29  # appconsts.NAMESPACE_SIZE without importing the wide module


class FollowerError(Exception):
    """A verification failure: a served proof did not check out against
    the certified commitments. This is the follower REFUSING data, not a
    transport error — transport problems retry inside the PeerSet."""


@dataclasses.dataclass
class FollowerConfig:
    request_timeout: float = 5.0
    retries: int = 3
    backoff: float = 0.05
    # resolve from advertised blob packs before the live route (a pack
    # miss — no manifest, namespace absent from it, or a chunk that
    # fails its hash — falls back to /blob/get)
    prefer_packs: bool = True
    # heights verified per sync() call (bounds one sweep's work)
    max_heights_per_sync: int = 256


def blobs_from_shares(shares: list[bytes]) -> list[bytes]:
    """Split a namespace's share run into blob payloads: sequences start
    at every start share (da/shares.py sparse layout); each reassembles
    independently so one namespace can carry many blobs per block."""
    from celestia_app_tpu.da import shares as shares_mod

    wrapped = [shares_mod.Share(s) for s in shares]
    out: list[bytes] = []
    run: list = []
    for sh in wrapped:
        if sh.is_sequence_start and run:
            out.append(shares_mod.parse_sparse_shares(run))
            run = []
        run.append(sh)
    if run:
        out.append(shares_mod.parse_sparse_shares(run))
    return out


class BlobFollower:
    """Follow one namespace across heights, verifying everything.

    Drive it with ``sync()`` (one sweep: follow head, verify pending
    heights, checkpoint) — the DASer's drive shape, so the CLI loop and
    tests treat both daemons alike."""

    def __init__(self, peers, namespace: bytes,
                 light: light_mod.LightClient, store: CheckpointStore,
                 cfg: FollowerConfig | None = None, header_source=None,
                 name: str = "follower"):
        if len(namespace) != NS:
            raise ValueError(f"namespace must be {NS} bytes")
        self.cfg = cfg or FollowerConfig()
        self.peers = peers if isinstance(peers, PeerSet) else PeerSet(
            peers, timeout=self.cfg.request_timeout,
            retries=self.cfg.retries, backoff=self.cfg.backoff,
        )
        self.namespace = namespace
        self.light = light
        self.store = store
        self.name = name
        from celestia_app_tpu.das import daser as daser_mod

        self.header_source = (header_source
                              or daser_mod.http_header_source(self.peers))
        self._lock = threading.Lock()
        # height -> (data_root_hex, square_size) for certified headers
        self._roots: dict[int, tuple[str, int]] = {}  # guarded-by: _lock
        # delivered blobs: height -> [payload bytes] (bounded by caller
        # draining via pop_blobs)
        self._blobs: dict[int, list[bytes]] = {}  # guarded-by: _lock
        self.next_height = 1  # first height NOT yet verified+delivered
        self._load_checkpoint()

    # -- checkpoint (das/checkpoint.py discipline, follower's own doc) ---

    def _load_checkpoint(self) -> None:
        """The follower's checkpoint doc is its own shape (§21.4), so it
        is read directly — CheckpointStore.load parses the DASer's."""
        path = self.store.path
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if doc.get("namespace") != self.namespace.hex():
            # a different namespace's progress is not ours to resume
            return
        self.next_height = int(doc.get("next_height", 1))

    def _checkpoint_doc(self) -> dict:
        return {
            "version": 1,
            "namespace": self.namespace.hex(),
            "next_height": self.next_height,
            "network_head": self.light.trusted.height,
        }

    def _save_checkpoint(self) -> None:
        with self._lock:
            doc = self._checkpoint_doc()
        # fsync OUTSIDE the lock (blocking-under-lock discipline)
        self.store.save_doc(doc)

    # -- header following (the DASer's follow loop) ----------------------

    def _follow_head(self) -> None:
        try:
            head = int(self.peers.request("/das/head")["height"])
        except (PeerError, KeyError, ValueError, TypeError):
            return
        while self.light.trusted.height < head:
            h = self.light.trusted.height + 1
            got = self.header_source(h)
            if got is None:
                break  # not yet certified anywhere; next sweep
            header, cert = got
            self.light.update(header, cert)  # LightClientError propagates
            with self._lock:
                self._roots[h] = (header.data_hash.hex(),
                                  header.square_size)

    # -- resolution: pack first, live fallback ---------------------------

    def _fetch_pack_doc(self, height: int, root_hex: str) -> dict | None:
        """The namespace's doc out of the peer's static blob pack, or
        None on any miss (no pack, namespace not packed — i.e. absent —
        or a chunk that fails verification). A hash mismatch penalizes
        the serving peer; it must not count as absence."""
        try:
            url, m = self.peers.request_from(f"/blob/pack?height={height}")
        except PeerError:
            return None
        if not (isinstance(m, dict) and m.get("data_root") == root_hex
                and isinstance(m.get("namespaces"), list)
                and isinstance(m.get("chunk_hashes"), list)
                and int(m.get("chunk_namespaces", 0)) > 0):
            return None
        ns_hex = self.namespace.hex()
        if ns_hex not in m["namespaces"]:
            return None  # absent from the pack ⇒ prove absence live
        pos = m["namespaces"].index(ns_hex)
        index = pos // int(m["chunk_namespaces"])
        try:
            raw = self.peers.request_pinned(
                url, f"/blob/pack/chunk?height={height}&index={index}",
                raw=True)
        except (OSError, ValueError):
            return None
        if hashlib.sha256(raw).hexdigest() != m["chunk_hashes"][index]:
            self.peers.penalize(url, "blob pack chunk hash mismatch")
            telemetry.incr("follower.verify_failures")
            return None
        from celestia_app_tpu.das.blob_packs import decode_chunk

        try:
            docs = decode_chunk(raw)
        except (ValueError, TypeError):
            # hash already checked out, so this is a malformed chunk the
            # SERVER built; fall back to the live route, visibly
            telemetry.incr("follower.verify_failures")
            return None
        for doc in docs:
            if isinstance(doc, dict) and doc.get("namespace") == ns_hex:
                telemetry.incr("follower.pack_reads")
                return doc
        return None

    def _fetch_live_doc(self, height: int) -> dict:
        doc = self.peers.request(
            f"/blob/get?height={height}&namespace={self.namespace.hex()}")
        telemetry.incr("follower.live_reads")
        return doc

    # -- verification ----------------------------------------------------

    def _certified_dah(self, height: int, root_hex: str,
                       square_size: int):
        """The height's commitments, fetched from an untrusted peer and
        VERIFIED against the certified data root (the DASer's
        commitments rule). Non-rs2d heights have no namespace surface —
        the follower refuses rather than trusting unverifiable docs."""
        doc = self.peers.request(f"/das/header?height={height}")
        scheme = doc.get("scheme", dacodec.RS2D_NAME)
        if scheme != dacodec.RS2D_NAME:
            raise FollowerError(
                f"height {height} commits {scheme}; namespace reads "
                f"need {dacodec.RS2D_NAME}"
            )
        codec = dacodec.get(scheme)
        try:
            return codec.commitments_from_doc(doc, root_hex, square_size)
        except (dacodec.CodecError, ValueError, KeyError, TypeError) as e:
            telemetry.incr("follower.verify_failures")
            raise FollowerError(
                f"height {height}: served commitments do not bind to the "
                f"certified data root: {e}"
            ) from None

    def _verified_nd(self, height: int, dah, root_hex: str,
                     doc: dict) -> "nsd_mod.NamespaceData":
        """Parse a served namespace doc and verify its claim against the
        certified DAH — raises FollowerError (and counts) on ANY
        mismatch: wrong data root, undecodable proof, or a proof that
        fails verify_namespace_data (tampered shares, incomplete range,
        fake absence)."""
        from celestia_app_tpu.chain.query import share_proof_from_json

        def refuse(why: str):
            telemetry.incr("follower.verify_failures")
            return FollowerError(
                f"height {height} namespace {self.namespace.hex()[:12]}: "
                f"{why}"
            )

        if not isinstance(doc, dict):
            raise refuse("malformed response")
        if doc.get("data_root") != root_hex:
            raise refuse(
                f"response claims data root {str(doc.get('data_root'))[:16]}"
                f" but the certified root is {root_hex[:16]}"
            )
        try:
            shares = [base64.b64decode(s) for s in doc.get("shares", [])]
            proof = (share_proof_from_json(doc["proof"])
                     if doc.get("proof") else None)
        except (ValueError, KeyError, TypeError):
            raise refuse("undecodable shares/proof") from None
        nd = nsd_mod.NamespaceData(namespace=self.namespace,
                                   shares=shares, proof=proof)
        if not nsd_mod.verify_namespace_data(dah, self.namespace, nd):
            raise refuse("inclusion/absence proof failed verification")
        return nd

    # -- the sweep --------------------------------------------------------

    def _read_height(self, height: int) -> dict:
        with self._lock:
            root_hex, square_size = self._roots[height]
        dah = self._certified_dah(height, root_hex, square_size)
        doc = None
        if self.cfg.prefer_packs:
            doc = self._fetch_pack_doc(height, root_hex)
        if doc is None:
            doc = self._fetch_live_doc(height)
        nd = self._verified_nd(height, dah, root_hex, doc)
        telemetry.incr("follower.heights")
        if nd.shares:
            payloads = blobs_from_shares(nd.shares)
            telemetry.incr("follower.blobs", len(payloads))
            with self._lock:
                self._blobs[height] = payloads
            return {"height": height, "blobs": len(payloads)}
        telemetry.incr("follower.absences")
        return {"height": height, "blobs": 0}

    def sync(self) -> dict:
        """One sweep: follow the head, verify every pending height (up
        to the config bound), checkpoint. Returns the sweep report."""
        self._follow_head()
        done = 0
        while (self.next_height <= self.light.trusted.height
               and done < self.cfg.max_heights_per_sync):
            h = self.next_height
            with self._lock:
                have = h in self._roots
            if not have:
                break  # header gap (restart): re-follow next sweep
            self._read_height(h)
            with self._lock:
                self.next_height = h + 1
                self._roots.pop(h, None)
            done += 1
            self._save_checkpoint()
        return {
            "head": self.light.trusted.height,
            "next_height": self.next_height,
            "verified": done,
        }

    def pop_blobs(self) -> dict[int, list[bytes]]:
        """Drain delivered blob payloads (height -> [bytes]) — the
        rollup execution layer's intake."""
        with self._lock:
            out, self._blobs = self._blobs, {}
        return out

    def catch_up_roots(self) -> None:
        """Restart path: a resumed follower trusts its checkpoint's
        ``next_height`` but its LightClient starts back at genesis —
        re-follow certifies the missing headers (cheap) without
        re-reading completed heights (the expensive part)."""
        self._follow_head()
        with self._lock:
            for h in [h for h in self._roots if h < self.next_height]:
                self._roots.pop(h)


def follower_status() -> dict:
    """Follower-side counters for operator surfaces."""
    counters = telemetry.snapshot()["counters"]

    def g(name: str) -> int:
        return int(counters.get(name, 0))

    return {
        "heights": g("follower.heights"),
        "blobs": g("follower.blobs"),
        "absences": g("follower.absences"),
        "pack_reads": g("follower.pack_reads"),
        "live_reads": g("follower.live_reads"),
        "verify_failures": g("follower.verify_failures"),
    }


__all__ = [
    "BlobFollower", "FollowerConfig", "FollowerError",
    "blobs_from_shares", "follower_status",
]
