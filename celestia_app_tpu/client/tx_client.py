"""Programmatic tx submission: Signer + TxClient.

Reference parity: pkg/user — `Signer` (multi-account sequence tracking,
signer.go:23-35), `TxClient` (gas estimation, fee calc, broadcast, ConfirmTx,
sequence-mismatch resubmission, tx_client.go:87-104,202-250,320-420). The
transport here is in-process against a Node (gRPC arrives with the service
layer); the resubmission loop mirrors app/errors/nonce_mismatch.go by parsing
the expected sequence out of the ante error string.
"""

from __future__ import annotations

import dataclasses
import re

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain import modules
from celestia_app_tpu.utils import telemetry
from celestia_app_tpu.chain.crypto import PrivateKey
from celestia_app_tpu.chain.tx import MsgPayForBlobs, MsgSend, Tx, TxBody, sign_tx
from celestia_app_tpu.da import blob as blob_mod
from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.da import commitment as commitment_mod

_SEQ_RE = re.compile(r"expected (\d+), got (\d+)")
_GAS_PRICE_RE = re.compile(r"insufficient gas price: [0-9.]+ < min ([0-9.]+)")


def parse_expected_sequence(err: str) -> int | None:
    """app/errors/nonce_mismatch.go:13-30 equivalent."""
    m = _SEQ_RE.search(err)
    return int(m.group(1)) if m else None


def parse_required_min_gas_price(err: str) -> float | None:
    """app/errors/insufficient_gas_price.go analog: the gas-price floor the
    node demands, parsed from the ante rejection (chain/ante.py step 4) so
    the client can re-price and resubmit instead of failing the user."""
    m = _GAS_PRICE_RE.search(err)
    return float(m.group(1)) if m else None


@dataclasses.dataclass
class Account:
    priv: PrivateKey
    number: int
    sequence: int

    @property
    def address(self) -> bytes:
        return self.priv.public_key().address()


class Signer:
    """Tracks account numbers/sequences and signs tx bodies (pkg/user Signer).

    `wire="proto"` (default) produces cosmos TxRaw bytes with
    SIGN_MODE_DIRECT sign docs — what the reference's pkg/user/signer.go
    emits; `wire="native"` keeps the framework's legacy codec."""

    def __init__(self, chain_id: str, wire: str = "proto"):
        self.chain_id = chain_id
        self.wire = wire
        self.accounts: dict[bytes, Account] = {}

    def add_account(self, priv: PrivateKey, number: int, sequence: int = 0) -> bytes:
        acc = Account(priv, number, sequence)
        self.accounts[acc.address] = acc
        return acc.address

    def create_tx(self, addr: bytes, msgs, fee: int, gas_limit: int, memo: str = ""):
        acc = self.accounts[addr]
        body = TxBody(
            msgs=tuple(msgs),
            chain_id=self.chain_id,
            account_number=acc.number,
            sequence=acc.sequence,
            fee=fee,
            gas_limit=gas_limit,
            memo=memo,
        )
        if self.wire == "proto":
            from celestia_app_tpu.wire import codec as wire_codec

            return wire_codec.sign_tx_proto(body, acc.priv)
        return sign_tx(body, acc.priv)

    def build_pfb_msg(
        self, addr: bytes, blobs: list[Blob], subtree_root_threshold: int = 64
    ) -> MsgPayForBlobs:
        """MsgPayForBlobs with per-blob share commitments — the expensive
        part (Merkle trees over all blob shares); build ONCE and re-sign
        with different fee/gas as needed."""
        return MsgPayForBlobs(
            signer=addr,
            namespaces=tuple(b.namespace.raw for b in blobs),
            blob_sizes=tuple(len(b.data) for b in blobs),
            share_commitments=tuple(
                commitment_mod.create_commitment(b, subtree_root_threshold)
                for b in blobs
            ),
            share_versions=tuple(b.share_version for b in blobs),
        )

    def create_pay_for_blobs(
        self, addr: bytes, blobs: list[Blob], fee: int, gas_limit: int,
        subtree_root_threshold: int = 64, msg: MsgPayForBlobs | None = None,
    ) -> bytes:
        """Build MsgPayForBlobs + sign + wrap in a BlobTx envelope
        (x/blob/types/payforblob.go:48-77 + blob.MarshalBlobTx). Pass a
        precomputed `msg` to skip recomputing commitments."""
        if msg is None:
            msg = self.build_pfb_msg(addr, blobs, subtree_root_threshold)
        tx = self.create_tx(addr, [msg], fee, gas_limit)
        return blob_mod.marshal_blob_tx(tx.encode(), blobs)


class HttpNodeClient:
    """Remote node transport: the same surface TxClient needs, over the
    HTTP JSON service (service/server.py) — the reference's gRPC remote
    mode (pkg/user/tx_client.go:320-330 BroadcastMode_SYNC + Simulate).

    Holds ONE persistent HTTP/1.1 keep-alive connection (the serving
    plane's dasload pattern): a submit-then-poll client issues many
    small requests, and a fresh TCP connect per request dominates the
    round-trip on small blobs under sustained load. A torn socket (idle
    reaper, server restart) reconnects transparently once per request
    (`txclient.reconnects`). One connection, one lock: the client is
    thread-safe but callers wanting concurrency (tools/txsim.py) give
    each sequence its own client."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        import threading
        import urllib.parse

        self.base_url = base_url.rstrip("/")
        p = urllib.parse.urlparse(self.base_url)
        self._host = p.hostname
        self._port = p.port or (443 if p.scheme == "https" else 80)
        self._tls = p.scheme == "https"
        self.timeout = timeout
        self._lock = threading.Lock()
        self._conn = None  # guarded-by: _lock

    def _new_conn(self):
        import http.client

        if self._tls:
            return http.client.HTTPSConnection(self._host, self._port,
                                               timeout=self.timeout)
        return http.client.HTTPConnection(self._host, self._port,
                                          timeout=self.timeout)

    def _request(self, method: str, path: str,
                 payload: dict | None = None) -> dict:
        import http.client
        import json as json_mod

        body = None
        headers = {}
        if payload is not None:
            body = json_mod.dumps(payload).encode()
            headers["Content-Type"] = "application/json"
        # one keep-alive connection IS the serialization point: HTTP/1.1
        # cannot multiplex, so requests must queue on the client's own
        # lock (callers wanting concurrency hold one client per
        # sequence); no node/service lock is ever involved
        with self._lock:  # lint: disable=blocking-under-lock
            for attempt in (0, 1):
                if self._conn is None:
                    self._conn = self._new_conn()
                    if attempt:
                        telemetry.incr("txclient.reconnects")
                try:
                    self._conn.request(method, path, body=body,
                                       headers=headers)
                    r = self._conn.getresponse()
                    data = r.read()
                    status = r.status
                    break
                except (OSError, http.client.HTTPException):
                    # keep-alive races are normal: one clean reconnect
                    try:
                        self._conn.close()
                    finally:
                        self._conn = None
                    if attempt:
                        raise
        try:
            out = json_mod.loads(data)
        except ValueError:
            out = {"error": data.decode("utf-8", "replace")}
        if status >= 400:
            # OSError family, like the urllib HTTPError the PeerClient
            # transport used to raise — existing callers (cli das --url)
            # catch OSError to degrade gracefully
            raise OSError(
                f"{method} {path} -> {status}: {out.get('error', out)}")
        return out

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                finally:
                    self._conn = None

    def _post(self, path: str, payload: dict) -> dict:
        return self._request("POST", path, payload)

    def broadcast_tx(self, raw: bytes):
        import base64

        out = self._post("/broadcast_tx", {"tx": base64.b64encode(raw).decode()})
        from celestia_app_tpu.chain.block import TxResult

        return TxResult(out["code"], out.get("log", ""),
                        out.get("gas_wanted", 0), out.get("gas_used", 0), [])

    def simulate_tx(self, raw: bytes) -> int:
        """-> gas_used; raises on a failed simulation."""
        import base64

        out = self._post("/simulate_tx", {"tx": base64.b64encode(raw).decode()})
        if out["code"] != 0:
            raise RuntimeError(f"simulation failed: {out.get('log')}")
        return out["gas_used"]

    def confirm_tx(
        self, raw: bytes, attempts: int = 1, interval: float = 3.0
    ) -> dict:
        """Poll the tx-by-hash route until found or attempts run out —
        {'found': bool, height?, index?}. The reference's ConfirmTx polls
        every 3s (tx_client.go:412); the server's own block loop (`start`)
        or a devnet /produce_block commits the tx between polls."""
        import hashlib
        import time as time_mod

        txhash = hashlib.sha256(raw).hexdigest()
        for i in range(max(1, attempts)):
            out = self._post("/abci_query", {"path": "tx", "data": {"hash": txhash}})
            if out.get("found"):
                return out
            if i + 1 < attempts:
                time_mod.sleep(interval)
        return out

    def status(self) -> dict:
        return self._request("GET", "/status")


class GrpcNodeClient:
    """Remote node transport over REAL gRPC — the reference's only remote
    mode (pkg/user/tx_client.go talks to :9090 exclusively). Speaks the
    cosmos service/method names with the byte-compat codecs; the channel
    plus identity (de)serializers stand in for generated stubs."""

    def __init__(self, target: str, timeout: float = 30.0):
        import grpc

        self._grpc = grpc
        self.timeout = timeout
        self.channel = grpc.insecure_channel(target)
        self._callables: dict[str, object] = {}

    def _call(self, service: str, method: str, request: bytes) -> bytes:
        path = f"/{service}/{method}"
        fn = self._callables.get(path)
        if fn is None:
            fn = self.channel.unary_unary(
                path,
                request_serializer=lambda x: x,
                response_deserializer=lambda x: x,
            )
            self._callables[path] = fn
        return fn(request, timeout=self.timeout)

    # -- tx service ------------------------------------------------------

    def broadcast_tx(self, raw: bytes):
        from celestia_app_tpu.chain.block import TxResult
        from celestia_app_tpu.wire import txpb

        out = txpb.parse_broadcast_tx_response(self._call(
            "cosmos.tx.v1beta1.Service", "BroadcastTx",
            txpb.broadcast_tx_request_pb(raw),
        ))
        return TxResult(out["code"], out["raw_log"],
                        out["gas_wanted"], out["gas_used"], [])

    def simulate_tx(self, raw: bytes) -> int:
        from celestia_app_tpu.wire import txpb

        out = txpb.parse_simulate_response(self._call(
            "cosmos.tx.v1beta1.Service", "Simulate",
            txpb.simulate_request_pb(raw),
        ))
        return out["gas_used"]

    def confirm_tx(self, raw: bytes, attempts: int = 10,
                   interval: float = 1.0) -> dict:
        """GetTx-polling confirmation (tx_client.go:412 ConfirmTx)."""
        import hashlib
        import time as time_mod

        from celestia_app_tpu.wire import txpb

        txhash = hashlib.sha256(raw).hexdigest()
        last_err = None
        for i in range(max(1, attempts)):
            try:
                out = txpb.parse_get_tx_response(self._call(
                    "cosmos.tx.v1beta1.Service", "GetTx",
                    txpb.get_tx_request_pb(txhash),
                ))
                return {"found": True, "height": out["height"],
                        "code": out["code"]}
            except self._grpc.RpcError as e:
                if e.code() != self._grpc.StatusCode.NOT_FOUND:
                    raise
                last_err = e
            if i + 1 < attempts:
                time_mod.sleep(interval)
        assert last_err is not None
        return {"found": False}

    # -- bootstrap queries (SetupTxClient surface) -----------------------

    def get_latest_block(self) -> dict:
        from celestia_app_tpu.wire import txpb

        return txpb.parse_get_latest_block_response(self._call(
            "cosmos.base.tendermint.v1beta1.Service", "GetLatestBlock", b""
        ))

    def query_account(self, address: str) -> dict | None:
        """-> {account_number, sequence, ...} or None when the account does
        not exist in state (SetupTxClient skips those)."""
        from celestia_app_tpu.wire import txpb

        try:
            return txpb.parse_query_account_response(self._call(
                "cosmos.auth.v1beta1.Query", "Account",
                txpb.query_account_request_pb(address),
            ))
        except self._grpc.RpcError as e:
            if e.code() == self._grpc.StatusCode.NOT_FOUND:
                return None
            raise

    def query_balance(self, address: str, denom: str = "") -> int:
        from celestia_app_tpu.wire import txpb

        _denom, amount = txpb.parse_query_balance_response(self._call(
            "cosmos.bank.v1beta1.Query", "Balance",
            txpb.query_balance_request_pb(address, denom),
        ))
        return amount

    def blob_params(self) -> dict:
        from celestia_app_tpu.wire import txpb

        return txpb.parse_blob_params_response(self._call(
            "celestia.blob.v1.Query", "Params", b""
        ))

    def minimum_gas_price(self) -> float:
        """max(local, network) — QueryMinimumGasPrice (tx_client.go:561-591),
        including the v1 fallback on 'unknown subspace: minfee'."""
        import json
        import re as re_mod

        from celestia_app_tpu.wire import txpb

        cfg = txpb.parse_node_config_response(self._call(
            "cosmos.base.node.v1beta1.Service", "Config", b""
        ))
        m = re_mod.match(r"([0-9.]+)", cfg)
        local = float(m.group(1)) if m else 0.0
        try:
            resp = txpb.parse_query_subspace_params_response(self._call(
                "cosmos.params.v1beta1.Query", "Params",
                txpb.query_subspace_params_request_pb(
                    "minfee", "NetworkMinGasPrice"
                ),
            ))
            network = float(json.loads(resp["value"])) if resp["value"] else 0.0
        except self._grpc.RpcError as e:
            if "unknown subspace: minfee" in (e.details() or ""):
                return local  # v1 chain: local price only
            raise
        return max(local, network)

    def close(self) -> None:
        self.channel.close()


def setup_tx_client_grpc(
    target: str, privs: list[PrivateKey], gas_multiplier: float = 1.1
) -> "TxClient":
    """SetupTxClient (pkg/user/tx_client.go:147-198) over gRPC alone:
    chain-id from GetLatestBlock, account number/sequence from auth Account
    (accounts missing from state are skipped), default gas price from
    QueryMinimumGasPrice — then a ready TxClient on the same channel."""
    from celestia_app_tpu.wire import bech32

    client = GrpcNodeClient(target)
    try:
        head = client.get_latest_block()
        signer = Signer(head["chain_id"])
        for priv in privs:
            addr = priv.public_key().address()
            acc = client.query_account(bech32.encode(addr))
            if acc is None:
                continue  # skip accounts that don't exist in state
            signer.add_account(priv, number=acc["account_number"],
                               sequence=acc["sequence"])
        if not signer.accounts:
            raise RuntimeError(
                "no provided key has an account in state; fund one first"
            )
        price = client.minimum_gas_price()
    except BaseException:
        client.close()
        raise
    return TxClient(client, signer, gas_multiplier=gas_multiplier,
                    default_gas_price=price)


class TxClient:
    """High-level submission against an in-process Node OR a remote
    transport (HttpNodeClient / GrpcNodeClient — all expose
    broadcast_tx/confirm_tx; gas estimation prefers true simulation when
    the transport offers it)."""

    def __init__(self, node, signer: Signer, gas_multiplier: float = 1.1,
                 default_gas_price: float | None = None):
        self.node = node
        self.signer = signer
        self.gas_multiplier = gas_multiplier
        self.default_gas_price = default_gas_price

    def _gas_price(self) -> float:
        if self.default_gas_price is not None:
            return self.default_gas_price
        return max(
            appconsts.DEFAULT_MIN_GAS_PRICE,
            appconsts.DEFAULT_NETWORK_MIN_GAS_PRICE,
        )

    def _simulate_gas(self, raw: bytes) -> int | None:
        """Simulate-based estimation (tx_client.go estimateGas): dry-run the
        tx and return measured gas, or None when no simulator is reachable
        (fall back to the linear model)."""
        sim = getattr(self.node, "simulate_tx", None)
        if sim is None:
            app = getattr(self.node, "app", None)
            sim = getattr(app, "simulate_tx", None)
        if sim is None:
            return None
        try:
            res = sim(raw)
        except Exception:
            # unreachable/failing simulator (HTTP errors, bad body, failed
            # simulation): fall back to the linear model as documented
            telemetry.incr("txclient.sim_fallback")
            return None
        if isinstance(res, int):
            return res
        return res.gas_used if res.code == 0 else None

    def estimate_gas(
        self, addr: bytes, msgs, blobs: list[Blob] | None = None, pfb_msg=None
    ) -> int:
        """Measured-gas estimation with the linear PFB model as fallback.
        Pass `pfb_msg` (from Signer.build_pfb_msg) to avoid recomputing
        blob commitments for the probe."""
        if blobs:
            probe = self.signer.create_pay_for_blobs(
                addr, blobs, fee=1, gas_limit=1 << 40, msg=pfb_msg
            )
        else:
            probe = self.signer.create_tx(addr, msgs, fee=1, gas_limit=1 << 40).encode()
        measured = self._simulate_gas(probe)
        if measured is not None:
            return int(measured * self.gas_multiplier)
        if blobs:
            return int(
                modules.estimate_pfb_gas([len(b.data) for b in blobs])
                * self.gas_multiplier
            )
        return 100_000

    def _recover_broadcast_failure(self, addr: bytes, res, gas: int,
                                   fee: int) -> int | None:
        """Shared resubmission logic (tx_client.go:330-360 + app/errors):
        a sequence mismatch resyncs the signer; an insufficient-gas-price
        rejection re-prices against the node's parsed floor. Returns the
        new fee to retry with, or None when the failure is terminal."""
        expected = parse_expected_sequence(res.log)
        if expected is not None:
            self.signer.accounts[addr].sequence = expected
            return fee
        floor = parse_required_min_gas_price(res.log)
        if floor is not None:
            # remember the node's floor: only the FIRST underpriced tx pays
            # the extra rejected round-trip
            self.default_gas_price = max(self._gas_price(), floor)
            return max(fee + 1, int(gas * floor) + 1)
        return None

    def _broadcast_with_retry(self, addr: bytes, make_raw, gas: int,
                              fee: int):
        """THE submit loop every submit_* goes through. 3 attempts: the two
        recoverable rejection classes (stale sequence, price below floor —
        tx_client.go:357 + app/errors parsing) can BOTH occur on one tx,
        each burning one attempt. `make_raw(fee)` re-signs with the
        current fee/sequence. On acceptance, bumps the cached sequence and
        confirms — the in-process Node drives blocks to commit and returns
        (height, TxResult); remote transports POLL the server's block
        production and return the tx-by-hash dict (check ['found'])."""
        for _attempt in range(3):
            raw = make_raw(fee)
            res = self.node.broadcast_tx(raw)
            if res.code == 0:
                self.signer.accounts[addr].sequence += 1
                if isinstance(self.node, (HttpNodeClient, GrpcNodeClient)):
                    return self.node.confirm_tx(raw, attempts=10,
                                                interval=1.0)
                return self.node.confirm_tx(raw)
            new_fee = self._recover_broadcast_failure(addr, res, gas, fee)
            if new_fee is None:
                raise RuntimeError(f"broadcast failed: {res.log}")
            fee = new_fee
        raise RuntimeError(f"resubmission failed; last rejection: {res.log}")

    def submit_pay_for_blob(self, addr: bytes, blobs: list[Blob]):
        """Estimate gas (simulate, falling back to the linear model), sign,
        broadcast, confirm. Blob commitments — the dominant client-side
        hashing cost — are computed exactly once."""
        pfb_msg = self.signer.build_pfb_msg(addr, blobs)
        gas = self.estimate_gas(addr, [], blobs, pfb_msg=pfb_msg)
        fee = max(1, int(gas * self._gas_price()) + 1)
        return self._broadcast_with_retry(
            addr,
            lambda f: self.signer.create_pay_for_blobs(
                addr, blobs, fee=f, gas_limit=gas, msg=pfb_msg
            ),
            gas, fee,
        )

    def submit_create_validator(self, addr: bytes, self_stake: int,
                                pubkey: bytes = b""):
        """MsgCreateValidator with the consensus pubkey registered on-chain
        (the reference tx staking create-validator; pubkey is what lets
        the new validator's votes verify — chain/reactor.py)."""
        from celestia_app_tpu.chain.tx import MsgCreateValidator

        gas = 200_000
        fee = max(1, int(gas * self._gas_price()) + 1)
        return self._broadcast_with_retry(
            addr,
            lambda f: self.signer.create_tx(
                addr, [MsgCreateValidator(addr, self_stake, pubkey)],
                fee=f, gas_limit=gas,
            ).encode(),
            gas, fee,
        )

    def submit_send(self, addr: bytes, to: bytes, amount: int):
        gas = 100_000
        fee = max(1, int(gas * self._gas_price()) + 1)
        return self._broadcast_with_retry(
            addr,
            lambda f: self.signer.create_tx(
                addr, [MsgSend(addr, to, amount)], fee=f, gas_limit=gas
            ).encode(),
            gas, fee,
        )
