"""Programmatic tx submission: Signer + TxClient.

Reference parity: pkg/user — `Signer` (multi-account sequence tracking,
signer.go:23-35), `TxClient` (gas estimation, fee calc, broadcast, ConfirmTx,
sequence-mismatch resubmission, tx_client.go:87-104,202-250,320-420). The
transport here is in-process against a Node (gRPC arrives with the service
layer); the resubmission loop mirrors app/errors/nonce_mismatch.go by parsing
the expected sequence out of the ante error string.
"""

from __future__ import annotations

import dataclasses
import re

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain import modules
from celestia_app_tpu.chain.crypto import PrivateKey
from celestia_app_tpu.chain.tx import MsgPayForBlobs, MsgSend, Tx, TxBody, sign_tx
from celestia_app_tpu.da import blob as blob_mod
from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.da import commitment as commitment_mod

_SEQ_RE = re.compile(r"expected (\d+), got (\d+)")


def parse_expected_sequence(err: str) -> int | None:
    """app/errors/nonce_mismatch.go:13-30 equivalent."""
    m = _SEQ_RE.search(err)
    return int(m.group(1)) if m else None


@dataclasses.dataclass
class Account:
    priv: PrivateKey
    number: int
    sequence: int

    @property
    def address(self) -> bytes:
        return self.priv.public_key().address()


class Signer:
    """Tracks account numbers/sequences and signs tx bodies (pkg/user Signer).

    `wire="proto"` (default) produces cosmos TxRaw bytes with
    SIGN_MODE_DIRECT sign docs — what the reference's pkg/user/signer.go
    emits; `wire="native"` keeps the framework's legacy codec."""

    def __init__(self, chain_id: str, wire: str = "proto"):
        self.chain_id = chain_id
        self.wire = wire
        self.accounts: dict[bytes, Account] = {}

    def add_account(self, priv: PrivateKey, number: int, sequence: int = 0) -> bytes:
        acc = Account(priv, number, sequence)
        self.accounts[acc.address] = acc
        return acc.address

    def create_tx(self, addr: bytes, msgs, fee: int, gas_limit: int, memo: str = ""):
        acc = self.accounts[addr]
        body = TxBody(
            msgs=tuple(msgs),
            chain_id=self.chain_id,
            account_number=acc.number,
            sequence=acc.sequence,
            fee=fee,
            gas_limit=gas_limit,
            memo=memo,
        )
        if self.wire == "proto":
            from celestia_app_tpu.wire import codec as wire_codec

            return wire_codec.sign_tx_proto(body, acc.priv)
        return sign_tx(body, acc.priv)

    def create_pay_for_blobs(
        self, addr: bytes, blobs: list[Blob], fee: int, gas_limit: int,
        subtree_root_threshold: int = 64,
    ) -> bytes:
        """Build MsgPayForBlobs + sign + wrap in a BlobTx envelope
        (x/blob/types/payforblob.go:48-77 + blob.MarshalBlobTx)."""
        msg = MsgPayForBlobs(
            signer=addr,
            namespaces=tuple(b.namespace.raw for b in blobs),
            blob_sizes=tuple(len(b.data) for b in blobs),
            share_commitments=tuple(
                commitment_mod.create_commitment(b, subtree_root_threshold) for b in blobs
            ),
            share_versions=tuple(b.share_version for b in blobs),
        )
        tx = self.create_tx(addr, [msg], fee, gas_limit)
        return blob_mod.marshal_blob_tx(tx.encode(), blobs)


class TxClient:
    """High-level submission against an in-process node."""

    def __init__(self, node, signer: Signer, gas_multiplier: float = 1.1):
        self.node = node
        self.signer = signer
        self.gas_multiplier = gas_multiplier

    def _gas_price(self) -> float:
        return max(
            appconsts.DEFAULT_MIN_GAS_PRICE,
            appconsts.DEFAULT_NETWORK_MIN_GAS_PRICE,
        )

    def submit_pay_for_blob(self, addr: bytes, blobs: list[Blob]):
        """Estimate gas, sign, broadcast, confirm; resubmit once on a
        sequence mismatch (tx_client.go:357 + nonce parsing)."""
        gas = int(
            modules.estimate_pfb_gas([len(b.data) for b in blobs]) * self.gas_multiplier
        )
        fee = max(1, int(gas * self._gas_price()) + 1)

        for _attempt in range(2):
            raw = self.signer.create_pay_for_blobs(addr, blobs, fee=fee, gas_limit=gas)
            res = self.node.broadcast_tx(raw)
            if res.code == 0:
                self.signer.accounts[addr].sequence += 1
                return self.node.confirm_tx(raw)
            expected = parse_expected_sequence(res.log)
            if expected is None:
                raise RuntimeError(f"broadcast failed: {res.log}")
            self.signer.accounts[addr].sequence = expected
        raise RuntimeError("sequence resubmission failed")

    def submit_send(self, addr: bytes, to: bytes, amount: int):
        gas = 100_000
        fee = max(1, int(gas * self._gas_price()) + 1)
        for _attempt in range(2):
            tx = self.signer.create_tx(
                addr, [MsgSend(addr, to, amount)], fee=fee, gas_limit=gas
            )
            res = self.node.broadcast_tx(tx.encode())
            if res.code == 0:
                self.signer.accounts[addr].sequence += 1
                return self.node.confirm_tx(tx.encode())
            expected = parse_expected_sequence(res.log)
            if expected is None:
                raise RuntimeError(f"broadcast failed: {res.log}")
            self.signer.accounts[addr].sequence = expected
        raise RuntimeError("sequence resubmission failed")
