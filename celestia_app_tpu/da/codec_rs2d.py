"""The default DA scheme behind the codec interface: 2D-RS + NMT.

A thin adapter — every algorithm stays where it always lived (da/eds.py
pipeline via da/edscache.py, da/proof_device.py provers, da/repair.py
sweep engine, da/fraud.py BEFPs), so the refactor is byte-identical by
construction: data roots, DAH hashes and sample proofs are pinned
against frozen pre-refactor vectors in tests/test_codec_iface.py, on
both engines. The codec object only gives the existing pipeline the
same face the CMT scheme (da/cmt.py) presents, so the DASer, the DAS
server, the bench and the conformance suite can treat the scheme as a
parameter.

Sampling threshold (the old hard-coded da/sampling.py constant): to
make any original share unrecoverable a withholder must hide more than
(k+1)^2 of the (2k)^2 extended cells — over a quarter — so CATCH_BP is
2500, a COMBINATORIAL bound (contrast the CMT scheme's empirical one).
"""

from __future__ import annotations

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.da.shares import uvarint

NMT_ROOT = appconsts.NMT_ROOT_SIZE  # 90


class Rs2dBadEncoding(codec_mod.BadEncodingDetected):
    """Normalized bad-encoding location: (axis, index) — a re-raise
    wrapper so codec callers need not import da/repair's exception."""

    def __init__(self, axis: str, index: int):
        super().__init__((axis, index), f"bad {axis} {index}")
        self.axis = axis
        self.index = index


class Rs2dNmtCodec(codec_mod.Codec):
    scheme_id = codec_mod.SCHEME_RS2D
    name = codec_mod.RS2D_NAME
    CATCH_BP = 2500

    # -- encode ----------------------------------------------------------

    def compute_entry(self, ods: np.ndarray, engine: str = "auto"):
        from celestia_app_tpu.da import edscache

        return edscache.compute_entry(ods, engine, scheme=self.name)

    # -- commitments on the wire (the /das/header doc shape, FORMATS §7) -

    def commitments_doc(self, entry) -> dict:
        dah = entry.dah
        return {
            "scheme": self.name,
            "square_width": len(dah.row_roots),
            "row_roots": [r.hex() for r in dah.row_roots],
            "col_roots": [c.hex() for c in dah.col_roots],
            "data_root": entry.data_root.hex(),
        }

    def commitments_from_doc(self, doc: dict, data_root_hex: str,
                             square_size: int):
        from celestia_app_tpu.da.dah import DataAvailabilityHeader

        try:
            dah = DataAvailabilityHeader(
                row_roots=tuple(bytes.fromhex(x)
                                for x in doc["row_roots"]),
                col_roots=tuple(bytes.fromhex(x)
                                for x in doc["col_roots"]),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise codec_mod.CodecError(
                f"malformed DAH doc: {e}") from None
        try:
            dah.validate_basic()
        except ValueError as e:
            raise codec_mod.CodecError(str(e)) from None
        if dah.hash().hex() != data_root_hex:
            raise codec_mod.CodecError(
                "served DAH does not bind to the certified root")
        if len(dah.row_roots) != 2 * square_size:
            raise codec_mod.CodecError(
                "served DAH width contradicts the header")
        return dah

    # -- sampling --------------------------------------------------------

    def sample_space(self, commitments) -> list[tuple[int, int]]:
        width = len(commitments.row_roots)
        return [(r, c) for r in range(width) for c in range(width)]

    def open_sample(self, entry, cell: tuple[int, int]) -> dict:
        import base64

        row, col = cell
        share, proof = entry.get_prover().prove_cell(row, col)
        return {
            "row": row,
            "col": col,
            "share": base64.b64encode(share).decode(),
            "proof": {
                "start": proof.start,
                "end": proof.end,
                "total": proof.total,
                "nodes": [base64.b64encode(n).decode()
                          for n in proof.nodes],
            },
        }

    def verify_sample(self, commitments, doc: dict):
        import base64

        from celestia_app_tpu.da import sampling
        from celestia_app_tpu.utils import nmt_host

        try:
            row, col = int(doc["row"]), int(doc["col"])
            share = base64.b64decode(doc["share"])
            proof = nmt_host.NmtRangeProof(
                start=int(doc["proof"]["start"]),
                end=int(doc["proof"]["end"]),
                total=int(doc["proof"]["total"]),
                nodes=[base64.b64decode(n)
                       for n in doc["proof"]["nodes"]],
            )
        except (KeyError, TypeError, ValueError):
            return None
        if not sampling.verify_sample(commitments, row, col, share,
                                      proof):
            return None
        return (row, col), share

    def sample_wire_bytes(self, doc: dict, commitments=None) -> int:
        import base64

        return (len(uvarint(int(doc["row"])))
                + len(uvarint(int(doc["col"])))
                + len(base64.b64decode(doc["share"]))
                + len(uvarint(int(doc["proof"]["start"])))
                + len(uvarint(int(doc["proof"]["end"])))
                + len(uvarint(int(doc["proof"]["total"])))
                + len(doc["proof"]["nodes"]) * NMT_ROOT)

    def hashes_per_sample_verify(self, commitments) -> int:
        # one leaf hash + one inner hash per proof node up the 2k tree
        width = len(commitments.row_roots)
        return 1 + (width - 1).bit_length()

    # -- repair / fraud --------------------------------------------------

    def repair(self, commitments, samples: dict,
               engine: str = "auto") -> np.ndarray:
        from celestia_app_tpu.da import repair as repair_mod

        width = len(commitments.row_roots)
        k = width // 2
        symbols = np.zeros((width, width, appconsts.SHARE_SIZE),
                           dtype=np.uint8)
        present = np.zeros((width, width), dtype=bool)
        for (r, c), share in sorted(samples.items()):
            symbols[r, c] = np.frombuffer(share, dtype=np.uint8)
            present[r, c] = True
        try:
            # repair_eds has its own engine axis ("batched" device sweep
            # vs "scalar" host reference, env-selected) — the codec-level
            # engine hint does not map onto it
            repaired = repair_mod.repair_eds(
                symbols, present,
                list(commitments.row_roots),
                list(commitments.col_roots),
            )
        except repair_mod.BadEncodingError as e:
            raise Rs2dBadEncoding(e.axis, e.index) from e
        return repaired[:k, :k]

    def build_fraud_proof(self, entry, location):
        from celestia_app_tpu.da import fraud

        axis, index = location
        return fraud.generate_befp(entry.eds, axis, index)

    def verify_fraud_proof(self, commitments, proof) -> bool:
        from celestia_app_tpu.da import fraud

        return fraud.verify_befp(commitments, proof)

    def fraud_proof_type(self) -> type:
        from celestia_app_tpu.da import fraud

        return fraud.BadEncodingProof


codec_mod.register(Rs2dNmtCodec())
