"""Data availability sampling: the light-node availability check.

The point of the whole 2D construction (arXiv:1809.09044, SURVEY §1): a
light node holding only the DAH samples s random cells of the EXTENDED
square and demands each share with an NMT proof under its row root. To
make even one original share unrecoverable, a withholding producer must
hide more than (k+1)² of the (2k)² extended cells — over a quarter of the
square — so every honest sample independently catches withholding with
probability > 1/4, and s samples miss with probability < (3/4)^s.

Server side: `BlockProver.prove_cell` answers sample requests from the
cached row trees. Client side: `sample_block` draws coordinates, verifies
every returned (share, proof) against the trusted DAH, and reports the
confidence; any failed or refused sample marks the block unavailable —
the signal that triggers rejection (and, with repair + fraud proofs,
da/repair.py's BadEncodingError path)."""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.utils import telemetry

NS = appconsts.NAMESPACE_SIZE


@dataclasses.dataclass
class SampleReport:
    samples: int
    verified: int
    failed: list[tuple[int, int]]  # coordinates that failed/refused
    confidence: float  # P(withholding would have been caught)

    @property
    def available(self) -> bool:
        return not self.failed


def catch_confidence(s: int, scheme: str = "rs2d-nmt") -> float:
    """Availability confidence after s verified samples, per scheme:
    1 - (1 - alpha)^s with alpha the SCHEME'S catch probability (the
    codec plane's per-scheme threshold, da/codec.py — 2D-RS's
    combinatorial 1/4, CMT's peeling threshold)."""
    from celestia_app_tpu.da import codec as dacodec

    return dacodec.get(scheme).confidence(s)


def samples_for_confidence(target: float = 0.99,
                           scheme: str = "rs2d-nmt") -> int:
    """Smallest s with catch_confidence(s, scheme) >= target."""
    from celestia_app_tpu.da import codec as dacodec

    return dacodec.get(scheme).samples_for_confidence(target)


def withholding_catch_confidence(s: int) -> float:
    """1 - (3/4)^s: the standard 2D-RS DAS bound (a withholding producer
    must hide > 1/4 of extended cells to lose any original share). The
    historical name for the default scheme's instance of
    `catch_confidence`; other schemes have their own thresholds on the
    codec interface."""
    return catch_confidence(s, "rs2d-nmt")


def leaf_namespace(row: int, col: int, share: bytes, k: int) -> bytes:
    from celestia_app_tpu.da.fraud import leaf_ns

    return leaf_ns(row, col, share, k)


def verify_sample(
    dah: DataAvailabilityHeader, row: int, col: int,
    share: bytes, proof,
) -> bool:
    """One sampled cell against the trusted DAH: the proof must cover
    exactly this column under the claimed row's committed root, with the
    pkg/wrapper leaf namespace rule applied."""
    k = len(dah.row_roots) // 2
    if not (0 <= row < 2 * k and 0 <= col < 2 * k):
        return False
    if len(share) != appconsts.SHARE_SIZE:
        return False
    if not (proof.start == col and proof.end == col + 1):
        return False
    ns = leaf_namespace(row, col, share, k)
    return proof.verify(dah.row_roots[row], [(ns, share)])


def sample_block(
    dah: DataAvailabilityHeader,
    fetch_cell,
    n_samples: int,
    rng,
) -> SampleReport:
    """Draw `n_samples` uniform cells and verify each. `fetch_cell(row,
    col) -> (share, proof)` is the network boundary (a BlockProver
    in-process, or any transport); raising/returning junk marks the cell
    failed. `rng` must be the LIGHT NODE's own entropy — predictable
    coordinates let a withholder serve exactly the sampled cells."""
    width = len(dah.row_roots)
    verified = 0
    failed: list[tuple[int, int]] = []
    for _ in range(n_samples):
        row = int(rng.integers(0, width))
        col = int(rng.integers(0, width))
        try:
            share, proof = fetch_cell(row, col)
            ok = verify_sample(dah, row, col, share, proof)
        except Exception:
            # refusals and junk count as failed samples below; the
            # counter separates "peer errored" from "proof rejected"
            telemetry.incr("sampling.fetch_errors")
            ok = False
        if ok:
            verified += 1
        else:
            failed.append((row, col))
    return SampleReport(
        samples=n_samples,
        verified=verified,
        failed=failed,
        confidence=withholding_catch_confidence(n_samples),
    )
