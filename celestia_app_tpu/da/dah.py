"""DataAvailabilityHeader: the per-block commitment to the extended square.

Reference parity: pkg/da/data_availability_header.go —
`DataAvailabilityHeader{RowRoots, ColumnRoots}` (:32-40), `Hash()` = binary
Merkle root over rowRoots || colRoots (:92-108), `ValidateBasic` bounds (:134-162),
`MinDataAvailabilityHeader` (:176-190). The heavy lifting (extension, NMT
hashing, root reduction) happens on device via da/eds.py; this module is the
host-side protocol object.
"""

from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import eds as eds_mod
from celestia_app_tpu.da import shares as shares_mod
from celestia_app_tpu.utils import merkle_host

# Axis bounds on the *extended* square (data_availability_header.go:17-27).
MIN_EXTENDED_SQUARE_WIDTH = 2 * appconsts.MIN_SQUARE_SIZE
MAX_EXTENDED_SQUARE_WIDTH = appconsts.MAX_EXTENDED_SQUARE_WIDTH


@dataclasses.dataclass(frozen=True)
class ExtendedDataSquare:
    """Host handle to a 2k x 2k extended square (kept as one u8 array)."""

    squares: np.ndarray  # (2k, 2k, SHARE_SIZE) uint8

    @property
    def width(self) -> int:
        return self.squares.shape[0]

    def row(self, i: int) -> np.ndarray:
        return self.squares[i]

    def col(self, i: int) -> np.ndarray:
        return self.squares[:, i, :]

    def flattened_ods(self) -> list[bytes]:
        k = self.width // 2
        return [self.squares[r, c].tobytes() for r in range(k) for c in range(k)]


@dataclasses.dataclass(frozen=True)
class DataAvailabilityHeader:
    row_roots: tuple[bytes, ...]  # 90-byte serialized NMT roots
    col_roots: tuple[bytes, ...]

    def hash(self) -> bytes:
        return merkle_host.hash_from_leaves(list(self.row_roots) + list(self.col_roots))

    @property
    def square_size(self) -> int:
        return len(self.row_roots) // 2

    def validate_basic(self) -> None:
        for name, roots in (("row", self.row_roots), ("column", self.col_roots)):
            if len(roots) < MIN_EXTENDED_SQUARE_WIDTH:
                raise ValueError(
                    f"too few {name} roots: {len(roots)} < {MIN_EXTENDED_SQUARE_WIDTH}"
                )
            if len(roots) > MAX_EXTENDED_SQUARE_WIDTH:
                raise ValueError(
                    f"too many {name} roots: {len(roots)} > {MAX_EXTENDED_SQUARE_WIDTH}"
                )
            for r in roots:
                if len(r) != appconsts.NMT_ROOT_SIZE:
                    raise ValueError(f"{name} root has size {len(r)} != 90")
        if len(self.row_roots) != len(self.col_roots):
            raise ValueError("row/column root counts differ")


def square_size_from_share_count(n: int) -> int:
    """Smallest power-of-two k with k*k >= n (da.SquareSize in the reference)."""
    k = 1
    while k * k < n:
        k *= 2
    return k


def shares_to_ods(share_bytes: list[bytes]) -> np.ndarray:
    """Row-major (k, k, 512) array from a perfect-square list of shares."""
    n = len(share_bytes)
    k = int(math.isqrt(n))
    if k * k != n or k & (k - 1):
        raise ValueError(f"share count {n} is not a power-of-two perfect square")
    flat = np.frombuffer(b"".join(share_bytes), dtype=np.uint8)
    return flat.reshape(k, k, appconsts.SHARE_SIZE)


def extend_shares(share_bytes: list[bytes]) -> ExtendedDataSquare:
    """da.ExtendShares equivalent (data_availability_header.go:65-75).

    Extension only — callers that also need roots should use
    `new_dah_from_ods` (one dispatch) instead of paying the NMT hashing here.
    """
    from celestia_app_tpu.ops import rs

    ods = shares_to_ods(share_bytes)
    k = ods.shape[0]
    eds = rs.jitted_extend(k)(jnp.asarray(ods))
    return ExtendedDataSquare(np.asarray(eds))


def new_dah_from_ods(ods: np.ndarray) -> tuple[DataAvailabilityHeader, ExtendedDataSquare, bytes]:
    """One device dispatch: ODS -> (DAH, EDS, data_root)."""
    k = ods.shape[0]
    eds, row_roots, col_roots, data_root = eds_mod.jitted_pipeline(k)(jnp.asarray(ods))
    dah = DataAvailabilityHeader(
        row_roots=tuple(bytes(np.asarray(r)) for r in np.asarray(row_roots)),
        col_roots=tuple(bytes(np.asarray(r)) for r in np.asarray(col_roots)),
    )
    return dah, ExtendedDataSquare(np.asarray(eds)), bytes(np.asarray(data_root))


def min_dah(scheme: str = "rs2d-nmt"):
    """Commitments of the minimum (empty-block) square — one tail-padding
    share — under the given DA scheme: the DataAvailabilityHeader of
    reference :176-190 for the default, the scheme's own commitments
    object otherwise (codec plane, da/codec.py). Either way
    ``.hash()`` is the scheme's genesis/empty data root (pinned per
    scheme in tests/test_codec_iface.py)."""
    if scheme == "rs2d-nmt":
        share = shares_mod.tail_padding_share()
        dah, _, _ = new_dah_from_ods(shares_to_ods([share]))
        return dah
    from celestia_app_tpu.da import codec as dacodec

    return dacodec.get(scheme).min_entry().dah


def min_data_root(scheme: str = "rs2d-nmt") -> bytes:
    """The empty-block data root per scheme (the value an empty-block
    header carries under that scheme)."""
    return min_dah(scheme).hash()
