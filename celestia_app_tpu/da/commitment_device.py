"""Batched blob share commitments on device (BASELINE.md config 3).

Computes the same commitments as da/commitment.py (go-square
`inclusion.CreateCommitment`, x/blob/types/payforblob.go:53) but for every
blob of a block at once. The MMR decomposition gives each blob a handful of
power-of-two-sized NMT subtrees (width ≤ SubtreeWidth ≤ 128); the device
formulation groups all subtrees of equal size s across all blobs into one
(T, s, 512) batched NMT launch — at most 8 launches per block regardless of
blob count, each a large vectorized SHA-256 workload (the Pallas kernel on
TPU). The final per-blob MMR root is a host-side Merkle fold over the ≤
log2-many 90-byte subtree roots — negligible hashing.

Shape bucketing: the per-size tree count T is padded to the next power of
two so repeated blocks reuse compiled programs; padding trees hash zeros and
are discarded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import commitment as commitment_mod
from celestia_app_tpu.da import shares as shares_mod
from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.ops import nmt
from celestia_app_tpu.utils import merkle_host

NS = appconsts.NAMESPACE_SIZE
SHARE = appconsts.SHARE_SIZE


# jit caches compiled programs per (t_padded, s, 512) input shape.
_jitted_roots = jax.jit(nmt.nmt_roots)


def commitments_device(
    blobs: list[Blob], subtree_root_threshold: int
) -> list[bytes]:
    """Share commitments for all blobs, batched by subtree size on device."""
    if not blobs:
        return []
    # Host: split each blob into shares and decompose into MMR chunks.
    plans: list[list[tuple[int, int]]] = []  # per blob: [(size, group_slot)]
    groups: dict[int, list[tuple[np.ndarray, bytes]]] = {}
    for blob in blobs:
        blob_shares = shares_mod.split_blob(
            blob.namespace, blob.data, blob.share_version
        )
        raw = np.frombuffer(
            b"".join(s.raw for s in blob_shares), dtype=np.uint8
        ).reshape(len(blob_shares), SHARE)
        width = commitment_mod.subtree_width(
            len(blob_shares), subtree_root_threshold
        )
        sizes = commitment_mod.merkle_mountain_range_sizes(
            len(blob_shares), width
        )
        plan = []
        cursor = 0
        for size in sizes:
            slot = len(groups.setdefault(size, []))
            groups[size].append((raw[cursor : cursor + size], blob.namespace.raw))
            plan.append((size, slot))
            cursor += size
        plans.append(plan)

    # Device: one batched launch per distinct subtree size.
    roots_by_size: dict[int, np.ndarray] = {}
    for size, chunks in groups.items():
        t = len(chunks)
        t_pad = commitment_mod.round_up_pow2(t)
        leaf_data = np.zeros((t_pad, size, SHARE), dtype=np.uint8)
        leaf_ns = np.zeros((t_pad, size, NS), dtype=np.uint8)
        for i, (chunk, ns_raw) in enumerate(chunks):
            leaf_data[i] = chunk
            leaf_ns[i] = np.frombuffer(ns_raw, dtype=np.uint8)
        out = _jitted_roots(jnp.asarray(leaf_ns), jnp.asarray(leaf_data))
        roots_by_size[size] = np.asarray(out)[:t]

    # Host: fold each blob's ordered subtree roots into its commitment.
    out_commitments = []
    for plan in plans:
        subtree_roots = [
            bytes(roots_by_size[size][slot]) for size, slot in plan
        ]
        out_commitments.append(merkle_host.hash_from_leaves(subtree_roots))
    return out_commitments
