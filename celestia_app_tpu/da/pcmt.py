"""Polar Coded Merkle Tree: the third DA commitment scheme
(arXiv:2201.07287, frozen-set design per arXiv:2301.08295).

Where the CMT (da/cmt.py) codes each tree layer with a sparse LDGM code,
the PCMT codes it with a polar code and commits the code's *pruned
factor graph* (ops/polar.py): every committed class — data, coded
output, and surviving intermediate stage value — is hashed, and the
degree-3 XOR checks between classes are the parity equations that give
peeling repair and one-violated-equation fraud proofs. The layering
mirrors the CMT: hash the base layer's committed classes, batch the
hashes into data symbols of the next layer, polar-code THAT layer, and
repeat until a layer has <= ROOT_MAX committed classes, whose hash list
is published outright as the block commitment; the 32-byte data root is
one sha256 over the parameterized root hash list (FORMATS §16.5).

One structural departure from the CMT's flat q=8 hash batching, forced
by measurement: the pruned polar graph commits ~2.4-7.3 classes per
data symbol *growing with log n* (ops/polar.py geometry; the factor-
graph interior is what buys polar its detection economics), so a flat
q=8 batch would never telescope — C_j/8 >= D_j from k=16 up. PCMT
therefore batches Q=64 hashes per parent data symbol and aggregates
each batch with a depth-6 binary Merkle subtree whose ROOT (32 bytes)
is the parent symbol. A sample proof step then carries 6 sibling
hashes (192 B) instead of 63 (2016 B), and the layer recursion shrinks
by ~Q/(C/D) ≈ 9-13x per step — at k=128 the tree telescopes in a few
layers and a sample proof stays smaller than both other schemes
(`bench.py --codec` measures the three-way).

Sampling threshold: light clients draw uniformly over the C_0 BASE
committed classes (each sample's proof carries one batch-subtree path
and one committed class of every upper layer — the CMT trick, polar
flavored). CATCH_BP declares 1/4: the pruned-graph peeling decoder
recovers from a uniformly random 25-30% erasure of the committed
classes with zero failures across 60 seeded trials at every deployed
size (D = 16 through 16384, measured before this module was written),
so a withholder must hide beyond that fraction to threaten recovery.
Like the CMT's, this threshold is empirical-random, not combinatorial —
the paper's informed frozen-set design *shrinks* stopping sets rather
than excluding them — which is exactly the trade `bench.py --scenario`
judges under identical seeded attacks.

Engine gating mirrors da/cmt.py: "device" demands jax (polar bit-matmul
butterflies + batched sha256 on device), "host" never touches it,
"auto" degrades loudly; the engines are pinned bit-identical in
tests/test_codec_iface.py, including SC-decode on inconsistent fraud
inputs.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.da.cmt import _hash_symbols
from celestia_app_tpu.da.shares import uvarint
from celestia_app_tpu.ops import polar

# hash-batch width: Q hashes of layer j aggregate (via a depth-LOG2Q
# binary subtree) into ONE 32-byte data symbol of layer j+1
Q = 64
LOG2Q = 6
HASH_BYTES = 32
# stop coding when a layer commits <= ROOT_MAX classes; its hash list
# IS the published commitment (same 16 KB ceiling as the CMT's)
ROOT_MAX = 512
DOMAIN = b"PCMT\x01"


class PcmtBadEncodingError(codec_mod.BadEncodingDetected):
    """A degree-3 check over commitment-verified classes is violated:
    the producer committed an invalid codeword at (layer, equation)."""

    def __init__(self, layer: int, equation: int):
        super().__init__(
            (layer, equation),
            f"bad PCMT encoding: layer {layer} equation {equation}")
        self.layer = layer
        self.equation = equation


def layer_plan(k: int) -> list[tuple[int, int]]:
    """[(n_data, sym_bytes)] per layer, base first — a pure function of
    k (the committed-class counts come from ops/polar.geometry, itself
    a pure function of n_data)."""
    plan = [(k * k, appconsts.SHARE_SIZE)]
    while polar.geometry(plan[-1][0]).C > ROOT_MAX:
        c = polar.geometry(plan[-1][0]).C
        plan.append((-(-c // Q), HASH_BYTES))
    return plan


def _layer_c(plan: list[tuple[int, int]], layer: int) -> int:
    return polar.geometry(plan[layer][0]).C


@dataclasses.dataclass(frozen=True)
class PcmtCommitments:
    """The per-block commitment a light client holds: parameters + the
    top layer's hash list. ``hash()`` is the header's data root."""

    k: int
    root_hashes: tuple[bytes, ...]

    def hash(self) -> bytes:
        out = bytearray(DOMAIN)
        out += uvarint(self.k) + uvarint(Q) + uvarint(ROOT_MAX)
        out += uvarint(len(self.root_hashes))
        for h in self.root_hashes:
            out += h
        return hashlib.sha256(bytes(out)).digest()

    @property
    def plan(self) -> list[tuple[int, int]]:
        return layer_plan(self.k)

    @property
    def n_base(self) -> int:
        """Base-layer committed class count — the sample space size."""
        return polar.geometry(self.k * self.k).C

    def validate_basic(self) -> None:
        plan = self.plan
        want = _layer_c(plan, len(plan) - 1)
        if len(self.root_hashes) != want:
            raise codec_mod.CodecError(
                f"root hash count {len(self.root_hashes)} != {want} "
                f"for k={self.k}")
        for h in self.root_hashes:
            if len(h) != HASH_BYTES:
                raise codec_mod.CodecError("root hash has size != 32")


class PcmtEntry:
    """One encoded block: every layer's committed class values, hash
    lists, and batch subtrees. Duck-compatible with the block plane's
    EdsCacheEntry surface (da/edscache.py)."""

    scheme = codec_mod.PCMT_NAME

    def __init__(self, commitments: PcmtCommitments,
                 layers: list[np.ndarray],
                 hash_lists: list[np.ndarray],
                 subtrees: list[list[np.ndarray]],
                 ods: np.ndarray):
        self.commitments = commitments
        self.layers = layers  # [(C_j, S_j) u8 committed values]
        self.hash_lists = hash_lists  # [(C_j, 32) u8]
        # per non-top layer: LOG2Q+1 levels, level 0 = zero-padded
        # leaf hashes (Q*D_{j+1}, 32), level LOG2Q = batch roots
        self.subtrees = subtrees
        self._ods = np.ascontiguousarray(ods, dtype=np.uint8)
        self.data_root = commitments.hash()
        self.eds = None

    @property
    def dah(self):
        return self.commitments

    @property
    def k(self) -> int:
        return self.commitments.k

    def ods(self) -> np.ndarray:
        k = self.commitments.k
        return self._ods.reshape(k, k, appconsts.SHARE_SIZE)

    def warm(self, engine: str = "auto") -> None:
        """Proof machinery (hash lists + subtrees) is built at encode —
        nothing to pre-build."""


def _subtree_levels(hashes: np.ndarray, n_batches: int,
                    engine: str) -> list[np.ndarray]:
    """Aggregate a layer's hash list into n_batches Q-wide binary
    Merkle subtrees; level 0 is the zero-padded leaves, the last level
    the (n_batches, 32) batch roots — layer j+1's data symbols."""
    padded = np.zeros((n_batches * Q, HASH_BYTES), dtype=np.uint8)
    padded[: len(hashes)] = hashes
    levels = [padded]
    cur = padded
    for _ in range(LOG2Q):
        cur = _hash_symbols(cur.reshape(-1, 2 * HASH_BYTES), engine)
        levels.append(cur)
    return levels


def build_from_base(ods: np.ndarray, base_vals: np.ndarray,
                    engine: str = "auto") -> PcmtEntry:
    """Hash-and-aggregate pipeline from given BASE committed values up
    to the root hash list. Split out of build_layers so the malicious
    fixture (testing/malicious.py) can grow a self-consistent tree over
    a corrupt base codeword."""
    k = ods.shape[0]
    plan = layer_plan(k)
    layers = [base_vals]
    hash_lists: list[np.ndarray] = []
    subtrees: list[list[np.ndarray]] = []
    vals = base_vals
    for j in range(len(plan)):
        hashes = _hash_symbols(vals, engine)
        hash_lists.append(hashes)
        if j + 1 < len(plan):
            levels = _subtree_levels(hashes, plan[j + 1][0], engine)
            subtrees.append(levels)
            vals = polar.encode(levels[-1], engine)
            layers.append(vals)
    commitments = PcmtCommitments(
        k=k, root_hashes=tuple(bytes(h) for h in hash_lists[-1]))
    return PcmtEntry(commitments, layers, hash_lists, subtrees, ods)


def build_layers(ods: np.ndarray, engine: str = "auto") -> PcmtEntry:
    """The encode pipeline: ODS -> PcmtEntry."""
    k = ods.shape[0]
    data = np.ascontiguousarray(ods, dtype=np.uint8).reshape(
        k * k, appconsts.SHARE_SIZE)
    return build_from_base(ods, polar.encode(data, engine), engine)


# ---------------------------------------------------------------------------
# sample proofs
# ---------------------------------------------------------------------------


def _b64(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


def open_sample(entry: PcmtEntry, layer: int, index: int) -> dict:
    """Serve committed class (layer, index) with its layered inclusion
    proof: LOG2Q batch-subtree siblings per step; the recomputed batch
    root IS the parent layer's data symbol, whose committed position is
    derived from the (deterministic) parent geometry."""
    plan = entry.commitments.plan
    if not 0 <= layer < len(plan):
        raise codec_mod.CodecError(f"no PCMT layer {layer}")
    if not 0 <= index < _layer_c(plan, layer):
        raise codec_mod.CodecError(
            f"class {index} outside layer {layer} "
            f"({_layer_c(plan, layer)} classes)")
    steps: list[list[str]] = []
    pos = index
    for j in range(layer, len(plan) - 1):
        levels = entry.subtrees[j]
        idx = pos
        sibs = []
        for lvl in range(LOG2Q):
            sibs.append(bytes(levels[lvl][idx ^ 1]))
            idx >>= 1
        steps.append([_b64(s) for s in sibs])
        parent_geom = polar.geometry(plan[j + 1][0])
        pos = int(parent_geom.data_class[pos // Q])
    return {
        "layer": layer,
        "index": index,
        "symbol": _b64(bytes(entry.layers[layer][index])),
        "steps": steps,
    }


def verify_sample(commitments: PcmtCommitments, doc: dict):
    """Check one served sample doc. Returns ((layer, index), symbol
    bytes) when the symbol is committed at that position, None on ANY
    failure (malformed, wrong size, wrong path, unbound root)."""
    import base64

    try:
        layer = int(doc["layer"])
        index = int(doc["index"])
        symbol = base64.b64decode(doc["symbol"])
        steps = doc["steps"]
    except (KeyError, TypeError, ValueError):
        return None
    plan = commitments.plan
    if not 0 <= layer < len(plan):
        return None
    if not 0 <= index < _layer_c(plan, layer) \
            or len(symbol) != plan[layer][1]:
        return None
    if not isinstance(steps, list) or len(steps) != len(plan) - 1 - layer:
        return None
    h = hashlib.sha256(symbol).digest()
    pos = index
    try:
        for j, step in zip(range(layer, len(plan) - 1), steps):
            if len(step) != LOG2Q:
                return None
            sibs = [base64.b64decode(s) for s in step]
            if any(len(s) != HASH_BYTES for s in sibs):
                return None
            idx = pos
            for sib in sibs:
                h = hashlib.sha256(
                    sib + h if idx & 1 else h + sib).digest()
                idx >>= 1
            # h is now the batch root == the parent data symbol
            parent_geom = polar.geometry(plan[j + 1][0])
            pos = int(parent_geom.data_class[pos // Q])
            h = hashlib.sha256(h).digest()
    except (TypeError, ValueError):
        return None
    if h != commitments.root_hashes[pos]:
        return None
    return (layer, index), symbol


def sample_wire_bytes(commitments: PcmtCommitments, doc: dict) -> int:
    """Canonical binary size of the proof (FORMATS §16.6): varint layer
    + varint index + symbol + LOG2Q*32 per step."""
    plan = commitments.plan
    layer = int(doc["layer"])
    return (len(uvarint(layer)) + len(uvarint(int(doc["index"])))
            + plan[layer][1]
            + len(doc["steps"]) * LOG2Q * HASH_BYTES)


# ---------------------------------------------------------------------------
# repair (SC peeling) + incorrect-coding fraud proofs
# ---------------------------------------------------------------------------


def repair(commitments: PcmtCommitments, samples: dict,
           engine: str = "auto") -> np.ndarray:
    """Reconstruct the ODS from verified samples {(layer, index):
    bytes}. Base-layer classes feed the SC peeling decoder; a violated
    check whose members were ALL served with proofs raises
    PcmtBadEncodingError (the fraud location a light node can prove
    from served symbols alone). A peel that stalls before recovering
    every data class raises ValueError (below threshold: withholding,
    not provably mis-coded). On success the recovered data's full
    recommitment must reproduce the committed root — a mismatch means
    an upper layer was mis-coded (not provable from base samples
    alone)."""
    plan = commitments.plan
    k = commitments.k
    d0, s0 = plan[0]
    g = polar.geometry(d0)
    base = {i: b for (layer, i), b in samples.items() if layer == 0}
    if not base:
        raise ValueError("no base-layer samples to reconstruct from")
    vals = np.zeros((g.C, s0), dtype=np.uint8)
    known = np.zeros(g.C, dtype=bool)
    for i, b in sorted(base.items()):
        vals[i] = np.frombuffer(b, dtype=np.uint8)
        known[i] = True
    vals, known, _sweeps = polar.peel(d0, vals, known, engine)
    violated = polar.check_equations(d0, vals, known)
    for eq in violated:
        members = equation_members(commitments, 0, int(eq))
        if all(m in base for m in members):
            raise PcmtBadEncodingError(0, int(eq))
    if violated.size:
        raise ValueError(
            f"PCMT layer 0 inconsistent at equations "
            f"{violated[:4].tolist()} but members were not all served")
    if not known[g.data_class].all():
        raise ValueError(
            f"below peeling threshold: "
            f"{int((~known[g.data_class]).sum())} of {d0} data classes "
            f"unrecovered")
    ods = vals[g.data_class].reshape(k, k, appconsts.SHARE_SIZE)
    rebuilt = build_layers(ods, engine)
    if rebuilt.data_root != commitments.hash():
        raise ValueError(
            "recovered data does not reproduce the committed root: an "
            "upper PCMT layer was mis-coded (fetch its symbols to "
            "prove)")
    return ods


def equation_members(commitments: PcmtCommitments, layer: int,
                     equation: int) -> list[int]:
    """Committed-class indices of one check's three members at a layer
    (deterministic pruned-graph construction) — the exact member order
    a PcmtFraudProof must carry."""
    g = polar.geometry(commitments.plan[layer][0])
    return [int(x) for x in g.checks[equation]]


@dataclasses.dataclass(frozen=True)
class PcmtSymbolWithProof:
    index: int  # committed-class index within the equation's layer
    symbol: bytes
    doc: dict  # the served sample doc (carries the layered proof)


@dataclasses.dataclass(frozen=True)
class PcmtFraudProof:
    """One violated degree-3 check: three members, each carried with
    its inclusion proof. O(1) in the block size."""

    layer: int
    equation: int
    members: tuple[PcmtSymbolWithProof, ...]


def generate_fraud(entry: PcmtEntry, layer: int,
                   equation: int) -> PcmtFraudProof:
    """Full-node side: assemble the proof from an entry it holds."""
    members = equation_members(entry.commitments, layer, equation)
    return PcmtFraudProof(
        layer=layer,
        equation=equation,
        members=tuple(
            PcmtSymbolWithProof(
                index=m,
                symbol=bytes(entry.layers[layer][m]),
                doc=open_sample(entry, layer, m),
            )
            for m in members
        ),
    )


def verify_fraud(commitments: PcmtCommitments,
                 proof: PcmtFraudProof) -> bool:
    """True iff the proof demonstrates the commitments commit an
    invalid codeword: every member symbol verifies against the
    commitments AT the positions the (deterministically recomputed)
    check demands, and the three members XOR to non-zero. False for
    malformed proofs and for honest blocks."""
    try:
        plan = commitments.plan
        if not 0 <= proof.layer < len(plan):
            return False
        g = polar.geometry(plan[proof.layer][0])
        if not 0 <= proof.equation < len(g.checks):
            return False
        expected = equation_members(commitments, proof.layer,
                                    proof.equation)
        if [m.index for m in proof.members] != expected:
            return False
        syms: list[bytes] = []
        for m in proof.members:
            got = verify_sample(commitments, m.doc)
            if got is None:
                return False
            (layer, index), symbol = got
            if layer != proof.layer or index != m.index \
                    or symbol != m.symbol:
                return False
            syms.append(symbol)
        acc = (np.frombuffer(syms[0], dtype=np.uint8)
               ^ np.frombuffer(syms[1], dtype=np.uint8))
        return not np.array_equal(
            acc, np.frombuffer(syms[2], dtype=np.uint8))
    except (KeyError, TypeError, ValueError, IndexError,
            AttributeError):
        # AttributeError: a proof routed against the wrong scheme's
        # commitments object is malformed input, not a crash
        return False


# ---------------------------------------------------------------------------
# the Codec implementation
# ---------------------------------------------------------------------------


class PcmtCodec(codec_mod.Codec):
    scheme_id = codec_mod.SCHEME_PCMT
    name = codec_mod.PCMT_NAME
    CATCH_BP = 2500  # declared sampling threshold (module docstring)

    def compute_entry(self, ods: np.ndarray,
                      engine: str = "auto") -> PcmtEntry:
        from celestia_app_tpu.da import edscache

        return edscache.compute_entry(ods, engine, scheme=self.name)

    def _encode_impl(self, ods: np.ndarray,
                     engine: str = "auto") -> PcmtEntry:
        return build_layers(ods, engine)

    def commitments_doc(self, entry) -> dict:
        c = entry.dah
        return {
            "scheme": self.name,
            "k": c.k,
            "q": Q,
            "root_max": ROOT_MAX,
            "root_hashes": [h.hex() for h in c.root_hashes],
            "data_root": entry.data_root.hex(),
        }

    def commitments_from_doc(self, doc: dict, data_root_hex: str,
                             square_size: int) -> PcmtCommitments:
        try:
            if int(doc["q"]) != Q or int(doc["root_max"]) != ROOT_MAX:
                raise codec_mod.CodecError(
                    "served PCMT parameters differ from this build's")
            c = PcmtCommitments(
                k=int(doc["k"]),
                root_hashes=tuple(
                    bytes.fromhex(h) for h in doc["root_hashes"]),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise codec_mod.CodecError(
                f"malformed PCMT commitments doc: {e}") from None
        c.validate_basic()
        if c.k != square_size:
            raise codec_mod.CodecError(
                "served PCMT k contradicts the header square size")
        if c.hash().hex() != data_root_hex:
            raise codec_mod.CodecError(
                "served PCMT commitments do not bind to the data root")
        return c

    def sample_space(self, commitments) -> list[tuple[int, int]]:
        # base layer only: each sample's proof carries one class of
        # every upper layer, implicitly sampling them (the CMT trick)
        return [(0, i) for i in range(commitments.n_base)]

    def open_sample(self, entry, cell: tuple[int, int]) -> dict:
        return open_sample(entry, cell[0], cell[1])

    def verify_sample(self, commitments, doc: dict):
        return verify_sample(commitments, doc)

    def sample_wire_bytes(self, doc: dict, commitments=None) -> int:
        if commitments is None:
            raise codec_mod.CodecError(
                "pcmt wire size needs commitments")
        return sample_wire_bytes(commitments, doc)

    def hashes_per_sample_verify(self, commitments) -> int:
        # the symbol hash, then LOG2Q subtree nodes + one parent-symbol
        # hash per layer step
        return 1 + (len(commitments.plan) - 1) * (LOG2Q + 1)

    def repair(self, commitments, samples: dict,
               engine: str = "auto") -> np.ndarray:
        return repair(commitments, samples, engine)

    def build_fraud_proof(self, entry, location) -> PcmtFraudProof:
        layer, equation = location
        return generate_fraud(entry, layer, equation)

    def verify_fraud_proof(self, commitments, proof) -> bool:
        return verify_fraud(commitments, proof)

    def fraud_proof_type(self) -> type:
        return PcmtFraudProof

    def fraud_cells(self, commitments, location) -> list[tuple]:
        layer, equation = location
        return [(layer, m)
                for m in equation_members(commitments, layer, equation)]

    def fraud_proof_from_members(self, commitments, location,
                                 members: list[tuple]) -> PcmtFraudProof:
        layer, equation = location
        return PcmtFraudProof(
            layer=layer, equation=equation,
            members=tuple(
                PcmtSymbolWithProof(index=cell[1], symbol=payload,
                                    doc=doc)
                for cell, payload, doc in members
            ),
        )


codec_mod.register(PcmtCodec())
