"""Block plane: the extend-once lifecycle's content-addressed EDS/DAH cache.

The node used to pay the full RS-extend + NMT pipeline up to THREE times
per height: once at PrepareProposal (chain/app.py, result discarded), once
at ProcessProposal (the proposer re-validating its own block, and every
follower validating the gossiped one), and once more when the first light
client sampled the height (chain/query.build_prover rebuilding the square
from raw txs). Amortizing the RS/commitment work across protocol phases is
exactly the cost lever arXiv:2201.08261 optimizes for RS-based DA
protocols; this module is that amortization:

- **Content addressing.** Entries are keyed by ``sha256(ODS share bytes)``
  — a pure function of the data square itself, never of a height or a
  header field a peer claimed. A follower validating a gossiped proposal
  and the proposer validating its own construct the identical ODS from the
  txs, so both hit the same entry; a Byzantine header can never poison the
  cache, because the cached value is a pure function of the key (a wrong
  ``data_hash`` still fails the header comparison — the cache only changes
  who pays for recomputing the truth).

- **Engine-gated, bit-identical.** ``compute_entry`` is THE one
  ODS -> (EDS, row/col roots, data root) implementation for both the
  device path (da/eds.jitted_pipeline, one fused dispatch) and the host
  path (utils/fast_host BLAS+hashlib) — previously copy-pasted between
  ``App._pipeline``, ``chain/query.build_prover``, and
  ``das/server._build_prover``. The two engines are pinned byte-identical
  (tests/test_fast_host.py, tests/test_edscache.py), so a cache populated
  by either serves the other.

- **Lazy provers + background warmup.** Each entry carries its
  BlockProver (and the transposed col-axis prover BEFP escalation needs)
  built at most once, on demand, under the entry's own lock — or ahead of
  demand by ``ProverWarmer``, the single coalescing daemon thread
  ``App.commit`` hands each committed entry to. The warmer builds the
  provers and fans the entry out to registered DAS serving planes
  (``das/server.SampleCore.seed_cache_entry``) WITHOUT holding any
  service/consensus lock, so the first light-client sample after a commit
  is pure index arithmetic instead of a rebuild + re-extend.

- **Mesh engine + device residency (the mesh plane).** ``compute_entry``
  gains a fourth engine: ``"mesh"`` dispatches through the sharded
  shard_map pipeline (parallel/mesh_engine.py — k rows split over the
  ``seq`` ICI axis, bit-identical to the single-device program), and the
  auto/device engines route any square of ``k >= CELESTIA_MESH_MIN_K``
  (default 256) there automatically. Mesh-built entries are
  ``DeviceEntry``: the EDS and (once warmed) the NMT level arrays stay
  on device, and host bytes materialize lazily — only when a proof or
  serve path actually needs them — each materialization counting
  ``edscache.host_crossings``. The produce path's batched dispatch
  (chain/producer.py) inserts the same entry type, so an
  extend→commit→prover-warm chain hands device arrays, not bytes,
  between stages.

Telemetry: ``da.extend_runs`` (every real pipeline dispatch),
``edscache.{hits,misses,evictions,seeded}``, ``edscache.warm_coalesced``
(a pending warm superseded by a newer commit), ``edscache.warm_errors``,
``edscache.host_crossings`` (device-resident arrays materialized to
host). Wire/metric formats in docs/FORMATS.md §14 and §18; design in
docs/DESIGN.md "The block plane" and "The mesh plane".
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
import weakref

import numpy as np

from celestia_app_tpu import obs
from celestia_app_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_app_tpu.obs import xfer
from celestia_app_tpu.utils import telemetry

# bounded LRU: at k=128 one entry holds ~32 MB of EDS plus ~24 MB of lazy
# row+col level arrays once warmed, so the default stays small — the
# lifecycle only ever needs the in-flight height plus a short serving tail
DEFAULT_MAX_ENTRIES = int(os.environ.get("CELESTIA_EDSCACHE_ENTRIES", "4"))

# the entry-count cap alone stops bounding memory once big squares are
# admitted: a k=512 entry is ~512 MB of EDS before levels, so four of
# them would silently pin >2 GB. The LRU is therefore ALSO bytes-aware:
# entries are charged a conservative static estimate (EDS bytes x2 —
# the x2 covers the row+col level arrays a warmed entry carries; see
# entry_nbytes) against CELESTIA_EDSCACHE_BYTES, and eviction runs while
# EITHER cap is exceeded. The newest entry is always retained even when
# it alone exceeds the byte budget (the in-flight height must be
# servable); at k <= 128 the default budget never binds, so historical
# behavior is unchanged.
DEFAULT_MAX_BYTES = int(os.environ.get("CELESTIA_EDSCACHE_BYTES",
                                       str(1 << 30)))


def entry_nbytes(entry) -> int:
    """Conservative byte charge for one cached entry: (2k)^2 x 512 of
    EDS, doubled for the per-orientation NMT level arrays a warmed entry
    holds (leaf level alone is (2k)^2 x 90 per orientation; inner levels
    add half that again). Entry types that know better (da/cmt.CmtEntry
    and friends) can expose their own ``nbytes()``."""
    own = getattr(entry, "nbytes", None)
    if callable(own):
        return int(own())
    two_k = 2 * entry.k
    return two_k * two_k * 512 * 2


def cache_key(ods: np.ndarray, scheme: str = "rs2d-nmt") -> bytes:
    """Content address of an original data square: sha256 over the ODS
    share bytes in row-major order. Shares are fixed-size (512 B) and the
    count is k*k, so the byte string determines the geometry — two squares
    collide iff they are the same square.

    Zero-copy: the usual producers (dah.shares_to_ods) hand over C-order
    arrays, so hashing goes straight over the buffer (`arr.data`) with no
    8 MB `.tobytes()` staging copy at k=128; `ascontiguousarray` is a
    no-op then and only copies for exotic layouts. The hash itself is
    single-digit ms at k=128 (OpenSSL SHA-NI) against the 2-3 full
    extend+NMT dispatches per height it deduplicates.

    Non-default codec-plane schemes (da/codec.py) prefix their name so
    the same square encoded under two schemes occupies two entries;
    the default scheme's keys stay byte-identical to pre-plane keys."""
    arr = np.ascontiguousarray(ods)
    h = hashlib.sha256()
    if scheme != "rs2d-nmt":
        h.update(scheme.encode() + b"\x00")
    h.update(arr.data)
    return h.digest()


class EdsCacheEntry:
    """One cached extension: ``(eds, row_roots, col_roots, data_root)``
    plus the lazily-built proof machinery. The extension fields are
    immutable after construction; the provers build at most once, under
    the entry's own lock (never a service/consensus lock), so concurrent
    samplers of a fresh entry pay one level pass between them.

    The ``scheme``/``k``/``warm`` surface is the codec plane's common
    entry contract (da/codec.py): non-default schemes cache their own
    entry types (e.g. da/cmt.CmtEntry) in the same EdsCache."""

    scheme = "rs2d-nmt"

    def __init__(self, eds: ExtendedDataSquare,
                 dah: DataAvailabilityHeader, data_root: bytes,
                 levels=None):
        self._eds = eds
        self.dah = dah
        self.data_root = data_root
        # host-computed row NMT levels (utils/fast_host shape), carried
        # when the host pipeline produced them anyway; None on the device
        # path, where the prover's jitted level pass recomputes them
        self.levels = levels
        # one lock PER prover: a sampler needing the (already-built) row
        # prover must never queue behind the warmer's in-progress col
        # level pass — the two builds are independent
        self._row_lock = threading.Lock()
        self._col_lock = threading.Lock()
        self._prover = None  # guarded-by: _row_lock
        self._col_prover = None  # guarded-by: _col_lock

    @property
    def eds(self) -> ExtendedDataSquare:
        """The host extended square. A plain attribute read here; the
        device-resident subclass overrides this with a lazy,
        crossing-counted materialization."""
        return self._eds

    def residency(self) -> str:
        """Where the entry's square bytes live: "host" for the classic
        entry; the device-resident subclass reports "device" until a
        proof/serve path materializes, then "device+host"."""
        return "host"

    def get_prover(self, engine: str = "auto"):
        """The row-axis BlockProver, built once (engine-gated)."""
        # the build-once lock EXISTS to serialize this first build (jit
        # compile included); samplers queue here instead of rebuilding
        with self._row_lock:  # lint: disable=blocking-under-lock
            if self._prover is None:
                self._prover = build_block_prover(
                    self.eds, self.dah, engine, levels=self.levels
                )
            return self._prover

    def _transposed_square(self):
        """(eds_t, dah_t): the transposed square whose ROW trees are
        this square's column trees — the leaf-namespace rule is
        transpose-invariant (parity iff outside Q0 survives
        (r,c)->(c,r)), so a col-axis prover is a row prover over this
        pair. The ONE copy of the construction both col-prover builds
        (base and device-resident) share."""
        # the HOST entry class: self.eds.squares is numpy here (the
        # device-resident twin overrides the col-prover path wholesale)
        eds_t = ExtendedDataSquare(
            np.ascontiguousarray(np.swapaxes(self.eds.squares, 0, 1))  # lint: disable=xfer-reach
        )
        dah_t = DataAvailabilityHeader(
            row_roots=self.dah.col_roots,
            col_roots=self.dah.row_roots,
        )
        return eds_t, dah_t

    def get_col_prover(self, engine: str = "auto"):
        """Column-axis prover (BEFP escalation serving): see
        _transposed_square — same batched level pass, no per-cell
        hashing."""
        # build-once serialization, same reasoning as get_prover
        with self._col_lock:  # lint: disable=blocking-under-lock
            if self._col_prover is None:
                t0 = telemetry.start_timer()
                eds_t, dah_t = self._transposed_square()
                self._col_prover = build_block_prover(eds_t, dah_t, engine)
                telemetry.measure_since("das.col_tree_build", t0)
            return self._col_prover

    @property
    def k(self) -> int:
        return self.eds.width // 2

    def warm(self, engine: str = "auto") -> None:
        """Pre-build both provers (the warmer's per-scheme hook)."""
        self.get_prover(engine)
        self.get_col_prover(engine)

    def warmed(self) -> bool:
        # fixed acquisition order (row, then col) — no other path nests
        # the two locks, so no inversion is possible
        with self._row_lock:
            row_ready = self._prover is not None
        with self._col_lock:
            return row_ready and self._col_prover is not None


class DeviceEntry(EdsCacheEntry):
    """A mesh-plane entry whose big arrays live on device.

    Construction hands over the device EDS (sharded over the mesh when
    the sharded pipeline built it) plus the HOST commitments — axis
    roots and data root are what every protocol phase compares, and at
    4k x 90 B they are not worth keeping remote. Everything else obeys
    the device-residency contract:

    - ``warm()`` runs the row+col NMT *level* passes on device and keeps
      the results there — the prover-warm stage of a batched produce
      chain never touches the host.
    - ``.eds`` / the provers materialize host bytes lazily, only when a
      proof or serve path actually needs them; every device->host array
      fetch counts ``edscache.host_crossings`` (the --mesh bench pins
      this at 0/block on the warmed produce path).

    Locking mirrors the base class's per-prover discipline: ONE lock
    per lazily-built resource (host EDS, row levels, col levels), so a
    sampler fetching the square never queues behind the warmer's
    in-progress col-orientation level pass (a first-call jit compile).
    Lock order: a prover lock (``_row_lock``/``_col_lock``, inherited)
    may take a resource lock inside it; the resource locks never nest
    with each other or with the prover locks, so no inversion is
    possible."""

    def __init__(self, eds_dev, dah: DataAvailabilityHeader,
                 data_root: bytes):
        super().__init__(None, dah, data_root)
        self._eds_dev = eds_dev  # device (2k, 2k, 512), possibly sharded
        # _eds (inherited) is the lazily-materialized host square;
        # device-side NMT level stacks, row and col orientation
        self._eds_lock = threading.Lock()
        self._levels_lock = threading.Lock()
        self._col_levels_lock = threading.Lock()
        self._levels_dev = None  # guarded-by: _levels_lock
        self._col_levels_dev = None  # guarded-by: _col_levels_lock

    @property
    def k(self) -> int:
        # geometry from the device array's shape — never a host fetch
        return int(self._eds_dev.shape[0]) // 2

    def residency(self) -> str:
        # deliberately lock-free: this is availability-record telemetry
        # read per served response, and taking _eds_lock here would
        # stall every note behind an in-progress (possibly hundreds of
        # MB) materialization. The race is benign and one-directional:
        # _eds only ever goes None -> set
        return "device+host" if self._eds is not None else "device"

    @staticmethod
    def _crossing(what: str) -> None:
        telemetry.incr("edscache.host_crossings")
        telemetry.incr(f"edscache.host_crossings.{what}")

    @property
    def eds(self) -> ExtendedDataSquare:
        """Host square bytes, materialized on first need (one counted
        crossing; later reads are free)."""
        with self._eds_lock:
            if self._eds is None:
                t0 = telemetry.start_timer()
                self._eds = ExtendedDataSquare(
                    xfer.to_host(self._eds_dev, "edscache.eds")
                )
                self._crossing("eds")
                telemetry.measure_since("edscache.host_fetch", t0)
            return self._eds

    def _device_levels(self, col: bool):
        """Device NMT levels for one orientation, computed (and kept)
        on device at most once — the warm stage's unit of work. Each
        orientation has its own build-once lock (same policy as
        get_prover): concurrent warmers/provers pay one level pass (jit
        compile included) between them — and ONLY between them, the
        other orientation and the EDS fetch never queue here."""
        return self._device_col_levels() if col else \
            self._device_row_levels()

    def _device_row_levels(self):
        from celestia_app_tpu.da import proof_device

        # build-once serialization (see _device_levels)
        with self._levels_lock:  # lint: disable=blocking-under-lock
            if self._levels_dev is None:
                self._levels_dev = proof_device._jitted_row_levels(
                    self.k)(self._eds_dev)
            return self._levels_dev

    def _device_col_levels(self):
        import jax.numpy as jnp

        from celestia_app_tpu.da import proof_device

        # build-once serialization (see _device_levels)
        with self._col_levels_lock:  # lint: disable=blocking-under-lock
            if self._col_levels_dev is None:
                arr = jnp.swapaxes(jnp.asarray(self._eds_dev), 0, 1)
                self._col_levels_dev = proof_device._jitted_row_levels(
                    self.k)(arr)
            return self._col_levels_dev

    def _host_levels(self, col: bool):
        """Materialized level arrays for a prover build (one counted
        crossing per orientation)."""
        levels = self._device_levels(col)
        t0 = telemetry.start_timer()
        site = "edscache.col_levels" if col else "edscache.levels"
        out = [tuple(triple)
               for triple in xfer.to_host(list(levels), site)]
        self._crossing("col_levels" if col else "levels")
        telemetry.measure_since("edscache.host_fetch", t0)
        return out

    def warm(self, engine: str = "auto") -> None:
        """Device-side warm: pre-run both orientations' level passes ON
        DEVICE. Provers (which need host bytes for share payloads) stay
        lazy — the first actual proof pays the materialization, counted;
        a produce->commit->warm chain that nobody samples never crosses
        the host boundary at all."""
        self._device_levels(col=False)
        self._device_levels(col=True)

    def warmed(self) -> bool:
        # fixed acquisition order (row, then col), same as the base
        # class's warmed(): nothing nests these two the other way
        with self._levels_lock:
            row_ready = self._levels_dev is not None
        with self._col_levels_lock:
            return row_ready and self._col_levels_dev is not None

    def get_prover(self, engine: str = "auto"):
        with self._row_lock:  # lint: disable=blocking-under-lock
            if self._prover is None:
                from celestia_app_tpu.da import proof_device

                self._prover = proof_device.BlockProver(
                    self.eds, self.dah,
                    levels=self._host_levels(col=False),
                )
            return self._prover

    def get_col_prover(self, engine: str = "auto"):
        with self._col_lock:  # lint: disable=blocking-under-lock
            if self._col_prover is None:
                from celestia_app_tpu.da import proof_device

                t0 = telemetry.start_timer()
                eds_t, dah_t = self._transposed_square()
                self._col_prover = proof_device.BlockProver(
                    eds_t, dah_t, levels=self._host_levels(col=True)
                )
                telemetry.measure_since("das.col_tree_build", t0)
            return self._col_prover


def compute_entry(ods: np.ndarray, engine: str = "auto",
                  scheme: str = "rs2d-nmt"):
    """THE encode+commit dispatch: ODS -> scheme entry, engine-gated.

    ``engine="device"`` requires the jax path (raises on failure),
    ``"host"`` never touches jax (the relay-down hang class: a down
    accelerator relay HANGS backend init, wedging whatever lock the
    caller holds), ``"auto"`` tries device and degrades loudly,
    ``"mesh"`` prefers the sharded multi-device pipeline
    (parallel/mesh_engine.py; returns a device-resident ``DeviceEntry``)
    whenever the square can shard, and is device-class otherwise — an
    unshardable square (the k=1 empty block) or a mesh failure takes the
    single-device jax path, never the host fallback; under auto/device,
    squares of ``k >= CELESTIA_MESH_MIN_K`` (default 256) take the mesh
    automatically when one exists, degrading to the single-device path
    on failure (counted). All four engines are pinned bit-identical.
    Every call is one real encode dispatch and counts ``da.extend_runs``
    — the telemetry pin tests assert at most one per (node, height),
    whichever scheme the chain runs. The default scheme's single-device
    body below is the pre-codec-plane pipeline, untouched (byte-identity
    pinned in tests/test_codec_iface.py); other schemes dispatch through
    the codec registry's raw encode hook (da/codec.py) — an unknown
    scheme raises BEFORE the counter moves (no phantom extend_runs), and
    "mesh" maps to "auto" for them (the sharded program is the default
    codec's)."""
    if scheme != "rs2d-nmt":
        from celestia_app_tpu.da import codec as codec_mod

        codec = codec_mod.get(scheme)  # CodecError on unknown schemes
        telemetry.incr("da.extend_runs")
        return codec._encode_impl(
            ods, "auto" if engine == "mesh" else engine
        )
    telemetry.incr("da.extend_runs")
    if engine in ("mesh", "device", "auto"):
        from celestia_app_tpu.parallel import mesh_engine

        k = int(ods.shape[0])
        if (engine == "mesh" and mesh_engine.mesh_for(k) is not None) \
                or (engine != "mesh" and mesh_engine.mesh_active_for(k)):
            try:
                return mesh_engine.compute_entry_mesh(ods)
            except Exception:
                # the single-device program computes the identical
                # bytes — degrade loudly and continue below. "mesh" is
                # device-class: an unshardable square (k=1 empty block)
                # or a mesh failure takes the single-device jax path,
                # and only a jax failure there raises.
                telemetry.incr("mesh.engine_fallbacks")
    if engine in ("device", "auto", "mesh"):
        try:
            from celestia_app_tpu.da import eds as eds_mod

            eds_arr, rows, cols, root = eds_mod.jitted_pipeline(
                ods.shape[0]
            )(xfer.to_device(ods, "edscache.compute_entry"))
            eds_h, rows_h, cols_h, root_h = xfer.to_host(
                (eds_arr, rows, cols, root), "edscache.compute_entry"
            )
            dah = DataAvailabilityHeader(
                row_roots=tuple(bytes(r) for r in rows_h),
                col_roots=tuple(bytes(c) for c in cols_h),
            )
            return EdsCacheEntry(
                ExtendedDataSquare(eds_h), dah, bytes(root_h),
            )
        except Exception:
            if engine in ("device", "mesh"):
                raise
            # engine=auto: count the silent degrade — a node that
            # quietly lost its accelerator should show it in /metrics
            telemetry.incr("app.device_path_fallback")
    # host path: BLAS+hashlib (utils/fast_host), bit-equal to the device
    # path and the refimpl oracle. The row levels come out of the same
    # pass that yields the row roots, so they ride the entry for free —
    # a later prover build on this entry is pure reshaping. Big squares
    # (k >= 256, the GF(2^16) code fast_host's BLAS formulation does not
    # cover) take Leopard's quasilinear host FFT encoder instead — the
    # NMT/level passes below are field-agnostic — so a host-engine
    # validator can follow a k=256/512 mesh chain.
    from celestia_app_tpu.ops import leopard
    from celestia_app_tpu.ops import rs as rs_ops
    from celestia_app_tpu.utils import fast_host, merkle_host

    if leopard.uses_gf16(ods.shape[0]):
        eds_arr = rs_ops.extend_square_np(ods)
    else:
        eds_arr = fast_host.extend_square_fast(ods)
    k = eds_arr.shape[0] // 2
    levels = fast_host.nmt_levels_fast(
        fast_host._axis_leaf_ns(eds_arr, k), eds_arr
    )
    lm, lx, lv = levels[-1]
    rows = np.concatenate([lm[:, 0], lx[:, 0], lv[:, 0]], axis=1)
    eds_t = np.swapaxes(eds_arr, 0, 1)
    cols = fast_host.nmt_roots_fast(
        fast_host._axis_leaf_ns(eds_t, k), eds_t
    )
    root = merkle_host.hash_from_leaves(
        [bytes(r) for r in rows] + [bytes(c) for c in cols]
    )
    dah = DataAvailabilityHeader(
        row_roots=tuple(bytes(r) for r in rows),
        col_roots=tuple(bytes(c) for c in cols),
    )
    return EdsCacheEntry(ExtendedDataSquare(eds_arr), dah, root,
                         levels=levels)


def build_block_prover(eds: ExtendedDataSquare,
                       dah: DataAvailabilityHeader,
                       engine: str = "auto", levels=None):
    """THE engine-gated BlockProver constructor — the one copy of what
    chain/query.build_prover and das/server._build_prover used to
    duplicate (they must stay bit-identical; now they are by
    construction). Precomputed host ``levels`` win regardless of engine
    (they are byte-identical to the jitted pass and already paid for).
    ``engine="mesh"`` is device-class here: prover level passes are a
    single-dispatch program either way (DeviceEntry overrides its own
    prover builds to reuse on-mesh levels before this is reached)."""
    from celestia_app_tpu.da import proof_device

    if levels is not None:
        return proof_device.BlockProver(eds, dah, levels=levels)
    if engine in ("device", "auto", "mesh"):
        try:
            return proof_device.BlockProver(eds, dah)  # jitted level pass
        except Exception:
            if engine in ("device", "mesh"):
                raise
            telemetry.incr("app.device_path_fallback")
    from celestia_app_tpu.utils import fast_host

    k = eds.width // 2
    levels = fast_host.nmt_levels_fast(
        fast_host._axis_leaf_ns(eds.squares, k), eds.squares
    )
    return proof_device.BlockProver(eds, dah, levels=levels)


class EdsCache:
    """Bounded, thread-safe, content-addressed LRU of EdsCacheEntry.

    A secondary index maps ``data_root -> key`` so the commit path — which
    holds a Block (header with data_hash), not a Square — can find the
    entry ProcessProposal populated. The index is safe because the data
    root is itself a pure function of the ODS bytes the key hashes: two
    different squares cannot share a root without a sha256 collision."""

    def __init__(self, max_entries: int | None = None,
                 max_bytes: int | None = None):
        self.max_entries = (DEFAULT_MAX_ENTRIES if max_entries is None
                            else max_entries)
        self.max_bytes = (DEFAULT_MAX_BYTES if max_bytes is None
                          else max_bytes)
        _caches.add(self)  # the residency gauge collector walks live caches
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict[bytes, EdsCacheEntry] = \
            collections.OrderedDict()  # guarded-by: _lock
        self._by_root: dict[bytes, bytes] = {}  # guarded-by: _lock
        self._nbytes = 0  # charged-byte total  # guarded-by: _lock
        # LRU churn evidence for soak verdicts: per-instance (the
        # process-global telemetry counter aggregates every cache)
        self.evictions = 0  # guarded-by: _lock

    def get(self, key: bytes) -> EdsCacheEntry | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                telemetry.incr("edscache.misses")
                return None
            self._entries.move_to_end(key)
            telemetry.incr("edscache.hits")
            return entry

    def put(self, key: bytes, entry: EdsCacheEntry) -> EdsCacheEntry:
        """Insert (idempotent: a racing earlier insert wins, so every
        caller holds the SAME object and lazy prover work is never
        duplicated). Returns the resident entry."""
        with self._lock:
            kept = self._entries.get(key)
            if kept is None:
                self._entries[key] = entry
                self._by_root[entry.data_root] = key
                self._nbytes += entry_nbytes(entry)
                kept = entry
            self._entries.move_to_end(key)
            # evict while EITHER cap is exceeded — but always retain the
            # newest entry (the in-flight height must stay servable even
            # when a single big-square entry exceeds the byte budget)
            while len(self._entries) > 1 and (
                    len(self._entries) > self.max_entries
                    or self._nbytes > self.max_bytes):
                _, old = self._entries.popitem(last=False)
                self._by_root.pop(old.data_root, None)
                self._nbytes -= entry_nbytes(old)
                self.evictions += 1
                telemetry.incr("edscache.evictions")
            return kept

    def lookup_root(self, data_root: bytes) -> EdsCacheEntry | None:
        """Commit-side lookup by the header's data_hash (no ODS in hand).
        Does not count hits/misses — it is bookkeeping, not a serving
        path; a miss just means the DAS plane warms lazily instead."""
        with self._lock:
            key = self._by_root.get(data_root)
            if key is None:
                return None
            self._entries.move_to_end(key)
            return self._entries[key]

    def get_or_compute(self, ods: np.ndarray, engine: str = "auto",
                       scheme: str = "rs2d-nmt") -> EdsCacheEntry:
        """The lifecycle read path: one encode per (scheme, content),
        ever."""
        key = cache_key(ods, scheme)
        entry = self.get(key)
        if entry is not None:
            return entry
        return self.put(key, compute_entry(ods, engine, scheme))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_root.clear()
            self._nbytes = 0

    def nbytes(self) -> int:
        """Charged-byte total of resident entries (static estimates —
        see entry_nbytes)."""
        with self._lock:
            return self._nbytes

    def residency_counts(self) -> dict[str, int]:
        """Resident entries bucketed by ``residency()`` state — the
        scrape-time source of the ``edscache.resident_entries{state=…}``
        gauges (PR 13 exposed the splits only inside /das/availability
        records; fleetmon and external scrapers need them in /metrics)."""
        with self._lock:
            entries = list(self._entries.values())
        counts = {"host": 0, "device": 0, "device+host": 0}
        for entry in entries:
            state = entry.residency()
            counts[state] = counts.get(state, 0) + 1
        return counts

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# Scrape-time residency gauges: every live cache in the process (weakly
# held — a dropped cache stops being counted) contributes its per-state
# entry counts. Registered once at import; the collector runs before
# each snapshot()/prometheus(), so /metrics always reflects the current
# device/host split without a background thread.
_caches: "weakref.WeakSet[EdsCache]" = weakref.WeakSet()


def _residency_collector() -> None:
    counts = {"host": 0, "device": 0, "device+host": 0}
    for cache in list(_caches):
        for state, n in cache.residency_counts().items():
            counts[state] = counts.get(state, 0) + n
    for state, n in sorted(counts.items()):
        telemetry.gauge(
            "edscache.resident_entries", n, labels={"state": state}
        )


telemetry.register_collector(_residency_collector)


class ProverWarmer:
    """Single coalescing background warmup worker.

    ``schedule`` replaces the pending slot (only the NEWEST commit
    matters — a blocksync batch replaying 64 heights must not queue 64
    prover builds; superseded slots count ``edscache.warm_coalesced``)
    and starts a worker thread if none is running. The worker builds the
    entry's row and col provers and hands the entry to every registered
    listener (the DAS serving planes' ``seed_cache_entry``), all WITHOUT
    holding any caller lock, then exits when the slot drains — so idle
    processes carry no thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = None  # guarded-by: _lock
        self._worker_alive = False  # guarded-by: _lock
        self._idle = threading.Event()
        self._idle.set()

    def schedule(self, height: int, entry: EdsCacheEntry, listeners,
                 engine: str = "auto", traces=None,
                 chain_id: str = "", pack_store=None,
                 blob_pack_store=None) -> None:
        with self._lock:
            if self._pending is not None:
                telemetry.incr("edscache.warm_coalesced")
            self._pending = (height, entry, tuple(listeners), engine,
                             traces, chain_id, pack_store,
                             blob_pack_store)
            self._idle.clear()
            if not self._worker_alive:
                self._worker_alive = True
                threading.Thread(
                    target=self._run, daemon=True,
                    name="edscache-warmer",
                ).start()

    def _run(self) -> None:
        while True:
            with self._lock:
                item, self._pending = self._pending, None
                if item is None:
                    self._worker_alive = False
                    self._idle.set()
                    return
            (height, entry, listeners, engine, traces, chain_id,
             pack_store, blob_pack_store) = item
            log = obs.get_logger("da.edscache")
            try:
                # the warm span joins the height's deterministic trace, so
                # the timeline waterfall shows prover warmup hanging off
                # the same trace id commit/first-sample use
                with obs.span(
                    "da.prover_warm", traces=traces,
                    trace_id=obs.trace_id_for(chain_id, height),
                    height=height, k=entry.k, engine=engine,
                    scheme=entry.scheme,
                ):
                    entry.warm(engine)
            except Exception as e:
                # warmup is an optimization: a failure must never take
                # the process down, but it must be visible
                telemetry.incr("edscache.warm_errors")
                log.error("prover warmup failed", height=height, err=e)
                continue  # an unwarmable entry must not be seeded
            for listener in listeners:
                try:
                    listener(height, entry)
                except Exception as e:
                    # isolate per listener: one broken serving core must
                    # not starve the others of the seed
                    telemetry.incr("edscache.seed_errors")
                    log.error("seed listener failed", height=height,
                              listener=getattr(listener, "__qualname__",
                                               str(listener)), err=e)
            if pack_store is not None:
                # serving plane (das/packs.py): the warmer owns warm
                # time, so this is where the height's static proof pack
                # is precomputed — provers are already built, so pack
                # assembly is pure index arithmetic + JSON + fsync.
                # Packs are an optimization: failure is counted and
                # logged, never fatal, and serving falls back to live
                # assembly.
                try:
                    with obs.span(
                        "packs.build", traces=traces,
                        trace_id=obs.trace_id_for(chain_id, height),
                        height=height, scheme=entry.scheme,
                    ):
                        pack_store.build(height, entry)
                except Exception as e:
                    telemetry.incr("packs.build_errors")
                    log.error("proof-pack build failed", height=height,
                              err=e)
            if blob_pack_store is not None:
                # read plane (das/blob_packs.py): warm time is also when
                # the height's per-namespace blob pack is precomputed —
                # provers are built, so each namespace's response is
                # index arithmetic + JSON + fsync. Same contract as the
                # sample packs: counted on failure, never fatal, live
                # queries keep serving.
                try:
                    with obs.span(
                        "blobpacks.build", traces=traces,
                        trace_id=obs.trace_id_for(chain_id, height),
                        height=height, scheme=entry.scheme,
                    ):
                        blob_pack_store.build(height, entry)
                except Exception as e:
                    telemetry.incr("blobpacks.build_errors")
                    log.error("blob-pack build failed", height=height,
                              err=e)

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no warm work is pending or running (tests, bench
        measurement points)."""
        return self._idle.wait(timeout)
