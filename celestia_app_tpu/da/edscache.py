"""Block plane: the extend-once lifecycle's content-addressed EDS/DAH cache.

The node used to pay the full RS-extend + NMT pipeline up to THREE times
per height: once at PrepareProposal (chain/app.py, result discarded), once
at ProcessProposal (the proposer re-validating its own block, and every
follower validating the gossiped one), and once more when the first light
client sampled the height (chain/query.build_prover rebuilding the square
from raw txs). Amortizing the RS/commitment work across protocol phases is
exactly the cost lever arXiv:2201.08261 optimizes for RS-based DA
protocols; this module is that amortization:

- **Content addressing.** Entries are keyed by ``sha256(ODS share bytes)``
  — a pure function of the data square itself, never of a height or a
  header field a peer claimed. A follower validating a gossiped proposal
  and the proposer validating its own construct the identical ODS from the
  txs, so both hit the same entry; a Byzantine header can never poison the
  cache, because the cached value is a pure function of the key (a wrong
  ``data_hash`` still fails the header comparison — the cache only changes
  who pays for recomputing the truth).

- **Engine-gated, bit-identical.** ``compute_entry`` is THE one
  ODS -> (EDS, row/col roots, data root) implementation for both the
  device path (da/eds.jitted_pipeline, one fused dispatch) and the host
  path (utils/fast_host BLAS+hashlib) — previously copy-pasted between
  ``App._pipeline``, ``chain/query.build_prover``, and
  ``das/server._build_prover``. The two engines are pinned byte-identical
  (tests/test_fast_host.py, tests/test_edscache.py), so a cache populated
  by either serves the other.

- **Lazy provers + background warmup.** Each entry carries its
  BlockProver (and the transposed col-axis prover BEFP escalation needs)
  built at most once, on demand, under the entry's own lock — or ahead of
  demand by ``ProverWarmer``, the single coalescing daemon thread
  ``App.commit`` hands each committed entry to. The warmer builds the
  provers and fans the entry out to registered DAS serving planes
  (``das/server.SampleCore.seed_cache_entry``) WITHOUT holding any
  service/consensus lock, so the first light-client sample after a commit
  is pure index arithmetic instead of a rebuild + re-extend.

Telemetry: ``da.extend_runs`` (every real pipeline dispatch),
``edscache.{hits,misses,evictions,seeded}``, ``edscache.warm_coalesced``
(a pending warm superseded by a newer commit), ``edscache.warm_errors``.
Wire/metric formats in docs/FORMATS.md §14; design in docs/DESIGN.md
"The block plane".
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading

import numpy as np

from celestia_app_tpu import obs
from celestia_app_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_app_tpu.utils import telemetry

# bounded LRU: at k=128 one entry holds ~32 MB of EDS plus ~24 MB of lazy
# row+col level arrays once warmed, so the default stays small — the
# lifecycle only ever needs the in-flight height plus a short serving tail
DEFAULT_MAX_ENTRIES = int(os.environ.get("CELESTIA_EDSCACHE_ENTRIES", "4"))


def cache_key(ods: np.ndarray, scheme: str = "rs2d-nmt") -> bytes:
    """Content address of an original data square: sha256 over the ODS
    share bytes in row-major order. Shares are fixed-size (512 B) and the
    count is k*k, so the byte string determines the geometry — two squares
    collide iff they are the same square.

    Zero-copy: the usual producers (dah.shares_to_ods) hand over C-order
    arrays, so hashing goes straight over the buffer (`arr.data`) with no
    8 MB `.tobytes()` staging copy at k=128; `ascontiguousarray` is a
    no-op then and only copies for exotic layouts. The hash itself is
    single-digit ms at k=128 (OpenSSL SHA-NI) against the 2-3 full
    extend+NMT dispatches per height it deduplicates.

    Non-default codec-plane schemes (da/codec.py) prefix their name so
    the same square encoded under two schemes occupies two entries;
    the default scheme's keys stay byte-identical to pre-plane keys."""
    arr = np.ascontiguousarray(ods)
    h = hashlib.sha256()
    if scheme != "rs2d-nmt":
        h.update(scheme.encode() + b"\x00")
    h.update(arr.data)
    return h.digest()


class EdsCacheEntry:
    """One cached extension: ``(eds, row_roots, col_roots, data_root)``
    plus the lazily-built proof machinery. The extension fields are
    immutable after construction; the provers build at most once, under
    the entry's own lock (never a service/consensus lock), so concurrent
    samplers of a fresh entry pay one level pass between them.

    The ``scheme``/``k``/``warm`` surface is the codec plane's common
    entry contract (da/codec.py): non-default schemes cache their own
    entry types (e.g. da/cmt.CmtEntry) in the same EdsCache."""

    scheme = "rs2d-nmt"

    def __init__(self, eds: ExtendedDataSquare,
                 dah: DataAvailabilityHeader, data_root: bytes,
                 levels=None):
        self.eds = eds
        self.dah = dah
        self.data_root = data_root
        # host-computed row NMT levels (utils/fast_host shape), carried
        # when the host pipeline produced them anyway; None on the device
        # path, where the prover's jitted level pass recomputes them
        self.levels = levels
        # one lock PER prover: a sampler needing the (already-built) row
        # prover must never queue behind the warmer's in-progress col
        # level pass — the two builds are independent
        self._row_lock = threading.Lock()
        self._col_lock = threading.Lock()
        self._prover = None  # guarded-by: _row_lock
        self._col_prover = None  # guarded-by: _col_lock

    def get_prover(self, engine: str = "auto"):
        """The row-axis BlockProver, built once (engine-gated)."""
        # the build-once lock EXISTS to serialize this first build (jit
        # compile included); samplers queue here instead of rebuilding
        with self._row_lock:  # lint: disable=blocking-under-lock
            if self._prover is None:
                self._prover = build_block_prover(
                    self.eds, self.dah, engine, levels=self.levels
                )
            return self._prover

    def get_col_prover(self, engine: str = "auto"):
        """Column-axis prover (BEFP escalation serving): the col trees of
        a square ARE the row trees of its transpose — same leaf-namespace
        rule (parity iff outside Q0 survives (r,c)->(c,r)), same batched
        level pass, no per-cell hashing."""
        # build-once serialization, same reasoning as get_prover
        with self._col_lock:  # lint: disable=blocking-under-lock
            if self._col_prover is None:
                t0 = telemetry.start_timer()
                eds_t = ExtendedDataSquare(
                    np.ascontiguousarray(
                        np.swapaxes(self.eds.squares, 0, 1)
                    )
                )
                dah_t = DataAvailabilityHeader(
                    row_roots=self.dah.col_roots,
                    col_roots=self.dah.row_roots,
                )
                self._col_prover = build_block_prover(eds_t, dah_t, engine)
                telemetry.measure_since("das.col_tree_build", t0)
            return self._col_prover

    @property
    def k(self) -> int:
        return self.eds.width // 2

    def warm(self, engine: str = "auto") -> None:
        """Pre-build both provers (the warmer's per-scheme hook)."""
        self.get_prover(engine)
        self.get_col_prover(engine)

    def warmed(self) -> bool:
        # fixed acquisition order (row, then col) — no other path nests
        # the two locks, so no inversion is possible
        with self._row_lock:
            row_ready = self._prover is not None
        with self._col_lock:
            return row_ready and self._col_prover is not None


def compute_entry(ods: np.ndarray, engine: str = "auto",
                  scheme: str = "rs2d-nmt"):
    """THE encode+commit dispatch: ODS -> scheme entry, engine-gated.

    ``engine="device"`` requires the jax path (raises on failure),
    ``"host"`` never touches jax (the relay-down hang class: a down
    accelerator relay HANGS backend init, wedging whatever lock the
    caller holds), ``"auto"`` tries device and degrades loudly. Every
    call is one real encode dispatch and counts ``da.extend_runs`` —
    the telemetry pin tests assert at most one per (node, height),
    whichever scheme the chain runs. The default scheme's body below is
    the pre-codec-plane pipeline, untouched (byte-identity pinned in
    tests/test_codec_iface.py); other schemes dispatch through the
    codec registry's raw encode hook (da/codec.py) — an unknown scheme
    raises BEFORE the counter moves (no phantom extend_runs)."""
    if scheme != "rs2d-nmt":
        from celestia_app_tpu.da import codec as codec_mod

        codec = codec_mod.get(scheme)  # CodecError on unknown schemes
        telemetry.incr("da.extend_runs")
        return codec._encode_impl(ods, engine)
    telemetry.incr("da.extend_runs")
    if engine in ("device", "auto"):
        try:
            import jax.numpy as jnp

            from celestia_app_tpu.da import eds as eds_mod

            eds_arr, rows, cols, root = eds_mod.jitted_pipeline(
                ods.shape[0]
            )(jnp.asarray(ods))
            dah = DataAvailabilityHeader(
                row_roots=tuple(bytes(r) for r in np.asarray(rows)),
                col_roots=tuple(bytes(c) for c in np.asarray(cols)),
            )
            return EdsCacheEntry(
                ExtendedDataSquare(np.asarray(eds_arr)), dah,
                bytes(np.asarray(root)),
            )
        except Exception:
            if engine == "device":
                raise
            # engine=auto: count the silent degrade — a node that
            # quietly lost its accelerator should show it in /metrics
            telemetry.incr("app.device_path_fallback")
    # host path: BLAS+hashlib (utils/fast_host), bit-equal to the device
    # path and the refimpl oracle. The row levels come out of the same
    # pass that yields the row roots, so they ride the entry for free —
    # a later prover build on this entry is pure reshaping.
    from celestia_app_tpu.utils import fast_host, merkle_host

    eds_arr = fast_host.extend_square_fast(ods)
    k = eds_arr.shape[0] // 2
    levels = fast_host.nmt_levels_fast(
        fast_host._axis_leaf_ns(eds_arr, k), eds_arr
    )
    lm, lx, lv = levels[-1]
    rows = np.concatenate([lm[:, 0], lx[:, 0], lv[:, 0]], axis=1)
    eds_t = np.swapaxes(eds_arr, 0, 1)
    cols = fast_host.nmt_roots_fast(
        fast_host._axis_leaf_ns(eds_t, k), eds_t
    )
    root = merkle_host.hash_from_leaves(
        [bytes(r) for r in rows] + [bytes(c) for c in cols]
    )
    dah = DataAvailabilityHeader(
        row_roots=tuple(bytes(r) for r in rows),
        col_roots=tuple(bytes(c) for c in cols),
    )
    return EdsCacheEntry(ExtendedDataSquare(eds_arr), dah, root,
                         levels=levels)


def build_block_prover(eds: ExtendedDataSquare,
                       dah: DataAvailabilityHeader,
                       engine: str = "auto", levels=None):
    """THE engine-gated BlockProver constructor — the one copy of what
    chain/query.build_prover and das/server._build_prover used to
    duplicate (they must stay bit-identical; now they are by
    construction). Precomputed host ``levels`` win regardless of engine
    (they are byte-identical to the jitted pass and already paid for)."""
    from celestia_app_tpu.da import proof_device

    if levels is not None:
        return proof_device.BlockProver(eds, dah, levels=levels)
    if engine in ("device", "auto"):
        try:
            return proof_device.BlockProver(eds, dah)  # jitted level pass
        except Exception:
            if engine == "device":
                raise
            telemetry.incr("app.device_path_fallback")
    from celestia_app_tpu.utils import fast_host

    k = eds.width // 2
    levels = fast_host.nmt_levels_fast(
        fast_host._axis_leaf_ns(eds.squares, k), eds.squares
    )
    return proof_device.BlockProver(eds, dah, levels=levels)


class EdsCache:
    """Bounded, thread-safe, content-addressed LRU of EdsCacheEntry.

    A secondary index maps ``data_root -> key`` so the commit path — which
    holds a Block (header with data_hash), not a Square — can find the
    entry ProcessProposal populated. The index is safe because the data
    root is itself a pure function of the ODS bytes the key hashes: two
    different squares cannot share a root without a sha256 collision."""

    def __init__(self, max_entries: int | None = None):
        self.max_entries = (DEFAULT_MAX_ENTRIES if max_entries is None
                            else max_entries)
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict[bytes, EdsCacheEntry] = \
            collections.OrderedDict()  # guarded-by: _lock
        self._by_root: dict[bytes, bytes] = {}  # guarded-by: _lock

    def get(self, key: bytes) -> EdsCacheEntry | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                telemetry.incr("edscache.misses")
                return None
            self._entries.move_to_end(key)
            telemetry.incr("edscache.hits")
            return entry

    def put(self, key: bytes, entry: EdsCacheEntry) -> EdsCacheEntry:
        """Insert (idempotent: a racing earlier insert wins, so every
        caller holds the SAME object and lazy prover work is never
        duplicated). Returns the resident entry."""
        with self._lock:
            kept = self._entries.get(key)
            if kept is None:
                self._entries[key] = entry
                self._by_root[entry.data_root] = key
                kept = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                _, old = self._entries.popitem(last=False)
                self._by_root.pop(old.data_root, None)
                telemetry.incr("edscache.evictions")
            return kept

    def lookup_root(self, data_root: bytes) -> EdsCacheEntry | None:
        """Commit-side lookup by the header's data_hash (no ODS in hand).
        Does not count hits/misses — it is bookkeeping, not a serving
        path; a miss just means the DAS plane warms lazily instead."""
        with self._lock:
            key = self._by_root.get(data_root)
            if key is None:
                return None
            self._entries.move_to_end(key)
            return self._entries[key]

    def get_or_compute(self, ods: np.ndarray, engine: str = "auto",
                       scheme: str = "rs2d-nmt") -> EdsCacheEntry:
        """The lifecycle read path: one encode per (scheme, content),
        ever."""
        key = cache_key(ods, scheme)
        entry = self.get(key)
        if entry is not None:
            return entry
        return self.put(key, compute_entry(ods, engine, scheme))

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_root.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class ProverWarmer:
    """Single coalescing background warmup worker.

    ``schedule`` replaces the pending slot (only the NEWEST commit
    matters — a blocksync batch replaying 64 heights must not queue 64
    prover builds; superseded slots count ``edscache.warm_coalesced``)
    and starts a worker thread if none is running. The worker builds the
    entry's row and col provers and hands the entry to every registered
    listener (the DAS serving planes' ``seed_cache_entry``), all WITHOUT
    holding any caller lock, then exits when the slot drains — so idle
    processes carry no thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending = None  # guarded-by: _lock
        self._worker_alive = False  # guarded-by: _lock
        self._idle = threading.Event()
        self._idle.set()

    def schedule(self, height: int, entry: EdsCacheEntry, listeners,
                 engine: str = "auto", traces=None,
                 chain_id: str = "", pack_store=None) -> None:
        with self._lock:
            if self._pending is not None:
                telemetry.incr("edscache.warm_coalesced")
            self._pending = (height, entry, tuple(listeners), engine,
                             traces, chain_id, pack_store)
            self._idle.clear()
            if not self._worker_alive:
                self._worker_alive = True
                threading.Thread(
                    target=self._run, daemon=True,
                    name="edscache-warmer",
                ).start()

    def _run(self) -> None:
        while True:
            with self._lock:
                item, self._pending = self._pending, None
                if item is None:
                    self._worker_alive = False
                    self._idle.set()
                    return
            (height, entry, listeners, engine, traces, chain_id,
             pack_store) = item
            log = obs.get_logger("da.edscache")
            try:
                # the warm span joins the height's deterministic trace, so
                # the timeline waterfall shows prover warmup hanging off
                # the same trace id commit/first-sample use
                with obs.span(
                    "da.prover_warm", traces=traces,
                    trace_id=obs.trace_id_for(chain_id, height),
                    height=height, k=entry.k, engine=engine,
                    scheme=entry.scheme,
                ):
                    entry.warm(engine)
            except Exception as e:
                # warmup is an optimization: a failure must never take
                # the process down, but it must be visible
                telemetry.incr("edscache.warm_errors")
                log.error("prover warmup failed", height=height, err=e)
                continue  # an unwarmable entry must not be seeded
            for listener in listeners:
                try:
                    listener(height, entry)
                except Exception as e:
                    # isolate per listener: one broken serving core must
                    # not starve the others of the seed
                    telemetry.incr("edscache.seed_errors")
                    log.error("seed listener failed", height=height,
                              listener=getattr(listener, "__qualname__",
                                               str(listener)), err=e)
            if pack_store is not None:
                # serving plane (das/packs.py): the warmer owns warm
                # time, so this is where the height's static proof pack
                # is precomputed — provers are already built, so pack
                # assembly is pure index arithmetic + JSON + fsync.
                # Packs are an optimization: failure is counted and
                # logged, never fatal, and serving falls back to live
                # assembly.
                try:
                    with obs.span(
                        "packs.build", traces=traces,
                        trace_id=obs.trace_id_for(chain_id, height),
                        height=height, scheme=entry.scheme,
                    ):
                        pack_store.build(height, entry)
                except Exception as e:
                    telemetry.incr("packs.build_errors")
                    log.error("proof-pack build failed", height=height,
                              err=e)

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no warm work is pending or running (tests, bench
        measurement points)."""
        return self._idle.wait(timeout)
