"""Namespaces: 29-byte (version || id) identifiers ordering the data square.

Reference parity: go-square ``namespace`` package as specified in
``specs/src/specs/namespace.md`` (reserved values, version-0 validity rules).
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts

NS_VER_0 = 0
NS_VER_MAX = 255
# Version-0 ids must carry 18 leading zero bytes; 10 bytes are user-chosen.
NS_V0_PREFIX_ZEROS = 18
NS_V0_USER_BYTES = appconsts.NAMESPACE_ID_SIZE - NS_V0_PREFIX_ZEROS  # 10


@dataclasses.dataclass(frozen=True, order=True)
class Namespace:
    """A 29-byte namespace; ordering is bytewise lexicographic over version||id."""

    raw: bytes  # version(1) || id(28)

    def __post_init__(self):
        if len(self.raw) != appconsts.NAMESPACE_SIZE:
            raise ValueError(
                f"namespace must be {appconsts.NAMESPACE_SIZE} bytes, got {len(self.raw)}"
            )

    @property
    def version(self) -> int:
        return self.raw[0]

    @property
    def id(self) -> bytes:
        return self.raw[1:]

    @classmethod
    def from_version_id(cls, version: int, ns_id: bytes) -> "Namespace":
        if len(ns_id) != appconsts.NAMESPACE_ID_SIZE:
            raise ValueError(f"namespace id must be 28 bytes, got {len(ns_id)}")
        return cls(bytes([version]) + ns_id)

    @classmethod
    def v0(cls, user_id: bytes) -> "Namespace":
        """Build a version-0 namespace from up to 10 user bytes (left-padded)."""
        if len(user_id) > NS_V0_USER_BYTES:
            raise ValueError(f"version-0 user id is at most {NS_V0_USER_BYTES} bytes")
        padded = user_id.rjust(NS_V0_USER_BYTES, b"\x00")
        return cls.from_version_id(NS_VER_0, b"\x00" * NS_V0_PREFIX_ZEROS + padded)

    def is_reserved(self) -> bool:
        return self <= MAX_PRIMARY_RESERVED or self >= MIN_SECONDARY_RESERVED

    def validate_for_blob(self) -> None:
        """A user blob namespace must be version 0, well-formed, unreserved."""
        if self.version != NS_VER_0:
            raise ValueError(f"blob namespace version must be 0, got {self.version}")
        if self.id[:NS_V0_PREFIX_ZEROS] != b"\x00" * NS_V0_PREFIX_ZEROS:
            raise ValueError("version-0 namespace id must have 18 leading zero bytes")
        if self.is_reserved():
            raise ValueError(f"blob namespace {self.raw.hex()} is reserved")

    def __repr__(self) -> str:
        return f"Namespace({self.raw.hex()})"


def _primary(last_byte: int) -> Namespace:
    return Namespace(b"\x00" * (appconsts.NAMESPACE_SIZE - 1) + bytes([last_byte]))


def _secondary(last_byte: int) -> Namespace:
    return Namespace(b"\xff" * (appconsts.NAMESPACE_SIZE - 1) + bytes([last_byte]))


# Reserved namespaces (specs/src/specs/namespace.md "Reserved Namespaces").
TX_NAMESPACE = _primary(0x01)
INTERMEDIATE_STATE_ROOT_NAMESPACE = _primary(0x02)
PAY_FOR_BLOB_NAMESPACE = _primary(0x04)
PRIMARY_RESERVED_PADDING_NAMESPACE = _primary(0xFF)
MAX_PRIMARY_RESERVED = _primary(0xFF)
MIN_SECONDARY_RESERVED = _secondary(0x00)
TAIL_PADDING_NAMESPACE = _secondary(0xFE)
PARITY_SHARE_NAMESPACE = _secondary(0xFF)

PARITY_NS_RAW = PARITY_SHARE_NAMESPACE.raw
