"""The codec plane: pluggable DA commitment schemes behind one interface.

The reference hard-wires a single DA construction — 2D Reed-Solomon over
GF(2^8) committed with NMTs (pkg/da, pkg/wrapper) — and until this module
so did this repo. Four of the five PAPERS.md entries are *alternative*
commitment constructions (Coded Merkle Tree arXiv:1910.01247 and its
polar-coded variants, RS-protocol trade-offs arXiv:2201.08261), each with
different bytes-per-sample / samples-to-confidence / fraud-proof-size
economics — the costs that dominate at millions of sampling light
clients. This registry makes the scheme an explicit, header-committed
choice instead of an assumption:

- ``Codec`` is the interface a scheme implements: encode ODS → extended
  payload + commitments + 32-byte data root; open/verify sample proofs;
  repair from a symbol subset; build/verify incorrect-coding fraud
  proofs; and the scheme's own confidence arithmetic (the per-sample
  catch probability differs per construction — the old hard-coded
  ``1-(3/4)^s`` is just the 2D-RS instance).
- The registry binds compact wire ids: scheme id 0 is the 2D-RS+NMT
  default (``da/codec_rs2d.py``, byte-identical to the pre-codec-plane
  pipeline — pinned against frozen vectors), id 1 the TPU-native Coded
  Merkle Tree (``da/cmt.py``). Headers carry the id (absent ⇒ 0, so
  every pre-plane hash is unchanged); ProcessProposal rejects proposals
  whose scheme differs from the node's configured codec; snapshots and
  DAS serving docs carry the scheme name.

Confidence helpers live here (not in the per-scheme modules) for the
same reason ``da/sampling.py`` keeps them: they are light-client-side
float math, outside the det-float consensus scope the scheme modules
ride in.

Design: docs/DESIGN.md "The codec plane"; wire formats: docs/FORMATS.md
§16.
"""

from __future__ import annotations

import math

# Wire scheme ids (FORMATS §16.1): headers encode the id (absent/0 =
# rs2d-nmt for back-compat), JSON surfaces carry the name.
SCHEME_RS2D = 0
SCHEME_CMT = 1
SCHEME_PCMT = 2

RS2D_NAME = "rs2d-nmt"
CMT_NAME = "cmt-ldpc"
PCMT_NAME = "pcmt-polar"


class CodecError(ValueError):
    """Malformed scheme input (unknown scheme, bad proof shape, ...)."""


class BadEncodingDetected(Exception):
    """Base of every scheme's incorrect-coding detection: repair() found
    the commitments provably commit an invalid codeword. ``location`` is
    the scheme's fraud coordinate (("row", 1) for rs2d-nmt, (layer,
    equation) for cmt-ldpc) — exactly what ``build_fraud_proof`` /
    ``fraud_cells`` consume, so the DASer's escalation path is
    scheme-generic (das/daser.py catches THIS type, never a concrete
    scheme's)."""

    def __init__(self, location: tuple, msg: str):
        super().__init__(msg)
        self.location = location


class Codec:
    """One DA commitment scheme. Stateless: entries carry the per-block
    payload; the codec owns the algorithms and parameters.

    The scheme's *entry* objects (returned by ``compute_entry``) share a
    small duck-typed surface with the block plane (da/edscache.py):
    ``.scheme`` (name), ``.data_root`` (32 bytes), ``.dah`` (the
    commitments object: a DataAvailabilityHeader for rs2d-nmt, a
    CmtCommitments for cmt-ldpc — both with ``.hash() == data_root``),
    ``.k`` (ODS width) and ``.warm(engine)`` (pre-build proof machinery
    off the hot path)."""

    scheme_id: int
    name: str

    # basis points of the per-sample withholding catch probability: the
    # fraction of the scheme's sampleable units an adversary must
    # withhold before data becomes unrecoverable (10000 = certainty)
    CATCH_BP: int

    # -- encode / commit -------------------------------------------------

    def compute_entry(self, ods, engine: str = "auto"):
        """(k, k, 512) u8 ODS -> scheme entry (commitments + payload +
        data root). THE one encode dispatch — engine-gated, host ≡
        device bit-identical, counts ``da.extend_runs``."""
        raise NotImplementedError

    def _encode_impl(self, ods, engine: str = "auto"):
        """Raw encode hook `da/edscache.compute_entry` resolves through
        the registry (it owns the front door: the ``da.extend_runs``
        counter and the default scheme's inline pipeline). Non-default
        schemes implement this; callers use ``compute_entry``."""
        raise NotImplementedError

    def min_entry(self, engine: str = "host"):
        """Entry of the minimum (empty-block) square: one tail-padding
        share — the scheme's genesis/empty data root."""
        import numpy as np

        from celestia_app_tpu.da import shares as shares_mod

        share = np.frombuffer(shares_mod.tail_padding_share(),
                              dtype=np.uint8)
        return self.compute_entry(share.reshape(1, 1, -1), engine)

    # -- commitments on the wire ----------------------------------------

    def commitments_doc(self, entry) -> dict:
        """The scheme-specific half of the /das/header JSON payload."""
        raise NotImplementedError

    def commitments_from_doc(self, doc: dict, data_root_hex: str,
                             square_size: int):
        """Parse + VERIFY a served commitments doc against the certified
        data root and header square size; raises CodecError if it does
        not bind. Returns the commitments object."""
        raise NotImplementedError

    # -- sampling --------------------------------------------------------

    def sample_space(self, commitments) -> list[tuple[int, int]]:
        """Every sampleable cell as a wire (a, b) pair — (row, col) of
        the extended square for rs2d-nmt, (layer, index) for cmt-ldpc.
        Light clients draw uniformly from this space."""
        raise NotImplementedError

    def open_sample(self, entry, cell: tuple[int, int]) -> dict:
        """Serve one cell: the sample JSON doc (FORMATS §7.2 / §16.3)."""
        raise NotImplementedError

    def verify_sample(self, commitments, doc: dict):
        """Verify one served sample doc against trusted commitments.
        Returns (cell, payload_bytes) on success, None on any failure."""
        raise NotImplementedError

    def sample_wire_bytes(self, doc: dict, commitments=None) -> int:
        """Exact canonical binary size of one sample proof (FORMATS
        §16.3) — the honest per-sample cost `bench.py --codec` reports
        (NOT the JSON/base64 transport inflation). Schemes whose wire
        size depends on geometry take the commitments too."""
        raise NotImplementedError

    def hashes_per_sample_verify(self, commitments) -> int:
        """SHA-256 compression *invocations* a verifier pays per sample
        (tree nodes for rs2d, one hash per layer step + the symbol hash
        for cmt)."""
        raise NotImplementedError

    # -- repair / fraud --------------------------------------------------

    def repair(self, commitments, samples: dict, engine: str = "auto"):
        """Reconstruct the full ODS from verified samples
        ({cell: payload bytes}). Raises the scheme's bad-encoding error
        (carrying the fraud location) when the commitments provably
        commit an invalid codeword, ValueError when simply short of the
        repair threshold. Returns the (k, k, 512) ODS."""
        raise NotImplementedError

    def build_fraud_proof(self, entry, location):
        """Producer/full-node side: the compact incorrect-coding proof
        for a bad location a repair attempt surfaced."""
        raise NotImplementedError

    def verify_fraud_proof(self, commitments, proof) -> bool:
        """Light-node side: True iff the proof demonstrates the
        commitments commit an invalid codeword."""
        raise NotImplementedError

    def fraud_proof_type(self) -> type:
        """The scheme's fraud-proof class. Gossip surfaces (the light
        client's submit_fraud_proof) resolve the codec from the proof's
        TYPE via the registry — adding a scheme never grows an if-chain
        there."""
        raise NotImplementedError

    def fraud_cells(self, commitments, location) -> list[tuple]:
        """The sample cells a light node must hold (served + verified)
        to assemble the fraud proof for ``location`` — what the DASer's
        scheme-generic escalation fetches (schemes whose fraud proofs
        cannot be assembled from served cells need not implement)."""
        raise NotImplementedError

    def fraud_proof_from_members(self, commitments, location,
                                 members: list[tuple]):
        """Assemble the proof from served members: ``members`` is one
        (cell, payload, sample-doc) triple per ``fraud_cells`` cell, in
        order."""
        raise NotImplementedError

    # -- confidence arithmetic (per-scheme; light-client math) -----------

    def catch_probability(self) -> float:
        """Per-sample probability a borderline withholding attack loses
        the sample (the scheme's availability threshold)."""
        return self.CATCH_BP / 10000.0

    def confidence(self, samples: int) -> float:
        """1 - (1 - catch)^s: availability confidence after s verified
        samples."""
        return 1.0 - (1.0 - self.catch_probability()) ** samples

    def samples_for_confidence(self, target: float = 0.99) -> int:
        """Smallest s with confidence(s) >= target."""
        if not 0.0 < target < 1.0:
            raise CodecError(f"confidence target {target} not in (0, 1)")
        miss = 1.0 - self.catch_probability()
        return max(1, math.ceil(math.log(1.0 - target) / math.log(miss)))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

_REGISTRY: dict[str, Codec] = {}
_BY_ID: dict[int, Codec] = {}


def register(codec: Codec) -> Codec:
    """Bind a codec under its name AND wire id (idempotent re-register
    of the same name replaces it — test fixtures re-import freely)."""
    _REGISTRY[codec.name] = codec
    _BY_ID[codec.scheme_id] = codec
    return codec


def _ensure_builtin() -> None:
    # lazy: the scheme modules import da/edscache & ops/, which must not
    # load at `import celestia_app_tpu.da.codec` time (cli --help paths)
    if RS2D_NAME not in _REGISTRY:
        from celestia_app_tpu.da import codec_rs2d  # noqa: F401
    if CMT_NAME not in _REGISTRY:
        from celestia_app_tpu.da import cmt  # noqa: F401
    if PCMT_NAME not in _REGISTRY:
        from celestia_app_tpu.da import pcmt  # noqa: F401


def _registered_desc() -> str:
    """'id=name' listing for unknown-scheme errors: whoever hits a wire
    id or name this build does not carry should see exactly what it
    DOES carry (tests pin both the id and the names appear)."""
    return ", ".join(
        f"{i}={_BY_ID[i].name}" for i in sorted(_BY_ID))


def get(name: str) -> Codec:
    """Codec by scheme name; raises CodecError for unknown schemes."""
    _ensure_builtin()
    codec = _REGISTRY.get(name)
    if codec is None:
        raise CodecError(
            f"unknown DA scheme {name!r} "
            f"(registered: {_registered_desc()})")
    return codec


def by_id(scheme_id: int) -> Codec:
    """Codec by wire id (header da_scheme field; absent ⇒ 0 = rs2d)."""
    _ensure_builtin()
    codec = _BY_ID.get(scheme_id)
    if codec is None:
        raise CodecError(
            f"unknown DA scheme id {scheme_id} "
            f"(registered: {_registered_desc()})")
    return codec


def default() -> Codec:
    return get(RS2D_NAME)


def names() -> list[str]:
    _ensure_builtin()
    return sorted(_REGISTRY)


def registered_ids() -> list[int]:
    """Sorted wire ids of every registered scheme — what the shared
    conformance suite (tests/test_codec_iface.py) parametrizes over, so
    a new scheme is conformance-covered by registration alone."""
    _ensure_builtin()
    return sorted(_BY_ID)
