"""Namespace data retrieval with presence/completeness/absence proofs.

The light-node side of the DA story (celestia-node's GetSharesByNamespace /
nmt ProveNamespace+VerifyNamespace): given a block's DAH, return EVERY
share of a namespace with a proof that the set is complete — or a proof
that the namespace is absent from the block.

Built on the framework's existing pieces: the square is namespace-sorted
(data_square_layout.md), so a namespace's shares form one contiguous
row-major range; NMT proof nodes are serialized as min_ns‖max_ns‖hash
(90 bytes), so a verifier can read each out-of-range subtree's namespace
window straight off the proof; and the DAH's row roots carry [min,max]
windows for whole rows.

Verification logic (nmt VerifyNamespace semantics):
- presence: the ShareProof chains to the data root, every returned share
  carries the target namespace, every OTHER row's root window excludes it,
  and every out-of-range proof node inside the touched rows excludes it —
  so no share of the namespace can exist outside the returned set.
- absence, no covering row: every row root window excludes the target.
- absence, straddling row (min < target < max with no exact match): a
  one-leaf proof of the SUCCESSOR share (the first leaf with ns > target);
  the left-side proof nodes' max < target proves nothing with the target
  sits before it, the successor's own ns > target proves nothing at it.
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.da.proof import ShareProof

NS = appconsts.NAMESPACE_SIZE


@dataclasses.dataclass
class NamespaceData:
    """shares + presence proof, or an absence witness."""

    namespace: bytes
    shares: list[bytes]  # [] when absent
    proof: ShareProof | None  # presence proof, or successor proof (absence)


def _root_window(root90: bytes) -> tuple[bytes, bytes]:
    return root90[:NS], root90[NS : 2 * NS]


def _out_of_range_subtrees(total: int, start: int, end: int):
    """Maximal out-of-range subtrees of the perfect `total`-leaf tree for
    range [start, end), in the walk order the prover emits proof nodes
    (matches BlockProver._range_proof / NmtRangeProof.verify)."""
    out: list[tuple[int, int]] = []

    def walk(lo: int, hi: int) -> None:
        if hi <= start or lo >= end:
            out.append((lo, hi))
            return
        if hi - lo == 1:
            return
        mid = lo + (hi - lo) // 2
        walk(lo, mid)
        walk(mid, hi)

    walk(0, total)
    return out


def get_namespace_data(prover, namespace: bytes) -> NamespaceData:
    """All shares of `namespace` in the prover's block, with proof.

    `prover` is a da/proof_device.BlockProver (cached row trees: proof
    assembly is pure index arithmetic)."""
    if len(namespace) != NS:
        raise ValueError(f"namespace must be {NS} bytes")
    k = prover.k
    ods = prover.eds.squares
    hits = [
        r * k + c
        for r in range(k)
        for c in range(k)
        if ods[r, c, :NS].tobytes() == namespace
    ]
    if hits:
        start, end = hits[0], hits[-1] + 1
        if hits != list(range(start, end)):
            raise AssertionError(
                "namespace shares are not contiguous: square is not sorted"
            )
        pf = prover.prove_shares(start, end, namespace)
        return NamespaceData(
            namespace=namespace,
            shares=[bytes(s) for s in pf.data],
            proof=pf,
        )
    # absence: find a Q0 row whose root window straddles the namespace
    for r in range(k):
        lo, hi = _root_window(prover.dah.row_roots[r])
        if lo <= namespace <= hi:
            # successor leaf: first column with a larger namespace (must
            # exist: hi >= namespace and no exact match)
            succ = next(
                c for c in range(k)
                if ods[r, c, :NS].tobytes() > namespace
            )
            pf = prover.prove_shares(
                r * k + succ, r * k + succ + 1,
                ods[r, succ, :NS].tobytes(),
            )
            return NamespaceData(namespace=namespace, shares=[], proof=pf)
    return NamespaceData(namespace=namespace, shares=[], proof=None)


def verify_namespace_data(
    dah: DataAvailabilityHeader, namespace: bytes, nd: NamespaceData
) -> bool:
    """True iff `nd` proves its claim (presence-and-complete, or absent)
    against the trusted DAH."""
    if nd.namespace != namespace or len(namespace) != NS:
        return False
    data_root = dah.hash()
    k = len(dah.row_roots) // 2

    def rows_exclude(rows) -> bool:
        for r in rows:
            lo, hi = _root_window(dah.row_roots[r])
            if lo <= namespace <= hi:
                return False
        return True

    def rows_bound(pf) -> bool:
        """The proof's row roots must BE the DAH's roots for the claimed
        row range — otherwise start_row/end_row are attacker-chosen labels
        and the completeness checks below skip the wrong rows."""
        want = [
            dah.row_roots[r]
            for r in range(pf.row_proof.start_row, pf.row_proof.end_row + 1)
        ]
        return list(pf.row_proof.row_roots) == want

    if nd.shares:
        pf = nd.proof
        if pf is None or pf.data != nd.shares:
            return False
        if not pf.verify(data_root) or not rows_bound(pf):
            return False
        if any(s[:NS] != namespace for s in nd.shares):
            return False
        start_row, end_row = pf.row_proof.start_row, pf.row_proof.end_row
        # completeness outside the touched rows
        if not rows_exclude(
            r for r in range(2 * k) if not start_row <= r <= end_row
        ):
            return False
        # completeness inside the touched rows: every out-of-range proof
        # node's namespace window must exclude the target
        for nproof in pf.share_proofs:
            for node in nproof.nodes:
                lo, hi = _root_window(node)
                if lo <= namespace <= hi:
                    return False
        return True

    if nd.proof is None:
        # absent with no covering row anywhere
        return rows_exclude(range(2 * k))

    # absent via successor proof in a straddling row
    pf = nd.proof
    if len(pf.data) != 1 or not pf.verify(data_root) or not rows_bound(pf):
        return False
    succ = pf.data[0]
    if not succ[:NS] > namespace:
        return False
    row = pf.row_proof.start_row
    if row != pf.row_proof.end_row or row >= k:
        return False
    # every other row must exclude the namespace outright
    if not rows_exclude(r for r in range(2 * k) if r != row):
        return False
    # left of the successor: every out-of-range subtree's max < target
    nproof = pf.share_proofs[0]
    subtrees = _out_of_range_subtrees(nproof.total, nproof.start, nproof.end)
    if len(subtrees) != len(nproof.nodes):
        return False
    for (lo_pos, hi_pos), node in zip(subtrees, nproof.nodes):
        lo, hi = _root_window(node)
        if hi_pos <= nproof.start:  # entirely left of the successor
            if hi >= namespace:
                return False
        else:  # right side: must start after the target
            if lo <= namespace:
                return False
    return True
