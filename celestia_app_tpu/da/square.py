"""Deterministic data-square layout: the go-square `square.Build`/`Construct`
equivalent (reference call sites: app/prepare_proposal.go:50,
app/process_proposal.go:122, app/extend_block.go:16).

Layout rules implemented (specs/src/specs/data_square_layout.md):
- normal txs -> one compact-share sequence in TRANSACTION_NAMESPACE,
  IndexWrapper-wrapped PFB txs -> one in PAY_FOR_BLOB_NAMESPACE;
- blobs sorted by namespace (stable: ties keep PFB priority order), each
  starting at a multiple of its SubtreeWidth (non-interactive default,
  `next_share_index`), with primary-reserved / namespace / tail padding;
- the square edge k is the smallest power of two fitting all shares
  (alignment is k-independent, so the share count is computed once).

`build` mirrors go-square Build: greedily include txs in priority order,
skipping any that would overflow the max square. `construct` mirrors
Construct: all txs must fit or the whole layout fails (ProcessProposal path).
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import blob as blob_mod
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.da import shares as shares_mod
from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.da.commitment import round_up_pow2, subtree_width
from celestia_app_tpu.da.shares import Share, uvarint


def next_share_index(cursor: int, blob_share_count: int, subtree_root_threshold: int) -> int:
    """Non-interactive default: first aligned index >= cursor for this blob."""
    width = subtree_width(blob_share_count, subtree_root_threshold)
    return -(-cursor // width) * width


def compact_shares_needed(total_bytes: int) -> int:
    """Shares for a compact sequence of `total_bytes` (incl. varint prefixes)."""
    if total_bytes == 0:
        return 0
    if total_bytes <= appconsts.FIRST_COMPACT_SHARE_CONTENT_SIZE:
        return 1
    rest = total_bytes - appconsts.FIRST_COMPACT_SHARE_CONTENT_SIZE
    return 1 + -(-rest // appconsts.CONTINUATION_COMPACT_SHARE_CONTENT_SIZE)


def _sequence_len(txs: list[bytes]) -> int:
    return sum(len(uvarint(len(t))) + len(t) for t in txs)


@dataclasses.dataclass(frozen=True)
class PfbEntry:
    """A blob tx admitted to layout: the unwrapped signed tx + its blobs."""

    tx: bytes
    blobs: tuple[Blob, ...]


@dataclasses.dataclass
class Square:
    """A built original data square plus the layout metadata proofs need."""

    size: int  # k
    shares: list[Share]  # k*k shares, row-major
    txs: list[bytes]  # normal txs included
    pfbs: list[PfbEntry]  # blob txs included (priority order)
    # start share index of each blob, parallel to the namespace-sorted order
    blob_start_indexes: dict[tuple[int, int], int]  # (pfb_idx, blob_idx) -> start
    tx_shares_len: int  # shares in TRANSACTION_NAMESPACE
    pfb_shares_len: int  # shares ACTUALLY written in PAY_FOR_BLOB_NAMESPACE
    # shares the layout reserved for the PFB sequence (worst-case index
    # sizing); blobs start after this, the gap is primary-reserved padding
    pfb_shares_reserved: int = 0

    def share_bytes(self) -> list[bytes]:
        return [s.raw for s in self.shares]

    def wrapped_pfb_txs(self) -> list[bytes]:
        """IndexWrapper-encoded PFB txs as placed in the square."""
        out = []
        for i, e in enumerate(self.pfbs):
            idxs = [self.blob_start_indexes[(i, j)] for j in range(len(e.blobs))]
            out.append(blob_mod.marshal_index_wrapper(e.tx, idxs))
        return out


class _Layout:
    """One deterministic layout pass over a candidate tx set.

    The PFB compact sequence is RESERVED at its worst-case size (every
    share index priced at the max square's max index,
    `index_wrapper_size_worst_case`) because blob start indexes — hence the
    actual packed-varint index bytes — are only known once the sequence
    length is fixed. go-square breaks the same cycle the same way
    (ADR-020 CompactShareCounter fed with worst-case-marshalled wrappers);
    the export pass writes the real (≤ reserved) wrapper bytes and fills
    the difference with primary-reserved padding shares."""

    def __init__(self, txs: list[bytes], pfbs: list[PfbEntry], threshold: int,
                 max_square_size: int):
        self.txs = txs
        self.pfbs = pfbs
        self.threshold = threshold
        self.max_square_size = max_square_size
        self.wrapped_sizes = [
            blob_mod.index_wrapper_size_worst_case(
                len(e.tx), len(e.blobs), max_square_size
            )
            for e in pfbs
        ]
        self.tx_shares = compact_shares_needed(_sequence_len(txs))
        self.pfb_shares_reserved = compact_shares_needed(
            sum(len(uvarint(s)) + s for s in self.wrapped_sizes)
        )
        # Stable namespace sort preserves PFB priority order within a namespace
        # and blob order within a PFB (data_square_layout.md "Ordering").
        self.ordered = sorted(
            [
                (e.blobs[j].namespace.raw, i, j)
                for i, e in enumerate(pfbs)
                for j in range(len(e.blobs))
            ],
            key=lambda t: (t[0],),
        )
        self.starts: dict[tuple[int, int], int] = {}
        cursor = self.tx_shares + self.pfb_shares_reserved
        self.first_blob_index = None
        worst_blob_shares = 0
        for ns_raw, i, j in self.ordered:
            count = pfbs[i].blobs[j].share_count()
            start = next_share_index(cursor, count, threshold)
            if self.first_blob_index is None:
                self.first_blob_index = start
            self.starts[(i, j)] = start
            cursor = start + count
            width = subtree_width(count, threshold)
            worst_blob_shares += count + width - 1
        self.total = cursor
        # the square size comes from the ESTIMATE (worst-case alignment
        # padding per blob, order-independent), not the exact layout —
        # ADR-020: "from the estimation can formulate the minimum square
        # size". Deterministic on both Prepare and Process sides.
        self.worst_total = (
            self.tx_shares + self.pfb_shares_reserved + worst_blob_shares
        )

    def square_size(self) -> int:
        k = 1
        while k * k < self.worst_total:
            k *= 2
        return k


def _export(layout: _Layout, k: int) -> Square:
    """Materialize the share list for a computed layout."""
    shares: list[Share] = []
    if layout.tx_shares:
        shares += shares_mod.split_txs(ns_mod.TX_NAMESPACE, layout.txs)
    pfb_shares_actual = 0
    if layout.pfb_shares_reserved:
        wrapped = [
            blob_mod.marshal_index_wrapper(
                e.tx,
                [layout.starts[(i, j)] for j in range(len(e.blobs))],
            )
            for i, e in enumerate(layout.pfbs)
        ]
        pfb = shares_mod.split_txs(ns_mod.PAY_FOR_BLOB_NAMESPACE, wrapped)
        pfb_shares_actual = len(pfb)
        # real index varints ≤ the reserved worst case; the gap up to the
        # first blob becomes primary-reserved padding below
        assert pfb_shares_actual <= layout.pfb_shares_reserved
        shares += pfb

    cursor = len(shares)
    prev_ns: ns_mod.Namespace | None = None
    for ns_raw, i, j in layout.ordered:
        b = layout.pfbs[i].blobs[j]
        start = layout.starts[(i, j)]
        if start > cursor:
            pad = (
                [shares_mod.reserved_padding_share()] * (start - cursor)
                if prev_ns is None
                else [shares_mod.namespace_padding_share(prev_ns)] * (start - cursor)
            )
            shares += pad
        shares += shares_mod.split_blob(b.namespace, b.data, b.share_version)
        cursor = start + b.share_count()
        prev_ns = b.namespace
    shares += shares_mod.tail_padding_shares(k * k - len(shares))
    return Square(
        size=k,
        shares=shares,
        txs=layout.txs,
        pfbs=layout.pfbs,
        blob_start_indexes=layout.starts,
        tx_shares_len=layout.tx_shares,
        pfb_shares_len=pfb_shares_actual,
        pfb_shares_reserved=layout.pfb_shares_reserved,
    )


def construct(
    txs: list[bytes],
    pfbs: list[PfbEntry],
    max_square_size: int,
    subtree_root_threshold: int,
) -> Square:
    """All txs must fit in max_square_size or ValueError (ProcessProposal)."""
    layout = _Layout(txs, pfbs, subtree_root_threshold, max_square_size)
    k = max(layout.square_size(), 1)
    if k > max_square_size:
        raise ValueError(
            f"block does not fit: needs square {k} > max {max_square_size}"
        )
    return _export(layout, k)


def build(
    txs: list[bytes],
    pfbs: list[PfbEntry],
    max_square_size: int,
    subtree_root_threshold: int,
) -> Square:
    """Greedy fill in priority order, dropping txs that overflow (proposer).

    Admission is O(1) per candidate via running counters with WORST-CASE
    padding accounting (each blob costs share_count + width−1: the maximum
    non-interactive-default alignment gap, `next_share_index` math), the
    same pessimistic-append design as go-square's Builder
    (go-square square/builder.go, ref app/prepare_proposal.go:50). Since
    worst-case ≥ exact, every admitted set is guaranteed to fit and the
    single exact layout pass at the end never needs an eviction loop —
    O(n log n) overall (the final sort) instead of the old per-admission
    full relayout (O(n² log n))."""
    cap = max_square_size * max_square_size
    kept_txs: list[bytes] = []
    seq_len = 0
    for t in txs:
        cand_len = seq_len + len(uvarint(len(t))) + len(t)
        if compact_shares_needed(cand_len) <= cap:
            kept_txs.append(t)
            seq_len = cand_len
    tx_shares = compact_shares_needed(seq_len)

    kept_pfbs: list[PfbEntry] = []
    pfb_seq_len = 0
    blob_shares_worst = 0
    for e in pfbs:
        wrapped = blob_mod.index_wrapper_size_worst_case(
            len(e.tx), len(e.blobs), max_square_size
        )
        cand_pfb_len = pfb_seq_len + len(uvarint(wrapped)) + wrapped
        cand_blob_worst = blob_shares_worst
        for b in e.blobs:
            count = b.share_count()
            width = subtree_width(count, subtree_root_threshold)
            cand_blob_worst += count + width - 1
        total_worst = (
            tx_shares + compact_shares_needed(cand_pfb_len) + cand_blob_worst
        )
        if total_worst <= cap:
            kept_pfbs.append(e)
            pfb_seq_len = cand_pfb_len
            blob_shares_worst = cand_blob_worst
    layout = _Layout(kept_txs, kept_pfbs, subtree_root_threshold, max_square_size)
    k = max(layout.square_size(), 1)
    assert k <= max_square_size, "worst-case accounting must over-approximate"
    return _export(layout, k)


def empty_square() -> Square:
    """The k=1 square holding a single tail-padding share."""
    return Square(
        size=1,
        shares=shares_mod.tail_padding_shares(1),
        txs=[],
        pfbs=[],
        blob_start_indexes={},
        tx_shares_len=0,
        pfb_shares_len=0,
    )
