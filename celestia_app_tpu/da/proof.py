"""Share & tx inclusion proofs against the data root.

Reference parity: pkg/proof (proof.go:23-202, row_proof.go, share_proof.go) —
a proof that a range of original-square shares is committed by the block's
data root consists of:

  1. per touched row, an NMT range proof of those leaves under the row root
     (parity subtree roots appear as proof nodes), and
  2. a RowProof: RFC-6962 Merkle proofs of each row root into the 4k axis
     roots behind the data root (row r = leaf r of rowRoots || colRoots).

Tx inclusion proofs locate the tx's bytes inside the compact-share sequences
(TRANSACTION_NAMESPACE for normal txs, PAY_FOR_BLOB_NAMESPACE for wrapped
PFBs — square.FindTxShareRange equivalent) and reduce to a share proof.
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_app_tpu.da.shares import uvarint
from celestia_app_tpu.da.square import Square
from celestia_app_tpu.utils import merkle_host, nmt_host

NS = appconsts.NAMESPACE_SIZE


@dataclasses.dataclass
class RowProof:
    row_roots: list[bytes]  # 90-byte serialized NMT roots
    proofs: list[merkle_host.Proof]
    start_row: int
    end_row: int  # inclusive, mirroring the reference

    def verify(self, data_root: bytes) -> bool:
        if len(self.row_roots) != len(self.proofs):
            return False
        if len(self.row_roots) != self.end_row - self.start_row + 1:
            return False
        for i, (root, proof) in enumerate(zip(self.row_roots, self.proofs)):
            # the leaf index must BE the claimed row: row r is leaf r of
            # the 4k-leaf rowRoots‖colRoots tree. Without this binding a
            # prover could label row 2's proof as row 3 and smuggle a
            # duplicated row past range-based completeness checks.
            if proof.index != self.start_row + i:
                return False
            if not proof.verify(data_root, root):
                return False
        return True


@dataclasses.dataclass
class ShareProof:
    data: list[bytes]  # the raw 512-byte shares being proven
    share_proofs: list[nmt_host.NmtRangeProof]  # one per touched row
    namespace: bytes  # 29-byte namespace of the proven shares
    row_proof: RowProof
    start_share: int  # ODS-global start index (row-major)
    end_share: int  # exclusive

    def verify(self, data_root: bytes) -> bool:
        if not self.data or len(self.share_proofs) != len(self.row_proof.row_roots):
            return False
        if not self.row_proof.verify(data_root):
            return False
        cursor = 0
        for row_root, nproof in zip(self.row_proof.row_roots, self.share_proofs):
            count = nproof.end - nproof.start
            row_shares = self.data[cursor : cursor + count]
            if len(row_shares) != count:
                return False
            # ODS leaves are namespaced by their own share prefix; the NMT
            # leaf hash binds it, so tampering with either ns or data fails.
            leaves = [(s[:NS], s) for s in row_shares]
            if not nproof.verify(row_root, leaves):
                return False
            cursor += count
        return cursor == len(self.data)

    def all_shares_in_namespace(self) -> bool:
        """True iff every proven share carries this proof's namespace (blob
        proofs; mixed/tx ranges legitimately span several namespaces)."""
        return all(s[:NS] == self.namespace for s in self.data)


def _row_tree(eds: ExtendedDataSquare, row: int) -> nmt_host.NmtTree:
    """Rebuild the NMT of one extended row (pkg/wrapper semantics: Q0 leaves
    keep their own namespace prefix, parity leaves use PARITY)."""
    k = eds.width // 2
    tree = nmt_host.NmtTree()
    for c in range(eds.width):
        share = eds.squares[row, c].tobytes()
        ns = share[:NS] if (row < k and c < k) else ns_mod.PARITY_NS_RAW
        tree.push(ns, share)
    return tree


def new_share_inclusion_proof(
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    start_share: int,
    end_share: int,
    namespace: bytes,
) -> ShareProof:
    """Prove ODS shares [start_share, end_share) (row-major over the k x k
    original square) against the data root."""
    k = eds.width // 2
    if not (0 <= start_share < end_share <= k * k):
        raise ValueError(f"invalid share range [{start_share}, {end_share})")
    start_row, end_row = start_share // k, (end_share - 1) // k

    data: list[bytes] = []
    nmt_proofs: list[nmt_host.NmtRangeProof] = []
    for row in range(start_row, end_row + 1):
        col_start = start_share - row * k if row == start_row else 0
        col_end = end_share - row * k if row == end_row else k
        tree = _row_tree(eds, row)
        nmt_proofs.append(tree.prove_range(col_start, col_end))
        data += [eds.squares[row, c].tobytes() for c in range(col_start, col_end)]

    all_roots = list(dah.row_roots) + list(dah.col_roots)
    _, proofs = merkle_host.proofs_from_leaves(all_roots)
    row_proof = RowProof(
        row_roots=[dah.row_roots[r] for r in range(start_row, end_row + 1)],
        proofs=[proofs[r] for r in range(start_row, end_row + 1)],
        start_row=start_row,
        end_row=end_row,
    )
    return ShareProof(
        data=data,
        share_proofs=nmt_proofs,
        namespace=namespace,
        row_proof=row_proof,
        start_share=start_share,
        end_share=end_share,
    )


# ---------------------------------------------------------------------------
# Tx -> share range (square.FindTxShareRange equivalent)
# ---------------------------------------------------------------------------


def _share_index_of_byte(offset: int) -> int:
    first = appconsts.FIRST_COMPACT_SHARE_CONTENT_SIZE
    cont = appconsts.CONTINUATION_COMPACT_SHARE_CONTENT_SIZE
    if offset < first:
        return 0
    return 1 + (offset - first) // cont


def tx_share_range(square: Square, tx_index: int) -> tuple[int, int]:
    """ODS share range [start, end) containing tx `tx_index`, counting normal
    txs first then wrapped PFB txs (block tx ordering)."""
    n_normal = len(square.txs)
    if tx_index < n_normal:
        units = square.txs
        base = 0
        j = tx_index
    else:
        units = square.wrapped_pfb_txs()
        base = square.tx_shares_len
        j = tx_index - n_normal
        if j >= len(units):
            raise ValueError(f"tx index {tx_index} out of range")
    start_byte = sum(len(uvarint(len(u))) + len(u) for u in units[:j])
    end_byte = start_byte + len(uvarint(len(units[j]))) + len(units[j])
    return (
        base + _share_index_of_byte(start_byte),
        base + _share_index_of_byte(end_byte - 1) + 1,
    )


def new_tx_inclusion_proof(
    square: Square,
    eds: ExtendedDataSquare,
    dah: DataAvailabilityHeader,
    tx_index: int,
) -> ShareProof:
    start, end = tx_share_range(square, tx_index)
    ns = (
        ns_mod.TX_NAMESPACE.raw
        if tx_index < len(square.txs)
        else ns_mod.PAY_FOR_BLOB_NAMESPACE.raw
    )
    return new_share_inclusion_proof(eds, dah, start, end, ns)


def blob_share_range(square: Square, pfb_index: int, blob_index: int) -> tuple[int, int]:
    """ODS share range of one blob of one PFB (square.BlobShareRange)."""
    start = square.blob_start_indexes[(pfb_index, blob_index)]
    count = square.pfbs[pfb_index].blobs[blob_index].share_count()
    return start, start + count
