"""Batched share/tx proof generation from device-computed row trees.

The host path (da/proof.py) rebuilds one NMT per touched row with recursive
hashlib calls — fine per proof, hopeless for proof *services* (the reference
serves `custom/txInclusionProof` / `custom/shareInclusionProof` ABCI queries,
pkg/proof/querier.go:20-67, over pkg/proof/proof.go:79-202). Here the device
computes EVERY node of EVERY row tree in one jitted pass (ops/nmt.nmt_levels
— the same level-synchronous reduction that produces the DAH roots), the
level arrays come back to the host once (~12 MB for a 128x128 block), and
each proof is then pure index arithmetic: the range proof's nodes are the
maximal out-of-range subtree roots of a perfect binary tree, addressed as
(level, index) — no hashing per proof at all.

Proofs produced are byte-identical to da/proof.py's (cross-checked in
tests/test_proof_device.py) and verify with the same NmtRangeProof/RowProof
machinery.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import eds as eds_mod
from celestia_app_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_app_tpu.da.proof import RowProof, ShareProof
from celestia_app_tpu.da.square import Square
from celestia_app_tpu.da import proof as proof_mod
from celestia_app_tpu.ops import nmt
from celestia_app_tpu.utils import merkle_host, nmt_host

NS = appconsts.NAMESPACE_SIZE


@functools.lru_cache(maxsize=None)
def _jitted_row_levels(k: int):
    """Compiled: (2k, 2k, 512) EDS -> per-level (mins, maxs, vs) node arrays."""

    def run(eds: jax.Array):
        leaf_ns = eds_mod._axis_leaf_ns(eds, k)
        return nmt.nmt_levels(leaf_ns, eds)

    return jax.jit(run)


class BlockProver:
    """Per-block proof factory: one device pass, then index-only proofs."""

    def __init__(self, eds: ExtendedDataSquare, dah: DataAvailabilityHeader,
                 levels=None):
        self.eds = eds
        self.dah = dah
        self.k = eds.width // 2
        from celestia_app_tpu.obs import xfer

        if levels is None:
            levels = xfer.to_host(
                _jitted_row_levels(self.k)(
                    xfer.to_device(eds.squares, "proof.row_levels")),
                "proof.row_levels")
        # [(mins, maxs, vs)] with node counts 2k, k, ..., 1 per row tree;
        # `levels` may be precomputed on the host (utils/fast_host
        # nmt_levels_fast) by engines that must not touch jax — only a
        # device-resident level crosses the boundary, and it crosses
        # counted (obs.xfer.ensure_host)
        self.levels = [
            (xfer.ensure_host(m, "proof.levels"),
             xfer.ensure_host(x, "proof.levels"),
             xfer.ensure_host(v, "proof.levels"))
            for m, x, v in levels
        ]
        all_roots = list(dah.row_roots) + list(dah.col_roots)
        _, self._root_proofs = merkle_host.proofs_from_leaves(all_roots)

    def _node(self, row: int, level: int, idx: int) -> bytes:
        m, x, v = self.levels[level]
        return m[row, idx].tobytes() + x[row, idx].tobytes() + v[row, idx].tobytes()

    def _range_proof(self, row: int, p_start: int, p_end: int) -> nmt_host.NmtRangeProof:
        """Maximal out-of-range subtree roots of the perfect 2k-leaf tree."""
        total = 2 * self.k
        nodes: list[bytes] = []

        def walk(lo: int, hi: int) -> None:
            if hi <= p_start or lo >= p_end:
                width = hi - lo
                level = width.bit_length() - 1
                nodes.append(self._node(row, level, lo >> level))
                return
            if hi - lo == 1:
                return  # in-range leaf: verifier recomputes
            mid = lo + (hi - lo) // 2  # split_point of a power of two
            walk(lo, mid)
            walk(mid, hi)

        walk(0, total)
        return nmt_host.NmtRangeProof(
            start=p_start, end=p_end, total=total, nodes=nodes
        )

    def prove_cell(self, row: int, col: int) -> tuple[bytes, "nmt_host.NmtRangeProof"]:
        """One EXTENDED-square cell (any quadrant) with its NMT proof under
        the row root — the unit a DAS sampler requests (da/sampling.py).
        Pure index arithmetic over the cached row trees."""
        width = 2 * self.k
        if not (0 <= row < width and 0 <= col < width):
            raise ValueError(f"cell ({row}, {col}) outside the {width}x{width} square")
        return (
            self.eds.squares[row, col].tobytes(),
            self._range_proof(row, col, col + 1),
        )

    def prove_shares(
        self, start_share: int, end_share: int, namespace: bytes
    ) -> ShareProof:
        """ShareProof for ODS shares [start_share, end_share), row-major."""
        k = self.k
        if not (0 <= start_share < end_share <= k * k):
            raise ValueError(f"invalid share range [{start_share}, {end_share})")
        start_row, end_row = start_share // k, (end_share - 1) // k
        data: list[bytes] = []
        nmt_proofs: list[nmt_host.NmtRangeProof] = []
        for row in range(start_row, end_row + 1):
            col_start = start_share - row * k if row == start_row else 0
            col_end = end_share - row * k if row == end_row else k
            nmt_proofs.append(self._range_proof(row, col_start, col_end))
            data += [
                self.eds.squares[row, c].tobytes()
                for c in range(col_start, col_end)
            ]
        row_proof = RowProof(
            row_roots=[self.dah.row_roots[r] for r in range(start_row, end_row + 1)],
            proofs=[self._root_proofs[r] for r in range(start_row, end_row + 1)],
            start_row=start_row,
            end_row=end_row,
        )
        return ShareProof(
            data=data,
            share_proofs=nmt_proofs,
            namespace=namespace,
            row_proof=row_proof,
            start_share=start_share,
            end_share=end_share,
        )

    def commitment_from_eds(
        self, square: Square, pfb_index: int, blob_index: int,
        subtree_root_threshold: int,
    ) -> bytes:
        """Blob share commitment recomputed from the committed EDS's cached
        row trees — zero hashing beyond the final MMR fold.

        Reference: pkg/inclusion/get_commit.go:12-30 with the
        EDSSubTreeRootCacher — the non-interactive defaults guarantee each
        MMR chunk of the blob aligns to a subtree of its row NMT, so every
        subtree root is a node the device pass already computed."""
        from celestia_app_tpu.da import commitment as commitment_mod

        start, end = proof_mod.blob_share_range(square, pfb_index, blob_index)
        n_shares = end - start
        width = commitment_mod.subtree_width(n_shares, subtree_root_threshold)
        sizes = commitment_mod.merkle_mountain_range_sizes(n_shares, width)
        k = self.k
        subtree_roots: list[bytes] = []
        cursor = start
        for size in sizes:
            row, col = cursor // k, cursor % k
            if col % size != 0 or col + size > k:
                raise ValueError(
                    "blob chunk not aligned to a row subtree (layout violation)"
                )
            level = size.bit_length() - 1
            subtree_roots.append(self._node(row, level, col >> level))
            cursor += size
        return merkle_host.hash_from_leaves(subtree_roots)

    def prove_tx(self, square: Square, tx_index: int) -> ShareProof:
        """Tx inclusion proof (pkg/proof/proof.go:NewTxInclusionProof)."""
        from celestia_app_tpu.da import namespace as ns_mod

        start, end = proof_mod.tx_share_range(square, tx_index)
        ns = (
            ns_mod.TX_NAMESPACE.raw
            if tx_index < len(square.txs)
            else ns_mod.PAY_FOR_BLOB_NAMESPACE.raw
        )
        return self.prove_shares(start, end, ns)
