"""Blob share commitments: the Merkle-mountain-range over NMT subtree roots.

Reference parity: go-square `inclusion.CreateCommitment` (called from
x/blob/types/payforblob.go:53 and blob_tx.go:98) per the spec's "Blob Share
Commitment Rules" (specs/src/specs/data_square_layout.md:38-58):

  SubtreeWidth  = min(roundUpPow2(ceil(shares / SubtreeRootThreshold)),
                      minSquareSize(shares))
  tree sizes    = MMR decomposition of the share count with max width
                  SubtreeWidth (full-width trees, then descending powers of 2)
  subtree roots = NMT roots over each chunk's ns-prefixed shares
  commitment    = RFC-6962 Merkle root over the serialized (90 B) subtree roots

Because blobs start at multiples of SubtreeWidth (non-interactive default,
square.py), these subtree roots appear verbatim as inner nodes of the row NMTs
for any square size — commitments are square-size independent (ADR-008/013).

Host path here (hashlib, used per-tx in CheckTx); da/commitment_device.py
batches every blob of a block into a few vectorized SHA launches (BASELINE
config 3) and is what ProcessProposal uses via blob_validation.batch_commitments.
"""

from __future__ import annotations

from celestia_app_tpu.da import shares as shares_mod
from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.utils import merkle_host, nmt_host


def round_up_pow2(n: int) -> int:
    k = 1
    while k < n:
        k *= 2
    return k


def min_square_size(share_count: int) -> int:
    """Smallest power-of-two square edge that fits `share_count` shares."""
    import math

    return round_up_pow2(math.isqrt(share_count - 1) + 1 if share_count > 1 else 1)


def subtree_width(share_count: int, subtree_root_threshold: int) -> int:
    s = -(-share_count // subtree_root_threshold)  # ceil
    return min(round_up_pow2(s), min_square_size(share_count))


def merkle_mountain_range_sizes(total: int, max_tree_size: int) -> list[int]:
    """Decompose `total` leaves into MMR tree sizes with cap `max_tree_size`."""
    sizes = []
    while total >= max_tree_size:
        sizes.append(max_tree_size)
        total -= max_tree_size
    if total:
        p = max_tree_size
        while total:
            while p > total:
                p //= 2
            sizes.append(p)
            total -= p
    return sizes


def create_commitment(blob: Blob, subtree_root_threshold: int) -> bytes:
    """32-byte share commitment of a blob."""
    blob_shares = shares_mod.split_blob(blob.namespace, blob.data, blob.share_version)
    width = subtree_width(len(blob_shares), subtree_root_threshold)
    sizes = merkle_mountain_range_sizes(len(blob_shares), width)
    subtree_roots: list[bytes] = []
    cursor = 0
    for size in sizes:
        tree = nmt_host.NmtTree()
        for s in blob_shares[cursor : cursor + size]:
            tree.push(blob.namespace.raw, s.raw)
        subtree_roots.append(nmt_host.serialize(tree.root()))
        cursor += size
    return merkle_host.hash_from_leaves(subtree_roots)


def create_commitments(blobs: list[Blob], subtree_root_threshold: int) -> list[bytes]:
    return [create_commitment(b, subtree_root_threshold) for b in blobs]
