"""Blob containers and tx envelopes.

Reference parity: go-square's `blob` package — `Blob`, `BlobTx` (a signed tx
plus the blobs it pays for, travelling together through the mempool and block
data but stripped before execution, app/check_tx.go:16-54) and `IndexWrapper`
(a PFB tx wrapped with the share indices of its blobs, as placed in the
PAY_FOR_BLOB_NAMESPACE compact shares).

BlobTx wire format: protobuf (celestia.core.v1.blob.BlobTx, type_id "BLOB" —
x/blob/types/blob_tx.go:37-108 semantics) is the DEFAULT and what reference
clients produce; the framework's legacy 4-byte-magic encoding is still
accepted on unmarshal for old fixtures.

IndexWrapper in-square bytes are the reference's protobuf encoding
(tendermint IndexWrapper with type_id "INDX" — app/encoding/
index_wrapper_decoder.go:10, coretypes.UnmarshalIndexWrapper), so a PFB
block's PAY_FOR_BLOB_NAMESPACE shares carry exactly what go-square writes.
Because packed-varint index bytes depend on index VALUES, the square
builder reserves compact shares using `index_wrapper_size_worst_case`
(every index priced at the max share index of the max square — go-square's
pessimistic-append, ADR-020) and fills the difference with primary-reserved
padding shares. The pre-round-4 fixed-width "INDX"-magic encoding is still
accepted on unmarshal for old fixtures.
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu.da import shares as shares_mod
from celestia_app_tpu.da.namespace import Namespace
from celestia_app_tpu.da.shares import read_uvarint, uvarint

BLOB_TX_MAGIC = b"BLOB"
INDEX_WRAPPER_MAGIC = b"INDX"


@dataclasses.dataclass(frozen=True)
class Blob:
    namespace: Namespace
    data: bytes
    share_version: int = 0

    def share_count(self) -> int:
        return shares_mod.sparse_shares_needed(len(self.data))

    def validate(self) -> None:
        self.namespace.validate_for_blob()
        if self.share_version not in (0,):
            raise ValueError(f"unsupported share version {self.share_version}")
        if len(self.data) == 0:
            raise ValueError("blob data must not be empty")


@dataclasses.dataclass(frozen=True)
class BlobTx:
    tx: bytes  # the signed PFB tx, blobs stripped
    blobs: tuple[Blob, ...]


def marshal_blob_tx(tx: bytes, blobs: list[Blob]) -> bytes:
    """Protobuf BlobTx (the reference wire format, blob.proto + type_id
    "BLOB") — the default envelope."""
    from celestia_app_tpu.wire import txpb

    return txpb.blob_tx_pb(
        tx, [(b.namespace.raw, b.data, b.share_version) for b in blobs]
    )


def marshal_blob_tx_legacy(tx: bytes, blobs: list[Blob]) -> bytes:
    out = bytearray(BLOB_TX_MAGIC)
    out += uvarint(len(tx)) + tx
    out += uvarint(len(blobs))
    for b in blobs:
        out += b.namespace.raw
        out += uvarint(b.share_version)
        out += uvarint(len(b.data)) + b.data
    return bytes(out)


def _try_parse_proto_blob_tx(raw: bytes):
    if not raw or raw[0] != 0x0A:  # protobuf field 1 (tx), length-delimited
        return None
    from celestia_app_tpu.wire import txpb

    try:
        tx, blob_tuples = txpb.parse_blob_tx(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    try:
        blobs = tuple(
            Blob(Namespace(ns), data, ver) for ns, data, ver in blob_tuples
        )
    except ValueError as e:
        raise ValueError(f"invalid blob in BlobTx: {e}") from None
    return BlobTx(tx=tx, blobs=blobs)


def try_unmarshal_blob_tx(raw: bytes) -> BlobTx | None:
    """One-shot envelope sniff+parse: the BlobTx when `raw` is one (either
    wire format), None when it is a plain tx. Raises ValueError for a
    well-formed envelope carrying invalid contents. Hot call sites should
    use THIS once instead of is_blob_tx()+unmarshal_blob_tx() (which would
    parse multi-megabyte envelopes twice)."""
    proto = _try_parse_proto_blob_tx(raw)
    if proto is not None:
        return proto
    if raw[:4] != BLOB_TX_MAGIC:
        return None
    return unmarshal_blob_tx(raw)


def is_blob_tx(raw: bytes) -> bool:
    if raw[:4] == BLOB_TX_MAGIC:
        return True
    try:
        return _try_parse_proto_blob_tx(raw) is not None
    except ValueError:
        return True  # well-formed proto BlobTx envelope with a bad blob


def unmarshal_blob_tx(raw: bytes) -> BlobTx:
    proto = _try_parse_proto_blob_tx(raw)
    if proto is not None:
        return proto
    if raw[:4] != BLOB_TX_MAGIC:
        raise ValueError("not a BlobTx envelope")
    off = 4
    tx_len, off = read_uvarint(raw, off)
    tx = raw[off : off + tx_len]
    off += tx_len
    n, off = read_uvarint(raw, off)
    blobs = []
    for _ in range(n):
        ns = Namespace(raw[off : off + 29])
        off += 29
        ver, off = read_uvarint(raw, off)
        dlen, off = read_uvarint(raw, off)
        data = raw[off : off + dlen]
        if len(data) != dlen:
            raise ValueError("truncated blob data")
        off += dlen
        blobs.append(Blob(ns, data, ver))
    if off != len(raw):
        raise ValueError("trailing bytes in BlobTx")
    return BlobTx(tx=tx, blobs=tuple(blobs))


@dataclasses.dataclass(frozen=True)
class IndexWrapper:
    tx: bytes
    share_indexes: tuple[int, ...]


def index_wrapper_size_worst_case(
    tx_len: int, n_blobs: int, max_square_size: int
) -> int:
    """Byte length of a protobuf IndexWrapper with every share index priced
    at the max share index of the max square (go-square's
    worstCaseShareIndexes: the one-pass builder must reserve compact shares
    for the PFB sequence BEFORE blob positions — hence index values — are
    known, ADR-020 'CompactShareCounter'). Mirrors wire/txpb.index_wrapper_pb
    field-for-field: bytes tx (1), packed uint32 share_indexes (2),
    string type_id "INDX" (3)."""
    idx_bytes = n_blobs * len(uvarint(max_square_size * max_square_size))
    return (
        1 + len(uvarint(tx_len)) + tx_len          # field 1: tx
        + 1 + len(uvarint(idx_bytes)) + idx_bytes  # field 2: packed indexes
        + 1 + 1 + 4                                # field 3: type_id "INDX"
    )


def marshal_index_wrapper(tx: bytes, share_indexes: list[int]) -> bytes:
    """Protobuf IndexWrapper — the reference's in-square wrapped-PFB bytes
    (coretypes.MarshalIndexWrapper)."""
    from celestia_app_tpu.wire import txpb

    return txpb.index_wrapper_pb(tx, share_indexes)


def is_index_wrapper(raw: bytes) -> bool:
    if raw[:4] == INDEX_WRAPPER_MAGIC:
        return True
    try:
        from celestia_app_tpu.wire import txpb

        txpb.parse_index_wrapper(raw)
        return True
    except ValueError:
        return False


def unmarshal_index_wrapper(raw: bytes) -> IndexWrapper:
    if raw[:4] != INDEX_WRAPPER_MAGIC:
        from celestia_app_tpu.wire import txpb

        tx, idxs = txpb.parse_index_wrapper(raw)
        return IndexWrapper(tx=tx, share_indexes=tuple(idxs))
    # legacy fixed-width encoding (pre-round-4 fixtures)
    off = 4
    tx_len, off = read_uvarint(raw, off)
    tx = raw[off : off + tx_len]
    off += tx_len
    n, off = read_uvarint(raw, off)
    idxs = []
    for _ in range(n):
        idxs.append(int.from_bytes(raw[off : off + 4], "big"))
        off += 4
    if off != len(raw):
        raise ValueError("trailing bytes in IndexWrapper")
    return IndexWrapper(tx=tx, share_indexes=tuple(idxs))
