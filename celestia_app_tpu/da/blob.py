"""Blob containers and tx envelopes.

Reference parity: go-square's `blob` package — `Blob`, `BlobTx` (a signed tx
plus the blobs it pays for, travelling together through the mempool and block
data but stripped before execution, app/check_tx.go:16-54) and `IndexWrapper`
(a PFB tx wrapped with the share indices of its blobs, as placed in the
PAY_FOR_BLOB_NAMESPACE compact shares).

BlobTx wire format: protobuf (celestia.core.v1.blob.BlobTx, type_id "BLOB" —
x/blob/types/blob_tx.go:37-108 semantics) is the DEFAULT and what reference
clients produce; the framework's legacy 4-byte-magic encoding is still
accepted on unmarshal for old fixtures.

IndexWrapper keeps the framework's fixed-width encoding inside squares
(4-byte big-endian indices, so a wrapped tx's length never depends on index
values and layout stays one-pass); the protobuf IndexWrapper codec lives in
wire/txpb.py for interop tooling. This is a deliberate, documented deviation
from go-square's in-square bytes.
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu.da import shares as shares_mod
from celestia_app_tpu.da.namespace import Namespace
from celestia_app_tpu.da.shares import read_uvarint, uvarint

BLOB_TX_MAGIC = b"BLOB"
INDEX_WRAPPER_MAGIC = b"INDX"


@dataclasses.dataclass(frozen=True)
class Blob:
    namespace: Namespace
    data: bytes
    share_version: int = 0

    def share_count(self) -> int:
        return shares_mod.sparse_shares_needed(len(self.data))

    def validate(self) -> None:
        self.namespace.validate_for_blob()
        if self.share_version not in (0,):
            raise ValueError(f"unsupported share version {self.share_version}")
        if len(self.data) == 0:
            raise ValueError("blob data must not be empty")


@dataclasses.dataclass(frozen=True)
class BlobTx:
    tx: bytes  # the signed PFB tx, blobs stripped
    blobs: tuple[Blob, ...]


def marshal_blob_tx(tx: bytes, blobs: list[Blob]) -> bytes:
    """Protobuf BlobTx (the reference wire format, blob.proto + type_id
    "BLOB") — the default envelope."""
    from celestia_app_tpu.wire import txpb

    return txpb.blob_tx_pb(
        tx, [(b.namespace.raw, b.data, b.share_version) for b in blobs]
    )


def marshal_blob_tx_legacy(tx: bytes, blobs: list[Blob]) -> bytes:
    out = bytearray(BLOB_TX_MAGIC)
    out += uvarint(len(tx)) + tx
    out += uvarint(len(blobs))
    for b in blobs:
        out += b.namespace.raw
        out += uvarint(b.share_version)
        out += uvarint(len(b.data)) + b.data
    return bytes(out)


def _try_parse_proto_blob_tx(raw: bytes):
    if not raw or raw[0] != 0x0A:  # protobuf field 1 (tx), length-delimited
        return None
    from celestia_app_tpu.wire import txpb

    try:
        tx, blob_tuples = txpb.parse_blob_tx(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    try:
        blobs = tuple(
            Blob(Namespace(ns), data, ver) for ns, data, ver in blob_tuples
        )
    except ValueError as e:
        raise ValueError(f"invalid blob in BlobTx: {e}") from None
    return BlobTx(tx=tx, blobs=blobs)


def try_unmarshal_blob_tx(raw: bytes) -> BlobTx | None:
    """One-shot envelope sniff+parse: the BlobTx when `raw` is one (either
    wire format), None when it is a plain tx. Raises ValueError for a
    well-formed envelope carrying invalid contents. Hot call sites should
    use THIS once instead of is_blob_tx()+unmarshal_blob_tx() (which would
    parse multi-megabyte envelopes twice)."""
    proto = _try_parse_proto_blob_tx(raw)
    if proto is not None:
        return proto
    if raw[:4] != BLOB_TX_MAGIC:
        return None
    return unmarshal_blob_tx(raw)


def is_blob_tx(raw: bytes) -> bool:
    if raw[:4] == BLOB_TX_MAGIC:
        return True
    try:
        return _try_parse_proto_blob_tx(raw) is not None
    except ValueError:
        return True  # well-formed proto BlobTx envelope with a bad blob


def unmarshal_blob_tx(raw: bytes) -> BlobTx:
    proto = _try_parse_proto_blob_tx(raw)
    if proto is not None:
        return proto
    if raw[:4] != BLOB_TX_MAGIC:
        raise ValueError("not a BlobTx envelope")
    off = 4
    tx_len, off = read_uvarint(raw, off)
    tx = raw[off : off + tx_len]
    off += tx_len
    n, off = read_uvarint(raw, off)
    blobs = []
    for _ in range(n):
        ns = Namespace(raw[off : off + 29])
        off += 29
        ver, off = read_uvarint(raw, off)
        dlen, off = read_uvarint(raw, off)
        data = raw[off : off + dlen]
        if len(data) != dlen:
            raise ValueError("truncated blob data")
        off += dlen
        blobs.append(Blob(ns, data, ver))
    if off != len(raw):
        raise ValueError("trailing bytes in BlobTx")
    return BlobTx(tx=tx, blobs=tuple(blobs))


@dataclasses.dataclass(frozen=True)
class IndexWrapper:
    tx: bytes
    share_indexes: tuple[int, ...]


def index_wrapper_size(tx_len: int, n_blobs: int) -> int:
    """Byte length of a marshalled IndexWrapper — independent of index values."""
    return 4 + len(uvarint(tx_len)) + tx_len + len(uvarint(n_blobs)) + 4 * n_blobs


def marshal_index_wrapper(tx: bytes, share_indexes: list[int]) -> bytes:
    out = bytearray(INDEX_WRAPPER_MAGIC)
    out += uvarint(len(tx)) + tx
    out += uvarint(len(share_indexes))
    for idx in share_indexes:
        out += idx.to_bytes(4, "big")
    return bytes(out)


def is_index_wrapper(raw: bytes) -> bool:
    return raw[:4] == INDEX_WRAPPER_MAGIC


def unmarshal_index_wrapper(raw: bytes) -> IndexWrapper:
    if not is_index_wrapper(raw):
        raise ValueError("not an IndexWrapper")
    off = 4
    tx_len, off = read_uvarint(raw, off)
    tx = raw[off : off + tx_len]
    off += tx_len
    n, off = read_uvarint(raw, off)
    idxs = []
    for _ in range(n):
        idxs.append(int.from_bytes(raw[off : off + 4], "big"))
        off += 4
    if off != len(raw):
        raise ValueError("trailing bytes in IndexWrapper")
    return IndexWrapper(tx=tx, share_indexes=tuple(idxs))
