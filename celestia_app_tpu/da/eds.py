"""The device compute core: ODS -> extended square -> axis roots -> data root.

This is the TPU-native replacement for the reference's
`da.ExtendShares` + `da.NewDataAvailabilityHeader` + `DAH.Hash()` chain
(pkg/da/data_availability_header.go:44-108): one jitted program per
power-of-two square-size bucket that

  1. 2D Reed-Solomon-extends the (k, k, 512) original square on the MXU
     (ops/rs.py bit-matrix matmuls),
  2. hashes all 2k row NMTs and 2k column NMTs level-synchronously on the VPU
     (ops/nmt.py), with Q0 leaves namespaced by their own share prefix and
     parity leaves by PARITY_SHARE_NAMESPACE
     (pkg/wrapper/nmt_wrapper.go:93-114 semantics), and
  3. reduces the 4k axis roots to the 32-byte data root with the RFC-6962
     binary Merkle tree (rowRoots || colRoots, data_availability_header.go:100-107).

Everything stays on device between stages; a single dispatch per block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.ops import merkle, nmt, rs

NS = appconsts.NAMESPACE_SIZE


def _axis_leaf_ns(eds: jax.Array, k: int) -> jax.Array:
    """Leaf namespaces for row trees of an EDS: own prefix in Q0, else parity.

    Symmetric under transpose (position (r, c) is in Q0 iff r < k and c < k),
    so the same function serves column trees on the transposed square.
    """
    two_k = 2 * k
    idx = jnp.arange(two_k)
    in_q0 = (idx[:, None] < k) & (idx[None, :] < k)  # (2k, 2k)
    # trace-time constant: numpy over a module-level byte string, baked
    # into the program — not a per-call host round-trip
    parity = jnp.asarray(np.frombuffer(ns_mod.PARITY_NS_RAW, dtype=np.uint8))  # lint: disable=jit-purity
    return jnp.where(in_q0[..., None], eds[:, :, :NS], parity)


def pipeline_fn(k: int):
    """Jittable: (k, k, 512) u8 ODS -> (eds, row_roots, col_roots, data_root)."""
    extend = rs.extend_square_fn(k)

    def run(ods: jax.Array):
        eds = extend(ods)  # (2k, 2k, 512)
        # Leaf (r, c) has the SAME preimage (0x00 || ns || share) in row
        # tree r and column tree c, so hash the 2k*2k leaf grid once and
        # transpose the digests for the column orientation — leaves are
        # 9 compression blocks each vs 3 for inners, so this halves the
        # dominant slice of the SHA work (nmt.roots_from_leaf_nodes).
        mins, maxs, vs = nmt.leaf_nodes(_axis_leaf_ns(eds, k), eds)
        # One 4k-tree reduction covers both orientations (rows first, then
        # the transposed grid as column trees): each level's SHA launch sees
        # 2x the messages, which measured ~2 ms faster than two separate
        # 2k-tree reductions on TPU (HW_NOTES_r4.md).
        m4 = jnp.concatenate([mins, jnp.swapaxes(mins, 0, 1)], axis=0)
        x4 = jnp.concatenate([maxs, jnp.swapaxes(maxs, 0, 1)], axis=0)
        v4 = jnp.concatenate([vs, jnp.swapaxes(vs, 0, 1)], axis=0)
        axis_roots = nmt.roots_from_leaf_nodes(m4, x4, v4)  # (4k, 90)
        row_roots, col_roots = axis_roots[: 2 * k], axis_roots[2 * k:]
        data_root = merkle.merkle_root_pow2(axis_roots)
        return eds, row_roots, col_roots, data_root

    return run


@functools.lru_cache(maxsize=None)
def jitted_pipeline(k: int):
    """Compiled pipeline for square size k (cached per bucket).
    Instrumented (obs/jax_profile): the cache miss counts one
    ``jax.compilations``; the wrapper splits first-call (compile) from
    steady-state (execute) latency per program."""
    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("eds.pipeline", k)
    return jax_profile.instrument(f"eds.pipeline[{k}]",
                                  jax.jit(pipeline_fn(k)))


@functools.lru_cache(maxsize=None)
def jitted_pipeline_batched(k: int):
    """Compiled (B, k, k, 512) -> batched (eds, row_roots, col_roots,
    data_roots): one dispatch covers B blocks, amortizing launch overhead
    and keeping the MXU fed when single squares underfill it (the
    one-chip analog of the sharded pipeline's `data` axis; BASELINE cfg 5
    throughput). vmap of the single-square program — bit-identical per
    block (tests/test_streaming.py)."""
    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("eds.pipeline_batched", k)
    return jax_profile.instrument(f"eds.pipeline_batched[{k}]",
                                  jax.jit(jax.vmap(pipeline_fn(k))))


def roots_only_fn(k: int):
    """Variant that keeps the EDS on device and returns only roots (less HBM
    traffic back to host for the PrepareProposal fast path)."""
    full = pipeline_fn(k)

    def run(ods: jax.Array):
        _, row_roots, col_roots, data_root = full(ods)
        return row_roots, col_roots, data_root

    return run


@functools.lru_cache(maxsize=None)
def jitted_roots_only(k: int):
    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("eds.roots_only", k)
    return jax_profile.instrument(f"eds.roots_only[{k}]",
                                  jax.jit(roots_only_fn(k)))


# live jit-cache-size accounting (obs/jax_profile collect_gauges): the
# gauge reads cache_info().currsize, so bench-driven cache_clear() calls
# keep it honest
from celestia_app_tpu.obs import jax_profile as _jax_profile  # noqa: E402

for _factory in (jitted_pipeline, jitted_pipeline_batched,
                 jitted_roots_only):
    _jax_profile.register_cache(_factory)
del _factory, _jax_profile
