"""Coded Merkle Tree: the second DA commitment scheme (arXiv:1910.01247).

Where the default scheme commits a 2D-RS square with 4k NMTs, CMT codes
the k*k ODS shares with a rate-1/2 sparse LDGM code (ops/ldpc.py), hashes
the 2k^2 coded symbols, batches every q=8 hashes into one data symbol of
the next layer, codes THAT layer the same way, and repeats until the
coded layer is small enough (<= ROOT_MAX symbols) to publish its hash
list outright as the block commitment. The 32-byte data root is one
sha256 over the parameterized root hash list (FORMATS §16.2).

Why a second scheme at all (the north star's economics):

- **Per-sample proof bytes.** A sample proof is the base symbol plus q-1
  sibling hashes per layer step — 512 + 3*224 + varints = 1187 canonical
  wire bytes at k=128 (FORMATS §16.3) against 2D-RS+NMT's
  512 + 8*90 + varints = 1238 (and 4 sha256 invocations to verify
  against 9): strictly smaller, `bench.py --codec` measures it.
- **O(1) fraud proofs.** Incorrect coding is proven by ONE violated
  parity equation — d+1 symbols with their inclusion proofs (~12 KB at
  k=128) — against a BEFP's k shares + orthogonal proofs (~160 KB).
- **Peeling repair.** Reconstruction is iterative degree-1 resolution
  (masked matmul sweeps, ops/ldpc.peel), not per-axis RS decoding.

Sampling threshold: light clients draw uniformly over the 2k^2 BASE
coded symbols (each sample's proof carries — and therefore implicitly
samples — one symbol of every upper layer, the CMT trick). CATCH_BP
declares 1/4: ops/ldpc.py's degree-8 construction peels a 1/4-erased
layer w.h.p. at every deployed size (measured, margin documented there),
so a withholder must hide beyond that fraction to threaten recovery and
each uniform sample then catches it with probability > 1/4. Unlike the
2D-RS bound this threshold is empirical-random, not combinatorial —
adversarially-shaped stopping sets below it are not excluded by
construction (the paper's hand-designed ensembles bound them; ours pins
the threshold by test) — which is exactly the kind of trade
`bench.py --codec` exists to surface.

Engine gating mirrors da/edscache.compute_entry: "device" demands jax
(LDPC bit-matmul + batched sha256 on device), "host" never touches it
(XOR-gather + hashlib), "auto" degrades loudly; the two are pinned
bit-identical in tests/test_codec_iface.py.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.da.shares import uvarint
from celestia_app_tpu.ops import ldpc

# hash-batch width: q hashes of layer j form one data symbol of layer j+1
Q = 8
HASH_BYTES = 32
# stop coding when a layer has <= ROOT_MAX coded symbols; its hash list
# IS the published commitment (16 KB ceiling — a third of a k=128 DAH)
ROOT_MAX = 512
DOMAIN = b"CMT\x01"


class CmtBadEncodingError(codec_mod.BadEncodingDetected):
    """A parity equation over commitment-verified symbols is violated:
    the producer committed an invalid codeword at (layer, equation)."""

    def __init__(self, layer: int, equation: int):
        super().__init__(
            (layer, equation),
            f"bad CMT encoding: layer {layer} equation {equation}")
        self.layer = layer
        self.equation = equation


def layer_plan(k: int) -> list[tuple[int, int]]:
    """[(n_data, sym_bytes)] per layer, base first — a pure function of
    k, so every node derives identical geometry from the header alone."""
    plan = [(k * k, appconsts.SHARE_SIZE)]
    while 2 * plan[-1][0] > ROOT_MAX:
        plan.append(((2 * plan[-1][0]) // Q, Q * HASH_BYTES))
    return plan


@dataclasses.dataclass(frozen=True)
class CmtCommitments:
    """The per-block commitment a light client holds: parameters + the
    top layer's hash list. ``hash()`` is the header's data root."""

    k: int
    root_hashes: tuple[bytes, ...]

    def hash(self) -> bytes:
        out = bytearray(DOMAIN)
        out += uvarint(self.k) + uvarint(Q) + uvarint(ldpc.DEGREE)
        out += uvarint(ROOT_MAX) + uvarint(len(self.root_hashes))
        for h in self.root_hashes:
            out += h
        return hashlib.sha256(bytes(out)).digest()

    @property
    def plan(self) -> list[tuple[int, int]]:
        return layer_plan(self.k)

    @property
    def n_base(self) -> int:
        return 2 * self.k * self.k

    def validate_basic(self) -> None:
        plan = self.plan
        if len(self.root_hashes) != 2 * plan[-1][0]:
            raise codec_mod.CodecError(
                f"root hash count {len(self.root_hashes)} != "
                f"{2 * plan[-1][0]} for k={self.k}")
        for h in self.root_hashes:
            if len(h) != HASH_BYTES:
                raise codec_mod.CodecError("root hash has size != 32")


def _hash_symbols(symbols: np.ndarray, engine: str) -> np.ndarray:
    """(n, S) u8 -> (n, 32) u8 sha256 digests, engine-gated (vmapped
    device SHA-256 vs hashlib over memoryview slices), bit-identical."""
    # host coded symbols (np.concatenate output), never a device value
    symbols = np.ascontiguousarray(symbols, dtype=np.uint8)  # lint: disable=xfer-reach
    if engine == "auto" and not ldpc.auto_wants_device():
        # CPU "auto": OpenSSL SHA-NI via hashlib beats the jnp scan path
        # by far (same gating reasoning as ops/ldpc.auto_wants_device)
        from celestia_app_tpu.utils import fast_host

        return fast_host._sha_many(symbols)
    if engine in ("device", "auto"):
        try:
            from celestia_app_tpu.obs import xfer
            from celestia_app_tpu.ops import sha256 as sha_mod

            return xfer.to_host(
                sha_mod.sha256(
                    xfer.to_device(symbols, "cmt.hash_symbols")),
                "cmt.hash_symbols")
        except Exception:
            if engine == "device":
                raise
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("app.device_path_fallback")
    from celestia_app_tpu.utils import fast_host

    return fast_host._sha_many(symbols)


class CmtEntry:
    """One encoded block: every layer's coded symbols + hash lists.
    Duck-compatible with the block plane's EdsCacheEntry surface
    (da/edscache.py): ``scheme``/``data_root``/``dah``/``k``/``warm``."""

    scheme = codec_mod.CMT_NAME

    def __init__(self, commitments: CmtCommitments,
                 layers: list[np.ndarray],
                 hash_lists: list[np.ndarray]):
        self.commitments = commitments
        self.layers = layers  # [(n_coded_j, S_j) u8]
        self.hash_lists = hash_lists  # [(n_coded_j, 32) u8]
        self.data_root = commitments.hash()
        # the block plane stores no EDS for this scheme; samplers get
        # symbols, never raw square cells
        self.eds = None

    @property
    def dah(self):
        """The scheme's commitments object (the ``.dah`` slot of the
        extend-once lifecycle carries 'whatever binds to data_root')."""
        return self.commitments

    @property
    def k(self) -> int:
        return self.commitments.k

    def ods(self) -> np.ndarray:
        k = self.commitments.k
        return self.layers[0][: k * k].reshape(
            k, k, appconsts.SHARE_SIZE)

    def warm(self, engine: str = "auto") -> None:
        """Proof machinery is the hash lists, already built at encode —
        nothing to pre-build (the warmer calls this for every scheme)."""


def build_layers(ods: np.ndarray,
                 engine: str = "auto") -> CmtEntry:
    """The encode pipeline: ODS -> CmtEntry. Layer j's coded symbols are
    [data || ldpc parity]; its hash list feeds layer j+1's data."""
    k = ods.shape[0]
    # the ODS argument is host bytes by codec contract (admission hands
    # the encode pipeline numpy shares)
    data = np.ascontiguousarray(ods, dtype=np.uint8).reshape(  # lint: disable=xfer-reach
        k * k, appconsts.SHARE_SIZE)
    layers: list[np.ndarray] = []
    hash_lists: list[np.ndarray] = []
    plan = layer_plan(k)
    for depth, (_n_data, _sym) in enumerate(plan):
        parity = ldpc.encode(data, engine)
        coded = np.concatenate([data, parity], axis=0)
        hashes = _hash_symbols(coded, engine)
        layers.append(coded)
        hash_lists.append(hashes)
        if depth + 1 < len(plan):
            data = hashes.reshape(-1, Q * HASH_BYTES)
    commitments = CmtCommitments(
        k=k, root_hashes=tuple(bytes(h) for h in hash_lists[-1]))
    return CmtEntry(commitments, layers, hash_lists)


# ---------------------------------------------------------------------------
# sample proofs
# ---------------------------------------------------------------------------


def _b64(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


def open_sample(entry: CmtEntry, layer: int, index: int) -> dict:
    """Serve coded symbol (layer, index) with its layered inclusion
    proof: q-1 sibling hashes per step up to the root hash list."""
    plan = entry.commitments.plan
    if not 0 <= layer < len(plan):
        raise codec_mod.CodecError(f"no CMT layer {layer}")
    n_coded = 2 * plan[layer][0]
    if not 0 <= index < n_coded:
        raise codec_mod.CodecError(
            f"symbol {index} outside layer {layer} ({n_coded} symbols)")
    steps: list[list[str]] = []
    pos = index
    for j in range(layer, len(plan) - 1):
        base = (pos // Q) * Q
        off = pos % Q
        sibs = [
            bytes(entry.hash_lists[j][base + t])
            for t in range(Q) if t != off
        ]
        steps.append([_b64(s) for s in sibs])
        pos //= Q
    return {
        "layer": layer,
        "index": index,
        "symbol": _b64(bytes(entry.layers[layer][index])),
        "steps": steps,
    }


def verify_sample(commitments: CmtCommitments, doc: dict):
    """Check one served sample doc. Returns ((layer, index), symbol
    bytes) when the symbol is committed at that position, None on ANY
    failure (malformed, wrong size, wrong path, unbound root)."""
    import base64

    try:
        layer = int(doc["layer"])
        index = int(doc["index"])
        symbol = base64.b64decode(doc["symbol"])
        steps = doc["steps"]
    except (KeyError, TypeError, ValueError):
        return None
    plan = commitments.plan
    if not 0 <= layer < len(plan):
        return None
    n_coded = 2 * plan[layer][0]
    if not 0 <= index < n_coded or len(symbol) != plan[layer][1]:
        return None
    if not isinstance(steps, list) or len(steps) != len(plan) - 1 - layer:
        return None
    h = hashlib.sha256(symbol).digest()
    pos = index
    try:
        for step in steps:
            if len(step) != Q - 1:
                return None
            sibs = [base64.b64decode(s) for s in step]
            if any(len(s) != HASH_BYTES for s in sibs):
                return None
            off = pos % Q
            parent = b"".join(sibs[:off]) + h + b"".join(sibs[off:])
            h = hashlib.sha256(parent).digest()
            pos //= Q
    except (TypeError, ValueError):
        return None
    if h != commitments.root_hashes[pos]:
        return None
    return (layer, index), symbol


def sample_wire_bytes(commitments: CmtCommitments, doc: dict) -> int:
    """Canonical binary size of the proof (FORMATS §16.3): varint layer +
    varint index + symbol + (q-1)*32 per step."""
    import base64

    plan = commitments.plan
    layer = int(doc["layer"])
    return (len(uvarint(layer)) + len(uvarint(int(doc["index"])))
            + plan[layer][1]
            + len(doc["steps"]) * (Q - 1) * HASH_BYTES)


# ---------------------------------------------------------------------------
# repair (peeling) + incorrect-coding fraud proofs
# ---------------------------------------------------------------------------


def repair(commitments: CmtCommitments, samples: dict,
           engine: str = "auto") -> np.ndarray:
    """Reconstruct the ODS from verified samples {(layer, index): bytes}.

    Base-layer symbols feed the peeling decoder; a violated parity
    equation whose members are ALL commitment-verified raises
    CmtBadEncodingError (the fraud location a light node can prove from
    served symbols alone). A peel that stalls before recovering every
    data symbol raises ValueError (below threshold: withholding, but not
    provably mis-coded). On success the recovered data's full
    recommitment must reproduce the committed root — a mismatch means an
    upper layer was mis-coded; it is reported (not provable from base
    samples alone; upper-layer equations need their own served symbols,
    which `DASer._build_cmt_fraud` fetches by (layer, index))."""
    plan = commitments.plan
    k = commitments.k
    n_data0, sym0 = plan[0]
    n0 = 2 * n_data0
    base = {i: b for (layer, i), b in samples.items() if layer == 0}
    if not base:
        raise ValueError("no base-layer samples to reconstruct from")
    symbols = np.zeros((n0, sym0), dtype=np.uint8)
    known = np.zeros(n0, dtype=bool)
    for i, b in sorted(base.items()):
        symbols[i] = np.frombuffer(b, dtype=np.uint8)
        known[i] = True
    symbols, known, _sweeps = ldpc.peel(symbols, known, engine)
    violated = ldpc.check_equations(symbols, known)
    for eq in violated:
        members = equation_members(commitments, 0, int(eq))
        if all(m in base for m in members):
            raise CmtBadEncodingError(0, int(eq))
    if violated.size:
        # inconsistent, but some member was only peeled, never served
        # with a proof: cannot attribute to a provable equation
        raise ValueError(
            f"CMT layer 0 inconsistent at equations "
            f"{violated[:4].tolist()} but members were not all served")
    if not known[:n_data0].all():
        raise ValueError(
            f"below peeling threshold: {int((~known[:n_data0]).sum())} "
            f"of {n_data0} data symbols unrecovered")
    ods = symbols[:n_data0].reshape(k, k, appconsts.SHARE_SIZE)
    rebuilt = build_layers(ods, engine)
    if rebuilt.data_root != commitments.hash():
        raise ValueError(
            "recovered data does not reproduce the committed root: an "
            "upper CMT layer was mis-coded (fetch its symbols to prove)")
    return ods


def equation_members(commitments: CmtCommitments, layer: int,
                     equation: int) -> list[int]:
    """Coded indices of one parity equation's members at a layer: the d
    data neighbors (deterministic ldpc construction) then the parity
    symbol itself — the exact member order a CmtFraudProof must carry."""
    n_data = commitments.plan[layer][0]
    idx = ldpc.parity_indices(n_data)
    return [int(m) for m in idx[equation]] + [n_data + equation]


@dataclasses.dataclass(frozen=True)
class CmtSymbolWithProof:
    index: int  # coded index within the equation's layer
    symbol: bytes
    doc: dict  # the served sample doc (carries the layered proof)


@dataclasses.dataclass(frozen=True)
class CmtFraudProof:
    """One violated parity equation: d data members + the parity member,
    each carried with its inclusion proof. O(1) in the block size."""

    layer: int
    equation: int
    members: tuple[CmtSymbolWithProof, ...]


def generate_fraud(entry: CmtEntry, layer: int,
                   equation: int) -> CmtFraudProof:
    """Full-node side: assemble the proof from an entry it holds."""
    members = equation_members(entry.commitments, layer, equation)
    return CmtFraudProof(
        layer=layer,
        equation=equation,
        members=tuple(
            CmtSymbolWithProof(
                index=m,
                symbol=bytes(entry.layers[layer][m]),
                doc=open_sample(entry, layer, m),
            )
            for m in members
        ),
    )


def verify_fraud(commitments: CmtCommitments,
                 proof: CmtFraudProof) -> bool:
    """True iff the proof demonstrates the commitments commit an invalid
    codeword: every member symbol verifies against the commitments AT
    the positions the (deterministically recomputed) equation demands,
    and the XOR of the data members differs from the parity member.
    False for malformed proofs and for honest blocks."""
    try:
        plan = commitments.plan
        if not 0 <= proof.layer < len(plan):
            return False
        n_data = plan[proof.layer][0]
        if not 0 <= proof.equation < n_data:
            return False
        expected = equation_members(commitments, proof.layer,
                                    proof.equation)
        if [m.index for m in proof.members] != expected:
            return False
        syms: list[bytes] = []
        for m in proof.members:
            got = verify_sample(commitments, m.doc)
            if got is None:
                return False
            (layer, index), symbol = got
            if layer != proof.layer or index != m.index \
                    or symbol != m.symbol:
                return False
            syms.append(symbol)
        acc = np.zeros(plan[proof.layer][1], dtype=np.uint8)
        for s in syms[:-1]:
            acc ^= np.frombuffer(s, dtype=np.uint8)
        return not np.array_equal(
            acc, np.frombuffer(syms[-1], dtype=np.uint8))
    except (KeyError, TypeError, ValueError, IndexError,
            AttributeError):
        # AttributeError: a proof routed against the wrong scheme's
        # commitments object (no .plan / .root_hashes) is malformed
        # input, not a crash
        return False


# ---------------------------------------------------------------------------
# the Codec implementation
# ---------------------------------------------------------------------------


class CmtCodec(codec_mod.Codec):
    scheme_id = codec_mod.SCHEME_CMT
    name = codec_mod.CMT_NAME
    CATCH_BP = 2500  # declared sampling threshold (see module docstring)

    def compute_entry(self, ods: np.ndarray,
                      engine: str = "auto") -> CmtEntry:
        from celestia_app_tpu.da import edscache

        return edscache.compute_entry(ods, engine, scheme=self.name)

    def _encode_impl(self, ods: np.ndarray,
                     engine: str = "auto") -> CmtEntry:
        return build_layers(ods, engine)

    def commitments_doc(self, entry) -> dict:
        c = entry.dah
        return {
            "scheme": self.name,
            "k": c.k,
            "q": Q,
            "degree": ldpc.DEGREE,
            "root_max": ROOT_MAX,
            "root_hashes": [h.hex() for h in c.root_hashes],
            "data_root": entry.data_root.hex(),
        }

    def commitments_from_doc(self, doc: dict, data_root_hex: str,
                             square_size: int) -> CmtCommitments:
        try:
            if (int(doc["q"]) != Q or int(doc["degree"]) != ldpc.DEGREE
                    or int(doc["root_max"]) != ROOT_MAX):
                raise codec_mod.CodecError(
                    "served CMT parameters differ from this build's")
            c = CmtCommitments(
                k=int(doc["k"]),
                root_hashes=tuple(
                    bytes.fromhex(h) for h in doc["root_hashes"]),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise codec_mod.CodecError(
                f"malformed CMT commitments doc: {e}") from None
        c.validate_basic()
        if c.k != square_size:
            raise codec_mod.CodecError(
                "served CMT k contradicts the header square size")
        if c.hash().hex() != data_root_hex:
            raise codec_mod.CodecError(
                "served CMT commitments do not bind to the data root")
        return c

    def sample_space(self, commitments) -> list[tuple[int, int]]:
        # base layer only: each sample's proof carries one symbol of
        # every upper layer, implicitly sampling them (the CMT trick)
        return [(0, i) for i in range(commitments.n_base)]

    def open_sample(self, entry, cell: tuple[int, int]) -> dict:
        return open_sample(entry, cell[0], cell[1])

    def verify_sample(self, commitments, doc: dict):
        return verify_sample(commitments, doc)

    def sample_wire_bytes(self, doc: dict, commitments=None) -> int:
        if commitments is None:
            raise codec_mod.CodecError("cmt wire size needs commitments")
        return sample_wire_bytes(commitments, doc)

    def hashes_per_sample_verify(self, commitments) -> int:
        return len(commitments.plan)  # symbol hash + one per step

    def repair(self, commitments, samples: dict,
               engine: str = "auto") -> np.ndarray:
        return repair(commitments, samples, engine)

    def build_fraud_proof(self, entry, location) -> CmtFraudProof:
        layer, equation = location
        return generate_fraud(entry, layer, equation)

    def verify_fraud_proof(self, commitments, proof) -> bool:
        return verify_fraud(commitments, proof)

    def fraud_proof_type(self) -> type:
        return CmtFraudProof

    def fraud_cells(self, commitments, location) -> list[tuple]:
        layer, equation = location
        return [(layer, m)
                for m in equation_members(commitments, layer, equation)]

    def fraud_proof_from_members(self, commitments, location,
                                 members: list[tuple]) -> CmtFraudProof:
        layer, equation = location
        return CmtFraudProof(
            layer=layer, equation=equation,
            members=tuple(
                CmtSymbolWithProof(index=cell[1], symbol=payload,
                                   doc=doc)
                for cell, payload, doc in members
            ),
        )


codec_mod.register(CmtCodec())
