"""Bad-encoding fraud proofs (BEFP — specs/src/specs/fraud_proofs.md).

If a block producer commits axis roots over shares that are NOT a valid
Reed-Solomon codeword, a light node cannot detect it from sampling alone —
a full node that notices generates a compact fraud proof any light node can
check against just the DataAvailabilityHeader:

  - the bad axis (row/col) index,
  - k of its shares, EACH carried with an NMT inclusion proof against the
    ORTHOGONAL axis roots (the columns vouch for a bad row's cells and vice
    versa — so the proof stands on commitments the header itself makes),

Verification: check every share's membership proof, RS-decode the unique
codeword those k shares determine (ops/leopard_decode — the O(n log n) FWHT
path), recompute what the axis NMT root HAD to be for that codeword, and
compare against the header's root. A mismatch proves the producer committed
a non-codeword: the block is fraudulent and must be rejected wholesale.
(The reference repo delegates BEFP to celestia-node; the construction here
follows the same spec section.)
"""

from __future__ import annotations

import dataclasses

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_app_tpu.ops import rs
from celestia_app_tpu.utils import nmt_host

NS = appconsts.NAMESPACE_SIZE


@dataclasses.dataclass(frozen=True)
class ShareWithProof:
    position: int  # index along the bad axis (the orthogonal tree's axis id)
    share: bytes  # 512 bytes
    proof: nmt_host.NmtRangeProof  # against the orthogonal axis root


@dataclasses.dataclass(frozen=True)
class BadEncodingProof:
    axis: str  # "row" | "col"
    index: int  # which row/col is claimed bad
    shares: tuple[ShareWithProof, ...]  # exactly k members


def leaf_ns(row: int, col: int, share: bytes, k: int) -> bytes:
    """THE pkg/wrapper leaf namespace rule (nmt_wrapper.go:93-114): Q0
    keeps the share's own prefix, every parity quadrant uses PARITY.
    Shared by fraud proving and 2D repair (da/repair.py) so both always
    verify against the same leaf construction."""
    return share[:NS] if (row < k and col < k) else ns_mod.PARITY_NS_RAW


def _axis_tree(eds: ExtendedDataSquare, axis: str, index: int) -> nmt_host.NmtTree:
    """Axis NMT of a possibly-CORRUPT square: leaves appended without the
    namespace-order check (the malicious producer's tree — reference
    test/util/malicious BlindTree/ForceAddLeaf), since a fraud prover must
    reproduce whatever the producer committed."""
    k = eds.width // 2
    tree = nmt_host.NmtTree()
    for j in range(eds.width):
        r, c = (index, j) if axis == "row" else (j, index)
        share = eds.squares[r, c].tobytes()
        tree.leaves.append((leaf_ns(r, c, share, k), share))
    return tree


def generate_befp(
    eds: ExtendedDataSquare, axis: str, index: int,
    positions: list[int] | None = None,
) -> BadEncodingProof:
    """Build the proof from a (possibly corrupt) EDS the prover holds.

    `positions` picks which k cells along the axis to carry (default: the
    first k); each is proven via its ORTHOGONAL axis tree, built from the
    same square — i.e. from the commitments the header actually made."""
    if axis not in ("row", "col"):
        raise ValueError(f"axis must be 'row' or 'col', not {axis!r}")
    k = eds.width // 2
    if not 0 <= index < eds.width:
        raise ValueError(f"axis index {index} out of range")
    positions = list(range(k)) if positions is None else sorted(positions)
    if len(positions) != k or len(set(positions)) != k:
        raise ValueError(f"need exactly {k} distinct share positions")
    if any(not 0 <= j < eds.width for j in positions):
        raise ValueError(f"positions out of range [0, {eds.width})")
    shares = []
    for j in positions:
        r, c = (index, j) if axis == "row" else (j, index)
        ortho = _axis_tree(eds, "col" if axis == "row" else "row", j)
        # the cell sits at leaf `index` of orthogonal axis j (for a bad ROW,
        # leaf `index` of column j; for a bad COL, leaf `index` of row j)
        proof = ortho.prove_range(index, index + 1)
        shares.append(
            ShareWithProof(
                position=j,
                share=eds.squares[r, c].tobytes(),
                proof=proof,
            )
        )
    return BadEncodingProof(axis=axis, index=index, shares=tuple(shares))


def _decode_axis(symbols: np.ndarray, present: list[int], k: int) -> np.ndarray:
    """Unique-codeword reconstruction from EXACTLY k present shares. With
    k shares the system is exactly determined, so the fused decode-matrix
    matmul and the FWHT error-locator path produce identical bytes
    (tests/test_repair.py); take the matmul only when its closure is
    already cached — a one-shot BEFP must not pay a jit compile."""
    pattern = tuple(sorted(present))
    # atomic get, gated on the batch-1 bucket being compiled: neither a
    # build nor a jit retrace may stall the gossip-rate path
    run = rs.repair_axes_get(k, pattern, batch_size=1)
    if run is not None:
        return np.asarray(run(symbols[None]))[0]
    return rs.repair_axis(symbols, list(present))


def _expected_axis_root(recovered: np.ndarray, axis: str, index: int,
                        k: int) -> bytes:
    """Root the header SHOULD carry for the decoded axis — BLIND leaf
    append (no namespace-order enforcement): a fraudulent row decodes to
    arbitrary prefixes, and the comparison is against whatever the
    producer committed, ordered or not. Fast path: the batched device NMT
    reduction (ops/nmt.eds_axis_roots, shared with the repair sweep
    engine) once its batch-1 program is warm, so a DASer fleet checks
    fraud proofs at gossip rate; the shared host recompute
    (da/repair._axis_root) covers cold programs and device failure,
    bit-identically."""
    from celestia_app_tpu.da import repair
    from celestia_app_tpu.ops import nmt
    from celestia_app_tpu.utils import telemetry

    recovered = np.ascontiguousarray(recovered, dtype=np.uint8)
    slab = recovered.reshape(2 * k, -1)
    # no-compile-on-gossip-path invariant (same as _decode_axis): a cold
    # (k, batch-1) program would stall the first verification for a full
    # XLA compile; until something has warmed it, the shared host
    # recompute (da/repair._axis_root — repair and BEFP verification
    # must agree on leaf construction, so there is exactly ONE host
    # implementation of the blind axis tree) IS gossip-rate
    if nmt.eds_axis_roots_compiled(k, 1):
        try:
            return nmt.eds_axis_roots(slab[None], [index], k)[0].tobytes()
        except Exception as e:
            # device/backend failure must not decide a fraud verdict:
            # fall back to the host tree (bit-identical) and count it
            telemetry.incr("fraud.device_root_fallbacks")
            from celestia_app_tpu import obs

            obs.get_logger("da.fraud").warning(
                "device axis-root recompute failed; host fallback", err=e)
    return repair._axis_root(slab, axis, index, k)


def verify_befp(dah: DataAvailabilityHeader, befp: BadEncodingProof) -> bool:
    """True iff the proof demonstrates the header commits a non-codeword.

    False for malformed proofs AND for honest blocks (where the decoded
    codeword reproduces the committed root)."""
    try:
        width = len(dah.row_roots)
        k = width // 2
        if befp.axis not in ("row", "col") or not 0 <= befp.index < width:
            return False
        if len(befp.shares) != k:
            return False
        ortho_roots = dah.col_roots if befp.axis == "row" else dah.row_roots
        symbols = np.zeros((width, appconsts.SHARE_SIZE), dtype=np.uint8)
        present = []
        seen = set()
        for swp in befp.shares:
            j = swp.position
            if not 0 <= j < width or j in seen or len(swp.share) != appconsts.SHARE_SIZE:
                return False
            seen.add(j)
            r, c = (befp.index, j) if befp.axis == "row" else (j, befp.index)
            ns = leaf_ns(r, c, swp.share, k)
            # the share must be committed at leaf `index` of orthogonal axis j
            if not swp.proof.verify(ortho_roots[j], [(ns, swp.share)]):
                return False
            if not (swp.proof.start == befp.index and swp.proof.end == befp.index + 1):
                return False
            symbols[j] = np.frombuffer(swp.share, dtype=np.uint8)
            present.append(j)
        # decode the unique codeword those k shares determine: the fused
        # decode-matrix matmul (the repair engine's primitive) when the
        # pattern's closure is already cached, else the FWHT decoder —
        # both reconstruct the same unique codeword from k shares
        recovered = _decode_axis(symbols, present, k)
        expected = _expected_axis_root(recovered, befp.axis, befp.index, k)
        committed = (
            dah.row_roots[befp.index]
            if befp.axis == "row"
            else dah.col_roots[befp.index]
        )
        return expected != committed
    except (ValueError, IndexError, TypeError):
        return False
