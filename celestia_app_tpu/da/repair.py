"""2D EDS repair: reconstruct a damaged extended square from any
sufficient subset of shares, verifying every axis against its committed
NMT root.

Reference parity: rsmt2d's `ExtendedDataSquare.Repair` (the API light
nodes and full nodes use to rebuild a block from sampled/gossiped shares;
rsmt2d repair.go `solveCrossword`). The algorithm is the same crossword
fixpoint: any row or column with ≥ k of its 2k shares present is decoded,
its recomputed NMT root is compared to the DAH's committed root, and the
recovered shares unlock further axes; iterate to fixpoint.

Two engines, bit-identical on every solvable mask (tier-1 differential
sweep, tests/test_repair.py):

- **batched** (default): the device-resident sweep engine. Per sweep,
  unverified axes are grouped by erasure pattern; each pattern's fused
  (2k, k) GF decode matrix (ops/leopard_decode.fused_decode_matrix,
  LRU-cached per (k, pattern) — the precomputed-decode-matrix technique
  of arXiv:2108.02692) reconstructs ALL axes sharing the pattern in one
  MXU bit-matmul (ops/rs.repair_axes_fn), rows and columns alike. Axis
  verification is batched too: every completed axis's NMT root is
  recomputed in one vmapped device reduction per sweep
  (ops/nmt.eds_axis_roots), and fully-present axes take the rsmt2d
  re-encode codeword check as one batched re-extend + compare. A pattern
  group smaller than CELESTIA_REPAIR_MIN_BATCH pays the scalar FWHT
  solver only when its decode closure has not already COMPILED this
  batch bucket (jit compiles per shape; batches pad to power-of-two
  buckets so per-pattern compiles are bounded) — a warm singleton still
  takes the matmul path.
- **scalar** (engine="scalar" / CELESTIA_REPAIR_ENGINE=scalar): the
  host-side per-axis path — Leopard's FWHT error-locator decode
  (ops/rs.repair_axis) plus a host NmtTree per axis — kept as the
  independent differential reference.

Mesh sharding (the mesh plane, PR 13): when
`parallel/mesh_engine.mesh_active_for(k)` holds — k at or above
CELESTIA_MESH_MIN_K with two or more devices — the batched engine's two
device programs run sharded over the flat device list: the per-pattern
fused decode matmul (ops/rs._RepairAxesRunner) and the per-sweep NMT
root reduction (ops/nmt.eds_axis_roots) both split their pow2-padded
batch dimension across devices before dispatch. The programs themselves
are untouched (jit partitions by input sharding), so mesh-sharded and
single-device sweeps are bit-identical by construction — k=256/512
repair is the same crossword, spread over the ICI.

Byzantine detection: when the input shares are AUTHENTIC (each proven
against the DAH before being fed here — the caller's job, as in DAS), a
root mismatch on a repaired or fully-present axis means the block
producer committed a NON-CODEWORD. That axis is exactly what a
bad-encoding fraud proof indicts: the raised `BadEncodingError` carries
(axis, index) ready for `da/fraud.generate_befp` (specs fraud_proofs.md;
rsmt2d ErrByzantineData semantics). Root-gating alone does NOT suffice
under batching: the matmul reconstructs from the first k sorted present
positions, and a corrupt present share OUTSIDE that use-set would leave
a root that matches the committed non-codeword (the reconstruction of
the missing cells equals what the producer committed). So the batched
path re-encode-checks every present position against the matmul output
(at the use positions the match holds by construction); a mismatching
axis holds inconsistent authentic shares and is re-decoded with the
scalar FWHT path, making its bytes — and therefore its root verdict —
identical to the scalar engine's on EVERY input, not just solvable
masks. Consistent axes get only their missing positions written back.
Error attribution is deterministic in both engines: rows are verified
before columns within a sweep, each in ascending index order, and for a
fully-present axis the re-encode check precedes the root check.
"""

from __future__ import annotations

import os

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu import obs
from celestia_app_tpu.ops import rs
from celestia_app_tpu.utils import nmt_host, telemetry

NS = appconsts.NAMESPACE_SIZE
SHARE = appconsts.SHARE_SIZE


def _min_device_batch() -> int:
    """Pattern groups below this size take the scalar FWHT solver UNLESS
    their decode closure has already compiled this batch bucket (compile
    cost is the only reason to prefer scalar; a compiled shape has
    none). Mirrors the admission plane's CELESTIA_ADMISSION_MIN_BATCH
    convention."""
    try:
        return max(1, int(os.environ.get("CELESTIA_REPAIR_MIN_BATCH", "2")))
    except ValueError:
        return 2


def _engine() -> str:
    return os.environ.get("CELESTIA_REPAIR_ENGINE", "batched")


class BadEncodingError(Exception):
    """A verified-share axis failed its committed root: the producer
    committed a non-codeword (rsmt2d ErrByzantineData). Carries what
    generate_befp needs to build the fraud proof."""

    def __init__(self, axis: str, index: int):
        self.axis = axis
        self.index = index
        super().__init__(
            f"{axis} {index} does not match its committed root: "
            "the square is not a valid codeword (bad encoding)"
        )


def _axis_root(slab: np.ndarray, axis: str, index: int, k: int) -> bytes:
    """Committed-root recomputation for one full axis of 2k shares, using
    the ONE leaf-namespace rule shared with the fraud prover
    (da/fraud.leaf_ns) — repair and BEFP verification must agree on leaf
    construction or the BadEncodingError handoff breaks."""
    from celestia_app_tpu.da.fraud import leaf_ns

    tree = nmt_host.NmtTree()
    for j in range(2 * k):
        r, c = (index, j) if axis == "row" else (j, index)
        share = slab[j].tobytes()
        tree.leaves.append((leaf_ns(r, c, share, k), share))
    return nmt_host.serialize(tree.root())


def _validate(symbols, present, row_roots, col_roots):
    symbols = np.array(symbols, dtype=np.uint8, copy=True)
    present = np.array(present, dtype=bool, copy=True)
    two_k = symbols.shape[0]
    if symbols.shape != (two_k, two_k, SHARE):
        raise ValueError(f"bad square shape {symbols.shape}")
    if present.shape != (two_k, two_k):
        raise ValueError(f"bad mask shape {present.shape}")
    if len(row_roots) != two_k or len(col_roots) != two_k:
        raise ValueError("need 2k row roots and 2k col roots")
    return symbols, present, two_k


def _unsolvable(present: np.ndarray) -> ValueError:
    missing = int((~present).sum())
    return ValueError(
        f"unsolvable erasure pattern: {missing} shares still "
        "missing and no row or column has k known shares"
    )


def repair_eds(
    symbols: np.ndarray,
    present: np.ndarray,
    row_roots: list[bytes],
    col_roots: list[bytes],
    *,
    engine: str | None = None,
    traces=None,
) -> np.ndarray:
    """Rebuild the full (2k, 2k, 512) EDS from the shares marked present.

    `symbols` may hold arbitrary bytes at missing positions; `present` is
    the (2k, 2k) bool mask of authentic shares. Raises ValueError when the
    erasure pattern is unsolvable, BadEncodingError when a completed axis
    contradicts its committed root. Returns the repaired square; on
    success every row/column root has been verified.

    `engine` picks "batched" (device sweep engine, the default) or
    "scalar" (per-axis host reference); `traces` pins the span sink
    (a light node passes its own TraceTables)."""
    engine = engine or _engine()
    if engine not in ("batched", "scalar"):
        raise ValueError(f"repair engine must be 'batched' or 'scalar', "
                         f"not {engine!r}")
    symbols, present, two_k = _validate(symbols, present,
                                        row_roots, col_roots)
    if engine == "scalar":
        return _repair_scalar(symbols, present, row_roots, col_roots,
                              two_k, traces)
    return _repair_batched(symbols, present, row_roots, col_roots,
                           two_k, traces)


# ---------------------------------------------------------------------------
# scalar engine: the per-axis host reference (FWHT decode + host NmtTree)
# ---------------------------------------------------------------------------


def _repair_scalar(symbols, present, row_roots, col_roots, two_k,
                   traces) -> np.ndarray:
    k = two_k // 2
    verified_rows = [False] * two_k
    verified_cols = [False] * two_k

    def _is_codeword(slab: np.ndarray) -> bool:
        """rsmt2d's re-encode check: a FULLY-PRESENT axis must itself be
        a valid codeword (re-extend its systematic half, demand byte
        identity). Axes completed by decoding are root-gated against the
        commitment, but a fully-present axis would otherwise sail
        through on a root match alone — committed trees over a
        non-codeword match their own leaves (rsmt2d ErrByzantineData
        covers exactly this)."""
        rec = rs.repair_axis(slab, list(range(k)))
        return bool(np.array_equal(rec.reshape(two_k, SHARE),
                                   np.asarray(slab)))

    def _finish_row(r: int, check_rs: bool = False) -> None:
        if check_rs and not _is_codeword(symbols[r]):
            raise BadEncodingError("row", r)
        if _axis_root(symbols[r], "row", r, k) != row_roots[r]:
            raise BadEncodingError("row", r)
        verified_rows[r] = True

    def _finish_col(c: int, check_rs: bool = False) -> None:
        if check_rs and not _is_codeword(symbols[:, c, :]):
            raise BadEncodingError("col", c)
        if _axis_root(symbols[:, c, :], "col", c, k) != col_roots[c]:
            raise BadEncodingError("col", c)
        verified_cols[c] = True

    sweep = 0
    while True:
        sweep += 1
        progress = False
        with obs.span("da.repair.sweep", traces=traces, engine="scalar",
                      sweep=sweep):
            for r in range(two_k):
                if verified_rows[r]:
                    continue
                n = int(present[r].sum())
                if n == two_k:
                    _finish_row(r, check_rs=True)
                    progress = True
                elif n >= k:
                    rec = rs.repair_axis(
                        symbols[r], list(np.flatnonzero(present[r]))
                    )
                    symbols[r] = rec.reshape(two_k, SHARE)
                    telemetry.incr("repair.axes_scalar")
                    _finish_row(r)
                    present[r] = True
                    progress = True
            for c in range(two_k):
                if verified_cols[c]:
                    continue
                n = int(present[:, c].sum())
                if n == two_k:
                    _finish_col(c, check_rs=True)
                    progress = True
                elif n >= k:
                    rec = rs.repair_axis(
                        symbols[:, c, :], list(np.flatnonzero(present[:, c]))
                    )
                    symbols[:, c, :] = rec.reshape(two_k, SHARE)
                    telemetry.incr("repair.axes_scalar")
                    _finish_col(c)
                    present[:, c] = True
                    progress = True
        if all(verified_rows) and all(verified_cols):
            return symbols
        if not progress:
            raise _unsolvable(present)


# ---------------------------------------------------------------------------
# batched engine: per-pattern matmul decode + per-sweep batched verification
# ---------------------------------------------------------------------------


def _axis_slab(symbols: np.ndarray, axis: str, i: int) -> np.ndarray:
    return symbols[i] if axis == "row" else symbols[:, i, :]


def _decode_phase(symbols, present, axis: str, verified, two_k: int) -> tuple:
    """Decode every repairable axis of one orientation. Returns
    (completed, full_set): `completed` is the ascending list of axis
    indices now holding all 2k shares (decoded this phase or fully
    present on entry), `full_set` the subset that was fully present
    (those owe the re-encode codeword check)."""
    k = two_k // 2
    min_batch = _min_device_batch()
    counts = present.sum(axis=1) if axis == "row" else present.sum(axis=0)
    full, patterns = [], {}
    for i in range(two_k):
        if verified[i]:
            continue
        n = int(counts[i])
        if n == two_k:
            full.append(i)
        elif n >= k:
            mask = present[i] if axis == "row" else present[:, i]
            patterns.setdefault(
                tuple(np.flatnonzero(mask).tolist()), []
            ).append(i)
    decoded = []
    for pattern, axes in patterns.items():
        if len(axes) >= min_batch:
            run = rs.repair_axes_fn(k, pattern)
        else:
            # cached-singleton policy: one atomic get (no peek-then-build
            # race), gated on THIS batch bucket having executed — a cold
            # small group goes scalar, never a jit build or retrace
            run = rs.repair_axes_get(k, pattern, batch_size=len(axes))
        if run is not None:
            # one fused decode+re-encode bit-matmul for the whole group.
            # The re-encode from the first k sorted present positions must
            # REPRODUCE every present share (at the use positions it does
            # so by construction; beyond them it is the rsmt2d consistency
            # check): a mismatching axis holds inconsistent authentic
            # shares, and it is re-decoded with the scalar FWHT path so
            # its bytes — and the root verdict they produce — are
            # identical to the scalar engine's. Consistent axes get ONLY
            # their missing positions written back.
            pres = list(pattern)
            miss = sorted(set(range(two_k)) - set(pattern))
            out = np.asarray(
                run(np.stack([_axis_slab(symbols, axis, i) for i in axes]))
            )
            n_batched = 0
            for b, i in enumerate(axes):
                slab = _axis_slab(symbols, axis, i)
                if np.array_equal(out[b, pres, :], slab[pres]):
                    if axis == "row":
                        symbols[i, miss, :] = out[b, miss, :]
                    else:
                        symbols[miss, i, :] = out[b, miss, :]
                    n_batched += 1
                else:
                    telemetry.incr("repair.inconsistent_axes")
                    _scalar_decode_axis(symbols, axis, i, pattern, two_k)
            if n_batched:
                telemetry.incr("repair.axes_batched", n_batched)
            if n_batched != len(axes):
                telemetry.incr("repair.axes_scalar", len(axes) - n_batched)
        else:
            for i in axes:
                _scalar_decode_axis(symbols, axis, i, pattern, two_k)
            telemetry.incr("repair.axes_scalar", len(axes))
        decoded += axes
    return sorted(full + decoded), set(full)


def _scalar_decode_axis(symbols, axis: str, i: int, pattern, two_k) -> None:
    rec = rs.repair_axis(
        _axis_slab(symbols, axis, i), list(pattern)
    ).reshape(two_k, SHARE)
    if axis == "row":
        symbols[i] = rec
    else:
        symbols[:, i, :] = rec


def _verify_phase(symbols, present, axis: str, verified, roots,
                  completed, full_set, two_k: int, traces) -> bool:
    """Batched verification of every axis completed this phase: ONE
    device NMT reduction recomputes all their roots, one batched
    re-extend covers the fully-present axes' codeword checks. Raises
    BadEncodingError at the lowest failing index (fully-present axes
    fail their re-encode check before their root check, matching the
    scalar engine's attribution)."""
    from celestia_app_tpu.ops import nmt

    if not completed:
        return False
    k = two_k // 2
    slabs = (symbols[completed] if axis == "row"
             else np.stack([symbols[:, c, :] for c in completed]))
    with obs.span("da.repair.verify_roots", traces=traces, axis=axis,
                  axes=len(completed)):
        codeword_ok = {}
        if full_set:
            ordered = sorted(full_set)
            pos = {i: b for b, i in enumerate(completed)}
            full_slabs = slabs[[pos[i] for i in ordered]]
            rec = np.asarray(
                rs.repair_axes_fn(k, tuple(range(two_k)))(full_slabs)
            )
            for b, i in enumerate(ordered):
                codeword_ok[i] = bool(np.array_equal(rec[b], full_slabs[b]))
        got = nmt.eds_axis_roots(slabs, completed, k)
    for b, i in enumerate(completed):
        if i in full_set and not codeword_ok[i]:
            raise BadEncodingError(axis, i)
        if got[b].tobytes() != roots[i]:
            raise BadEncodingError(axis, i)
        verified[i] = True
        if axis == "row":
            present[i] = True
        else:
            present[:, i] = True
    return True


def _repair_batched(symbols, present, row_roots, col_roots, two_k,
                    traces) -> np.ndarray:
    verified_rows = [False] * two_k
    verified_cols = [False] * two_k
    sweep = 0
    while True:
        sweep += 1
        progress = False
        with obs.span("da.repair.sweep", traces=traces, engine="batched",
                      sweep=sweep) as sp:
            for axis, verified, roots in (
                ("row", verified_rows, row_roots),
                ("col", verified_cols, col_roots),
            ):
                completed, full_set = _decode_phase(
                    symbols, present, axis, verified, two_k
                )
                if _verify_phase(symbols, present, axis, verified, roots,
                                 completed, full_set, two_k, traces):
                    progress = True
            sp.set(progress=progress)
        if all(verified_rows) and all(verified_cols):
            return symbols
        if not progress:
            raise _unsolvable(present)
