"""2D EDS repair: reconstruct a damaged extended square from any
sufficient subset of shares, verifying every axis against its committed
NMT root.

Reference parity: rsmt2d's `ExtendedDataSquare.Repair` (the API light
nodes and full nodes use to rebuild a block from sampled/gossiped shares;
rsmt2d repair.go `solveCrossword`). The algorithm is the same crossword
fixpoint: any row or column with ≥ k of its 2k shares present is decoded
with the Leopard erasure decoder (ops/rs.repair_axis — the FWHT
error-locator path), its recomputed NMT root is compared to the DAH's
committed root, and the recovered shares unlock further axes; iterate to
fixpoint.

Byzantine detection: when the input shares are AUTHENTIC (each proven
against the DAH before being fed here — the caller's job, as in DAS), a
root mismatch on a repaired or fully-present axis means the block
producer committed a NON-CODEWORD. That axis is exactly what a
bad-encoding fraud proof indicts: the raised `BadEncodingError` carries
(axis, index) ready for `da/fraud.generate_befp` (specs fraud_proofs.md;
rsmt2d ErrByzantineData semantics).
"""

from __future__ import annotations

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.ops import rs
from celestia_app_tpu.utils import nmt_host

NS = appconsts.NAMESPACE_SIZE
SHARE = appconsts.SHARE_SIZE


class BadEncodingError(Exception):
    """A verified-share axis failed its committed root: the producer
    committed a non-codeword (rsmt2d ErrByzantineData). Carries what
    generate_befp needs to build the fraud proof."""

    def __init__(self, axis: str, index: int):
        self.axis = axis
        self.index = index
        super().__init__(
            f"{axis} {index} does not match its committed root: "
            "the square is not a valid codeword (bad encoding)"
        )


def _axis_root(slab: np.ndarray, axis: str, index: int, k: int) -> bytes:
    """Committed-root recomputation for one full axis of 2k shares, using
    the ONE leaf-namespace rule shared with the fraud prover
    (da/fraud.leaf_ns) — repair and BEFP verification must agree on leaf
    construction or the BadEncodingError handoff breaks."""
    from celestia_app_tpu.da.fraud import leaf_ns

    tree = nmt_host.NmtTree()
    for j in range(2 * k):
        r, c = (index, j) if axis == "row" else (j, index)
        share = slab[j].tobytes()
        tree.leaves.append((leaf_ns(r, c, share, k), share))
    return nmt_host.serialize(tree.root())


def repair_eds(
    symbols: np.ndarray,
    present: np.ndarray,
    row_roots: list[bytes],
    col_roots: list[bytes],
) -> np.ndarray:
    """Rebuild the full (2k, 2k, 512) EDS from the shares marked present.

    `symbols` may hold arbitrary bytes at missing positions; `present` is
    the (2k, 2k) bool mask of authentic shares. Raises ValueError when the
    erasure pattern is unsolvable, BadEncodingError when a completed axis
    contradicts its committed root. Returns the repaired square; on
    success every row/column root has been verified."""
    symbols = np.array(symbols, dtype=np.uint8, copy=True)
    present = np.array(present, dtype=bool, copy=True)
    two_k = symbols.shape[0]
    k = two_k // 2
    if symbols.shape != (two_k, two_k, SHARE):
        raise ValueError(f"bad square shape {symbols.shape}")
    if present.shape != (two_k, two_k):
        raise ValueError(f"bad mask shape {present.shape}")
    if len(row_roots) != two_k or len(col_roots) != two_k:
        raise ValueError("need 2k row roots and 2k col roots")

    verified_rows = [False] * two_k
    verified_cols = [False] * two_k

    def _is_codeword(slab: np.ndarray) -> bool:
        """rsmt2d's re-encode check: a FULLY-PRESENT axis must itself be
        a valid codeword (re-extend its systematic half, demand byte
        identity). Axes completed by decoding are codewords by
        construction, but a fully-present axis would otherwise sail
        through on a root match alone — committed trees over a
        non-codeword match their own leaves (rsmt2d ErrByzantineData
        covers exactly this)."""
        rec = rs.repair_axis(slab, list(range(k)))
        return bool(np.array_equal(rec.reshape(two_k, SHARE),
                                   np.asarray(slab)))

    def _finish_row(r: int, check_rs: bool = False) -> None:
        if check_rs and not _is_codeword(symbols[r]):
            raise BadEncodingError("row", r)
        if _axis_root(symbols[r], "row", r, k) != row_roots[r]:
            raise BadEncodingError("row", r)
        verified_rows[r] = True

    def _finish_col(c: int, check_rs: bool = False) -> None:
        if check_rs and not _is_codeword(symbols[:, c, :]):
            raise BadEncodingError("col", c)
        if _axis_root(symbols[:, c, :], "col", c, k) != col_roots[c]:
            raise BadEncodingError("col", c)
        verified_cols[c] = True

    while True:
        progress = False
        # batched fast path: rows sharing one erasure pattern (whole
        # columns missing — the dominant DA-repair shape) are decoded in a
        # single device bit-matmul (ops/rs.repair_axes_fn). The per-axis
        # root check below still gates every repaired row, so the batched
        # re-encode cannot mask a byzantine axis.
        patterns: dict[tuple[int, ...], list[int]] = {}
        for r in range(two_k):
            if verified_rows[r]:
                continue
            n = int(present[r].sum())
            if k <= n < two_k:
                patterns.setdefault(
                    tuple(np.flatnonzero(present[r]).tolist()), []
                ).append(r)
        for pattern, rows in patterns.items():
            if len(rows) < 2:
                continue
            run = rs.repair_axes_fn(k, pattern)
            out = np.asarray(run(symbols[rows]))
            for i, r in enumerate(rows):
                symbols[r] = out[i]
                _finish_row(r)
                present[r] = True
                progress = True
        for r in range(two_k):
            if verified_rows[r]:
                continue
            n = int(present[r].sum())
            if n == two_k:
                _finish_row(r, check_rs=True)
                progress = True
            elif n >= k:
                rec = rs.repair_axis(
                    symbols[r], list(np.flatnonzero(present[r]))
                )
                symbols[r] = rec.reshape(two_k, SHARE)
                _finish_row(r)
                present[r] = True
                progress = True
        for c in range(two_k):
            if verified_cols[c]:
                continue
            n = int(present[:, c].sum())
            if n == two_k:
                _finish_col(c, check_rs=True)
                progress = True
            elif n >= k:
                rec = rs.repair_axis(
                    symbols[:, c, :], list(np.flatnonzero(present[:, c]))
                )
                symbols[:, c, :] = rec.reshape(two_k, SHARE)
                _finish_col(c)
                present[:, c] = True
                progress = True
        if all(verified_rows) and all(verified_cols):
            return symbols
        if not progress:
            missing = int((~present).sum())
            raise ValueError(
                f"unsolvable erasure pattern: {missing} shares still "
                "missing and no row or column has k known shares"
            )
