"""Batched namespace-range search over the resident NMT level stacks.

The read plane's resolver (PAPER §1's millions-of-readers workload,
reference ``pkg/proof`` + the x/blob query surface): one serving node
answers many ``(namespace, height)`` queries per request, so the
per-query host scan in `da/namespace_data.get_namespace_data` — k²
Python slice-compares per query — must become ONE dispatch over the
whole batch. The level-0 ``mins`` of the prover's cached row trees
(da/proof_device.BlockProver.levels — the arrays the block lifecycle's
device pass already produced) ARE the Q0 share namespaces, so the
namespace → share-range search is a single vectorized equality over a
``(queries, k², 29)`` comparison, on device (one jitted dispatch) or on
host SIMD — no square traversal, no per-share Python.

Byte-identity contract: the search only picks each query's contiguous
hit range; proof assembly then runs the SAME ``prover.prove_shares`` /
absence-successor walk the host reference runs, so every returned
`NamespaceData` is byte-identical to `get_namespace_data`'s — pinned
per engine in tests/test_read_plane.py.

Engine gating is the edscache/commitment_device playbook:

- "host" never imports (let alone dispatches) jax — a validator next to
  a dead TPU relay must not hang resolving a read;
- "device"/"mesh" run the jitted search, but a dispatch failure here
  falls back to the host pass COUNTED (``blob.device_fallbacks``),
  never raised — reads are a serving surface, not a consensus phase;
- "auto" uses the device only at/above the ``CELESTIA_BLOB_MIN_BATCH``
  gate (below it the fixed dispatch overhead loses to host SIMD).

The small share→namespace helpers at the bottom are THE one
implementation the DA service's prove_shares route and the blob pack
builder share (service/da_service.py, das/blob_packs.py).
"""

from __future__ import annotations

import functools
import os

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da.namespace_data import (
    NamespaceData,
    _root_window,
    get_namespace_data,
)
from celestia_app_tpu.utils import telemetry

NS = appconsts.NAMESPACE_SIZE


def _min_device_batch() -> int:
    """Queries below this gate resolve on host even under engine="auto"
    (env knob CELESTIA_BLOB_MIN_BATCH; det-reach barrier — both paths
    are pinned byte-identical, so the knob can only move work, never
    change bytes)."""
    return int(os.environ.get("CELESTIA_BLOB_MIN_BATCH", "16"))


# -- shared share→namespace helpers (one implementation; satellite of the
#    read plane: service/da_service.py and das/blob_packs.py call these) --


def decode_namespace(value: str) -> bytes:
    """Hex-decode a namespace request field ('' stays empty — callers
    may default it from a share). Raises ValueError on non-hex input."""
    return bytes.fromhex(value)


def parse_namespace(value: str) -> bytes:
    """Strict form: hex-decode AND require exactly 29 bytes."""
    ns = decode_namespace(value)
    if len(ns) != NS:
        raise ValueError(f"namespace must be {NS} bytes, got {len(ns)}")
    return ns


def share_namespace(share) -> bytes:
    """The 29-byte namespace prefix of one share (bytes or an ODS array
    cell)."""
    if isinstance(share, (bytes, bytearray, memoryview)):
        return bytes(share[:NS])
    return np.asarray(share).tobytes()[:NS]


def leaf_namespaces(prover) -> np.ndarray:
    """(k², 29) uint8: every Q0 share's namespace in row-major order,
    read straight off the prover's resident level-0 ``mins`` (an NMT
    leaf's min IS its namespace) — no ODS materialization, which on a
    mesh DeviceEntry would cost a device→host crossing."""
    from celestia_app_tpu.obs import xfer

    mins = prover.levels[0][0]
    k = prover.k
    # a mesh DeviceEntry keeps `mins` resident: the k×k corner crosses
    # the boundary counted; host provers pass through copy-free
    sub = xfer.ensure_host(mins[:k, :k], "namespace.leaf_mins")
    # reshape of the strided corner always lands in fresh C-order
    # memory (and a materialized device slice is already contiguous)
    return sub.reshape(k * k, NS)


# -- the batched search -----------------------------------------------------


def _as_query_matrix(namespaces) -> np.ndarray:
    """(Q, 29) uint8 from the query namespaces; validates lengths with
    the host reference's error."""
    for ns in namespaces:
        if len(ns) != NS:
            raise ValueError(f"namespace must be {NS} bytes")
    return np.frombuffer(b"".join(namespaces), dtype=np.uint8).reshape(
        len(namespaces), NS
    )


def _search_host(leaf_ns: np.ndarray, qs: np.ndarray):
    """(starts, ends, counts) per query — one SIMD pass, no Python per
    share. Namespaces compare as fixed-width void scalars (memcmp), so
    the (Q, k²) equality matrix is the only intermediate."""
    n = leaf_ns.shape[0]
    void = np.dtype((np.void, NS))
    leaf_v = np.ascontiguousarray(leaf_ns).view(void).reshape(n)
    qs_v = np.ascontiguousarray(qs).view(void).reshape(qs.shape[0])
    eq = qs_v[:, None] == leaf_v[None, :]
    idx = np.arange(n)
    starts = np.where(eq, idx, n).min(axis=1)
    ends = np.where(eq, idx + 1, 0).max(axis=1)
    return starts, ends, eq.sum(axis=1)


@functools.lru_cache(maxsize=None)
def _jitted_search(n_leaves: int, n_queries: int):
    """Compiled (leaf_ns, qs) -> (starts, ends, counts); query counts
    are padded to powers of two by the caller so the compile cache stays
    small."""
    import jax
    import jax.numpy as jnp

    def run(leaf_ns: "jax.Array", qs: "jax.Array"):
        eq = jnp.all(leaf_ns[None, :, :] == qs[:, None, :], axis=-1)
        idx = jnp.arange(n_leaves, dtype=jnp.int32)
        starts = jnp.min(jnp.where(eq, idx, n_leaves), axis=1)
        ends = jnp.max(jnp.where(eq, idx + 1, 0), axis=1)
        return starts, ends, jnp.sum(eq.astype(jnp.int32), axis=1)

    return jax.jit(run)


# queries never legitimately target the parity namespace (it labels
# extended-quadrant shares only), so it is the safe device-pad value
_PAD_NS = b"\xff" * NS


def _search_device(leaf_ns: np.ndarray, qs: np.ndarray):
    """One engine dispatch for the whole batch. May raise (jax missing,
    relay down, OOM) — the caller degrades to the host pass, counted."""
    q = qs.shape[0]
    padded = 1 << max(0, (q - 1)).bit_length()
    if padded != q:
        pad = np.frombuffer(_PAD_NS * (padded - q),
                            dtype=np.uint8).reshape(padded - q, NS)
        qs = np.concatenate([qs, pad], axis=0)
    starts, ends, counts = _jitted_search(leaf_ns.shape[0], padded)(
        leaf_ns, qs
    )
    return (np.asarray(starts)[:q], np.asarray(ends)[:q],
            np.asarray(counts)[:q])


def _absence_data(prover, namespace: bytes) -> NamespaceData:
    """The host reference's absence walk, verbatim semantics
    (da/namespace_data.get_namespace_data lines after the hit scan):
    first straddling Q0 row window → one-leaf successor proof; no
    straddling row → no proof needed."""
    k = prover.k
    ods = prover.eds.squares
    for r in range(k):
        lo, hi = _root_window(prover.dah.row_roots[r])
        if lo <= namespace <= hi:
            succ = next(
                c for c in range(k)
                if ods[r, c, :NS].tobytes() > namespace
            )
            pf = prover.prove_shares(
                r * k + succ, r * k + succ + 1,
                ods[r, succ, :NS].tobytes(),
            )
            return NamespaceData(namespace=namespace, shares=[], proof=pf)
    return NamespaceData(namespace=namespace, shares=[], proof=None)


def get_namespace_data_batched(prover, namespaces,
                               engine: str = "auto") -> list[NamespaceData]:
    """Resolve many namespace queries against one block in one pass.

    Returns one `NamespaceData` per query, in request order, each
    byte-identical to ``get_namespace_data(prover, ns)`` (pinned in
    tests/test_read_plane.py). The search runs batched (device or host
    SIMD per the engine gate); proof assembly is the host reference's
    own machinery either way."""
    namespaces = list(namespaces)
    if not namespaces:
        return []
    qs = _as_query_matrix(namespaces)
    leaf_ns = leaf_namespaces(prover)
    want_device = engine in ("device", "mesh") or (
        engine == "auto" and len(namespaces) >= _min_device_batch()
    )
    starts = None
    if want_device and engine != "host":
        try:
            starts, ends, counts = _search_device(leaf_ns, qs)
            telemetry.incr("blob.device_batches")
        except Exception:
            # reads are a serving surface: a dead relay or missing jax
            # degrades to the host pass, loudly counted, never raised
            telemetry.incr("blob.device_fallbacks")
            starts = None
    if starts is None:
        starts, ends, counts = _search_host(leaf_ns, qs)
    out: list[NamespaceData] = []
    for i, namespace in enumerate(namespaces):
        count = int(counts[i])
        if count == 0:
            out.append(_absence_data(prover, namespace))
            continue
        start, end = int(starts[i]), int(ends[i])
        if end - start != count:
            raise AssertionError(
                "namespace shares are not contiguous: square is not sorted"
            )
        pf = prover.prove_shares(start, end, namespace)
        out.append(NamespaceData(
            namespace=namespace,
            shares=[bytes(s) for s in pf.data],
            proof=pf,
        ))
    return out


__all__ = [
    "NS",
    "decode_namespace",
    "parse_namespace",
    "share_namespace",
    "leaf_namespaces",
    "get_namespace_data",
    "get_namespace_data_batched",
]
