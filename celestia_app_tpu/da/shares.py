"""Share codec: the 512-byte atomic units of the data square.

Byte-exact implementation of specs/src/specs/shares.md (reference
implementation: go-square/shares):

  share := namespace(29) || info(1) || [sequence_len(4, BE, first share only)]
           || [reserved(4, BE, compact shares only)] || data || zero-fill
  info  := share_version(7 bits) << 1 | sequence_start(1 bit)

Sparse shares carry blob data (one blob = one sequence). Compact shares carry
the length-delimited (uvarint-prefixed) transactions of a reserved namespace
as a single sequence, with 4 reserved bytes holding the in-share offset of the
first unit that starts in the share (0 if none). Padding shares
(namespace/primary-reserved/tail) have sequence_start=1, sequence_len=0 and a
zero body.
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts as c
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.da.namespace import Namespace


def uvarint(n: int) -> bytes:
    """Protobuf unsigned varint encoding."""
    if n < 0:
        raise ValueError("uvarint of negative value")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(data: bytes, offset: int) -> tuple[int, int]:
    """Decode a uvarint at `offset`; returns (value, next_offset)."""
    result = 0
    shift = 0
    while True:
        if offset >= len(data):
            raise ValueError("truncated uvarint")
        b = data[offset]
        offset += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, offset
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


@dataclasses.dataclass(frozen=True)
class Share:
    raw: bytes

    def __post_init__(self):
        if len(self.raw) != c.SHARE_SIZE:
            raise ValueError(f"share must be {c.SHARE_SIZE} bytes, got {len(self.raw)}")

    @property
    def namespace(self) -> Namespace:
        return Namespace(self.raw[: c.NAMESPACE_SIZE])

    @property
    def info_byte(self) -> int:
        return self.raw[c.NAMESPACE_SIZE]

    @property
    def version(self) -> int:
        return self.info_byte >> 1

    @property
    def is_sequence_start(self) -> bool:
        return bool(self.info_byte & 1)

    def sequence_len(self) -> int:
        if not self.is_sequence_start:
            raise ValueError("sequence_len only present on the first share")
        off = c.NAMESPACE_SIZE + c.SHARE_INFO_BYTES
        return int.from_bytes(self.raw[off : off + c.SEQUENCE_LEN_BYTES], "big")

    def is_compact(self) -> bool:
        return self.namespace in (ns_mod.TX_NAMESPACE, ns_mod.PAY_FOR_BLOB_NAMESPACE)

    def is_padding(self) -> bool:
        return self.is_sequence_start and not self.is_compact() and self.sequence_len() == 0

    def content(self) -> bytes:
        """Data region (after header fields; includes any zero fill)."""
        off = c.NAMESPACE_SIZE + c.SHARE_INFO_BYTES
        if self.is_sequence_start:
            off += c.SEQUENCE_LEN_BYTES
        if self.is_compact():
            off += c.SHARE_RESERVED_BYTES
        return self.raw[off:]


def _info_byte(version: int, sequence_start: bool) -> int:
    if version not in c.SUPPORTED_SHARE_VERSIONS:
        raise ValueError(f"unsupported share version {version}")
    return (version << 1) | int(sequence_start)


# ---------------------------------------------------------------------------
# Sparse (blob) shares
# ---------------------------------------------------------------------------


def sparse_shares_needed(blob_len: int) -> int:
    """Number of shares a blob of `blob_len` bytes occupies."""
    if blob_len <= c.FIRST_SPARSE_SHARE_CONTENT_SIZE:
        return 1
    rest = blob_len - c.FIRST_SPARSE_SHARE_CONTENT_SIZE
    return 1 + -(-rest // c.CONTINUATION_SPARSE_SHARE_CONTENT_SIZE)


def split_blob(ns: Namespace, data: bytes, share_version: int = 0) -> list[Share]:
    """Share-split a blob (shares.md "Share Splitting")."""
    shares: list[Share] = []
    first = True
    pos = 0
    while first or pos < len(data):
        if first:
            header = ns.raw + bytes([_info_byte(share_version, True)]) + len(data).to_bytes(4, "big")
            take = c.FIRST_SPARSE_SHARE_CONTENT_SIZE
        else:
            header = ns.raw + bytes([_info_byte(share_version, False)])
            take = c.CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        chunk = data[pos : pos + take]
        pos += take
        shares.append(Share(header + chunk + b"\x00" * (take - len(chunk))))
        first = False
    return shares


def parse_sparse_shares(shares: list[Share]) -> bytes:
    """Reassemble one blob from its share sequence."""
    if not shares or not shares[0].is_sequence_start:
        raise ValueError("sequence must begin with a start share")
    total = shares[0].sequence_len()
    data = b"".join(s.content() for s in shares)
    if len(data) < total:
        raise ValueError("share sequence shorter than sequence_len")
    return data[:total]


# ---------------------------------------------------------------------------
# Compact (transaction) shares
# ---------------------------------------------------------------------------


def split_txs(ns: Namespace, txs: list[bytes]) -> list[Share]:
    """Encode txs as one compact-share sequence in `ns` (shares.md
    "Transaction Shares"). Each tx is uvarint-length-prefixed; reserved bytes
    point at the in-share offset of the first unit starting in each share."""
    blob = b"".join(uvarint(len(tx)) + tx for tx in txs)
    # Unit start offsets within the concatenated sequence data.
    unit_starts = []
    off = 0
    for tx in txs:
        unit_starts.append(off)
        off += len(uvarint(len(tx))) + len(tx)

    shares: list[Share] = []
    pos = 0
    first = True
    while first or pos < len(blob):
        if first:
            fixed = ns.raw + bytes([_info_byte(0, True)]) + len(blob).to_bytes(4, "big")
            take = c.FIRST_COMPACT_SHARE_CONTENT_SIZE
        else:
            fixed = ns.raw + bytes([_info_byte(0, False)])
            take = c.CONTINUATION_COMPACT_SHARE_CONTENT_SIZE
        content_abs_off = len(fixed) + c.SHARE_RESERVED_BYTES
        starts_here = [u for u in unit_starts if pos <= u < pos + take]
        reserved = (content_abs_off + starts_here[0] - pos) if starts_here else 0
        chunk = blob[pos : pos + take]
        pos += take
        share = fixed + reserved.to_bytes(4, "big") + chunk + b"\x00" * (take - len(chunk))
        shares.append(Share(share))
        first = False
    return shares


def parse_compact_shares(shares: list[Share]) -> list[bytes]:
    """Decode the uvarint-delimited txs of a compact-share sequence."""
    if not shares:
        return []
    if not shares[0].is_sequence_start:
        raise ValueError("compact sequence must begin with a start share")
    total = shares[0].sequence_len()
    if total == 0:
        return []
    data = b"".join(s.content() for s in shares)[:total]
    txs = []
    off = 0
    while off < len(data):
        length, off = read_uvarint(data, off)
        if off + length > len(data):
            raise ValueError("truncated tx in compact shares")
        txs.append(data[off : off + length])
        off += length
    return txs


# ---------------------------------------------------------------------------
# Padding shares
# ---------------------------------------------------------------------------


def _padding_share(ns: Namespace) -> bytes:
    body = ns.raw + bytes([_info_byte(0, True)]) + (0).to_bytes(4, "big")
    return body + b"\x00" * (c.SHARE_SIZE - len(body))


def namespace_padding_share(ns: Namespace) -> Share:
    return Share(_padding_share(ns))


def reserved_padding_share() -> Share:
    return Share(_padding_share(ns_mod.PRIMARY_RESERVED_PADDING_NAMESPACE))


def tail_padding_share() -> bytes:
    return _padding_share(ns_mod.TAIL_PADDING_NAMESPACE)


def tail_padding_shares(n: int) -> list[Share]:
    return [Share(tail_padding_share()) for _ in range(n)]
