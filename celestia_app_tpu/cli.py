"""celestia-appd-style CLI: init, start, status, query, keys, tools.

Reference parity: cmd/celestia-appd/cmd/root.go:53-154 assembles the node
commands (init/start/query/keys/rollback) plus the tools/ binaries. Here:

    python -m celestia_app_tpu init  --home DIR --chain-id ID \
        [--account HEXADDR=BALANCE ...] [--validator HEXADDR=POWER ...]
    python -m celestia_app_tpu start --home DIR [--listen PORT] \
        [--block-time SECONDS] [--blocks N]
    python -m celestia_app_tpu status --home DIR
    python -m celestia_app_tpu query --home DIR PATH [JSON_DATA]
    python -m celestia_app_tpu keys derive SEED
    python -m celestia_app_tpu rollback --home DIR HEIGHT
    python -m celestia_app_tpu export --home DIR
    python -m celestia_app_tpu blocktime --home DIR [--last N]
    python -m celestia_app_tpu blockscan --home DIR
    python -m celestia_app_tpu txsim --home DIR [--rounds N ...]
    python -m celestia_app_tpu tx send|pay-for-blob --home DIR --from-seed S ...
    python -m celestia_app_tpu devnet --home DIR [--validators N] [--load]
    python -m celestia_app_tpu snapshot create|restore --home DIR --out DIR

`start` runs the single-process node loop (chain/node.py) with the HTTP
service attached; state persists under --home/data and survives restarts.
`devnet` runs an N-validator consensus network in-process (local_devnet
analog); `snapshot` is verified state-sync for fresh-home bootstrap.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


# Apps opened by _make_app during one cli.main() call; main() closes the
# ones ITS dispatch opened on the way out. A real CLI process exits anyway,
# but in-process callers (tests, tools embedding cli.main) would otherwise
# leak the storage engine's writer flock until GC and wedge the next
# command on the home. Weakrefs: direct _make_app callers (outside main)
# own their app's lifecycle — the registry must not pin those forever.
_OPEN_APPS: list = []  # list[weakref.ref[App]]


def _make_app(home: str):
    from celestia_app_tpu import appconsts
    from celestia_app_tpu.chain.app import App

    cfg_path = os.path.join(home, "config.json")
    with open(cfg_path) as f:
        cfg = json.load(f)
    app = App(
        chain_id=cfg["chain_id"],
        app_version=cfg.get("app_version", 1),
        engine=cfg.get("engine", "auto"),
        data_dir=os.path.join(home, "data"),
        min_gas_price=cfg.get("min_gas_price", appconsts.DEFAULT_MIN_GAS_PRICE),
        invariant_check_period=cfg.get("invariant_check_period", 0),
        v2_upgrade_height=cfg.get("v2_upgrade_height"),
        upgrade_height_delay=cfg.get("upgrade_height_delay"),
        da_scheme=cfg.get("da_scheme", "rs2d-nmt"),
        pack_keep=cfg.get("pack_keep", 4),
        max_square_size=cfg.get("max_square_size"),
    )
    import weakref

    _OPEN_APPS.append(weakref.ref(app))
    latest = app.db.latest_height()
    if latest is None:
        with open(os.path.join(home, "genesis.json")) as f:
            genesis = json.load(f)
        app.init_chain(genesis)
    else:
        app.load()
    return app, cfg


def _mempool_kwargs(cfg: dict) -> dict:
    """CAT pool knobs from <home>/config.json -> Node(...) kwargs (one
    reader for every command that builds a Node)."""
    from celestia_app_tpu import appconsts

    return {
        "mempool_ttl": cfg.get(
            "mempool_ttl_blocks", appconsts.MEMPOOL_TX_TTL_BLOCKS),
        "mempool_ttl_seconds": cfg.get(
            "mempool_ttl_seconds", appconsts.MEMPOOL_TX_TTL_SECONDS),
        "mempool_max_txs": cfg.get(
            "mempool_max_txs", appconsts.MEMPOOL_MAX_TXS),
        "mempool_max_bytes": cfg.get(
            "mempool_max_pool_bytes", appconsts.MEMPOOL_MAX_POOL_BYTES),
    }


def cmd_init(args) -> int:
    from celestia_app_tpu import appconsts

    os.makedirs(args.home, exist_ok=True)
    accounts = []
    for spec in args.account or []:
        addr, bal = spec.split("=")
        accounts.append({"address": addr, "balance": int(bal)})
    validators = []
    for spec in args.validator or []:
        addr, power = spec.split("=")
        validators.append({"operator": addr, "power": int(power)})
    if not accounts:
        # fund the default txsim/dev key ring (`keys derive 0..9` seeds) so
        # a fresh home is immediately usable — the reference's testnode
        # genesis funds its well-known accounts the same way
        from celestia_app_tpu.chain.crypto import PrivateKey

        for i in range(10):
            pk = PrivateKey.from_seed(str(i).encode())
            accounts.append(
                {
                    "address": pk.public_key().address().hex(),
                    "balance": 10**12,  # 1M TIA
                }
            )
    if not validators:
        validators.append({"operator": accounts[0]["address"], "power": 10})
    genesis = {
        "time_unix": time.time(),
        "accounts": accounts,
        "validators": validators,
    }
    with open(os.path.join(args.home, "genesis.json"), "w") as f:
        json.dump(genesis, f, indent=2)
    _write_config(args.home, args.chain_id, engine=args.engine)
    print(f"initialized {args.home} (chain-id {args.chain_id})")
    return 0


def _load_genesis(home: str) -> dict:
    with open(os.path.join(home, "genesis.json")) as f:
        return json.load(f)


def _store_genesis(home: str, genesis: dict) -> None:
    with open(os.path.join(home, "genesis.json"), "w") as f:
        json.dump(genesis, f, indent=2)


def _gentx_sign_doc(doc: dict) -> bytes:
    """Canonical bytes covered by a gentx signature (everything but the
    signature field, sorted-key JSON — the same canonicalization the vote
    and header sign-docs use)."""
    unsigned = {k: v for k, v in doc.items() if k != "signature"}
    return json.dumps(unsigned, sort_keys=True, separators=(",", ":")).encode()


def cmd_genesis_add_account(args) -> int:
    """genutil AddGenesisAccountCmd analog (ref cmd/root.go:130): append a
    funded account to an un-started chain's genesis."""
    genesis = _load_genesis(args.home)
    addr = args.address.lower()
    try:
        if len(bytes.fromhex(addr)) != 20:
            print(f"address {addr!r} is not 20 bytes", file=sys.stderr)
            return 1
    except ValueError:
        print(f"address {addr!r} is not hex", file=sys.stderr)
        return 1
    if int(args.balance) < 0:
        print("balance must be non-negative", file=sys.stderr)
        return 1
    if any(a["address"].lower() == addr for a in genesis.get("accounts", [])):
        print(f"account {addr} already in genesis", file=sys.stderr)
        return 1
    genesis.setdefault("accounts", []).append(
        {"address": addr, "balance": int(args.balance)}
    )
    _store_genesis(args.home, genesis)
    print(f"added {addr} with balance {args.balance}")
    return 0


def cmd_genesis_gentx(args) -> int:
    """genutil GenTxCmd analog (ref cmd/root.go:132): emit a signed
    validator-candidacy document into <home>/gentx/ for collect-gentxs to
    verify and merge. The reference wraps a MsgCreateValidator in a tx;
    the same roles here are (operator, power, pubkey) + signature."""
    from celestia_app_tpu.chain.crypto import PrivateKey

    priv = PrivateKey.from_seed(args.seed.encode())
    pub = priv.public_key()
    doc = {
        "moniker": args.moniker,
        "operator": pub.address().hex(),
        "power": int(args.power),
        "pubkey": pub.compressed.hex(),
    }
    doc["signature"] = priv.sign(_gentx_sign_doc(doc)).hex()
    gdir = os.path.join(args.home, "gentx")
    os.makedirs(gdir, exist_ok=True)
    path = os.path.join(gdir, f"gentx-{doc['operator'][:16]}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"wrote {path}")
    return 0


def cmd_genesis_collect(args) -> int:
    """genutil CollectGenTxsCmd analog (ref cmd/root.go:128): verify every
    gentx in <home>/gentx/ (signature against its own pubkey, operator ==
    address(pubkey), operator funded in genesis) and merge them into the
    genesis validator set."""
    import glob as glob_mod

    from celestia_app_tpu.chain.crypto import PublicKey

    genesis = _load_genesis(args.home)
    funded = {a["address"].lower() for a in genesis.get("accounts", [])}
    validators = {
        v["operator"].lower(): v for v in genesis.get("validators", [])
    }
    n_merged = 0
    merged_ops: set[str] = set()
    for path in sorted(glob_mod.glob(os.path.join(args.home, "gentx", "*.json"))):
        # a gentx file is UNTRUSTED input: any malformed field gets the
        # same "<path>: reason" treatment as a failed signature, never a
        # traceback
        try:
            with open(path) as f:
                doc = json.load(f)
            pub = PublicKey(bytes.fromhex(doc["pubkey"]))
            operator = str(doc["operator"]).lower()
            signature = bytes.fromhex(doc["signature"])
            power = int(doc["power"])
        except (json.JSONDecodeError, KeyError, ValueError, TypeError) as e:
            print(f"{path}: malformed gentx ({type(e).__name__}: {e})",
                  file=sys.stderr)
            return 1
        if operator != pub.address().hex():
            print(f"{path}: operator does not match pubkey", file=sys.stderr)
            return 1
        if not pub.verify(signature, _gentx_sign_doc(doc)):
            print(f"{path}: bad signature", file=sys.stderr)
            return 1
        if operator not in funded:
            print(f"{path}: operator {operator} has no genesis "
                  "account (add-account first)", file=sys.stderr)
            return 1
        if power <= 0:
            print(f"{path}: non-positive power", file=sys.stderr)
            return 1
        if operator in merged_ops:
            print(f"{path}: duplicate gentx for operator {operator} "
                  "(delete the stale file)", file=sys.stderr)
            return 1
        merged_ops.add(operator)
        validators[operator] = {
            "operator": operator,
            "power": power,
            "pubkey": doc["pubkey"],
        }
        n_merged += 1
    genesis["validators"] = list(validators.values())
    _store_genesis(args.home, genesis)
    print(f"collected {n_merged} gentx(s); validator set size "
          f"{len(genesis['validators'])}")
    return 0


def cmd_genesis_validate(args) -> int:
    """genutil ValidateGenesisCmd analog (ref cmd/root.go:133): structural
    checks mirroring what init_chain assumes, so a bad hand-edited genesis
    fails HERE with a message instead of inside the node."""
    from celestia_app_tpu.chain.crypto import PublicKey

    genesis = _load_genesis(args.home)
    errors: list[str] = []
    seen: set[str] = set()
    for i, a in enumerate(genesis.get("accounts", [])):
        addr = str(a.get("address", "")).lower()
        try:
            if len(bytes.fromhex(addr)) != 20:
                errors.append(f"accounts[{i}]: address not 20 bytes")
        except ValueError:
            errors.append(f"accounts[{i}]: address not hex")
        if addr in seen:
            errors.append(f"accounts[{i}]: duplicate address {addr}")
        seen.add(addr)
        try:
            if int(a.get("balance", -1)) < 0:
                errors.append(f"accounts[{i}]: negative balance")
        except (ValueError, TypeError):
            errors.append(f"accounts[{i}]: balance not an integer")
    vals = genesis.get("validators", [])
    if not vals:
        errors.append("no validators")
    for i, v in enumerate(vals):
        try:
            if int(v.get("power", 0)) <= 0:
                errors.append(f"validators[{i}]: non-positive power")
        except (ValueError, TypeError):
            errors.append(f"validators[{i}]: power not an integer")
        try:
            op = bytes.fromhex(str(v.get("operator", "")))
            if len(op) != 20:
                errors.append(f"validators[{i}]: operator not 20 bytes")
        except ValueError:
            errors.append(f"validators[{i}]: operator not hex")
            op = None
        pubhex = v.get("pubkey")
        if pubhex and op is not None:
            try:
                if PublicKey(bytes.fromhex(pubhex)).address() != op:
                    errors.append(
                        f"validators[{i}]: pubkey does not match operator"
                    )
            except Exception:
                errors.append(f"validators[{i}]: malformed pubkey")
    for khex, vhex in genesis.get("raw_modules", {}).items():
        try:
            bytes.fromhex(khex), bytes.fromhex(vhex)
        except ValueError:
            errors.append(f"raw_modules[{khex[:16]}...]: not hex")
            break
    for e in errors:
        print(f"invalid genesis: {e}", file=sys.stderr)
    if not errors:
        print("genesis.json is valid")
    return 1 if errors else 0


# Known-network genesis pins (ref cmd/download_genesis.go:19-24 — the
# command's real value is the hash check, which works offline too).
_GENESIS_SHA256 = {
    "celestia": "9727aac9bbfb021ce7fc695a92f901986421283a891b89e0af97bc9fad187793",
    "mocha-4": "0846b99099271b240b638a94e17a6301423b5e4047f6558df543d6e91db7e575",
    "arabica-10": "fad0a187669f7a2c11bb07f9dc27140d66d2448b7193e186312713856f28e3e1",
    "arabica-11": "77605cee57ce545b1be22402110d4baacac837bdc7fc3f5c74020abf9a08810f",
}


def cmd_download_genesis(args) -> int:
    """cmd/download_genesis.go analog: fetch (or locally verify) a known
    network's genesis and check it against the pinned SHA-256."""
    import hashlib

    from celestia_app_tpu.net import transport

    chain_id = args.chain_id
    if chain_id not in _GENESIS_SHA256:
        print(f"unknown chain-id: {chain_id}. Must be one of: "
              + ", ".join(sorted(_GENESIS_SHA256)), file=sys.stderr)
        return 1
    out = os.path.join(args.home, "genesis.json")
    downloaded = False
    if not os.path.exists(out):
        url = ("https://raw.githubusercontent.com/celestiaorg/networks/"
               f"master/{chain_id}/genesis.json")
        try:
            os.makedirs(args.home, exist_ok=True)
            # raw bytes, not JSON: the pinned sha256 is over the exact
            # served bytes
            data = transport.DEFAULT.request(url, "", raw=True, timeout=10)
            with open(out, "wb") as f:
                f.write(data)
            downloaded = True
        except OSError as e:
            print(f"download failed ({e}); if you already have the file, "
                  f"place it at {out} and re-run to verify its hash",
                  file=sys.stderr)
            return 1
    with open(out, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()
    want = _GENESIS_SHA256[chain_id]
    if digest != want:
        if downloaded:
            # never leave a just-fetched bad file wedging future runs
            os.remove(out)
            print(f"sha256 MISMATCH for {chain_id}: got {digest}, want "
                  f"{want}; removed the downloaded file — re-run to retry",
                  file=sys.stderr)
        else:
            print(f"sha256 MISMATCH for {chain_id}: got {digest}, want "
                  f"{want}; delete {out} to re-download", file=sys.stderr)
        return 1
    print(f"{out}: sha256 verified for {chain_id}")
    return 0


def _write_config(home: str, chain_id: str, engine: str = "auto") -> None:
    """THE node-local config writer (SURVEY §5.6 layer 4 — the reference's
    app.toml/config.toml knobs), shared by `init` and validator/devnet
    home setup so the key set can never drift between them."""
    from celestia_app_tpu import appconsts

    with open(os.path.join(home, "config.json"), "w") as f:
        json.dump(
            {
                "chain_id": chain_id,
                "app_version": 1,
                "engine": engine,
                "da_scheme": "rs2d-nmt",
                # mesh plane (docs/FORMATS.md §18.1): max_square_size
                # raises the CONSENSUS square cap to admit k=256/512
                # (null = reference 128; every validator must match);
                # produce_batch > 1 batch-extends that many planned
                # blocks per device dispatch on the produce path
                "max_square_size": None,
                "produce_batch": 1,
                # serving plane (das/packs.py): newest-N proof packs
                # kept under <home>/packs (0 = keep all, null = off)
                "pack_keep": 4,
                "min_gas_price": appconsts.DEFAULT_MIN_GAS_PRICE,
                "invariant_check_period": 0,
                "v2_upgrade_height": None,
                "upgrade_height_delay": None,
                "mempool_ttl_blocks": appconsts.MEMPOOL_TX_TTL_BLOCKS,
                "mempool_ttl_seconds": appconsts.MEMPOOL_TX_TTL_SECONDS,
                "mempool_max_txs": appconsts.MEMPOOL_MAX_TXS,
                "mempool_max_pool_bytes": appconsts.MEMPOOL_MAX_POOL_BYTES,
            },
            f, indent=2,
        )


def cmd_config(args) -> int:
    """config.Cmd analog (ref cmd/root.go:135): read or set node-local
    config keys in <home>/config.json. `get` with no key prints the whole
    effective config; `set` parses the value as JSON when possible (so
    numbers/null/bools round-trip) and refuses unknown keys — the writer
    above owns the key set."""
    path = os.path.join(args.home, "config.json")
    try:
        with open(path) as f:
            cfg = json.load(f)
    except FileNotFoundError:
        print(f"no config.json in {args.home} — run `init` first",
              file=sys.stderr)
        return 1
    if args.action == "get":
        if args.key is None:
            print(json.dumps(cfg, indent=2))
            return 0
        if args.key not in cfg:
            print(f"unknown config key {args.key!r}; known: "
                  + ", ".join(sorted(cfg)), file=sys.stderr)
            return 1
        print(json.dumps(cfg[args.key]))
        return 0
    if args.key is None or args.value is None:
        print("config set needs KEY and VALUE", file=sys.stderr)
        return 1
    if args.key not in cfg:
        print(f"unknown config key {args.key!r}; known: "
              + ", ".join(sorted(cfg)), file=sys.stderr)
        return 1
    try:
        value = json.loads(args.value)
    except json.JSONDecodeError:
        value = args.value  # bare string
    cfg[args.key] = value
    with open(path, "w") as f:
        json.dump(cfg, f, indent=2)
    print(f"{args.key} = {json.dumps(value)}")
    return 0


def cmd_start(args) -> int:
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.service.server import NodeService

    app, cfg = _make_app(args.home)
    from celestia_app_tpu import appconsts

    if args.trace:
        trace_path = os.path.join(args.home, "data", "store_trace.jsonl")
        os.makedirs(os.path.dirname(trace_path), exist_ok=True)
        app.enable_store_trace(trace_path)
        print(f"store trace -> {trace_path}", file=sys.stderr)
    node = Node(app, **_mempool_kwargs(cfg))
    svc = NodeService(node, port=args.listen)
    svc.serve_background()
    grpc_srv = None
    if args.grpc is not None:
        from celestia_app_tpu.service.grpc_server import GrpcTxServer

        grpc_srv = GrpcTxServer(node, port=args.grpc, lock=svc.lock,
                                da_core=svc.da_core)
    print(
        f"node started: chain {app.chain_id} at height {app.height}, "
        f"http on 127.0.0.1:{svc.port}"
        + (f", grpc on 127.0.0.1:{grpc_srv.port}" if grpc_srv else "")
        + f", block time {args.block_time}s",
        file=sys.stderr,
    )
    snap_interval = cfg.get(
        "snapshot_interval_blocks", appconsts.SNAPSHOT_INTERVAL_BLOCKS
    )
    snap_keep = cfg.get("snapshot_keep_recent", appconsts.SNAPSHOT_KEEP_RECENT)
    snap_root = os.path.join(args.home, "snapshots")
    # mesh plane: produce_batch > 1 plans that many blocks from the
    # mempool and batch-extends them in ONE device dispatch before the
    # per-block rounds run (chain/producer.py; FORMATS §18.1). The
    # planning+extend runs OUTSIDE the service lock — only the per-block
    # consensus round holds it, exactly as with batching off.
    produce_batch = max(1, int(cfg.get("produce_batch", 1)))
    produced = 0
    try:
        while args.blocks is None or produced < args.blocks:
            time.sleep(args.block_time)
            # one plan+warm per BATCH WINDOW (planning B squares per
            # produced block would multiply the greedy layout work by
            # B); a mid-window mempool change just means a per-block
            # extend for the affected heights
            if produce_batch > 1 and produced % produce_batch == 0:
                from celestia_app_tpu.chain import producer

                try:
                    plans = producer.plan_block_squares(
                        app, node._reap(), produce_batch)
                    producer.warm_block_batch(app, plans)
                except Exception as e:
                    print(f"produce prewarm failed: {e}", file=sys.stderr)
            with svc.lock:
                blk, results = node.produce_block()
            produced += 1
            print(
                f"height {blk.header.height}: {len(blk.txs)} txs, "
                f"square {blk.header.square_size}, "
                f"data root {blk.header.data_hash.hex()[:16]}",
                file=sys.stderr,
            )
            if snap_interval and blk.header.height % snap_interval == 0:
                # interval state-sync snapshots with keep-recent pruning
                # (default_overrides.go:294-297: interval 1500, keep 2).
                # Only the state CAPTURE holds the service lock; chunk
                # encoding and disk writes run outside it. Snapshots are
                # auxiliary: any failure is logged, never fatal to block
                # production.
                from celestia_app_tpu.chain import consensus as _cons

                try:
                    with svc.lock:
                        cap = _cons.capture_app_snapshot(app)
                    m, chunks = _cons.encode_app_snapshot(cap)
                    _write_snapshot_files(
                        m, chunks,
                        os.path.join(snap_root, str(blk.header.height)),
                    )
                    _prune_snapshots(snap_root, snap_keep)
                    print(
                        f"snapshot at height {m['height']} "
                        f"({m['n_chunks']} chunks)",
                        file=sys.stderr,
                    )
                except Exception as e:
                    print(f"snapshot at height {blk.header.height} "
                          f"failed: {e}", file=sys.stderr)
    except KeyboardInterrupt:
        pass
    finally:
        svc.shutdown()
        if grpc_srv is not None:
            grpc_srv.stop()
    return 0


def cmd_status(args) -> int:
    from celestia_app_tpu.chain.query import QueryRouter

    app, _ = _make_app(args.home)
    print(json.dumps(QueryRouter(app).query("status", {}), indent=2))
    return 0


def cmd_query(args) -> int:
    from celestia_app_tpu.chain.query import QueryRouter

    app, _ = _make_app(args.home)
    data = json.loads(args.data) if args.data else {}
    print(json.dumps(QueryRouter(app).query(args.path, data), indent=2))
    return 0


def cmd_tx(args) -> int:
    """tx send / tx pay-for-blob against the local home: sign (protobuf
    wire), run through the node (CheckTx + one block), print the result —
    the x/blob CLI `tx blob pay-for-blob` analog (client/cli/payforblob.go)."""
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
    from celestia_app_tpu.client.tx_client import Signer, TxClient
    from celestia_app_tpu.da.blob import Blob
    from celestia_app_tpu.da.namespace import Namespace

    if args.action == "send" and (args.to is None or args.amount is None):
        print("tx send requires --to and --amount", file=sys.stderr)
        return 2
    if args.action == "create-validator" and args.amount is None:
        print("tx create-validator requires --amount (self-stake, utia)",
              file=sys.stderr)
        return 2
    if args.action == "pay-for-blob" and args.input_file is None and (
        args.namespace is None or args.data is None
    ):
        print("tx pay-for-blob requires --namespace and --data "
              "(or --input-file blobs.json)", file=sys.stderr)
        return 2

    app, _cfg = _make_app(args.home)
    node = Node(app)
    priv = PrivateKey.from_seed(args.from_seed.encode())
    addr = priv.public_key().address()
    ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                  app.chain_id, app.app_version)
    acc = app.auth.account(ctx, addr)
    signer = Signer(app.chain_id)
    signer.add_account(priv, acc["number"] if acc else 0,
                       acc["sequence"] if acc else 0)
    client = TxClient(node, signer)
    if args.action == "send":
        height, res = client.submit_send(
            addr, bytes.fromhex(args.to), int(args.amount)
        )
    elif args.action == "create-validator":
        # stake in with the signer's own consensus pubkey registered
        # on-chain, so a running autonomous network adopts this address
        # into rotation (chain/reactor.py valset-update flow)
        height, res = client.submit_create_validator(
            addr, int(args.amount), priv.public_key().compressed
        )
    else:  # pay-for-blob
        if args.input_file is not None:
            if args.namespace is not None or args.data is not None:
                print("--input-file conflicts with --namespace/--data; "
                      "pass one or the other", file=sys.stderr)
                return 2
            # multi-blob file input, the reference's --input-file JSON
            # schema (x/blob/client/cli/payforblob.go:60-76):
            # {"Blobs": [{"namespaceID": "0x..10 bytes..", "blob": "0x.."}]}
            # The file is user input: every malformed shape gets a usage
            # error naming the entry, never a traceback.
            try:
                with open(args.input_file) as f:
                    doc = json.load(f)
                entries = (doc.get("Blobs") or doc.get("blobs")
                           if isinstance(doc, dict) else None)
                if not entries:
                    print(f"{args.input_file}: no Blobs array",
                          file=sys.stderr)
                    return 2
                blobs = []
                for i, e in enumerate(entries):
                    if not isinstance(e, dict) or "namespaceID" not in e \
                            or "blob" not in e:
                        print(f"{args.input_file}: Blobs[{i}] needs "
                              "namespaceID and blob", file=sys.stderr)
                        return 2
                    ns_hex = str(e["namespaceID"]).removeprefix("0x")
                    blob_hex = str(e["blob"]).removeprefix("0x")
                    blobs.append(
                        Blob(Namespace.v0(bytes.fromhex(ns_hex)),
                             bytes.fromhex(blob_hex))
                    )
            except (OSError, json.JSONDecodeError, ValueError) as e:
                print(f"{args.input_file}: {e}", file=sys.stderr)
                return 2
        else:
            ns = Namespace.v0(bytes.fromhex(args.namespace))
            if args.data.startswith("@"):
                with open(args.data[1:], "rb") as f:
                    payload = f.read()
            else:
                payload = bytes.fromhex(args.data)
            blobs = [Blob(ns, payload)]
        height, res = client.submit_pay_for_blob(addr, blobs)
    # commits already hit disk inside produce_block (durable save_commit)
    print(json.dumps({
        "height": height,
        "code": res.code,
        "log": res.log,
        "gas_wanted": res.gas_wanted,
        "gas_used": res.gas_used,
    }, indent=2))
    return 0 if res.code == 0 else 1


def _ensure_home_config(home: str, chain_id: str) -> None:
    """Make a validator home a first-class CLI --home: with config.json in
    place (and data under <home>/data), `snapshot create`, `query`,
    `export`, `blockscan` etc. all work against a stopped validator.
    Validators run engine=host (ValidatorNode's App does)."""
    if not os.path.exists(os.path.join(home, "config.json")):
        _write_config(home, chain_id, engine="host")


def _check_legacy_validator_home(home: str) -> str | None:
    """Pre-round-4 layout detection: validator state at the HOME ROOT
    instead of <home>/data. Returns an error message, or None when clean.
    Silently adopting such a home would reset the validator to genesis AND
    re-sign heights it already signed."""
    data_dir = os.path.join(home, "data")
    legacy = [
        p for p in ("state", "wal", "LATEST")
        if os.path.exists(os.path.join(home, p))
    ]
    if legacy and not os.path.isdir(data_dir):
        return (
            f"{home} holds pre-round-4 validator state "
            f"({', '.join(legacy)}) at the home root; move it under "
            f"{data_dir}/ before starting, or this validator would "
            "silently reset to genesis and double-sign."
        )
    return None


def cmd_relayer(args) -> int:
    """IBC relayer daemon over two live nodes' HTTP services (the hermes
    role; tools/relayer.py). Loops step() until --passes completes or
    forever; each pass prints its delivery counts."""
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.tools.relayer import HttpChainHandle, Relayer

    def handle(url: str, seed: str, client_id: str,
               verifying: bool) -> HttpChainHandle:
        from celestia_app_tpu.net import transport

        priv = PrivateKey.from_seed(seed.encode())
        addr = priv.public_key().address()
        chain_id = transport.request_json(url, "/status")["chain_id"]
        signer = Signer(chain_id)
        # bootstrap the account number/sequence from the node
        acc = transport.request_json(
            url, "/abci_query",
            {"path": "auth/account", "data": {"address": addr.hex()}},
        ).get("account") or {}
        signer.add_account(priv, acc.get("number", 0),
                           acc.get("sequence", 0))
        return HttpChainHandle(url, signer, addr, client_id,
                               verifying=verifying)

    verifying = not args.insecure
    a = handle(args.url_a, args.seed_a, args.client_a, verifying)
    b = handle(args.url_b, args.seed_b, args.client_b, verifying)
    relayer = Relayer(a, b)
    done = 0
    while args.passes is None or done < args.passes:
        try:
            out = relayer.step()
        except (OSError, RuntimeError) as e:
            print(f"pass failed: {e}", file=sys.stderr)
            out = None
        if out is not None:
            print(json.dumps(out), flush=True)
        done += 1
        if args.passes is None or done < args.passes:
            time.sleep(args.interval)
    return 0


def cmd_da_serve(args) -> int:
    """Standalone DA-core service (SURVEY §7.1.7 sidecar shape): serves
    /da/extend_commit and /da/prove_shares with NO chain attached — a
    foreign node (see shim/go/tpuda) points its da.ExtendShares
    replacement here and keeps everything else. --grpc also serves
    celestia_tpu.da.v1.DAService on that port."""
    from celestia_app_tpu.service.da_service import DACore, DAService

    core = DACore(engine=args.engine)
    svc = DAService(core, port=args.listen)
    grpc_srv = None
    if args.grpc is not None:
        from concurrent import futures as _futures

        import grpc as _g

        from celestia_app_tpu.service.grpc_server import (
            DA_SERVICE,
            DAGrpcService,
            _handler,
        )

        da = DAGrpcService(core)
        server = _g.server(_futures.ThreadPoolExecutor(max_workers=4))
        server.add_generic_rpc_handlers((
            _g.method_handlers_generic_handler(DA_SERVICE, {
                "ExtendAndCommit": _handler(da.extend_and_commit),
                "ProveShares": _handler(da.prove_shares),
            }),
        ))
        grpc_port = server.add_insecure_port(f"127.0.0.1:{args.grpc}")
        if grpc_port == 0:
            # add_insecure_port returns 0 on bind FAILURE (port taken);
            # serving HTTP-only silently would strand the foreign caller
            print(f"da-serve: cannot bind gRPC port {args.grpc}",
                  file=sys.stderr)
            return 1
        server.start()
        grpc_srv = server
        print(f"da-serve: grpc on :{grpc_port}", flush=True)
    print(f"da-serve: http on :{svc.port} (engine={core.engine})",
          flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if grpc_srv is not None:
            grpc_srv.stop(grace=0.5)
    return 0


def cmd_das_serve(args) -> int:
    """DAS sample-proof server over a full node's home (das/server.py):
    answers light-node samplers (`das-follow`) with cells + NMT proofs
    from the committed block store — the serving half of the DAS plane,
    deployable next to (or instead of) the full node process."""
    from celestia_app_tpu.das.server import SampleCore, SampleService

    app, _cfg = _make_app(args.home)
    core = SampleCore(app, cache_heights=args.cache_heights)
    if getattr(args, "no_packs", False):
        core.pack_store = None
    svc = SampleService(core, port=args.listen)
    packs_on = core.pack_store is not None
    print(f"das-serve: http on :{svc.port} (height {app.height}, "
          f"engine={getattr(app, 'engine', 'host')}, "
          f"packs={'on' if packs_on else 'off'})", flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def cmd_blob_serve(args) -> int:
    """Read-plane sidecar over a full node's home (das/blob_server.py):
    answers rollup readers — GET /blob/get, batched POST
    /blob/namespaces, static blob-pack chunks — plus the /das/* routes a
    verifying follower needs for headers. Deployable next to (or instead
    of) the full node process; any number can front one home."""
    from celestia_app_tpu.das.blob_server import BlobCore, BlobService
    from celestia_app_tpu.das.server import SampleCore

    app, _cfg = _make_app(args.home)
    core = SampleCore(app, cache_heights=args.cache_heights)
    blob_core = BlobCore(core)
    if getattr(args, "no_packs", False):
        blob_core.pack_store = None
    svc = BlobService(blob_core, port=args.listen)
    packs_on = blob_core.pack_store is not None
    print(f"blob-serve: http on :{svc.port} (height {app.height}, "
          f"engine={getattr(app, 'engine', 'host')}, "
          f"packs={'on' if packs_on else 'off'})", flush=True)
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def cmd_das_follow(args) -> int:
    """DASer daemon (das/daser.py): follow a chain as a light node —
    verify headers by commit certificate (chain/light.py), sample every
    height, checkpoint progress under --home, and halt on a verified
    bad-encoding fraud proof. Exit codes: 0 clean stop, 1 halted, 2 bad
    invocation."""
    import numpy as np

    from celestia_app_tpu.chain.light import LightClient, TrustedState
    from celestia_app_tpu.das.checkpoint import CheckpointStore
    from celestia_app_tpu.das.daser import DASer, DASerConfig

    if not args.peer:
        print("error: das-follow needs at least one --peer", file=sys.stderr)
        return 2
    genesis_path = os.path.join(args.home, "genesis.json")
    if not os.path.exists(genesis_path):
        print(f"error: no genesis.json under {args.home} (trust root)",
              file=sys.stderr)
        return 2
    with open(genesis_path) as f:
        genesis = json.load(f)
    validators, powers = {}, {}
    for v in genesis.get("validators", []):
        if "pubkey" not in v:
            print("error: genesis validators need pubkeys for light "
                  "verification", file=sys.stderr)
            return 2
        op = bytes.fromhex(v["operator"])
        validators[op] = bytes.fromhex(v["pubkey"])
        powers[op] = int(v["power"])
    light = LightClient(args.chain_id, TrustedState(
        height=0, header_hash=b"", validators=validators, powers=powers,
    ))
    store = CheckpointStore(os.path.join(args.home, "das",
                                         "checkpoint.json"))
    cfg = DASerConfig(
        samples_per_header=args.samples,
        workers=args.workers,
        poll_interval=args.interval,
        prefer_packs=not getattr(args, "no_packs", False),
    )
    daser = DASer(list(args.peer), light, store, cfg=cfg,
                  rng=np.random.default_rng(args.seed), name="das-follow")
    if daser.halted:
        print(json.dumps({"halted": daser.cp.halted}), flush=True)
        return 1
    try:
        while not daser.halted:
            out = daser.sync()
            print(json.dumps(out), flush=True)
            if out.get("halted"):
                break  # a halt during header following returns a
                # halted-only dict; fall through to the exit-1 line
            if args.once and out.get("sample_from", 0) > out.get("head", -1) >= 1:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    print(json.dumps({"halted": daser.cp.halted}), flush=True)
    return 1


def cmd_blob_follow(args) -> int:
    """Rollup follower daemon (client/follower.py): track ONE namespace
    across heights as a verifying light client — headers by commit
    certificate (chain/light.py), every inclusion/absence proof checked
    against the certified data root, progress checkpointed under
    --home/blob/. Exit codes: 0 clean stop, 1 verification refusal,
    2 bad invocation."""
    from celestia_app_tpu.chain.light import LightClient, TrustedState
    from celestia_app_tpu.client.follower import (
        BlobFollower,
        FollowerConfig,
        FollowerError,
    )
    from celestia_app_tpu.das.checkpoint import CheckpointStore

    if not args.peer:
        print("error: blob-follow needs at least one --peer",
              file=sys.stderr)
        return 2
    try:
        namespace = bytes.fromhex(args.namespace)
    except ValueError:
        namespace = b""
    if len(namespace) != 29:
        print("error: --namespace must be 29 bytes of hex",
              file=sys.stderr)
        return 2
    genesis_path = os.path.join(args.home, "genesis.json")
    if not os.path.exists(genesis_path):
        print(f"error: no genesis.json under {args.home} (trust root)",
              file=sys.stderr)
        return 2
    with open(genesis_path) as f:
        genesis = json.load(f)
    validators, powers = {}, {}
    for v in genesis.get("validators", []):
        if "pubkey" not in v:
            print("error: genesis validators need pubkeys for light "
                  "verification", file=sys.stderr)
            return 2
        op = bytes.fromhex(v["operator"])
        validators[op] = bytes.fromhex(v["pubkey"])
        powers[op] = int(v["power"])
    light = LightClient(args.chain_id, TrustedState(
        height=0, header_hash=b"", validators=validators, powers=powers,
    ))
    store = CheckpointStore(os.path.join(args.home, "blob",
                                         "checkpoint.json"))
    follower = BlobFollower(
        list(args.peer), namespace, light, store,
        cfg=FollowerConfig(prefer_packs=not getattr(args, "no_packs",
                                                    False)),
        name="blob-follow",
    )
    try:
        while True:
            try:
                out = follower.sync()
            except FollowerError as e:
                print(json.dumps({"refused": str(e)}), flush=True)
                return 1
            for h, blobs in sorted(follower.pop_blobs().items()):
                print(json.dumps({"height": h, "blobs": [
                    b.hex() for b in blobs]}), flush=True)
            print(json.dumps(out), flush=True)
            if args.once and out["next_height"] > out["head"] >= 1:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_verify(args) -> int:
    """Blobstream verification CLI (x/blobstream/client verify analog,
    ref client/verify.go:27-38): prove that shares at a height are
    covered by an on-chain data-commitment attestation — share proof to
    the block's data root, then the data-root tuple proof to the
    attestation's commitment root, the exact value an EVM Blobstream
    contract stores per nonce. The reference queries a live Ethereum
    contract; with no external chain here, the root is recomputed from
    the home's own attested height range, which is the same statement an
    orchestrator would have relayed."""
    from celestia_app_tpu.chain import blobstream as bs
    from celestia_app_tpu.chain.query import QueryRouter
    from celestia_app_tpu.chain.state import Context, InfiniteGasMeter

    app, _cfg = _make_app(args.home)
    if app.height < args.height:
        print(f"home is at height {app.height}; {args.height} not committed",
              file=sys.stderr)
        return 1
    ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                  app.chain_id, app.app_version)

    # find the data-commitment attestation whose range covers the height
    latest = app.blobstream.latest_attestation_nonce(ctx)
    if latest is None:
        print("no blobstream attestations in state (v1 only; the module "
              "is disabled from app version 2)", file=sys.stderr)
        return 1
    dc = None
    for nonce in range(latest, 0, -1):
        att = app.blobstream.attestation_by_nonce(ctx, nonce)
        if (isinstance(att, bs.DataCommitment)
                and att.begin_block <= args.height < att.end_block):
            dc = att
            break
    if dc is None:
        print(f"height {args.height} is not covered by any data "
              "commitment yet (window boundary not reached)",
              file=sys.stderr)
        return 1

    # share proof -> data root (the same prover the query routes use)
    qr = QueryRouter(app)
    prover, data_root = qr.prover_for(args.height)
    ns = bytes.fromhex(args.namespace) if args.namespace else \
        prover.eds.squares[0, 0, :29].tobytes()
    proof = prover.prove_shares(args.start, args.end, ns)
    if not proof.verify(data_root):
        print("FAILED: share proof does not verify against the data root",
              file=sys.stderr)
        return 1

    # data root -> attestation tuple root (what the EVM contract stores)
    data_roots = {}
    for h in range(dc.begin_block, dc.end_block):
        if h < 1 or h > app.height:
            continue
        data_roots[h] = app.db.load_block(h).header.data_hash
    tuple_root = bs.data_commitment_root(dc, data_roots)
    tproof = bs.data_root_tuple_proof(dc, data_roots, args.height)
    if not bs.verify_data_root_inclusion(
        args.height, data_root, tuple_root, tproof
    ):
        print("FAILED: data root not included in the attestation's "
              "tuple root", file=sys.stderr)
        return 1
    print(json.dumps({
        "verified": True,
        "height": args.height,
        "shares": [args.start, args.end],
        "namespace": ns.hex(),
        "data_root": data_root.hex(),
        "attestation_nonce": dc.nonce,
        "attestation_range": [dc.begin_block, dc.end_block],
        "data_commitment_root": tuple_root.hex(),
    }, indent=2))
    return 0


def cmd_multihost_worker(args) -> int:
    """One host of the multi-host mesh (spawned by multihost-dryrun; env
    is prepared by the spawner BEFORE this interpreter starts)."""
    from celestia_app_tpu.parallel import multihost

    out = multihost.worker_main(
        args.process_id, args.num_processes, args.coordinator,
        args.k, args.batch, args.devices_per_host,
    )
    print(json.dumps(out))
    return 0


def cmd_multihost_dryrun(args) -> int:
    """N OS processes x M virtual devices = one global mesh running the
    sharded block pipeline, every host feeding only its own shards; roots
    must agree across hosts AND match the single-host oracle."""
    from celestia_app_tpu.parallel import multihost

    if args.processes < 1 or args.devices_per_host < 1:
        print("--processes and --devices-per-host must be >= 1",
              file=sys.stderr)
        return 2
    out = multihost.spawn_dryrun(
        k=args.k, batch=args.batch, num_processes=args.processes,
        devices_per_host=args.devices_per_host,
    )
    print(json.dumps(out))
    return 0 if out["all_hosts_match_oracle"] else 1


def cmd_e2e_bench(args) -> int:
    """Throughput benchmark on the autonomous process devnet — see
    tools/e2e_bench.py (the test/e2e/benchmark/throughput.go analog)."""
    from celestia_app_tpu.tools import e2e_bench

    return e2e_bench.run(args, _spawn_validator_processes,
                         _terminate_processes)


def cmd_validator_serve(args) -> int:
    """One validator as its own OS process (the reference's one-binary-per-
    validator deployment): loads key + genesis from --home, resumes durable
    state, replays any WAL entries ahead of the committed height, then
    serves the HTTP consensus surface until killed. Writes endpoint.json
    (host/port) into --home so the spawner can discover the bound port."""
    from celestia_app_tpu.chain import consensus
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.service.validator_server import ValidatorService

    with open(os.path.join(args.home, "genesis.json")) as f:
        genesis = json.load(f)
    with open(os.path.join(args.home, "key.json")) as f:
        key_doc = json.load(f)
    _ensure_home_config(args.home, args.chain_id)
    priv = PrivateKey.from_seed(bytes.fromhex(key_doc["seed_hex"]))
    # layout: validator state lives under <home>/data (so the home doubles
    # as a CLI --home); a pre-round-4 home is refused loudly
    err = _check_legacy_validator_home(args.home)
    if err is not None:
        print(f"ERROR: {err}", file=sys.stderr)
        return 1
    with open(os.path.join(args.home, "config.json")) as f:
        home_cfg = json.load(f)
    vnode = consensus.ValidatorNode(
        key_doc.get("name", "val"), priv, genesis, args.chain_id,
        data_dir=os.path.join(args.home, "data"),
        # the coordinated v1->v2 flip height (reference
        # --v2-upgrade-height) and the x/signal scheduling delay: both
        # consensus-critical, so both ride the home config every
        # validator is provisioned with
        v2_upgrade_height=home_cfg.get("v2_upgrade_height"),
        upgrade_height_delay=home_cfg.get("upgrade_height_delay"),
        # the DA commitment scheme (codec plane) is consensus-critical
        # like the upgrade knobs above: every validator of a chain must
        # be provisioned with the same one (absent ⇒ rs2d-nmt)
        da_scheme=home_cfg.get("da_scheme", "rs2d-nmt"),
        # serving plane: precompute static proof packs at warm time
        # (<home>/packs, newest-N kept; null = off)
        pack_keep=home_cfg.get("pack_keep", 4),
        # mesh plane: the consensus-critical k=256/512 square-cap
        # override — provisioned identically across the chain or absent
        max_square_size=home_cfg.get("max_square_size"),
        # validators default to engine=host (the relay-hang policy —
        # _ensure_home_config writes "host"); a home explicitly
        # provisioned with "mesh"/"device"/"auto" opts in, which is how
        # a mesh validator (and its produce_batch prewarm) is deployed
        engine=home_cfg.get("engine", "host"),
    )
    # fault plane (chaos provisioning): <home>/faults.json arms named
    # fault points for THIS process at startup — the config-file twin of
    # the CELESTIA_FAULTS env and the runtime /faults/* admin endpoint
    faults_path = os.path.join(args.home, "faults.json")
    if os.path.exists(faults_path):
        from celestia_app_tpu import faults as faults_mod

        with open(faults_path) as f:
            armed = faults_mod.arm_from_spec(json.load(f))
        print(f"armed {len(armed)} fault(s) from faults.json",
              file=sys.stderr, flush=True)
    try:
        vnode.app.load()  # resume at the durable committed height
    except ValueError:
        pass  # fresh home: stay at the genesis state init_chain built
    replayed = vnode.replay_wal()
    svc = ValidatorService(vnode, port=args.port)
    endpoint = {"host": "127.0.0.1", "port": svc.port}
    http_service = None
    if args.http is not None:
        # the node query surface (status/block/abci_query incl. proof
        # routes, /trace, /metrics) from the same process
        from celestia_app_tpu.service.server import NodeService

        http_service = NodeService(vnode, port=args.http)
        http_service.lock = svc.lock  # one writer lock for the process
        http_service.das_core.app_lock = svc.lock
        http_service.serve_background()
        endpoint["http_port"] = http_service.port
    grpc_server = None
    if args.grpc is not None:
        # the full client surface on the SAME process (one binary per
        # validator, as the reference serves gRPC:9090 from the node):
        # tx broadcast/simulate/GetTx + the SetupTxClient bootstrap queries
        from celestia_app_tpu.service.grpc_server import GrpcTxServer

        grpc_server = GrpcTxServer(vnode, port=args.grpc, lock=svc.lock)
        endpoint["grpc_port"] = grpc_server.port
    # atomic publish: the spawner polls for this file and must never read
    # a half-written JSON body
    ep_tmp = os.path.join(args.home, "endpoint.json.tmp")
    with open(ep_tmp, "w") as f:
        json.dump(endpoint, f)
    os.replace(ep_tmp, os.path.join(args.home, "endpoint.json"))
    print(
        f"{vnode.name}: serving on 127.0.0.1:{svc.port} at height "
        f"{vnode.app.height} (wal replayed {replayed})",
        file=sys.stderr, flush=True,
    )
    if getattr(args, "autonomous", False):
        # peer discovery: the spawner learns every endpoint, then drops
        # peers.json into each home — the address-book handoff (the
        # reference's persistent_peers config.toml entry)
        import threading
        import time as time_mod

        def arm_reactor() -> None:
            peers_path = os.path.join(args.home, "peers.json")
            for _ in range(1200):
                if os.path.exists(peers_path):
                    break
                time_mod.sleep(0.25)
            else:
                print("no peers.json appeared; reactor not started",
                      file=sys.stderr, flush=True)
                return
            with open(peers_path) as f:
                peers = json.load(f)
            from celestia_app_tpu.chain.reactor import ReactorConfig

            cfg_doc = {}
            cfg_path = os.path.join(args.home, "reactor.json")
            if os.path.exists(cfg_path):
                with open(cfg_path) as f:
                    cfg_doc = json.load(f)
            # sync plane: the home config's snapshot knobs (the same keys
            # cmd_start reads) feed the reactor's interval-snapshot hook;
            # an explicit reactor.json entry wins
            if "snapshot_interval" not in cfg_doc and \
                    "snapshot_interval_blocks" in home_cfg:
                cfg_doc["snapshot_interval"] = \
                    home_cfg["snapshot_interval_blocks"]
            if "snapshot_keep" not in cfg_doc and \
                    "snapshot_keep_recent" in home_cfg:
                cfg_doc["snapshot_keep"] = home_cfg["snapshot_keep_recent"]
            # mesh plane: the produce→commit batching knob rides the
            # same home-config feed (an explicit reactor.json wins)
            if "produce_batch" not in cfg_doc and \
                    "produce_batch" in home_cfg:
                cfg_doc["produce_batch"] = home_cfg["produce_batch"]
            cfg = ReactorConfig(**cfg_doc)
            svc.attach_reactor([u for u in peers if u !=
                                f"http://127.0.0.1:{svc.port}"], cfg)
            print(f"{vnode.name}: autonomous reactor up "
                  f"({len(peers) - 1} peers)", file=sys.stderr, flush=True)

        threading.Thread(target=arm_reactor, daemon=True).start()
    try:
        svc.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if grpc_server is not None:
            grpc_server.stop()
        if http_service is not None:
            http_service.shutdown()
    return 0


def _spawn_validator_processes(args, genesis, extra_flags=(),
                               reactor_cfg: dict | None = None):
    """Shared devnet scaffolding: one `validator-serve` OS process per
    validator home under args.home. Writes genesis/key (+ optional
    reactor.json), clears stale discovery files, spawns, then polls each
    home's endpoint.json. Returns (procs, homes, urls); on ANY setup
    failure the already-spawned processes are killed before the error
    propagates (the caller's finally never sees half a fleet)."""
    import subprocess
    import time as time_mod

    procs, homes, urls = [], [], []
    try:
        for i in range(args.validators):
            home = os.path.join(args.home, f"val{i}")
            os.makedirs(home, exist_ok=True)
            # fail fast here too: the spawned validator's own refusal
            # would otherwise surface only as a 50s "never came up"
            # timeout (its output goes to <home>/validator.log)
            err = _check_legacy_validator_home(home)
            if err is not None:
                raise RuntimeError(err)
            with open(os.path.join(home, "genesis.json"), "w") as f:
                json.dump(genesis, f)
            with open(os.path.join(home, "key.json"), "w") as f:
                json.dump({"seed_hex": f"devnet-{i}".encode().hex(),
                           "name": f"val{i}"}, f)
            if reactor_cfg is not None:
                with open(os.path.join(home, "reactor.json"), "w") as f:
                    json.dump(reactor_cfg, f)
            for stale in ("endpoint.json", "peers.json"):
                sp = os.path.join(home, stale)
                if os.path.exists(sp):
                    os.unlink(sp)
            # per-validator log file (the reference's --log-to-file): a
            # devnulled validator would hide reactor errors exactly when
            # a devnet misbehaves
            log_f = open(os.path.join(home, "validator.log"), "a")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "celestia_app_tpu",
                 "validator-serve", "--home", home,
                 "--chain-id", args.chain_id, *extra_flags],
                stdout=log_f, stderr=subprocess.STDOUT,
            ))
            log_f.close()  # the child holds its own fd now
            homes.append(home)

        for i, home in enumerate(homes):
            ep = os.path.join(home, "endpoint.json")
            for _ in range(200):  # first process start imports jax: slow
                if os.path.exists(ep):
                    break
                time_mod.sleep(0.25)
            else:
                raise RuntimeError(f"validator at {home} never came up")
            with open(ep) as f:
                doc = json.load(f)
            urls.append(f"http://{doc['host']}:{doc['port']}")
            extras = ", ".join(
                f"{k.removesuffix('_port')} :{v}"
                for k, v in doc.items() if k.endswith("_port")
            )
            print(f"val{i}: consensus {urls[-1]}"
                  + (f", {extras}" if extras else ""), file=sys.stderr)
        return procs, homes, urls
    except BaseException:
        _terminate_processes(procs)
        raise


def _terminate_processes(procs) -> None:
    for pr in procs:
        pr.terminate()
    for pr in procs:
        try:
            pr.wait(timeout=5)
        except Exception:
            pr.kill()


def _devnet_autonomous(args, privs, genesis) -> int:
    """devnet --processes --autonomous: one OS process per validator and NO
    coordinator — each process runs its own consensus reactor
    (chain/reactor.py), gossiping proposals/votes/txs peer-to-peer. This
    process only seeds the address book (peers.json), optionally submits
    load, and watches statuses for progress + divergence (the reference's
    devnet observer role)."""
    import base64
    import time as time_mod

    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.net.transport import PeerClient, TransportConfig

    n = args.validators
    procs, homes, urls = _spawn_validator_processes(
        args, genesis,
        extra_flags=("--autonomous", "--grpc", "0", "--http", "0"),
        # pace the reactors to the requested block time; generous propose
        # window (a first proposal may pay a cold jit compile) but quick
        # rotation past dead peers
        reactor_cfg={
            "timeout_propose": max(15.0, 10 * args.block_time),
            "timeout_prevote": max(8.0, 5 * args.block_time),
            "timeout_precommit": max(8.0, 5 * args.block_time),
            "timeout_delta": 2.0,
            "block_interval": args.block_time,
        },
    )
    try:
        # hand every validator the address book; reactors arm on sight
        for home in homes:
            tmp = os.path.join(home, "peers.json.tmp")
            with open(tmp, "w") as f:
                json.dump(urls, f)
            os.replace(tmp, os.path.join(home, "peers.json"))

        # the observer's transport: breaker state keeps the watch loop
        # from stalling 5 s per poll on a crashed validator
        net = PeerClient(TransportConfig(timeout=5.0, retries=1),
                         name="devnet-observer")

        def status(u: str) -> dict | None:
            try:
                return net.get(u, "/consensus/status")
            except OSError:
                return None

        def commit_at(u: str, h: int) -> dict | None:
            try:
                return net.get(u, f"/gossip/commit_at?height={h}") or None
            except OSError:
                return None

        signer = Signer(args.chain_id)
        for i, p in enumerate(privs):
            signer.add_account(p, number=i)
        a0 = privs[0].public_key().address()
        a1 = privs[1 % n].public_key().address()
        target = args.blocks or 5
        sent = 0
        deadline = time_mod.monotonic() + max(120.0, 30.0 * target)
        last_min = -1
        while time_mod.monotonic() < deadline:
            sts = [status(u) for u in urls]
            heights = [s["height"] for s in sts if s]
            if not heights:
                time_mod.sleep(0.5)
                continue
            lo = min(heights)
            if lo != last_min:
                print(f"heights: {heights}", file=sys.stderr)
                last_min = lo
            if args.load and sent < lo + 1:
                tx = signer.create_tx(a0, [MsgSend(a0, a1, 1 + sent)],
                                      fee=2000, gas_limit=100_000)
                try:
                    res = net.post(
                        urls[sent % n], "/broadcast_tx",
                        {"tx": base64.b64encode(tx.encode()).decode()},
                        timeout=10,
                    )
                    if res["code"] == 0:
                        signer.accounts[a0].sequence += 1
                        sent += 1
                except OSError:
                    pass
            if lo >= target:
                break
            time_mod.sleep(args.block_time / 4)
        else:
            print("ERROR: devnet did not reach the target height",
                  file=sys.stderr)
            return 1

        # divergence gate: every validator that holds the commit record
        # for the last common height must report the SAME block hash (the
        # header commits to the previous app hash, so block-hash equality
        # is state equality one height back)
        final_heights = [
            s["height"] for s in (status(u) for u in urls) if s
        ]
        if not final_heights:
            print("ERROR: no validator reachable for the final check",
                  file=sys.stderr)
            return 1
        lo = min(final_heights)
        block_hashes = set()
        holders = 0
        for u in urls:
            doc = commit_at(u, lo)
            if doc:
                holders += 1
                block_hashes.add(doc["cert"]["block_hash"])
        if holders >= 2 and len(block_hashes) != 1:
            print(f"DIVERGENCE at height {lo}: {sorted(block_hashes)}",
                  file=sys.stderr)
            return 1
        print(json.dumps({
            "validators": n,
            "processes": True,
            "autonomous": True,
            "blocks": lo,
            "txs_submitted": sent,
            "block_hash": next(iter(block_hashes)) if block_hashes else None,
        }))
        return 0
    finally:
        _terminate_processes(procs)


def _devnet_processes(args, privs, genesis) -> int:
    """devnet --processes: one OS process per validator, consensus over
    sockets (VERDICT r3 #4). Produces --blocks heights through the
    SocketNetwork orchestrator and checks every process lands on the same
    app hash."""
    import time as time_mod

    from celestia_app_tpu.chain.remote_consensus import (
        RemoteValidator, SocketNetwork,
    )
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.chain.tx import MsgSend

    n = args.validators
    procs, homes, urls = _spawn_validator_processes(
        args, genesis, extra_flags=("--grpc", "0", "--http", "0"),
    )
    try:
        peers = [RemoteValidator(u) for u in urls]
        net = SocketNetwork(peers, genesis, args.chain_id)

        signer = Signer(args.chain_id)
        for i, p in enumerate(privs):
            signer.add_account(p, number=i)
        a0 = privs[0].public_key().address()
        a1 = privs[1 % n].public_key().address()
        t = time.time()
        produced = 0
        while args.blocks is None or produced < args.blocks:
            if args.load and n >= 2:
                tx = signer.create_tx(
                    a0, [MsgSend(a0, a1, 1 + produced)],
                    fee=2000, gas_limit=100_000,
                )
                if net.broadcast_tx(tx.encode()):
                    signer.accounts[a0].sequence += 1
            t += args.block_time
            height, app_hash = net.produce_height(t=t)
            if height is None:
                print("round failed; rotating proposer", file=sys.stderr)
                continue
            produced += 1
            statuses = [p.status() for p in net.peers]
            print(
                f"height {height}: processes at "
                f"{[s['height'] for s in statuses]}, app hash "
                f"{sorted({s['app_hash'][:12] for s in statuses})}",
                file=sys.stderr,
            )
            if args.blocks is None:
                time_mod.sleep(args.block_time)
        final = {p.status()["app_hash"] for p in net.peers}
        if len(final) != 1:
            print(f"DIVERGENCE: {sorted(final)}", file=sys.stderr)
            return 1
        print(json.dumps({
            "validators": n,
            "processes": True,
            "blocks": produced,
            "final_height": net.peers[0].status()["height"],
            "app_hash": next(iter(final)),
        }))
        return 0
    finally:
        _terminate_processes(procs)


def cmd_devnet(args) -> int:
    """N-validator in-process devnet (the reference's local_devnet
    docker-compose analog): real consensus (signed precommits, >2/3
    certificates, WAL, per-node durable state under --home/val<i>), one
    HTTP service per validator, txsim-style load if requested."""
    from celestia_app_tpu.chain import consensus
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.service.server import NodeService
    from celestia_app_tpu.chain.tx import MsgSend

    n = args.validators
    privs = [PrivateKey.from_seed(f"devnet-{i}".encode()) for i in range(n)]
    genesis = {
        "time_unix": time.time(),
        "accounts": [
            {"address": p.public_key().address().hex(), "balance": 10**12}
            for p in privs
        ],
        "validators": [
            {
                "operator": p.public_key().address().hex(),
                "power": 10,
                "pubkey": p.public_key().compressed.hex(),
            }
            for p in privs
        ],
    }
    os.makedirs(args.home, exist_ok=True)
    if getattr(args, "autonomous", False):
        if not args.processes:
            print("ERROR: --autonomous requires --processes",
                  file=sys.stderr)
            return 1
        return _devnet_autonomous(args, privs, genesis)
    if args.processes:
        return _devnet_processes(args, privs, genesis)
    nodes = []
    for i in range(n):
        home = os.path.join(args.home, f"val{i}")
        os.makedirs(home, exist_ok=True)
        err = _check_legacy_validator_home(home)
        if err is not None:
            print(f"ERROR: {err}", file=sys.stderr)
            return 1
        with open(os.path.join(home, "genesis.json"), "w") as f:
            json.dump(genesis, f)
        _ensure_home_config(home, args.chain_id)
        nodes.append(consensus.ValidatorNode(
            f"val{i}", privs[i], genesis, args.chain_id,
            data_dir=os.path.join(home, "data"),
        ))
    net = consensus.LocalNetwork(nodes)
    services = []
    for vn in net.nodes:
        svc = NodeService(Node(vn.app), port=0)
        svc.serve_background()
        services.append(svc)
        print(f"{vn.name}: http://127.0.0.1:{svc.port}", file=sys.stderr)

    signer = Signer(args.chain_id)
    for i, p in enumerate(privs):
        signer.add_account(p, number=i)
    t = time.time()
    produced = 0
    a0 = privs[0].public_key().address()
    a1 = privs[1 % n].public_key().address()
    try:
        while args.blocks is None or produced < args.blocks:
            if args.load and n >= 2:
                tx = signer.create_tx(
                    a0, [MsgSend(a0, a1, 1 + produced)],
                    fee=2000, gas_limit=100_000,
                )
                if net.broadcast_tx(tx.encode()):
                    signer.accounts[a0].sequence += 1
            t += args.block_time
            blk, cert = net.produce_height(t=t)
            if blk is None:
                print("round failed; rotating proposer", file=sys.stderr)
                continue
            produced += 1
            heights = {vn.app.height for vn in net.nodes}
            hashes = {vn.app.last_app_hash.hex()[:12] for vn in net.nodes}
            print(
                f"height {blk.header.height}: {len(blk.txs)} txs, "
                f"{len(cert.votes)} votes, nodes at {sorted(heights)}, "
                f"app hash {sorted(hashes)}",
                file=sys.stderr,
            )
            if args.blocks is None:
                time.sleep(args.block_time)
    except KeyboardInterrupt:
        pass
    finally:
        for svc in services:
            svc.shutdown()
        for vn in net.nodes:
            vn.app.close()  # release writer flocks for follow-up commands
    final_hashes = {vn.app.last_app_hash for vn in net.nodes}
    if len(final_hashes) != 1:
        print(
            f"DIVERGENCE: {sorted(h.hex() for h in final_hashes)}",
            file=sys.stderr,
        )
        return 1
    print(json.dumps({
        "validators": n,
        "blocks": produced,
        "final_height": net.nodes[0].app.height,
        "app_hash": net.nodes[0].app.last_app_hash.hex(),
    }))
    return 0


def _write_snapshot_files(manifest: dict, chunks: list, out_dir: str) -> None:
    """Persist already-captured snapshot chunks + manifest — ONE writer
    (chain/sync.write_snapshot_dir, manifest last + fsync'd, so a
    half-written snapshot is never restorable) shared with the sync
    plane's interval-snapshot hook and the /sync/* serving store."""
    from celestia_app_tpu.chain import sync as sync_mod

    sync_mod.write_snapshot_dir(manifest, chunks, out_dir)


def _write_snapshot(app, out_dir: str) -> dict:
    """One-shot capture + write for `snapshot create` (no concurrent
    mutator). The start loop splits capture/encode around its service
    lock and calls _write_snapshot_files directly."""
    from celestia_app_tpu.chain import consensus

    manifest, chunks = consensus.snapshot_app_chunks(app)
    _write_snapshot_files(manifest, chunks, out_dir)
    return manifest


def _prune_snapshots(root: str, keep: int) -> None:
    """Keep-recent pruning, delegated to the sync plane's ONE
    implementation (chain/sync.prune_snapshots; default_overrides.go:
    294-297 semantics, 0 = keep everything)."""
    from celestia_app_tpu.chain import sync as sync_mod

    sync_mod.prune_snapshots(root, keep)


def cmd_snapshot(args) -> int:
    """State-sync snapshots (cmd/root.go snapshot commands +
    default_overrides.go:294-297 semantics): `create` writes the committed
    state as verified chunks; `restore` bootstraps a FRESH home from them,
    verifying every chunk hash and the final app hash against the manifest
    before adopting anything."""
    from celestia_app_tpu.chain import consensus

    if args.action == "create":
        app, _ = _make_app(args.home)
        manifest = _write_snapshot(app, args.out)
        print(json.dumps({
            "height": manifest["height"],
            "chunks": manifest["n_chunks"],
            "app_hash": manifest["app_hash"],
        }))
        return 0

    # restore into a fresh home (init must have been run for config/genesis)
    with open(os.path.join(args.out, "manifest.json")) as f:
        manifest = json.load(f)
    chunks = []
    for i in range(manifest["n_chunks"]):
        with open(os.path.join(args.out, f"chunk_{i:06d}.json"), "rb") as f:
            chunks.append(f.read())
    app, _ = _make_app(args.home)
    consensus.state_sync_bootstrap(app, manifest, chunks)
    app.persist_identity()
    print(json.dumps({
        "restored_height": app.height,
        "app_hash": app.last_app_hash.hex(),
    }))
    return 0


def cmd_das(args) -> int:
    """Data availability sampling (da/sampling.py), two modes:

    --url: light-node check against a remote node. The DAH is fetched over
    HTTP, validated, and bound to a data root. With --trusted-root (a data
    root from a TRUSTED source — a light client following commit
    certificates, chain/light.py) the server cannot fabricate a block:
    withholding, tampering, and a wrong DAH all fail. Without it the root
    comes from the server's own header (trust-on-first-use; the report
    carries "header_trusted": false) and only withholding/inconsistency
    within the served block is detectable.

    --home: local self-audit of a stored block — the square is rebuilt and
    revalidated against the stored header (disk corruption surfaces as
    unavailable, not a traceback)."""
    import numpy as np

    from celestia_app_tpu.da import sampling

    if args.samples < 1:
        print("error: --samples must be >= 1", file=sys.stderr)
        return 2
    if bool(args.url) == bool(args.home):
        print("error: das needs exactly one of --home or --url",
              file=sys.stderr)
        return 2
    rng = np.random.default_rng(args.seed)
    header_trusted = True

    def _unavailable(height, msg):
        print(json.dumps({
            "height": height, "available": False, "error": msg,
        }, indent=2))
        return 1

    if args.url:
        import base64 as b64

        from celestia_app_tpu.client.tx_client import HttpNodeClient
        from celestia_app_tpu.da.dah import DataAvailabilityHeader
        from celestia_app_tpu.utils import nmt_host

        remote = HttpNodeClient(args.url)
        height = args.height
        try:
            if height is None:
                height = remote.status()["height"]
            dah_doc = remote._post(
                "/abci_query", {"path": "custom/dah",
                                "data": {"height": height}}
            )
            dah = DataAvailabilityHeader(
                row_roots=tuple(
                    bytes.fromhex(x) for x in dah_doc["row_roots"]
                ),
                col_roots=tuple(
                    bytes.fromhex(x) for x in dah_doc["col_roots"]
                ),
            )
            # structural validation of UNTRUSTED input before anything
            # touches it (bounds, root shapes — dah.validate_basic)
            dah.validate_basic()
        except (OSError, ValueError, KeyError) as e:
            return _unavailable(height, f"fetching DAH failed: {e}")
        if args.trusted_root:
            root_hex = args.trusted_root.lower()
        else:
            header_trusted = False  # bound only to the server's own header
            try:
                from celestia_app_tpu.net import transport

                root_hex = transport.request_json(
                    remote.base_url, f"/block/{height}", timeout=30
                )["data_hash"]
            except (OSError, ValueError, KeyError) as e:
                return _unavailable(height, f"fetching header failed: {e}")
        if dah.hash().hex() != root_hex:
            return _unavailable(
                height, "served DAH does not bind to the data root"
            )

        def fetch_cell(row, col):
            out = remote._post(
                "/abci_query",
                {"path": "custom/sampleCell",
                 "data": {"height": height, "row": row, "col": col}},
            )
            proof = nmt_host.NmtRangeProof(
                start=out["proof"]["start"],
                end=out["proof"]["end"],
                total=out["proof"]["total"],
                nodes=[b64.b64decode(n) for n in out["proof"]["nodes"]],
            )
            return b64.b64decode(out["share"]), proof
    else:
        from celestia_app_tpu.chain.query import QueryError, QueryRouter

        app, _cfg = _make_app(args.home)
        router = QueryRouter(app)
        height = args.height if args.height is not None else app.height
        try:
            prover, root = router.prover_for(height)
        except (QueryError, FileNotFoundError, KeyError, ValueError) as e:
            # corrupted/missing stored block = unavailable, not a crash
            print(json.dumps({
                "height": height, "available": False, "error": str(e),
            }, indent=2))
            return 1
        dah, fetch_cell, root_hex = prover.dah, prover.prove_cell, root.hex()

    rep = sampling.sample_block(dah, fetch_cell, args.samples, rng)
    print(json.dumps({
        "height": height,
        "data_root": root_hex,
        "header_trusted": header_trusted,
        "samples": rep.samples,
        "verified": rep.verified,
        "failed": rep.failed,
        "available": rep.available,
        "confidence": round(rep.confidence, 6),
    }, indent=2))
    return 0 if rep.available else 1


def cmd_keys(args) -> int:
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.wire import bech32

    pk = PrivateKey.from_seed(args.seed.encode())
    pub = pk.public_key()
    print(json.dumps({
        "address": pub.address().hex(),
        "bech32": bech32.encode(pub.address()),
        "pubkey": pub.compressed.hex(),
    }, indent=2))
    return 0


def cmd_addr_conversion(args) -> int:
    """cmd/root.go addr-conversion: bech32 <-> hex for celestia addresses."""
    from celestia_app_tpu.wire import bech32

    a = args.address
    if a.startswith("celestia"):
        pos = a.rfind("1")
        hrp = a[:pos]
        raw = bech32.decode(a, hrp)
        print(json.dumps({"hex": raw.hex(), "bech32": a}))
    else:
        raw = bytes.fromhex(a)
        print(json.dumps({
            "hex": a,
            "bech32": bech32.encode(raw),
            "valoper": bech32.encode(raw, bech32.HRP_VALOPER),
        }))
    return 0


def cmd_rollback(args) -> int:
    app, _ = _make_app(args.home)
    app.load_height(args.height)
    app.persist_identity()  # point LATEST back so starts resume here
    print(f"rolled back to height {app.height}")
    return 0


def cmd_export(args) -> int:
    app, _ = _make_app(args.home)
    print(json.dumps(app.export_genesis(), indent=2, sort_keys=True))
    return 0


def cmd_blocktime(args) -> int:
    from celestia_app_tpu.tools import blocktime

    print(json.dumps(blocktime.report(os.path.join(args.home, "data"), args.last), indent=2))
    return 0


def cmd_blockscan(args) -> int:
    from celestia_app_tpu.tools import blockscan

    for row in blockscan.scan(os.path.join(args.home, "data")):
        print(json.dumps(row))
    return 0


def cmd_timeline(args) -> int:
    """Cross-node span waterfall (tools/timeline.py): scrape
    /trace/spans from every node of a devnet, merge by the deterministic
    per-height trace ids, render per-height timelines (or dump JSON)."""
    from celestia_app_tpu.tools import timeline

    return timeline.main(
        ["--nodes", args.nodes]
        + (["--height", str(args.height)] if args.height is not None else [])
        + (["--since", str(args.since)] if args.since else [])
        + ["--limit", str(args.limit), "--last", str(args.last)]
        + (["--json"] if args.json else [])
        + (["--no-xfer"] if getattr(args, "no_xfer", False) else [])
    )


def cmd_fleetmon(args) -> int:
    """Fleet-wide SLO verdict (tools/fleetmon.py): scrape every node,
    evaluate the declarative rule file, print one deterministic verdict
    JSON; exit code 2 on violation so CI can gate on it."""
    from celestia_app_tpu.tools import fleetmon

    return fleetmon.main(
        ["--nodes", args.nodes, "--rules", args.rules]
        + (["--no-availability"] if args.no_availability else [])
        + (["--out", args.out] if args.out else [])
    )


def cmd_txsim(args) -> int:
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.tools import txsim

    from celestia_app_tpu import appconsts as _consts

    if args.url:
        return _txsim_load(args)
    if not args.home:
        print("ERROR: txsim needs --home (paced mode) or --url "
              "(sustained-load mode)", file=sys.stderr)
        return 1
    app, cfg = _make_app(args.home)
    node = Node(app, **_mempool_kwargs(cfg))
    from celestia_app_tpu.chain.state import Context, InfiniteGasMeter

    ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                  app.chain_id, app.app_version)
    signer = Signer(app.chain_id)
    accounts = []
    for i in range(args.accounts):
        # seeds are the decimal strings "0", "1", ... so `keys derive 0`
        # prints the matching address for genesis funding
        pk = PrivateKey.from_seed(str(i).encode())
        addr = pk.public_key().address()
        acc = app.auth.account(ctx, addr)
        number = acc["number"] if acc else i
        sequence = acc["sequence"] if acc else 0
        signer.add_account(pk, number, sequence)
        accounts.append(addr)
    validators = None
    if args.stake_sequences:
        validators = [op for op, _p in app.staking.validators(ctx)]
    rep = txsim.run(
        node, signer, accounts,
        rounds=args.rounds,
        blob_sequences=args.blob_sequences,
        send_sequences=args.send_sequences,
        stake_sequences=args.stake_sequences,
        blob_sizes=tuple(int(x) for x in args.blob_sizes.split("-")),
        blobs_per_pfb=tuple(int(x) for x in args.blobs_per_pfb.split("-")),
        validators=validators,
    )
    print(json.dumps(rep.as_dict(), indent=2))
    return 0


def _txsim_load(args) -> int:
    """Sustained-load txsim against a live devnet (tools/txsim.run_load):
    N concurrent sequences over persistent keep-alive connections, each
    confirm-polling its txs to commit. Accounts are the standard derive
    keys ("0", "1", ...), resolved against the node's auth state — fund
    them first (`init` funds 0..9 by default)."""
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.client.tx_client import HttpNodeClient, Signer
    from celestia_app_tpu.tools import txsim

    probe = HttpNodeClient(args.url[0])
    status = probe.status()
    signer = Signer(status["chain_id"])
    accounts = []
    n_seq = args.blob_sequences + args.send_sequences
    for i in range(max(args.accounts, n_seq)):
        pk = PrivateKey.from_seed(str(i).encode())
        addr = pk.public_key().address()
        out = probe._post("/abci_query", {"path": "auth/account",
                                          "data": {"address": addr.hex()}})
        acc = out.get("account")
        if acc is None:
            print(f"ERROR: derive key {i} ({addr.hex()}) has no funded "
                  f"account on the node; fund it first", file=sys.stderr)
            probe.close()
            return 1
        signer.add_account(pk, acc["number"], acc["sequence"])
        accounts.append(addr)
    probe.close()
    lo, hi = (float(x) for x in args.gas_prices.split("-"))
    cfg = txsim.LoadConfig(
        blob_sequences=args.blob_sequences,
        send_sequences=args.send_sequences,
        txs_per_sequence=args.txs_per_sequence,
        blob_sizes=tuple(int(x) for x in args.blob_sizes.split("-")),
        blobs_per_pfb=tuple(int(x) for x in args.blobs_per_pfb.split("-")),
        gas_prices=(lo, hi),
        seed=args.seed,
        confirm_timeout_s=args.confirm_timeout,
    )
    rep = txsim.run_load(args.url, signer, accounts, cfg)
    print(json.dumps(rep.as_dict(), indent=2))
    return 0


def cmd_dasload(args) -> int:
    """Serving-plane load harness (tools/dasload.py): drive N concurrent
    persistent-connection samplers at a devnet's /das/* surface and
    print the JSON report (samples_per_sec, p99_ms, pack_hit_ratio)."""
    from celestia_app_tpu.tools import dasload

    argv = ["--url", args.url, "--samplers", str(args.samplers),
            "--requests", str(args.requests), "--cells", str(args.cells),
            "--mode", args.mode]
    if args.heights:
        argv += ["--heights", args.heights]
    return dasload.main(argv)


def cmd_blobload(args) -> int:
    """Read-plane load harness (tools/blobload.py): drive N concurrent
    persistent-connection namespace readers at a devnet's /blob/*
    surface and print the JSON report (namespace_queries_per_sec,
    p99_ms, present_ratio, pack_hit_ratio)."""
    from celestia_app_tpu.tools import blobload

    argv = ["--url", args.url, "--readers", str(args.readers),
            "--requests", str(args.requests), "--mode", args.mode,
            "--batch", str(args.batch)]
    if args.heights:
        argv += ["--heights", args.heights]
    if args.namespaces:
        argv += ["--namespaces", args.namespaces]
    return blobload.main(argv)


def _git_changed_package_files(pkg_root: str) -> set[str] | None:
    """Package-relative paths of .py files changed vs HEAD (staged,
    unstaged, and untracked), or None when git is unavailable."""
    import subprocess

    pkg_root = os.path.abspath(pkg_root)
    try:
        top = subprocess.run(
            ["git", "-C", pkg_root, "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, timeout=30)
        if top.returncode != 0:
            return None
        repo = top.stdout.strip()
        diff = subprocess.run(
            ["git", "-C", repo, "diff", "--name-only", "HEAD"],
            capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "-C", repo, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30)
        if diff.returncode != 0 or untracked.returncode != 0:
            return None
    except (OSError, subprocess.SubprocessError):
        return None
    changed: set[str] = set()
    for line in (diff.stdout + untracked.stdout).splitlines():
        abspath = os.path.join(repo, line.strip())
        rel = os.path.relpath(abspath, pkg_root)
        if line.strip().endswith(".py") and not rel.startswith(".."):
            changed.add(rel.replace(os.sep, "/"))
    return changed


def cmd_analyze(args) -> int:
    """The analysis plane (tools/analyze): run every registered rule
    over the package tree against the committed analyze.toml. Exit 0
    on a clean (or fully waived) tree, 1 when any error-severity
    violation survives — the same verdict tests/test_analyze.py pins —
    and 2 on operator error (unknown --rule names the registry)."""
    from celestia_app_tpu.tools.analyze import load_config, run_analysis
    from celestia_app_tpu.tools.analyze.engine import registered_rule_ids
    from celestia_app_tpu.tools.analyze.report import to_json_text, to_text

    config = load_config(args.config) if args.config else None
    only = None
    if args.rule:
        only = {r.strip() for spec in args.rule
                for r in spec.split(",") if r.strip()}
        known = registered_rule_ids()
        unknown = sorted(only - known)
        if unknown:
            print(f"analyze: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            print(f"registered rules: {', '.join(sorted(known))}",
                  file=sys.stderr)
            return 2
    rep = run_analysis(root=args.root, config=config, only_rules=only,
                       cache=not args.no_cache)
    if args.scopes:
        from celestia_app_tpu.tools.analyze.taint import scopes_report

        if rep.program is None:
            print("analyze: --scopes needs the interprocedural rules "
                  "enabled (det-reach)", file=sys.stderr)
            return 2
        print(scopes_report(rep.program,
                            config if config else load_config()))
        return 1 if rep.errors else 0
    if args.effects:
        from celestia_app_tpu.tools.analyze.effects import describe_symbol

        if rep.program is None:
            print("analyze: --effects needs the interprocedural rules "
                  "enabled (they link the call graph)", file=sys.stderr)
            return 2
        print(describe_symbol(rep.program, args.effects))
        return 1 if rep.errors else 0
    if args.changed:
        changed = _git_changed_package_files(rep.root)
        if changed is None:
            print("analyze: --changed needs a git checkout",
                  file=sys.stderr)
            return 2

        def _touches_changed(v) -> bool:
            # interprocedural violations anchor at the ROOT of the
            # chain (blocking-under-lock reports at the lock holder),
            # so an edit to any file on the call path must surface too
            if v.path in changed:
                return True
            return any(node.split("::")[0] in changed
                       for node in (v.call_path or ()))

        rep.violations = [v for v in rep.violations
                          if _touches_changed(v)]
    if args.json:
        print(to_json_text(rep))
    else:
        print(to_text(rep, verbose=args.verbose))
    return 1 if rep.errors else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="celestia_app_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("init")
    p.add_argument("--home", required=True)
    p.add_argument("--chain-id", default="celestia-tpu-1")
    p.add_argument("--engine", default="auto")
    p.add_argument("--account", action="append")
    p.add_argument("--validator", action="append")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start")
    p.add_argument("--home", required=True)
    p.add_argument("--listen", type=int, default=26658)
    p.add_argument("--grpc", type=int, default=None,
                   help="also serve cosmos.tx.v1beta1.Service on this port "
                        "(9090 in the reference; 0 = ephemeral)")
    p.add_argument("--block-time", type=float, default=6.0)
    p.add_argument("--blocks", type=int, default=None)
    p.add_argument("--trace", action="store_true",
                   help="append every committed store write/delete to "
                        "data/store_trace.jsonl (SetCommitMultiStoreTracer "
                        "analog)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status")
    p.add_argument("--home", required=True)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("query")
    p.add_argument("--home", required=True)
    p.add_argument("path")
    p.add_argument("data", nargs="?")
    p.set_defaults(fn=cmd_query)

    p = sub.add_parser("tx")
    p.add_argument("action",
                   choices=["send", "pay-for-blob", "create-validator"])
    p.add_argument("--home", required=True)
    p.add_argument("--from-seed", required=True,
                   help="key seed (matches `keys derive`)")
    p.add_argument("--to", help="recipient address hex (send)")
    p.add_argument("--amount", help="utia amount (send)")
    p.add_argument("--namespace", help="10-hex-char v0 namespace id (pfb)")
    p.add_argument("--data", help="blob hex, or @file for raw bytes (pfb)")
    p.add_argument("--input-file",
                   help="multi-blob JSON file (reference --input-file "
                        "schema: {\"Blobs\": [{\"namespaceID\": \"0x..\", "
                        "\"blob\": \"0x..\"}]})")
    p.set_defaults(fn=cmd_tx)

    p = sub.add_parser("devnet")
    p.add_argument("--home", required=True)
    p.add_argument("--chain-id", default="celestia-devnet-1")
    p.add_argument("--validators", type=int, default=3)
    p.add_argument("--blocks", type=int, default=None)
    p.add_argument("--block-time", type=float, default=1.0)
    p.add_argument("--load", action="store_true",
                   help="submit a send per block (txsim-lite)")
    p.add_argument("--processes", action="store_true",
                   help="one OS process per validator; consensus over sockets")
    p.add_argument("--autonomous", action="store_true",
                   help="with --processes: no coordinator — each validator "
                        "runs its own consensus reactor and gossips "
                        "proposals/votes/txs peer-to-peer")
    p.set_defaults(fn=cmd_devnet)

    p = sub.add_parser(
        "relayer",
        help="IBC relayer daemon between two live nodes over HTTP "
             "(hermes role): packets, acks, and timeouts, all "
             "proof-gated consensus txs")
    p.add_argument("--url-a", required=True, help="node A HTTP URL")
    p.add_argument("--url-b", required=True, help="node B HTTP URL")
    p.add_argument("--seed-a", required=True,
                   help="relayer key seed on chain A (keys derive)")
    p.add_argument("--seed-b", required=True)
    p.add_argument("--client-a", default="client-b",
                   help="client ON chain A tracking chain B")
    p.add_argument("--client-b", default="client-a")
    p.add_argument("--passes", type=int, default=None,
                   help="relay passes to run (default: forever)")
    p.add_argument("--interval", type=float, default=3.0,
                   help="seconds between passes (ConfirmTx-style poll)")
    p.add_argument("--insecure", action="store_true",
                   help="relay on say-so roots instead of certified "
                        "headers — requires clients created with an "
                        "authorized relayer; test fixtures only "
                        "(default: verifying light-client updates)")
    p.set_defaults(fn=cmd_relayer)

    p = sub.add_parser(
        "da-serve",
        help="standalone DA-core service for foreign nodes (§7.1.7 "
             "shim): /da/extend_commit + /da/prove_shares, no chain "
             "attached; --grpc adds celestia_tpu.da.v1.DAService")
    p.add_argument("--listen", type=int, default=26659)
    p.add_argument("--grpc", type=int, default=None)
    p.add_argument("--engine", default="host", choices=("host", "device"))
    p.set_defaults(fn=cmd_da_serve)

    p = sub.add_parser(
        "das-serve",
        help="DAS sample-proof server over a node home (das/server.py): "
             "GET /das/sample + batched POST /das/samples from committed "
             "blocks — the full-node half of the DAS plane")
    p.add_argument("--home", required=True)
    p.add_argument("--listen", type=int, default=26660)
    p.add_argument("--cache-heights", type=int, default=4,
                   help="LRU square-cache depth (per-height row trees)")
    p.add_argument("--no-packs", action="store_true",
                   help="disable static proof-pack serving (GET /das/pack"
                        "*) even when <home>/packs holds packs")
    p.set_defaults(fn=cmd_das_serve)

    p = sub.add_parser(
        "blob-serve",
        help="read-plane sidecar over a node home (das/blob_server.py): "
             "GET /blob/get + batched POST /blob/namespaces + static "
             "blob-pack chunks for rollup readers")
    p.add_argument("--home", required=True)
    p.add_argument("--listen", type=int, default=26661)
    p.add_argument("--cache-heights", type=int, default=4,
                   help="LRU square-cache depth (per-height row trees)")
    p.add_argument("--no-packs", action="store_true",
                   help="disable static blob-pack serving (GET /blob/pack"
                        "*) even when <home>/blobpacks holds packs")
    p.set_defaults(fn=cmd_blob_serve)

    p = sub.add_parser(
        "das-follow",
        help="DASer light-node daemon (das/daser.py): follow headers by "
             "commit certificate, sample every height, checkpoint under "
             "--home/das/, halt on a verified bad-encoding fraud proof")
    p.add_argument("--home", required=True,
                   help="holds genesis.json (the trust root) and the "
                        "das/checkpoint.json progress record")
    p.add_argument("--chain-id", default="celestia-tpu-1")
    p.add_argument("--peer", action="append",
                   help="sampling/header peer URL (repeatable)")
    p.add_argument("--samples", type=int, default=16,
                   help="cells sampled per header (confidence 1-(3/4)^s)")
    p.add_argument("--workers", type=int, default=3,
                   help="parallel catch-up workers")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between sweeps")
    p.add_argument("--seed", type=int, default=None,
                   help="sampling rng seed (default: fresh entropy)")
    p.add_argument("--once", action="store_true",
                   help="exit 0 once caught up to the served head")
    p.add_argument("--no-packs", action="store_true",
                   help="never fetch advertised proof-pack chunks; "
                        "sample via live /das/samples only")
    p.set_defaults(fn=cmd_das_follow)

    p = sub.add_parser(
        "blob-follow",
        help="rollup follower daemon (client/follower.py): track one "
             "namespace as a verifying light client — certified "
             "headers, checked inclusion/absence proofs, checkpoint "
             "under --home/blob/")
    p.add_argument("--home", required=True,
                   help="holds genesis.json (the trust root) and the "
                        "blob/checkpoint.json progress record")
    p.add_argument("--chain-id", default="celestia-tpu-1")
    p.add_argument("--peer", action="append",
                   help="serving peer URL (repeatable)")
    p.add_argument("--namespace", required=True,
                   help="29-byte namespace hex to follow")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between sweeps")
    p.add_argument("--once", action="store_true",
                   help="exit 0 once caught up to the served head")
    p.add_argument("--no-packs", action="store_true",
                   help="never read advertised blob-pack chunks; resolve "
                        "via live /blob/get only")
    p.set_defaults(fn=cmd_blob_follow)

    p = sub.add_parser(
        "verify",
        help="blobstream verify (x/blobstream client verify analog): "
             "prove shares at a height up to the covering data-commitment "
             "attestation's tuple root")
    p.add_argument("--home", required=True)
    p.add_argument("--height", type=int, required=True)
    p.add_argument("--start", type=int, default=0,
                   help="ODS share start index (row-major)")
    p.add_argument("--end", type=int, default=1, help="exclusive end index")
    p.add_argument("--namespace",
                   help="29-byte namespace hex (default: share 0's)")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser(
        "multihost-dryrun",
        help="prove the cross-host SPMD path: N processes x M virtual CPU "
             "devices as ONE global mesh (jax.distributed + Gloo, the DCN "
             "stand-in), sharded pipeline, every host checking the mesh's "
             "root against the independently recomputed CPU oracle")
    p.add_argument("--processes", type=int, default=2)
    p.add_argument("--devices-per-host", type=int, default=4)
    p.add_argument("--k", type=int, default=16)
    p.add_argument("--batch", type=int, default=2)
    p.set_defaults(fn=cmd_multihost_dryrun)

    p = sub.add_parser("multihost-worker")  # internal (spawned)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--coordinator", required=True)
    p.add_argument("--k", type=int, required=True)
    p.add_argument("--batch", type=int, required=True)
    p.add_argument("--devices-per-host", type=int, required=True)
    p.set_defaults(fn=cmd_multihost_worker)

    p = sub.add_parser(
        "e2e-bench",
        help="throughput benchmark over the autonomous process devnet "
             "(the reference test/e2e/benchmark analog: PFB flood, "
             "injected gossip latency, BlockSummary scrape, >=90%%-of-"
             "target pass criterion)")
    p.add_argument("--home", required=True)
    p.add_argument("--chain-id", default="celestia-e2e-bench")
    p.add_argument("--validators", type=int, default=2)
    p.add_argument("--blocks", type=int, default=8)
    p.add_argument("--block-time", type=float, default=1.0)
    p.add_argument("--blob-kb", type=int, default=200,
                   help="per-blob size (reference floods 200 KB blobs)")
    p.add_argument("--blobs-per-tx", type=int, default=2)
    p.add_argument("--txs-per-block", type=int, default=4,
                   help="load pacing: PFBs submitted per committed height "
                        "(txsim's per-sequence-per-block pacing; the "
                        "default 4 x 400 KB fills the 1.97 MB default "
                        "square without flooding the mempool cap)")
    p.add_argument("--latency-ms", type=float, default=70.0,
                   help="injected per-message gossip latency "
                        "(BitTwister's 70 ms in the reference manifests)")
    p.add_argument("--target-mb", type=float, default=1.0,
                   help="pass if some block >= 90%% of this "
                        "(TwoNodeSimple criterion: 1 MB)")
    p.set_defaults(fn=cmd_e2e_bench)

    p = sub.add_parser("validator-serve",
                       help="one validator process: HTTP consensus service")
    p.add_argument("--home", required=True,
                   help="validator home (genesis.json + key.json inside)")
    p.add_argument("--chain-id", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--grpc", type=int, default=None,
                   help="also serve the cosmos gRPC surface on this port "
                        "(0 = ephemeral)")
    p.add_argument("--http", type=int, default=None,
                   help="also serve the node HTTP query surface (status/"
                        "block/abci_query/trace/metrics; 0 = ephemeral)")
    p.add_argument("--autonomous", action="store_true",
                   help="run the consensus reactor in-process: wait for "
                        "<home>/peers.json, then drive rounds by gossiping "
                        "with those peers (no external orchestrator)")
    p.set_defaults(fn=cmd_validator_serve)

    p = sub.add_parser("addr-conversion")
    p.add_argument("address", help="bech32 celestia1.../hex address")
    p.set_defaults(fn=cmd_addr_conversion)

    p = sub.add_parser("genesis", help="genesis file toolkit (genutil analog)")
    gsub = p.add_subparsers(dest="gcmd", required=True)
    g = gsub.add_parser("add-account")
    g.add_argument("--home", required=True)
    g.add_argument("--address", required=True, help="20-byte hex address")
    g.add_argument("--balance", required=True, type=int)
    g.set_defaults(fn=cmd_genesis_add_account)
    g = gsub.add_parser("gentx")
    g.add_argument("--home", required=True)
    g.add_argument("--seed", required=True, help="key seed (as `keys`)")
    g.add_argument("--moniker", default="validator")
    g.add_argument("--power", required=True, type=int)
    g.set_defaults(fn=cmd_genesis_gentx)
    g = gsub.add_parser("collect-gentxs")
    g.add_argument("--home", required=True)
    g.set_defaults(fn=cmd_genesis_collect)
    g = gsub.add_parser("validate")
    g.add_argument("--home", required=True)
    g.set_defaults(fn=cmd_genesis_validate)

    p = sub.add_parser("config", help="get/set node-local config keys")
    p.add_argument("action", choices=["get", "set"])
    p.add_argument("key", nargs="?")
    p.add_argument("value", nargs="?")
    p.add_argument("--home", required=True)
    p.set_defaults(fn=cmd_config)

    p = sub.add_parser("download-genesis",
                       help="fetch/verify a known network's genesis "
                            "against its pinned sha256")
    p.add_argument("chain_id", nargs="?", default="celestia")
    p.add_argument("--home", required=True)
    p.set_defaults(fn=cmd_download_genesis)

    p = sub.add_parser("snapshot")
    p.add_argument("action", choices=["create", "restore"])
    p.add_argument("--home", required=True)
    p.add_argument("--out", required=True, help="snapshot directory")
    p.set_defaults(fn=cmd_snapshot)

    p = sub.add_parser("das", help="sample a block's data availability")
    p.add_argument("--home", help="local self-audit of a stored block")
    p.add_argument("--url", help="light-node mode against a remote node")
    p.add_argument("--height", type=int, default=None)
    p.add_argument("--samples", type=int, default=16)
    p.add_argument("--trusted-root",
                   help="hex data root from a TRUSTED source (e.g. a light "
                        "client following certificates); binds the served "
                        "DAH so the server cannot fabricate the block")
    p.add_argument("--seed", type=int, default=None,
                   help="sampling entropy (default: OS randomness)")
    p.set_defaults(fn=cmd_das)

    p = sub.add_parser("keys")
    p.add_argument("action", choices=["derive"])
    p.add_argument("seed")
    p.set_defaults(fn=cmd_keys)

    p = sub.add_parser("rollback")
    p.add_argument("--home", required=True)
    p.add_argument("height", type=int)
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser("export")
    p.add_argument("--home", required=True)
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("blocktime")
    p.add_argument("--home", required=True)
    p.add_argument("--last", type=int, default=None)
    p.set_defaults(fn=cmd_blocktime)

    p = sub.add_parser(
        "timeline",
        help="cross-node span waterfall: scrape /trace/spans from every "
             "node, merge by trace_id, render per-height timelines",
    )
    p.add_argument("--nodes", required=True,
                   help="comma-separated node/validator service URLs")
    p.add_argument("--height", type=int, default=None,
                   help="only this height's trace")
    p.add_argument("--since", type=int, default=0)
    p.add_argument("--limit", type=int, default=10_000)
    p.add_argument("--last", type=int, default=5,
                   help="render the N most recent heights (text mode)")
    p.add_argument("--json", action="store_true",
                   help="dump merged spans as JSON")
    p.add_argument("--no-xfer", action="store_true",
                   help="skip the transfer-ledger rows (/trace/xfer)")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "fleetmon",
        help="fleet-wide SLO verdict (tools/fleetmon.py): scrape "
             "/metrics + status from every node, evaluate a declarative "
             "rule file, exit 0 pass / 2 violation",
    )
    p.add_argument("--nodes", required=True,
                   help="comma-separated node/validator service URLs")
    p.add_argument("--rules", required=True,
                   help="SLO rule file (JSON, FORMATS §22.1)")
    p.add_argument("--no-availability", action="store_true",
                   help="skip the /das/availability scrape")
    p.add_argument("--out", default=None,
                   help="also write the verdict JSON to this file")
    p.set_defaults(fn=cmd_fleetmon)

    p = sub.add_parser("blockscan")
    p.add_argument("--home", required=True)
    p.set_defaults(fn=cmd_blockscan)

    p = sub.add_parser(
        "txsim",
        help="tx load generator (tools/txsim.py): paced in-process "
             "rounds against --home, or the sustained-load engine "
             "(concurrent keep-alive sequences, confirm-polling) "
             "against a live devnet via --url")
    p.add_argument("--home",
                   help="paced mode: the node home to drive in-process")
    p.add_argument("--url", action="append", default=None,
                   help="load mode: devnet service URL (repeatable; "
                        "sequences round-robin over them)")
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--accounts", type=int, default=3)
    p.add_argument("--blob-sequences", type=int, default=2)
    p.add_argument("--send-sequences", type=int, default=1)
    p.add_argument("--stake-sequences", type=int, default=0)
    p.add_argument("--blob-sizes", default="100-2000")
    p.add_argument("--blobs-per-pfb", default="1-3")
    p.add_argument("--txs-per-sequence", type=int, default=8,
                   help="load mode: txs each sequence submits")
    p.add_argument("--gas-prices", default="0.002-0.02",
                   help="load mode: uniform gas-price draw LO-HI")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--confirm-timeout", type=float, default=60.0)
    p.set_defaults(fn=cmd_txsim)

    p = sub.add_parser(
        "dasload",
        help="serving-plane load harness (tools/dasload.py): thousands "
             "of concurrent persistent-connection samplers against a "
             "devnet's /das/* surface; prints the JSON report")
    p.add_argument("--url", required=True)
    p.add_argument("--samplers", type=int, default=1000)
    p.add_argument("--requests", type=int, default=3)
    p.add_argument("--cells", type=int, default=16)
    p.add_argument("--mode", choices=("live", "pack", "auto"),
                   default="auto")
    p.add_argument("--heights", default="",
                   help="comma-separated heights (default: last 8 below "
                        "the served head)")
    p.set_defaults(fn=cmd_dasload)

    p = sub.add_parser(
        "blobload",
        help="read-plane load harness (tools/blobload.py): concurrent "
             "persistent-connection namespace readers against a devnet's "
             "/blob/* surface; prints the JSON report")
    p.add_argument("--url", required=True)
    p.add_argument("--readers", type=int, default=256)
    p.add_argument("--requests", type=int, default=4)
    p.add_argument("--mode", choices=("single", "batch", "pack"),
                   default="batch")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--heights", default="",
                   help="comma-separated heights (default: last 4 below "
                        "the served head)")
    p.add_argument("--namespaces", default="",
                   help="comma-separated namespace hex (default: the "
                        "heights' packed namespaces)")
    p.set_defaults(fn=cmd_blobload)

    p = sub.add_parser(
        "analyze",
        help="static-analysis plane: consensus-determinism, exception "
             "hygiene, jit purity, and lock-discipline rules over the "
             "package tree (config: analyze.toml)",
    )
    p.add_argument("--json", action="store_true",
                   help="JSON report (docs/FORMATS.md §11) instead of text")
    p.add_argument("--root", default=None,
                   help="directory to analyze (default: the installed "
                        "celestia_app_tpu package)")
    p.add_argument("--config", default=None,
                   help="alternate analyze.toml")
    p.add_argument("--rule", action="append",
                   help="run only these rule ids (comma-separated, "
                        "repeatable); unknown names exit 2 listing "
                        "the registry")
    p.add_argument("--verbose", action="store_true",
                   help="also print waived violations")
    p.add_argument("--scopes", action="store_true",
                   help="print the computed consensus-reachable scope "
                        "audit (det-reach roots -> minimal det-* "
                        "include lists) instead of violations")
    p.add_argument("--changed", action="store_true",
                   help="report only violations in files changed vs "
                        "git HEAD (dev loop; the full tree still "
                        "feeds the call graph)")
    p.add_argument("--effects", metavar="QUALNAME", default=None,
                   help="print one symbol's computed effect summary "
                        "(nearest unledgered host sink with its path, "
                        "transitive lock acquisitions, required-held "
                        "locks, escaping exceptions) instead of "
                        "violations; accepts path.py::Qual.name or a "
                        "unique ::symbol suffix")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the per-file incremental result cache "
                        "(.analyze_cache.json)")
    p.set_defaults(fn=cmd_analyze)

    args = ap.parse_args(argv)
    mark = len(_OPEN_APPS)  # only close what THIS invocation opens — tests
    try:                    # may hold apps from direct _make_app calls
        return args.fn(args)
    except BrokenPipeError:
        # stdout piped into a pager/head that exited: normal CLI etiquette
        # is a silent success, not a traceback
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
    finally:
        while len(_OPEN_APPS) > mark:
            app = _OPEN_APPS.pop()()
            if app is not None:
                try:
                    app.close()
                except Exception:
                    pass


if __name__ == "__main__":
    sys.exit(main())
