"""Minimal telemetry registry: counters + timing histograms.

Reference parity: the reference instruments its hot paths with
``telemetry.MeasureSince`` (app/prepare_proposal.go:23,
app/process_proposal.go:25) and go-metrics counters. This registry is
process-local and lock-free (CPython dict ops are atomic enough for the
single-threaded node loop; the HTTP service reads a snapshot copy).

Usage:
    t0 = time.perf_counter()
    ...
    telemetry.measure_since("prepare_proposal", t0)
    telemetry.incr("process_proposal.rejected")
Snapshot via telemetry.snapshot() — surfaced in /status and the CLI.
"""

from __future__ import annotations

import time


class Registry:
    def __init__(self):
        self.counters: dict[str, int] = {}
        self.timers: dict[str, dict] = {}

    def incr(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def measure_since(self, name: str, t0: float) -> float:
        dt = time.perf_counter() - t0
        t = self.timers.setdefault(
            name, {"count": 0, "total_s": 0.0, "max_s": 0.0, "last_s": 0.0}
        )
        t["count"] += 1
        t["total_s"] += dt
        t["max_s"] = max(t["max_s"], dt)
        t["last_s"] = dt
        return dt

    def snapshot(self) -> dict:
        out = {"counters": dict(self.counters), "timers": {}}
        for name, t in self.timers.items():
            avg = t["total_s"] / t["count"] if t["count"] else 0.0
            out["timers"][name] = {**t, "avg_s": avg}
        return out

    def reset(self) -> None:
        self.counters.clear()
        self.timers.clear()


_global = Registry()

incr = _global.incr
measure_since = _global.measure_since
snapshot = _global.snapshot
reset = _global.reset
