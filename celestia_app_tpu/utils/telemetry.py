"""Minimal telemetry registry: counters + timing histograms.

Reference parity: the reference instruments its hot paths with
``telemetry.MeasureSince`` (app/prepare_proposal.go:23,
app/process_proposal.go:25) and go-metrics counters. This registry is
process-local and lock-free (CPython dict ops are atomic enough for the
single-threaded node loop; the HTTP service reads a snapshot copy).

Usage:
    t0 = time.perf_counter()
    ...
    telemetry.measure_since("prepare_proposal", t0)
    telemetry.incr("process_proposal.rejected")
Snapshot via telemetry.snapshot() — surfaced in /status and the CLI.
"""

from __future__ import annotations

import time


class Registry:
    def __init__(self):
        self.counters: dict[str, int] = {}
        self.timers: dict[str, dict] = {}
        self.gauges: dict[str, float] = {}

    def incr(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    def gauge(self, name: str, value: float) -> None:
        """Set-type metric (pool sizes, queue depths): last write wins."""
        self.gauges[name] = value

    def measure_since(self, name: str, t0: float) -> float:
        dt = time.perf_counter() - t0
        t = self.timers.setdefault(
            name, {"count": 0, "total_s": 0.0, "max_s": 0.0, "last_s": 0.0}
        )
        t["count"] += 1
        t["total_s"] += dt
        t["max_s"] = max(t["max_s"], dt)
        t["last_s"] = dt
        return dt

    def snapshot(self) -> dict:
        out = {"counters": dict(self.counters), "timers": {},
               "gauges": dict(self.gauges)}
        for name, t in self.timers.items():
            avg = t["total_s"] / t["count"] if t["count"] else 0.0
            out["timers"][name] = {**t, "avg_s": avg}
        return out

    def reset(self) -> None:
        self.counters.clear()
        self.timers.clear()
        self.gauges.clear()

    def prometheus(self, prefix: str = "celestia") -> str:
        """Prometheus text exposition of the registry (the reference wires
        node.DefaultMetricsProvider + a prometheus endpoint —
        test/util/testnode/full_node.go:44, SURVEY §5.1). Counters become
        `<prefix>_<name>_total`; timers become `_seconds_{count,sum,max}`."""

        def _san(name: str) -> str:
            return "".join(
                ch if ch.isalnum() or ch == "_" else "_" for ch in name
            )

        # snapshot copies: another thread may insert a first-time metric
        # mid-scrape (the docstring's promise that readers see a copy)
        counters = dict(self.counters)
        timers = {k: dict(v) for k, v in dict(self.timers).items()}
        gauges = dict(self.gauges)
        lines: list[str] = []
        for name, v in sorted(counters.items()):
            m = f"{prefix}_{_san(name)}_total"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {v}")
        for name, v in sorted(gauges.items()):
            m = f"{prefix}_{_san(name)}"
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {v}")
        for name, t in sorted(timers.items()):
            base = f"{prefix}_{_san(name)}_seconds"
            lines.append(f"# TYPE {base} summary")
            lines.append(f"{base}_count {t['count']}")
            lines.append(f"{base}_sum {t['total_s']:.9f}")
            lines.append(f"{base}_max {t['max_s']:.9f}")
        return "\n".join(lines) + "\n"


class TraceTables:
    """Columnar event tracing — the celestia-core ``pkg/trace`` analog
    (SURVEY §5.1): PER-NODE tables of schema'd rows (``BlockSummary``,
    ``RoundState``-style) that e2e tooling pulls over RPC
    (test/e2e/testnet/node.go:52-75). Each App owns an instance
    (`app.traces`) so multi-node in-process networks never interleave;
    the module-level singleton below serves ad-hoc/process-wide use.
    Tables are bounded ring buffers; rows carry a monotonically
    increasing index so pullers can resume."""

    MAX_ROWS = 10_000

    def __init__(self):
        self._tables: dict[str, list[dict]] = {}
        self._next_index: dict[str, int] = {}

    def write(self, table: str, **row) -> None:
        rows = self._tables.setdefault(table, [])
        idx = self._next_index.get(table, 0)
        rows.append({"_index": idx, **row})
        self._next_index[table] = idx + 1
        if len(rows) > self.MAX_ROWS:
            del rows[: len(rows) - self.MAX_ROWS]

    def read(self, table: str, since_index: int = 0, limit: int = 1000) -> list[dict]:
        rows = self._tables.get(table, [])
        return [r for r in rows if r["_index"] >= since_index][:limit]

    def tables(self) -> list[str]:
        return sorted(self._tables)

    def reset(self) -> None:
        self._tables.clear()
        self._next_index.clear()


_global = Registry()
_traces = TraceTables()

incr = _global.incr
gauge = _global.gauge
measure_since = _global.measure_since
snapshot = _global.snapshot
prometheus = _global.prometheus
reset = _global.reset
trace = _traces.write
read_trace = _traces.read
trace_tables = _traces.tables
reset_traces = _traces.reset
