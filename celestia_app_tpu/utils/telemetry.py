"""Telemetry registry: counters, gauges, bucketed histograms, trace tables.

Reference parity: the reference instruments its hot paths with
``telemetry.MeasureSince`` (app/prepare_proposal.go:23,
app/process_proposal.go:25) and go-metrics counters, and serves them
through a Prometheus endpoint (SURVEY §5.1). This registry is
process-local and lock-light (CPython dict ops are atomic enough for the
single-threaded node loop; the HTTP service reads snapshot copies).

Timers are **log-spaced bucketed histograms** (×2 ladder, 1 µs … ~137 s):
every ``measure_since``/``observe`` lands in a bucket, so ``snapshot()``
reports p50/p95/p99 estimates (interpolated within the containing bucket
— error bounded by one bucket width) and ``prometheus()`` emits proper
``_bucket{le=...}`` / ``_sum`` / ``_count`` histogram families with
``# HELP`` lines. The nonstandard per-timer max survives as a SEPARATE
gauge family (``<name>_seconds_max``) so promtool-style parsers accept
the page. Counters, gauges, and timers all take an optional ``labels``
dict; labeled series share one family (one HELP/TYPE) in the exposition.

Usage:
    t0 = time.perf_counter()
    ...
    telemetry.measure_since("prepare_proposal", t0)
    telemetry.incr("process_proposal.rejected")
    telemetry.observe("batch_bytes_s", 0.004, labels={"peer": "val1"})
Snapshot via telemetry.snapshot() — surfaced in /status and the CLI.
"""

from __future__ import annotations

import bisect
import threading
import time

# log-spaced bucket ladder: ×2 per step from 1 µs to ~137 s (28 bounds;
# a 29th implicit +Inf bucket catches the rest). Wide enough for a jit
# compile, fine enough that p99 interpolation stays within ~2× truth.
BUCKET_BOUNDS = tuple(1e-6 * (2.0 ** i) for i in range(28))


def start_timer() -> float:
    """Opaque t0 for ``measure_since``. Consensus-critical modules call
    this instead of reading ``time.perf_counter`` directly, so the
    analyzer's det-wallclock rule keeps raw clock reads out of them —
    the value flows only into telemetry, never into state."""
    return time.perf_counter()


def _series_key(name: str, labels: dict | None) -> str:
    """Storage key: the bare name for unlabeled series (the historical
    snapshot shape), name{k="v",...} for labeled ones."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{labels[k]}"' for k in sorted(labels)
    )
    return f"{name}{{{inner}}}"


def _quantile(buckets: list[int], count: int, q: float) -> float:
    """Histogram quantile estimate: find the bucket holding the q-rank
    observation and interpolate linearly inside it (Prometheus
    histogram_quantile semantics; error <= one bucket width)."""
    if count <= 0:
        return 0.0
    target = q * count
    cum = 0
    for i, n in enumerate(buckets):
        if n == 0:
            continue
        lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
        hi = BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) \
            else BUCKET_BOUNDS[-1]
        if cum + n >= target:
            frac = (target - cum) / n
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += n
    return BUCKET_BOUNDS[-1]


class Registry:
    """Writes land from the node loop, reactor threads, and HTTP
    handler threads concurrently; the read-modify-write on a counter
    (``get + 1`` then store) and the multi-field histogram update are
    NOT atomic under that load, so every access to the four data maps
    goes through ``_lock`` (the static lock-guard rule enforces it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {}          # guarded-by: _lock
        self.timers: dict[str, dict] = {}           # guarded-by: _lock
        self.gauges: dict[str, float] = {}          # guarded-by: _lock
        # series key -> (family name, labels) for labeled exposition
        self._series: dict[str, tuple[str, dict]] = {}  # guarded-by: _lock
        self._help: dict[str, str] = {}
        self._collectors: list = []

    # -- registration -----------------------------------------------------

    def set_help(self, name: str, text: str) -> None:
        """Attach a # HELP line to a metric family (optional; families
        without one get a generated description)."""
        self._help[name] = text

    def register_collector(self, fn) -> None:
        """Scrape-time hook: `fn()` runs (exceptions swallowed) before
        every snapshot()/prometheus() so gauges that are derived from
        live state (device memory, cache sizes) stay fresh without a
        background thread."""
        if fn not in self._collectors:
            self._collectors.append(fn)

    def _collect(self) -> None:
        # runs OUTSIDE _lock: collectors call gauge()/incr(), which take
        # it — holding it here would self-deadlock
        for fn in list(self._collectors):
            try:
                fn()
            except Exception:
                # a broken collector must never break a scrape — but a
                # scrape that silently loses gauges must be visible
                self.incr("telemetry.collector_errors")

    def _note_series_locked(self, key: str, name: str,
                            labels: dict | None) -> None:
        if labels and key not in self._series:
            self._series[key] = (name, dict(labels))

    # -- writes -----------------------------------------------------------

    def incr(self, name: str, by: int = 1, labels: dict | None = None) -> None:
        key = _series_key(name, labels)
        with self._lock:
            self._note_series_locked(key, name, labels)
            self.counters[key] = self.counters.get(key, 0) + by

    def gauge(self, name: str, value: float,
              labels: dict | None = None) -> None:
        """Set-type metric (pool sizes, queue depths): last write wins."""
        key = _series_key(name, labels)
        with self._lock:
            self._note_series_locked(key, name, labels)
            self.gauges[key] = value

    def observe(self, name: str, value_s: float,
                labels: dict | None = None) -> float:
        """Record one observation (seconds, or any unit — the ladder is
        unitless) into the named histogram."""
        key = _series_key(name, labels)
        with self._lock:
            self._note_series_locked(key, name, labels)
            t = self.timers.get(key)
            if t is None:
                t = self.timers[key] = {
                    "count": 0, "total_s": 0.0, "max_s": 0.0,
                    "last_s": 0.0,
                    "buckets": [0] * (len(BUCKET_BOUNDS) + 1),
                }
            t["count"] += 1
            t["total_s"] += value_s
            if value_s > t["max_s"]:
                t["max_s"] = value_s
            t["last_s"] = value_s
            t["buckets"][bisect.bisect_left(BUCKET_BOUNDS, value_s)] += 1
        return value_s

    def measure_since(self, name: str, t0: float,
                      labels: dict | None = None) -> float:
        return self.observe(name, time.perf_counter() - t0, labels=labels)

    # -- reads ------------------------------------------------------------

    def quantiles(self, name: str, qs=(0.5, 0.95, 0.99),
                  labels: dict | None = None) -> dict[float, float]:
        with self._lock:
            t = self.timers.get(_series_key(name, labels))
            if t is None:
                return {q: 0.0 for q in qs}
            buckets, count = list(t["buckets"]), t["count"]
        return {q: _quantile(buckets, count, q) for q in qs}

    def snapshot(self) -> dict:
        self._collect()
        with self._lock:
            counters = dict(self.counters)
            timers = {k: {**v, "buckets": list(v["buckets"])}
                      for k, v in self.timers.items()}
            gauges = dict(self.gauges)
        out = {"counters": counters, "timers": {}, "gauges": gauges}
        for name, t in timers.items():
            buckets = t.pop("buckets")
            count = t["count"]
            avg = t["total_s"] / count if count else 0.0
            out["timers"][name] = {
                **t, "avg_s": avg,
                "p50_s": _quantile(buckets, count, 0.5),
                "p95_s": _quantile(buckets, count, 0.95),
                "p99_s": _quantile(buckets, count, 0.99),
            }
        return out

    def export(self) -> dict:
        """Raw registry state for in-process metric consumers (the SLO
        engine's sim adapter, tools/fleetmon.py): counters/gauges by
        series key, timers WITH their bucket arrays, and the
        series-key -> (family, labels) map. snapshot() serves human
        surfaces and drops the buckets; this keeps them so a consumer
        can diff two exports and compute quantiles over the delta."""
        self._collect()
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timers": {k: {**v, "buckets": list(v["buckets"])}
                           for k, v in self.timers.items()},
                "series": dict(self._series),
            }

    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.timers.clear()
            self.gauges.clear()
            self._series.clear()

    # -- Prometheus text exposition ---------------------------------------

    @staticmethod
    def _family(key: str, series: dict) -> tuple[str, str]:
        """(family name, label string incl. braces or '') for a series."""
        if key in series:
            name, labels = series[key]
            inner = ",".join(
                f'{k}="{labels[k]}"' for k in sorted(labels)
            )
            return name, inner
        return key, ""

    @staticmethod
    def _san(name: str) -> str:
        return "".join(
            ch if ch.isalnum() or ch == "_" else "_" for ch in name
        )

    def _help_line(self, metric: str, family: str, default: str) -> str:
        return f"# HELP {metric} {self._help.get(family, default)}"

    def prometheus(self, prefix: str = "celestia") -> str:
        """Prometheus text exposition (the reference wires
        node.DefaultMetricsProvider + a prometheus endpoint —
        test/util/testnode/full_node.go:44, SURVEY §5.1). Counters become
        ``<prefix>_<name>_total``; timers are real histograms
        (``_bucket``/``_sum``/``_count``) with the per-timer max exposed
        as a SEPARATE ``_max`` gauge family; every family carries
        ``# HELP`` + ``# TYPE``."""
        self._collect()
        # snapshot copies under the lock: another thread may insert a
        # first-time metric mid-scrape (readers always see a copy)
        with self._lock:
            counters = dict(self.counters)
            timers = {k: {**v, "buckets": list(v["buckets"])}
                      for k, v in self.timers.items()}
            gauges = dict(self.gauges)
            series = dict(self._series)

        # group series into families so HELP/TYPE appear once per family
        def families(keys):
            fams: dict[str, list[tuple[str, str]]] = {}
            for key in sorted(keys):
                fam, inner = self._family(key, series)
                fams.setdefault(fam, []).append((inner, key))
            return sorted(fams.items())

        lines: list[str] = []
        for fam, members in families(counters):
            m = f"{prefix}_{self._san(fam)}_total"
            lines.append(self._help_line(m, fam, f"counter {fam}"))
            lines.append(f"# TYPE {m} counter")
            for inner, key in members:
                lbl = f"{{{inner}}}" if inner else ""
                lines.append(f"{m}{lbl} {counters[key]}")
        for fam, members in families(gauges):
            m = f"{prefix}_{self._san(fam)}"
            lines.append(self._help_line(m, fam, f"gauge {fam}"))
            lines.append(f"# TYPE {m} gauge")
            for inner, key in members:
                lbl = f"{{{inner}}}" if inner else ""
                lines.append(f"{m}{lbl} {gauges[key]}")
        timer_fams = families(timers)
        for fam, members in timer_fams:
            base = f"{prefix}_{self._san(fam)}_seconds"
            lines.append(self._help_line(
                base, fam, f"latency histogram {fam} (seconds)"
            ))
            lines.append(f"# TYPE {base} histogram")
            for inner, key in members:
                t = timers[key]
                cum = 0
                for i, bound in enumerate(BUCKET_BOUNDS):
                    cum += t["buckets"][i]
                    le = f'le="{bound:.9g}"'
                    lbl = f"{{{inner},{le}}}" if inner else f"{{{le}}}"
                    lines.append(f"{base}_bucket{lbl} {cum}")
                lbl = f'{{{inner},le="+Inf"}}' if inner \
                    else '{le="+Inf"}'
                lines.append(f"{base}_bucket{lbl} {t['count']}")
                slbl = f"{{{inner}}}" if inner else ""
                lines.append(f"{base}_sum{slbl} {t['total_s']:.9f}")
                lines.append(f"{base}_count{slbl} {t['count']}")
        for fam, members in timer_fams:
            # the max is NOT a histogram series: its own gauge family
            # (promtool rejects unknown suffixes inside a histogram)
            m = f"{prefix}_{self._san(fam)}_seconds_max"
            lines.append(self._help_line(
                m, fam + ".max", f"max observed latency of {fam} (seconds)"
            ))
            lines.append(f"# TYPE {m} gauge")
            for inner, key in members:
                lbl = f"{{{inner}}}" if inner else ""
                lines.append(f"{m}{lbl} {timers[key]['max_s']:.9f}")
        return "\n".join(lines) + "\n"


class TraceTables:
    """Columnar event tracing — the celestia-core ``pkg/trace`` analog
    (SURVEY §5.1): PER-NODE tables of schema'd rows (``BlockSummary``,
    ``RoundState``-style, and the observability plane's ``spans``) that
    e2e tooling pulls over RPC (test/e2e/testnet/node.go:52-75). Each App
    owns an instance (`app.traces`) so multi-node in-process networks
    never interleave; the module-level singleton below serves
    ad-hoc/process-wide use. Tables are bounded ring buffers; rows carry
    a monotonically increasing index so pullers can resume. Writes are
    locked: spans land from HTTP handler threads and reactor threads
    concurrently."""

    MAX_ROWS = 10_000

    def __init__(self):
        self._tables: dict[str, list[dict]] = {}   # guarded-by: _lock
        self._next_index: dict[str, int] = {}      # guarded-by: _lock
        self._lock = threading.Lock()

    def write(self, table: str, **row) -> None:
        with self._lock:
            rows = self._tables.setdefault(table, [])
            idx = self._next_index.get(table, 0)
            rows.append({"_index": idx, **row})
            self._next_index[table] = idx + 1
            if len(rows) > self.MAX_ROWS:
                del rows[: len(rows) - self.MAX_ROWS]

    def read(self, table: str, since_index: int = 0,
             limit: int = 1000) -> list[dict]:
        """Rows with _index >= since_index (at most `limit`). _index is
        monotonic within a table, so the resume point is found with
        bisect + slice — O(log n + limit), not the former O(n) full-table
        scan e2e pullers paid on every poll tick."""
        with self._lock:
            rows = self._tables.get(table, [])
            start = bisect.bisect_left(
                rows, since_index, key=lambda r: r["_index"]
            )
            return [dict(r) for r in rows[start:start + limit]]

    def tables(self) -> list[str]:
        with self._lock:
            return sorted(self._tables)

    def reset(self) -> None:
        with self._lock:
            self._tables.clear()
            self._next_index.clear()


_global = Registry()
_traces = TraceTables()

incr = _global.incr
gauge = _global.gauge
observe = _global.observe
measure_since = _global.measure_since
quantiles = _global.quantiles
snapshot = _global.snapshot
export = _global.export
prometheus = _global.prometheus
reset = _global.reset
set_help = _global.set_help
register_collector = _global.register_collector
trace = _traces.write
read_trace = _traces.read
trace_tables = _traces.tables
reset_traces = _traces.reset
