"""Host-side RFC-6962 binary Merkle tree with inclusion proofs.

Reference parity: go-square/merkle (CometBFT merkle) — used for the data root
over axis roots (pkg/da/data_availability_header.go:92-108), share commitments
over subtree roots (x/blob/types/payforblob.go:48-77) and row proofs
(pkg/proof/row_proof.go). Semantics per specs/src/specs/data_structures.md:
leaf `SHA256(0x00 || d)`, inner `SHA256(0x01 || l || r)`, empty `SHA256("")`,
split point = largest power of two strictly less than n.
"""

from __future__ import annotations

import dataclasses
import hashlib


def _sha(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha(b"\x00" + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha(b"\x01" + left + right)


def split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    k = 1
    while k * 2 < n:
        k *= 2
    return k


def hash_from_leaves(leaves: list[bytes]) -> bytes:
    n = len(leaves)
    if n == 0:
        return _sha(b"")
    if n == 1:
        return leaf_hash(leaves[0])
    k = split_point(n)
    return inner_hash(hash_from_leaves(leaves[:k]), hash_from_leaves(leaves[k:]))


@dataclasses.dataclass
class Proof:
    """CometBFT-style Merkle proof: sibling hashes from the leaf upward."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes]

    def root(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if not (0 <= self.index < self.total):
            return False
        return self.leaf_hash == leaf_hash(leaf) and self.root() == root


def _compute_from_aunts(index: int, total: int, lh: bytes, aunts: list[bytes]) -> bytes:
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single-leaf tree")
        return lh
    if not aunts:
        raise ValueError("proof too short")
    k = split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, lh, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, lh, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs_from_leaves(leaves: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root plus one inclusion proof per leaf."""
    n = len(leaves)
    proofs = [Proof(total=n, index=i, leaf_hash=leaf_hash(leaves[i]), aunts=[])
              for i in range(n)]

    def build(lo: int, hi: int) -> bytes:
        if hi - lo == 1:
            return proofs[lo].leaf_hash
        k = split_point(hi - lo)
        left = build(lo, lo + k)
        right = build(lo + k, hi)
        for i in range(lo, lo + k):
            proofs[i].aunts.append(right)
        for i in range(lo + k, hi):
            proofs[i].aunts.append(left)
        return inner_hash(left, right)

    if n == 0:
        return _sha(b""), []
    return build(0, n), proofs
