"""ctypes binding for the native storage engine (native/chaindb.cc).

The engine is a segmented append-only record store — (stream, height) ->
payload with CRC framing, torn-tail recovery, rollback/prune tombstones and
dead-segment GC. chain/storage.py layers the commit semantics (delta
chains, snapshot cadence, prune windows) on top; see that module for the
reference parity notes (tm-db/IAVL + celestia-core block store,
app/app.go:427-435).

``load()`` builds the .so via the native Makefile on first use (cheap,
dependency-tracked) and raises RuntimeError when no toolchain is available
— callers fall back to the pure-Python file backend.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(REPO, "native")
LIB = os.path.join(NATIVE_DIR, "libchaindb.so")

_lib = None


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    # ALWAYS run make (a no-op when fresh): its dependency tracking is what
    # keeps a stale .so from silently serving an outdated engine after
    # chaindb.cc changes. Only a missing .so makes a failed build fatal.
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR, "libchaindb.so"],
            check=True, capture_output=True, timeout=120,
        )
    except Exception as e:
        if not os.path.exists(LIB):
            raise RuntimeError(f"cannot build libchaindb.so: {e}")
    lib = ctypes.CDLL(LIB)
    lib.cdb_open.restype = ctypes.c_void_p
    lib.cdb_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
                             ctypes.c_int]
    lib.cdb_put.restype = ctypes.c_int
    lib.cdb_put.argtypes = [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
                            ctypes.c_char_p, ctypes.c_uint32]
    lib.cdb_tomb_at.restype = ctypes.c_int
    lib.cdb_tomb_at.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                ctypes.c_uint64]
    lib.cdb_tomb_above.restype = ctypes.c_int
    lib.cdb_tomb_above.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.cdb_sync.restype = ctypes.c_int
    lib.cdb_sync.argtypes = [ctypes.c_void_p]
    lib.cdb_get_len.restype = ctypes.c_int64
    lib.cdb_get_len.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                ctypes.c_uint64]
    lib.cdb_get.restype = ctypes.c_int
    lib.cdb_get.argtypes = [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
                            ctypes.c_char_p, ctypes.c_uint32]
    lib.cdb_latest.restype = ctypes.c_int64
    lib.cdb_latest.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.cdb_count.restype = ctypes.c_uint64
    lib.cdb_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.cdb_heights.restype = ctypes.c_int64
    lib.cdb_heights.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                ctypes.POINTER(ctypes.c_uint64),
                                ctypes.c_uint64]
    lib.cdb_segments.restype = ctypes.c_uint64
    lib.cdb_segments.argtypes = [ctypes.c_void_p]
    lib.cdb_close.restype = None
    lib.cdb_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    try:
        load()
        return True
    except (RuntimeError, OSError):
        # RuntimeError: no toolchain. OSError: a .so exists but cannot load
        # (wrong arch, truncated build) — fall back to the file engine
        # rather than wedging every ChainDB open.
        return False


class NativeLog:
    """One open chaindb directory. Thin, typed veneer over the C ABI."""

    def __init__(self, directory: str, *, read_only: bool = False):
        lib = load()
        err = ctypes.create_string_buffer(256)
        self._h = lib.cdb_open(directory.encode(), 1 if read_only else 0,
                               err, len(err))
        if not self._h:
            raise IOError(f"chaindb open failed: {err.value.decode()}")
        self._lib = lib

    def put(self, stream: int, height: int, payload: bytes) -> None:
        if self._lib.cdb_put(self._h, stream, height, payload,
                             len(payload)) != 0:
            raise IOError("chaindb put failed")

    def get(self, stream: int, height: int) -> bytes | None:
        n = self._lib.cdb_get_len(self._h, stream, height)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(max(int(n), 1))
        rc = self._lib.cdb_get(self._h, stream, height, buf, int(n))
        if rc < 0:
            raise IOError(f"chaindb get failed (rc={rc})")
        return buf.raw[:rc]

    def tomb_at(self, stream: int, height: int) -> None:
        if self._lib.cdb_tomb_at(self._h, stream, height) != 0:
            raise IOError("chaindb tomb_at failed")

    def tomb_above(self, height: int) -> None:
        if self._lib.cdb_tomb_above(self._h, height) != 0:
            raise IOError("chaindb tomb_above failed")

    def sync(self) -> None:
        if self._lib.cdb_sync(self._h) != 0:
            raise IOError("chaindb sync failed")

    def latest(self, stream: int) -> int | None:
        h = self._lib.cdb_latest(self._h, stream)
        return None if h < 0 else int(h)

    def heights(self, stream: int) -> list[int]:
        n = int(self._lib.cdb_count(self._h, stream))
        if n == 0:
            return []
        arr = (ctypes.c_uint64 * n)()
        got = self._lib.cdb_heights(self._h, stream, arr, n)
        return sorted(int(x) for x in arr[: abs(int(got))])

    def segments(self) -> int:
        return int(self._lib.cdb_segments(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.cdb_close(self._h)
            self._h = None

    def __del__(self):  # best-effort: tests open/close many
        try:
            self.close()
        except Exception:  # lint: disable=except-swallow
            # logging (or any import) inside __del__ at interpreter
            # shutdown can itself raise; silence is the only safe option
            pass
