"""Host-side Namespaced Merkle Tree with namespace range proofs.

Reference parity: celestiaorg/nmt as configured by pkg/wrapper/nmt_wrapper.go
(sha256, 29-byte namespaces, IgnoreMaxNamespace=true). Node semantics per
specs/src/specs/data_structures.md:236-263 — identical to ops/nmt.py, which is
cross-checked against this implementation in tests. Split point for n leaves
matches RFC-6962 (largest power of two < n).

Used for: proof generation/verification on arbitrary ranges (pkg/proof
equivalents), the namespace-ordering validity check the nmt hasher enforces,
and as the golden oracle for the device kernel.
"""

from __future__ import annotations

import dataclasses
import hashlib

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod

NS = appconsts.NAMESPACE_SIZE
PARITY = ns_mod.PARITY_NS_RAW

Node = tuple[bytes, bytes, bytes]  # (min_ns, max_ns, digest)


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_node(ns: bytes, data: bytes) -> Node:
    assert len(ns) == NS
    return (ns, ns, _sha(b"\x00" + ns + data))


def inner_node(left: Node, right: Node) -> Node:
    n_min = min(left[0], right[0])
    if left[0] == PARITY:
        n_max = PARITY
    elif right[0] == PARITY:
        n_max = left[1]  # IgnoreMaxNamespace: parity children don't raise max
    else:
        n_max = max(left[1], right[1])
    v = _sha(b"\x01" + left[0] + left[1] + left[2] + right[0] + right[1] + right[2])
    return (n_min, n_max, v)


def serialize(node: Node) -> bytes:
    return node[0] + node[1] + node[2]  # 90 bytes


def deserialize(raw: bytes) -> Node:
    assert len(raw) == appconsts.NMT_ROOT_SIZE
    return (raw[:NS], raw[NS : 2 * NS], raw[2 * NS :])


def split_point(n: int) -> int:
    k = 1
    while k * 2 < n:
        k *= 2
    return k


class NmtTree:
    """An NMT over (namespace, data) leaves pushed in namespace order."""

    def __init__(self) -> None:
        self.leaves: list[tuple[bytes, bytes]] = []

    def push(self, ns: bytes, data: bytes) -> None:
        if self.leaves and ns < self.leaves[-1][0]:
            raise ValueError(
                f"namespace out of order: {ns.hex()} < {self.leaves[-1][0].hex()}"
            )
        self.leaves.append((ns, data))

    def _subtree(self, start: int, end: int) -> Node:
        if end - start == 1:
            return leaf_node(*self.leaves[start])
        k = split_point(end - start)
        return inner_node(self._subtree(start, start + k), self._subtree(start + k, end))

    def root(self) -> Node:
        if not self.leaves:
            empty = hashlib.sha256(b"").digest()
            zero = b"\x00" * NS
            return (zero, zero, empty)
        return self._subtree(0, len(self.leaves))

    # -- range proofs (celestiaorg/nmt ProveRange semantics) ---------------

    def prove_range(self, p_start: int, p_end: int) -> "NmtRangeProof":
        """Prove leaves [p_start, p_end); nodes are the maximal out-of-range
        subtree roots in left-to-right order."""
        if not (0 <= p_start < p_end <= len(self.leaves)):
            raise ValueError(f"invalid range [{p_start}, {p_end})")
        nodes: list[Node] = []

        def walk(start: int, end: int) -> None:
            if end <= p_start or start >= p_end:
                nodes.append(self._subtree(start, end))
                return
            if end - start == 1:
                return  # in-range leaf: verifier recomputes it
            k = split_point(end - start)
            walk(start, start + k)
            walk(start + k, end)

        walk(0, len(self.leaves))
        return NmtRangeProof(
            start=p_start,
            end=p_end,
            total=len(self.leaves),
            nodes=[serialize(n) for n in nodes],
        )


@dataclasses.dataclass
class NmtRangeProof:
    """Range proof over an NMT: out-of-range subtree roots, left to right."""

    start: int
    end: int
    total: int
    nodes: list[bytes]

    def verify(self, root: bytes, leaves: list[tuple[bytes, bytes]]) -> bool:
        """Check `leaves` = [(ns, data)] occupy [start, end) under `root` (90B)."""
        if len(leaves) != self.end - self.start or self.total < self.end:
            return False
        node_iter = iter(self.nodes)
        leaf_iter = iter(leaves)

        def rebuild(start: int, end: int) -> Node:
            if end <= self.start or start >= self.end:
                return deserialize(next(node_iter))
            if end - start == 1:
                return leaf_node(*next(leaf_iter))
            k = split_point(end - start)
            left = rebuild(start, start + k)
            right = rebuild(start + k, end)
            return inner_node(left, right)

        try:
            got = rebuild(0, self.total)
            if next(node_iter, None) is not None:
                return False
        except (StopIteration, AssertionError):
            return False
        return serialize(got) == root
