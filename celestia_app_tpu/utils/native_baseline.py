"""Build + invoke the native C++ baseline pipeline (native/baseline_pipeline.cc).

Shared by bench.py (baseline measurement) and tests (cross-validation of the
independent C++ reimplementation against the Python pipelines)."""

from __future__ import annotations

import json
import os
import subprocess
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(REPO, "native")
BINARY = os.path.join(NATIVE_DIR, "baseline_pipeline")


def build(timeout: int = 180) -> bool:
    """(Re)build via make — the Makefile's dependency tracking means a stale
    binary is rebuilt whenever the source changed. False if no toolchain."""
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR], check=True, capture_output=True,
            timeout=timeout,
        )
        return os.path.exists(BINARY)
    except Exception as e:
        # no toolchain in this container: the caller falls back to the
        # Python baseline; say so once at debug level instead of nothing
        from celestia_app_tpu import obs

        _log = obs.get_logger("utils.native")
        _log.debug("native baseline build unavailable", err=e)
        return False


def run(ods: np.ndarray, reps: int = 3, timeout: int = 600) -> dict:
    """Run the pipeline on a (k, k, 512) ODS: {"cpu_ms": ..., "data_root": hex}."""
    k = ods.shape[0]
    assert ods.shape == (k, k, 512) and ods.dtype == np.uint8
    if not build():
        raise RuntimeError("native baseline toolchain unavailable")
    with tempfile.NamedTemporaryFile(delete=False, suffix=".ods") as f:
        f.write(ods.tobytes())
        path = f.name
    try:
        out = subprocess.run(
            [BINARY, path, str(k), str(reps)],
            check=True, capture_output=True, text=True, timeout=timeout,
        )
    finally:
        os.unlink(path)
    return json.loads(out.stdout)
