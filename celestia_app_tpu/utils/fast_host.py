"""Fast CPU implementation of the DA pipeline (numpy BLAS + hashlib).

This is the *baseline to beat* for bench.py: the strongest CPU path we can
field without the reference's Go toolchain — the same role rsmt2d's SIMD
LeoRS codec + hardware SHA-256 play in the reference
(pkg/da/data_availability_header.go:65-108). It is also a fast oracle for
tests (bit-identical to utils/refimpl, which is pure-Python-slow).

- RS extension: the GF(256) generator as an (8k, 8k) GF(2) bit matrix,
  applied as one float32 BLAS matmul per axis pass (exact: dot products of
  0/1 vectors of length ≤ 2048 are well inside f32's integer range).
- NMT/Merkle hashing: level-synchronous; preimages for a whole tree level
  are assembled as one contiguous array and hashed with hashlib (OpenSSL,
  SHA-NI where available) over memoryview slices.
"""

from __future__ import annotations

import hashlib

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.ops import leopard
from celestia_app_tpu.utils import merkle_host

NS = appconsts.NAMESPACE_SIZE
SHARE = appconsts.SHARE_SIZE
PARITY = np.frombuffer(ns_mod.PARITY_NS_RAW, dtype=np.uint8)


def _bits(x: np.ndarray) -> np.ndarray:
    """(..., n, S) u8 -> (..., 8n, S) f32 bits, LSB-first (matches ops/rs.py)."""
    n, s = x.shape[-2], x.shape[-1]
    b = np.unpackbits(x[..., None], axis=-1, bitorder="little")  # (..., n, S, 8)
    return np.swapaxes(b, -1, -2).reshape(*x.shape[:-2], 8 * n, s).astype(np.float32)


def _bytes(b: np.ndarray) -> np.ndarray:
    """Inverse of _bits for integer-valued bit arrays."""
    n, s = b.shape[-2] // 8, b.shape[-1]
    u = b.astype(np.uint8).reshape(*b.shape[:-2], n, 8, s)
    return np.packbits(np.swapaxes(u, -1, -2), axis=-1, bitorder="little")[..., 0]


def extend_square_fast(ods: np.ndarray) -> np.ndarray:
    """(k, k, 512) -> (2k, 2k, 512); same codewords as ops/rs.extend_square_fn."""
    k = ods.shape[0]
    if leopard.uses_gf16(k):
        raise ValueError(
            "fast_host's BLAS formulation covers the GF(2^8) range (k <= 128);"
            " use ops.rs.extend_square_np for wider squares"
        )
    bm = leopard.bit_matrix(k).astype(np.float32)  # (8k, 8k)

    def mix(rows: np.ndarray) -> np.ndarray:
        # rows: (m, k, S) -> parity (m, k, S); one (8k,8k)@(8k, m*S) matmul.
        m = rows.shape[0]
        rb = _bits(rows)  # (m, 8k, S)
        flat = np.moveaxis(rb, 1, 0).reshape(8 * k, m * SHARE)
        par = bm @ flat
        par = np.moveaxis(par.reshape(8 * k, m, SHARE), 0, 1)
        return _bytes(par.astype(np.int64) & 1)

    q1 = mix(ods)  # row pass
    q2 = np.swapaxes(mix(np.swapaxes(ods, 0, 1)), 0, 1)  # column pass
    q3 = mix(q2)  # Q3 = row-extend Q2
    top = np.concatenate([ods, q1], axis=1)
    bottom = np.concatenate([q2, q3], axis=1)
    return np.concatenate([top, bottom], axis=0)


def _sha_many(preimages: np.ndarray) -> np.ndarray:
    """(N, L) u8 -> (N, 32) u8, hashlib over contiguous memoryview slices."""
    n, l = preimages.shape
    buf = memoryview(np.ascontiguousarray(preimages).reshape(-1).data)
    out = np.empty((n, 32), dtype=np.uint8)
    sha = hashlib.sha256
    for i in range(n):
        out[i] = np.frombuffer(sha(buf[i * l : (i + 1) * l]).digest(), np.uint8)
    return out


def _ns_lt(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Lexicographic a < b over (..., 29) u8 arrays."""
    lt = np.zeros(a.shape[:-1], dtype=bool)
    eq = np.ones(a.shape[:-1], dtype=bool)
    for i in range(NS):
        lt |= eq & (a[..., i] < b[..., i])
        eq &= a[..., i] == b[..., i]
    return lt


def nmt_levels_fast(
    leaf_ns: np.ndarray, leaf_data: np.ndarray
) -> list[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """All NMT tree levels, leaves first: the host twin of ops/nmt.py
    nmt_levels (same (mins, maxs, vs) shape per level), feeding proof
    generation on validators whose engine never touches jax."""
    t, l, d = leaf_data.shape
    pre = np.concatenate(
        [
            np.zeros((t * l, 1), np.uint8),
            leaf_ns.reshape(t * l, NS),
            leaf_data.reshape(t * l, d),
        ],
        axis=1,
    )
    vs = _sha_many(pre).reshape(t, l, 32)
    mins = leaf_ns.copy()
    maxs = leaf_ns.copy()
    levels = [(mins, maxs, vs)]
    while vs.shape[1] > 1:
        lm, rm = mins[:, 0::2], mins[:, 1::2]
        lx, rx = maxs[:, 0::2], maxs[:, 1::2]
        lv, rv = vs[:, 0::2], vs[:, 1::2]
        half = lv.shape[1]
        pre = np.concatenate(
            [
                np.ones((t * half, 1), np.uint8),
                lm.reshape(-1, NS), lx.reshape(-1, NS), lv.reshape(-1, 32),
                rm.reshape(-1, NS), rx.reshape(-1, NS), rv.reshape(-1, 32),
            ],
            axis=1,
        )
        vs = _sha_many(pre).reshape(t, half, 32)
        lt = _ns_lt(lm, rm)[..., None]
        mins = np.where(lt, lm, rm)
        l_par = np.all(lm == PARITY, axis=-1)[..., None]
        r_par = np.all(rm == PARITY, axis=-1)[..., None]
        mx = np.where(_ns_lt(lx, rx)[..., None], rx, lx)
        maxs = np.where(l_par, PARITY, np.where(r_par, lx, mx))
        levels.append((mins, maxs, vs))
    return levels


def nmt_roots_fast(leaf_ns: np.ndarray, leaf_data: np.ndarray) -> np.ndarray:
    """Batched NMT roots (T, L, 29)+(T, L, D) -> (T, 90); nmt semantics as in
    ops/nmt.py (IgnoreMaxNamespace=true, parity propagation)."""
    mins, maxs, vs = nmt_levels_fast(leaf_ns, leaf_data)[-1]
    return np.concatenate([mins[:, 0], maxs[:, 0], vs[:, 0]], axis=1)


def _axis_leaf_ns(axis_major: np.ndarray, k: int) -> np.ndarray:
    """(2k, 2k, SHARE) axis-major slab -> (2k, 2k, 29) leaf namespaces."""
    idx = np.arange(2 * k)
    in_q0 = (idx[:, None] < k) & (idx[None, :] < k)
    return np.where(in_q0[..., None], axis_major[:, :, :NS], PARITY)


def axis_roots_fast(eds: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """EDS -> (row_roots (2k, 90), col_roots (2k, 90))."""
    k = eds.shape[0] // 2
    rows = nmt_roots_fast(_axis_leaf_ns(eds, k), eds)
    eds_t = np.swapaxes(eds, 0, 1)
    cols = nmt_roots_fast(_axis_leaf_ns(eds_t, k), eds_t)
    return rows, cols


def pipeline_fast(ods: np.ndarray):
    """(k, k, 512) -> (eds, row_roots, col_roots, data_root) on CPU."""
    eds = extend_square_fast(ods)
    rows, cols = axis_roots_fast(eds)
    leaves = [bytes(r) for r in rows] + [bytes(c) for c in cols]
    data_root = merkle_host.hash_from_leaves(leaves)
    return eds, rows, cols, np.frombuffer(data_root, dtype=np.uint8)
