"""Host-only (numpy + hashlib) implementation of the full DA pipeline.

Mirrors da/eds.py step for step without importing jax: 2D RS extension via
GF(256) byte-domain matmuls, per-axis NMT roots via utils.nmt_host, data root
via utils.merkle_host. Three uses:

  1. golden oracle for the device pipeline (tests assert bit-equality),
  2. fallback execution path when no accelerator is attached,
  3. proof generation inputs for host tooling.

Slow by design (pure Python hashing) — the device path is the product.
"""

from __future__ import annotations

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.ops import leopard
from celestia_app_tpu.utils import merkle_host, nmt_host

NS = appconsts.NAMESPACE_SIZE


def extend_square_host(ods: np.ndarray) -> np.ndarray:
    """(k, k, 512) -> (2k, 2k, 512), identical to ops/rs.py extension."""
    k = ods.shape[0]
    if k > 128:
        raise ValueError(
            "refimpl covers the GF(2^8) range (k <= 128, all protocol-legal "
            "squares); use ops.rs.extend_square_np for benchmark-scale squares"
        )
    e = leopard.encode_matrix(k)
    q1 = np.stack([leopard.matmul(e, ods[r]) for r in range(k)])
    q2 = np.stack([leopard.matmul(e, ods[:, c, :]) for c in range(k)], axis=1)
    q3 = np.stack([leopard.matmul(e, q2[r]) for r in range(k)])
    top = np.concatenate([ods, q1], axis=1)
    bottom = np.concatenate([q2, q3], axis=1)
    return np.concatenate([top, bottom], axis=0)


def axis_roots_host(eds: np.ndarray) -> tuple[list[bytes], list[bytes]]:
    """Row and column NMT roots (90-byte serialized) of an extended square."""
    two_k = eds.shape[0]
    k = two_k // 2

    def tree_root(axis_get, axis_index) -> bytes:
        tree = nmt_host.NmtTree()
        for j in range(two_k):
            share = axis_get(j).tobytes()
            in_q0 = axis_index < k and j < k
            ns = share[:NS] if in_q0 else ns_mod.PARITY_NS_RAW
            tree.push(ns, share)
        return nmt_host.serialize(tree.root())

    rows = [tree_root(lambda j, r=r: eds[r, j], r) for r in range(two_k)]
    cols = [tree_root(lambda j, c=c: eds[j, c], c) for c in range(two_k)]
    return rows, cols


def pipeline_host(ods: np.ndarray):
    """Full host pipeline: ODS -> (eds, row_roots, col_roots, data_root)."""
    eds = extend_square_host(ods)
    rows, cols = axis_roots_host(eds)
    data_root = merkle_host.hash_from_leaves(rows + cols)
    return eds, rows, cols, data_root
