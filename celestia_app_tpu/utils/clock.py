"""THE time source abstraction: every library-side sleep/backoff/TTL clock.

The scenario plane (docs/DESIGN.md "The scenario plane") needs to run
hours of chain time in seconds, deterministically: tens of validators and
hundreds of DASer light nodes in one process, same seed ⇒ byte-identical
event trace. That is impossible while the reactor's poll loops, the
transport's retry backoff and breaker timers, the DASer's sweep/retry
backoffs, and the mempool's wall-clock TTL stamps each read ``time.time``
/ ``time.monotonic`` / ``time.sleep`` directly — so those components now
take an injected :class:`Clock`.

Two implementations:

- :class:`SystemClock` (the module singleton ``SYSTEM``) is the default
  everywhere: it delegates straight to the ``time`` module, so production
  behavior is unchanged (pinned by the pre-existing reactor/DASer/
  transport test suites, which never pass a clock).
- :class:`VirtualClock` is the simulation time source: ``now()`` returns
  simulated seconds, ``sleep()`` ADVANCES simulated time instead of
  blocking, and ``wait()`` resolves an event wait against simulated time.
  The sim scheduler (celestia_app_tpu/sim/scheduler.py) owns one and
  steps it from a seeded event heap.

The one behavioral improvement to the default path: ``wait(event, t)`` is
the *interruptible* sleep — ``SystemClock.wait`` is ``event.wait(t)`` —
so loops that used to hard-sleep (``time.sleep(poll)``) and made
``stop()`` block up to a full poll interval now wake the moment their
stop event is set.

Determinism contract (enforced by the analysis plane: this module and
``sim/`` ride the det-wallclock/det-rng scopes in analyze.toml): the ONLY
raw wall-clock reads live in ``SystemClock``, below, each carrying an
explicit pragma — any other ``time.time()``/``random`` reachable from a
scenario run is a tree error.
"""

from __future__ import annotations

import threading
import time as _time


class Clock:
    """Abstract time source. ``now()`` is wall-clock-shaped (unix
    seconds: block timestamps, TTL stamps); ``monotonic()`` is
    deadline-shaped (never goes backwards; breaker timers, phase
    timeouts). A VirtualClock serves both from the one simulated
    timeline."""

    def now(self) -> float:
        raise NotImplementedError

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait(self, event: threading.Event, timeout: float) -> bool:
        """Interruptible sleep: return as soon as `event` is set (True)
        or `timeout` elapses (the event's state). THE primitive every
        stoppable loop must use instead of a bare sleep."""
        raise NotImplementedError


class SystemClock(Clock):
    """Real time — the production default, pinned to the ``time``
    module's behavior exactly."""

    def now(self) -> float:
        return _time.time()  # lint: disable=det-wallclock

    def monotonic(self) -> float:
        return _time.monotonic()  # lint: disable=det-wallclock

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)

    def wait(self, event: threading.Event, timeout: float) -> bool:
        return event.wait(timeout)


#: The process default. Components accept ``clock=None`` and fall back to
#: this, so existing call sites (and production processes) are unchanged.
SYSTEM = SystemClock()


class VirtualClock(Clock):
    """Simulated time. ``sleep(dt)`` advances the timeline by ``dt``
    immediately — inside a simulation event, backoffs and retry delays
    cost virtual seconds, not real ones — and ``wait(event, t)`` resolves
    instantly against simulated time. The scheduler additionally calls
    :meth:`advance_to` when it pops each event, so time never runs
    backwards (events scheduled in the past run "late" at the current
    simulated instant, exactly like an overloaded real node).

    ``now()`` is ``epoch + elapsed``: wall-clock-shaped consumers (TTL
    stamps) see plausible unix times while ``monotonic()`` counts
    simulated seconds from zero.
    """

    def __init__(self, epoch: float = 1_700_000_000.0):
        self.epoch = epoch
        self._t = 0.0  # guarded-by: _lock
        self._lock = threading.Lock()

    def now(self) -> float:
        return self.epoch + self.monotonic()

    def monotonic(self) -> float:
        with self._lock:
            return self._t

    def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            return
        with self._lock:
            self._t += seconds

    def wait(self, event: threading.Event, timeout: float) -> bool:
        if event.is_set():
            return True
        self.sleep(timeout)
        return event.is_set()

    def advance_to(self, t: float) -> None:
        """Move simulated time forward to `t` (never backwards)."""
        with self._lock:
            if t > self._t:
                self._t = t
