"""celestia_app_tpu — a TPU-native data-availability framework.

A from-scratch rebuild of the capabilities of celestia-app (the Celestia
DA network's state machine) designed TPU-first:

- The compute core — 2D Reed-Solomon extension of the data square, namespaced
  Merkle tree (NMT) hashing, share commitments and inclusion proofs — runs as
  batched GF(256) bit-matrix matmuls (MXU) and vectorized SHA-256 (VPU/Pallas)
  under ``jax.jit``, with static power-of-two shape buckets.
- The protocol plane — deterministic square layout, PrepareProposal /
  ProcessProposal / CheckTx semantics, the PayForBlobs state machine, gas and
  fee rules — runs host-side in deterministic Python.
- Multi-chip scaling shards the extended square per-row over a
  ``jax.sharding.Mesh`` with XLA collectives (all-to-all transpose between the
  row and column passes, all-gather of axis roots).

Layout:
  appconsts   protocol constants (immutable / versioned / governed layers)
  ops         device kernels: GF(256) RS codec, SHA-256, NMT reduction, Merkle
  da          data-availability pipeline: namespaces, shares, square layout,
              EDS extension, DA header, commitments, proofs
  chain       ABCI-shaped state machine: app, ante, modules (blob/bank/auth/
              mint/signal/minfee), tx codec
  parallel    device-mesh sharded execution of the DA pipeline
  client      tx signer / client
  utils       host-side reference implementations and helpers
"""

__version__ = "0.1.0"

# Runtime lock-order detection (the analysis plane's dynamic half):
# CELESTIA_RACE=1 wraps threading.Lock/RLock before any submodule
# creates one, so chaos/stress runs — including their subprocess
# nodes, which inherit the env — record lock acquisition order and
# surface ABBA inversions. CELESTIA_LOCKPROF=1 installs the SAME
# wrapper but for contention profiling (per-creation-site lock.wait
# histograms + hold gauges in /metrics) — order bookkeeping stays off
# unless CELESTIA_RACE asks for it. See tools/analyze/racecheck.py.
import os as _os

_race = _os.environ.get("CELESTIA_RACE", "").strip() == "1"
_lockprof = _os.environ.get("CELESTIA_LOCKPROF", "").strip() == "1"
if _race or _lockprof:
    from celestia_app_tpu.tools.analyze import racecheck as _racecheck

    _racecheck.install()
    _racecheck.set_order_tracking(_race)
    _racecheck.set_profiling(_lockprof)
