"""The observability plane: spans, structured logging, JAX profiling.

Five sub-modules, one import surface (``from celestia_app_tpu import
obs``):

- ``obs.spans`` — context-manager span API over the columnar TraceTables
  with DETERMINISTIC per-height trace ids (``trace_id_for(chain_id, h)``)
  so proposer, followers, and DAS light nodes correlate without clock
  sync; HTTP propagation via the ``X-Celestia-Trace`` header.
- ``obs.log`` — the leveled structured stderr logger library modules use
  instead of calling ``print`` (lint-enforced).
- ``obs.jax_profile`` — the compile-vs-execute split for the jitted
  pipelines, device gauges, and the /debug/profile capture worker.
- ``obs.xfer`` — the host↔device transfer ledger: every device_put /
  device_get in the tree routes through ``xfer.to_device``/``to_host``
  so bytes, calls, and latency are attributed per call-site label, and
  ``xfer.no_implicit_transfers()`` turns stray implicit copies into
  hard errors for tier-1 residency pins.
- ``obs.gil`` — GIL-pressure oversleep samplers per HTTP service and
  the ``process.peak_rss_bytes`` /metrics gauge (collector registers on
  import of this package).

Histograms/labels/Prometheus exposition live in utils/telemetry.py (the
metric registry predates this package and everything already imports it).
docs/DESIGN.md "The observability plane" has the span model; FORMATS §10
the wire formats.
"""

from celestia_app_tpu.obs import gil  # noqa: F401  (registers the peak-RSS collector)
from celestia_app_tpu.obs.log import get_logger  # noqa: F401
from celestia_app_tpu.obs.spans import (  # noqa: F401
    NOOP,
    SPAN_TABLE,
    TRACE_HEADER,
    Span,
    begin_request,
    capture,
    enabled,
    end_request,
    http_header,
    resume,
    route_profile,
    route_trace,
    serve_metrics,
    set_enabled,
    span,
    trace_id_for,
)
from celestia_app_tpu.obs.xfer import (  # noqa: F401
    ImplicitTransferError,
    no_implicit_transfers,
    to_device,
    to_host,
)
