"""Cross-node span tracing over the columnar trace plane.

The reference measures hot paths per process (telemetry.MeasureSince) and
pulls per-node columnar tables (celestia-core pkg/trace) — but neither can
answer "where did block H spend its 400 ms between proposer and light
node?".  Spans close that gap with three deliberate choices:

- **Deterministic per-height trace ids.** The trace id for block H is
  ``sha256(chain_id + "/" + H)[:16]`` (`trace_id_for`), so the proposer,
  every follower, and every DAS light node stamp their spans with the SAME
  id without any clock sync, id exchange, or coordinator.  A merge tool
  (tools/timeline.py) only needs to group by trace_id.
- **Rows, not a protocol.** A finished span is ONE row in the existing
  ``TraceTables`` ("spans" table): trace_id / span_id / parent_id / name /
  start_unix / dur_ms / attrs.  It rides the same bounded ring buffers,
  the same ``/trace/spans`` pull route, and the same per-App isolation the
  BlockSummary rows already have.
- **Context propagation that survives sockets and threads.**  Within a
  thread, spans nest through a thread-local stack.  Across a peer call,
  the hardened transport (net/transport.py) injects an
  ``X-Celestia-Trace: <trace_id>:<span_id>`` header and the HTTP services
  install it as the *incoming* context (`begin_request`), which the next
  root span on that handler thread adopts as its remote parent.  Across
  an in-process thread hop (the reactor's sender queues), `capture()` /
  `resume()` carry the context explicitly.

Recording is gated by ``CELESTIA_OBS`` (off/0/false disables; see
`enabled`): a disabled span is a shared no-op object, so the hot path
pays one dict lookup and one truthiness check. ``bench.py --obs``
measures exactly this on/off delta.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import threading
import time

from celestia_app_tpu.utils import telemetry

# the wire header every peer call carries while a span is active
TRACE_HEADER = "X-Celestia-Trace"

SPAN_TABLE = "spans"

_tls = threading.local()
# span ids: per-process random prefix + counter — unique across the
# processes of a devnet without coordination, and cheap to mint
_SPAN_PREFIX = os.urandom(3).hex()
_counter = itertools.count(1)

_enabled: bool | None = None


def enabled() -> bool:
    """Span recording gate (CELESTIA_OBS=off|0|false disables). Resolved
    once and cached; tests/benches flip it with `set_enabled`."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("CELESTIA_OBS", "on").strip().lower() \
            not in ("off", "0", "false", "no")
    return _enabled


def set_enabled(value: bool | None) -> None:
    """Override the gate (None = re-read CELESTIA_OBS on next check)."""
    global _enabled
    _enabled = None if value is None else bool(value)


def trace_id_for(chain_id: str, height: int) -> str:
    """THE deterministic per-height trace id: every process that knows
    (chain_id, height) — proposer, follower, light node — derives the
    same id, so cross-node correlation needs no clock sync or handshake."""
    return hashlib.sha256(
        f"{chain_id}/{int(height)}".encode()
    ).hexdigest()[:16]


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    """One in-flight span; use as a context manager. `set(**attrs)` adds
    attributes before exit; the row is written on __exit__."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "sink",
                 "attrs", "start_unix", "_t0")

    def __init__(self, name: str, trace_id: str, parent_id: str | None,
                 sink, attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{_SPAN_PREFIX}{next(_counter):06x}"
        self.parent_id = parent_id
        self.sink = sink
        self.attrs = attrs

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        _stack().append(self)
        self.start_unix = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:  # unbalanced exit (generator teardown): heal
            st.remove(self)
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        try:
            self.sink.write(
                SPAN_TABLE,
                trace_id=self.trace_id,
                span_id=self.span_id,
                parent_id=self.parent_id,
                name=self.name,
                start_unix=round(self.start_unix, 6),
                dur_ms=round(dur_ms, 3),
                **self.attrs,
            )
        except Exception:
            # must never take down the instrumented path — but count it:
            # a span plane that silently drops rows looks "quiet", not ok
            telemetry.incr("obs.span_errors")


class _NoopSpan:
    """Shared disabled span: context manager + set() that do nothing."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def set(self, **attrs) -> "_NoopSpan":
        return self

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


NOOP = _NoopSpan()


def span(name: str, *, traces=None, trace_id: str | None = None, **attrs):
    """Open a span. Parentage resolution, in order:

    1. an active span on this thread (nesting) — its trace id wins unless
       an explicit `trace_id` is given (same-id case in practice, since
       both derive from (chain_id, height));
    2. the incoming HTTP context installed by `begin_request` — adopted
       as a REMOTE parent when its trace id matches (or no explicit id
       was given), which is what links a served request into the caller's
       trace;
    3. a fresh root (explicit or random trace id).

    `traces` pins the sink (per-App TraceTables); otherwise the parent's
    sink, else the process-global tables."""
    if not enabled():
        return NOOP
    st = _stack()
    parent = st[-1] if st else None
    incoming = getattr(_tls, "incoming", None) if parent is None else None
    if parent is not None:
        sink = traces if traces is not None else parent.sink
        if trace_id is not None and trace_id != parent.trace_id:
            # explicit DIFFERENT trace (blocksync pulling another height
            # under a reactor.round span): a cross-trace parent edge
            # would orphan this span in per-trace merges — root it in
            # its own trace instead
            tid, pid = trace_id, None
        else:
            tid = parent.trace_id
            pid = parent.span_id
    else:
        if incoming is not None and (trace_id is None
                                     or incoming[0] == trace_id):
            tid, pid = incoming
        else:
            tid = trace_id or os.urandom(8).hex()
            pid = None
        sink = traces if traces is not None else telemetry._traces
    return Span(name, tid, pid, sink, attrs)


# -- cross-thread / cross-socket propagation --------------------------------


def capture():
    """Snapshot the current span context for another thread (the reactor
    sender queues); None when no span is active."""
    st = getattr(_tls, "stack", None)
    if not st:
        return None
    top = st[-1]
    return (top.trace_id, top.span_id, top.sink)


def resume(ctx, name: str, *, traces=None, **attrs):
    """Open a span on THIS thread parented to a `capture()`d context.
    No-op when the context is None or recording is off."""
    if ctx is None or not enabled():
        return NOOP
    tid, pid, sink = ctx
    return Span(name, tid, pid, traces if traces is not None else sink,
                attrs)


def http_header() -> str | None:
    """Outbound X-Celestia-Trace value for the current span, or None.
    Called by the peer transport on every request."""
    st = getattr(_tls, "stack", None)
    if not st or not enabled():
        return None
    top = st[-1]
    return f"{top.trace_id}:{top.span_id}"


def begin_request(headers) -> None:
    """Install the incoming trace context from request headers (HTTP
    handler entry); the next ROOT span on this thread adopts it."""
    raw = headers.get(TRACE_HEADER) if headers is not None else None
    if raw and ":" in raw:
        tid, _, sid = raw.partition(":")
        if tid and sid:
            _tls.incoming = (tid, sid)
            return
    _tls.incoming = None


def end_request() -> None:
    """Clear the incoming context (HTTP handler exit; handler threads are
    pooled, so a stale context must not leak into the next request)."""
    _tls.incoming = None


# -- shared HTTP surface (ONE implementation for every service) -------------


def serve_metrics(handler) -> None:
    """Write the Prometheus text exposition to a BaseHTTPRequestHandler —
    the /metrics route of both the node and validator services."""
    from celestia_app_tpu.utils import telemetry

    body = telemetry.prometheus().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "text/plain; version=0.0.4")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def route_profile(payload: dict) -> tuple[int, dict]:
    """The POST /debug/profile body -> (status, response) for both HTTP
    services: runs the on-demand jax.profiler capture, mapping every
    client-side problem to a 400."""
    from celestia_app_tpu.obs import jax_profile

    if not isinstance(payload, dict):
        return 400, {"error": "body must be a JSON object"}
    try:
        return 200, jax_profile.capture_profile(
            payload.get("dir"), seconds=payload.get("seconds", 0.5)
        )
    except jax_profile.ProfileError as e:
        return 400, {"error": str(e)}


# -- the /trace/* route, shared by every HTTP service -----------------------


def route_trace(traces, path: str) -> dict:
    """Serve /trace/<table>?since=<index>&limit=<n> from `traces`. Raises
    ValueError on malformed query (transports answer 400)."""
    from urllib.parse import parse_qs, urlparse

    parsed = urlparse(path)
    parts = parsed.path.split("/")
    table = parts[2] if len(parts) > 2 and parts[2] else ""
    qs = parse_qs(parsed.query)
    rows = traces.read(
        table,
        since_index=int(qs.get("since", ["0"])[0]),
        limit=int(qs.get("limit", ["1000"])[0]),
    )
    return {"table": table, "rows": rows, "tables": traces.tables()}
