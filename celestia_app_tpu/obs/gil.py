"""Process-pressure observables: the GIL sampler + the peak-RSS gauge.

ROADMAP item 5 (escaping the GIL for the HTTP serving planes) has so
far rested on an inference — pack-vs-live serving ratios — rather than
a measured contention number. This module produces that number the way
scheduler-latency probes do: an **oversleep-drift sampler**. A daemon
thread asks for a fixed short sleep (`INTERVAL_S`); under CPython, a
thread waking from `sleep()` must reacquire the GIL before it runs
again, so the drift between requested and actual sleep is a direct
sample of how long runnable threads in THIS process wait for the
interpreter (plus OS scheduler noise, which is the same for every
service and cancels in comparisons). Each service starts one sampler
under its own label:

- histogram ``gil.oversleep{service=…}`` — per-wake drift seconds
  (p50/p99 in /metrics via the registry's bucket ladder);
- gauge ``gil.pressure{service=…}`` — EWMA of drift/interval (0 ≈
  idle interpreter; 1.0 means wakes are delayed by a full interval).

Sampling is ``CELESTIA_OBS``-gated (the spans gate — `start` is a
no-op when observability is off) and costs one mostly-sleeping thread
per service: ~20 wakes/s of a few µs each (the interval sits well
above CPython's 5 ms switch interval on purpose — a probe at the
switch interval competes for the GIL instead of observing it), which
is what ``bench.py --obs`` arms when it measures the observatory's
overhead.

The peak-RSS collector rides along because it is the same kind of
process-level pressure number: PR 18 tracked ``peak_rss_bytes`` only
inside scenario verdicts; registering a scrape-time collector here
makes it a proper /metrics gauge (``process.peak_rss_bytes``) for
fleetmon and external scrapers. The collector registers at import —
importing the obs package is enough, no sampler needed.
"""

from __future__ import annotations

import sys
import threading
import time

from celestia_app_tpu.utils import telemetry

# 50 ms: an order of magnitude above CPython's 5 ms switch interval, so
# the probe samples GIL pressure instead of synchronizing with the
# switcher and creating it (a 5 ms probe costs ~10% wall on a busy
# interpreter; 50 ms is noise-level and still ~20 samples/s).
INTERVAL_S = 0.05

_lock = threading.Lock()
_samplers: dict = {}  # service -> _Sampler  # guarded-by: _lock

telemetry.set_help(
    "gil.oversleep",
    "sampler oversleep drift (GIL+scheduler wait) per wake (seconds)",
)
telemetry.set_help(
    "gil.pressure",
    "EWMA of oversleep drift / requested interval (0=idle interpreter)",
)
telemetry.set_help(
    "process.peak_rss_bytes", "peak resident set size of this process"
)


def peak_rss_bytes() -> int:
    """Peak resident set of this process in bytes (Linux ru_maxrss is
    KiB, macOS bytes; 0 where getrusage is unavailable)."""
    try:
        import resource
    except ImportError:  # non-POSIX
        return 0
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(peak) * (1 if sys.platform == "darwin" else 1024)


def _rss_collector() -> None:
    telemetry.gauge("process.peak_rss_bytes", peak_rss_bytes())


telemetry.register_collector(_rss_collector)


class _Sampler(threading.Thread):
    """One oversleep probe: sleep INTERVAL_S in a loop, record the
    drift. Daemon — it must never hold a process open."""

    def __init__(self, service: str):
        super().__init__(name=f"gil-sampler-{service}", daemon=True)
        self.service = service
        self._stop = threading.Event()
        self._ewma = 0.0

    def run(self) -> None:
        labels = {"service": self.service}
        while True:
            t0 = time.perf_counter()  # lint: disable=det-wallclock — the probe IS a clock measurement; feeds telemetry only
            if self._stop.wait(INTERVAL_S):
                return
            drift = (time.perf_counter() - t0) - INTERVAL_S  # lint: disable=det-wallclock — probe measurement, telemetry only
            drift = max(drift, 0.0)
            telemetry.observe("gil.oversleep", drift, labels=labels)
            self._ewma = 0.9 * self._ewma + 0.1 * (drift / INTERVAL_S)
            telemetry.gauge("gil.pressure", round(self._ewma, 6),
                            labels=labels)

    def stop(self) -> None:
        self._stop.set()


def start(service: str) -> bool:
    """Start the sampler for `service` (idempotent per label). No-op —
    returns False — when observability is gated off (CELESTIA_OBS),
    same gate as span recording."""
    from celestia_app_tpu.obs import spans

    if not spans.enabled():
        return False
    with _lock:
        s = _samplers.get(service)
        if s is not None and s.is_alive():
            return False
        s = _Sampler(service)
        _samplers[service] = s
        s.start()
        return True


def stop_all() -> None:
    """Stop every sampler (tests, bench teardown). Threads exit at
    their next wake (≤ INTERVAL_S)."""
    with _lock:
        samplers = list(_samplers.values())
        _samplers.clear()
    for s in samplers:
        s.stop()


def running() -> list[str]:
    with _lock:
        return sorted(
            name for name, s in _samplers.items() if s.is_alive()
        )
