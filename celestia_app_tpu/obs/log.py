"""Leveled structured logger for library modules.

Library code (chain/, das/, mempool/, faults/…) must never call
``print`` — a tier-1 lint test enforces it (tests/test_obs.py), the same
pattern as the urlopen gate. This is the replacement: a tiny stderr
logger with

- **levels** (debug/info/warning/error), filtered by ``CELESTIA_LOG_LEVEL``
  (default ``info``; ``CELESTIA_LOG_LEVEL=error`` quiets a devnet's
  reactors to real failures only);
- **structured fields** — ``log.warning("round error", height=h, err=e)``
  renders ``key=value`` pairs in text mode and proper JSON objects with
  ``CELESTIA_LOG_FORMAT=json`` (machine-ingestable, one object per line);
- **telemetry coupling** — every emitted record counts in the global
  registry (``log.<level>`` counters), so "how many errors did this node
  log" is scrapeable from /metrics without parsing stderr.

stdlib ``logging`` is deliberately not used: its global config is owned
by embedding applications, and this package must never reconfigure a
host process's logging tree.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from celestia_app_tpu.utils import telemetry

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}

_lock = threading.Lock()
_config: dict | None = None


def _cfg() -> dict:
    global _config
    if _config is None:
        level = os.environ.get("CELESTIA_LOG_LEVEL", "info").strip().lower()
        _config = {
            "threshold": _LEVELS.get(level, 20),
            "json": os.environ.get("CELESTIA_LOG_FORMAT", "").strip().lower()
            == "json",
        }
    return _config


def configure(level: str | None = None, json_mode: bool | None = None) -> None:
    """Override env config (tests, embedding tools). level=None +
    json_mode=None resets to the environment."""
    global _config
    if level is None and json_mode is None:
        _config = None
        return
    cfg = dict(_cfg())
    if level is not None:
        cfg["threshold"] = _LEVELS.get(level.lower(), cfg["threshold"])
    if json_mode is not None:
        cfg["json"] = bool(json_mode)
    _config = cfg


class Logger:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def _emit(self, level: str, msg: str, fields: dict) -> None:
        cfg = _cfg()
        if _LEVELS[level] < cfg["threshold"]:
            return
        telemetry.incr(f"log.{level}")
        if cfg["json"]:
            line = json.dumps({
                "ts": round(time.time(), 3), "level": level,
                "logger": self.name, "msg": msg,
                # log lines are operator output, not consensus: field
                # order is the writer's insertion order on purpose
                **{k: _jsonable(v) for k, v in fields.items()},  # lint: disable=det-dict-hash
            })
        else:
            kv = " ".join(f"{k}={_jsonable(v)}" for k, v in fields.items())
            line = f"[{self.name}] {level.upper()}: {msg}" \
                + (f" {kv}" if kv else "")
        with _lock:
            try:
                sys.stderr.write(line + "\n")
                sys.stderr.flush()
            except (OSError, ValueError):
                pass  # a closed stderr must never crash the library

    def debug(self, msg: str, **fields) -> None:
        self._emit("debug", msg, fields)

    def info(self, msg: str, **fields) -> None:
        self._emit("info", msg, fields)

    def warning(self, msg: str, **fields) -> None:
        self._emit("warning", msg, fields)

    def error(self, msg: str, **fields) -> None:
        self._emit("error", msg, fields)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, BaseException):
        return f"{type(v).__name__}: {v}"
    return repr(v)


_loggers: dict[str, Logger] = {}


def get_logger(name: str) -> Logger:
    lg = _loggers.get(name)
    if lg is None:
        lg = _loggers[name] = Logger(name)
    return lg
