"""JAX/TPU profiling hooks: the compile-vs-execute split + device gauges.

On a TPU stack the single most important attribution is *XLA compilation
time vs execution time* — a 30 s jit compile hiding inside a "slow
prepare_proposal" is a completely different problem from a slow kernel.
The hooks here make that split a first-class metric without ever forcing
a backend to initialize:

- `note_compile(name, key)` — called from inside the lru-cached jitted
  factories (da/eds.py) so it fires EXACTLY once per cache miss: counts
  ``jax.compilations`` (and per-(fn, k) under ``by_fn``); the live
  jit-cache-size gauge reads the registered factories' cache_info() at
  scrape time (`register_cache`), staying honest across cache_clear().
- `instrument(name, fn)` — wraps the jitted callable; the first call
  (which pays tracing + XLA compilation) lands in the ``jax.compile``
  histogram, every later call in ``jax.execute``, each labeled with the
  program name. The wrapper proxies attribute access, so
  ``jitted_pipeline.cache_clear()`` / ``.lower()`` keep working.
- `collect_gauges()` — a telemetry collector run at scrape time that
  exports device count, bytes-in-use, and live-buffer gauges. It reads
  ``sys.modules`` and only touches backends that ALREADY initialized:
  a host-engine validator process (which must never import-and-dispatch
  jax — the relay-down hang class, see service/server.py) serves
  /metrics without waking a backend.
- `capture_profile(out_dir, seconds)` — the /debug/profile endpoint's
  worker: an on-demand ``jax.profiler`` trace capture to a directory
  (open with TensorBoard / xprof). Refuses when jax is not already
  loaded in the process, for the same hang-class reason.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from celestia_app_tpu.utils import telemetry


class ProfileError(ValueError):
    """Client-side profiling problem (jax absent, capture in flight,
    bad duration): transports answer 4xx."""


_lock = threading.Lock()
_capturing = False
# lru-cached jitted factories registered for live cache-size accounting
# (reading cache_info() at scrape time stays honest across cache_clear(),
# which bench.py calls repeatedly)
_factories: list = []

MAX_CAPTURE_SECONDS = 30.0


def note_compile(name: str, key) -> None:
    """One jitted-factory cache miss == one program compilation coming.
    Call from INSIDE the lru-cached factory body (it only runs on miss);
    `key` is the cache key (the square-size bucket), labeled so compile
    storms attribute to the bucket that caused them."""
    telemetry.incr("jax.compilations")
    telemetry.incr("jax.compilations.by_fn",
                   labels={"fn": name, "k": str(key)})


def register_cache(factory) -> None:
    """Register an lru-cached jitted factory; the scrape-time collector
    sums live cache_info().currsize into the jit-cache-size gauge."""
    with _lock:
        if factory not in _factories:
            _factories.append(factory)


class _Instrumented:
    """Transparent wrapper over a jitted callable: first call -> the
    ``jax.compile`` histogram (tracing + XLA compile + first run), later
    calls -> ``jax.execute``. Attribute access proxies to the wrapped
    function so AOT/lowering APIs stay reachable."""

    __slots__ = ("_name", "_fn", "_compiled", "_flag_lock")

    def __init__(self, name: str, fn):
        self._name = name
        self._fn = fn
        self._compiled = False
        self._flag_lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        if self._compiled:
            # steady state measures DISPATCH, deliberately: blocking here
            # would serialize the streaming pipelines whose whole design
            # is overlapping host work with device compute (parallel/
            # streaming.py). On async backends this is enqueue latency —
            # device-side time comes from /debug/profile (FORMATS §10.2).
            telemetry.measure_since("jax.execute", t0,
                                    labels={"fn": self._name})
        else:
            # exactly ONE call may claim the compile observation — two
            # threads racing the first call (reactor + HTTP handler)
            # must not both pollute the compile histogram
            with self._flag_lock:
                first = not self._compiled
                self._compiled = True
            if first:
                # the compile number must include the real first run, not
                # just its dispatch: block before stopping the clock
                # (one-time cost; compile dominates it anyway)
                try:
                    import jax

                    jax.block_until_ready(out)
                except Exception:
                    telemetry.incr("jax.profile_probe_errors")
            telemetry.measure_since(
                "jax.compile" if first else "jax.execute", t0,
                labels={"fn": self._name},
            )
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument(name: str, fn):
    return _Instrumented(name, fn)


# -- device gauges (scrape-time collector) ----------------------------------


def collect_gauges() -> None:
    """Export device gauges IF a jax backend already initialized in this
    process; otherwise do nothing (never triggers backend init — the
    /metrics route on a host-engine process must stay hang-free)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        from jax._src import xla_bridge

        backends = getattr(xla_bridge, "_backends", None)
        if not backends:
            return
        devices = jax.devices()
    except Exception:
        telemetry.incr("jax.profile_probe_errors")
        return
    telemetry.gauge("jax.device_count", len(devices))
    with _lock:
        factories = list(_factories)
    try:
        telemetry.gauge("jax.jit_cache_size", float(sum(
            f.cache_info().currsize for f in factories
        )))
    except Exception:
        telemetry.incr("jax.profile_probe_errors")
    try:
        telemetry.gauge("jax.live_buffers", float(len(jax.live_arrays())))
    except Exception:
        telemetry.incr("jax.profile_probe_errors")
    in_use = peak = 0.0
    seen = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            telemetry.incr("jax.profile_probe_errors")
            stats = None
        if not stats:
            continue
        seen = True
        in_use += float(stats.get("bytes_in_use", 0))
        peak += float(stats.get("peak_bytes_in_use", 0))
    if seen:
        telemetry.gauge("jax.device_memory_bytes_in_use", in_use)
        telemetry.gauge("jax.device_memory_peak_bytes", peak)


telemetry.register_collector(collect_gauges)


# -- on-demand profiler capture (/debug/profile) ----------------------------


def capture_profile(out_dir: str | None = None,
                    seconds: float = 0.5) -> dict:
    """Capture a jax.profiler trace for `seconds` into `out_dir` (a fresh
    temp dir when None). Synchronous: the handler thread sleeps through
    the window while OTHER threads' dispatches land in the trace.
    One capture at a time; refuses when jax was never imported here."""
    global _capturing
    if "jax" not in sys.modules:
        raise ProfileError(
            "jax is not loaded in this process (host-engine services "
            "never import it; point /debug/profile at a device-engine "
            "process)"
        )
    try:
        seconds = float(seconds)
    except (TypeError, ValueError):
        raise ProfileError("seconds must be a number") from None
    if not 0.0 < seconds <= MAX_CAPTURE_SECONDS:
        raise ProfileError(
            f"seconds must be in (0, {MAX_CAPTURE_SECONDS:g}]"
        )
    with _lock:
        if _capturing:
            raise ProfileError("a profile capture is already running")
        _capturing = True
    t0 = time.perf_counter()
    # EVERYTHING between the flag set and the finally maps to
    # ProfileError (a 4xx, never a 5xx) and releases the flag — an
    # unwritable out_dir must not wedge the endpoint forever
    try:
        import jax

        if out_dir is None:
            import tempfile

            out_dir = tempfile.mkdtemp(prefix="celestia-jax-profile-")
        else:
            os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    except ProfileError:
        raise
    except Exception as e:
        raise ProfileError(
            f"profiler capture failed: {type(e).__name__}: {e}"
        ) from None
    finally:
        with _lock:
            _capturing = False
    telemetry.incr("jax.profile_captures")
    return {
        "dir": out_dir,
        "seconds": seconds,
        "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
    }
