"""The host↔device transfer ledger: every boundary crossing, counted.

ROADMAP item 2 (zero-copy blob path) is blocked on one number that no
counter in the tree produces: how many bytes cross the host↔device
boundary per committed block. `edscache.host_crossings` counts one
narrow path (lazy host materialization of a device-resident entry);
the dispatch uploads, commitment fetches, streaming drains, and ops
runner round-trips are all invisible. This module closes that hole the
way arXiv:2108.02692 profiles erasure-coding kernels — measure the
memory traffic first, then optimize:

- **Counted helpers.** `to_device(value, site)` / `to_host(value,
  site)` wrap `jax.device_put` / `jax.device_get` and attribute bytes,
  call count, and latency to the CALL-SITE label: labeled counters
  ``xfer.h2d_bytes{site=…}`` / ``xfer.d2h_bytes{site=…}`` (+ the
  ``_calls`` twins) and latency histograms ``xfer.h2d``/``xfer.d2h``
  land in the telemetry registry, so /metrics exposes the full
  per-site traffic matrix. Every `device_put`/`device_get` in the
  tree (edscache, mesh_engine, streaming, ops runners) routes through
  them.
- **Ledger rows.** When span recording is on (CELESTIA_OBS) and a span
  is active, each transfer also writes one row to the ``xfer`` trace
  table of the span's sink, stamped with the span's trace id — so a
  block's transfers merge into its per-height waterfall
  (tools/timeline.py) exactly like its spans do.
- **A pinnable residency claim.** `no_implicit_transfers()` makes any
  boundary crossing the helpers did NOT mediate an error. On
  accelerator backends ``jax.transfer_guard("disallow")`` does this in
  XLA. On the CPU backend a committed array is host memory behind the
  C buffer protocol, so ``np.asarray`` reads it zero-copy and no guard
  can fire; to keep residency claims testable under JAX_PLATFORMS=cpu
  the context ALSO swaps ``numpy.asarray`` for a probe that rejects
  jax.Array arguments on the claiming thread unless the call comes
  from a ledger helper. Tier-1 pins the warmed produce path with it.
- **The per-block gauge.** The cumulative totals (`totals()`,
  `bytes_crossed()`) let chain/app.py compute a per-commit delta —
  gauge ``xfer.host_bytes_crossed_per_block`` — which is PR 20's
  baseline and acceptance gate.

Counting is always-on (two dict writes under the registry lock — the
same cost class as `edscache.host_crossings`); only the ledger ROWS
follow the CELESTIA_OBS gate. ``bench.py --obs`` measures the armed
on/off delta.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

import numpy as np

from celestia_app_tpu.utils import telemetry

XFER_TABLE = "xfer"


class ImplicitTransferError(RuntimeError):
    """An uncounted host↔device crossing inside `no_implicit_transfers()`:
    a device value was materialized to host memory by a path the ledger
    cannot see (stray ``np.asarray`` instead of `to_host`)."""


_tls = threading.local()

_totals_lock = threading.Lock()
# cumulative process-wide boundary traffic — the source of the
# per-block delta gauge (chain/app.py reads totals() at each commit)
_totals = {
    "h2d_bytes": 0, "d2h_bytes": 0,
    "h2d_calls": 0, "d2h_calls": 0,
}

telemetry.set_help(
    "xfer.h2d_bytes", "host->device bytes through the transfer ledger"
)
telemetry.set_help(
    "xfer.d2h_bytes", "device->host bytes through the transfer ledger"
)
telemetry.set_help(
    "xfer.host_bytes_crossed_per_block",
    "host<->device bytes crossed while committing the last block",
)


def nbytes_of(value) -> int:
    """Byte size of an array, buffer, or (possibly nested) container of
    them — the unit the ledger counts. Unknown leaves count 0 rather
    than raising: the ledger must never take down a transfer."""
    if value is None:
        return 0
    if isinstance(value, (bytes, bytearray, memoryview)):
        return len(value)
    if isinstance(value, (list, tuple)):
        return sum(nbytes_of(v) for v in value)
    if isinstance(value, dict):
        return sum(nbytes_of(v) for v in value.values())
    n = getattr(value, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(value, (bool, int, float)):
        return 8  # python scalar -> one device word
    return 0


def totals() -> dict:
    """Snapshot of the cumulative process-wide transfer totals."""
    with _totals_lock:
        return dict(_totals)


def bytes_crossed() -> int:
    """Cumulative h2d+d2h bytes — the monotone the per-block delta
    gauge is computed from."""
    with _totals_lock:
        return _totals["h2d_bytes"] + _totals["d2h_bytes"]


@contextmanager
def _explicit():
    """Mark this thread as inside a ledger helper, so the
    `no_implicit_transfers()` probe lets the mediated numpy read pass."""
    d = getattr(_tls, "explicit", 0)
    _tls.explicit = d + 1
    try:
        yield
    finally:
        _tls.explicit = d


def _account(direction: str, site: str, nbytes: int, t0: float) -> None:
    """Attribute one transfer to `site`: counters + latency histogram +
    (when a span is active) one ledger row in the span's trace sink."""
    dur_s = telemetry.measure_since(
        f"xfer.{direction}", t0, labels={"site": site}
    )
    telemetry.incr(f"xfer.{direction}_bytes", nbytes, labels={"site": site})
    telemetry.incr(f"xfer.{direction}_calls", labels={"site": site})
    with _totals_lock:
        _totals[f"{direction}_bytes"] += nbytes
        _totals[f"{direction}_calls"] += 1
    from celestia_app_tpu.obs import spans

    ctx = spans.capture() if spans.enabled() else None
    if ctx is None:
        return
    tid, sid, sink = ctx
    try:
        sink.write(
            XFER_TABLE,
            trace_id=tid,
            parent_id=sid,
            site=site,
            dir=direction,
            bytes=int(nbytes),
            # wall-clock start so timeline can order the row among the
            # spans of its height; display only, never hashed
            start_unix=round(time.time() - dur_s, 6),  # lint: disable=det-wallclock
            dur_ms=round(dur_s * 1e3, 3),
        )
    except Exception:
        # must never take down the transfer it measures — but count it:
        # a ledger that silently drops rows looks "quiet", not correct
        telemetry.incr("obs.xfer_row_errors")


def to_device(value, site: str, *, placement=None):
    """`jax.device_put` with the boundary accounted to `site`.
    `placement` passes a Device or Sharding through unchanged (the mesh
    plane's sharded uploads)."""
    import jax

    t0 = telemetry.start_timer()
    with _explicit():
        if placement is not None:
            out = jax.device_put(value, placement)  # xfer: ledger
        else:
            out = jax.device_put(value)  # xfer: ledger
    _account("h2d", site, nbytes_of(value), t0)
    return out


def to_host(value, site: str):
    """`jax.device_get` (blocks until the value is ready) with the
    boundary accounted to `site`. Accepts pytrees; returns numpy."""
    import jax

    t0 = telemetry.start_timer()
    with _explicit():
        out = jax.device_get(value)  # xfer: ledger
    _account("d2h", site, nbytes_of(out), t0)
    return out


def ensure_host(value, site: str):
    """Materialize-if-device: a device value comes back through the
    counted d2h path (`to_host`, attributed to `site`); anything
    already host passes through ``np.asarray`` unchanged and counts
    NOTHING — the helper for boundary-normalization call sites whose
    inputs are only sometimes device-resident (a fake ledger row for a
    zero-copy host read would be worse than none)."""
    try:
        import jax
    except ImportError:
        jax = None
    if jax is not None and isinstance(value, jax.Array):
        return to_host(value, site)
    with _explicit():
        return np.asarray(value)  # xfer: ledger


# -- the residency pin -------------------------------------------------------

_probe_lock = threading.Lock()
_probe_refs = 0           # guarded-by: _probe_lock
_orig_asarray = None      # guarded-by: _probe_lock


def _probe_asarray(a, *args, **kwargs):
    """`numpy.asarray` stand-in while a `no_implicit_transfers()` region
    is active anywhere in the process: on threads inside such a region,
    a jax.Array argument outside a ledger helper is an uncounted
    boundary crossing. All other calls delegate unchanged."""
    if (
        getattr(_tls, "guard", 0) > 0
        and getattr(_tls, "explicit", 0) == 0
    ):
        import jax

        if isinstance(a, jax.Array):
            raise ImplicitTransferError(
                "np.asarray on a device value inside "
                "no_implicit_transfers() — route it through "
                "obs.xfer.to_host(value, site) so the ledger counts it"
            )
    return _orig_asarray(a, *args, **kwargs)


@contextmanager
def no_implicit_transfers():
    """Pin a device-residency claim: any host↔device crossing the ledger
    helpers did not mediate raises inside this context.

    Two mechanisms, because the backends differ: on accelerators,
    ``jax.transfer_guard("disallow")`` makes XLA reject implicit
    transfers while explicit `device_put`/`device_get` (and therefore
    `to_device`/`to_host`) stay legal. On the CPU backend a committed
    array is host memory behind the C buffer protocol — numpy reads it
    zero-copy, so no XLA guard can fire; the context additionally swaps
    ``numpy.asarray`` for a probe that rejects jax.Array arguments on
    the claiming thread (other threads are untouched: the probe checks
    a thread-local flag before doing anything). Without jax installed
    the context is a no-op."""
    try:
        import jax
    except Exception:
        # no jax (or a backend that refuses to init): there IS no
        # device boundary to guard — count the vacuous pin so a tier-1
        # run on a jaxless box shows the claim was not exercised
        telemetry.incr("obs.xfer_guard_noop")
        yield
        return
    global _probe_refs, _orig_asarray
    with _probe_lock:
        if _probe_refs == 0:
            _orig_asarray = np.asarray
            np.asarray = _probe_asarray
        _probe_refs += 1
    _tls.guard = getattr(_tls, "guard", 0) + 1
    try:
        with jax.transfer_guard("disallow"):
            yield
    finally:
        _tls.guard -= 1
        with _probe_lock:
            _probe_refs -= 1
            if _probe_refs == 0:
                np.asarray = _orig_asarray
                _orig_asarray = None
