"""Leopard-RS GF(2^8) codec: the reference's wire-compatible erasure code.

The reference chains to ``rsmt2d.NewLeoRSCodec``
(pkg/appconsts/global_consts.go:92, invoked from
pkg/da/data_availability_header.go:65-75), which is klauspost/reedsolomon's
Leopard mode — the additive-FFT Reed-Solomon construction of Lin, Chung & Han
("Novel Polynomial Basis and Its Application to Reed-Solomon Erasure Codes",
FOCS 2014) as implemented by catid/leopard. For ≤256 total shards (square
sizes up to k=128, i.e. every protocol-legal square) that is the 8-bit code
over GF(2^8)/0x11D with the Cantor basis {1, 214, 152, 146, 86, 200, 88, 230}.

This module implements that code from the algorithm, not from any source
port, in three layers:

1. Field tables in "label space". Leopard's byte labels are related to the
   standard polynomial representation by the GF(2)-linear Cantor change of
   basis C (label bit b ↦ basis element β_b). Multiplication on labels is the
   standard field multiplication conjugated by C; addition is XOR either way.
   In label space the FFT evaluation point of index i is simply the label i,
   and the d-dimensional FFT subspace U_d is {0, …, 2^d−1}.

2. The LCH additive FFT. With ŝ_d the subspace polynomial of U_d normalized
   so ŝ_d(2^d) = 1, the decimation-in-time butterfly over a block at offset γ
   with half-width 2^d uses the constant w = ŝ_d(γ):

       FFT:  x ^= w·y ; y ^= x        IFFT:  y ^= x ; x ^= w·y

   (the second half of each block differs from the first by β_d, and
   ŝ_d(x ⊕ β_d) = ŝ_d(x) ⊕ 1 by linearity + normalization, hence the
   multiplier-free second step). Subspace polynomials are linearized, so
   ŝ_d(γ) is the XOR of ŝ_d(2^b) over the set bits b of γ — an 8×8 table.

3. Encode. For k data shards (k a power of two) and k recovery shards:
   coefficients = IFFT over the coset at offset k (where the data logically
   sits, points [k, 2k)), recovery = FFT of those coefficients over the coset
   at offset 0 (points [0, k)). The transmitted codeword is
   [data | recovery], matching rsmt2d's row layout [ODS half | parity half].

Validation (tests/test_leopard.py): the Cantor basis satisfies the defining
recurrence β_{i+1}² ⊕ β_{i+1} = β_i with β_0 = 1 (uniquely pinning the
constants), the butterfly network is cross-checked against direct evaluation
of the novel polynomial basis X_j(x) = Π_d ŝ_d(x)^{j_d}, and the code is
verified systematic + MDS (every erasure pattern at small k, randomized at
large k). Constant data extends to constant parity, so the reference's
pinned constant-share DAH hashes (tests/test_dah_golden.py) remain exact
under this codec — and varied-data squares now also produce the reference's
codewords.

Residual bit-compat risk (stated honestly): no Leopard-generated varied-data
vector is available in this offline environment to pin against, so two
conventions rest on the structure of the leopard encode rather than an
external golden: (a) recovery symbols are the FFT outputs at points [0, k)
in natural order, mapped to rsmt2d's parity half with data at points
[k, 2k); (b) no bit-reversal permutation is applied to FFT outputs. Both
follow from the published algorithm's single FFT/IFFT pass; everything else
(field, basis, butterflies, skews) is pinned by the structural tests.
"""

from __future__ import annotations

import functools

import numpy as np

K_BITS = 8
ORDER = 1 << K_BITS  # 256
MODULUS = ORDER - 1  # 255
POLY = 0x11D

# Cantor basis over GF(2^8)/0x11D: beta_0 = 1, beta_{i+1}^2 + beta_{i+1} =
# beta_i (verified in tests). Label bit b represents basis element beta_b.
CANTOR_BASIS = (1, 214, 152, 146, 86, 200, 88, 230)


@functools.lru_cache(maxsize=None)
def _tables() -> tuple[np.ndarray, np.ndarray]:
    """(LOG, EXP) on byte labels.

    LOG[x] = discrete log (base 2 in the standard representation) of the
    Cantor-mapped label x; EXP is its inverse permutation. LOG[0] = MODULUS
    is the zero sentinel. mul(a, b) = EXP[(LOG[a] + LOG[b]) mod MODULUS] is
    then exactly the standard field multiplication conjugated by the Cantor
    change of basis.
    """
    lfsr_log = np.zeros(ORDER, dtype=np.int32)
    state = 1
    for i in range(MODULUS):
        lfsr_log[state] = i
        state <<= 1
        if state & ORDER:
            state ^= POLY
    lfsr_log[0] = MODULUS

    cantor = np.zeros(ORDER, dtype=np.int64)
    for b in range(K_BITS):
        w = 1 << b
        cantor[w : 2 * w] = cantor[:w] ^ CANTOR_BASIS[b]

    log = lfsr_log[cantor]
    exp = np.zeros(ORDER, dtype=np.int32)
    exp[log] = np.arange(ORDER)
    return log, exp


def mul(a: int, b: int) -> int:
    """GF(2^8) product of two byte labels (leopard representation)."""
    if a == 0 or b == 0:
        return 0
    log, exp = _tables()
    return int(exp[(int(log[a]) + int(log[b])) % MODULUS])


def inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    log, exp = _tables()
    return int(exp[(MODULUS - int(log[a])) % MODULUS])


def mul_vec(w: int, x: np.ndarray) -> np.ndarray:
    """w ·gf x elementwise for a scalar label w and uint8 array x."""
    if w == 0:
        return np.zeros_like(x)
    log, exp = _tables()
    out = exp[(int(log[w]) + log[x.astype(np.int32)]) % MODULUS]
    return np.where(x == 0, 0, out).astype(np.uint8)


# ---------------------------------------------------------------------------
# Subspace polynomials and skews
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _skew_basis() -> np.ndarray:
    """S[d, b] = ŝ_d(label 2^b) for b ≥ d (0 below the diagonal).

    s_d(x) = Π_{a ∈ U_d} (x ⊕ a) with U_d = {0..2^d−1};
    ŝ_d = s_d / s_d(2^d). Linearized, so ŝ_d at any label is the XOR of
    these basis values over the label's set bits.
    """
    s = np.zeros((K_BITS, K_BITS), dtype=np.int64)

    def s_d_at(d: int, x: int) -> int:
        acc = 1
        for a in range(1 << d):
            acc = mul(acc, x ^ a)
        return acc

    for d in range(K_BITS):
        norm = inv(s_d_at(d, 1 << d))
        for b in range(d, K_BITS):
            s[d, b] = mul(s_d_at(d, 1 << b), norm)
    return s


def skew(d: int, gamma: int) -> int:
    """ŝ_d(γ): the butterfly multiplier at layer d, block offset γ."""
    s = _skew_basis()
    acc = 0
    b = d  # bits below d contribute 0 (ŝ_d vanishes on U_d)
    g = gamma >> d
    while g:
        if g & 1:
            acc ^= int(s[d, b])
        g >>= 1
        b += 1
    return acc


# ---------------------------------------------------------------------------
# Additive FFT butterflies (byte-vector shards, vectorized over numpy)
# ---------------------------------------------------------------------------


def fft(buf: np.ndarray, offset: int) -> np.ndarray:
    """In-place-style FFT over a (n, ...) uint8 shard stack, n a power of 2.

    Transforms novel-basis coefficients into evaluations at labels
    [offset, offset + n). ``offset`` must be a multiple of n.
    """
    n = buf.shape[0]
    out = buf.copy()
    d = n.bit_length() - 2  # log2(n) - 1
    while d >= 0:
        half = 1 << d
        for j in range(0, n, 2 * half):
            w = skew(d, offset + j)
            x = out[j : j + half]
            y = out[j + half : j + 2 * half]
            if w:
                x ^= mul_vec(w, y)
            y ^= x
        d -= 1
    return out


def ifft(buf: np.ndarray, offset: int) -> np.ndarray:
    """Inverse of :func:`fft` (evaluations at [offset, offset+n) → coeffs)."""
    n = buf.shape[0]
    out = buf.copy()
    for d in range(n.bit_length() - 1):
        half = 1 << d
        for j in range(0, n, 2 * half):
            w = skew(d, offset + j)
            x = out[j : j + half]
            y = out[j + half : j + 2 * half]
            y ^= x
            if w:
                x ^= mul_vec(w, y)
    return out


# ---------------------------------------------------------------------------
# Encode / matrices
# ---------------------------------------------------------------------------


def encode(data: np.ndarray) -> np.ndarray:
    """(k, ...) data shards → (k, ...) recovery shards, k a power of two.

    Leopard encode for original_count == recovery_count == k: data are the
    evaluations at points [k, 2k); recovery are the evaluations of the same
    (novel-basis) polynomial at points [0, k).
    """
    k = data.shape[0]
    if k & (k - 1) or not (1 <= k <= ORDER // 2):
        raise ValueError(f"k must be a power of two in [1, {ORDER // 2}], got {k}")
    if k == 1:
        return data.copy()  # degree-0 polynomial: repetition
    coeffs = ifft(np.ascontiguousarray(data, dtype=np.uint8), k)
    return fft(coeffs, 0)


@functools.lru_cache(maxsize=None)
def encode_matrix(k: int) -> np.ndarray:
    """(k, k) uint8 E with recovery = E ·gf data (GF(2^8) label space).

    Derived by encoding the identity: shard i carries the i-th unit byte
    vector, so recovery shard j carries row j of E. Exact because the
    butterfly network is GF-linear in the shard bytes.
    """
    eye = np.eye(k, dtype=np.uint8)
    return encode(eye)


@functools.lru_cache(maxsize=None)
def generator_matrix(k: int) -> np.ndarray:
    """(2k, k): codeword = G ·gf data with G = [I_k ; E]."""
    return np.concatenate([np.eye(k, dtype=np.uint8), encode_matrix(k)], axis=0)


def matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product in label space (host; tests and small squares).

    a is (m, k) byte labels; b is (k, ...) byte vectors. Row operations are
    numpy-vectorized over b's trailing axes.
    """
    assert a.ndim == 2 and b.ndim >= 2 and a.shape[1] == b.shape[0]
    out = np.zeros((a.shape[0],) + b.shape[1:], dtype=np.uint8)
    for i in range(a.shape[0]):
        acc = np.zeros(b.shape[1:], dtype=np.uint8)
        for j in range(a.shape[1]):
            if a[i, j]:
                acc ^= mul_vec(int(a[i, j]), b[j])
        out[i] = acc
    return out


def to_bit_matrix(m: np.ndarray) -> np.ndarray:
    """(r, c) GF(2^8) label matrix -> (8r, 8c) 0/1 int8 GF(2) expansion.

    y = M ·gf x is GF(2)-linear in x's label bits: with bits packed
    LSB-first within each byte, B[8j+i, 8l+b] = bit i of mul(M[j,l], 1<<b),
    so y_bits = (B @ x_bits) mod 2 for ANY label matrix (encode, decode,
    or their products)."""
    m = m.astype(np.int32)
    log, exp = _tables()
    powers = (1 << np.arange(8)).astype(np.int32)  # labels 2^b
    prod = exp[(log[m][:, :, None] + log[powers][None, None, :]) % MODULUS]
    prod = np.where(m[:, :, None] == 0, 0, prod)
    bits = (prod[:, None, :, :] >> np.arange(8)[None, :, None, None]) & 1
    return bits.reshape(8 * m.shape[0], 8 * m.shape[1]).astype(np.int8)


@functools.lru_cache(maxsize=None)
def bit_matrix(k: int) -> np.ndarray:
    """(8k, 8k) GF(2) expansion of encode_matrix(k) — the constant the
    device RS kernel folds into its MXU matmul (ops/rs.py): the whole
    Leopard encode collapses into one int8 matrix once the code is seen as
    GF(2)-linear."""
    return to_bit_matrix(encode_matrix(k))


def _gf_invert(a: np.ndarray) -> np.ndarray:
    """Invert a (n, n) label-space matrix by Gauss-Jordan elimination."""
    n = a.shape[0]
    m = a.astype(np.uint8).copy()
    out = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = col + int(np.argmax(m[col:, col] != 0))
        if m[piv, col] == 0:
            raise np.linalg.LinAlgError(f"singular GF(256) matrix at column {col}")
        if piv != col:
            m[[col, piv]] = m[[piv, col]]
            out[[col, piv]] = out[[piv, col]]
        ipv = inv(int(m[col, col]))
        m[col] = mul_vec(ipv, m[col])
        out[col] = mul_vec(ipv, out[col])
        mask = (m[:, col] != 0) & (np.arange(n) != col)
        for r in np.nonzero(mask)[0]:
            f = int(m[r, col])
            m[r] ^= mul_vec(f, m[col])
            out[r] ^= mul_vec(f, out[col])
    return out


@functools.lru_cache(maxsize=1024)  # keyed by erasure pattern: bounded, unlike the k-keyed caches
def decode_matrix(k: int, present: tuple[int, ...]) -> np.ndarray:
    """(k, k) matrix mapping k present codeword symbols → k data symbols.

    ``present`` are codeword positions in [0, 2k) — data at [0, k), recovery
    at [k, 2k), rsmt2d row order. Any k positions work (MDS): the matrix is
    the inverse of the corresponding row-submatrix of the generator.
    """
    if len(present) != k:
        raise ValueError(f"need exactly {k} present positions")
    sub = generator_matrix(k)[list(present)]
    return _gf_invert(sub)

# ---------------------------------------------------------------------------
# GF(2^16): squares wider than 128 (>256 shards/row need the 16-bit code,
# as klauspost's WithLeopardGF picks FF16 beyond 256 total shards).
# Shards are interpreted as little-endian uint16 symbols (256 per share).
# ---------------------------------------------------------------------------

K_BITS16 = 16
ORDER16 = 1 << K_BITS16
MODULUS16 = ORDER16 - 1
POLY16 = 0x1002D

# Cantor basis over GF(2^16)/0x1002D, derived from the defining recurrence
# beta_0 = 1, beta_{i+1}^2 + beta_{i+1} = beta_i, choosing the even root at
# each step — the same construction that exactly reproduces the verified
# 8-bit basis above (tests re-derive and cross-check it). No reference pins
# exist for >128 squares, so the selection rule is the documented convention.
CANTOR_BASIS16 = (
    0x0001, 0xACCA, 0x3C0E, 0x163E, 0xC582, 0xED2E, 0x914C, 0x4012,
    0x6C98, 0x10D8, 0x6A72, 0xB900, 0xFDB8, 0xFB34, 0xFF38, 0x991E,
)


@functools.lru_cache(maxsize=None)
def _tables16() -> tuple[np.ndarray, np.ndarray]:
    """(LOG, EXP) on 16-bit labels, same construction as _tables()."""
    lfsr_log = np.zeros(ORDER16, dtype=np.int64)
    state = 1
    for i in range(MODULUS16):
        lfsr_log[state] = i
        state <<= 1
        if state & ORDER16:
            state ^= POLY16
    lfsr_log[0] = MODULUS16

    cantor = np.zeros(ORDER16, dtype=np.int64)
    for b in range(K_BITS16):
        w = 1 << b
        cantor[w : 2 * w] = cantor[:w] ^ CANTOR_BASIS16[b]

    log = lfsr_log[cantor]
    exp = np.zeros(ORDER16, dtype=np.int64)
    exp[log] = np.arange(ORDER16)
    return log, exp


def mul16(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    log, exp = _tables16()
    return int(exp[(int(log[a]) + int(log[b])) % MODULUS16])


def inv16(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^16) inverse of 0")
    log, exp = _tables16()
    return int(exp[(MODULUS16 - int(log[a])) % MODULUS16])


def mul_vec16(w: int, x: np.ndarray) -> np.ndarray:
    if w == 0:
        return np.zeros_like(x)
    log, exp = _tables16()
    out = exp[(int(log[w]) + log[x.astype(np.int64)]) % MODULUS16]
    return np.where(x == 0, 0, out).astype(np.uint16)


@functools.lru_cache(maxsize=None)
def _skew_basis16() -> np.ndarray:
    """S[d, b] = ŝ_d(label 2^b), b >= d, over the 16-bit field.

    s_d evaluated via its linearized form (s_d(x) = XOR over set bits of the
    precomputed s_d(2^c) for c < d plus the product definition at basis
    points) — the direct product over U_d is infeasible at 2^15 elements, so
    s_{d+1}(x) = s_d(x) ·gf s_d(x ^ beta_d) is used (the standard subspace
    polynomial recursion: U_{d+1} = U_d ∪ (beta_d ⊕ U_d))."""
    s = np.zeros((K_BITS16, K_BITS16), dtype=np.int64)
    # s_d evaluated at all basis points 2^b via the recursion; track
    # s_d(2^b) and s_d(2^b ^ 2^d) style values lazily with a dict cache
    cache: dict[tuple[int, int], int] = {}

    def s_d_at(d: int, x: int) -> int:
        if d == 0:
            return x
        key = (d, x)
        if key not in cache:
            cache[key] = mul16(s_d_at(d - 1, x), s_d_at(d - 1, x ^ (1 << (d - 1))))
        return cache[key]

    for d in range(K_BITS16):
        norm = inv16(s_d_at(d, 1 << d))
        for b in range(d, K_BITS16):
            s[d, b] = mul16(s_d_at(d, 1 << b), norm)
    return s


def skew16(d: int, gamma: int) -> int:
    s = _skew_basis16()
    acc = 0
    b = d
    g = gamma >> d
    while g:
        if g & 1:
            acc ^= int(s[d, b])
        g >>= 1
        b += 1
    return acc


def fft16(buf: np.ndarray, offset: int) -> np.ndarray:
    """(n, ...) uint16 stacks; mirrors fft() over the 16-bit field."""
    n = buf.shape[0]
    out = buf.copy()
    d = n.bit_length() - 2
    while d >= 0:
        half = 1 << d
        for j in range(0, n, 2 * half):
            w = skew16(d, offset + j)
            x = out[j : j + half]
            y = out[j + half : j + 2 * half]
            if w:
                x ^= mul_vec16(w, y)
            y ^= x
        d -= 1
    return out


def ifft16(buf: np.ndarray, offset: int) -> np.ndarray:
    n = buf.shape[0]
    out = buf.copy()
    for d in range(n.bit_length() - 1):
        half = 1 << d
        for j in range(0, n, 2 * half):
            w = skew16(d, offset + j)
            x = out[j : j + half]
            y = out[j + half : j + 2 * half]
            y ^= x
            if w:
                x ^= mul_vec16(w, y)
    return out


def encode16(data: np.ndarray) -> np.ndarray:
    """(k, ...) uint16 data shards -> (k, ...) recovery shards."""
    k = data.shape[0]
    if k & (k - 1) or not (1 <= k <= ORDER16 // 2):
        raise ValueError(f"k must be a power of two in [1, {ORDER16 // 2}], got {k}")
    if k == 1:
        return data.copy()
    coeffs = ifft16(np.ascontiguousarray(data, dtype=np.uint16), k)
    return fft16(coeffs, 0)


@functools.lru_cache(maxsize=None)
def encode_matrix16(k: int) -> np.ndarray:
    """(k, k) uint16 E16 with recovery = E16 ·gf data (16-bit label space)."""
    eye = np.eye(k, dtype=np.uint16)
    return encode16(eye)


def matmul16(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """16-bit label-space matrix product (host reference for tests/repair)."""
    assert a.ndim == 2 and b.ndim >= 2 and a.shape[1] == b.shape[0]
    out = np.zeros((a.shape[0],) + b.shape[1:], dtype=np.uint16)
    for i in range(a.shape[0]):
        acc = np.zeros(b.shape[1:], dtype=np.uint16)
        for j in range(a.shape[1]):
            if a[i, j]:
                acc ^= mul_vec16(int(a[i, j]), b[j])
        out[i] = acc
    return out


def to_bit_matrix16(m: np.ndarray) -> np.ndarray:
    """(r, c) GF(2^16) label matrix -> (16r, 16c) GF(2) expansion
    (to_bit_matrix's 16-bit twin)."""
    m = m.astype(np.int64)
    log, exp = _tables16()
    powers = (1 << np.arange(16)).astype(np.int64)
    prod = exp[(log[m][:, :, None] + log[powers][None, None, :]) % MODULUS16]
    prod = np.where(m[:, :, None] == 0, 0, prod)
    bits = (prod[:, None, :, :] >> np.arange(16)[None, :, None, None]) & 1
    return bits.reshape(16 * m.shape[0], 16 * m.shape[1]).astype(np.int8)


@functools.lru_cache(maxsize=None)
def bit_matrix16(k: int) -> np.ndarray:
    """(16k, 16k) GF(2) expansion of encode_matrix16(k); with shares
    unpacked as little-endian uint16 symbols this drops into the same MXU
    bit-matmul as the 8-bit code (ops/rs.py picks the matrix by k)."""
    return to_bit_matrix16(encode_matrix16(k))


def _gf_invert16(a: np.ndarray) -> np.ndarray:
    n = a.shape[0]
    m = a.astype(np.uint16).copy()
    out = np.eye(n, dtype=np.uint16)
    for col in range(n):
        piv = col + int(np.argmax(m[col:, col] != 0))
        if m[piv, col] == 0:
            raise np.linalg.LinAlgError(f"singular GF(2^16) matrix at column {col}")
        if piv != col:
            m[[col, piv]] = m[[piv, col]]
            out[[col, piv]] = out[[piv, col]]
        ipv = inv16(int(m[col, col]))
        m[col] = mul_vec16(ipv, m[col])
        out[col] = mul_vec16(ipv, out[col])
        for r in np.nonzero((m[:, col] != 0) & (np.arange(n) != col))[0]:
            f = int(m[r, col])
            m[r] ^= mul_vec16(f, m[col])
            out[r] ^= mul_vec16(f, out[col])
    return out


@functools.lru_cache(maxsize=None)
def generator_matrix16(k: int) -> np.ndarray:
    return np.concatenate([np.eye(k, dtype=np.uint16), encode_matrix16(k)], axis=0)


@functools.lru_cache(maxsize=1024)  # pattern-keyed: bounded (see decode_matrix)
def decode_matrix16(k: int, present: tuple[int, ...]) -> np.ndarray:
    if len(present) != k:
        raise ValueError(f"need exactly {k} present positions")
    return _gf_invert16(generator_matrix16(k)[list(present)])


MAX_K8 = ORDER // 2  # widest square the 8-bit code covers


def uses_gf16(k: int) -> bool:
    """Codec selection: 8-bit up to 256 total shards, 16-bit beyond —
    klauspost reedsolomon's WithLeopardGF threshold.

    ``CELESTIA_GF16_THRESHOLD`` (test/dryrun knob) LOWERS the cutover so the
    16-bit codec can be exercised on meshes/CI at affordable square sizes.
    It is snapshotted at first use (per-k codec caches key on the resolved
    field, so a mid-process env flip cannot make encode and repair disagree)
    and validated: only a power of two in [1, MAX_K8] is accepted — raising
    the cutover past the protocol default could route k>128 into the 8-bit
    code, which cannot represent it."""
    return k > _gf16_threshold()


@functools.lru_cache(maxsize=None)
def _gf16_threshold() -> int:
    import os

    raw = os.environ.get("CELESTIA_GF16_THRESHOLD")
    if raw in (None, ""):
        return MAX_K8
    try:
        t = int(raw)
    except ValueError:
        raise ValueError(
            f"CELESTIA_GF16_THRESHOLD={raw!r} is not an integer"
        ) from None
    if t < 1 or t > MAX_K8 or (t & (t - 1)):
        raise ValueError(
            f"CELESTIA_GF16_THRESHOLD must be a power of two in "
            f"[1, {MAX_K8}], got {t}"
        )
    return t
