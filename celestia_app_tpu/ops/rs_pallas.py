"""Pallas fused RS-extend pass: unpack → GF(2) matmul → pack in ONE kernel.

The XLA formulation (ops/rs.py) materializes the unpacked bit tensor and
the int32 matmul accumulator in HBM between ops; on hardware that showed up
as the pipeline's dominant cost (round-3 --stages: extend ≈ 73 ms of the
84 ms block at k=128). This kernel keeps the whole chain — byte unpack,
(8k,8k)·(8k,tile) MXU matmul (bf16 with exact f32 accumulation of 0/1
products), mod-2, repack — inside VMEM per (row, share-tile) grid cell, so
HBM sees only the packed bytes in and out.

Correctness is pinned by interpret-mode equality with the XLA path in
tests (all fields' logic is identical; gf8 only — k ≤ 128 covers every
protocol-legal square). The bench's schedule calibration probes it as
layout "pallas" and falls back automatically if it fails to compile on
the current toolchain.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from celestia_app_tpu import appconsts
from celestia_app_tpu.ops import leopard

SHARE = appconsts.SHARE_SIZE
S_TILE = 256  # share-byte tile per grid cell (VMEM budget)


def _extend_pass_kernel(k: int):
    def kernel(b_ref, x_ref, o_ref):
        x = x_ref[0].astype(jnp.int32)  # (k, S_TILE) — one row, one tile
        shifts = jnp.arange(8, dtype=jnp.int32)
        # Mosaic has no u8->bf16 cast; widen to i32 for the shift, then go
        # through f32 (both casts lower on the TPU toolchain)
        bits = (
            ((x[:, None, :] >> shifts[None, :, None]) & 1)
            .astype(jnp.float32)
            .astype(jnp.bfloat16)
        )  # (k, 8, S_TILE)
        bits = bits.reshape(8 * k, S_TILE)
        acc = jnp.dot(
            b_ref[...], bits, preferred_element_type=jnp.float32
        )  # (8k, S_TILE); 0/1 products sum ≤ 8k < 2^24: exact
        pb = (acc.astype(jnp.int32) & 1).reshape(k, 8, S_TILE)
        weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
        o_ref[0] = jnp.sum(pb * weights, axis=1).astype(jnp.uint8)

    return kernel


@functools.lru_cache(maxsize=None)
def _pass_call(k: int, interpret: bool):
    """One RS pass: (k, k, 512) data-shard rows -> (k, k, 512) parity."""
    grid = (k, SHARE // S_TILE)
    return pl.pallas_call(
        _extend_pass_kernel(k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * k, 8 * k), lambda r, s: (0, 0)),  # B resident
            pl.BlockSpec((1, k, S_TILE), lambda r, s: (r, 0, s)),
        ],
        out_specs=pl.BlockSpec((1, k, S_TILE), lambda r, s: (r, 0, s)),
        out_shape=jax.ShapeDtypeStruct((k, k, SHARE), jnp.uint8),
        interpret=interpret,
    )


def _interpret_default() -> bool:
    import os

    return os.environ.get("CELESTIA_PALLAS_INTERPRET", "") == "1"


def extend_square_fn(k: int, interpret: bool | None = None):
    """(k, k, 512) ODS -> (2k, 2k, 512) EDS via three fused-pass launches.
    GF(2^8) only (k ≤ 128 — every protocol-legal square). `interpret`
    defaults from CELESTIA_PALLAS_INTERPRET=1 (CPU composition tests)."""
    if leopard.uses_gf16(k):
        raise ValueError("pallas RS path covers the GF(2^8) field (k <= 128)")
    if interpret is None:
        interpret = _interpret_default()
    bit_mat = jnp.asarray(leopard.bit_matrix(k), dtype=jnp.bfloat16)
    call = _pass_call(k, interpret)

    def extend(ods: jax.Array) -> jax.Array:
        q1 = call(bit_mat, ods)
        q2 = jnp.swapaxes(call(bit_mat, jnp.swapaxes(ods, 0, 1)), 0, 1)
        q3 = call(bit_mat, q2)
        top = jnp.concatenate([ods, q1], axis=1)
        bottom = jnp.concatenate([q2, q3], axis=1)
        return jnp.concatenate([top, bottom], axis=0)

    return extend
