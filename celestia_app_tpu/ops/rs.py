"""Device-side 2D Reed-Solomon extension of the data square.

TPU-native formulation of what the reference does with
`rsmt2d.ComputeExtendedDataSquare` (pkg/da/data_availability_header.go:65-75):

    Q1 = RS-extend each row of Q0
    Q2 = RS-extend each column of Q0
    Q3 = RS-extend each row of Q2
    (specs/src/specs/data_structures.md "2D Reed-Solomon Encoding Scheme")

Instead of per-row scalar GF loops, each pass is ONE bit-matrix matmul on the
MXU: bytes are unpacked to bits (LSB-first), parity_bits = (B @ data_bits) & 1
with B = leopard.bit_matrix(k) of shape (8k, 8k) — the reference's Leopard-RS
code (rsmt2d.NewLeoRSCodec) collapsed to a GF(2) matrix, so varied-data
squares produce the reference's exact codewords — batched over all k rows /
columns at once. For k=128 that is 3 matmuls of (1024,1024)x(1024,512) per
batch of 128 — ~0.4 TFLOP total, well inside a v5e chip's budget.

All functions are shape-static per power-of-two k bucket and cached per k.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.ops import leopard

SHARE = appconsts.SHARE_SIZE


def bytes_to_bits(x: jax.Array) -> jax.Array:
    """(..., n, S) uint8 -> (..., 8n, S) int8 bits, LSB-first within each byte."""
    n = x.shape[-2]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> shifts[None, :, None]) & 1
    return bits.reshape(*x.shape[:-2], 8 * n, x.shape[-1]).astype(jnp.int8)


def bits_to_bytes(b: jax.Array) -> jax.Array:
    """(..., 8n, S) int bits -> (..., n, S) uint8, LSB-first within each byte."""
    n = b.shape[-2] // 8
    b = b.reshape(*b.shape[:-2], n, 8, b.shape[-1]).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    return jnp.sum(b * weights, axis=-2).astype(jnp.uint8)


def _gf_mix(bit_mat: jax.Array, x_bits: jax.Array) -> jax.Array:
    """(8k,8k) x (..., 8k, S) -> (..., 8k, S), all arithmetic mod 2 via int matmul."""
    out = jnp.einsum(
        "pq,...qs->...ps", bit_mat, x_bits, preferred_element_type=jnp.int32
    )
    return (out & 1).astype(jnp.int8)


def extend_square_fn(k: int):
    """Return a jittable fn: (k, k, 512) uint8 ODS -> (2k, 2k, 512) uint8 EDS."""
    bit_mat = jnp.asarray(leopard.bit_matrix(k))  # constant folded into the jaxpr

    def extend(ods: jax.Array) -> jax.Array:
        assert ods.shape == (k, k, SHARE), ods.shape
        # Row pass: mix across the share index within each row.
        q0_row_bits = bytes_to_bits(ods)  # (k rows, 8k, S)
        q1 = bits_to_bytes(_gf_mix(bit_mat, q0_row_bits))  # (k, k, S)
        # Column pass: transpose so columns become the mixing axis.
        q0_col_bits = bytes_to_bits(jnp.swapaxes(ods, 0, 1))  # (k cols, 8k, S)
        q2_t = bits_to_bytes(_gf_mix(bit_mat, q0_col_bits))  # (k cols, k, S)
        q2 = jnp.swapaxes(q2_t, 0, 1)  # (k rows of parity, k cols, S)
        # Q3 = row-extend Q2 (== column-extend Q1, data_structures.md:304-310).
        q3 = bits_to_bytes(_gf_mix(bit_mat, bytes_to_bits(q2)))
        top = jnp.concatenate([ods, q1], axis=1)
        bottom = jnp.concatenate([q2, q3], axis=1)
        return jnp.concatenate([top, bottom], axis=0)

    return extend


@functools.lru_cache(maxsize=None)
def jitted_extend(k: int):
    return jax.jit(extend_square_fn(k))


# ---------------------------------------------------------------------------
# Host-side reference + repair (numpy byte-domain; used by tests and the
# light-node reconstruction path — the "any 50% recovers all" MDS property).
# ---------------------------------------------------------------------------


def extend_square_np(ods: np.ndarray) -> np.ndarray:
    """Byte-domain numpy reference of the same extension."""
    k = ods.shape[0]
    assert ods.shape == (k, k, SHARE)
    e = leopard.encode_matrix(k)
    q1 = np.stack([leopard.matmul(e, ods[r]) for r in range(k)])  # rows
    q2 = np.stack(
        [leopard.matmul(e, ods[:, c, :]) for c in range(k)], axis=1
    )  # columns
    q3 = np.stack([leopard.matmul(e, q2[r]) for r in range(k)])
    top = np.concatenate([ods, q1], axis=1)
    bottom = np.concatenate([q2, q3], axis=1)
    return np.concatenate([top, bottom], axis=0)


def repair_axis(symbols: np.ndarray, present: list[int]) -> np.ndarray:
    """Recover all 2k symbols of one row/column from any k known ones.

    `symbols` is (2k, S) with arbitrary content at missing positions;
    `present` lists the >=k known positions (first k are used).
    """
    two_k = symbols.shape[0]
    k = two_k // 2
    if len(present) < k:
        raise ValueError(f"need at least {k} of {two_k} symbols, got {len(present)}")
    use = tuple(sorted(present)[:k])
    m = leopard.decode_matrix(k, use)
    data = leopard.matmul(m, symbols[list(use)])
    parity = leopard.matmul(leopard.encode_matrix(k), data)
    return np.concatenate([data, parity], axis=0)
