"""Device-side 2D Reed-Solomon extension of the data square.

TPU-native formulation of what the reference does with
`rsmt2d.ComputeExtendedDataSquare` (pkg/da/data_availability_header.go:65-75):

    Q1 = RS-extend each row of Q0
    Q2 = RS-extend each column of Q0
    Q3 = RS-extend each row of Q2
    (specs/src/specs/data_structures.md "2D Reed-Solomon Encoding Scheme")

Instead of per-row scalar GF loops, each pass is ONE bit-matrix matmul on the
MXU: bytes are unpacked to bits (LSB-first), parity_bits = (B @ data_bits) & 1
with B = leopard.bit_matrix(k) of shape (8k, 8k) — the Leopard-RS
construction the reference uses (rsmt2d.NewLeoRSCodec) collapsed to a GF(2)
matrix — batched over all k rows / columns at once. Codeword bit-compat for
varied data is argued structurally (see ops/leopard.py "residual risk": the
FFT-output-to-parity ordering and no-bit-reversal conventions are pinned by
construction and by the independent C++ reimplementation + round-trip
decoder, not yet by an external rsmt2d-generated vector). For k=128 that is 3 matmuls of (1024,1024)x(1024,512) per
batch of 128 — ~0.4 TFLOP total, well inside a v5e chip's budget.

All functions are shape-static per power-of-two k bucket and cached per k.
"""

from __future__ import annotations

import collections
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.ops import leopard, pow2_bucket

SHARE = appconsts.SHARE_SIZE


def bytes_to_bits(x: jax.Array) -> jax.Array:
    """(..., n, S) uint8 -> (..., 8n, S) int8 bits, LSB-first within each byte."""
    n = x.shape[-2]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., :, None, :] >> shifts[None, :, None]) & 1
    return bits.reshape(*x.shape[:-2], 8 * n, x.shape[-1]).astype(jnp.int8)


def bits_to_bytes(b: jax.Array) -> jax.Array:
    """(..., 8n, S) int bits -> (..., n, S) uint8, LSB-first within each byte."""
    n = b.shape[-2] // 8
    b = b.reshape(*b.shape[:-2], n, 8, b.shape[-1]).astype(jnp.int32)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    return jnp.sum(b * weights, axis=-2).astype(jnp.uint8)


def _gf_mix(bit_mat: jax.Array, x_bits: jax.Array) -> jax.Array:
    """(8k,8k) x (..., 8k, S) -> (..., 8k, S), all arithmetic mod 2 via int matmul."""
    if bit_mat.dtype == jnp.bfloat16:
        # 0/1 products accumulate exactly in f32 up to 2^24 terms; the dot
        # length is 8k (gf8, ≤1024) or 16k (gf16, ≤524288 at the field's
        # max k=32768) — far below 2^24 — so the mod-2 result is exact
        # while the matmul runs at the MXU's bf16 rate
        out = jnp.einsum(
            "pq,...qs->...ps",
            bit_mat,
            x_bits.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return (out.astype(jnp.int32) & 1).astype(jnp.int8)
    out = jnp.einsum(
        "pq,...qs->...ps", bit_mat, x_bits, preferred_element_type=jnp.int32
    )
    return (out & 1).astype(jnp.int8)


def bytes_to_bits16(x: jax.Array) -> jax.Array:
    """(..., n, D) uint8 -> (..., 16n, D//2) int8 bits of LE uint16 symbols.

    Symbol p of a share is bytes (2p, 2p+1) little-endian; symbol-bit b is
    bit b%8 of byte 2p + b//8. Row 16l+b = bit b of shard l's symbols."""
    n, d = x.shape[-2], x.shape[-1]
    sym = x.reshape(*x.shape[:-2], n, d // 2, 2)
    shifts = jnp.arange(8, dtype=jnp.uint8)
    # (..., n, d/2, byte(2), bit(8)): symbol-bit order is byte0 bits 0..7
    # then byte1 bits 0..7, so flattening (byte, bit) is already LE order
    bits = (sym[..., None] >> shifts) & 1
    bits = bits.reshape(*x.shape[:-2], n, d // 2, 16)
    bits = jnp.swapaxes(bits, -2, -1)  # (..., n, 16, d/2)
    return bits.reshape(*x.shape[:-2], 16 * n, d // 2).astype(jnp.int8)


def bits_to_bytes16(b: jax.Array) -> jax.Array:
    """Inverse of bytes_to_bits16: (..., 16n, D//2) -> (..., n, D) uint8."""
    n = b.shape[-2] // 16
    half = b.shape[-1]
    bits = b.reshape(*b.shape[:-2], n, 16, half).astype(jnp.int32)
    bits = jnp.swapaxes(bits, -2, -1)  # (..., n, half, 16)
    bits = bits.reshape(*b.shape[:-2], n, half, 2, 8)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))
    by = jnp.sum(bits * weights, axis=-1).astype(jnp.uint8)  # (..., n, half, 2)
    return by.reshape(*b.shape[:-2], n, 2 * half)


def _codec(k: int):
    """(bit_matrix, to_bits, from_bits, bits_per_symbol) for the field."""
    if leopard.uses_gf16(k):
        return leopard.bit_matrix16(k), bytes_to_bits16, bits_to_bytes16, 16
    return leopard.bit_matrix(k), bytes_to_bits, bits_to_bytes, 8


def _gf_mix_flat(bit_mat: jax.Array, x_bits: jax.Array) -> jax.Array:
    """Same contraction as _gf_mix but reshaped into ONE large GEMM:
    (8k, 8k) @ (8k, batch*S). A single big matmul keeps the MXU pipeline
    full where `batch` small GEMMs each pay their own tiling overhead —
    the layout the bench's --stages probe compares against the batched
    einsum on hardware (select with CELESTIA_RS_LAYOUT=flat)."""
    lead = x_bits.shape[:-2]
    q, s = x_bits.shape[-2], x_bits.shape[-1]
    flat = x_bits.reshape(-1, q, s)
    b = flat.shape[0]
    x = jnp.transpose(flat, (1, 0, 2)).reshape(q, b * s)
    if bit_mat.dtype == jnp.bfloat16:
        out = jnp.matmul(
            bit_mat, x.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        out = (out.astype(jnp.int32) & 1).astype(jnp.int8)
    else:
        out = jnp.matmul(bit_mat, x, preferred_element_type=jnp.int32)
        out = (out & 1).astype(jnp.int8)
    return jnp.transpose(out.reshape(q, b, s), (1, 0, 2)).reshape(*lead, q, s)


def _rs_layout() -> str:
    import os

    return os.environ.get("CELESTIA_RS_LAYOUT", "batched")


def _rs_dtype() -> str:
    import os

    return os.environ.get("CELESTIA_RS_DTYPE", "int8")


def extend_square_fn(k: int, layout: str | None = None, dtype: str | None = None):
    """Return a jittable fn: (k, k, 512) uint8 ODS -> (2k, 2k, 512) uint8 EDS.

    k <= 128 uses the GF(2^8) code; k >= 256 the GF(2^16) code (leopard16),
    both as one bit-matrix MXU matmul per pass. `layout`/`dtype` (or envs
    CELESTIA_RS_LAYOUT / CELESTIA_RS_DTYPE) pick the matmul schedule:
    "batched" einsum vs "flat" single-GEMM, int8 accumulate-int32 vs bf16
    accumulate-f32 — all four bit-identical, different hardware paths."""
    mat, to_bits, from_bits, sym_bits = _codec(k)
    dtype = dtype or _rs_dtype()
    layout = layout or _rs_layout()
    if dtype not in ("int8", "bf16"):
        raise ValueError(f"RS dtype must be 'int8' or 'bf16', not {dtype!r}")
    if layout not in ("batched", "flat", "fused", "pallas"):
        raise ValueError(
            f"RS layout must be 'batched', 'flat', 'fused' or 'pallas', "
            f"not {layout!r}"
        )
    if layout == "pallas":
        # the Pallas pass is inherently bf16-accumulate-f32 (dtype is
        # implied; an explicit different dtype is a caller error)
        if dtype not in (None, "bf16") and dtype != _rs_dtype():
            raise ValueError("layout='pallas' implies dtype='bf16'")
        if leopard.uses_gf16(k):
            # the Pallas pass covers the 8-bit field; 16-bit squares use
            # the XLA formulation
            layout = "flat"
        else:
            from celestia_app_tpu.ops import rs_pallas

            return rs_pallas.extend_square_fn(k)
    mm_dtype = jnp.bfloat16 if dtype == "bf16" else jnp.int8
    bit_mat = jnp.asarray(mat, dtype=mm_dtype)  # constant folded into the jaxpr
    mix = _gf_mix_flat if layout in ("flat", "fused") else _gf_mix

    def extend(ods: jax.Array) -> jax.Array:
        assert ods.shape == (k, k, SHARE), ods.shape
        # Row pass: mix across the share index within each row.
        q1 = from_bits(mix(bit_mat, to_bits(ods)))  # (k, k, S)
        # Column pass: transpose so columns become the mixing axis.
        col_bits = mix(bit_mat, to_bits(jnp.swapaxes(ods, 0, 1)))
        q2 = jnp.swapaxes(from_bits(col_bits), 0, 1)  # (k parity rows, k cols, S)
        if layout == "fused":
            # Q3 feeds on Q2's BITS directly: the column pass produced
            # (col, sym_bits*parity_row + i, s); a pure bit-space transpose
            # gives the row pass's (row, sym_bits*col + i, s) — eliding a
            # pack+unpack round trip through the byte domain
            sdim = col_bits.shape[-1]
            b4 = col_bits.reshape(k, k, sym_bits, sdim)  # (c, r, i, s)
            q3_in = jnp.transpose(b4, (1, 0, 2, 3)).reshape(k, sym_bits * k, sdim)
            q3 = from_bits(mix(bit_mat, q3_in))
        else:
            # Q3 = row-extend Q2 (== column-extend Q1,
            # data_structures.md:304-310)
            q3 = from_bits(mix(bit_mat, to_bits(q2)))
        top = jnp.concatenate([ods, q1], axis=1)
        bottom = jnp.concatenate([q2, q3], axis=1)
        return jnp.concatenate([top, bottom], axis=0)

    return extend


@functools.lru_cache(maxsize=None)
def jitted_extend(k: int):
    return jax.jit(extend_square_fn(k))


# ---------------------------------------------------------------------------
# Host-side reference + repair (numpy byte-domain; used by tests and the
# light-node reconstruction path — the "any 50% recovers all" MDS property).
# ---------------------------------------------------------------------------


def _encode_axis_np(block: np.ndarray) -> np.ndarray:
    """(k, D) data shards -> (k, D) parity, byte domain, codec by k."""
    k = block.shape[0]
    if leopard.uses_gf16(k):
        u16 = np.ascontiguousarray(block).view("<u2").reshape(k, -1)
        return leopard.encode16(u16).view(np.uint8).reshape(k, -1)
    return leopard.encode(block)


def extend_square_np(ods: np.ndarray) -> np.ndarray:
    """Byte-domain numpy reference of the same extension (FFT-based encode,
    quasilinear: fast enough for k=256 host tests)."""
    k = ods.shape[0]
    assert ods.shape == (k, k, SHARE)
    q1 = np.stack([_encode_axis_np(ods[r]) for r in range(k)])  # rows
    q2 = np.stack(
        [_encode_axis_np(ods[:, c, :]) for c in range(k)], axis=1
    )  # columns
    q3 = np.stack([_encode_axis_np(q2[r]) for r in range(k)])
    top = np.concatenate([ods, q1], axis=1)
    bottom = np.concatenate([q2, q3], axis=1)
    return np.concatenate([top, bottom], axis=0)


# (k, present) -> jitted closure; each entry pins a device bit matrix, so
# the cache is an explicit LRU (not functools.lru_cache) with hit/miss
# telemetry. Build-free consumers (the sweep engine's cached-singleton
# policy, one-shot BEFP verification) use the ATOMIC `repair_axes_get`;
# `repair_axes_cached` is a test-only probe and racy as a policy hook.
_AXES_FN_LOCK = threading.Lock()
_AXES_FN_CACHE: "collections.OrderedDict[tuple, object]" = \
    collections.OrderedDict()
_AXES_FN_MAXSIZE = 64


def repair_axes_cached(k: int, present: tuple[int, ...]) -> bool:
    """True iff `repair_axes_fn(k, present)` would be a cache hit (no
    matrix build, no jit compile). Does not touch LRU order or counters."""
    with _AXES_FN_LOCK:
        return (k, tuple(present)) in _AXES_FN_CACHE


class _RepairAxesRunner:
    """Host wrapper around one pattern's jitted matmul: pads every batch
    to a power-of-two bucket before dispatch (bounding per-pattern
    compiles to log2(2k) shapes instead of one per batch size — jax.jit
    retraces per SHAPE, so a bare closure would recompile for every new
    group width) and records which buckets have actually executed.
    Build-free consumers gate on `compiled_for(n)`: a cached closure that
    has never run this batch bucket would still pay a full XLA compile."""

    __slots__ = ("_run", "_buckets", "_lock", "_k")

    def __init__(self, run, k: int = 0):
        self._run = run
        self._buckets: set[int] = set()
        self._lock = threading.Lock()
        self._k = k  # square size, for the mesh plane's sharding gate

    def compiled_for(self, n: int) -> bool:
        with self._lock:
            return pow2_bucket(n) in self._buckets

    def __call__(self, symbols_batch) -> np.ndarray:
        from celestia_app_tpu.obs import xfer

        batch = np.asarray(symbols_batch)
        n = batch.shape[0]
        bucket = pow2_bucket(n)
        if bucket != n:
            batch = np.concatenate([
                batch,
                np.zeros((bucket - n, *batch.shape[1:]), dtype=batch.dtype),
            ])
        # mesh plane: when active for this square size, split the padded
        # batch over the flat device list BEFORE dispatch — the jitted
        # fused-decode matmul partitions by input sharding, so the
        # repair sweep runs mesh-sharded with identical bytes (the pow2
        # bucket discipline already makes shard extents shape-static)
        dev_batch = batch
        if self._k:
            from celestia_app_tpu.parallel import mesh_engine

            dev_batch = mesh_engine.maybe_shard_batch(batch, self._k)
        if dev_batch is batch:
            dev_batch = xfer.to_device(batch, "ops.repair_dispatch")
        out = xfer.to_host(self._run(dev_batch), "ops.repair_fetch")[:n]
        with self._lock:
            self._buckets.add(bucket)
        return out


def repair_axes_get(k: int, present: tuple[int, ...],
                    batch_size: int | None = None):
    """The cached runner for (k, present), or None — ONE atomic lookup,
    so a caller that must never pay a build/compile (one-shot BEFP
    verification, the sweep engine's cached-singleton policy) cannot race
    an eviction between a peek and a `repair_axes_fn` call. With
    `batch_size`, the runner is returned only if its power-of-two bucket
    has already EXECUTED (compiled): presence in the LRU alone does not
    mean this shape is compiled. A returned runner counts into
    `repair.matrix_cache_hits`; a None is not a miss (nothing is
    built)."""
    from celestia_app_tpu.utils import telemetry

    key = (k, tuple(present))
    with _AXES_FN_LOCK:
        run = _AXES_FN_CACHE.get(key)
        if run is not None:
            _AXES_FN_CACHE.move_to_end(key)
    if run is not None and batch_size is not None \
            and not run.compiled_for(batch_size):
        return None
    if run is not None:
        telemetry.incr("repair.matrix_cache_hits")
    return run


def repair_axes_cache_clear() -> None:
    with _AXES_FN_LOCK:
        _AXES_FN_CACHE.clear()


def repair_axes_fn(k: int, present: tuple[int, ...]):
    """Jitted BATCHED erasure repair for one shared pattern: the
    TPU-native path for the common DA-repair shape, where whole COLUMNS of
    the square are missing and every row therefore has the same erasure
    pattern. Repairing n axes collapses into one MXU bit-matmul over the
    batch — (bits·2k, bits·k) @ (n, bits·k, S) — instead of rsmt2d's
    per-axis heap decodes.

    Returns run((n, 2k, SHARE) uint8, garbage at missing) -> (n, 2k, SHARE)
    full codewords (a `_RepairAxesRunner`: the batch is padded to a
    power-of-two bucket before the jitted dispatch and the result comes
    back as numpy, so per-pattern compiles are bounded at log2(2k)
    shapes). NOTE the output is the full RE-ENCODE from the first k
    sorted present positions: for a consistent codeword it equals
    repair_axis's output bit-for-bit (tests/test_repair.py), but any EXTRA
    present shares are overwritten rather than passed through — a caller
    doing byzantine DETECTION must compare output vs input at present
    positions (da/repair.py's sweep engine does exactly that, falling
    back to the FWHT decoder on mismatch so both engines agree
    bit-for-bit; root-gating alone cannot catch a corrupt present share
    outside the first-k use-set).

    Closures are LRU-cached per (k, pattern); hits and misses count into
    `repair.matrix_cache_hits` / `repair.matrix_cache_misses`."""
    from celestia_app_tpu.utils import telemetry

    key = (k, tuple(present))
    with _AXES_FN_LOCK:
        run = _AXES_FN_CACHE.get(key)
        if run is not None:
            _AXES_FN_CACHE.move_to_end(key)
            telemetry.incr("repair.matrix_cache_hits")
            return run
    telemetry.incr("repair.matrix_cache_misses")
    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("rs.repair_axes", k)
    from celestia_app_tpu.ops import leopard_decode

    two_k = 2 * k
    if len(present) < k:
        raise ValueError(f"need at least {k} of {two_k} symbols")
    use = tuple(sorted(present)[:k])
    labels = leopard_decode.fused_decode_matrix(k, use)
    # one branch assigns the matched (matrix, packers) triple — the bit
    # matrix and the bit packers must always come from the same field
    if leopard.uses_gf16(k):
        bitmat = jnp.asarray(leopard.to_bit_matrix16(labels))
        to_bits, from_bits = bytes_to_bits16, bits_to_bytes16
    else:
        bitmat = jnp.asarray(leopard.to_bit_matrix(labels))
        to_bits, from_bits = bytes_to_bits, bits_to_bytes

    @jax.jit
    def run(symbols_batch: jax.Array) -> jax.Array:
        x = symbols_batch[:, list(use), :]
        return from_bits(_gf_mix(bitmat, to_bits(x))).astype(jnp.uint8)

    runner = _RepairAxesRunner(run, k=k)
    with _AXES_FN_LOCK:
        _AXES_FN_CACHE[key] = runner
        while len(_AXES_FN_CACHE) > _AXES_FN_MAXSIZE:
            _AXES_FN_CACHE.popitem(last=False)
    return runner


def repair_axis(symbols: np.ndarray, present: list[int]) -> np.ndarray:
    """Recover all 2k symbols of one row/column from any k known ones.

    `symbols` is (2k, S) with arbitrary content at missing positions;
    `present` lists the >=k known positions. Uses Leopard's own O(n log n)
    FWHT/error-locator decoder (ops/leopard_decode.py); the O(k^3) matrix-
    inversion path remains as `repair_axis_matrix` for cross-checking.
    """
    from celestia_app_tpu.ops import leopard_decode

    two_k = symbols.shape[0]
    k = two_k // 2
    if len(present) < k:
        raise ValueError(f"need at least {k} of {two_k} symbols, got {len(present)}")
    if leopard.uses_gf16(k):
        sym16 = np.ascontiguousarray(symbols).view("<u2").reshape(2 * k, -1)
        out = leopard_decode.decode16(sym16, list(present))
        return out.view(np.uint8).reshape(2 * k, -1)
    return leopard_decode.decode8(
        np.ascontiguousarray(symbols), list(present)
    )


def repair_axis_matrix(symbols: np.ndarray, present: list[int]) -> np.ndarray:
    """Matrix-inversion repair (independent of the FFT decode path)."""
    two_k = symbols.shape[0]
    k = two_k // 2
    if len(present) < k:
        raise ValueError(f"need at least {k} of {two_k} symbols, got {len(present)}")
    use = tuple(sorted(present)[:k])
    if leopard.uses_gf16(k):
        m = leopard.decode_matrix16(k, use)
        sym16 = np.ascontiguousarray(symbols).view("<u2").reshape(2 * k, -1)
        data16 = leopard.matmul16(m, sym16[list(use)])
        parity16 = leopard.encode16(data16)
        out = np.concatenate([data16, parity16], axis=0)
        return out.view(np.uint8).reshape(2 * k, -1)
    m = leopard.decode_matrix(k, use)
    data = leopard.matmul(m, symbols[list(use)])
    parity = leopard.matmul(leopard.encode_matrix(k), data)
    return np.concatenate([data, parity], axis=0)
