"""RFC-6962-style binary Merkle tree (CometBFT flavor) — device-batched.

Reference parity: go-square/merkle `HashFromByteSlices` as specified in
specs/src/specs/data_structures.md:173-203 — leaf `SHA256(0x00 || d)`, inner
`SHA256(0x01 || l || r)`, empty tree `SHA256("")`, split point for n leaves =
largest power of two < n.

`merkle_root_pow2` is the device fast path for power-of-two leaf counts (the
DAH hash over 4k axis roots); `utils.merkle_host` carries the general
arbitrary-n implementation plus proofs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from celestia_app_tpu.ops import sha256


def leaf_hashes(leaves: jax.Array) -> jax.Array:
    """(N, D) u8 leaves -> (N, 32) leaf-node hashes SHA256(0x00 || leaf)."""
    n = leaves.shape[0]
    prefix = jnp.zeros((n, 1), dtype=jnp.uint8)
    return sha256.sha256(jnp.concatenate([prefix, leaves], axis=1))


def inner_hashes(left: jax.Array, right: jax.Array) -> jax.Array:
    """(N, 32) x (N, 32) -> (N, 32) inner hashes SHA256(0x01 || l || r)."""
    n = left.shape[0]
    prefix = jnp.ones((n, 1), dtype=jnp.uint8)
    return sha256.sha256(jnp.concatenate([prefix, left, right], axis=1))


def merkle_root_pow2(leaves: jax.Array) -> jax.Array:
    """Merkle root of a power-of-two number of equal-length leaves -> (32,) u8.

    With n a power of two the RFC-6962 split rule always bisects, so the tree
    is complete and reduces level-synchronously in log2(n) batched launches.
    """
    n = leaves.shape[0]
    assert n >= 1 and n & (n - 1) == 0, f"leaf count {n} not a power of two"
    nodes = leaf_hashes(leaves)
    while nodes.shape[0] > 1:
        nodes = inner_hashes(nodes[0::2], nodes[1::2])
    return nodes[0]
