"""Leopard's own decoder: the FWHT error-locator path (not matrix inversion).

Implements the decode algorithm of the Lin–Chung–Han FFT erasure code as
realized by Leopard (the construction behind rsmt2d.NewLeoRSCodec,
pkg/appconsts/global_consts.go:92): an O(n log n) erasure decoder that
exercises every structural convention of the encoder — the Cantor-basis
label space, the skew tables, the FFT/IFFT butterflies, the point layout
(recovery at points [0, k), data at [k, 2k)) and the log-domain
Walsh-Hadamard error locator. It is the independent check the round-2
VERDICT asked for: a decode path derived from the published algorithm that
round-trips the encoder across every erasure pattern (tests), rather than
inverting the generator matrix the encoder itself produced.

Algorithm (for original = recovery = k, n = 2k, field order Q):

1. Error locator by FWHT. With LOG the label-space log table (log 0 := 0),
   precompute LogWalsh = FWHT(LOG) over the XOR group of the field. For an
   erasure indicator e (over all Q labels, 1 at each erased POINT),
   ``loc = FWHT(FWHT(e) ∘ LogWalsh)`` gives, at every label y, the log of
   Π_{p erased} (y ⊕ ω_p) — XOR-convolution of logs. The log-of-zero
   sentinel (≡ 0 mod Q−1) makes the locator at an erased point
   automatically SKIP its own factor, i.e. loc[p] = log Λ'(ω_p)-analog.
2. Multiply received evaluations by exp(loc), zero the erasures.
3. IFFT to novel-basis coefficients; take the basis' formal derivative
   (width-block XOR folds); FFT back.
4. Each erased evaluation is work[p] ·gf exp(−loc[p]).
"""

from __future__ import annotations

import functools

import numpy as np

from celestia_app_tpu.ops import leopard


@functools.lru_cache(maxsize=512)
def fused_decode_matrix(k: int, use: tuple[int, ...]) -> np.ndarray:
    """The (2k, k) LABEL-space matrix mapping the k chosen present symbols
    of an erasure pattern to the FULL 2k codeword: G ·gf D, with D the
    decode matrix for the pattern (inverse of the generator rows at `use`)
    and G the generator — decode and re-encode fused into one matmul, the
    precomputed-decode-matrix technique of arXiv:2108.02692 applied to the
    Leopard code. Cached per (k, pattern) so a sweep engine pays the O(k^3)
    inversion once per DISTINCT pattern, then reconstructs every axis
    sharing it with dense GF matmuls (ops/rs.repair_axes_fn lowers this to
    a device bit-matmul). Entries are labels ((2k, k) bytes/uint16s); the
    ~bits²-times-larger GF(2) expansion is built per jitted closure, not
    hoarded per pattern."""
    if len(use) != k or tuple(sorted(use)) != tuple(use):
        raise ValueError(f"use must be k={k} sorted positions, got {use!r}")
    if leopard.uses_gf16(k):
        return leopard.matmul16(
            leopard.generator_matrix16(k), leopard.decode_matrix16(k, use)
        )
    return leopard.matmul(
        leopard.generator_matrix(k), leopard.decode_matrix(k, use)
    )


def _fwht_mod(a: np.ndarray, modulus: int) -> np.ndarray:
    """Walsh–Hadamard transform over the XOR group, values mod `modulus`.

    Butterfly (x, y) → (x+y, x−y) mod m; self-inverse up to a factor the
    log-domain usage cancels (leopard applies it twice the same way)."""
    a = a.astype(np.int64).copy()
    n = a.shape[0]
    h = 1
    while h < n:
        a = a.reshape(-1, 2, h)
        x = a[:, 0, :].copy()
        y = a[:, 1, :].copy()
        a[:, 0, :] = (x + y) % modulus
        a[:, 1, :] = (x - y) % modulus
        a = a.reshape(n)
        h *= 2
    return a


@functools.lru_cache(maxsize=None)
def _log_walsh8() -> np.ndarray:
    log, _ = leopard._tables()
    lw = log.astype(np.int64).copy()
    lw[0] = 0
    return _fwht_mod(lw, leopard.MODULUS)


@functools.lru_cache(maxsize=None)
def _log_walsh16() -> np.ndarray:
    log, _ = leopard._tables16()
    lw = log.astype(np.int64).copy()
    lw[0] = 0
    return _fwht_mod(lw, leopard.MODULUS16)


def _error_locator(
    missing_points: list[int], order: int, modulus: int, log_walsh: np.ndarray
) -> np.ndarray:
    err = np.zeros(order, dtype=np.int64)
    err[missing_points] = 1
    w = _fwht_mod(err, modulus)
    w = (w * log_walsh) % modulus
    return _fwht_mod(w, modulus)


def _formal_derivative(work: np.ndarray) -> np.ndarray:
    """The novel-basis formal derivative: for each i, fold the width-block
    above i down (leopard's VectorXOR pattern)."""
    n = work.shape[0]
    out = work.copy()
    for i in range(1, n):
        width = ((i ^ (i - 1)) + 1) >> 1
        out[i - width : i] ^= out[i : i + width]
    return out


def decode8(codeword: np.ndarray, present: list[int]) -> np.ndarray:
    """Recover the full (2k, ...) GF(2^8) codeword from ≥k known symbols.

    `codeword` is in rsmt2d layout [data(k) | recovery(k)] with arbitrary
    content at missing positions; `present` lists the known positions."""
    two_k = codeword.shape[0]
    k = two_k // 2
    present_set = set(present)
    if len(present_set) < k:
        raise ValueError(f"need at least {k} of {two_k} symbols")
    if len(present_set) == two_k:
        return codeword.copy()
    log, exp = leopard._tables()

    # rsmt2d layout -> leopard point space: recovery at [0,k), data at [k,2k)
    def point_of(pos: int) -> int:
        return pos + k if pos < k else pos - k

    missing = [point_of(p) for p in range(two_k) if p not in present_set]
    loc = _error_locator(missing, leopard.ORDER, leopard.MODULUS, _log_walsh8())

    work = np.zeros_like(codeword)
    for pos in range(two_k):
        if pos in present_set:
            pt = point_of(pos)
            work[pt] = _mul_by_log(codeword[pos], int(loc[pt]), log, exp,
                                   leopard.MODULUS)
    coeffs = leopard.ifft(work, 0)
    deriv = _formal_derivative(coeffs)
    evals = leopard.fft(deriv, 0)

    out = codeword.copy()
    for pos in range(two_k):
        if pos not in present_set:
            pt = point_of(pos)
            inv_log = (leopard.MODULUS - int(loc[pt])) % leopard.MODULUS
            out[pos] = _mul_by_log(evals[pt], inv_log, log, exp, leopard.MODULUS)
    return out


def _mul_by_log(x: np.ndarray, w_log: int, log, exp, modulus: int) -> np.ndarray:
    """x ·gf exp(w_log) elementwise (log-domain scalar times shard vector)."""
    out = exp[(w_log + log[x.astype(np.int64)]) % modulus]
    return np.where(x == 0, 0, out).astype(x.dtype)


def decode16(codeword: np.ndarray, present: list[int]) -> np.ndarray:
    """GF(2^16) variant: (2k, ...) uint16 symbol shards, k up to 32768."""
    two_k = codeword.shape[0]
    k = two_k // 2
    present_set = set(present)
    if len(present_set) < k:
        raise ValueError(f"need at least {k} of {two_k} symbols")
    if len(present_set) == two_k:
        return codeword.copy()
    log, exp = leopard._tables16()

    def point_of(pos: int) -> int:
        return pos + k if pos < k else pos - k

    missing = [point_of(p) for p in range(two_k) if p not in present_set]
    loc = _error_locator(
        missing, leopard.ORDER16, leopard.MODULUS16, _log_walsh16()
    )

    work = np.zeros_like(codeword)
    for pos in range(two_k):
        if pos in present_set:
            pt = point_of(pos)
            work[pt] = _mul_by_log(codeword[pos], int(loc[pt]), log, exp,
                                   leopard.MODULUS16)
    coeffs = leopard.ifft16(work, 0)
    deriv = _formal_derivative(coeffs)
    evals = leopard.fft16(deriv, 0)

    out = codeword.copy()
    for pos in range(two_k):
        if pos not in present_set:
            pt = point_of(pos)
            inv_log = (leopard.MODULUS16 - int(loc[pt])) % leopard.MODULUS16
            out[pos] = _mul_by_log(evals[pt], inv_log, log, exp,
                                   leopard.MODULUS16)
    return out
