"""GF(2^8) arithmetic and Reed-Solomon generator construction (host side).

The TPU-native RS formulation: a systematic code whose k data symbols are the
evaluations of a degree-<k polynomial at field points 0..k-1 and whose k parity
symbols are its evaluations at points k..2k-1 (Lagrange basis). Any k of the 2k
codeword symbols reconstruct the data (MDS), which is the property the DA
scheme requires (specs/src/specs/data_structures.md "Reed-Solomon Erasure
Coding": any 50% of 2k pieces recover the original).

Reference parity note: the reference chains to `rsmt2d.NewLeoRSCodec`
(pkg/appconsts/global_consts.go:92), a Leopard-FFT systematic RS over GF(2^8).
Both codes are systematic RS over GF(2^8); the parity bytes differ because the
evaluation-point bases differ. This framework is self-consistent end-to-end
(encode, decode, roots, proofs all agree); the codec is pluggable behind
`ops.rs` should bit-compatibility with LeoRS codewords be required.

Field: GF(2^8) with the standard primitive polynomial x^8+x^4+x^3+x^2+1
(0x11D), generator 2 — the same field used by klauspost/reedsolomon.

Device mapping: GF(256) multiply-accumulate is GF(2)-linear in the bits of the
input, so the whole row-extension `parity = E · data` becomes one (8k × 8k)
0/1 bit-matrix matmul per row batch — an MXU-friendly int8 matmul followed by
`& 1` (see ops/rs.py).
"""

from __future__ import annotations

import functools

import numpy as np

_POLY = 0x11D


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[:255]  # wraparound so exp[(la+lb)] needs no mod
    return exp, log


EXP, LOG = _build_tables()


def mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP[LOG[a] + LOG[b]])


def inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(EXP[255 - LOG[a]])


def mul_vec(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise GF(256) product of two uint8 arrays."""
    a = a.astype(np.int32)
    b = b.astype(np.int32)
    out = EXP[LOG[a] + LOG[b]]
    return np.where((a == 0) | (b == 0), 0, out).astype(np.uint8)


def matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(256) matrix product (host reference; used for tests and setup)."""
    assert a.ndim == 2 and b.ndim >= 2
    out = np.zeros((a.shape[0],) + b.shape[1:], dtype=np.uint8)
    for i in range(a.shape[0]):
        acc = np.zeros(b.shape[1:], dtype=np.uint8)
        for j in range(a.shape[1]):
            if a[i, j]:
                acc ^= mul_vec(np.full(b.shape[1:], a[i, j], np.uint8), b[j])
        out[i] = acc
    return out


def _lagrange_row(xs: np.ndarray, i: int, x: int) -> int:
    """ℓ_i(x) over evaluation points xs, in GF(256)."""
    num, den = 1, 1
    xi = int(xs[i])
    for j, xj in enumerate(xs):
        if j == i:
            continue
        num = mul(num, x ^ int(xj))
        den = mul(den, xi ^ int(xj))
    return mul(num, inv(den))


@functools.lru_cache(maxsize=None)
def encode_matrix(k: int) -> np.ndarray:
    """(k, k) uint8 matrix E with parity = E ·gf data.

    Data symbols sit at field points 0..k-1; parity j is the interpolating
    polynomial evaluated at point k+j: E[j, i] = ℓ_i(k + j).
    """
    if not (1 <= k <= 128):
        raise ValueError(f"k must be in [1, 128], got {k}")
    xs = np.arange(k, dtype=np.int32)
    e = np.zeros((k, k), dtype=np.uint8)
    for j in range(k):
        for i in range(k):
            e[j, i] = _lagrange_row(xs, i, k + j)
    return e


@functools.lru_cache(maxsize=None)
def decode_matrix(k: int, present: tuple[int, ...]) -> np.ndarray:
    """(k, k) matrix mapping k present codeword symbols -> k data symbols.

    `present` are codeword positions in [0, 2k) (field points), exactly k of
    them. Row d of the result gives data symbol d = Σ M[d, t] · c[present[t]].
    """
    if len(present) != k:
        raise ValueError(f"need exactly {k} present positions")
    xs = np.array(present, dtype=np.int32)
    m = np.zeros((k, k), dtype=np.uint8)
    for d in range(k):  # data point d
        for t in range(k):
            m[d, t] = _lagrange_row(xs, t, d)
    return m


@functools.lru_cache(maxsize=None)
def bit_matrix(k: int) -> np.ndarray:
    """(8k, 8k) 0/1 int8 expansion of encode_matrix(k) over GF(2).

    y = c ·gf x is linear in x's bits: y = XOR_b x_b · (c ·gf 2^b). With bits
    packed LSB-first within each byte, B[8j+i, 8l+b] = bit i of
    mul(E[j,l], 1<<b), and parity_bits = (B @ data_bits) mod 2.
    """
    e = encode_matrix(k)
    powers = (1 << np.arange(8)).astype(np.uint8)  # 2^b
    # prod[j, l, b] = E[j,l] ·gf 2^b
    prod = mul_vec(
        np.broadcast_to(e[:, :, None], (k, k, 8)).copy(),
        np.broadcast_to(powers[None, None, :], (k, k, 8)).copy(),
    ).astype(np.int32)
    # bits[j, i, l, b] = bit i of prod[j, l, b]; row index (j,i) -> 8j+i,
    # column index (l,b) -> 8l+b fall out of the reshape directly.
    bits = (prod[:, None, :, :] >> np.arange(8)[None, :, None, None]) & 1
    return bits.reshape(8 * k, 8 * k).astype(np.int8)
