"""GF(2^8) field arithmetic in the standard polynomial representation.

Field: GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11D),
generator 2 — the field underlying both the reference's Leopard codec and
this framework's tables. These helpers operate on the *standard* (polynomial
coefficient) byte representation; the production RS codec lives in
ops/leopard.py, whose byte labels are related to this representation by the
GF(2)-linear Cantor change of basis and therefore carry their own multiply
tables. Use this module for standard-representation math (e.g. verifying
the Cantor basis recurrence); use ops/leopard.py for anything touching
codewords.
"""


from __future__ import annotations


import numpy as np

_POLY = 0x11D


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.int32)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[:255]  # wraparound so exp[(la+lb)] needs no mod
    return exp, log


EXP, LOG = _build_tables()


def mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP[LOG[a] + LOG[b]])


def inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(EXP[255 - LOG[a]])
