"""Namespaced Merkle Tree reduction on device, batched over many trees.

Implements the NMT node semantics of celestiaorg/nmt as configured by the
reference (pkg/wrapper/nmt_wrapper.go:59-61: sha256, NamespaceIDSize=29,
IgnoreMaxNamespace=true), per specs/src/specs/data_structures.md:236-263:

  leaf:  n_min = n_max = ns;  v = SHA256(0x00 || ns || data)
  inner: n_min = min(l.n_min, r.n_min)
         n_max = PARITY            if l.n_min == PARITY
               = l.n_max           elif r.n_min == PARITY   (IgnoreMaxNamespace)
               = max(l.n_max, r.n_max) otherwise
         v = SHA256(0x01 || l.n_min || l.n_max || l.v || r.n_min || r.n_max || r.v)

The reduction is level-synchronous: every level of every tree in the batch is
hashed in one vectorized SHA-256 launch. Roots serialize as min||max||v (90 B)
— the axis-root format stored in the DataAvailabilityHeader.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.ops import sha256

NS = appconsts.NAMESPACE_SIZE  # 29
PARITY_NS = np.frombuffer(ns_mod.PARITY_NS_RAW, dtype=np.uint8)


def _ns_words(ns_u8: jax.Array) -> jax.Array:
    """(..., 29) u8 -> (..., 8) u32 big-endian words (3 zero bytes appended).

    Equal-length byte strings compare identically under BE-word lexicographic
    order, so 29-byte namespace comparisons become 8 u32 compares.
    """
    pad = jnp.zeros((*ns_u8.shape[:-1], 3), dtype=jnp.uint8)
    padded = jnp.concatenate([ns_u8, pad], axis=-1).astype(jnp.uint32)
    quads = padded.reshape(*ns_u8.shape[:-1], 8, 4)
    be = jnp.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=jnp.uint32)
    return jnp.sum(quads * be, axis=-1, dtype=jnp.uint32)


def ns_less(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a < b over (..., 29) u8 namespaces -> (...) bool."""
    aw, bw = _ns_words(a), _ns_words(b)
    lt = jnp.zeros(aw.shape[:-1], dtype=bool)
    eq = jnp.ones(aw.shape[:-1], dtype=bool)
    for i in range(8):
        lt = lt | (eq & (aw[..., i] < bw[..., i]))
        eq = eq & (aw[..., i] == bw[..., i])
    return lt


def ns_min(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.where(ns_less(a, b)[..., None], a, b)


def ns_max(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.where(ns_less(a, b)[..., None], b, a)


def _is_parity(ns_u8: jax.Array) -> jax.Array:
    return jnp.all(ns_u8 == jnp.asarray(PARITY_NS), axis=-1)


def leaf_nodes(
    leaf_ns: jax.Array, leaf_data: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Hash all leaves: (T, L, 29) ns + (T, L, D) data -> (min, max, v) arrays."""
    t, l, d = leaf_data.shape
    prefix = jnp.zeros((t * l, 1), dtype=jnp.uint8)
    preimage = jnp.concatenate(
        [prefix, leaf_ns.reshape(t * l, NS), leaf_data.reshape(t * l, d)], axis=1
    )
    v = sha256.sha256(preimage).reshape(t, l, 32)
    return leaf_ns, leaf_ns, v


def reduce_level(
    mins: jax.Array, maxs: jax.Array, vs: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Combine adjacent node pairs: (T, L, .) -> (T, L/2, .)."""
    l_min, r_min = mins[:, 0::2], mins[:, 1::2]
    l_max, r_max = maxs[:, 0::2], maxs[:, 1::2]
    l_v, r_v = vs[:, 0::2], vs[:, 1::2]
    t, half = l_v.shape[0], l_v.shape[1]

    prefix = jnp.ones((t * half, 1), dtype=jnp.uint8)
    preimage = jnp.concatenate(
        [
            prefix,
            l_min.reshape(-1, NS), l_max.reshape(-1, NS), l_v.reshape(-1, 32),
            r_min.reshape(-1, NS), r_max.reshape(-1, NS), r_v.reshape(-1, 32),
        ],
        axis=1,
    )  # (T*half, 181)
    v = sha256.sha256(preimage).reshape(t, half, 32)

    node_min = ns_min(l_min, r_min)
    parity = jnp.broadcast_to(jnp.asarray(PARITY_NS), l_max.shape)
    node_max = jnp.where(
        _is_parity(l_min)[..., None],
        parity,
        jnp.where(_is_parity(r_min)[..., None], l_max, ns_max(l_max, r_max)),
    )
    return node_min, node_max, v


def nmt_levels(
    leaf_ns: jax.Array, leaf_data: jax.Array
) -> list[tuple[jax.Array, jax.Array, jax.Array]]:
    """All tree levels, leaves first: [(T, L/2^i, .) for i in 0..log2(L)].

    The level list is what batched proof generation consumes — every node of
    every row tree in one device pass (da/proof_device.py); nmt_roots is the
    tail of it.
    """
    t, l, _ = leaf_data.shape
    assert l & (l - 1) == 0 and l >= 1, f"leaf count {l} not a power of two"
    levels = [leaf_nodes(leaf_ns, leaf_data)]
    while levels[-1][0].shape[1] > 1:
        levels.append(reduce_level(*levels[-1]))
    return levels


def nmt_roots(leaf_ns: jax.Array, leaf_data: jax.Array) -> jax.Array:
    """Batched NMT roots: (T, L, 29) ns + (T, L, D) leaves -> (T, 90) u8 roots.

    L must be a power of two (axis lengths of the extended square always are).
    """
    return roots_from_leaf_nodes(*leaf_nodes(leaf_ns, leaf_data))


def roots_from_leaf_nodes(
    mins: jax.Array, maxs: jax.Array, vs: jax.Array
) -> jax.Array:
    """Inner-node reduction only: precomputed (T, L, .) leaf nodes ->
    (T, 90) roots.

    Exists so callers with shared leaves can hash them ONCE: in an EDS the
    leaf at (r, c) has the identical preimage (0x00 || ns || share) in row
    tree r and column tree c, and leaves dominate the hash work (542-byte
    preimages = 9 compression blocks vs 3 for the 181-byte inner nodes) —
    see da/eds.pipeline_fn, which transposes one leaf-node grid to serve
    both orientations.
    """
    l = vs.shape[1]
    assert l & (l - 1) == 0 and l >= 1, f"leaf count {l} not a power of two"
    level = (mins, maxs, vs)
    while level[0].shape[1] > 1:
        level = reduce_level(*level)
    l_min, l_max, l_v = level
    return jnp.concatenate([l_min[:, 0], l_max[:, 0], l_v[:, 0]], axis=1)
