"""Namespaced Merkle Tree reduction on device, batched over many trees.

Implements the NMT node semantics of celestiaorg/nmt as configured by the
reference (pkg/wrapper/nmt_wrapper.go:59-61: sha256, NamespaceIDSize=29,
IgnoreMaxNamespace=true), per specs/src/specs/data_structures.md:236-263:

  leaf:  n_min = n_max = ns;  v = SHA256(0x00 || ns || data)
  inner: n_min = min(l.n_min, r.n_min)
         n_max = PARITY            if l.n_min == PARITY
               = l.n_max           elif r.n_min == PARITY   (IgnoreMaxNamespace)
               = max(l.n_max, r.n_max) otherwise
         v = SHA256(0x01 || l.n_min || l.n_max || l.v || r.n_min || r.n_max || r.v)

The reduction is level-synchronous: every level of every tree in the batch is
hashed in one vectorized SHA-256 launch. Roots serialize as min||max||v (90 B)
— the axis-root format stored in the DataAvailabilityHeader.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.ops import pow2_bucket, sha256

NS = appconsts.NAMESPACE_SIZE  # 29
PARITY_NS = np.frombuffer(ns_mod.PARITY_NS_RAW, dtype=np.uint8)


def _ns_words(ns_u8: jax.Array) -> jax.Array:
    """(..., 29) u8 -> (..., 8) u32 big-endian words (3 zero bytes appended).

    Equal-length byte strings compare identically under BE-word lexicographic
    order, so 29-byte namespace comparisons become 8 u32 compares.
    """
    pad = jnp.zeros((*ns_u8.shape[:-1], 3), dtype=jnp.uint8)
    padded = jnp.concatenate([ns_u8, pad], axis=-1).astype(jnp.uint32)
    quads = padded.reshape(*ns_u8.shape[:-1], 8, 4)
    be = jnp.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=jnp.uint32)
    return jnp.sum(quads * be, axis=-1, dtype=jnp.uint32)


def ns_less(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a < b over (..., 29) u8 namespaces -> (...) bool."""
    aw, bw = _ns_words(a), _ns_words(b)
    lt = jnp.zeros(aw.shape[:-1], dtype=bool)
    eq = jnp.ones(aw.shape[:-1], dtype=bool)
    for i in range(8):
        lt = lt | (eq & (aw[..., i] < bw[..., i]))
        eq = eq & (aw[..., i] == bw[..., i])
    return lt


def ns_min(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.where(ns_less(a, b)[..., None], a, b)


def ns_max(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.where(ns_less(a, b)[..., None], b, a)


def _is_parity(ns_u8: jax.Array) -> jax.Array:
    return jnp.all(ns_u8 == jnp.asarray(PARITY_NS), axis=-1)


def leaf_nodes(
    leaf_ns: jax.Array, leaf_data: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Hash all leaves: (T, L, 29) ns + (T, L, D) data -> (min, max, v) arrays."""
    t, l, d = leaf_data.shape
    prefix = jnp.zeros((t * l, 1), dtype=jnp.uint8)
    preimage = jnp.concatenate(
        [prefix, leaf_ns.reshape(t * l, NS), leaf_data.reshape(t * l, d)], axis=1
    )
    v = sha256.sha256(preimage).reshape(t, l, 32)
    return leaf_ns, leaf_ns, v


def reduce_level(
    mins: jax.Array, maxs: jax.Array, vs: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Combine adjacent node pairs: (T, L, .) -> (T, L/2, .)."""
    l_min, r_min = mins[:, 0::2], mins[:, 1::2]
    l_max, r_max = maxs[:, 0::2], maxs[:, 1::2]
    l_v, r_v = vs[:, 0::2], vs[:, 1::2]
    t, half = l_v.shape[0], l_v.shape[1]

    prefix = jnp.ones((t * half, 1), dtype=jnp.uint8)
    preimage = jnp.concatenate(
        [
            prefix,
            l_min.reshape(-1, NS), l_max.reshape(-1, NS), l_v.reshape(-1, 32),
            r_min.reshape(-1, NS), r_max.reshape(-1, NS), r_v.reshape(-1, 32),
        ],
        axis=1,
    )  # (T*half, 181)
    v = sha256.sha256(preimage).reshape(t, half, 32)

    node_min = ns_min(l_min, r_min)
    parity = jnp.broadcast_to(jnp.asarray(PARITY_NS), l_max.shape)
    node_max = jnp.where(
        _is_parity(l_min)[..., None],
        parity,
        jnp.where(_is_parity(r_min)[..., None], l_max, ns_max(l_max, r_max)),
    )
    return node_min, node_max, v


def nmt_levels(
    leaf_ns: jax.Array, leaf_data: jax.Array
) -> list[tuple[jax.Array, jax.Array, jax.Array]]:
    """All tree levels, leaves first: [(T, L/2^i, .) for i in 0..log2(L)].

    The level list is what batched proof generation consumes — every node of
    every row tree in one device pass (da/proof_device.py); nmt_roots is the
    tail of it.
    """
    t, l, _ = leaf_data.shape
    assert l & (l - 1) == 0 and l >= 1, f"leaf count {l} not a power of two"
    levels = [leaf_nodes(leaf_ns, leaf_data)]
    while levels[-1][0].shape[1] > 1:
        levels.append(reduce_level(*levels[-1]))
    return levels


def nmt_roots(leaf_ns: jax.Array, leaf_data: jax.Array) -> jax.Array:
    """Batched NMT roots: (T, L, 29) ns + (T, L, D) leaves -> (T, 90) u8 roots.

    L must be a power of two (axis lengths of the extended square always are).
    """
    return roots_from_leaf_nodes(*leaf_nodes(leaf_ns, leaf_data))


def eds_axis_leaf_ns(slabs: jax.Array, indices: jax.Array, k: int) -> jax.Array:
    """Leaf namespaces for a BATCH of EDS axes: (n, 2k, 512) axis slabs +
    (n,) axis indices -> (n, 2k, 29). Axis i's leaf j sits in Q0 (own
    share prefix) iff indices[i] < k and j < k, else PARITY — the
    pkg/wrapper rule (da/fraud.leaf_ns), symmetric under transpose, so the
    same formula serves row slabs (index = row) and column slabs
    (index = column)."""
    in_q0 = (indices[:, None] < k) & (jnp.arange(slabs.shape[1])[None, :] < k)
    parity = jnp.asarray(PARITY_NS)
    return jnp.where(in_q0[..., None], slabs[:, :, :NS], parity)


@functools.lru_cache(maxsize=None)
def jitted_eds_axis_roots(k: int, n: int):
    """Compiled: ((n, 2k, 512) u8 slabs, (n,) i32 indices) -> (n, 90) u8
    NMT roots. One level-synchronous reduction hashes every tree of the
    batch per SHA launch — the repair sweep engine's per-sweep root
    verification and the BEFP fast path both land here. Cached per
    (k, batch-bucket); callers pad n to a power-of-two bucket so sweeps of
    varying width reuse a handful of programs."""
    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("nmt.eds_axis_roots", (k, n))

    def run(slabs: jax.Array, indices: jax.Array) -> jax.Array:
        return nmt_roots(eds_axis_leaf_ns(slabs, indices, k), slabs)

    return jax.jit(run)


# (k, bucket) pairs whose program has actually EXECUTED (jit compiles
# per shape, so lru presence of the factory is not enough); consumers
# that must never stall on a compile gate on eds_axis_roots_compiled
_EXEC_BUCKETS: set[tuple[int, int]] = set()


def eds_axis_roots_compiled(k: int, n: int) -> bool:
    """True iff `eds_axis_roots` for a batch of n axes of a 2k-wide
    square would dispatch an already-compiled program."""
    return (k, pow2_bucket(n)) in _EXEC_BUCKETS


def eds_axis_roots(slabs: np.ndarray, indices, k: int) -> np.ndarray:
    """Host wrapper over `jitted_eds_axis_roots`: pads the batch to a
    power-of-two bucket (pad axes carry index k -> all-parity namespaces,
    discarded on slice) and returns (n, 90) u8 serialized roots —
    byte-identical to utils/nmt_host trees over the same leaves (pinned in
    tests/test_nmt.py / tests/test_repair.py)."""
    slabs = np.ascontiguousarray(slabs, dtype=np.uint8)
    n = slabs.shape[0]
    if n == 0:
        return np.zeros((0, 90), dtype=np.uint8)
    bucket = pow2_bucket(n)
    if bucket != n:
        slabs = np.concatenate(
            [slabs, np.zeros((bucket - n, *slabs.shape[1:]), dtype=np.uint8)]
        )
    idx = np.full(bucket, k, dtype=np.int32)
    idx[:n] = np.asarray(indices, dtype=np.int32)
    # mesh plane: split the padded tree batch over the flat device list
    # when active for this square size — the level-synchronous reduction
    # is per-tree, so jit partitions it cleanly by input sharding and
    # the roots come back bit-identical (tests/test_mesh_plane.py)
    from celestia_app_tpu.obs import xfer
    from celestia_app_tpu.parallel import mesh_engine

    slabs_dev = mesh_engine.maybe_shard_batch(slabs, k)
    idx_dev = mesh_engine.maybe_shard_batch(idx, k)
    if slabs_dev is slabs:
        slabs_dev = xfer.to_device(slabs, "ops.roots_dispatch")
    if idx_dev is idx:
        idx_dev = xfer.to_device(idx, "ops.roots_dispatch")
    out = jitted_eds_axis_roots(k, bucket)(slabs_dev, idx_dev)
    out = xfer.to_host(out, "ops.roots_fetch")[:n]
    _EXEC_BUCKETS.add((k, bucket))
    return out


def roots_from_leaf_nodes(
    mins: jax.Array, maxs: jax.Array, vs: jax.Array
) -> jax.Array:
    """Inner-node reduction only: precomputed (T, L, .) leaf nodes ->
    (T, 90) roots.

    Exists so callers with shared leaves can hash them ONCE: in an EDS the
    leaf at (r, c) has the identical preimage (0x00 || ns || share) in row
    tree r and column tree c, and leaves dominate the hash work (542-byte
    preimages = 9 compression blocks vs 3 for the 181-byte inner nodes) —
    see da/eds.pipeline_fn, which transposes one leaf-node grid to serve
    both orientations.
    """
    l = vs.shape[1]
    assert l & (l - 1) == 0 and l >= 1, f"leaf count {l} not a power of two"
    level = (mins, maxs, vs)
    while level[0].shape[1] > 1:
        level = reduce_level(*level)
    l_min, l_max, l_v = level
    return jnp.concatenate([l_min[:, 0], l_max[:, 0], l_v[:, 0]], axis=1)
