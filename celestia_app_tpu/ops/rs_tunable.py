"""Tunable-rate Reed-Solomon: the arXiv:2201.08261 protocol trade study.

The production 2D-RS scheme (ops/rs.py, ops/leopard.py) is pinned at
rate 1/2 per axis — k data shards always extend to n = 2k. The paper's
point is that the extension factor is a PROTOCOL KNOB, not a law of
nature: stretching an axis to n > 2k raises the fraction an adversary
must withhold (fewer samples to a confidence target, at more encoded
bytes), while n < 2k trades the other way. This module is the
bench-level instrument for that sweep — a systematic RS code with a
*parametrized* (k, n) per axis, n_r x n_c rectangles included — NOT a
registered wire codec: `bench.py --codec` sweeps it next to the three
committed schemes so the knob's economics are measured, not assumed.

Construction: classic GF(2^8) evaluation RS. Data shard j sits at
evaluation point j; the codeword is the degree-(k-1) interpolating
polynomial evaluated at points 0..n-1 (so the code is systematic and
any k of n shards recover all n — MDS). The field caps n at 256
points; sweeps past the cap are skipped and logged, never silently
truncated. Encode/decode matrices are Lagrange-basis evaluations,
host-side table arithmetic; the device engine lifts the fixed (n-k, k)
GF matrix to an (8(n-k), 8k) GF(2) bit-matrix and runs ONE jitted
bit-matmul per axis pass — the exact ops/rs.py playbook, bit-identical
to the host loops (pinned in tests/test_rs_tunable.py).

Engine gating follows ops/ldpc.py: "device" demands jax and raises,
"host" never touches it, "auto" degrades loudly via the
app.device_path_fallback counter.
"""

from __future__ import annotations

import functools
import math

import numpy as np

from celestia_app_tpu import appconsts

# GF(2^8) modulus x^8+x^4+x^3+x^2+1 — the classic RS polynomial (0x11D),
# NOT tied to ops/leopard.py's field: this code is a measurement
# instrument, deliberately independent of the production codec's tables.
GF_POLY = 0x11D
FIELD = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(510, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]  # wraparound: exp[(la+lb) % 255] sans mod
    return exp, log


_EXP, _LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_EXP[int(_LOG[a]) + int(_LOG[b])])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of 0")
    return int(_EXP[255 - int(_LOG[a])])


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m, k) u8 GF matrix x (k, D) u8 shards -> (m, D) u8: the host
    engine's axis pass. Vectorized per data shard (k <= 256 iterations
    of one table-lookup outer product), exact GF(256) arithmetic."""
    m = a.shape[0]
    out = np.zeros((m, b.shape[1]), dtype=np.uint8)
    for j in range(a.shape[1]):
        col = a[:, j]
        row = b[j]
        nz = col != 0
        if not nz.any():
            continue
        prod = _EXP[_LOG[col[nz]][:, None] + _LOG[row][None, :]]
        prod = np.where(row[None, :] == 0, 0, prod)
        out[nz] ^= prod
    return out


def _lagrange_row(xs: list[int], x_eval: int) -> list[int]:
    """Coefficients c_i with p(x_eval) = XOR_i c_i * p(xs[i]) for any
    polynomial of degree < len(xs) — one Lagrange basis evaluation."""
    coeffs = []
    for i, xi in enumerate(xs):
        num, den = 1, 1
        for m, xm in enumerate(xs):
            if m == i:
                continue
            num = gf_mul(num, x_eval ^ xm)
            den = gf_mul(den, xi ^ xm)
        coeffs.append(gf_mul(num, gf_inv(den)))
    return coeffs


def _check_kn(k: int, n: int) -> None:
    if not 1 <= k < n:
        raise ValueError(f"need 1 <= k < n, got k={k} n={n}")
    if n > FIELD:
        raise ValueError(
            f"n={n} exceeds the GF(256) point budget ({FIELD}); "
            f"sweeps must skip (and log) this combination")


@functools.lru_cache(maxsize=256)
def encode_matrix(k: int, n: int) -> np.ndarray:
    """(n-k, k) u8: parity shard r (point k+r) from the k data shards
    (points 0..k-1). Pure function of (k, n) — nothing rides the wire."""
    _check_kn(k, n)
    xs = list(range(k))
    mat = np.array(
        [_lagrange_row(xs, x) for x in range(k, n)], dtype=np.uint8)
    mat.setflags(write=False)
    return mat


@functools.lru_cache(maxsize=256)
def decode_matrix(k: int, n: int, use: tuple[int, ...]) -> np.ndarray:
    """(k, k) u8: the data shards from any k distinct present points
    ``use`` — the MDS any-k-of-n interpolation."""
    _check_kn(k, n)
    if len(use) != k or len(set(use)) != k \
            or not all(0 <= u < n for u in use):
        raise ValueError(f"use must be k={k} distinct points < {n}")
    xs = list(use)
    mat = np.array(
        [_lagrange_row(xs, x) for x in range(k)], dtype=np.uint8)
    mat.setflags(write=False)
    return mat


def _to_bit_matrix(gf_mat: np.ndarray) -> np.ndarray:
    """Lift an (m, k) GF(256) matrix to the (8m, 8k) GF(2) bit-matrix of
    the same linear map under ops/rs.py's LSB-first bit packing:
    bit (8r+a) of the output depends on bit (8j+b) of the input iff bit
    a of gf_mul(M[r, j], 1 << b) is set."""
    m, k = gf_mat.shape
    out = np.zeros((8 * m, 8 * k), dtype=np.int8)
    for r in range(m):
        for j in range(k):
            c = int(gf_mat[r, j])
            if c == 0:
                continue
            for b in range(8):
                prod = gf_mul(c, 1 << b)
                for a in range(8):
                    if (prod >> a) & 1:
                        out[8 * r + a, 8 * j + b] = 1
    return out


def encode_axis_host(data: np.ndarray, n: int) -> np.ndarray:
    """(k, D) u8 data shards -> (n-k, D) parity shards."""
    return gf_matmul(encode_matrix(data.shape[0], n), data)


@functools.lru_cache(maxsize=64)
def jitted_encode_axis(k: int, n: int, shard_bytes: int):
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.obs import jax_profile
    from celestia_app_tpu.ops import rs

    jax_profile.note_compile("rs_tunable.encode", (k, n, shard_bytes))
    bit_mat = jnp.asarray(_to_bit_matrix(np.asarray(encode_matrix(k, n))))

    @jax.jit
    def run(data: jax.Array) -> jax.Array:
        bits = rs.bytes_to_bits(data)
        out = jnp.einsum("pq,qs->ps", bit_mat, bits,
                         preferred_element_type=jnp.int32)
        return rs.bits_to_bytes((out & 1).astype(jnp.int8))

    return run


def encode_axis(data: np.ndarray, n: int,
                engine: str = "auto") -> np.ndarray:
    """Engine-gated parity encode for one axis; both paths
    bit-identical."""
    from celestia_app_tpu.ops import ldpc

    data = np.ascontiguousarray(data, dtype=np.uint8)
    _check_kn(data.shape[0], n)
    if engine == "auto" and not ldpc.auto_wants_device():
        return encode_axis_host(data, n)
    if engine in ("device", "auto"):
        try:
            import jax.numpy as jnp

            run = jitted_encode_axis(data.shape[0], n, data.shape[1])
            return np.asarray(run(jnp.asarray(data)))
        except Exception:
            if engine == "device":
                raise
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("app.device_path_fallback")
    return encode_axis_host(data, n)


def extend_axis(data: np.ndarray, n: int,
                engine: str = "auto") -> np.ndarray:
    """(k, D) -> (n, D): systematic codeword (data verbatim, then
    parity)."""
    return np.concatenate([np.ascontiguousarray(data, dtype=np.uint8),
                           encode_axis(data, n, engine)], axis=0)


def recover_axis(symbols: np.ndarray, present: list[int],
                 k: int) -> np.ndarray:
    """Recover the full n-shard codeword from any >= k known shards
    ((n, D) with garbage at missing positions)."""
    n = symbols.shape[0]
    if len(present) < k:
        raise ValueError(
            f"need at least {k} of {n} shards, got {len(present)}")
    use = tuple(sorted(present)[:k])
    data = gf_matmul(decode_matrix(k, n, use), symbols[list(use)])
    return np.concatenate([data, encode_axis_host(data, n)], axis=0)


def extend_2d(ods: np.ndarray, n_r: int, n_c: int,
              engine: str = "auto") -> np.ndarray:
    """(k, k, S) ODS -> (n_r, n_c, S) rectangle: rows stretched to n_c,
    then every (now n_c-wide) column stretched to n_r — the generalized
    Q1/Q2/Q3 of ops/rs.py, rates decoupled per axis."""
    k = ods.shape[0]
    s = ods.shape[2]
    flat = np.ascontiguousarray(ods, dtype=np.uint8)
    # row pass: mix across the column index within each row
    rows = np.stack([extend_axis(flat[r], n_c, engine)
                     for r in range(k)])  # (k, n_c, S)
    # column pass over the full-width intermediate
    cols = np.stack(
        [extend_axis(rows[:, c, :], n_r, engine)
         for c in range(n_c)], axis=1)  # (n_r, n_c, S)
    assert cols.shape == (n_r, n_c, s)
    return cols


def analytics(k: int, n_r: int, n_c: int) -> dict:
    """The paper's protocol economics for one (k, n_r, n_c) point —
    closed-form, so sweeps are free:

    - rate: useful fraction of encoded bytes, k^2 / (n_r * n_c).
    - min_unrecoverable: the smallest withholding that defeats repair —
      an (n_r-k+1) x (n_c-k+1) sub-rectangle (every surviving row and
      column then has < k shards), the MDS generalization of the rate-
      1/2 (k+1)^2 bound.
    - catch_probability: min_unrecoverable / (n_r * n_c) — one uniform
      sample hits a minimal withholding at this rate.
    - samples_99: draws to 99% confidence at that per-sample catch.
    - commitment_bytes: one 32-byte root per row + column (the NMT
      commitment layout generalized to the rectangle).
    - proof_bytes_model: share + one axis Merkle path, ceil(log2 n_c)
      nodes of (32 + 2*NAMESPACE_SIZE) bytes — a MODEL of the NMT proof
      (the committed schemes' bench numbers are measured; this knob is
      analytic by design and labeled so in the bench output).
    """
    _check_kn(k, n_r)
    _check_kn(k, n_c)
    min_unrec = (n_r - k + 1) * (n_c - k + 1)
    catch = min_unrec / (n_r * n_c)
    node = 32 + 2 * appconsts.NAMESPACE_SIZE
    return {
        "k": k,
        "n_rows": n_r,
        "n_cols": n_c,
        "rate": (k * k) / (n_r * n_c),
        "min_unrecoverable": min_unrec,
        "catch_probability": catch,
        "samples_99": max(
            1, math.ceil(math.log(0.01) / math.log(1.0 - catch))),
        "commitment_bytes": (n_r + n_c) * 32,
        "proof_bytes_model":
            appconsts.SHARE_SIZE + math.ceil(math.log2(n_c)) * node,
    }
