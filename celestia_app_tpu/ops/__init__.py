"""Device kernels. Shared helper: the ONE power-of-two batch-bucket rule
every shape-bucketed jitted program in this package pads to (jax.jit
retraces per shape; bucketing bounds per-program compiles at log2(n_max)
— `ops/rs._RepairAxesRunner`, `ops/nmt.eds_axis_roots`)."""


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (n >= 1)."""
    return 1 << (n - 1).bit_length() if n > 1 else 1
