"""Batched secp256k1 ECDSA verification as vmapped JAX int-limb arithmetic.

The per-user hot path (tx admission) verifies signatures one at a time in
pure Python (chain/crypto.py `_py_verify`) — the GF(256) playbook from
ops/gf256.py and the RS pipeline applies here too: fixed-width limb
arithmetic with no data-dependent control flow, batched into one device
dispatch (the program-optimization framing of arXiv:2108.02692, carried
from GF(256) matmuls to mod-p field math).

Design:

- Field elements are 10 uint64 limbs of 26 bits (libsecp256k1's 10x26
  field layout): products of 30-bit-bounded limbs fit uint64 with room to
  accumulate a full 10-term convolution column, and secp256k1's
  pseudo-Mersenne prime p = 2^256 - 0x1000003D1 reduces by a few shifted
  adds (2^260 ≡ 0x1000003D10 (mod p), so the high convolution columns
  fold straight back into the low ones).
- Point arithmetic uses the COMPLETE addition formulas of Renes-Costello-
  Batina (EUROCRYPT 2016, algorithms 7/9 for a=0) in homogeneous
  projective coordinates: one formula covers generic addition, doubling,
  the identity, and P + (-P) with NO case analysis — branch-free by
  construction, which is what makes the batched path agree bit-for-bit
  with the scalar `_py_verify` reference on adversarial inputs instead of
  only on the happy path. The identity is (0 : 1 : 0).
- u1·G + u2·Q runs as a fixed-window (w=4) Strauss-Shamir double-scalar
  multiplication: 64 shared window steps of 4 doublings, one add from a
  per-lane Q table ([0..15]Q, identity included — the complete formula
  absorbs digit 0), and one add from a precomputed affine G table
  ([0..15]G module constants; digit 0 selected out, as the affine table
  cannot encode the identity).
- The final check avoids any modular inversion: x_affine(R) mod n == r
  iff X == r·Z or X == (r+n)·Z (mod p, when r+n < p), since n < p < 2n.
  R at infinity (Z ≡ 0) verifies False, exactly as `_py_verify`.

One `vmap`/`jit` dispatch verifies a whole batch and returns a bool lane
mask. uint64 requires x64 — enabled through the THREAD-LOCAL
`jax.experimental.enable_x64` scope around trace and dispatch, so the
rest of the process keeps the default 32-bit world. Scalar host work per
signature (pubkey decompression, r/s range checks, s^-1 mod n, window
digits) stays in Python: it is microseconds against the milliseconds of
EC arithmetic the kernel amortizes.

`verify_batch` has exactly `_py_verify`'s semantics per lane (same
parsing, same range checks, no low-S or length policy — those are
`PublicKey.verify` wrapper policy, applied by chain/admission.py). Where
JAX is unavailable the scalar reference runs per lane, so callers always
get `_py_verify`-identical answers.
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

from celestia_app_tpu.chain import crypto as _crypto

_P = _crypto._P
_N = _crypto._N

# -- limb layout -------------------------------------------------------------

N_LIMBS = 10
LIMB_BITS = 26
_M26 = (1 << 26) - 1
_M22 = (1 << 22) - 1
# 2^256 ≡ C (mod p); 2^260 ≡ 16·C = R1·2^26 + R0
_C0, _C1 = 977, 64          # C = 0x1000003D1 = C1·2^26 + C0
_R0, _R1 = 15632, 1024      # 16·C = R1·2^26 + R0


_LIMB_POWS = (np.uint64(1) << np.arange(LIMB_BITS, dtype=np.uint64))


def _to_limbs(x: int) -> np.ndarray:
    bits = np.unpackbits(
        np.frombuffer(x.to_bytes(33, "little"), np.uint8),
        bitorder="little",
    )[: N_LIMBS * LIMB_BITS]
    return bits.reshape(N_LIMBS, LIMB_BITS).astype(np.uint64) @ _LIMB_POWS


def _from_limbs(l) -> int:
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(l))


# p in the redundant "all limbs maximal" form (libsecp fe_negate's P∞):
# subtracting a magnitude-m element from 2(m+1)·P∞ can never borrow.
_P_INF = np.array(
    [0x3FFFC2F, 0x3FFFFBF] + [0x3FFFFFF] * 7 + [0x3FFFFF], dtype=np.uint64
)
_NEG = {m: (2 * (m + 1)) * _P_INF for m in (1, 2, 3)}
# 2^260 - p, for the conditional-subtract in full normalization
_K_COMP = _to_limbs((1 << 260) - _P)

WINDOW = 4
N_WINDOWS = 33            # w=4 windows covering the |k| < 2^132 GLV halves
G_WINDOW = 8
N_G_WINDOWS = 17          # w=8 windows covering the same range


def _digits(u: int, count: int, width: int) -> np.ndarray:
    """`count` `width`-bit windows of a scalar, most significant first."""
    nbytes = (count * width + 7) // 8
    bits = np.unpackbits(
        np.frombuffer(u.to_bytes(nbytes, "little"), np.uint8),
        bitorder="little",
    )[: count * width]
    pows = np.int32(1) << np.arange(width, dtype=np.int32)
    return (bits.reshape(count, width).astype(np.int32) @ pows)[::-1]


# ---------------------------------------------------------------------------
# GLV endomorphism: derived from first principles at import, then verified
# ---------------------------------------------------------------------------
# secp256k1 has j-invariant 0, so x -> beta·x (beta a primitive cube root
# of unity mod p) is an endomorphism acting as scalar multiplication by
# lambda (a cube root of unity mod n): (beta·x, y) = lambda·(x, y). A
# scalar u then splits as u = k1 + k2·lambda (mod n) with |k1|, |k2| on
# the order of sqrt(n), which HALVES the doubling chain of the Strauss
# ladder. Nothing here is a memorized constant: beta/lambda come from
# Fermat exponentiation, the matching (lambda vs lambda^2) is pinned by
# checking the action on G, and the lattice basis comes from the
# classic extended-Euclid construction (Guide to ECC, alg 3.74).


def _derive_glv() -> tuple[int, int]:
    def cube_root_of_unity(m: int) -> int:
        g = 2
        while True:
            w = pow(g, (m - 1) // 3, m)
            if w != 1:
                return w
            g += 1

    beta = cube_root_of_unity(_P)
    lam = cube_root_of_unity(_N)
    gx, gy = _crypto._GX, _crypto._GY
    for lam_c in (lam, pow(lam, 2, _N)):
        pt = _crypto._to_affine(_crypto._jac_mult(_crypto._G, lam_c))
        for beta_c in (beta, pow(beta, 2, _P)):
            if pt == (beta_c * gx % _P, gy):
                return lam_c, beta_c
    raise AssertionError("GLV cube-root pairing failed to verify on G")


_LAMBDA, _BETA = _derive_glv()


def _glv_basis() -> tuple[int, int, int, int]:
    """Two short lattice vectors (a, b) with a + b·lambda ≡ 0 (mod n)."""
    import math

    sq = math.isqrt(_N)
    rows = [(_N, 0), (_LAMBDA, 1)]
    while rows[-1][0] >= sq:
        q = rows[-2][0] // rows[-1][0]
        rows.append((rows[-2][0] - q * rows[-1][0],
                     rows[-2][1] - q * rows[-1][1]))
    a1, b1 = rows[-1][0], -rows[-1][1]
    q = rows[-2][0] // rows[-1][0]
    nxt = (rows[-2][0] - q * rows[-1][0], rows[-2][1] - q * rows[-1][1])
    cand = [(rows[-2][0], -rows[-2][1]), (nxt[0], -nxt[1])]
    a2, b2 = min(cand, key=lambda v: v[0] * v[0] + v[1] * v[1])
    for a, b in ((a1, b1), (a2, b2)):
        if (a + b * _LAMBDA) % _N:
            raise AssertionError("GLV basis vector not in the lattice")
    return a1, b1, a2, b2


_A1, _B1, _A2, _B2 = _glv_basis()


def _glv_split(u: int) -> tuple[int, int]:
    """u ≡ k1 + k2·lambda (mod n) with |k1|, |k2| ~ sqrt(n). The caller
    re-checks the congruence and the 2^132 bound per lane (falling back
    to the scalar path on any violation, which never fires in practice)."""
    c1 = (2 * _B2 * u + _N) // (2 * _N)     # round(b2·u / n)
    c2 = (-2 * _B1 * u + _N) // (2 * _N)    # round(-b1·u / n)
    k1 = u - c1 * _A1 - c2 * _A2
    k2 = -c1 * _B1 - c2 * _B2
    return k1, k2


# ---------------------------------------------------------------------------
# precomputed G tables (lazy: ~0.5 s of host point arithmetic, built on
# first use and kept for the process lifetime)
# ---------------------------------------------------------------------------
# For the G side both GLV halves use PER-POSITION w=8 tables, so G adds
# never need the shared doubling chain: entry (j, s, d) is ±d·2^(8j)·B
# for base B in {G, lambda·G}, with s selecting the negated-y mirror
# (negative GLV halves flip the point, not the digit).


@functools.lru_cache(maxsize=None)
def _g_pos_tables() -> np.ndarray:
    """(2, 17, 512, 2, 10): [base][position][sign·256 + digit][x, y]."""
    out = np.zeros((2, N_G_WINDOWS, 2 * 256, 2, N_LIMBS), dtype=np.uint64)
    for bi, base_scalar in enumerate((1, _LAMBDA)):
        base = _crypto._jac_mult(_crypto._G, base_scalar)
        for j in range(N_G_WINDOWS):
            acc = (0, 0, 0)
            for d in range(1, 256):
                acc = _crypto._jac_add(acc, base)
                x, y = _crypto._to_affine(acc)
                out[bi, j, d, 0] = _to_limbs(x)
                out[bi, j, d, 1] = _to_limbs(y)
                out[bi, j, 256 + d, 0] = out[bi, j, d, 0]
                out[bi, j, 256 + d, 1] = _to_limbs(_P - y)
            for _ in range(G_WINDOW):
                base = _crypto._jac_double(base)
    return out


def available() -> bool:
    try:
        import jax  # noqa: F401

        return True
    except ImportError:
        return False


# ---------------------------------------------------------------------------
# the kernel (everything below `_build` traces under enable_x64)
# ---------------------------------------------------------------------------
# Magnitude discipline (all bounds static, checked in comments):
#   fe_mul / fe_sub / fe_mul21 outputs are WEAK: limbs < 2^26 (+1 ulp on
#   the ripple tail), top limb < 2^22 + 1, value < 2p.  fe_add outputs
#   carry the summed magnitude.  Every multiplication input stays below
#   2^30 per limb, so convolution columns stay below 10·2^60 < 2^64.


def _kernel_fns():
    import jax
    import jax.numpy as jnp

    u64 = jnp.uint64

    def _shift1(c):
        """One limb up along the limb axis: [0, c0, ..., c_{n-2}]."""
        z = jnp.zeros_like(c[..., :1])
        return jnp.concatenate([z, c[..., :-1]], axis=-1)

    def _pass(x):
        """One parallel carry pass that first folds the top limb's
        >= 2^256 bits through C (so no overflow bit is ever dropped),
        then masks and shifts every limb's carry up one slot."""
        hi = x[..., 9] >> 22                 # all bits of weight >= 2^256
        x = x.at[..., 9].set(x[..., 9] & u64(_M22))
        x = x.at[..., 0].add(hi * u64(_C0))
        x = x.at[..., 1].add(hi * u64(_C1))
        return (x & u64(_M26)) + _shift1(x >> 26)

    # Bound discipline (all static, comments carry the proofs):
    #   M1   = _pass(_pass(·)) output: limbs < 2^26 + 2^9, top < 2^22 + 1
    #   sums of ≤ 3 M1 values stay subtractable through _NEG[3]
    #   LAZY = fe_sub output: limbs < 2^29.4 (no normalization at all)
    #   every fe_mul operand is ≤ LAZY + M1 sums < 2^29.6, so 10-term
    #   convolution columns stay < 10 · 2^59.2 < 2^62.6 < 2^64.
    neg3 = jnp.asarray(_NEG[3], dtype=jnp.uint64)

    def fe_mul(a, b):
        """Schoolbook convolution + pseudo-Mersenne fold; M1 output.

        Operands may be lazy (limbs < 2^30): column sums < 2^63. Shapes
        are (..., 10); independent multiplications are STACKED along the
        leading axis so one call amortizes the whole carry machinery."""
        cols = jnp.zeros(a.shape[:-1] + (2 * N_LIMBS,), jnp.uint64)
        for i in range(N_LIMBS):
            cols = cols.at[..., i : i + N_LIMBS].add(a[..., i : i + 1] * b)
        # one carry pass caps columns at 2^26 + 2^37, small enough for
        # the R0/R1 fold multipliers to stay under 2^64
        cols = (cols & u64(_M26)) + _shift1(cols >> 26)
        h = cols[..., N_LIMBS:]              # weights 2^260 · 2^26j
        l = (cols[..., :N_LIMBS] + h * u64(_R0) + _shift1(h) * u64(_R1))
        spill = h[..., 9] * u64(_R1)         # weight 2^260 again
        l = l.at[..., 0].add(spill * u64(_R0))  # < 2^61
        l = l.at[..., 1].add(spill * u64(_R1))
        return _pass(_pass(l))

    def fe_sub(a, b):
        """a - b (mod p), b any sum of ≤ 3 M1 values; LAZY output
        (limbs < 2^29.4) — safe directly as a fe_mul operand."""
        return a + (neg3 - b)

    def fe_mul21(a):
        """3b = 21 scaling (b = 7 for secp256k1); M1 output."""
        return _pass(a * u64(21))

    def fe_norm(x):
        """Full canonical (UNIQUE-limb) form: sequential carry
        propagation to strict 26-bit limbs (folding BOTH the top limb's
        >= 2^256 bits and the chain's 2^260 carry-out each pass), then
        one conditional subtract of p. Equality tests compare only
        these. Accepts any lazy element; shape (B, 10)."""
        for _ in range(3):                    # value < 2^256 after pass 3
            carry = jnp.zeros_like(x[..., 0])
            limbs = []
            for k in range(N_LIMBS):
                v = x[..., k] + carry
                limbs.append(v & u64(_M26))
                carry = v >> 26               # final: weight 2^260
            hi = limbs[9] >> 22               # weight 2^256
            limbs[9] = limbs[9] & u64(_M22)
            limbs[0] = limbs[0] + carry * u64(_R0) + hi * u64(_C0)
            limbs[1] = limbs[1] + carry * u64(_R1) + hi * u64(_C1)
            x = jnp.stack(limbs, axis=-1)
        carry = jnp.zeros_like(x[..., 0])
        d = []
        for k in range(N_LIMBS):
            v = x[..., k] + k_comp[k] + carry
            d.append(v & u64(_M26))
            carry = v >> 26
        ge = (carry > 0)[..., None]           # 1 iff x >= p
        return jnp.where(ge, jnp.stack(d, axis=-1), x)

    k_comp = jnp.asarray(_K_COMP, dtype=jnp.uint64)

    # -- complete point arithmetic (Renes-Costello-Batina, a=0, b3=21) ----
    # Points are (X, Y, Z) triples of (B, 10) limb arrays. The 12M of the
    # complete add and the 8M of the doubling run as TWO / THREE stacked
    # fe_mul calls: the formulas' independent products concatenate along
    # the lane axis, so the carry/fold machinery amortizes 6x.

    def _mul_stack(parts_a, parts_b):
        a = jnp.concatenate(parts_a, axis=0)
        b = jnp.concatenate(parts_b, axis=0)
        m = fe_mul(a, b)
        n = parts_a[0].shape[0]
        return [m[i * n : (i + 1) * n] for i in range(len(parts_a))]

    def pt_add(p, q):
        """Algorithm 7: complete addition, any P/Q including identity."""
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        t0, t1, t2, ta, tb, tc = _mul_stack(
            [X1, Y1, Z1, X1 + Y1, Y1 + Z1, X1 + Z1],
            [X2, Y2, Z2, X2 + Y2, Y2 + Z2, X2 + Z2],
        )
        t3 = fe_sub(ta, t0 + t1)              # X1Y2 + X2Y1
        t4 = fe_sub(tb, t1 + t2)              # Y1Z2 + Y2Z1
        ty = fe_sub(tc, t0 + t2)              # X1Z2 + X2Z1
        t0_3 = (t0 + t0) + t0                 # 3·X1X2
        t2b = fe_mul21(t2)                    # 3b·Z1Z2
        z3p = t1 + t2b                        # Y1Y2 + 3bZ1Z2
        t1m = fe_sub(t1, t2b)                 # Y1Y2 - 3bZ1Z2
        y3b = fe_mul21(ty)                    # 3b·(X1Z2 + X2Z1)
        m0, m1, m2, m3, m4, m5 = _mul_stack(
            [t4, t3, y3b, t1m, t0_3, z3p],
            [y3b, t1m, t0_3, z3p, t3, t4],
        )
        X3 = fe_sub(m1, m0)                   # t3·t1m - t4·y3b
        Y3 = m3 + m2                          # t1m·z3p + y3b·t0_3
        Z3 = m5 + m4                          # z3p·t4 + t0_3·t3
        return (X3, Y3, Z3)

    def pt_dbl(p):
        """Algorithm 9: complete doubling (identity doubles to identity)."""
        X, Y, Z = p
        t0, t1, t2 = _mul_stack([Y, Y, Z], [Y, Z, Z])
        z3a = (t0 + t0) + (t0 + t0)
        z3a = z3a + z3a                       # 8·Y²
        t2b = fe_mul21(t2)                    # 3b·Z²
        x3, z3, txy = _mul_stack([t2b, t1, X], [z3a, z3a, Y])
        y3p = t0 + t2b
        t0s = fe_sub(t0, (t2b + t2b) + t2b)   # Y² - 9bZ²
        ma, mb = _mul_stack([t0s, t0s], [y3p, txy])
        Y3 = x3 + ma                          # t2b·z3a + t0s·y3p
        X3 = mb + mb                          # 2·t0s·txy
        return (X3, Y3, z3)

    beta_c = jnp.asarray(_to_limbs(_BETA), dtype=jnp.uint64)

    def verify_kernel(qx, qy, ydiff, kq1d, kq2d, kg1d, kg2d,
                      sg1, sg2, r_l, r2_l, has_r2):
        """The batched verifier: (B,...) arrays in, (B,) bool mask out.

        Computes u2·Q = |k1|·(±Q) + |k2|·(±λQ) over the shared 33-window
        doubling chain (the GLV halves), then folds in the G side from
        the per-position tables (no doubles needed there), and checks
        the x-coordinate equation projectively."""
        n = qx.shape[0]
        zero = jnp.zeros((n, N_LIMBS), jnp.uint64)
        one = zero.at[:, 0].set(u64(1))
        ident = (zero, one, zero)
        q = (qx, qy, one)
        # per-lane Q table: [0..15]·(±Q); entry 0 is the identity, which
        # the complete formula handles natively (no digit mask needed)
        tab = [ident, q]
        for d in range(2, 16):
            tab.append(pt_dbl(tab[d // 2]) if d % 2 == 0
                       else pt_add(tab[d - 1], q))
        qtab = tuple(
            jnp.stack([t[i] for t in tab], axis=1) for i in range(3)
        )  # 3 × (B, 16, 10)
        # λQ table via the endomorphism applied ENTRY-WISE: φ(d·Q) =
        # d·λQ = (β·X : ±Y : Z) — one stacked β·X multiply, a sign
        # select on Y when the two GLV halves disagree in sign, Z shared.
        lx = fe_mul(qtab[0].reshape(n * 16, N_LIMBS), beta_c)
        ly = jnp.where(ydiff[:, None, None], _pass(neg3 - qtab[1]), qtab[1])
        ltab = (lx.reshape(n, 16, N_LIMBS), ly, qtab[2])

        def gather(tab3, d):
            idx = d[:, None, None]
            return tuple(
                jnp.take_along_axis(c, idx, axis=1)[:, 0] for c in tab3
            )

        def body(i, acc):
            acc = jax.lax.fori_loop(0, WINDOW, lambda _j, a: pt_dbl(a), acc)
            d1 = jax.lax.dynamic_slice_in_dim(kq1d, i, 1, axis=1)[:, 0]
            acc = pt_add(acc, gather(qtab, d1))
            d2 = jax.lax.dynamic_slice_in_dim(kq2d, i, 1, axis=1)[:, 0]
            acc = pt_add(acc, gather(ltab, d2))
            return acc

        acc = jax.lax.fori_loop(0, N_WINDOWS, body, ident)

        # G side: affine entries from the (2, 17, 512, ...) const tables,
        # flattened so one take() resolves [base][position][sign·256+d]
        gtab = jnp.asarray(
            _g_pos_tables().reshape(2 * N_G_WINDOWS * 512, 2, N_LIMBS),
            dtype=jnp.uint64,
        )
        sbase1 = sg1.astype(jnp.int32) * 256
        sbase2 = sg2.astype(jnp.int32) * 256

        def g_body(j, acc):
            def one_add(acc, base_off, sbase, dig):
                d = jax.lax.dynamic_slice_in_dim(dig, j, 1, axis=1)[:, 0]
                idx = base_off + j * 512 + sbase + d
                tg = jnp.take(gtab, idx, axis=0)   # (B, 2, 10)
                added = pt_add(acc, (tg[:, 0], tg[:, 1], one))
                # affine tables cannot encode the identity: digit 0 keeps acc
                keep = (d == 0)[:, None]
                return tuple(
                    jnp.where(keep, a, b) for a, b in zip(acc, added)
                )

            acc = one_add(acc, 0, sbase1, kg1d)
            acc = one_add(acc, N_G_WINDOWS * 512, sbase2, kg2d)
            return acc

        X, Y, Z = jax.lax.fori_loop(0, N_G_WINDOWS, g_body, acc)

        # x_affine mod n == r  ⇔  X == r·Z or X == (r+n)·Z (mod p); the
        # identity (Z ≡ 0) verifies False, as in _py_verify
        rz, r2z = _mul_stack([r_l, r2_l], [Z, Z])
        xn = fe_norm(X)
        eq1 = jnp.all(xn == fe_norm(rz), axis=-1)
        eq2 = jnp.all(xn == fe_norm(r2z), axis=-1) & has_r2
        z_zero = jnp.all(fe_norm(Z) == u64(0), axis=-1)
        return (~z_zero) & (eq1 | eq2)

    return verify_kernel


@functools.lru_cache(maxsize=None)
def jitted_verify(n: int):
    """Compiled batch verifier for one padded lane count (bucketed so the
    jit cache stays bounded). Instrumented like every jitted factory
    (obs/jax_profile): the cache miss counts one ``jax.compilations``.

    On the CPU backend the program is AOT-compiled with the thunk
    runtime disabled — measured ~25% faster on this kernel's long
    elementwise chains — as a PER-PROGRAM compiler option, so the
    process-wide XLA flags (and the tuned RS/NMT pipelines) are
    untouched. Any failure falls back to the plain jitted path."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("secp256k1.verify", n)
    fn = jax.jit(_kernel_fns())
    try:
        if jax.devices()[0].platform == "cpu":
            u64 = jnp.uint64
            i32 = jnp.int32
            s = jax.ShapeDtypeStruct
            shapes = (
                s((n, N_LIMBS), u64), s((n, N_LIMBS), u64),
                s((n,), jnp.bool_),
                s((n, N_WINDOWS), i32), s((n, N_WINDOWS), i32),
                s((n, N_G_WINDOWS), i32), s((n, N_G_WINDOWS), i32),
                s((n,), i32), s((n,), i32),
                s((n, N_LIMBS), u64), s((n, N_LIMBS), u64),
                s((n,), jnp.bool_),
            )
            with jax.experimental.enable_x64():
                fn = fn.lower(*shapes).compile(
                    compiler_options={"xla_cpu_use_thunk_runtime": False}
                )
    except Exception as e:
        from celestia_app_tpu import obs
        from celestia_app_tpu.utils import telemetry

        telemetry.incr("secp256k1.aot_compile_fallbacks")
        obs.get_logger("ops.secp256k1").warning(
            "AOT compile with scoped compiler options failed; "
            "using the default jit path", err=e,
        )
    return jax_profile.instrument(f"secp256k1.verify[{n}]", fn)


from celestia_app_tpu.obs import jax_profile as _jax_profile  # noqa: E402

_jax_profile.register_cache(jitted_verify)
del _jax_profile


# ---------------------------------------------------------------------------
# host orchestration
# ---------------------------------------------------------------------------

_MIN_BUCKET = 32
# 512 lanes keeps the stacked (3072, 20) uint64 intermediates inside L2
# on the CPU backend (measured fastest: larger dispatches regress)
MAX_DISPATCH = 512


def _bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


_SLOW = object()  # sentinel: decomposition irregularity -> scalar fallback


def _prep(pubkey: bytes, signature: bytes, message: bytes):
    """The scalar prefix of _py_verify: parse, range-check, compute
    (u1, u2) = (z/s, r/s) mod n, and GLV-split both scalars. None =
    verifies False with no EC work; _SLOW = verify on the scalar path."""
    q = _crypto._decompress(pubkey)
    if q is None:
        return None
    r = int.from_bytes(signature[:32], "big")
    s = int.from_bytes(signature[32:], "big")
    if not (1 <= r < _N and 1 <= s < _N):
        return None
    z = int.from_bytes(hashlib.sha256(message).digest(), "big") % _N
    w = pow(s, -1, _N)
    u1, u2 = z * w % _N, r * w % _N
    k1a, k2a = _glv_split(u1)
    k1b, k2b = _glv_split(u2)
    for u, k1, k2 in ((u1, k1a, k2a), (u2, k1b, k2b)):
        if (k1 + k2 * _LAMBDA - u) % _N or max(
            abs(k1), abs(k2)
        ).bit_length() > WINDOW * N_WINDOWS:
            return _SLOW  # never expected; the scalar path stays correct
    # Q side rides the doubling chain: base point sign-adjusted for k1b,
    # the λQ table's Y sign-flipped on device when k2b's sign differs
    qy = q[1] if k1b >= 0 else _P - q[1]
    return (
        q[0], qy, (k2b < 0) != (k1b < 0),
        abs(k1b), abs(k2b), abs(k1a), abs(k2a),
        int(k1a < 0), int(k2a < 0), r,
    )


def verify_batch(items, backend: str = "auto") -> np.ndarray:
    """Verify a batch of (pubkey33, signature, message) triples in one
    device dispatch per MAX_DISPATCH chunk; returns a bool lane mask with
    exactly `_py_verify`'s per-item semantics. backend: "auto" (device
    when JAX imports, else scalar) | "device" | "scalar"."""
    out = np.zeros(len(items), dtype=bool)
    if not items:
        return out
    use_device = backend == "device" or (backend == "auto" and available())
    if not use_device:
        for i, (pk, sig, msg) in enumerate(items):
            out[i] = _crypto._py_verify(pk, sig, msg)
        return out

    preps = [_prep(pk, sig, msg) for pk, sig, msg in items]
    lanes = []
    for i, p in enumerate(preps):
        if p is _SLOW:
            out[i] = _crypto._py_verify(*items[i])
        elif p is not None:
            lanes.append(i)
    for start in range(0, len(lanes), MAX_DISPATCH):
        chunk = lanes[start : start + MAX_DISPATCH]
        out[chunk] = _dispatch([preps[i] for i in chunk])
    return out


def _dispatch(preps) -> np.ndarray:
    import jax

    n = len(preps)
    b = _bucket(n)
    qx = np.zeros((b, N_LIMBS), np.uint64)
    qy = np.zeros((b, N_LIMBS), np.uint64)
    ydiff = np.zeros((b,), bool)
    kq1d = np.zeros((b, N_WINDOWS), np.int32)
    kq2d = np.zeros((b, N_WINDOWS), np.int32)
    kg1d = np.zeros((b, N_G_WINDOWS), np.int32)
    kg2d = np.zeros((b, N_G_WINDOWS), np.int32)
    sg1 = np.zeros((b,), np.int32)
    sg2 = np.zeros((b,), np.int32)
    r_l = np.zeros((b, N_LIMBS), np.uint64)
    r2_l = np.zeros((b, N_LIMBS), np.uint64)
    has_r2 = np.zeros((b,), bool)
    for i, (x, y, yd, k1b, k2b, k1a, k2a, s1, s2, r) in enumerate(preps):
        qx[i] = _to_limbs(x)
        qy[i] = _to_limbs(y)
        ydiff[i] = yd
        kq1d[i] = _digits(k1b, N_WINDOWS, WINDOW)
        kq2d[i] = _digits(k2b, N_WINDOWS, WINDOW)
        # G digits run LSB-first: position table j carries d·2^(8j)·base
        kg1d[i] = _digits(k1a, N_G_WINDOWS, G_WINDOW)[::-1]
        kg2d[i] = _digits(k2a, N_G_WINDOWS, G_WINDOW)[::-1]
        sg1[i] = s1
        sg2[i] = s2
        r_l[i] = _to_limbs(r)
        if r + _N < _P:
            r2_l[i] = _to_limbs(r + _N)
            has_r2[i] = True
    with jax.experimental.enable_x64():
        mask = np.asarray(
            jitted_verify(b)(qx, qy, ydiff, kq1d, kq2d, kg1d, kg2d,
                             sg1, sg2, r_l, r2_l, has_r2)
        )
    return mask[:n]
