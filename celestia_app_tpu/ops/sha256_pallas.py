"""Pallas TPU kernel for batched SHA-256 (BASELINE.md config 2).

The jnp path in ops/sha256.py materializes the 64-entry message schedule as a
(64, N) array and round-trips it through fori_loop dynamic updates — XLA keeps
that buffer live across all 112 sequential steps, so for the ~1.6M-compression
workload of a 128x128 block the VPU stalls on VMEM/HBM traffic instead of
doing register arithmetic. This kernel is the classic register formulation:

- the message schedule lives in a rolling window of 16 (8, 128) u32 vregs
  (slot i%16 is rewritten with w[i+16] right after round i consumes it),
- the working state is 8 more vregs, fully unrolled over the 64 rounds,
- each grid step hashes a 1024-message lane tile; the multi-block loop over a
  message's 64-byte blocks is a fori_loop with dynamic leading-dim reads.

Input layout is word-major — (total_words, n_tiles, 8, 128) u32, i.e. word w
of message m lives at [w, m//1024, (m%1024)//128, m%128] — so every round's
w[i] read is one contiguous vreg, not a gather. HBM traffic is exactly
"read each padded block once, write 32 bytes per digest".

Reference workload shape: NMT leaves are 542-byte preimages (9 blocks),
inner nodes 181 bytes (3 blocks) — pkg/wrapper/nmt_wrapper.go hashing via
crypto/sha256; see SURVEY.md §7.2.2.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from celestia_app_tpu.ops.sha256_consts import H0_WORDS, K_WORDS

LANES = 128
SUBLANES = 8
TILE = LANES * SUBLANES  # messages per grid step


def _rotr(x: jax.Array, n: int) -> jax.Array:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _kernel(nblocks: int, x_ref, o_ref):
    """x_ref: (16*nblocks, 1, 8, 128) u32; o_ref: (8, 1, 8, 128) u32."""
    h0 = tuple(
        jnp.full((SUBLANES, LANES), np.uint32(H0_WORDS[j]), jnp.uint32)
        for j in range(8)
    )

    def block_step(b, hs):
        w = [x_ref[b * 16 + i, 0] for i in range(16)]
        a, bb, c, d, e, f, g, hh = hs
        for i in range(64):
            wi = w[i % 16]
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = hh + s1 + ch + np.uint32(K_WORDS[i]) + wi
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & bb) ^ (a & c) ^ (bb & c)
            t2 = s0 + maj
            hh, g, f, e, d, c, bb, a = g, f, e, d + t1, c, bb, a, t1 + t2
            if i < 48:
                wl = w[(i + 1) % 16]
                wh = w[(i + 14) % 16]
                sig0 = _rotr(wl, 7) ^ _rotr(wl, 18) ^ (wl >> np.uint32(3))
                sig1 = _rotr(wh, 17) ^ _rotr(wh, 19) ^ (wh >> np.uint32(10))
                w[i % 16] = wi + sig0 + w[(i + 9) % 16] + sig1
        out = (a, bb, c, d, e, f, g, hh)
        return tuple(hs[j] + out[j] for j in range(8))

    hs = jax.lax.fori_loop(0, nblocks, block_step, h0, unroll=False)
    for j in range(8):
        o_ref[j, 0] = hs[j]


@functools.lru_cache(maxsize=None)
def _compiled_call(nblocks: int, n_tiles: int, interpret: bool):
    kernel = functools.partial(_kernel, nblocks)
    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (16 * nblocks, 1, SUBLANES, LANES), lambda m: (0, m, 0, 0)
            )
        ],
        out_specs=pl.BlockSpec((8, 1, SUBLANES, LANES), lambda m: (0, m, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, n_tiles, SUBLANES, LANES), jnp.uint32),
        interpret=interpret,
    )


def compress_words(blocks: jax.Array, interpret: bool = False) -> jax.Array:
    """(nblocks, 16, N) u32 big-endian words -> (8, N) u32 digest state.

    Drop-in replacement for the jnp scan-of-compressions in ops/sha256.py;
    N is padded up to a multiple of 1024 lanes internally.
    """
    nblocks, sixteen, n = blocks.shape
    assert sixteen == 16, blocks.shape
    n_pad = -(-n // TILE) * TILE
    x = jnp.zeros((nblocks * 16, n_pad), dtype=jnp.uint32)
    x = x.at[:, :n].set(blocks.reshape(nblocks * 16, n))
    n_tiles = n_pad // TILE
    x = x.reshape(nblocks * 16, n_tiles, SUBLANES, LANES)
    out = _compiled_call(nblocks, n_tiles, interpret)(x)
    return out.reshape(8, n_pad)[:, :n]
