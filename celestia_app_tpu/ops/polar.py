"""GF(2) polar transform kernels for the Polar Coded Merkle Tree scheme.

The PCMT construction (arXiv:2201.07287) replaces the CMT's sparse LDGM
layers with polar codes: layer data is placed on the information set of
the n x n polar transform G_N = F^{(x) log2 n} (F = [[1,0],[1,1]]), and
the *pruned factor graph* of the transform — every intermediate stage
value that survives known-zero simplification — is what gets committed
and sampled. Degree-3 XOR checks between committed classes give the same
two properties the LDGM code gave the CMT: peeling repair from a symbol
subset, and one-violated-equation incorrect-coding fraud proofs. This
module is the code itself, scheme-agnostic:

- **Frozen set (informed design, arXiv:2301.08295).** The information
  set is the n_data most reliable synthetic channels under a Q32
  fixed-point Bhattacharyya recursion for BEC(1/2) — pure u64 integer
  arithmetic, so every node derives the identical set with no float in
  sight (det-float scope). Ties are broken toward HIGHER Hamming weight
  first (the informed-design bias: high-weight rows give the pruned
  graph better stopping-set geometry), then by a sha256-keyed
  deterministic shuffle exactly like ops/ldpc.parity_indices derives its
  permutations — nothing rides the wire, verifiers recompute everything
  from (n, n_data) alone. The resulting set is *up-closed* under bitwise
  domination (asserted), which is what makes the two-transform encode
  below systematic.

- **Pruned factor graph.** Stage values v_s[i] (s = 0..m, i = 0..n-1)
  with v_{s+1}[i] = v_s[i] ^ v_s[i | 2^s] when bit s of i is clear and
  v_{s+1}[i] = v_s[i] when set. Equal-value chains collapse to one
  committed class; frozen inputs are known zero and propagate; checks
  that lose members to zeros/cancellation degrade (degree 1 forces a
  zero, degree 2 merges two classes) until a fixpoint of degree-3 checks
  over non-zero classes remains. The committed classes (canonically
  ordered by minimum node id) are the layer's coded symbols; the
  deduplicated check list is its parity-equation set.

- **Encode.** Systematic double transform: scatter data onto A, apply
  G, re-mask to A, apply G again — up-closure of A makes x[A] == data
  exactly (G restricted to A is an involution). Host engine: numpy
  XOR butterflies. Device engine: one jitted dispatch per (n_data,
  sym_bytes) bucket — the first transform as ONE dense GF(2) bit-matmul
  (G @ bits) & 1 on the MXU for n <= POLAR_MATMUL_MAX_N (the ops/rs.py
  / ops/ldpc.py playbook; above that the same algebra runs as in-jit
  reshape-XOR butterfly stages), the second as butterfly stages with
  per-stage gathers of the committed representatives. Bit-identical by
  exact integer algebra (pinned in tests/test_codec_iface.py).

- **Peeling (successive-cancellation) decode.** Iterative degree-1
  resolution over the pruned checks: per sweep, every check with
  exactly one unknown member resolves it to the XOR of its two known
  members; contended targets go to the LOWEST check index via a
  commutative scatter-min. The device engine runs the whole peel as
  masked gather/scatter sweeps inside one ``lax.while_loop`` dispatch
  with only commutative (.min/.max) updates, so host numpy and device
  recover byte-identical values even from *inconsistent* (fraud)
  inputs.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib

import numpy as np

FROZEN_TAG = b"pcmt/frozen"

# Largest transform size whose first pass runs as a dense n x n bit-
# matmul on the device engine (int8 generator: 64 MB at 8192). Larger
# transforms use the identical-algebra in-jit butterfly stages — the
# k=128 base layer (n = 32768) would need a 1 GB generator for a
# transform the butterflies do in m=15 XOR passes.
POLAR_MATMUL_MAX_N = 8192

_Q32_CAP = np.uint64((1 << 32) - 1)


def reliability(n: int) -> np.ndarray:
    """(n,) u64 Q32 fixed-point Bhattacharyya parameters of the n
    synthetic channels for BEC(1/2): z(-) = 2z - z^2 (minus/upper
    branch), z(+) = z^2, natural bit order (index bit s = branch taken
    at level s). Lower is more reliable. Pure wrapping-u64 arithmetic —
    deterministic across platforms, no float."""
    z = np.array([1 << 31], dtype=np.uint64)
    m = n.bit_length() - 1
    with np.errstate(over="ignore"):
        for _ in range(m):
            z2 = (z * z) >> np.uint64(32)
            minus = np.minimum(np.uint64(2) * z - z2, _Q32_CAP)
            z = np.concatenate([minus, z2])
    return z


def _popcounts(n: int) -> np.ndarray:
    v = np.arange(n, dtype=np.uint64)
    pc = np.zeros(n, dtype=np.int64)
    for s in range(max(1, n.bit_length() - 1)):
        pc += ((v >> np.uint64(s)) & np.uint64(1)).astype(np.int64)
    return pc


def _tie_keys(n: int) -> np.ndarray:
    """sha256-derived u64 tie-break keys (the ops/ldpc.parity_indices
    discipline: seeded hashing is the one sanctioned entropy source)."""
    keys = np.empty(n, dtype=np.uint64)
    nb = n.to_bytes(8, "big")
    for i in range(n):
        h = hashlib.sha256(
            FROZEN_TAG + nb + i.to_bytes(8, "big")).digest()[:8]
        keys[i] = int.from_bytes(h, "big")
    return keys


def info_set(n: int, n_data: int) -> np.ndarray:
    """The information set A: the n_data channel indices picked by
    (reliability asc, Hamming weight desc, sha256 key asc, index asc),
    returned sorted ascending. Up-closed under bitwise domination (every
    superset-mask of a member is a member) — the property behind the
    systematic two-transform encode; violations would be a construction
    bug, so they raise."""
    z = reliability(n)
    pc = _popcounts(n)
    keys = _tie_keys(n)
    order = np.lexsort((np.arange(n), keys, -pc, z))
    a = np.sort(order[:n_data]).astype(np.int64)
    in_a = np.zeros(n, dtype=bool)
    in_a[a] = True
    for s in range(n.bit_length() - 1):
        up = a | np.int64(1 << s)
        if not in_a[up].all():
            raise AssertionError(
                f"info set not up-closed at n={n}, n_data={n_data}")
    return a


@dataclasses.dataclass(frozen=True)
class PolarGeometry:
    """The pruned factor graph of one (n_data -> n) polar layer — a pure
    function of n_data, canonical across nodes (node ids are s*n + i;
    class representative = minimum node id; checks deduplicated and
    lexicographically ordered)."""

    n: int
    m: int
    n_data: int
    A: np.ndarray  # (n_data,) i64 information set, ascending
    C: int  # committed class count
    reps: np.ndarray  # (C, 2) i32 (stage, position) representative
    checks: np.ndarray  # (n_checks, 3) i32 committed-class triples
    data_class: np.ndarray  # (n_data,) i32 committed index of data t


def _compress(p: np.ndarray) -> np.ndarray:
    while True:
        p2 = p[p]
        if np.array_equal(p2, p):
            return p2
        p = p2


@functools.lru_cache(maxsize=32)
def geometry(n_data: int) -> PolarGeometry:
    """Build the pruned factor graph for an n_data-symbol layer
    (n = smallest power of two >= 2*n_data, rate <= 1/2).

    Simplification runs set-at-a-time to a fixpoint: map check members
    to class roots, drop known-zero members, GF(2)-cancel duplicate
    members, then degree 0 drops the check, degree 1 forces its member
    zero, degree 2 merges the pair (kept until the merge lands so
    chained equalities in one round are not lost). The inferences are
    monotone, so the fixpoint — and with it the committed geometry — is
    unique regardless of sweep order."""
    if n_data < 1:
        raise ValueError(f"n_data must be >= 1, got {n_data}")
    n = 1
    while n < 2 * n_data:
        n *= 2
    m = n.bit_length() - 1
    a = info_set(n, n_data)
    nn = (m + 1) * n
    inf = nn  # sentinel for a cancelled/absent member

    ii = np.arange(n, dtype=np.int64)
    p = np.arange(nn, dtype=np.int64)
    for s in range(m):
        sel = ii[(ii >> s) & 1 == 1]
        p[(s + 1) * n + sel] = s * n + sel  # equal-value chain link
    p = _compress(p)

    zero = np.zeros(nn, dtype=bool)
    frozen = np.ones(n, dtype=bool)
    frozen[a] = False
    zero[p[ii[frozen]]] = True

    row_parts = []
    for s in range(m):
        sel = ii[(ii >> s) & 1 == 0]
        row_parts.append(np.stack(
            [(s + 1) * n + sel, s * n + sel, s * n + (sel | (1 << s))],
            axis=1))
    rows = np.concatenate(row_parts, axis=0)

    while True:
        changed = False
        p = _compress(p)
        safe = np.minimum(rows, nn - 1)
        r = np.where(rows < inf, p[safe], inf)
        zr = (rows < inf) & zero[np.minimum(r, nn - 1)]
        r = np.where(zr, inf, r)
        r.sort(axis=1)
        eq01 = (r[:, 0] == r[:, 1]) & (r[:, 0] < inf)
        eq12 = (r[:, 1] == r[:, 2]) & (r[:, 1] < inf)
        all3 = eq01 & eq12
        out = r.copy()
        pair01 = eq01 & ~all3
        out[pair01, 0] = r[pair01, 2]
        out[pair01 | eq12 | all3, 2] = inf
        out[pair01 | (eq12 & ~all3) | all3, 1] = inf
        out.sort(axis=1)
        r = out
        deg = (r < inf).sum(axis=1)
        ones = r[deg == 1, 0]
        if ones.size:
            if not zero[ones].all():
                changed = True
            zero[ones] = True
        two = r[deg == 2, :2]
        if len(two):
            lo = two.min(axis=1)
            hi = two.max(axis=1)
            before = p[hi].copy()
            np.minimum.at(p, hi, lo)
            if not np.array_equal(before, p[hi]):
                changed = True
            # zero flows across the merge in both directions
            zmerge = zero[lo] | zero[hi]
            zero[lo] |= zmerge
            zero[hi] |= zmerge
        keep = deg >= 2
        if not keep.all():
            changed = True
        rows = r[keep]
        if not changed:
            break

    p = _compress(p)
    # propagate zero flags to final roots
    zero_roots = np.zeros(nn, dtype=bool)
    np.logical_or.at(zero_roots, p, zero)
    zero = zero_roots

    deg = (rows < inf).sum(axis=1)
    if (deg != 3).any():
        raise AssertionError("unconsumed sub-degree-3 check at fixpoint")
    final = p[rows]
    if zero[final].any():
        raise AssertionError("zero member survived simplification")
    final.sort(axis=1)
    final = np.unique(final, axis=0)

    x_roots = p[m * n + ii]
    if zero[x_roots].any():
        raise AssertionError("coded position forced zero by frozen set")
    committed = np.unique(np.concatenate([x_roots, final.ravel()]))
    cidx = np.full(nn, -1, dtype=np.int64)
    cidx[committed] = np.arange(len(committed))
    checks = cidx[final].astype(np.int32)
    data_class = cidx[p[m * n + a]].astype(np.int32)
    if len(np.unique(data_class)) != n_data:
        raise AssertionError("data positions share a committed class")
    reps = np.stack([committed // n, committed % n],
                    axis=1).astype(np.int32)
    for arr in (a, reps, checks, data_class):
        arr.setflags(write=False)
    return PolarGeometry(n=n, m=m, n_data=n_data, A=a,
                         C=len(committed), reps=reps, checks=checks,
                         data_class=data_class)


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def _butterfly_stages(v: np.ndarray, m: int) -> list[np.ndarray]:
    """All m+1 stage arrays of the transform, stage 0 = input."""
    out = v.copy()
    stages = [out.copy()]
    for s in range(m):
        w = out.reshape(-1, 2, 1 << s, out.shape[1])
        w[:, 0] ^= w[:, 1]
        stages.append(out.copy())
    return stages


def encode_host(data: np.ndarray) -> np.ndarray:
    """(n_data, S) u8 data -> (C, S) u8 committed class values, pure
    numpy XOR butterflies (the host engine)."""
    g = geometry(data.shape[0])
    s_bytes = data.shape[1]
    t = np.zeros((g.n, s_bytes), dtype=np.uint8)
    t[g.A] = data
    w = _butterfly_stages(t, g.m)[-1]
    u = np.zeros_like(w)
    u[g.A] = w[g.A]
    stages = _butterfly_stages(u, g.m)
    if not np.array_equal(stages[-1][g.A], data):
        raise AssertionError("systematic property failed")  # impossible
    vals = np.empty((g.C, s_bytes), dtype=np.uint8)
    for s in range(g.m + 1):
        sel = g.reps[:, 0] == s
        if sel.any():
            vals[sel] = stages[s][g.reps[sel, 1]]
    return vals


@functools.lru_cache(maxsize=32)
def jitted_encode(n_data: int, sym_bytes: int):
    """Compiled device encode for one layer geometry: (n_data, S) u8 ->
    (C, S) u8 in ONE dispatch. First transform is the dense GF(2)
    bit-matmul (G @ bits) & 1 for n <= POLAR_MATMUL_MAX_N (the
    ops/ldpc.jitted_encode playbook with the polar generator as the bit
    matrix); the second transform unrolls the m butterfly stages and
    gathers each stage's committed representatives."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("polar.encode", (n_data, sym_bytes))
    g = geometry(n_data)
    n, m = g.n, g.m
    a = jnp.asarray(g.A)
    use_matmul = n <= POLAR_MATMUL_MAX_N
    if use_matmul:
        jj = np.arange(n, dtype=np.int64)
        # G[i, j] = 1 iff i is a bitwise subset of j (x = G v matches
        # the butterfly orientation: x[i] = XOR of v over supersets)
        gen = jnp.asarray(
            ((jj[:, None] & jj[None, :]) == jj[:, None]).astype(np.int8))
    stage_sel = [np.flatnonzero(g.reps[:, 0] == s) for s in range(m + 1)]
    stage_pos = [g.reps[idx, 1] for idx in stage_sel]

    def butterfly(x, s):
        w = x.reshape(-1, 2, 1 << s, x.shape[-1])
        return jnp.concatenate([w[:, 0] ^ w[:, 1], w[:, 1]],
                               axis=1).reshape(x.shape)

    def run(data: jax.Array) -> jax.Array:
        t = jnp.zeros((n, sym_bytes), jnp.uint8).at[a].set(data)
        if use_matmul:
            shifts = jnp.arange(8, dtype=jnp.uint8)
            bits = ((t[..., None] >> shifts) & 1).reshape(n, -1)
            wb = jnp.einsum("ij,js->is", gen, bits.astype(jnp.int8),
                            preferred_element_type=jnp.int32) & 1
            by = wb.reshape(n, sym_bytes, 8).astype(jnp.uint8)
            weights = (1 << jnp.arange(8, dtype=jnp.uint8))
            w = jnp.sum(by * weights, axis=-1).astype(jnp.uint8)
        else:
            w = t
            for s in range(m):
                w = butterfly(w, s)
        x = jnp.zeros((n, sym_bytes), jnp.uint8).at[a].set(w[a])
        vals = jnp.zeros((g.C, sym_bytes), jnp.uint8)
        for s in range(m + 1):
            if len(stage_sel[s]):
                vals = vals.at[jnp.asarray(stage_sel[s])].set(
                    x[jnp.asarray(stage_pos[s])])
            if s < m:
                x = butterfly(x, s)
        return vals

    return jax.jit(run)


def encode(data: np.ndarray, engine: str = "auto") -> np.ndarray:
    """Engine-gated committed-class encode; both paths bit-identical."""
    from celestia_app_tpu.ops import ldpc

    # host shares in, by contract (build_layers hands numpy symbols)
    data = np.ascontiguousarray(data, dtype=np.uint8)  # lint: disable=xfer-reach
    if engine == "auto" and not ldpc.auto_wants_device():
        return encode_host(data)
    if engine in ("device", "auto"):
        try:
            from celestia_app_tpu.obs import xfer

            run = jitted_encode(data.shape[0], data.shape[1])
            return xfer.to_host(
                run(xfer.to_device(data, "polar.encode")), "polar.encode")
        except Exception:
            if engine == "device":
                raise
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("app.device_path_fallback")
    return encode_host(data)


# ---------------------------------------------------------------------------
# peeling (successive-cancellation) decode
# ---------------------------------------------------------------------------


def peel_host(n_data: int, vals: np.ndarray, known: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray, int]:
    """Peel erasures out of one committed layer on the host.

    ``vals`` is (C, S) u8, ``known`` (C,) bool; unknown positions are
    normalized to zero so both engines see identical state. Returns
    (vals, known, sweeps). Resolution rule (shared with the device
    sweep): per sweep every degree-3 check with exactly one unknown
    member resolves it to the XOR of the known two; contended targets
    go to the LOWEST check index."""
    g = geometry(n_data)
    checks = g.checks
    known = np.asarray(known, dtype=bool).copy()
    vals = np.where(known[:, None],
                    np.ascontiguousarray(vals, dtype=np.uint8), 0)
    n_checks = len(checks)
    sweeps = 0
    while n_checks:
        kn = known[checks]  # (nc, 3)
        resolvable = kn.sum(axis=1) == 2
        if not resolvable.any():
            break
        sweeps += 1
        masked = vals * known[:, None]
        eqxor = (masked[checks[:, 0]] ^ masked[checks[:, 1]]
                 ^ masked[checks[:, 2]])
        tgt = checks[np.arange(n_checks), np.argmin(kn, axis=1)]
        eq_ids = np.flatnonzero(resolvable)
        best = np.full(g.C, n_checks, dtype=np.int64)
        np.minimum.at(best, tgt[resolvable], eq_ids)
        chosen = best[tgt[resolvable]] == eq_ids
        vals[tgt[resolvable][chosen]] = eqxor[resolvable][chosen]
        known[tgt[resolvable][chosen]] = True
    return vals, known, sweeps


@functools.lru_cache(maxsize=32)
def jitted_peel(n_data: int, sym_bytes: int):
    """Compiled whole-peel program for one layer geometry: a
    lax.while_loop of masked gather/scatter sweeps entirely on device —
    gather each check's member knowledge, XOR its known members, pick
    one check per contended target with a commutative scatter-min, and
    land the resolved symbols with commutative scatter-max updates
    (unknown state is all-zero, so max IS assignment). One dispatch
    peels to fixpoint, byte-identical to peel_host even on inconsistent
    fraud inputs."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("polar.peel", (n_data, sym_bytes))
    g = geometry(n_data)
    checks = jnp.asarray(g.checks.astype(np.int32))
    n_checks = len(g.checks)

    def body(state):
        vals, known, _progressed, sweeps = state
        kn = known[checks]  # (nc, 3) u8 0/1
        resolvable = kn.astype(jnp.int32).sum(axis=1) == 2
        masked = vals * known[:, None]
        eqxor = (masked[checks[:, 0]] ^ masked[checks[:, 1]]
                 ^ masked[checks[:, 2]])
        tgt = checks[jnp.arange(n_checks), jnp.argmin(kn, axis=1)]
        eqid = jnp.where(resolvable, jnp.arange(n_checks), n_checks)
        best = jnp.full((g.C,), n_checks, dtype=jnp.int32) \
            .at[tgt].min(eqid.astype(jnp.int32))
        chosen = resolvable & (jnp.arange(n_checks) == best[tgt])
        vals = vals.at[tgt].max(
            jnp.where(chosen[:, None], eqxor, 0))
        known = known.at[tgt].max(chosen.astype(jnp.uint8))
        return vals, known, chosen.any(), sweeps + 1

    def run(vals: jax.Array, known: jax.Array):
        state = (vals.astype(jnp.uint8), known.astype(jnp.uint8),
                 jnp.bool_(True), jnp.int32(0))
        if n_checks == 0:
            return state[0], state[1].astype(jnp.bool_), jnp.int32(0)
        vals, kn, _p, sweeps = jax.lax.while_loop(
            lambda s: s[2], body, state)
        return vals, kn.astype(jnp.bool_), sweeps

    return jax.jit(run)


def peel(n_data: int, vals: np.ndarray, known: np.ndarray,
         engine: str = "auto") -> tuple[np.ndarray, np.ndarray, int]:
    """Engine-gated peeling; device and host are bit-identical (pinned
    in tests/test_codec_iface.py, including on inconsistent inputs)."""
    from celestia_app_tpu.ops import ldpc

    known = np.asarray(known, dtype=bool)
    vals = np.where(known[:, None],
                    np.ascontiguousarray(vals, dtype=np.uint8), 0)
    if engine == "auto" and not ldpc.auto_wants_device():
        return peel_host(n_data, vals, known)
    if engine in ("device", "auto"):
        try:
            import jax.numpy as jnp

            run = jitted_peel(n_data, vals.shape[1])
            v, kn, sweeps = run(jnp.asarray(vals), jnp.asarray(known))
            return (np.asarray(v), np.asarray(kn),
                    max(0, int(sweeps) - 1))  # final sweep: no progress
        except Exception:
            if engine == "device":
                raise
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("app.device_path_fallback")
    return peel_host(n_data, vals, known)


def check_equations(n_data: int, vals: np.ndarray,
                    known: np.ndarray) -> np.ndarray:
    """Check audit over one committed layer: ascending ids of VIOLATED
    checks among those with every member known. A violation on
    fully-verified members is exactly an incorrect-coding fraud
    (da/pcmt.py carries the lowest attributable one as the proof's
    equation)."""
    g = geometry(n_data)
    if not len(g.checks):
        return np.zeros(0, dtype=np.int64)
    known = np.asarray(known, dtype=bool)
    full = known[g.checks].all(axis=1)
    vals = np.ascontiguousarray(vals, dtype=np.uint8)
    eqxor = (vals[g.checks[:, 0]] ^ vals[g.checks[:, 1]]
             ^ vals[g.checks[:, 2]])
    bad = full & eqxor.any(axis=1)
    return np.flatnonzero(bad).astype(np.int64)
