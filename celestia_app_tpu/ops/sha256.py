"""Vectorized SHA-256 for JAX: hash N same-length messages in one launch.

The DA pipeline's hashing workload (reference: `crypto/sha256` inside the nmt
hasher, pkg/wrapper/nmt_wrapper.go) is millions of *independent* fixed-length
messages per block — NMT leaves are 542-byte preimages, inner nodes 181 bytes,
binary-Merkle nodes 65 bytes. That maps to the TPU VPU as pure u32 lane
arithmetic: one traced program hashing a whole tree level at a time, with the
64-round compression unrolled so XLA fuses it into a single elementwise chain.

Semantics match FIPS 180-4 exactly (golden-tested against hashlib).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.ops.sha256_consts import H0_WORDS, K_WORDS

_K = np.array(K_WORDS, dtype=np.uint32)
_H0 = np.array(H0_WORDS, dtype=np.uint32)


def _rotr(x: jax.Array, n) -> jax.Array:
    n = jnp.asarray(n, dtype=jnp.uint32)
    return (x >> n) | (x << (np.uint32(32) - n))


def _compress(state: jax.Array, block_words: jax.Array) -> jax.Array:
    """One SHA-256 block over N lanes: state (8, N) u32, block (16, N) u32.

    Rolled with fori_loop so the traced graph stays small — hashing is called
    at every tree level of every pipeline, and an unrolled 64-round body
    multiplies XLA compile time by ~100x for zero VPU runtime benefit.
    """
    n = state.shape[1]
    w = jnp.zeros((64, n), dtype=jnp.uint32).at[:16].set(block_words)

    def schedule(i, w):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> np.uint32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> np.uint32(10))
        return w.at[i].set(w[i - 16] + s0 + w[i - 7] + s1)

    w = jax.lax.fori_loop(16, 64, schedule, w)
    k_const = jnp.asarray(_K)

    def round_fn(i, s):
        a, b, c, d, e, f, g, h = s
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_const[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(0, 64, round_fn, tuple(state))
    return state + jnp.stack(out)


def _pad_len(msg_len: int) -> int:
    return ((msg_len + 8) // 64 + 1) * 64


def use_pallas() -> bool:
    """Pallas kernel on accelerator backends; jnp scan path on CPU.

    Override with CELESTIA_SHA256_IMPL=pallas|jnp (the bench harness uses
    this to fall back if the kernel fails to compile on a new toolchain).
    """
    impl = os.environ.get("CELESTIA_SHA256_IMPL", "")
    if impl == "pallas":
        return True
    if impl == "jnp":
        return False
    # axon is the tunneled TPU platform (its MLIR lowerings alias to tpu's);
    # anything else (cpu, gpu) takes the portable jnp path.
    return jax.default_backend() in ("tpu", "axon")


def sha256(msgs: jax.Array) -> jax.Array:
    """SHA-256 of N equal-length messages: (N, L) uint8 -> (N, 32) uint8.

    L is static; padding and block count are resolved at trace time. Blocks
    are consumed by the Pallas register kernel on TPU (sha256_pallas.py) or
    a lax.scan of compressions on CPU.
    """
    n, msg_len = msgs.shape
    total = _pad_len(msg_len)
    tail = np.zeros(total - msg_len, dtype=np.uint8)
    tail[0] = 0x80
    bit_len = msg_len * 8
    # trace-time constant: L is static, so the padding tail is host
    # numpy over Python ints, baked into the traced program
    tail[-8:] = np.frombuffer(bit_len.to_bytes(8, "big"), dtype=np.uint8)  # lint: disable=jit-purity
    padded = jnp.concatenate(
        [msgs, jnp.broadcast_to(jnp.asarray(tail), (n, tail.shape[0]))], axis=1
    )
    # Big-endian u32 words, grouped per block: (nblocks, 16, N)
    quads = padded.reshape(n, total // 4, 4).astype(jnp.uint32)
    be = jnp.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=jnp.uint32)
    words = jnp.sum(quads * be, axis=-1, dtype=jnp.uint32)  # (N, total/4)
    blocks = jnp.transpose(words.reshape(n, total // 64, 16), (1, 2, 0))

    if use_pallas() and n >= 1024:
        # Pallas register kernel for the big batched levels; tiny upper tree
        # levels (N < one 1024-lane tile) stay on the jnp path rather than
        # paying a nearly-all-padding kernel dispatch per level.
        from celestia_app_tpu.ops import sha256_pallas

        state = sha256_pallas.compress_words(blocks)
    else:
        state0 = jnp.broadcast_to(jnp.asarray(_H0)[:, None], (8, n))

        def step(state, block_words):
            return _compress(state, block_words), None

        state, _ = jax.lax.scan(step, state0, blocks)
    digest_words = jnp.transpose(state)  # (N, 8) u32
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    out = (digest_words[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return out.reshape(n, 32).astype(jnp.uint8)


EMPTY_SHA256 = bytes.fromhex(
    "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"
)
