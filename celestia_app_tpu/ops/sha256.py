"""Vectorized SHA-256 for JAX: hash N same-length messages in one launch.

The DA pipeline's hashing workload (reference: `crypto/sha256` inside the nmt
hasher, pkg/wrapper/nmt_wrapper.go) is millions of *independent* fixed-length
messages per block — NMT leaves are 542-byte preimages, inner nodes 181 bytes,
binary-Merkle nodes 65 bytes. That maps to the TPU VPU as pure u32 lane
arithmetic: one traced program hashing a whole tree level at a time, with the
64-round compression unrolled so XLA fuses it into a single elementwise chain.

Semantics match FIPS 180-4 exactly (golden-tested against hashlib).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x: jax.Array, n) -> jax.Array:
    n = jnp.asarray(n, dtype=jnp.uint32)
    return (x >> n) | (x << (np.uint32(32) - n))


def _compress(state: jax.Array, block_words: jax.Array) -> jax.Array:
    """One SHA-256 block over N lanes: state (8, N) u32, block (16, N) u32.

    Rolled with fori_loop so the traced graph stays small — hashing is called
    at every tree level of every pipeline, and an unrolled 64-round body
    multiplies XLA compile time by ~100x for zero VPU runtime benefit.
    """
    n = state.shape[1]
    w = jnp.zeros((64, n), dtype=jnp.uint32).at[:16].set(block_words)

    def schedule(i, w):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ (w[i - 15] >> np.uint32(3))
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ (w[i - 2] >> np.uint32(10))
        return w.at[i].set(w[i - 16] + s0 + w[i - 7] + s1)

    w = jax.lax.fori_loop(16, 64, schedule, w)
    k_const = jnp.asarray(_K)

    def round_fn(i, s):
        a, b, c, d, e, f, g, h = s
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k_const[i] + w[i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    out = jax.lax.fori_loop(0, 64, round_fn, tuple(state))
    return state + jnp.stack(out)


def _pad_len(msg_len: int) -> int:
    return ((msg_len + 8) // 64 + 1) * 64


def sha256(msgs: jax.Array) -> jax.Array:
    """SHA-256 of N equal-length messages: (N, L) uint8 -> (N, 32) uint8.

    L is static; padding and block count are resolved at trace time. Blocks
    are consumed with lax.scan (compile-time O(1) in block count).
    """
    n, msg_len = msgs.shape
    total = _pad_len(msg_len)
    tail = np.zeros(total - msg_len, dtype=np.uint8)
    tail[0] = 0x80
    bit_len = msg_len * 8
    tail[-8:] = np.frombuffer(bit_len.to_bytes(8, "big"), dtype=np.uint8)
    padded = jnp.concatenate(
        [msgs, jnp.broadcast_to(jnp.asarray(tail), (n, tail.shape[0]))], axis=1
    )
    # Big-endian u32 words, grouped per block: (nblocks, 16, N)
    quads = padded.reshape(n, total // 4, 4).astype(jnp.uint32)
    be = jnp.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=jnp.uint32)
    words = jnp.sum(quads * be, axis=-1, dtype=jnp.uint32)  # (N, total/4)
    blocks = jnp.transpose(words.reshape(n, total // 64, 16), (1, 2, 0))

    state0 = jnp.broadcast_to(jnp.asarray(_H0)[:, None], (8, n))

    def step(state, block_words):
        return _compress(state, block_words), None

    state, _ = jax.lax.scan(step, state0, blocks)
    digest_words = jnp.transpose(state)  # (N, 8) u32
    shifts = jnp.array([24, 16, 8, 0], dtype=jnp.uint32)
    out = (digest_words[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return out.reshape(n, 32).astype(jnp.uint8)


EMPTY_SHA256 = bytes.fromhex(
    "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"
)
