"""Sparse GF(2) LDPC (LDGM) codes for the Coded Merkle Tree scheme.

The CMT construction (arXiv:1910.01247) codes every tree layer with a
sparse erasure code whose *peeling* decoder gives (a) cheap repair from
any large-enough symbol subset and (b) O(1)-sized incorrect-coding fraud
proofs: one violated parity equation, carried with the Merkle proofs of
its d+1 members. This module is the code itself, scheme-agnostic:

- **Construction.** Systematic LDGM: coded = [data || parity], parity p
  is the XOR of ``degree`` distinct data symbols. The neighbor table is a
  pure function of (n_data, degree, tag) — ``degree`` deterministic
  pseudorandom permutations of [0, n_data) from a splitmix64 stream
  seeded by sha256(tag), with collisions probed away — so every node
  derives the identical code from the scheme parameters alone; nothing
  rides the wire, and fraud verifiers recompute the equation membership
  they check against (da/cmt.py).

- **Encode.** Host engine: one XOR-gather (``np.bitwise_xor.reduce`` over
  the gathered neighbor symbols). Device engine: the same GF(2) algebra
  as ops/rs.py — unpack symbols to bits, ONE bit-matmul
  ``(G @ data_bits) & 1`` with the dense 0/1 generator on the MXU, pack —
  jitted per (n_data, symbol-size) bucket. Bit-identical by construction
  (pinned in tests/test_codec_iface.py).

- **Peeling decode.** Iterative degree-1 resolution expressed as masked
  matmul sweeps (the fused-decode-matrix discipline of
  ops/leopard_decode.py): per sweep, ``M @ unknown`` counts unknowns per
  equation, ``(M * known) @ sym_bits`` XORs each equation's known
  members, and equations with exactly one unknown scatter that XOR into
  their missing symbol. Equation→symbol assignment is made deterministic
  by a commutative scatter-min (the LOWEST equation index resolving a
  symbol wins), so the host numpy sweep and the jitted lax.while_loop
  sweep recover byte-identical symbols even from *inconsistent* (fraud)
  inputs.
"""

from __future__ import annotations

import functools
import hashlib

import numpy as np

# Regular degree of every parity equation (and, by the permutation
# construction, of every data symbol). Rate is fixed at 1/2: n_parity ==
# n_data, so the coded layer is exactly twice the data layer. Degree 8
# is the measured sweet spot for peeling under random erasure at this
# rate: d<=4 collapses below a 1/8 erasure fraction at large n, d=6
# holds 1/8 but not 1/4, d=8 peels a 1/4-erased layer w.h.p. from n=16
# through n=16384 (the k=128 base layer) — the margin behind the
# scheme's declared sampling threshold (da/cmt.py CATCH_BP).
DEGREE = 8


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: u64 counters -> u64 keys.
    Platform-pinned integer arithmetic (wrapping u64), no RNG state."""
    z = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


@functools.lru_cache(maxsize=64)
def parity_indices(n_data: int, degree: int = DEGREE,
                   tag: bytes = b"cmt") -> np.ndarray:
    """(n_data, d) int32 neighbor table: parity p = XOR of data[idx[p]].

    d = min(degree, n_data). Column j is a deterministic pseudorandom
    permutation of [0, n_data) (seeded from sha256(tag || j || n_data)),
    so data-symbol degree is exactly d as well; within-row collisions are
    resolved by +1 probing, preserving distinctness per equation (a
    repeated neighbor would XOR-cancel out of the code)."""
    d = min(degree, n_data)
    cols = []
    for j in range(d):
        seed = int.from_bytes(
            hashlib.sha256(
                tag + b"/" + j.to_bytes(4, "big")
                + n_data.to_bytes(8, "big")
            ).digest()[:8],
            "big",
        )
        with np.errstate(over="ignore"):
            keys = _splitmix64(
                np.uint64(seed) + np.arange(n_data, dtype=np.uint64)
            )
        cols.append(np.argsort(keys, kind="stable").astype(np.int32))
    idx = np.stack(cols, axis=1)  # (n_data, d)
    for j in range(1, d):
        while True:
            dup = (idx[:, :j] == idx[:, j:j + 1]).any(axis=1)
            if not dup.any():
                break
            idx[dup, j] = (idx[dup, j] + 1) % n_data
    idx.setflags(write=False)
    return idx


@functools.lru_cache(maxsize=64)
def membership(n_data: int, degree: int = DEGREE,
               tag: bytes = b"cmt") -> np.ndarray:
    """(n_parity, n_coded) u8 0/1 membership matrix of every parity
    equation over the CODED symbols: the idx neighbors plus the parity
    symbol itself (coded index n_data + p). The device sweep's fixed
    per-layer matrix."""
    idx = parity_indices(n_data, degree, tag)
    n_parity, d = idx.shape
    m = np.zeros((n_parity, 2 * n_data), dtype=np.uint8)
    rows = np.repeat(np.arange(n_parity), d)
    m[rows, idx.ravel()] = 1
    m[np.arange(n_parity), n_data + np.arange(n_parity)] = 1
    m.setflags(write=False)
    return m


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def encode_host(data: np.ndarray, n_data: int | None = None,
                degree: int = DEGREE, tag: bytes = b"cmt") -> np.ndarray:
    """(n_data, S) u8 data symbols -> (n_data, S) u8 parity symbols, pure
    numpy XOR-gather (the host engine's encode; ~ms even at the k=128
    base layer, where the matmul formulation would be a 1 TFLOP GEMM)."""
    n = data.shape[0] if n_data is None else n_data
    idx = parity_indices(n, degree, tag)
    return np.bitwise_xor.reduce(data[idx], axis=1)


@functools.lru_cache(maxsize=32)
def jitted_encode(n_data: int, sym_bytes: int, degree: int = DEGREE,
                  tag: bytes = b"cmt"):
    """Compiled device encode for one layer geometry: (n_data, S) u8 ->
    (n_data, S) u8 parity as ONE GF(2) bit-matmul (G @ data_bits) & 1 —
    the ops/rs.py playbook with the LDGM generator as the bit matrix.
    The generator rides as a closed-over device constant per (n_data, S)
    bucket; upper CMT layers reuse buckets across heights."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("ldpc.encode", (n_data, sym_bytes))
    idx = parity_indices(n_data, degree, tag)
    g = np.zeros((n_data, n_data), dtype=np.int8)
    g[np.repeat(np.arange(n_data), idx.shape[1]), idx.ravel()] = 1
    g = jnp.asarray(g)

    def run(data: jax.Array) -> jax.Array:
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((data[..., None] >> shifts) & 1).reshape(n_data, -1)
        out = jnp.einsum("pq,qs->ps", g, bits.astype(jnp.int8),
                         preferred_element_type=jnp.int32) & 1
        by = out.reshape(n_data, sym_bytes, 8).astype(jnp.uint8)
        weights = (1 << jnp.arange(8, dtype=jnp.uint8))
        return jnp.sum(by * weights, axis=-1).astype(jnp.uint8)

    return jax.jit(run)


def auto_wants_device() -> bool:
    """Whether engine="auto" should take the jitted path: only on a real
    accelerator backend. On CPU the XOR-gather/hashlib host paths beat
    XLA's dense bit-matmuls by orders of magnitude at the base-layer
    sizes (the same reasoning that makes utils/fast_host the CPU
    baseline); the matmul formulation exists for the MXU. "device"
    still forces the jitted path on any backend (bit-identity tests)."""
    try:
        import jax

        return jax.default_backend() not in ("cpu",)
    except Exception:
        # no usable jax at all: fall to host, visibly
        from celestia_app_tpu.utils import telemetry

        telemetry.incr("app.device_path_fallback")
        return False


def encode(data: np.ndarray, engine: str = "auto", degree: int = DEGREE,
           tag: bytes = b"cmt") -> np.ndarray:
    """Engine-gated parity encode; both paths bit-identical."""
    # host shares in, by contract (build_layers hands numpy symbols)
    data = np.ascontiguousarray(data, dtype=np.uint8)  # lint: disable=xfer-reach
    if engine == "auto" and not auto_wants_device():
        return encode_host(data, degree=degree, tag=tag)
    if engine in ("device", "auto"):
        try:
            from celestia_app_tpu.obs import xfer

            run = jitted_encode(data.shape[0], data.shape[1], degree, tag)
            return xfer.to_host(
                run(xfer.to_device(data, "ldpc.encode")), "ldpc.encode")
        except Exception:
            if engine == "device":
                raise
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("app.device_path_fallback")
    return encode_host(data, degree=degree, tag=tag)


# ---------------------------------------------------------------------------
# peeling decode
# ---------------------------------------------------------------------------


def peel_host(symbols: np.ndarray, known: np.ndarray,
              degree: int = DEGREE,
              tag: bytes = b"cmt") -> tuple[np.ndarray, np.ndarray, int]:
    """Peel erasures out of one coded layer on the host.

    ``symbols`` is (n_coded, S) u8 with arbitrary bytes at unknown
    positions; ``known`` (n_coded,) bool. Returns (symbols, known,
    sweeps) with every peelable symbol resolved — the caller decides
    whether a residual unknown set means unavailability. Inputs are not
    mutated. Resolution rule (shared with the device sweep, so the two
    engines agree even on inconsistent fraud inputs): per sweep, every
    equation with exactly one unknown member resolves it to the XOR of
    its known members; when several equations target the same symbol the
    LOWEST equation index wins."""
    n_coded = symbols.shape[0]
    n_data = n_coded // 2
    idx = parity_indices(n_data, degree, tag)
    d = idx.shape[1]
    members = np.concatenate(
        [idx, (n_data + np.arange(n_data, dtype=np.int32))[:, None]],
        axis=1,
    )  # (n_parity, d+1)
    symbols = symbols.copy()
    known = known.copy()
    sweeps = 0
    while True:
        unk = ~known
        m_unk = unk[members]  # (n_parity, d+1)
        cnt = m_unk.sum(axis=1)
        resolvable = cnt == 1
        if not resolvable.any():
            return symbols, known, sweeps
        sweeps += 1
        masked = symbols * known[:, None]
        eqxor = np.bitwise_xor.reduce(masked[members], axis=1)
        targets = members[resolvable,
                          np.argmax(m_unk[resolvable], axis=1)]
        # lowest-equation-wins on contended targets (mirrors the device
        # sweep's commutative scatter-min)
        eq_ids = np.flatnonzero(resolvable)
        best = np.full(n_coded, len(members), dtype=np.int64)
        np.minimum.at(best, targets, eq_ids)
        chosen = best[targets] == eq_ids
        symbols[targets[chosen]] = eqxor[resolvable][chosen]
        known[targets[chosen]] = True


@functools.lru_cache(maxsize=32)
def jitted_peel(n_data: int, sym_bytes: int, degree: int = DEGREE,
                tag: bytes = b"cmt"):
    """Compiled whole-peel program for one layer geometry: a
    lax.while_loop of masked-matmul sweeps entirely on device —
    ``M @ unknown`` counts unknowns per equation, ``(M*known) @ bits``
    XORs known members, a scatter-min picks one equation per target
    (commutative, hence deterministic), and a one-hot matmul scatters
    the resolved bits. One dispatch peels to fixpoint."""
    import jax
    import jax.numpy as jnp

    from celestia_app_tpu.obs import jax_profile

    jax_profile.note_compile("ldpc.peel", (n_data, sym_bytes))
    m_np = membership(n_data, degree, tag)
    n_parity, n_coded = m_np.shape
    # int8 end to end with int32 ACCUMULATION only (the jitted_encode
    # discipline): the dense membership matrix is 512 MB at the k=128
    # base layer already — an int32 copy would quadruple it
    m = jnp.asarray(m_np, dtype=jnp.int8)

    def body(state):
        bits, known, _progressed, sweeps = state
        kn = known.astype(jnp.int8)
        cnt = jnp.einsum("pq,q->p", m, 1 - kn,
                         preferred_element_type=jnp.int32)
        resolvable = cnt == 1
        eqxor = (jnp.einsum("pq,qs->ps", m * kn[None, :], bits,
                            preferred_element_type=jnp.int32)
                 & 1).astype(jnp.int8)  # (n_parity, 8S)
        tgt_onehot = m * (1 - kn)[None, :]  # the single unknown member
        t = jnp.argmax(tgt_onehot, axis=1)  # (n_parity,)
        eqid = jnp.where(resolvable, jnp.arange(n_parity), n_parity)
        best = jnp.full((n_coded,), n_parity, dtype=jnp.int32) \
            .at[t].min(eqid.astype(jnp.int32))
        chosen = resolvable & (jnp.arange(n_parity) == best[t])
        sel = tgt_onehot * chosen[:, None]  # one-hot rows, disjoint tgts
        new_bits = jnp.einsum("pq,ps->qs", sel, eqxor,
                              preferred_element_type=jnp.int32) & 1
        newly = jnp.einsum("pq->q", sel.astype(jnp.int32)) > 0
        bits = jnp.where(newly[:, None], new_bits.astype(jnp.int8), bits)
        known = known | newly
        return bits, known, newly.any(), sweeps + 1

    def run(sym_bits: jax.Array, known: jax.Array):
        # progressed seeds True so the first sweep always runs; the loop
        # exits after the first sweep that resolves nothing
        state = (sym_bits.astype(jnp.int8), known, jnp.bool_(True),
                 jnp.int32(0))
        bits, kn, _p, sweeps = jax.lax.while_loop(
            lambda s: s[2], body, state)
        return bits.astype(jnp.uint8), kn, sweeps

    return jax.jit(run)


def _u8_to_bits(x: np.ndarray) -> np.ndarray:
    return np.unpackbits(x, axis=-1, bitorder="little")


def _bits_to_u8(b: np.ndarray) -> np.ndarray:
    return np.packbits(b.astype(np.uint8), axis=-1, bitorder="little")


def peel(symbols: np.ndarray, known: np.ndarray, engine: str = "auto",
         degree: int = DEGREE,
         tag: bytes = b"cmt") -> tuple[np.ndarray, np.ndarray, int]:
    """Engine-gated peeling; device and host are bit-identical (pinned in
    tests/test_codec_iface.py, including on inconsistent inputs)."""
    symbols = np.ascontiguousarray(symbols, dtype=np.uint8)
    known = np.asarray(known, dtype=bool)
    if engine == "auto" and not auto_wants_device():
        return peel_host(symbols, known, degree, tag)
    if engine in ("device", "auto"):
        try:
            import jax.numpy as jnp

            n_data = symbols.shape[0] // 2
            run = jitted_peel(n_data, symbols.shape[1], degree, tag)
            bits, kn, sweeps = run(
                jnp.asarray(_u8_to_bits(symbols)), jnp.asarray(known))
            return (_bits_to_u8(np.asarray(bits)), np.asarray(kn),
                    int(sweeps) - 1)  # final sweep makes no progress
        except Exception:
            if engine == "device":
                raise
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("app.device_path_fallback")
    return peel_host(symbols, known, degree, tag)


def check_equations(symbols: np.ndarray, known: np.ndarray,
                    degree: int = DEGREE,
                    tag: bytes = b"cmt") -> np.ndarray:
    """Parity-equation audit over one coded layer: ascending ids of
    VIOLATED equations among those with every member known. A violation
    on fully-verified members is exactly an incorrect-coding fraud
    (da/cmt.py carries the lowest one as the proof's equation)."""
    n_coded = symbols.shape[0]
    n_data = n_coded // 2
    idx = parity_indices(n_data, degree, tag)
    members = np.concatenate(
        [idx, (n_data + np.arange(n_data, dtype=np.int32))[:, None]],
        axis=1,
    )
    full = known[members].all(axis=1)
    eqxor = np.bitwise_xor.reduce(symbols[members], axis=1)
    bad = full & eqxor.any(axis=1)
    return np.flatnonzero(bad).astype(np.int64)
