"""e2e throughput benchmark over the autonomous multi-process devnet.

The reference's e2e benchmark harness (test/e2e/benchmark/throughput.go)
provisions validator pods, injects network latency via BitTwister (70 ms,
5 MB/s per peer), floods PFB load from txsim, then scrapes per-node
BlockSummary traces and passes if some block reaches >= 90% of
MaxBlockBytes (TwoNodeSimple: >= 1 MB). This is that harness for THIS
framework, with OS processes for pods and the reactor's gossip_delay for
the latency injection:

  python -m celestia_app_tpu e2e-bench --home DIR \
      --validators 2 --blocks 8 --blob-kb 200 --blobs-per-tx 2 \
      --latency-ms 70 --target-mb 1.0

Spawns `validator-serve --autonomous` processes, floods multi-blob PFBs
at every validator's /broadcast_tx from a load thread, waits for the
target height, then pulls /trace/block_summary (the rows the reactor
writes at every commit) and reports blocks/s + block-byte statistics
with the reference's >= 90%-of-target pass criterion.
"""

from __future__ import annotations

import base64
import json
import os
import random
import sys
import threading
import time

from celestia_app_tpu.net.transport import PeerClient, TransportConfig

# one shared hardened client for the whole harness (load thread + watch
# loop): a validator that dies mid-bench trips its breaker once instead
# of costing every poll a connect timeout
_NET = PeerClient(TransportConfig(timeout=10.0, retries=1),
                  name="e2e-bench")


def _post(url: str, path: str, payload: dict, timeout: float = 30.0) -> dict:
    return _NET.post(url, path, payload, timeout=timeout)


def _get(url: str, path: str, timeout: float = 10.0):
    return _NET.get(url, path, timeout=timeout)


class BlobLoad(threading.Thread):
    """txsim-lite: continuously submit multi-blob PFBs round-robin across
    validators, tracking per-account sequence and re-syncing on the
    sequence-mismatch rejection (the reference's txsim blob sequence)."""

    def __init__(self, urls: list[str], privs, chain_id: str,
                 blob_kb: int, blobs_per_tx: int, txs_per_block: int = 8,
                 seed: int = 7):
        super().__init__(daemon=True)
        from celestia_app_tpu.client.tx_client import Signer

        self.urls = urls
        self.chain_id = chain_id
        self.blob_kb = blob_kb
        self.blobs_per_tx = blobs_per_tx
        # paced like the reference's txsim (one tx per sequence per
        # block): an unpaced flood starves the consensus threads of the
        # writer lock (every CheckTx recomputes blob commitments) and
        # bloats the mempool past what one square can hold
        self.txs_per_block = txs_per_block
        self.rng = random.Random(seed)
        self.signers = []
        for i, p in enumerate(privs):
            s = Signer(chain_id)
            s.add_account(p, number=i)
            self.signers.append((p.public_key().address(), s))
        self.submitted = 0
        self.rejected = 0
        self.stop_flag = threading.Event()

    def _height(self) -> int:
        for u in self.urls:
            try:
                return _get(u, "/consensus/status", timeout=5)["height"]
            except OSError:
                continue
        return 0

    def run(self) -> None:
        from celestia_app_tpu.chain.modules import estimate_pfb_gas
        from celestia_app_tpu.client.tx_client import parse_expected_sequence
        from celestia_app_tpu.da.blob import Blob
        from celestia_app_tpu.da.namespace import Namespace

        i = 0
        height = self._height()
        sent_this_height = 0
        while not self.stop_flag.is_set():
            if sent_this_height >= self.txs_per_block:
                h = self._height()
                if h == height:
                    time.sleep(0.2)
                    continue
                height, sent_this_height = h, 0
            addr, signer = self.signers[i % len(self.signers)]
            url = self.urls[i % len(self.urls)]
            i += 1
            blobs = [
                Blob(
                    Namespace.v0(self.rng.randbytes(10)),
                    self.rng.randbytes(self.blob_kb * 1024),
                )
                for _ in range(self.blobs_per_tx)
            ]
            gas = int(estimate_pfb_gas([len(b.data) for b in blobs]) * 1.2)
            fee = max(1, int(gas * 0.002) + 1)
            raw = signer.create_pay_for_blobs(
                addr, blobs, fee=fee, gas_limit=gas
            )
            try:
                res = _post(url, "/broadcast_tx",
                            {"tx": base64.b64encode(raw).decode()})
            except OSError:
                time.sleep(0.2)
                continue
            if res.get("code") == 0:
                signer.accounts[addr].sequence += 1
                self.submitted += 1
                sent_this_height += 1
            else:
                self.rejected += 1
                exp = parse_expected_sequence(res.get("log", ""))
                if exp is not None:
                    signer.accounts[addr].sequence = exp
                else:
                    time.sleep(0.1)  # mempool full / floor: back off


def run(args, spawn_processes, terminate_processes) -> int:
    """The benchmark driver; `spawn_processes`/`terminate_processes` come
    from the CLI's shared devnet scaffolding."""
    from celestia_app_tpu.chain.crypto import PrivateKey

    n = args.validators
    privs = [
        PrivateKey.from_seed(f"devnet-{i}".encode()) for i in range(n)
    ]
    genesis = {
        "time_unix": time.time(),
        "accounts": [
            {"address": p.public_key().address().hex(), "balance": 10**14}
            for p in privs
        ],
        "validators": [
            {
                "operator": p.public_key().address().hex(),
                "power": 10,
                "pubkey": p.public_key().compressed.hex(),
            }
            for p in privs
        ],
    }
    os.makedirs(args.home, exist_ok=True)
    procs, homes, urls = spawn_processes(
        args, genesis,
        extra_flags=("--autonomous", "--http", "0"),
        reactor_cfg={
            "timeout_propose": 60.0,  # a 2 MB square build + extend can
            "timeout_prevote": 30.0,  # take a while on a loaded host
            "timeout_precommit": 30.0,
            "timeout_delta": 5.0,
            "block_interval": args.block_time,
            "gossip_delay": args.latency_ms / 1000.0,
        },
    )
    load = None
    try:
        # reactors arm on sight of the address book
        for home in homes:
            tmp = os.path.join(home, "peers.json.tmp")
            with open(tmp, "w") as f:
                json.dump(urls, f)
            os.replace(tmp, os.path.join(home, "peers.json"))

        load = BlobLoad(urls, privs, args.chain_id,
                        args.blob_kb, args.blobs_per_tx,
                        txs_per_block=args.txs_per_block)
        load.start()

        deadline = time.monotonic() + max(300.0, 60.0 * args.blocks)
        while time.monotonic() < deadline:
            heights = []
            for u in urls:
                try:
                    heights.append(_get(u, "/consensus/status")["height"])
                except OSError:
                    pass
            if heights and min(heights) >= args.blocks:
                break
            if heights:
                print(f"heights: {heights}, submitted {load.submitted}, "
                      f"rejected {load.rejected}", file=sys.stderr)
            time.sleep(max(0.5, args.block_time))
        else:
            print("ERROR: benchmark never reached the target height",
                  file=sys.stderr)
            return 1
        load.stop_flag.set()

        # scrape BlockSummary traces from validator 0's node HTTP service
        with open(os.path.join(homes[0], "endpoint.json")) as f:
            ep = json.load(f)
        http = f"http://{ep['host']}:{ep['http_port']}"
        rows = _get(http, "/trace/block_summary?limit=100000")
        rows = rows.get("rows", rows) if isinstance(rows, dict) else rows
        if not rows:
            print("ERROR: no block_summary traces", file=sys.stderr)
            return 1
        by_height = {}
        for r in rows:
            by_height[r["height"]] = r
        blocks = sorted(by_height.values(), key=lambda r: r["height"])
        bytes_list = [r["block_bytes"] for r in blocks]
        times = [r["time_unix"] for r in blocks]
        span = max(times) - min(times)
        bps = (len(blocks) - 1) / span if span > 0 and len(blocks) > 1 \
            else None
        target = int(args.target_mb * 1024 * 1024)
        max_bytes = max(bytes_list)
        out = {
            "validators": n,
            "latency_ms": args.latency_ms,
            "blocks": len(blocks),
            "blocks_per_sec": round(bps, 3) if bps else None,
            "max_block_bytes": max_bytes,
            "avg_block_bytes": sum(bytes_list) // len(bytes_list),
            "txs_total": sum(r["n_txs"] for r in blocks),
            "pfb_submitted": load.submitted,
            "target_bytes": target,
            # the reference pass criterion: SOME block >= 90% of target
            # (test/e2e/benchmark/throughput.go:124-125)
            "pass": max_bytes >= int(0.9 * target),
        }
        print(json.dumps(out))
        return 0 if out["pass"] else 1
    finally:
        if load is not None:
            load.stop_flag.set()
        terminate_processes(procs)
