"""blobload: the rollup-reader read-plane load harness.

Models the north star's READ shape — a fleet of rollup followers each
pulling its namespace's blobs + proofs from one serving node — and
measures what the read plane delivers under that concurrency
(tools/dasload.py is the sampling-plane sibling; same harness shape:
persistent connections, barrier start, one JSON report):

- every reader is a thread holding ONE persistent HTTP/1.1 connection,
  released off a start barrier so the clock covers steady state only;
- ``mode="single"`` issues one ``GET /blob/get`` per (height, namespace)
  query — the per-request host loop the batched route is measured
  against;
- ``mode="batch"`` folds ``batch`` queries into one
  ``POST /blob/namespaces`` round-trip — the read plane's intended
  shape (one engine-gated dispatch resolves the whole batch);
- ``mode="pack"`` reads the namespace's doc out of the height's static
  blob pack (manifest position -> chunk index, chunk sha256-checked
  against the manifest).

Report: ``namespace_queries_per_sec``, per-request ``p50_ms``/``p99_ms``,
``present_ratio``, ``pack_hit_ratio``, error counts. ``bench.py --read``
drives single vs batch (the >=5x gate) and pack vs live head to head and
emits the BENCH JSON lines; docs/FORMATS.md §21.5 is the schema.

Standalone use against any devnet:

    python -m celestia_app_tpu blobload --url http://127.0.0.1:26658 \
        --readers 256 --requests 4 --mode batch --batch 64
"""

from __future__ import annotations

import hashlib
import json
import threading
import time

from celestia_app_tpu.tools.dasload import _Conn, _percentile

DEFAULT_READERS = 256
DEFAULT_REQUESTS = 4
DEFAULT_BATCH = 64


class _Stats:
    """The run's shared tally (lock-guarded; readers report per
    request)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies_ms: list[float] = []  # guarded-by: lock
        self.queries = 0        # guarded-by: lock
        self.present = 0        # guarded-by: lock
        self.pack_queries = 0   # guarded-by: lock
        self.errors = 0         # guarded-by: lock
        self.chunk_mismatches = 0  # guarded-by: lock

    def note(self, dt_ms: float, queries: int, present: int,
             via_pack: bool) -> None:
        with self.lock:
            self.latencies_ms.append(dt_ms)
            self.queries += queries
            self.present += present
            if via_pack:
                self.pack_queries += queries

    def note_error(self) -> None:
        with self.lock:
            self.errors += 1

    def note_mismatch(self) -> None:
        with self.lock:
            self.chunk_mismatches += 1


def _fetch_manifests(url: str, heights: list[int],
                     timeout: float) -> dict[int, dict | None]:
    """One blob-pack manifest fetch per height, shared by the fleet (a
    CDN would cache these identically); None marks a pack-less
    height."""
    import http.client

    conn = _Conn(url, timeout)
    out: dict[int, dict | None] = {}
    for h in heights:
        try:
            status, body = conn.request("GET", f"/blob/pack?height={h}")
            out[h] = json.loads(body) if status == 200 else None
        except (OSError, ValueError, http.client.HTTPException):
            out[h] = None
    conn.close()
    return out


def _query_plan(tid: int, i: int, heights: list[int],
                namespaces: list[str], batch: int) -> list[tuple[int, str]]:
    """The (height, namespace) queries one request covers — a rotating
    deterministic schedule, so every run over the same inputs asks the
    same questions (reproducible load, no rng)."""
    out = []
    base = tid * DEFAULT_REQUESTS + i
    for j in range(batch):
        idx = base + j
        out.append((heights[idx % len(heights)],
                    namespaces[idx % len(namespaces)]))
    return out


def _reader(tid: int, url: str, heights: list[int], namespaces: list[str],
            manifests: dict[int, dict | None], mode: str, requests: int,
            batch: int, timeout: float, barrier: threading.Barrier,
            stats: _Stats) -> None:
    import http.client

    conn = _Conn(url, timeout)
    try:
        barrier.wait()
    except threading.BrokenBarrierError:
        return
    for i in range(requests):
        plan = _query_plan(tid, i, heights, namespaces,
                           batch if mode == "batch" else 1)
        t0 = time.perf_counter()
        try:
            if mode == "batch":
                body = json.dumps({"queries": [
                    {"height": h, "namespace": ns} for h, ns in plan
                ]}).encode()
                status, out = conn.request("POST", "/blob/namespaces",
                                           body)
                if status != 200:
                    stats.note_error()
                    continue
                docs = json.loads(out).get("queries", [])
                ok = [d for d in docs if "error" not in d]
                stats.note((time.perf_counter() - t0) * 1e3, len(ok),
                           sum(1 for d in ok if d.get("present")),
                           via_pack=False)
            elif mode == "pack":
                h, ns = plan[0]
                m = manifests.get(h)
                if not m or ns not in m.get("namespaces", []):
                    # pack-less height or unpacked (absent) namespace:
                    # the pack path cannot answer — counts an error so
                    # pack runs against absent namespaces are visible
                    stats.note_error()
                    continue
                ci = (m["namespaces"].index(ns)
                      // int(m["chunk_namespaces"]))
                status, body = conn.request(
                    "GET", f"/blob/pack/chunk?height={h}&index={ci}")
                if status != 200:
                    stats.note_error()
                    continue
                if (hashlib.sha256(body).hexdigest()
                        != m["chunk_hashes"][ci]):
                    stats.note_mismatch()
                    continue
                docs = json.loads(body)
                doc = next((d for d in docs
                            if d.get("namespace") == ns), None)
                if doc is None:
                    stats.note_error()
                    continue
                stats.note((time.perf_counter() - t0) * 1e3, 1,
                           1 if doc.get("present") else 0, via_pack=True)
            else:  # single
                h, ns = plan[0]
                status, body = conn.request(
                    "GET", f"/blob/get?height={h}&namespace={ns}")
                if status != 200:
                    stats.note_error()
                    continue
                doc = json.loads(body)
                stats.note((time.perf_counter() - t0) * 1e3, 1,
                           1 if doc.get("present") else 0, via_pack=False)
        except (OSError, ValueError, KeyError,
                http.client.HTTPException):
            stats.note_error()
    conn.close()


def run_load(url: str, heights: list[int], namespaces: list[str],
             readers: int = DEFAULT_READERS,
             requests: int = DEFAULT_REQUESTS, mode: str = "single",
             batch: int = DEFAULT_BATCH, timeout: float = 30.0) -> dict:
    """Drive ``readers`` concurrent persistent-connection namespace
    readers at a serving node and return the aggregate report.
    ``mode``: "single" (GET /blob/get per query), "batch" (POST
    /blob/namespaces with ``batch`` queries per request), "pack" (static
    chunk reads, sha256-verified)."""
    if mode not in ("single", "batch", "pack"):
        raise ValueError(f"unknown blobload mode {mode!r}")
    if not heights or not namespaces:
        raise ValueError("blobload needs heights and namespaces")
    manifests = (_fetch_manifests(url, heights, timeout)
                 if mode == "pack" else {})
    stats = _Stats()
    barrier = threading.Barrier(readers + 1)
    threads = [
        threading.Thread(
            target=_reader,
            args=(tid, url, heights, namespaces, manifests, mode,
                  requests, batch, timeout, barrier, stats),
            daemon=True,
        )
        for tid in range(readers)
    ]
    for t in threads:
        t.start()
    barrier.wait()  # every connection is up: the clock starts here
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    lat = sorted(stats.latencies_ms)
    total = stats.queries
    return {
        "mode": mode,
        "readers": readers,
        "requests_per_reader": requests,
        "batch": batch if mode == "batch" else 1,
        "heights": len(heights),
        "namespaces": len(namespaces),
        "wall_s": round(wall_s, 3),
        "requests_ok": len(lat),
        "errors": stats.errors,
        "chunk_hash_mismatches": stats.chunk_mismatches,
        "namespace_queries": total,
        "namespace_queries_per_sec": round(total / wall_s, 1)
        if wall_s else 0.0,
        "requests_per_sec": round(len(lat) / wall_s, 1) if wall_s
        else 0.0,
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p99_ms": round(_percentile(lat, 0.99), 3),
        "present_ratio": round(stats.present / total, 4) if total
        else 0.0,
        "pack_hit_ratio": round(stats.pack_queries / total, 4) if total
        else 0.0,
    }


def _discover(url: str, timeout: float) -> tuple[list[int], list[str]]:
    """Default inputs: the served head's last 4 heights, and the union
    of their packed namespaces (absent packs leave the list empty — the
    caller must then pass --namespaces)."""
    conn = _Conn(url, timeout)
    _status, body = conn.request("GET", "/das/head")
    head = int(json.loads(body)["height"])
    heights = list(range(max(1, head - 3), head + 1))
    seen: list[str] = []
    for h in heights:
        try:
            status, body = conn.request("GET", f"/blob/pack?height={h}")
            if status != 200:
                continue
            for ns in json.loads(body).get("namespaces", []):
                if ns not in seen:
                    seen.append(ns)
        except (OSError, ValueError):
            continue
    conn.close()
    return heights, seen


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="blobload",
        description="read-plane namespace load harness (FORMATS §21.5)")
    ap.add_argument("--url", required=True)
    ap.add_argument("--readers", type=int, default=DEFAULT_READERS)
    ap.add_argument("--requests", type=int, default=DEFAULT_REQUESTS)
    ap.add_argument("--mode", choices=("single", "batch", "pack"),
                    default="batch")
    ap.add_argument("--batch", type=int, default=DEFAULT_BATCH)
    ap.add_argument("--heights", default="",
                    help="comma-separated heights (default: the served "
                         "head's last 4)")
    ap.add_argument("--namespaces", default="",
                    help="comma-separated namespace hex strings "
                         "(default: the heights' packed namespaces)")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    heights, namespaces = [], []
    if args.heights:
        heights = [int(x) for x in args.heights.split(",")]
    if args.namespaces:
        namespaces = [x.strip() for x in args.namespaces.split(",") if x]
    if not heights or not namespaces:
        d_heights, d_namespaces = _discover(args.url, args.timeout)
        heights = heights or d_heights
        namespaces = namespaces or d_namespaces
    if not namespaces:
        print(json.dumps({"error": "no namespaces discovered; pass "
                                   "--namespaces"}))
        return 2
    rep = run_load(args.url, heights, namespaces, readers=args.readers,
                   requests=args.requests, mode=args.mode,
                   batch=args.batch, timeout=args.timeout)
    print(json.dumps(rep, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
