"""txsim: transaction load generator (reference test/txsim/run.go analog).

Two engines:

- **Sustained load** (`run_load`, the traffic plane — ISSUE 15): N
  concurrent SEQUENCES, each owning one `client/tx_client` Signer
  account and ONE persistent keep-alive `HttpNodeClient`, submitting
  PFB blobs (sizes, namespaces, and gas prices drawn from configurable
  distributions) or sends over HTTP against a LIVE devnet, each tx
  confirm-polled to commit. Reports end-to-end ``blobs_per_sec``,
  admission->commit p50/p99 latency, and per-type
  submitted/accepted/confirmed counts (mirrored into the process-wide
  ``txsim.*`` telemetry counters). This is the reference's
  `test/txsim` shape: sequences are independent nonce lanes, so the
  fleet saturates admission without self-inflicted sequence races.
- **Paced rounds** (`run`, the original in-process loop): one tx per
  sequence per round against a Node object, a block produced between
  rounds — deterministic, good for fixtures; stake sequences
  (delegate/undelegate alternation, test/txsim/stake.go) live here.

Usage (CLI):
  python -m celestia_app_tpu txsim --home DIR --rounds 5        # paced
  python -m celestia_app_tpu txsim --url http://127.0.0.1:26658 \
      --blob-sequences 8 --txs-per-sequence 16                  # load
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.da.namespace import Namespace
# ONE percentile convention across the load harnesses: dasload's
# (nearest-rank over the sorted sample) — a fix there fixes both reports
from celestia_app_tpu.tools.dasload import _percentile
from celestia_app_tpu.utils import telemetry


@dataclasses.dataclass
class TxSimReport:
    rounds: int = 0
    blocks: int = 0
    pfbs_submitted: int = 0
    pfbs_accepted: int = 0
    sends_submitted: int = 0
    sends_accepted: int = 0
    stakes_submitted: int = 0
    stakes_accepted: int = 0
    bytes_submitted: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def run(
    node,
    signer,
    accounts: list[bytes],
    rounds: int = 5,
    blob_sequences: int = 2,
    send_sequences: int = 1,
    stake_sequences: int = 0,
    blob_sizes: tuple[int, int] = (100, 2000),
    blobs_per_pfb: tuple[int, int] = (1, 3),
    validators: list[bytes] | None = None,
    seed: int = 0,
    block_time: float | None = None,
) -> TxSimReport:
    """Run `rounds` rounds: each round submits one tx per sequence, then
    produces a block (the reference's sequence loop, test/txsim/run.go:37-70).

    Each sequence OWNS one account (run.go:52: sequences get dedicated
    accounts) — normal txs order before blob txs inside a block, so a
    same-account blob+send mix would break sequence continuity by design.
    Needs len(accounts) >= blob_sequences + send_sequences +
    stake_sequences; stake sequences additionally need `validators`
    (operator addresses to delegate to — test/txsim/stake.go)."""
    from celestia_app_tpu.chain.tx import MsgDelegate, MsgSend, MsgUndelegate

    n_seq = blob_sequences + send_sequences + stake_sequences
    if len(accounts) < n_seq:
        raise ValueError(
            f"need {n_seq} accounts (one per sequence), got {len(accounts)}"
        )
    if stake_sequences and not validators:
        raise ValueError("stake sequences need validator operator addresses")
    rng = np.random.default_rng(seed)
    rep = TxSimReport()
    # per (stake sequence, validator) running total of what WE delegated,
    # so undelegates always target a validator with enough of our stake
    staked: dict[tuple[int, bytes], int] = {}
    t = block_time if block_time is not None else 1_800_000_000.0
    for rnd in range(rounds):
        for seq in range(blob_sequences):
            addr = accounts[seq]
            n_blobs = int(rng.integers(blobs_per_pfb[0], blobs_per_pfb[1] + 1))
            blobs = []
            for b in range(n_blobs):
                size = int(rng.integers(blob_sizes[0], blob_sizes[1] + 1))
                ns = Namespace.v0(bytes([seq + 1, b + 1]) * 5)
                blobs.append(
                    Blob(ns, rng.integers(0, 256, size, dtype=np.uint8).tobytes())
                )
                rep.bytes_submitted += size
            raw = signer.create_pay_for_blobs(
                addr, blobs, fee=300_000, gas_limit=5_000_000
            )
            rep.pfbs_submitted += 1
            if node.broadcast_tx(raw).code == 0:
                rep.pfbs_accepted += 1
                signer.accounts[addr].sequence += 1
        for seq in range(send_sequences):
            a = accounts[blob_sequences + seq]
            b = accounts[(blob_sequences + seq + 1) % len(accounts)]
            tx = signer.create_tx(
                a, [MsgSend(a, b, int(rng.integers(1, 1000)))],
                fee=2000, gas_limit=100_000,
            )
            rep.sends_submitted += 1
            if node.broadcast_tx(tx.encode()).code == 0:
                rep.sends_accepted += 1
                signer.accounts[a].sequence += 1
        for seq in range(stake_sequences):
            # stake.go's loop: delegate on even rounds; on odd rounds
            # undelegate PART OF WHAT THIS SEQUENCE DELEGATED (tracked per
            # validator — undelegating stake we never placed would just
            # bounce off the staking keeper)
            a = accounts[blob_sequences + send_sequences + seq]
            funded = [
                (s, v) for (s, v), amt in staked.items()
                if s == seq and amt > 0
            ]
            if rnd % 2 == 0 or not funded:
                val = validators[(rnd + seq) % len(validators)]
                amount = int(rng.integers(1_000, 100_000))
                msg = MsgDelegate(a, val, amount)
                delta = amount
            else:
                _s, val = funded[int(rng.integers(0, len(funded)))]
                amount = max(1, staked[(seq, val)] // 2)
                msg = MsgUndelegate(a, val, amount)
                delta = -amount
            tx = signer.create_tx(a, [msg], fee=4000, gas_limit=300_000)
            rep.stakes_submitted += 1
            if node.broadcast_tx(tx.encode()).code == 0:
                rep.stakes_accepted += 1
                signer.accounts[a].sequence += 1
                staked[(seq, val)] = staked.get((seq, val), 0) + delta
        t += 6.0
        node.produce_block(t=t)
        rep.blocks += 1
        rep.rounds += 1
    return rep


# ---------------------------------------------------------------------------
# the sustained-load engine (the traffic plane)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LoadConfig:
    """Knobs of one sustained-load run. Sequences split into blob
    sequences (PFB submitters) first, then send sequences; each owns
    one account, so `blob_sequences + send_sequences` accounts are
    required. Distributions are uniform over inclusive ranges, drawn
    per tx from the sequence's own seeded rng (runs are reproducible
    per (seed, sequence) regardless of thread interleaving)."""

    blob_sequences: int = 4
    send_sequences: int = 0
    txs_per_sequence: int = 8
    blob_sizes: tuple[int, int] = (100, 2000)
    blobs_per_pfb: tuple[int, int] = (1, 2)
    # gas-price draw: the fee rides fee = gas_limit * price + 1, so a
    # spread exercises the pool's priority ordering under load
    gas_prices: tuple[float, float] = (0.002, 0.02)
    namespaces: int = 2  # distinct namespaces per blob sequence
    confirm_timeout_s: float = 60.0
    poll_interval_s: float = 0.05
    seed: int = 0


@dataclasses.dataclass
class LoadReport:
    sequences: int = 0
    wall_s: float = 0.0
    pfbs_submitted: int = 0
    pfbs_accepted: int = 0
    pfbs_confirmed: int = 0
    sends_submitted: int = 0
    sends_accepted: int = 0
    sends_confirmed: int = 0
    blobs_submitted: int = 0
    blobs_confirmed: int = 0
    bytes_submitted: int = 0
    blobs_per_sec: float = 0.0
    txs_per_sec: float = 0.0
    admission_commit_p50_ms: float = 0.0
    admission_commit_p99_ms: float = 0.0
    resyncs: int = 0
    errors: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)




class _LoadStats:
    """The run's shared tally (lock-guarded; sequences report per tx).
    Mirrors into the process-wide `txsim.*` telemetry counters so a
    co-located node's /metrics (and the bench) see the load shape."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies_ms: list = []  # guarded-by: lock
        self.report = LoadReport()    # guarded-by: lock

    def note_submit(self, kind: str, accepted: bool, n_blobs: int = 0,
                    n_bytes: int = 0) -> None:
        with self.lock:
            r = self.report
            if kind == "pfb":
                r.pfbs_submitted += 1
                r.pfbs_accepted += int(accepted)
                r.blobs_submitted += n_blobs
                r.bytes_submitted += n_bytes
            else:
                r.sends_submitted += 1
                r.sends_accepted += int(accepted)
        telemetry.incr("txsim.submitted")
        telemetry.incr("txsim.accepted" if accepted else "txsim.rejected")

    def note_confirm(self, kind: str, dt_ms: float, n_blobs: int) -> None:
        with self.lock:
            r = self.report
            self.latencies_ms.append(dt_ms)
            if kind == "pfb":
                r.pfbs_confirmed += 1
                r.blobs_confirmed += n_blobs
            else:
                r.sends_confirmed += 1
        telemetry.incr("txsim.confirmed")

    def note_resync(self) -> None:
        with self.lock:
            self.report.resyncs += 1
        telemetry.incr("txsim.resyncs")

    def note_error(self) -> None:
        with self.lock:
            self.report.errors += 1
        telemetry.incr("txsim.errors")


def _confirm(client, raw: bytes, cfg: LoadConfig) -> bool:
    """Poll the tx to commit within the confirm budget (the reference's
    ConfirmTx loop, paced for devnet block times)."""
    deadline = time.perf_counter() + cfg.confirm_timeout_s
    while True:
        out = client.confirm_tx(raw, attempts=1)
        if out.get("found"):
            return True
        if time.perf_counter() >= deadline:
            return False
        time.sleep(cfg.poll_interval_s)


def _sequence_worker(seq: int, kind: str, client, signer, addr: bytes,
                     peers: list, cfg: LoadConfig,
                     barrier: threading.Barrier,
                     stats: _LoadStats) -> None:
    """One sequence: an independent nonce lane submitting
    cfg.txs_per_sequence txs of its kind, each confirm-polled. A
    rejected tx resyncs the sequence number once from the node's error
    (app/errors/nonce_mismatch.go parity) before moving on."""
    from celestia_app_tpu.chain import modules
    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.client.tx_client import parse_expected_sequence

    rng = np.random.default_rng(cfg.seed * 65537 + seq)
    try:
        barrier.wait()
    except threading.BrokenBarrierError:
        return
    for _i in range(cfg.txs_per_sequence):
        try:
            if kind == "pfb":
                n_blobs = int(rng.integers(cfg.blobs_per_pfb[0],
                                           cfg.blobs_per_pfb[1] + 1))
                blobs = []
                for _b in range(n_blobs):
                    size = int(rng.integers(cfg.blob_sizes[0],
                                            cfg.blob_sizes[1] + 1))
                    ns_id = 1 + int(rng.integers(0, max(1, cfg.namespaces)))
                    ns = Namespace.v0(bytes([seq + 1, ns_id]) * 5)
                    blobs.append(Blob(ns, rng.integers(
                        0, 256, size, dtype=np.uint8).tobytes()))
                n_bytes = sum(len(b.data) for b in blobs)
                gas = int(modules.estimate_pfb_gas(
                    [len(b.data) for b in blobs]) * 1.2)
            else:
                blobs, n_blobs, n_bytes = [], 0, 0
                gas = 100_000
            price = float(rng.uniform(cfg.gas_prices[0], cfg.gas_prices[1]))
            fee = max(1, int(gas * price) + 1)

            def make_raw() -> bytes:
                if kind == "pfb":
                    return signer.create_pay_for_blobs(
                        addr, blobs, fee=fee, gas_limit=gas)
                to = peers[(seq + 1) % len(peers)]
                return signer.create_tx(
                    addr, [MsgSend(addr, to, 1 + int(rng.integers(1000)))],
                    fee=fee, gas_limit=gas,
                ).encode()

            raw = make_raw()
            t0 = time.perf_counter()
            res = client.broadcast_tx(raw)
            if res.code != 0:
                expected = parse_expected_sequence(res.log)
                if expected is not None:
                    # one resync + resubmit: a restarted node or a
                    # dropped confirm can leave the local lane ahead
                    signer.accounts[addr].sequence = expected
                    stats.note_resync()
                    raw = make_raw()
                    t0 = time.perf_counter()
                    res = client.broadcast_tx(raw)
            accepted = res.code == 0
            stats.note_submit(kind, accepted, n_blobs, n_bytes)
            if not accepted:
                continue
            signer.accounts[addr].sequence += 1
            if _confirm(client, raw, cfg):
                stats.note_confirm(
                    kind, (time.perf_counter() - t0) * 1e3, n_blobs)
        except Exception:
            stats.note_error()
    close = getattr(client, "close", None)
    if close is not None:
        close()


def run_load(urls: list, signer, accounts: list, cfg: LoadConfig,
             client_factory=None) -> LoadReport:
    """Drive `blob_sequences + send_sequences` concurrent sequences at a
    live devnet (sequences round-robin over `urls`; someone else — the
    devnet's reactor or a BlockDriver — produces blocks) and return the
    aggregate LoadReport. `accounts` are the signer-registered sequence
    owners, one per sequence. `client_factory(url)` overrides the
    transport (tests); the default is one persistent-connection
    HttpNodeClient per sequence."""
    from celestia_app_tpu.client.tx_client import HttpNodeClient

    n_seq = cfg.blob_sequences + cfg.send_sequences
    if len(accounts) < n_seq:
        raise ValueError(
            f"need {n_seq} accounts (one per sequence), got {len(accounts)}")
    if client_factory is None:
        client_factory = HttpNodeClient
    stats = _LoadStats()
    barrier = threading.Barrier(n_seq + 1)
    threads = []
    for seq in range(n_seq):
        kind = "pfb" if seq < cfg.blob_sequences else "send"
        client = client_factory(urls[seq % len(urls)])
        threads.append(threading.Thread(
            target=_sequence_worker,
            args=(seq, kind, client, signer, accounts[seq], accounts, cfg,
                  barrier, stats),
            daemon=True,
        ))
    for t in threads:
        t.start()
    barrier.wait()  # every connection is up: the clock starts here
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    rep = stats.report
    rep.sequences = n_seq
    rep.wall_s = round(wall_s, 3)
    if wall_s > 0:
        rep.blobs_per_sec = round(rep.blobs_confirmed / wall_s, 2)
        rep.txs_per_sec = round(
            (rep.pfbs_confirmed + rep.sends_confirmed) / wall_s, 2)
    lat = sorted(stats.latencies_ms)
    rep.admission_commit_p50_ms = round(_percentile(lat, 0.50), 3)
    rep.admission_commit_p99_ms = round(_percentile(lat, 0.99), 3)
    return rep


class BlockDriver(threading.Thread):
    """Background block producer for harness runs where no autonomous
    reactor drives the chain (bench/tests): calls `produce()` every
    `block_time` seconds until stopped. `produce` owns its own locking
    (e.g. `with svc.lock: node.produce_block()`)."""

    def __init__(self, produce, block_time: float = 0.2):
        super().__init__(daemon=True)
        self._produce = produce
        self._block_time = block_time
        # NOT named _stop: threading.Thread owns a private _stop method
        # that join() calls on a finished thread
        self._halt = threading.Event()
        self.blocks = 0
        self.errors = 0

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self._produce()
                self.blocks += 1
            except Exception:
                # an empty-mempool or mid-shutdown round is not fatal to
                # the driver; the harness reads .errors for visibility
                self.errors += 1
            self._halt.wait(self._block_time)

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=30)
