"""txsim: transaction load generator (reference test/txsim/run.go analog).

Drives a node with a configurable mix of sequences — send sequences, blob
sequences with size/count distributions (test/txsim/blob.go's ranges), and
stake sequences alternating delegate/undelegate against the validator set
(test/txsim/stake.go) — either in-process (Node object) or over the HTTP
service. Reports per-type submission counts, acceptance, and blocks
produced.

Usage (CLI): python -m celestia_app_tpu txsim --blob-sequences 2 \
    --send-sequences 2 --stake-sequences 1 --blob-sizes 100-2000 \
    --blobs-per-pfb 1-3 --rounds 5
"""

from __future__ import annotations

import dataclasses

import numpy as np

from celestia_app_tpu.da.blob import Blob
from celestia_app_tpu.da.namespace import Namespace


@dataclasses.dataclass
class TxSimReport:
    rounds: int = 0
    blocks: int = 0
    pfbs_submitted: int = 0
    pfbs_accepted: int = 0
    sends_submitted: int = 0
    sends_accepted: int = 0
    stakes_submitted: int = 0
    stakes_accepted: int = 0
    bytes_submitted: int = 0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def run(
    node,
    signer,
    accounts: list[bytes],
    rounds: int = 5,
    blob_sequences: int = 2,
    send_sequences: int = 1,
    stake_sequences: int = 0,
    blob_sizes: tuple[int, int] = (100, 2000),
    blobs_per_pfb: tuple[int, int] = (1, 3),
    validators: list[bytes] | None = None,
    seed: int = 0,
    block_time: float | None = None,
) -> TxSimReport:
    """Run `rounds` rounds: each round submits one tx per sequence, then
    produces a block (the reference's sequence loop, test/txsim/run.go:37-70).

    Each sequence OWNS one account (run.go:52: sequences get dedicated
    accounts) — normal txs order before blob txs inside a block, so a
    same-account blob+send mix would break sequence continuity by design.
    Needs len(accounts) >= blob_sequences + send_sequences +
    stake_sequences; stake sequences additionally need `validators`
    (operator addresses to delegate to — test/txsim/stake.go)."""
    from celestia_app_tpu.chain.tx import MsgDelegate, MsgSend, MsgUndelegate

    n_seq = blob_sequences + send_sequences + stake_sequences
    if len(accounts) < n_seq:
        raise ValueError(
            f"need {n_seq} accounts (one per sequence), got {len(accounts)}"
        )
    if stake_sequences and not validators:
        raise ValueError("stake sequences need validator operator addresses")
    rng = np.random.default_rng(seed)
    rep = TxSimReport()
    # per (stake sequence, validator) running total of what WE delegated,
    # so undelegates always target a validator with enough of our stake
    staked: dict[tuple[int, bytes], int] = {}
    t = block_time if block_time is not None else 1_800_000_000.0
    for rnd in range(rounds):
        for seq in range(blob_sequences):
            addr = accounts[seq]
            n_blobs = int(rng.integers(blobs_per_pfb[0], blobs_per_pfb[1] + 1))
            blobs = []
            for b in range(n_blobs):
                size = int(rng.integers(blob_sizes[0], blob_sizes[1] + 1))
                ns = Namespace.v0(bytes([seq + 1, b + 1]) * 5)
                blobs.append(
                    Blob(ns, rng.integers(0, 256, size, dtype=np.uint8).tobytes())
                )
                rep.bytes_submitted += size
            raw = signer.create_pay_for_blobs(
                addr, blobs, fee=300_000, gas_limit=5_000_000
            )
            rep.pfbs_submitted += 1
            if node.broadcast_tx(raw).code == 0:
                rep.pfbs_accepted += 1
                signer.accounts[addr].sequence += 1
        for seq in range(send_sequences):
            a = accounts[blob_sequences + seq]
            b = accounts[(blob_sequences + seq + 1) % len(accounts)]
            tx = signer.create_tx(
                a, [MsgSend(a, b, int(rng.integers(1, 1000)))],
                fee=2000, gas_limit=100_000,
            )
            rep.sends_submitted += 1
            if node.broadcast_tx(tx.encode()).code == 0:
                rep.sends_accepted += 1
                signer.accounts[a].sequence += 1
        for seq in range(stake_sequences):
            # stake.go's loop: delegate on even rounds; on odd rounds
            # undelegate PART OF WHAT THIS SEQUENCE DELEGATED (tracked per
            # validator — undelegating stake we never placed would just
            # bounce off the staking keeper)
            a = accounts[blob_sequences + send_sequences + seq]
            funded = [
                (s, v) for (s, v), amt in staked.items()
                if s == seq and amt > 0
            ]
            if rnd % 2 == 0 or not funded:
                val = validators[(rnd + seq) % len(validators)]
                amount = int(rng.integers(1_000, 100_000))
                msg = MsgDelegate(a, val, amount)
                delta = amount
            else:
                _s, val = funded[int(rng.integers(0, len(funded)))]
                amount = max(1, staked[(seq, val)] // 2)
                msg = MsgUndelegate(a, val, amount)
                delta = -amount
            tx = signer.create_tx(a, [msg], fee=4000, gas_limit=300_000)
            rep.stakes_submitted += 1
            if node.broadcast_tx(tx.encode()).code == 0:
                rep.stakes_accepted += 1
                signer.accounts[a].sequence += 1
                staked[(seq, val)] = staked.get((seq, val), 0) + delta
        t += 6.0
        node.produce_block(t=t)
        rep.blocks += 1
        rep.rounds += 1
    return rep
