"""blocktime: block interval statistics from a chain data dir
(tools/blocktime/main.go analog: average time between consecutive blocks)."""

from __future__ import annotations

from celestia_app_tpu.chain.storage import ChainDB


def report(data_dir: str, last_n: int | None = None) -> dict:
    db = ChainDB(data_dir, read_only=True)  # safe against a live home
    heights = db.block_heights()
    if last_n:
        heights = heights[-last_n - 1 :]
    if len(heights) < 2:
        return {"blocks": len(heights), "avg_interval_s": None}
    times = [db.load_block(h).header.time_unix for h in heights]
    deltas = [b - a for a, b in zip(times, times[1:])]
    return {
        "blocks": len(heights),
        "from_height": heights[0],
        "to_height": heights[-1],
        "avg_interval_s": sum(deltas) / len(deltas),
        "min_interval_s": min(deltas),
        "max_interval_s": max(deltas),
    }
