"""dasload: the thousand-sampler DAS serving-plane load harness.

Models the north star's client shape — a large fleet of *dumb* samplers
(arXiv:1910.01247's light-client model) hammering one serving node — and
measures what the serving plane actually delivers under that
concurrency:

- every sampler is a thread holding ONE persistent HTTP/1.1 connection
  (``http.client.HTTPConnection`` keep-alive; urllib would re-connect
  per request and measure socket setup, not serving), all released
  together off a start barrier so the clock covers steady state only;
- each request models one height's DAS round: draw ``cells`` coordinates
  from the sampler's own rng and obtain their proof docs either LIVE
  (one batched ``POST /das/samples``) or from the height's static proof
  pack (``GET /das/pack/chunk`` covering the drawn cells, sha256-checked
  against the manifest);
- ``mode="auto"`` prefers the pack and falls back to live per height —
  the DASer's own policy — so ``pack_hit_ratio`` reports how much of the
  fleet's demand the static path absorbed.

Output (and the ``run_load`` return value) is one JSON report:
``samples_per_sec``, ``requests_per_sec``, ``p50_ms``/``p99_ms`` per
request, ``pack_hit_ratio``, error counts. ``bench.py --serve`` drives
two runs of this harness (live vs pack) head to head and emits the
BENCH JSON lines; docs/FORMATS.md §17.5 is the schema.

Standalone use against any devnet:

    python -m celestia_app_tpu dasload --url http://127.0.0.1:26658 \
        --samplers 1000 --requests 3 --cells 16 --mode auto
"""

from __future__ import annotations

import hashlib
import http.client
import json
import random
import threading
import time
import urllib.parse

DEFAULT_SAMPLERS = 1000
DEFAULT_REQUESTS = 3
DEFAULT_CELLS = 16


class _Conn:
    """One sampler's persistent connection: keep-alive across requests,
    transparent single reconnect on a torn socket (the server's idle
    reaper or a request cap may close it mid-run)."""

    def __init__(self, url: str, timeout: float):
        p = urllib.parse.urlparse(url)
        self.host = p.hostname
        self.port = p.port or (443 if p.scheme == "https" else 80)
        self.timeout = timeout
        self.conn: http.client.HTTPConnection | None = None

    def _connect(self) -> http.client.HTTPConnection:
        if self.conn is None:
            self.conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout)
        return self.conn

    def close(self) -> None:
        if self.conn is not None:
            try:
                self.conn.close()
            finally:
                self.conn = None

    def request(self, method: str, path: str,
                body: bytes | None = None) -> tuple[int, bytes]:
        """(status, body); one reconnect attempt on connection-level
        failure (keep-alive races are normal, not errors)."""
        for attempt in (0, 1):
            conn = self._connect()
            try:
                headers = {}
                if body is not None:
                    headers["Content-Type"] = "application/json"
                conn.request(method, path, body=body, headers=headers)
                r = conn.getresponse()
                return r.status, r.read()
            except (OSError, http.client.HTTPException):
                self.close()
                if attempt:
                    raise
        raise OSError("unreachable")


class _Stats:
    """The run's shared tally (lock-guarded; samplers report per
    request)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latencies_ms: list[float] = []  # guarded-by: lock
        self.samples = 0          # guarded-by: lock
        self.pack_samples = 0     # guarded-by: lock
        self.live_samples = 0     # guarded-by: lock
        self.errors = 0           # guarded-by: lock
        self.chunk_mismatches = 0  # guarded-by: lock

    def note(self, dt_ms: float, samples: int, via_pack: bool) -> None:
        with self.lock:
            self.latencies_ms.append(dt_ms)
            self.samples += samples
            if via_pack:
                self.pack_samples += samples
            else:
                self.live_samples += samples

    def note_error(self) -> None:
        with self.lock:
            self.errors += 1

    def note_mismatch(self) -> None:
        with self.lock:
            self.chunk_mismatches += 1


def _percentile(sorted_ms: list[float], p: float) -> float:
    if not sorted_ms:
        return 0.0
    i = min(len(sorted_ms) - 1, int(p * (len(sorted_ms) - 1) + 0.5))
    return sorted_ms[i]


def _fetch_manifests(url: str, heights: list[int],
                     timeout: float) -> dict[int, dict | None]:
    """One manifest fetch per height, shared by the whole fleet (a CDN
    would cache these identically); None marks a pack-less height."""
    conn = _Conn(url, timeout)
    out: dict[int, dict | None] = {}
    for h in heights:
        try:
            status, body = conn.request("GET", f"/das/pack?height={h}")
            out[h] = json.loads(body) if status == 200 else None
        except (OSError, ValueError, http.client.HTTPException):
            out[h] = None
    conn.close()
    return out


def _fetch_draw_spaces(url: str, heights: list[int],
                       timeout: float) -> dict[int, tuple]:
    """height -> ("rs2d", width) | ("cmt", n_layer0): the live draw
    space per height, from one upfront /das/header fetch shared by the
    fleet — live samplers must draw over the REAL space (an unlearned
    width would sample a 2x2 corner and flatter the assembly path)."""
    conn = _Conn(url, timeout)
    out: dict[int, tuple] = {}
    for h in heights:
        space = ("rs2d", 2)
        try:
            status, body = conn.request("GET", f"/das/header?height={h}")
            if status == 200:
                doc = json.loads(body)
                if "square_width" in doc:
                    space = ("rs2d", int(doc["square_width"]))
                elif "k" in doc:
                    # CMT: light clients draw layer-0 coded symbols
                    # (FORMATS §16.3) — 2k² of them at rate 1/2
                    space = ("cmt", 2 * int(doc["k"]) ** 2)
        except (OSError, ValueError, http.client.HTTPException):
            pass
        out[h] = space
    conn.close()
    return out


def _sampler(tid: int, url: str, heights: list[int],
             manifests: dict[int, dict | None],
             spaces: dict[int, tuple], mode: str,
             requests: int, cells: int, timeout: float,
             barrier: threading.Barrier, stats: _Stats) -> None:
    rng = random.Random(0xDA5 + tid)
    conn = _Conn(url, timeout)
    try:
        barrier.wait()
    except threading.BrokenBarrierError:
        return
    for i in range(requests):
        h = heights[(tid + i) % len(heights)]
        m = manifests.get(h) if mode in ("pack", "auto") else None
        if mode == "pack" and m is None:
            stats.note_error()
            continue
        t0 = time.perf_counter()
        try:
            if m is not None:
                # chunk-granular sampling (the CMT/pack model): draw one
                # random cell, fetch THE chunk that covers it, verify
                # the bytes against the manifest — every doc the chunk
                # carries is a served, verifiable proof, which is the
                # whole economic point of static packs (one read serves
                # the neighborhood). One round-trip, like a live batch.
                n_cells = int(m["n_cells"])
                chunk_cells = int(m["chunk_cells"])
                ci = rng.randrange(n_cells) // chunk_cells
                status, body = conn.request(
                    "GET", f"/das/pack/chunk?height={h}&index={ci}")
                ok = status == 200
                if ok and (hashlib.sha256(body).hexdigest()
                           != m["chunk_hashes"][ci]):
                    stats.note_mismatch()
                    ok = False
                if ok:
                    served = min(chunk_cells, n_cells - ci * chunk_cells)
                    stats.note((time.perf_counter() - t0) * 1e3,
                               served, via_pack=True)
                    continue
                if mode == "pack":
                    stats.note_error()
                    continue
                # auto: fall through to live for this height
            # live assembly: the sampler's real draw shape over the
            # REAL sample space (fetched upfront per height) — the
            # server resolves the height once and proves each cell
            kind, n = spaces.get(h, ("rs2d", 2))
            if kind == "cmt":
                draw = [[0, rng.randrange(n)] for _ in range(cells)]
            else:
                draw = [[rng.randrange(n), rng.randrange(n)]
                        for _ in range(cells)]
            body = json.dumps({"height": h, "cells": draw}).encode()
            status, out = conn.request("POST", "/das/samples", body)
            if status != 200:
                stats.note_error()
                continue
            doc = json.loads(out)
            served = sum(1 for s in doc.get("samples", [])
                         if "error" not in s)
            stats.note((time.perf_counter() - t0) * 1e3, served,
                       via_pack=False)
        except (OSError, ValueError, KeyError,
                http.client.HTTPException):
            stats.note_error()
    conn.close()


def run_load(url: str, heights: list[int], samplers: int = DEFAULT_SAMPLERS,
             requests: int = DEFAULT_REQUESTS, cells: int = DEFAULT_CELLS,
             mode: str = "auto", timeout: float = 30.0) -> dict:
    """Drive ``samplers`` concurrent persistent-connection samplers at a
    serving node and return the aggregate report. ``mode``: "live"
    (always POST /das/samples), "pack" (pack chunks only; a pack-less
    height counts an error), "auto" (pack preferred, live fallback)."""
    if mode not in ("live", "pack", "auto"):
        raise ValueError(f"unknown dasload mode {mode!r}")
    manifests = (_fetch_manifests(url, heights, timeout)
                 if mode in ("pack", "auto") else {})
    spaces = (_fetch_draw_spaces(url, heights, timeout)
              if mode in ("live", "auto") else {})
    stats = _Stats()
    barrier = threading.Barrier(samplers + 1)
    threads = [
        threading.Thread(
            target=_sampler,
            args=(tid, url, heights, manifests, spaces, mode, requests,
                  cells, timeout, barrier, stats),
            daemon=True,
        )
        for tid in range(samplers)
    ]
    for t in threads:
        t.start()
    barrier.wait()  # every connection is up: the clock starts here
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    lat = sorted(stats.latencies_ms)
    total = stats.samples
    return {
        "mode": mode,
        "samplers": samplers,
        "requests_per_sampler": requests,
        "cells_per_request": cells,
        "heights": len(heights),
        "wall_s": round(wall_s, 3),
        "requests_ok": len(lat),
        "errors": stats.errors,
        "chunk_hash_mismatches": stats.chunk_mismatches,
        "samples": total,
        "samples_per_sec": round(total / wall_s, 1) if wall_s else 0.0,
        "requests_per_sec": round(len(lat) / wall_s, 1) if wall_s
        else 0.0,
        "p50_ms": round(_percentile(lat, 0.50), 3),
        "p99_ms": round(_percentile(lat, 0.99), 3),
        "pack_hit_ratio": round(stats.pack_samples / total, 4)
        if total else 0.0,
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="dasload",
        description="DAS serving-plane load harness (FORMATS §17.5)")
    ap.add_argument("--url", required=True)
    ap.add_argument("--samplers", type=int, default=DEFAULT_SAMPLERS)
    ap.add_argument("--requests", type=int, default=DEFAULT_REQUESTS)
    ap.add_argument("--cells", type=int, default=DEFAULT_CELLS)
    ap.add_argument("--mode", choices=("live", "pack", "auto"),
                    default="auto")
    ap.add_argument("--heights", default="",
                    help="comma-separated heights (default: the served "
                         "head's last 8)")
    ap.add_argument("--timeout", type=float, default=30.0)
    args = ap.parse_args(argv)
    if args.heights:
        heights = [int(x) for x in args.heights.split(",")]
    else:
        conn = _Conn(args.url, args.timeout)
        _status, body = conn.request("GET", "/das/head")
        head = int(json.loads(body)["height"])
        conn.close()
        heights = list(range(max(1, head - 7), head + 1))
    rep = run_load(args.url, heights, samplers=args.samplers,
                   requests=args.requests, cells=args.cells,
                   mode=args.mode, timeout=args.timeout)
    print(json.dumps(rep, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
