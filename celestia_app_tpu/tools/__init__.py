"""Operator tooling: txsim load generator, blocktime, blockscan."""
